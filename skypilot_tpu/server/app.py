"""API server: stdlib HTTP + JSON routing (twin of sky/server/server.py).

The reference uses FastAPI; this image bakes no web framework, so the
server is a ThreadingHTTPServer with a small router — zero dependencies,
same wire contract as ``client/remote_client.py``:

  POST /api/<verb>            → {"request_id": ...}
  GET  /api/get?request_id=X  → {"status", "result"|"error"}
  GET  /api/requests          → request list (sky api logs twin)
  POST /api/requests/cancel   → cancel a queued/running request
  GET  /health                → {"status": "healthy", "version": ...}
"""
from __future__ import annotations

import argparse
import json
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, Optional, Tuple

from skypilot_tpu import sky_logging
from skypilot_tpu.server import executor
from skypilot_tpu.server import metrics
from skypilot_tpu.server import payloads
from skypilot_tpu.server import requests_db

logger = sky_logging.init_logger(__name__)

API_VERSION = 1


# ---- route table -----------------------------------------------------------


def _submit_verb(verb: str, body: Dict[str, Any]) -> Dict[str, Any]:
    func, kwargs = payloads.resolve(verb, body)
    request_id = executor.schedule_request(verb, body.get('user', 'anon'),
                                           body, func, kwargs)
    return {'request_id': request_id}


def _get_request(params: Dict[str, str]) -> Tuple[int, Dict[str, Any]]:
    # Status-only fast path first: polling is the chattiest verb on the
    # wire (every SDK call polls until terminal), and while a request
    # is PENDING/RUNNING the body/result deserialization that
    # requests_db.get() pays buys the poller nothing. Only a terminal
    # row that actually carries a result/error takes the full read.
    record = requests_db.get_status(params.get('request_id', ''))
    if record is None:
        return 404, {'error': 'request not found'}
    payload: Dict[str, Any] = {
        'request_id': record['request_id'],
        'name': record['name'],
        'status': record['status'].value,
        # Additive field: the request-scoped trace, usable with
        # `xsky trace` while the request is still running.
        'trace_id': record.get('trace_id'),
    }
    if record['status'] in (requests_db.RequestStatus.SUCCEEDED,
                            requests_db.RequestStatus.FAILED):
        full = requests_db.get(record['request_id'])
        if full is None:
            # Retention GC raced the two reads and reclaimed the row:
            # answer like any other missing request, never a
            # SUCCEEDED payload with a silently-null result.
            return 404, {'error': 'request not found'}
        if record['status'] == requests_db.RequestStatus.SUCCEEDED:
            payload['result'] = payloads.jsonify(full['result'])
        else:
            payload['error'] = full['error']
    if params.get('include_log') == '1':
        payload['log'] = requests_db.read_log(record['request_id'])
    return 200, payload


#: Verbs that operate on an existing cluster named in the body — their
#: workspace is the cluster record's, not the caller's choice.
_CLUSTER_VERBS = frozenset({
    'exec', 'start', 'stop', 'down', 'autostop', 'queue', 'cancel',
    'logs', 'cluster_hosts', 'endpoints',
})


def _target_workspace(verb: str, body: Dict[str, Any]) -> 'Optional[str]':
    """The workspace this verb operates in, or None when unscoped.

    Used for per-workspace authz (ref: workspace policies in
    sky/users/rbac.py + sky/workspaces/core.py): `launch` targets the
    requested workspace; cluster lifecycle verbs target the workspace
    the cluster lives in.
    """
    from skypilot_tpu.workspaces import context as ws_context
    if verb == 'launch':
        # Reusing an existing cluster must be authorized against the
        # workspace the CLUSTER lives in, not the caller's requested
        # one — otherwise a non-member could run code on (and re-home)
        # a private-workspace cluster by naming it with no 'workspace'
        # field (code-review r4 finding).
        cluster = body.get('cluster_name')
        if cluster:
            from skypilot_tpu import state
            record = state.get_cluster_from_name(cluster)
            if record is not None:
                return (record.get('workspace')
                        or ws_context.DEFAULT_WORKSPACE)
        return body.get('workspace') or ws_context.get_active()
    if verb in ('workspaces.members', 'workspaces.get_config'):
        # Reads of a workspace's roster/config are member-scoped (the
        # config overlay can carry project ids and launch settings).
        return body.get('workspace')
    if verb in _CLUSTER_VERBS:
        cluster = body.get('cluster_name')
        if not cluster:
            return None
        from skypilot_tpu import state
        record = state.get_cluster_from_name(cluster)
        if record is None:
            return None   # nonexistent cluster: the verb 404s itself
        return record.get('workspace') or ws_context.DEFAULT_WORKSPACE
    if verb in ('jobs.launch', 'serve.up'):
        # Submissions target the requested (or active) workspace; the
        # payload resolver re-validates and records it on the job/
        # service row for the lifecycle verbs below.
        return body.get('workspace') or ws_context.get_active()
    if verb in ('jobs.cancel', 'jobs.logs', 'jobs.watch_logs'):
        # Managed jobs belong to the workspace recorded at submit time
        # (advisor r4: these verbs bypassed workspace isolation).
        try:
            job_id = int(body.get('job_id'))
        except (TypeError, ValueError):
            return None   # the verb itself rejects the bad id
        from skypilot_tpu.jobs import state as jobs_state
        record = jobs_state.get_job(job_id)
        if record is None:
            return None   # nonexistent job: the verb no-ops/404s
        return record.get('workspace') or ws_context.DEFAULT_WORKSPACE
    if verb in ('serve.down', 'serve.update', 'serve.logs',
                'serve.controller_logs', 'serve.history',
                'serve.watch_logs'):
        service = body.get('service_name')
        if not service:
            return None
        from skypilot_tpu.serve import state as serve_state
        record = serve_state.get_service(service)
        if record is None:
            return None   # nonexistent service: the verb 404s itself
        return record.get('workspace') or ws_context.DEFAULT_WORKSPACE
    return None


def _cancel_request(body: Dict[str, Any]) -> Dict[str, Any]:
    ok = requests_db.mark_cancelled(body.get('request_id', ''))
    return {'cancelled': ok}


# ---- HTTP plumbing ---------------------------------------------------------


class _Handler(BaseHTTPRequestHandler):
    server_version = 'xsky-api'
    # Keep-alive (measured by tools/bench_controlplane.py): the default
    # HTTP/1.0 closes the connection after every response, so each poll
    # paid a fresh TCP connect + handler-thread spawn. Every response
    # path sets Content-Length, which HTTP/1.1 persistence requires.
    protocol_version = 'HTTP/1.1'
    # Without TCP_NODELAY the headers-then-body write pattern trips
    # Nagle against delayed ACKs: ~40 ms added to EVERY round trip on
    # loopback (bench measured poll p50 at 50 ms; ~2 ms after).
    disable_nagle_algorithm = True
    # Keep-alive must not let idle/half-open peers pin handler threads
    # forever (ThreadingHTTPServer = one thread per connection; the
    # old HTTP/1.0 close-per-response bounded thread lifetime). A
    # timed-out read surfaces as close_connection, ending the thread.
    # CONNECT tunnels idle in select(), which this does not interrupt.
    timeout = 120

    def log_message(self, fmt, *args):  # quiet default access log
        logger.debug('%s - %s' % (self.address_string(), fmt % args))

    def _send(self, code: int, payload: Dict[str, Any]) -> None:
        metrics.observe_http(
            urllib.parse.urlparse(self.path).path, code)
        data = json.dumps(payload, default=str).encode()
        self.send_response(code)
        self.send_header('Content-Type', 'application/json')
        self.send_header('Content-Length', str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _read_body(self) -> Dict[str, Any]:
        length = int(self.headers.get('Content-Length') or 0)
        if length == 0:
            return {}
        try:
            return json.loads(self.rfile.read(length))
        except json.JSONDecodeError:
            return {}

    def _discard_body(self) -> None:
        """Keep-alive hygiene for routes that ignore request bodies:
        unread body bytes would be parsed as the NEXT request on this
        persistent connection (a GET with a Content-Length body is
        nonstandard but legal). Chunked bodies can't be skipped by
        length, so those connections close after the response."""
        if self.headers.get('Transfer-Encoding'):
            self.close_connection = True
            return
        length = int(self.headers.get('Content-Length') or 0)
        while length > 0:
            chunk = self.rfile.read(min(length, 65536))
            if not chunk:
                break
            length -= len(chunk)

    def do_GET(self) -> None:  # noqa: N802
        self._discard_body()
        parsed = urllib.parse.urlparse(self.path)
        params = dict(urllib.parse.parse_qsl(parsed.query))
        if parsed.path == '/health':
            # Additive fields only (wire surface is append-only):
            # version + the authenticated caller, for `xsky api info`.
            from skypilot_tpu import version as version_lib
            from skypilot_tpu.users import core as users_core
            user = users_core.authenticate(
                self.headers.get('Authorization'))
            self._send(200, {'status': 'healthy',
                             'api_version': API_VERSION,
                             'version': version_lib.__version__,
                             'auth_required': users_core.auth_required(),
                             'user': ({'name': user['name'],
                                       'role': user['role']}
                                      if user else None)})
        elif parsed.path == '/metrics':
            # Prometheus text exposition (twin of sky/server/metrics.py).
            # ?name=<prefix> filters to matching series AND skips the
            # state-DB gauge recomputation behind everything else —
            # scrapers sampling one plane don't pay for the fleet sweep.
            data = metrics.render(params.get('name') or None).encode()
            metrics.observe_http('/metrics', 200)
            self.send_response(200)
            self.send_header('Content-Type',
                             'text/plain; version=0.0.4; charset=utf-8')
            self.send_header('Content-Length', str(len(data)))
            self.end_headers()
            self.wfile.write(data)
        elif parsed.path in ('/', '/dashboard', '/dashboard/'):
            from skypilot_tpu import dashboard
            data = dashboard.index_html()
            self.send_response(200)
            self.send_header('Content-Type', 'text/html; charset=utf-8')
            self.send_header('Content-Length', str(len(data)))
            self.end_headers()
            self.wfile.write(data)
        elif parsed.path == '/api/get':
            if not self._authenticated():
                self._send(401, {'error': 'authentication required'})
                return
            code, payload = _get_request(params)
            self._send(code, payload)
        elif parsed.path == '/api/requests':
            if not self._authenticated():
                self._send(401, {'error': 'authentication required'})
                return
            try:
                limit = int(params.get('limit', '100'))
            except (TypeError, ValueError):
                limit = 100
            try:
                offset = max(0, int(params.get('offset', '0')))
            except (TypeError, ValueError):
                offset = 0
            # Clamp: SQLite treats LIMIT -1 as unlimited.
            limit = max(1, min(limit, 1000))
            self._send(200, {'requests':
                             requests_db.list_requests(limit=limit,
                                                       offset=offset)})
        elif parsed.path == '/api/request_log':
            # Incremental captured-output read for the dashboard's
            # request drill-down (live while the request runs).
            caller = self._caller()
            if caller is None:
                self._send(401, {'error': 'authentication required'})
                return
            request_id = params.get('request_id', '')
            # Status-only read: this route tails the log FILE — the
            # row's body/result never leave the DB.
            record = requests_db.get_status(request_id)
            if record is None:
                self._send(404, {'error': f'no request {request_id}'})
                return
            # Captured output may carry env/config details: readable by
            # its submitter and admins only.
            if caller['role'] != 'admin' and \
                    record.get('user') not in (None, caller['name']):
                self._send(403, {'error': 'not your request'})
                return
            try:
                offset = max(0, int(params.get('offset', '0')))
            except (TypeError, ValueError):
                offset = 0
            path = requests_db.log_path(request_id)
            data = ''
            try:
                with open(path, 'rb') as f:
                    f.seek(offset)
                    chunk = f.read(262144)
                data = chunk.decode('utf-8', errors='replace')
                offset += len(chunk)
            except OSError:
                pass
            status = record['status']
            self._send(200, {'request_id': request_id,
                             'status': getattr(status, 'value', status),
                             'offset': offset, 'data': data})
        elif parsed.path == '/api/job_log':
            # Live per-job log tail: one backend poll per GET.
            caller = self._caller()
            if caller is None:
                self._send(401, {'error': 'authentication required'})
                return
            from skypilot_tpu import core as core_lib
            cluster = params.get('cluster_name', '')
            if not self._can_read_cluster(caller, cluster):
                self._send(403, {'error': 'not a member of this '
                                          "cluster's workspace"})
                return
            try:
                job_id = int(params.get('job_id', ''))
                offset = max(0, int(params.get('offset', '0')))
            except (TypeError, ValueError):
                self._send(400, {'error': 'job_id/offset must be ints'})
                return
            try:
                self._send(200, core_lib.watch_job_log(
                    cluster, job_id, offset))
            except Exception as e:  # pylint: disable=broad-except
                self._send(404, {'error': str(e)})
        elif parsed.path == '/api/serve_replica_log':
            # Live replica tail: one task-cluster poll per GET, gated
            # on the service's owning workspace (same isolation as the
            # serve.* verbs).
            caller = self._caller()
            if caller is None:
                self._send(401, {'error': 'authentication required'})
                return
            service = params.get('service_name', '')
            try:
                replica_id = int(params.get('replica_id', ''))
                offset = max(0, int(params.get('offset', '0')))
            except (TypeError, ValueError):
                self._send(400, {'error': 'replica_id/offset must be '
                                          'ints'})
                return
            if not self._can_read_service(caller, service):
                self._send(403, {'error': 'not a member of this '
                                          "service's workspace"})
                return
            from skypilot_tpu.serve import core as serve_core
            try:
                self._send(200, serve_core.watch_replica_logs(
                    service, replica_id, offset))
            except Exception as e:  # pylint: disable=broad-except
                self._send(404, {'error': str(e)})
        elif parsed.path == '/api/managed_job_log':
            # Live managed-job tail: one task-cluster poll per GET,
            # gated on the job's OWNING workspace (same isolation as
            # the jobs.cancel/jobs.logs verbs).
            caller = self._caller()
            if caller is None:
                self._send(401, {'error': 'authentication required'})
                return
            try:
                job_id = int(params.get('job_id', ''))
                offset = max(0, int(params.get('offset', '0')))
            except (TypeError, ValueError):
                self._send(400, {'error': 'job_id/offset must be ints'})
                return
            if not self._can_read_managed_job(caller, job_id):
                self._send(403, {'error': 'not a member of this '
                                          "job's workspace"})
                return
            from skypilot_tpu.jobs import core as jobs_core
            try:
                self._send(200, jobs_core.watch_logs(job_id, offset))
            except Exception as e:  # pylint: disable=broad-except
                self._send(404, {'error': str(e)})
        else:
            self._send(404, {'error': f'no route {parsed.path}'})

    def _authorize(self, verb: str,
                   body: Dict[str, Any]) -> Optional[Tuple[int, str]]:
        """Auth + RBAC (when XSKY_REQUIRE_AUTH=1). Returns (code, error)
        on rejection, None when allowed; fills body['user']/['role']."""
        from skypilot_tpu.users import core as users_core
        from skypilot_tpu.users import rbac
        if not users_core.auth_required():
            # Local single-user mode: admin-equivalent, no credentials.
            body.setdefault('user', 'anon')
            return None
        user = users_core.authenticate(
            self.headers.get('Authorization'))
        if user is None:
            return 401, ('authentication required (Basic auth or '
                         'Bearer token)')
        if not rbac.check_permission(user['role'], verb):
            return 403, (f'role {user["role"]!r} may not call {verb!r}')
        workspace = _target_workspace(verb, body)
        if workspace is not None:
            from skypilot_tpu.workspaces import core as workspaces_core
            if not workspaces_core.check_access(
                    user['name'], user['role'], workspace):
                return 403, (f'user {user["name"]!r} is not a member of '
                             f'workspace {workspace!r}')
        # Attribution only. Never write the caller's role into the body:
        # verbs like users.set_role read a 'role' FIELD from it.
        body['user'] = user['name']
        return None

    def _authenticated(self) -> bool:
        """Plain authentication gate for request-introspection routes."""
        from skypilot_tpu.users import core as users_core
        if not users_core.auth_required():
            return True
        return users_core.authenticate(
            self.headers.get('Authorization')) is not None

    def _caller(self) -> Optional[Dict[str, Any]]:
        """Authenticated user record, or None; {'role': 'admin'} stands
        in when auth is off (local single-user mode)."""
        from skypilot_tpu.users import core as users_core
        if not users_core.auth_required():
            return {'name': 'anon', 'role': 'admin'}
        return users_core.authenticate(
            self.headers.get('Authorization'))

    def _record_workspace_allows(self, user: Dict[str, Any],
                                 record: Optional[Dict[str, Any]]
                                 ) -> bool:
        """Workspace-membership gate shared by every GET log route —
        GETs must match the POST verbs' authz (code-review r4: GETs
        bypassed the isolation the verbs enforce). A missing record
        passes: the handler 404s/NOT_FOUNDs it itself."""
        if record is None:
            return True
        from skypilot_tpu.workspaces import context as ws_context
        from skypilot_tpu.workspaces import core as workspaces_core
        workspace = record.get('workspace') or \
            ws_context.DEFAULT_WORKSPACE
        return workspaces_core.check_access(user['name'], user['role'],
                                            workspace)

    def _can_read_cluster(self, user: Dict[str, Any],
                          cluster_name: str) -> bool:
        from skypilot_tpu import state
        return self._record_workspace_allows(
            user, state.get_cluster_from_name(cluster_name))

    def _can_read_service(self, user: Dict[str, Any],
                          service_name: str) -> bool:
        from skypilot_tpu.serve import state as serve_state
        return self._record_workspace_allows(
            user, serve_state.get_service(service_name))

    def _can_read_managed_job(self, user: Dict[str, Any],
                              job_id: int) -> bool:
        from skypilot_tpu.jobs import state as jobs_state
        return self._record_workspace_allows(
            user, jobs_state.get_job(job_id))

    def do_POST(self) -> None:  # noqa: N802
        parsed = urllib.parse.urlparse(self.path)
        if self.headers.get('Transfer-Encoding'):
            # Chunked bodies are not parsed here — rejecting
            # explicitly beats silently running the verb on an empty
            # body. Close afterwards: under HTTP/1.1 keep-alive the
            # unread chunk data would be parsed as the NEXT request
            # on this connection.
            self.close_connection = True
            self._send(411, {'error': 'chunked request bodies are not '
                                      'supported; send Content-Length'})
            return
        body = self._read_body()
        if parsed.path == '/api/requests/cancel':
            if not self._authenticated():
                self._send(401, {'error': 'authentication required'})
                return
            self._send(200, _cancel_request(body))
            return
        if parsed.path.startswith('/api/'):
            verb = parsed.path[len('/api/'):]
            if not payloads.known_verb(verb):
                self._send(404, {'error': f'unknown verb {verb}'})
                return
            rejected = self._authorize(verb, body)
            if rejected is not None:
                code, error = rejected
                self._send(code, {'error': error})
                return
            try:
                self._send(200, _submit_verb(verb, body))
            except payloads.BadRequest as e:
                self._send(400, {'error': str(e)})
            return
        self._send(404, {'error': f'no route {parsed.path}'})


    @staticmethod
    def _tunnel_target_allowed(host: str) -> bool:
        """Only cluster hosts may be tunneled to — the CONNECT endpoint
        must not be an open relay into the server's network. Override
        with XSKY_TUNNEL_ALLOW_ANY=1 (trusted networks only)."""
        import os
        if os.environ.get('XSKY_TUNNEL_ALLOW_ANY') == '1':
            return True
        from skypilot_tpu import state
        try:
            for record in state.get_clusters():
                handle = record.get('handle')
                info = getattr(handle, 'cluster_info', None)
                for inst in getattr(info, 'instances', {}).values():
                    if host in (inst.internal_ip, inst.external_ip):
                        return True
        except Exception:  # pylint: disable=broad-except
            return False
        return False

    def do_CONNECT(self) -> None:  # noqa: N802
        """TCP tunnel to a cluster host (ssh-over-API-server; twin of the
        reference's websocket proxy, sky/templates/websocket_proxy.py)."""
        import socket
        if not self._authenticated():
            self._send(401, {'error': 'authentication required'})
            return
        host, _, port_s = self.path.partition(':')
        if not self._tunnel_target_allowed(host):
            self._send(403, {'error': f'{host} is not a cluster host'})
            return
        try:
            upstream = socket.create_connection(
                (host, int(port_s or 22)), timeout=30)
        except (OSError, ValueError) as e:
            self._send(502, {'error': f'cannot reach {self.path}: {e}'})
            return
        self.send_response(200, 'Connection established')
        self.end_headers()
        try:
            import select
            # Splice any client bytes the handler's buffered reader read
            # past the CONNECT headers (pipelined first payload).
            self.connection.setblocking(False)
            try:
                pending = self.rfile.read1(65536)
            except (BlockingIOError, ValueError, OSError):
                pending = b''
            self.connection.setblocking(True)
            if pending:
                upstream.sendall(pending)
            conns = [self.connection, upstream]
            while True:
                # Long idle timeout: interactive sessions idle legitimately;
                # dead peers are reaped by TCP resets on the next select.
                readable, _, _ = select.select(conns, [], [], 14400)
                if not readable:
                    break
                done = False
                for src in readable:
                    dst = upstream if src is self.connection else \
                        self.connection
                    data = src.recv(65536)
                    if not data:
                        done = True
                        break
                    dst.sendall(data)
                if done:
                    break
        finally:
            upstream.close()
        self.close_connection = True


class _ApiServer(ThreadingHTTPServer):
    # Default listen backlog (5) resets connections under concurrent
    # client bursts; size for fleets of CLI/SDK pollers.
    request_queue_size = 128
    daemon_threads = True


def make_server(host: str = '127.0.0.1',
                port: int = 46580,
                tls_certfile: Optional[str] = None,
                tls_keyfile: Optional[str] = None
                ) -> ThreadingHTTPServer:
    server = _ApiServer((host, port), _Handler)
    if tls_certfile:
        # TLS at the server socket (deployments without an ingress to
        # terminate HTTPS; the helm chart's ingress path stays the
        # recommended production setup).
        from skypilot_tpu.utils import tls as tls_utils
        tls_utils.wrap_server_socket(server, tls_certfile, tls_keyfile)
    return server


def server_dir() -> str:
    import os
    return os.path.expanduser('~/.xsky/server')


def pid_file() -> str:
    import os
    return os.path.join(server_dir(), 'api.pid')


def log_file() -> str:
    import os
    return os.path.join(server_dir(), 'api.log')


def run(host: str = '127.0.0.1', port: int = 46580,
        tls_certfile: Optional[str] = None,
        tls_keyfile: Optional[str] = None) -> None:
    import os
    import signal
    from skypilot_tpu.users import core as users_core
    if users_core.auth_required():
        users_core.bootstrap_admin_if_empty()
    server = make_server(host, port, tls_certfile=tls_certfile,
                         tls_keyfile=tls_keyfile)
    bound_port = server.server_address[1]   # real port (0 = ephemeral)
    os.makedirs(server_dir(), exist_ok=True)
    with open(pid_file(), 'w', encoding='utf-8') as f:
        f.write(f'{os.getpid()}\n{host}:{bound_port}\n')

    def _on_term(signum, frame):
        # SystemExit unwinds through the finally below; the default
        # SIGTERM disposition would kill without pidfile cleanup.
        del signum, frame
        raise SystemExit(0)

    signal.signal(signal.SIGTERM, _on_term)
    # Horizontal control plane (PR 17): register this process in the
    # live server set BEFORE the startup reconcile, so the pass below
    # already shards by the membership view that includes us. From
    # here on, controller respawns / the recorder role / repair
    # takeovers are arbitrated by leases across every server sharing
    # this state DB (utils/ownership.py).
    try:
        from skypilot_tpu.utils import ownership
        sid = ownership.start_server_lease()
        logger.info(f'Registered server lease server/{sid}')
    except Exception as e:  # pylint: disable=broad-except
        logger.warning(f'Server lease registration failed: {e}')
    # Startup reconciliation (HA, VERDICT r3 #9): jobs/serve/request
    # state lives in sqlite under ~/.xsky (the helm chart's PVC) — a
    # kill -9 of the previous server strands RUNNING requests, WAITING
    # jobs whose controllers died with it, and orphaned task clusters.
    # One reconcile pass repairs every scope (requeue PENDING requests,
    # fail-abort RUNNING ones, re-exec dead jobs/serve controllers,
    # tear down orphan clusters), journalling each repair; the
    # background tick keeps healing crash windows that open while the
    # server runs (a controller OOMing between restarts).
    try:
        from skypilot_tpu import reconciler
        repairs = reconciler.reconcile()
        if repairs:
            logger.info(
                f'Startup reconciliation repaired {len(repairs)} '
                'scope(s): ' + ', '.join(
                    f"{r['action']}:{r['scope']}" for r in repairs))
        reconciler.start_background_reconciler()
    except Exception as e:  # pylint: disable=broad-except
        logger.warning(f'Startup reconciliation failed: {e}')
    # Metrics history recorder: samples the merged /metrics exposition
    # into the bounded metric_points table on an interval and folds the
    # journalled anomaly detectors (utils/metrics_history.py) — the
    # trend substrate `xsky metrics`, `--trend` sparklines and the
    # autoscaler/LB arc read.
    try:
        from skypilot_tpu.utils import metrics_history
        metrics_history.start_background_recorder()
    except Exception as e:  # pylint: disable=broad-except
        logger.warning(f'Metrics recorder failed to start: {e}')
    scheme = 'https' if tls_certfile else 'http'
    logger.info(
        f'xsky API server listening on {scheme}://{host}:{bound_port}')
    try:
        server.serve_forever()
    finally:
        try:
            from skypilot_tpu.utils import ownership
            # Clean exits hand shards back immediately; a SIGKILL
            # skips this and peers re-own within one lease TTL.
            ownership.stop_server_lease()
        except Exception:  # pylint: disable=broad-except
            pass
        try:
            os.remove(pid_file())
        except OSError:
            pass


def run_in_thread(host: str = '127.0.0.1',
                  port: int = 0) -> Tuple[ThreadingHTTPServer, int]:
    """Start in a daemon thread (tests + `xsky api start` child)."""
    server = make_server(host, port)
    thread = threading.Thread(target=server.serve_forever,
                              name='xsky-api-server', daemon=True)
    thread.start()
    return server, server.server_address[1]


if __name__ == '__main__':
    parser = argparse.ArgumentParser()
    parser.add_argument('--host', default='127.0.0.1')
    parser.add_argument('--port', type=int, default=46580)
    parser.add_argument('--tls-certfile', default=None)
    parser.add_argument('--tls-keyfile', default=None)
    args = parser.parse_args()
    run(args.host, args.port, tls_certfile=args.tls_certfile,
        tls_keyfile=args.tls_keyfile)
