"""Hyperbolic marketplace REST transport.

Role twin of sky/provision/hyperbolic/utils.py on this repo's
transport pattern. Key from $HYPERBOLIC_API_KEY or
~/.hyperbolic/api_key.
"""
from __future__ import annotations

import json
import os
import time
import urllib.error
import urllib.request
from typing import Any, Dict, Optional

from skypilot_tpu import exceptions

API_ENDPOINT = 'https://api.hyperbolic.xyz'
CREDENTIALS_PATH = '~/.hyperbolic/api_key'
_MAX_ATTEMPTS = 4
_BACKOFF_S = 2.0


class HyperbolicApiError(Exception):

    def __init__(self, status: int, message: str) -> None:
        super().__init__(f'{status}: {message}')
        self.status = status
        self.message = message


def load_api_key() -> Optional[str]:
    key = os.environ.get('HYPERBOLIC_API_KEY')
    if key:
        return key
    path = os.path.expanduser(CREDENTIALS_PATH)
    if not os.path.exists(path):
        return None
    try:
        with open(path, encoding='utf-8') as f:
            return f.read().strip() or None
    except OSError:
        return None


def classify_error(e: HyperbolicApiError,
                   region: Optional[str] = None) -> Exception:
    text = e.message.lower()
    where = f' in {region}' if region else ''
    if 'no available' in text or 'out of capacity' in text or \
            'insufficient' in text:
        return exceptions.CapacityError(f'Hyperbolic capacity{where}: {e}')
    if 'quota' in text or 'balance' in text:
        return exceptions.QuotaExceededError(
            f'Hyperbolic quota{where}: {e}')
    if e.status in (401, 403):
        return exceptions.PermissionError_(f'Hyperbolic auth: {e}')
    if e.status in (400, 422):
        return exceptions.InvalidRequestError(f'Hyperbolic request: {e}')
    return exceptions.ProvisionError(f'Hyperbolic API{where}: {e}')


class Transport:

    def __init__(self, api_key: Optional[str] = None) -> None:
        key = api_key or load_api_key()
        if not key:
            raise exceptions.PermissionError_(
                'Hyperbolic API key not found (set $HYPERBOLIC_API_KEY '
                f'or populate {CREDENTIALS_PATH}).')
        self._key = key

    def call(self, method: str, path: str,
             body: Optional[Dict[str, Any]] = None) -> Any:
        url = f'{API_ENDPOINT}{path}'
        data = json.dumps(body).encode() if body is not None else None
        for attempt in range(_MAX_ATTEMPTS):
            req = urllib.request.Request(
                url, data=data, method=method,
                headers={'Authorization': f'Bearer {self._key}',
                         'Content-Type': 'application/json'})
            try:
                with urllib.request.urlopen(req, timeout=60) as resp:
                    payload = resp.read()
                    return json.loads(payload) if payload else {}
            except urllib.error.HTTPError as e:
                if e.code == 429 and attempt < _MAX_ATTEMPTS - 1:
                    time.sleep(_BACKOFF_S * (attempt + 1))
                    continue
                try:
                    err = json.loads(e.read() or b'{}')
                    message = err.get('message') or err.get(
                        'error') or str(e)
                    raise HyperbolicApiError(e.code, str(message))
                except (ValueError, AttributeError):
                    raise HyperbolicApiError(e.code, str(e)) from e
            except urllib.error.URLError as e:
                raise exceptions.ProvisionError(
                    f'Hyperbolic API unreachable: {e}') from e
        # Unreachable: every iteration returns or raises.
