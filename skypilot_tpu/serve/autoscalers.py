"""Autoscalers (twin of sky/serve/autoscalers.py: Autoscaler:116,
RequestRateAutoscaler:441, hysteresis :357)."""
from __future__ import annotations

import collections
import dataclasses
import math
import threading
import time
from typing import Deque, Optional

from skypilot_tpu.serve import service_spec as spec_lib


@dataclasses.dataclass
class AutoscalerDecision:
    target_num_replicas: int


class Autoscaler:

    def __init__(self, spec: spec_lib.SkyServiceSpec) -> None:
        self.spec = spec
        self.target_num_replicas = spec.min_replicas

    def collect_request_information(self, num_requests: int,
                                    window_seconds: float) -> None:
        pass

    def evaluate(self, num_ready_replicas: int) -> AutoscalerDecision:
        return AutoscalerDecision(self.spec.min_replicas)

    def split_targets(self, target: int,
                      num_ready_spot: int) -> 'tuple[int, int]':
        """(spot_target, ondemand_target) for a mixed fleet.

        Twin of the reference's FallbackRequestRateAutoscaler
        (sky/serve/autoscalers.py:557): `base_ondemand_fallback_replicas`
        are always on-demand; with `dynamic_ondemand_fallback`,
        not-yet-ready spot replicas are covered by temporary on-demand
        ones (the fleet temporarily overprovisions to target + gap) that
        scale back down as spot capacity recovers.
        """
        spec = self.spec
        base = min(target, spec.base_ondemand_fallback_replicas)
        spot_target = target - base
        ondemand = base
        if spec.dynamic_ondemand_fallback:
            ondemand += max(0, spot_target - num_ready_spot)
        return spot_target, ondemand

    def inherit_state(self, old: 'Autoscaler') -> None:
        """Carry scaling state across a rolling update.

        A `serve update` must not collapse a scaled-up service back to
        min_replicas: the new autoscaler adopts the old target (clamped
        to the new spec's bounds) and, when both sides track QPS, the
        request window — so reconcile_versions drains the old fleet
        only after a same-sized new fleet is ready.
        """
        target = max(self.spec.min_replicas, old.target_num_replicas)
        if self.spec.max_replicas is not None:
            target = min(target, self.spec.max_replicas)
        self.target_num_replicas = target


class FixedReplicaAutoscaler(Autoscaler):
    """No autoscaling: hold min_replicas."""


class RequestRateAutoscaler(Autoscaler):
    """QPS-based scaling with upscale/downscale hysteresis delays.

    Target count = ceil(qps / target_qps_per_replica), clamped to
    [min, max]. A scale decision only takes effect after the respective
    delay has continuously elapsed — preventing flapping (twin of the
    reference's upscale/downscale counters).
    """

    QPS_WINDOW_SECONDS = 60.0

    def __init__(self, spec: spec_lib.SkyServiceSpec) -> None:
        super().__init__(spec)
        # Appended from every LB handler thread, trimmed from the
        # controller tick thread — guard with a lock; a deque keeps the
        # trim O(expired) instead of rebuilding the whole window.
        self._request_timestamps: Deque[float] = collections.deque()
        self._window_lock = threading.Lock()
        self._upscale_since: Optional[float] = None
        self._downscale_since: Optional[float] = None

    def collect_request_information(self, num_requests: int,
                                    window_seconds: float = 0.0) -> None:
        now = time.time()
        cutoff = now - self.QPS_WINDOW_SECONDS
        with self._window_lock:
            ts = self._request_timestamps
            ts.extend([now] * num_requests)
            while ts and ts[0] < cutoff:
                ts.popleft()

    def inherit_state(self, old: 'Autoscaler') -> None:
        super().inherit_state(old)
        if isinstance(old, RequestRateAutoscaler):
            with old._window_lock:
                snapshot = list(old._request_timestamps)
            with self._window_lock:
                self._request_timestamps = collections.deque(snapshot)

    def current_qps(self) -> float:
        self.collect_request_information(0)
        with self._window_lock:
            return len(self._request_timestamps) / self.QPS_WINDOW_SECONDS

    def evaluate(self, num_ready_replicas: int) -> AutoscalerDecision:
        spec = self.spec
        qps = self.current_qps()
        desired = math.ceil(qps / spec.target_qps_per_replica) \
            if spec.target_qps_per_replica else spec.min_replicas
        desired = max(spec.min_replicas,
                      min(desired, spec.max_replicas or desired))
        now = time.time()

        if desired > self.target_num_replicas:
            self._downscale_since = None
            if self._upscale_since is None:
                self._upscale_since = now
            if now - self._upscale_since >= spec.upscale_delay_seconds:
                self.target_num_replicas = desired
                self._upscale_since = None
        elif desired < self.target_num_replicas:
            self._upscale_since = None
            if self._downscale_since is None:
                self._downscale_since = now
            if now - self._downscale_since >= spec.downscale_delay_seconds:
                self.target_num_replicas = desired
                self._downscale_since = None
        else:
            self._upscale_since = None
            self._downscale_since = None
        return AutoscalerDecision(self.target_num_replicas)


def make_autoscaler(spec: spec_lib.SkyServiceSpec) -> Autoscaler:
    if spec.autoscaling_enabled:
        return RequestRateAutoscaler(spec)
    return FixedReplicaAutoscaler(spec)
