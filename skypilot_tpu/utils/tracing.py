"""Request-scoped tracing: spans with parent/child links, persisted.

Every control-plane operation runs inside a *span*; spans belonging to
one logical request share a ``trace_id`` minted at API-server request
acceptance (``server/executor.py``) or lazily at the first span of a
local CLI/SDK call. The context travels:

  * **within a thread** — a :mod:`contextvars` ContextVar, so nested
    ``with span(...)`` blocks chain parent→child automatically;
  * **across threads** — :func:`capture` the context before spawning
    and pass it as ``span(..., parent=ctx)`` in the worker (used by
    ``parallelism.run_in_parallel`` for per-rank spans);
  * **across processes** — ``XSKY_TRACE_CONTEXT=<trace_id>:<span_id>``
    in the child's env (:func:`env_for_child`; the jobs/serve
    controller spawns inject it), so a managed job's recovery spans
    link back to the ``jobs.launch`` request that created it.

Finished spans are persisted to the bounded ``spans`` table in
``state.py`` with the same never-raise discipline as the recovery
journal — tracing must not take down the path it measures. Span ends
also feed the in-process metrics registry
(``xsky_phase_duration_seconds{phase=...}``), which is what the API
server's ``/metrics`` endpoint exports.

Disabled tracing (``XSKY_TRACING=0``) is zero-allocation on the hot
path: :func:`span` returns a module-level no-op singleton — no Span
object, no ids, no DB row, no metric.

Surfaces: ``xsky trace <request-id|cluster|trace-id>`` renders the
waterfall; recovery-journal rows record their ``trace_id`` so
``xsky events`` and ``xsky trace`` cross-link.
"""
from __future__ import annotations

import atexit
import contextvars
import os
import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

ENV_TRACE_CONTEXT = 'XSKY_TRACE_CONTEXT'   # "<trace_id>:<span_id>"
ENV_TRACING = 'XSKY_TRACING'               # "0" disables

# Cross-hop HTTP propagation (the serve LB→replica relay leg): the LB
# injects these on every upstream attempt (so retried legs stay under
# the SAME ids) and the replica handler extracts them onto the
# orchestrator Request — the join key of the request-anatomy waterfall.
HEADER_TRACE_ID = 'X-Xsky-Trace-Id'
HEADER_REQUEST_ID = 'X-Xsky-Request-Id'
# Remaining end-to-end budget in SECONDS at injection time (not an
# absolute wall deadline: the hop's clocks need not agree).
HEADER_DEADLINE_S = 'X-Xsky-Deadline-S'

# Holds the active Span object (this thread opened it) or a
# (trace_id, span_id) tuple (context re-attached from another thread /
# process, where the parent Span object is not ours to annotate).
_ctx: 'contextvars.ContextVar[Any]' = contextvars.ContextVar(
    'xsky_trace', default=None)


def enabled() -> bool:
    return os.environ.get(ENV_TRACING, '1') != '0'


def new_trace_id() -> str:
    return uuid.uuid4().hex[:16]


def _new_span_id() -> str:
    return uuid.uuid4().hex[:16]


def capture() -> Optional[Tuple[str, str]]:
    """The current (trace_id, span_id), or None. Pass the result to
    ``span(..., parent=...)`` from another thread, or to
    :func:`env_for_child` implicitly for a subprocess."""
    cur = _ctx.get()
    if isinstance(cur, Span):
        return (cur.trace_id, cur.span_id)
    if isinstance(cur, tuple):
        return cur
    env = os.environ.get(ENV_TRACE_CONTEXT)
    if env and ':' in env:
        trace_id, _, span_id = env.partition(':')
        if trace_id and span_id:
            return (trace_id, span_id)
    return None


def current_trace_id() -> Optional[str]:
    ctx = capture()
    return ctx[0] if ctx else None


def env_for_child(env: Optional[Dict[str, str]] = None) -> Dict[str, str]:
    """A copy of `env` (default os.environ) carrying the current trace
    context, for detached controller/worker subprocesses. Never raises
    — it sits on the controller-spawn path, and tracing must not take
    down a spawn it merely annotates."""
    out = None
    try:
        out = dict(env if env is not None else os.environ)
        ctx = capture()
        if ctx is not None and enabled():
            out[ENV_TRACE_CONTEXT] = f'{ctx[0]}:{ctx[1]}'
        else:
            out.pop(ENV_TRACE_CONTEXT, None)
        return out
    except Exception:  # pylint: disable=broad-except
        # `out` already holds the plain copy unless dict() itself
        # rejected the input — the handler must stay provably
        # non-raising (the never-raise rule checks it), so no calls
        # here.
        if out is None:
            out = {}
        return out


def inject_headers(headers: Dict[str, str],
                   trace_id: Optional[str] = None,
                   request_id: Optional[Any] = None,
                   deadline_s: Optional[float] = None
                   ) -> Dict[str, str]:
    """Fold the trace context into an outbound header dict (the serve
    LB's upstream relay leg). `trace_id` defaults to the ambient
    context; `deadline_s` is the REMAINING budget, re-measured by the
    caller per attempt so retries shrink it. Mutates and returns
    `headers`. Never raises — it sits on the relay hot path, and a
    malformed id must not turn into a 502."""
    try:
        if trace_id is None:
            trace_id = current_trace_id()
        if trace_id:
            headers[HEADER_TRACE_ID] = str(trace_id)
        if request_id is not None:
            headers[HEADER_REQUEST_ID] = str(request_id)
        if deadline_s is not None:
            headers[HEADER_DEADLINE_S] = f'{float(deadline_s):.3f}'
        return headers
    except Exception:  # pylint: disable=broad-except
        return headers


def extract_headers(headers: Any
                    ) -> Tuple[Optional[str], Optional[str],
                               Optional[float]]:
    """(trace_id, request_id, deadline_s) from an inbound request's
    headers (an ``http.server`` message object or a plain dict).
    Missing or malformed values degrade to None — the replica must
    serve untraced requests exactly as before. Never raises."""
    out = (None, None, None)
    try:
        trace_id = headers.get(HEADER_TRACE_ID) or None
        request_id = headers.get(HEADER_REQUEST_ID) or None
        raw = headers.get(HEADER_DEADLINE_S)
        deadline_s = float(raw) if raw else None
        return (trace_id, request_id, deadline_s)
    except Exception:  # pylint: disable=broad-except
        return out


def annotate_append(key: str, value: Any) -> None:
    """Append `value` to a list-valued attribute of the current span
    (used by chaos to record every fault injected under the span).
    Never raises."""
    try:
        cur = _ctx.get()
        if isinstance(cur, Span):
            cur.attrs.setdefault(key, []).append(value)
    except Exception:  # pylint: disable=broad-except
        pass


class _NoopSpan:
    """Singleton returned when tracing is disabled: nothing allocated,
    nothing recorded."""
    __slots__ = ()

    trace_id = None
    span_id = None

    def __enter__(self) -> '_NoopSpan':
        return self

    def __exit__(self, *exc) -> bool:
        return False

    def set(self, **attrs: Any) -> None:
        pass


NOOP_SPAN = _NoopSpan()


class Span:
    """One traced operation. Context manager; records on exit.

    ``process_top`` marks a span with no in-process parent Span (a
    true root, or the top of this process's contribution to a trace
    inherited via env) — its exit flushes the span buffer, so a
    long-lived controller's spans become visible per operation, not
    per process lifetime.
    """

    __slots__ = ('trace_id', 'span_id', 'parent_span_id', 'name',
                 'attrs', 'status', 'process_top', '_start', '_token')

    def __init__(self, name: str, trace_id: str,
                 parent_span_id: Optional[str],
                 attrs: Dict[str, Any],
                 process_top: bool = False) -> None:
        self.name = name
        self.trace_id = trace_id
        self.span_id = _new_span_id()
        self.parent_span_id = parent_span_id
        self.attrs = attrs
        self.status = 'OK'
        self.process_top = process_top
        self._start = 0.0
        self._token = None

    def set(self, **attrs: Any) -> None:
        self.attrs.update(attrs)

    def __enter__(self) -> 'Span':
        self._start = time.time()
        self._token = _ctx.set(self)
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if self._token is not None:
            _ctx.reset(self._token)
            self._token = None
        end = time.time()
        if exc_type is not None:
            self.status = 'ERROR'
            self.attrs.setdefault('error', f'{exc_type.__name__}: '
                                           f'{str(exc)[:300]}')
        self._record(end)
        return False

    def _record(self, end_ts: float) -> None:
        """Buffer for persistence + feed metrics. NEVER raises —
        tracing is observability, and these run on provisioning and
        recovery paths. Spans are BATCHED (a per-span sqlite commit
        would put an fsync on every fan-out rank — measured at ~43%
        launch overhead on 16 hosts before batching): buffered rows
        flush on root-span exit, every _FLUSH_AT spans, when the
        buffer goes stale, and at process exit."""
        _enqueue({
            'trace_id': self.trace_id, 'span_id': self.span_id,
            'parent_span_id': self.parent_span_id, 'name': self.name,
            'start_ts': self._start, 'end_ts': end_ts,
            'status': self.status, 'attrs': self.attrs or None,
        }, root=self.process_top)
        if self.name.endswith('.rank'):
            # Rank spans already feed the dedicated
            # xsky_fanout_rank_duration_seconds histogram (with a
            # clean phase label) — double-counting them here would
            # mint a pseudo-phase series per fan-out phase.
            return
        try:
            from skypilot_tpu.utils import metrics
            metrics.observe(
                'xsky_phase_duration_seconds',
                'Traced phase duration by span name.',
                max(0.0, end_ts - self._start), phase=self.name,
                status=self.status)
        except Exception:  # pylint: disable=broad-except
            pass


# ---- span buffer -----------------------------------------------------------
# One sqlite commit per span would fsync on every fan-out rank of
# every phase; the buffer turns a launch's worth of spans into a
# handful of batched writes (state.record_spans).

_FLUSH_AT = 64            # rows
_STALE_FLUSH_S = 5.0      # long-lived controllers: don't sit unflushed
_buffer_lock = threading.Lock()
_buffer: List[Dict[str, Any]] = []
_last_flush = 0.0
_atexit_registered = False


def _enqueue(row: Dict[str, Any], root: bool) -> None:
    global _last_flush, _atexit_registered
    rows = None
    try:
        now = time.monotonic()
        with _buffer_lock:
            _buffer.append(row)
            if _last_flush == 0.0:
                # First span of the process: start the staleness clock
                # here, or monotonic-minus-zero would force a solo
                # flush of row one.
                _last_flush = now
            if not _atexit_registered:
                atexit.register(flush)
                _atexit_registered = True
            if root or len(_buffer) >= _FLUSH_AT or \
                    now - _last_flush > _STALE_FLUSH_S:
                rows = list(_buffer)
                _buffer.clear()
                _last_flush = now
    except Exception:  # pylint: disable=broad-except
        return
    if rows:
        _write(rows)


def flush() -> None:
    """Drain the span buffer to the state DB. Never raises. Called at
    root-span exit / process exit; tests call it before reading
    spans of still-open traces."""
    try:
        with _buffer_lock:
            rows = list(_buffer)
            _buffer.clear()
        if rows:
            _write(rows)
    except Exception:  # pylint: disable=broad-except
        pass


def _write(rows: List[Dict[str, Any]]) -> None:
    try:
        from skypilot_tpu import state
        state.record_spans(rows)
    except Exception:  # pylint: disable=broad-except
        pass


def reset_for_test() -> None:
    global _last_flush
    with _buffer_lock:
        _buffer.clear()
        _last_flush = 0.0


def span(name: str, parent: Optional[Tuple[str, str]] = None,
         **attrs: Any) -> Any:
    """Open a span named `name`.

    With tracing disabled, returns the no-op singleton. Otherwise the
    span joins the active trace (contextvar, then the env handoff);
    with no active trace it becomes the root of a freshly minted one —
    local CLI/SDK calls get a complete tree without an explicit
    request boundary. `parent` overrides the ambient context (thread
    fan-out: pass the :func:`capture` of the spawning thread).
    """
    try:
        if not enabled():
            return NOOP_SPAN
        if parent is not None:
            # Explicit parent (thread fan-out): the spawning thread's
            # span owns the buffer flush.
            return Span(name, parent[0], parent[1], attrs)
        # No in-process parent Span ⇒ this span is the top of THIS
        # process's contribution (a fresh root, or env-inherited
        # trace): its exit flushes the buffer.
        top = not isinstance(_ctx.get(), Span)
        ctx = capture()
        if ctx is None:
            return Span(name, new_trace_id(), None, attrs,
                        process_top=top)
        return Span(name, ctx[0], ctx[1], attrs, process_top=top)
    except Exception:  # pylint: disable=broad-except
        # Tracing must never take down the path it measures: a failed
        # span open degrades to not recording this operation.
        return NOOP_SPAN


def request_span(trace_id: Optional[str], name: str, **attrs: Any) -> Any:
    """Root span of a request-scoped trace (API-server executor): the
    trace_id was minted at acceptance so the id is known before the
    work runs. Falls back to :func:`span` semantics when tracing is
    disabled or no id was minted."""
    try:
        if not enabled():
            return NOOP_SPAN
        if trace_id is None:
            return span(name, **attrs)
        return Span(name, trace_id, None, attrs, process_top=True)
    except Exception:  # pylint: disable=broad-except
        return NOOP_SPAN
