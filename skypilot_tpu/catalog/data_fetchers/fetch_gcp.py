"""Offline GCP catalog generator (twin of sky/catalog/data_fetchers/fetch_gcp.py).

The reference queries the Cloud Billing SKU service (fetch_gcp.py:34-83) and
hand-patches hidden TPU zones. This build has no egress, so the generator
embeds a snapshot of public list prices (2025) and *derives* every TPU slice
offering from the topology database — chips, hosts, HBM and price scale
consistently with slice size by construction.

Run ``python -m skypilot_tpu.catalog.data_fetchers.fetch_gcp`` to regenerate
``skypilot_tpu/catalog/data/gcp/catalog.csv``; `load_catalog` also invokes
:func:`generate` lazily when the CSV is missing.
"""
from __future__ import annotations

from typing import Dict, List, Tuple

from skypilot_tpu.catalog import common
from skypilot_tpu.utils import tpu_topology

# ---- TPU price snapshot: $/chip-hour (on-demand, spot) by generation ------
# Public list prices, us-central-ish regions. Regions without published v6e
# pricing get 0.0 like the reference does (examples/tpu/v6e/README.md:7-9).
_TPU_CHIP_PRICES: Dict[str, Tuple[float, float]] = {
    'v2': (1.125, 0.3375),
    'v3': (2.00, 0.60),
    'v4': (3.22, 0.966),
    'v5e': (1.20, 0.42),
    'v5p': (4.20, 1.47),
    'v6e': (2.70, 0.945),
}

# Zones where each TPU generation is offered (snapshot).
_TPU_ZONES: Dict[str, List[str]] = {
    'v2': ['us-central1-b', 'us-central1-c', 'europe-west4-a'],
    'v3': ['us-central1-a', 'us-central1-b', 'europe-west4-a'],
    'v4': ['us-central2-b'],
    'v5e': [
        'us-central1-a', 'us-west4-a', 'us-east1-c', 'us-east5-b',
        'europe-west4-b', 'asia-southeast1-b'
    ],
    'v5p': ['us-east5-a', 'us-central2-b', 'europe-west4-b'],
    'v6e': [
        'us-central2-b', 'us-east5-b', 'europe-west4-a', 'asia-northeast1-b',
        'us-south1-a'
    ],
}

# Host VM shape fronting each TPU generation (vCPUs, memory GiB) per host.
# v2/v3 figures match the reference's forced host sizes
# (sky/clouds/gcp.py:688-739: 96 vCPU / 334 GB; v4: 240/400).
_TPU_HOST_SPECS: Dict[str, Tuple[float, float]] = {
    'v2': (96, 334),
    'v3': (96, 334),
    'v4': (240, 400),
    'v5e': (112, 192),
    'v5p': (208, 448),
    'v6e': (180, 720),
}

# ---- GPU / CPU VM snapshot ------------------------------------------------
# (instance_type, acc_name, acc_count, vcpus, mem, acc_mem_gib, price, spot)
_GPU_VMS = [
    ('a2-highgpu-1g', 'A100', 1, 12, 85, 40, 3.673, 1.102),
    ('a2-highgpu-2g', 'A100', 2, 24, 170, 80, 7.347, 2.204),
    ('a2-highgpu-4g', 'A100', 4, 48, 340, 160, 14.694, 4.408),
    ('a2-highgpu-8g', 'A100', 8, 96, 680, 320, 29.387, 8.816),
    ('a2-ultragpu-1g', 'A100-80GB', 1, 12, 170, 80, 5.069, 1.521),
    ('a2-ultragpu-8g', 'A100-80GB', 8, 96, 1360, 640, 40.550, 12.165),
    ('a3-highgpu-8g', 'H100', 8, 208, 1872, 640, 88.249, 26.475),
    ('g2-standard-4', 'L4', 1, 4, 16, 24, 0.705, 0.212),
    ('g2-standard-48', 'L4', 4, 48, 192, 96, 3.997, 1.199),
    ('n1-standard-8-t4', 'T4', 1, 8, 30, 16, 0.730, 0.219),
    ('n1-standard-8-v100', 'V100', 1, 8, 30, 16, 2.860, 0.858),
]
_CPU_VMS = [
    ('n2-standard-2', 2, 8, 0.0971, 0.0291),
    ('n2-standard-4', 4, 16, 0.1942, 0.0583),
    ('n2-standard-8', 8, 32, 0.3885, 0.1165),
    ('n2-standard-16', 16, 64, 0.7769, 0.2331),
    ('n2-standard-32', 32, 128, 1.5539, 0.4662),
    ('n2-highmem-8', 8, 64, 0.5241, 0.1572),
]
_VM_ZONES = [
    'us-central1-a', 'us-central1-b', 'us-central2-b', 'us-west4-a',
    'us-east1-c', 'us-east5-a', 'us-east5-b', 'europe-west4-a',
    'europe-west4-b', 'asia-northeast1-b', 'asia-southeast1-b', 'us-south1-a'
]


def _region_of(zone: str) -> str:
    return zone.rsplit('-', 1)[0]


def generate() -> List[common.CatalogEntry]:
    entries: List[common.CatalogEntry] = []

    # TPU slices: every standard size × every zone for the generation.
    for gen_name, zones in _TPU_ZONES.items():
        gen = tpu_topology.GENERATIONS[gen_name]
        od_chip, spot_chip = _TPU_CHIP_PRICES[gen_name]
        host_vcpus, host_mem = _TPU_HOST_SPECS[gen_name]
        for chips in tpu_topology.list_standard_sizes(gen_name):
            count = chips * gen.cores_per_chip if gen.cores_per_chip > 1 \
                else chips
            name = f'tpu-{gen_name}-{count}'
            topo = tpu_topology.parse(name)
            for zone in zones:
                # v6e pricing not published in US central/south regions
                # (mirrors reference behavior of 0.0 placeholders).
                od, spot = od_chip * chips, spot_chip * chips
                if gen_name == 'v6e' and _region_of(zone) in (
                        'us-central2', 'us-south1'):
                    od, spot = 0.0, 0.0
                entries.append(
                    common.CatalogEntry(
                        instance_type='',
                        accelerator_name=name,
                        accelerator_count=1,
                        vcpus=host_vcpus * topo.num_hosts,
                        memory_gib=host_mem * topo.num_hosts,
                        accelerator_memory_gib=topo.hbm_gib,
                        price=od,
                        spot_price=spot,
                        region=_region_of(zone),
                        zone=zone,
                    ))

    for (itype, acc, n, vcpus, mem, acc_mem, price, spot) in _GPU_VMS:
        for zone in _VM_ZONES:
            entries.append(
                common.CatalogEntry(itype, acc, n, vcpus, mem, acc_mem, price,
                                    spot, _region_of(zone), zone))
    for (itype, vcpus, mem, price, spot) in _CPU_VMS:
        for zone in _VM_ZONES:
            entries.append(
                common.CatalogEntry(itype, '', 0, vcpus, mem, 0, price, spot,
                                    _region_of(zone), zone))
    return entries


if __name__ == '__main__':
    path = common.save_catalog('gcp', generate())
    print(f'Wrote {path}')
