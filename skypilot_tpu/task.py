"""Task: a user workload (twin of sky/task.py:236).

YAML surface kept compatible with the reference (name / workdir / num_nodes /
resources / envs / secrets / file_mounts / setup / run / service / config),
so reference task YAMLs port with at most resource-name edits.
"""
from __future__ import annotations

import os
import re
import typing
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

import yaml

from skypilot_tpu import resources as resources_lib
from skypilot_tpu.utils import common_utils

if typing.TYPE_CHECKING:
    from skypilot_tpu.data import storage as storage_lib

_VALID_NAME_RE = re.compile(r'^[a-zA-Z0-9]+(?:[._-]{1,2}[a-zA-Z0-9]+)*$')

CommandOrCommandGen = Union[None, str, Callable[[int, List[str]], str]]

_RUN_FN_CHECK_FAIL_MSG = (
    'run command generator must take (node_rank: int, ip_list: List[str]) '
    'and return a shell command string or None.')


class Task:

    def __init__(
        self,
        name: Optional[str] = None,
        *,
        setup: Optional[str] = None,
        run: CommandOrCommandGen = None,
        envs: Optional[Dict[str, str]] = None,
        secrets: Optional[Dict[str, str]] = None,
        workdir: Optional[str] = None,
        num_nodes: Optional[int] = None,
        file_mounts: Optional[Dict[str, str]] = None,
    ) -> None:
        self.name = name
        self.setup = setup
        self.run = run
        self.workdir = workdir
        self._envs = dict(envs) if envs else {}
        self._secrets = dict(secrets) if secrets else {}
        self.num_nodes = num_nodes if num_nodes is not None else 1
        self.file_mounts: Optional[Dict[str, str]] = \
            dict(file_mounts) if file_mounts else None
        self.storage_mounts: Dict[str, 'storage_lib.Storage'] = {}
        self.service: Optional[Any] = None  # serve.SkyServiceSpec
        self._resources: List[resources_lib.Resources] = \
            [resources_lib.Resources()]
        self._resources_ordered = False
        # DAG wiring (set by Dag context)
        self._validate()

    def _validate(self) -> None:
        if self.name is not None and not _VALID_NAME_RE.match(self.name):
            raise ValueError(f'Invalid task name {self.name!r}')
        if self.num_nodes < 1:
            raise ValueError('num_nodes must be >= 1')
        if self.run is not None and not isinstance(self.run, str) and \
                not callable(self.run):
            raise ValueError(_RUN_FN_CHECK_FAIL_MSG)
        for key in self._envs:
            if not re.match(r'^[A-Za-z_][A-Za-z0-9_]*$', key):
                raise ValueError(f'Invalid env var name {key!r}')
        if self.workdir is not None:
            expanded = os.path.expanduser(self.workdir)
            if os.path.isabs(expanded) and not os.path.isdir(expanded):
                raise ValueError(
                    f'workdir {self.workdir!r} does not exist or is not a '
                    'directory. (Relative workdirs resolve at launch.)')

    # ---- resources ----

    @property
    def resources(self) -> List[resources_lib.Resources]:
        return self._resources

    @property
    def resources_ordered(self) -> bool:
        """True if the user ranked candidates (ordered:) — optimizer must
        respect the order rather than cost-rank."""
        return self._resources_ordered

    def set_resources(
        self, resources: Union[resources_lib.Resources,
                               List[resources_lib.Resources]],
        ordered: bool = False
    ) -> 'Task':
        if isinstance(resources, resources_lib.Resources):
            resources = [resources]
        if not resources:
            raise ValueError('resources must be non-empty')
        self._resources = list(resources)
        self._resources_ordered = ordered
        return self

    # ---- envs / secrets ----

    @property
    def envs(self) -> Dict[str, str]:
        return dict(self._envs)

    @property
    def secrets(self) -> Dict[str, str]:
        return dict(self._secrets)

    @property
    def envs_and_secrets(self) -> Dict[str, str]:
        out = dict(self._envs)
        out.update(self._secrets)
        return out

    def update_envs(self, envs: Dict[str, str]) -> 'Task':
        for k, v in envs.items():
            if v is None:
                raise ValueError(
                    f'Env var {k!r} has no value; pass --env {k}=VALUE.')
            self._envs[k] = str(v)
        return self

    def update_secrets(self, secrets: Dict[str, str]) -> 'Task':
        for k, v in secrets.items():
            if v is None:
                raise ValueError(
                    f'Secret {k!r} has no value; pass --secret {k}=VALUE.')
            self._secrets[k] = str(v)
        return self

    # ---- mounts ----

    def set_file_mounts(self, file_mounts: Optional[Dict[str, str]]) -> 'Task':
        self.file_mounts = dict(file_mounts) if file_mounts else None
        return self

    def update_file_mounts(self, file_mounts: Dict[str, str]) -> 'Task':
        if self.file_mounts is None:
            self.file_mounts = {}
        self.file_mounts.update(file_mounts)
        return self

    def set_storage_mounts(self, storage_mounts) -> 'Task':
        from skypilot_tpu.data import storage as storage_lib2
        converted = {}
        for target, value in (storage_mounts or {}).items():
            if isinstance(value, dict):
                value = storage_lib2.Storage.from_yaml_config(value)
            converted[target] = value
        self.storage_mounts = converted
        return self

    def sync_storage_mounts(self) -> 'Task':
        """Create buckets + upload local sources for all storage mounts.

        Twin of sky/task.py:1200 — runs client/server-side before the
        cluster-side mount stage.
        """
        for storage in self.storage_mounts.values():
            storage.sync_all_stores()
        return self

    # ---- YAML ----

    @classmethod
    def from_yaml_config(cls, config: Dict[str, Any],
                         env_overrides: Optional[Dict[str, str]] = None,
                         secret_overrides: Optional[Dict[str, str]] = None
                         ) -> 'Task':
        from skypilot_tpu.utils import schemas
        schemas.validate_task_config(config)
        config = dict(config or {})
        envs = dict(config.pop('envs', None) or {})
        secrets = dict(config.pop('secrets', None) or {})
        if env_overrides:
            envs.update(env_overrides)
        if secret_overrides:
            secrets.update(secret_overrides)
        missing = [k for k, v in {**envs, **secrets}.items() if v is None]
        if missing:
            raise ValueError(
                f'Env/secret(s) {missing} declared with null values; '
                'pass values via --env/--secret.')

        raw_mounts = config.pop('file_mounts', None)
        plain_mounts: Optional[Dict[str, str]] = None
        storage_mounts: Dict[str, Any] = {}
        if raw_mounts:
            from skypilot_tpu.data import storage as storage_lib2
            plain_mounts, storage_mounts = (
                storage_lib2.storage_mounts_from_file_mounts(raw_mounts))
        task = cls(
            name=config.pop('name', None),
            setup=config.pop('setup', None),
            run=config.pop('run', None),
            envs=envs,
            secrets=secrets,
            workdir=config.pop('workdir', None),
            num_nodes=config.pop('num_nodes', None),
            file_mounts=plain_mounts,
        )
        if storage_mounts:
            task.set_storage_mounts(storage_mounts)
        resources_config = config.pop('resources', None)
        parsed = resources_lib.Resources.from_yaml_config(resources_config)
        ordered = bool(resources_config) and 'ordered' in resources_config
        task.set_resources(parsed, ordered=ordered)

        service = config.pop('service', None)
        if service is not None:
            from skypilot_tpu.serve import service_spec
            task.service = service_spec.SkyServiceSpec.from_yaml_config(
                service)

        config.pop('config', None)  # per-task config overrides; applied by
        # execution via skypilot_tpu.config.override.
        unknown = set(config)
        if unknown:
            raise ValueError(f'Unknown task fields: {sorted(unknown)}')
        return task

    @classmethod
    def from_yaml(cls, path: str, **kwargs) -> 'Task':
        with open(os.path.expanduser(path), 'r', encoding='utf-8') as f:
            config = yaml.safe_load(f)
        if config is None:
            config = {}
        if isinstance(config, str):
            raise ValueError(
                f'{path} is not a task YAML (parsed as a string).')
        return cls.from_yaml_config(config, **kwargs)

    @staticmethod
    def chain_to_config(task) -> Any:
        """Wire/DB form of one Task or a pipeline sequence: a single
        config dict, or a list of them. The ONE place that decides the
        single-vs-chain encoding (local submit, controller relay, and
        API client all call this)."""
        tasks = (list(task) if isinstance(task, (list, tuple))
                 else [task])
        if not tasks:
            raise ValueError('empty task chain')
        if len(tasks) > 1:
            return [t.to_yaml_config() for t in tasks]
        return tasks[0].to_yaml_config()

    @classmethod
    def load_chain(cls, path: str, **kwargs
                   ) -> Tuple[Optional[str], List['Task']]:
        """Load a pipeline YAML: `---`-separated task documents run as
        a sequential chain (twin of the reference's chain-DAG yaml,
        sky/utils/dag_utils.py load_chain_dag_from_yaml). An optional
        leading document containing only `name:` names the pipeline.
        A single-document file yields (None, [task]).
        """
        with open(os.path.expanduser(path), 'r', encoding='utf-8') as f:
            docs = [d for d in yaml.safe_load_all(f) if d]
        name = None
        if docs and set(docs[0]) <= {'name'}:
            name = docs[0].get('name')
            docs = docs[1:]
        if not docs:
            raise ValueError(f'{path} contains no task documents.')
        return name, [cls.from_yaml_config(d, **kwargs) for d in docs]

    def to_yaml_config(self) -> Dict[str, Any]:
        config: Dict[str, Any] = {}

        def add(key, value):
            if value is not None and value != {} and value != []:
                config[key] = value

        add('name', self.name)
        if len(self._resources) == 1:
            add('resources', self._resources[0].to_yaml_config())
        else:
            key = 'ordered' if self._resources_ordered else 'any_of'
            add('resources',
                {key: [r.to_yaml_config() for r in self._resources]})
        add('num_nodes', self.num_nodes if self.num_nodes != 1 else None)
        add('workdir', self.workdir)
        add('envs', self._envs or None)
        add('secrets', self._secrets or None)
        all_mounts: Dict[str, Any] = dict(self.file_mounts or {})
        for target, storage in (self.storage_mounts or {}).items():
            all_mounts[target] = storage.to_yaml_config()
        add('file_mounts', all_mounts or None)
        add('setup', self.setup)
        if isinstance(self.run, str):
            add('run', self.run)
        if self.service is not None:
            add('service', self.service.to_yaml_config())
        return config

    def to_yaml(self, path: str) -> None:
        with open(os.path.expanduser(path), 'w', encoding='utf-8') as f:
            f.write(common_utils.dump_yaml_str(self.to_yaml_config()))

    def __repr__(self) -> str:
        name = self.name or '<unnamed>'
        r = self._resources[0] if len(self._resources) == 1 else \
            f'{len(self._resources)} candidates'
        return f'Task({name}, num_nodes={self.num_nodes}, resources={r})'
