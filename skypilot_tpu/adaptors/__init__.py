"""Lazy cloud-SDK adaptors (twin of sky/adaptors/)."""
