"""Remote serve-controller mode: controller + LB on a provisioned cluster.

Twin of the reference's serve-controller-as-a-cluster
(sky/templates/sky-serve-controller.yaml.j2 + sky/serve/service.py:155):
the API server provisions a dedicated controller cluster once, then
forwards every serve verb to it by running
``python -m skypilot_tpu.serve.remote_exec <verb>`` on the controller
head over the backend command runner (shared relay:
utils/controller_relay.py). The serve DB, every service's controller
process, and the load balancers live on that cluster — an
API-server-host crash no longer takes the services' control loops (or
their traffic path) with it, and a restarted API server reattaches by
relaying ``status`` to the still-running controller cluster.

Enabled with XSKY_SERVE_CONTROLLER_REMOTE=1 (or =<cluster-name>).
Controller sizing comes from config key serve.controller.resources.
"""
from __future__ import annotations

import json
import os
import tempfile
from typing import Any, Dict, List, Optional

from skypilot_tpu import sky_logging
from skypilot_tpu import task as task_lib
from skypilot_tpu.utils import controller_relay

logger = sky_logging.init_logger(__name__)

_relay = controller_relay.ControllerRelay(
    env_var='XSKY_SERVE_CONTROLLER_REMOTE',
    default_cluster='xsky-serve-controller',
    config_key=('serve', 'controller', 'resources'),
    exec_module='skypilot_tpu.serve.remote_exec',
    task_name='serve-controller',
    payload_dir='.xsky/serve_tasks',
    not_up_hint='run `serve up` first.')

cluster_name = _relay.cluster_name
ensure_controller_cluster = _relay.ensure_controller_cluster


def _head_host(handle) -> str:
    # Local-process providers (fake, ssh-to-self) report fictitious
    # cluster IPs; their LB really listens on this host's loopback.
    if getattr(handle, 'is_local_provider', False):
        return '127.0.0.1'
    try:
        ips = handle.cluster_info.get_feasible_ips()
        if ips:
            return ips[0]
    except Exception:  # pylint: disable=broad-except
        pass
    return '127.0.0.1'


def _payload_call(verb: str, task: task_lib.Task, *args: str,
                  provision: bool) -> Any:
    with tempfile.NamedTemporaryFile('w', suffix='.json',
                                     prefix='xsky-serve-',
                                     delete=False) as f:
        f.write(json.dumps(task.to_yaml_config()))
        local_path = f.name
    try:
        return _relay.call(verb, *args, payload_file=local_path,
                           provision=provision)
    finally:
        os.unlink(local_path)


def up(task: task_lib.Task, service_name: Optional[str],
       wait_ready: bool, timeout_s: float) -> str:
    reply = _payload_call(
        'up', task, *(['--name', service_name] if service_name else []),
        '--wait' if wait_ready else '--nowait', str(timeout_s),
        provision=True)
    return reply['service_name']


def update(task: task_lib.Task, service_name: str, wait_done: bool,
           timeout_s: float, mode: str = 'rolling') -> int:
    # `mode` is appended only when non-default, so a newer client can
    # still drive a controller host provisioned before the arg existed
    # (its exec does a fixed 4-way unpack); remote_exec defaults the
    # missing arg for the same reason in the other direction.
    extra = [mode] if mode != 'rolling' else []
    reply = _payload_call('update', task, service_name,
                          '--wait' if wait_done else '--nowait',
                          str(timeout_s), *extra, provision=False)
    return int(reply['version'])


def status(service_names: Optional[List[str]]) -> List[Dict[str, Any]]:
    bh = _relay.backend_and_handle(provision=False)
    reply = _relay.call('status', json.dumps(service_names or []),
                        backend_and_handle=bh)
    host = _head_host(bh[1])

    def _rewrite(endpoint):
        # The controller host reports loopback endpoints; rewrite to
        # the controller cluster's address for off-host clients
        # (preserving an https:// scheme from a TLS-terminating LB).
        if not endpoint:
            return endpoint
        scheme = ''
        if '://' in endpoint:
            scheme, endpoint = endpoint.split('://', 1)
            scheme += '://'
        return f"{scheme}{host}:{endpoint.rsplit(':', 1)[-1]}"

    for record in reply:
        record['endpoint'] = _rewrite(record.get('endpoint'))
        for rep in record.get('replicas', []):
            rep['endpoint'] = _rewrite(rep.get('endpoint'))
    return reply


def down(service_name: str) -> None:
    _relay.call('down', service_name)


def tail_logs(service_name: str, replica_id: int,
              job_id: Optional[int]) -> str:
    reply = _relay.call('logs', service_name, str(replica_id),
                        str(job_id if job_id is not None else -1))
    return reply['logs']


def controller_logs(service_name: str) -> str:
    return _relay.call('controller-logs', service_name)['logs']


def metrics_history(service_name: str, limit: int) -> List[Dict[str, Any]]:
    return _relay.call('history', service_name, str(int(limit)))


def watch_replica_logs(service_name: str, replica_id: int,
                       offset: int) -> Dict[str, Any]:
    return _relay.call('watch-logs', service_name, str(int(replica_id)),
                       str(int(offset)))
