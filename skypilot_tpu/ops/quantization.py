"""Int8 weight-only quantization for serving.

Role-twin of the reference's serving quantization (the v6e serving
recipe quantizes weights to fit + feed the chip; cf. JetStream-class
engines), designed TPU-first: weights are stored int8 with
per-output-channel fp32 scales and dequantized INSIDE the consuming
matmul — `(x @ w_q.astype(bf16)) * scale` — which XLA fuses into the
matmul epilogue. Decode is HBM-bandwidth-bound, so halving the bytes
per weight read is a direct step-time win, and an 8B model's weights
(16 GB bf16) fit a single 16 GB chip at int8.

Design notes:
  * `QuantizedTensor` is a registered pytree: it flows through jit,
    `lax.scan` (leading-axis slices of both q and scale stay paired),
    and device_put without special cases.
  * The contraction axis is static aux data, counted FROM THE END so a
    stacked `[L, in, out]` weight stays valid after scan slices it to
    `[in, out]`.
  * `matmul`/`embed_rows`/`tied_head`/`expert_einsum` dispatch on
    type: plain arrays pass through untouched, so training code paths
    share the same call sites at zero cost.
  * Scales are fp32 `max(|w|)/127` per output channel — symmetric,
    zero-point-free, which keeps the dequant a single fused multiply.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict

import jax
import jax.numpy as jnp

Params = Dict[str, Any]


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass(frozen=True)
class QuantizedTensor:
    """int8 values + per-output-channel fp32 scales.

    `axis` is the CONTRACTION axis as a negative index; `scale` has
    the shape of `q` with that axis removed.
    """
    q: jax.Array
    scale: jax.Array
    axis: int = -2

    def tree_flatten(self):
        return (self.q, self.scale), self.axis

    @classmethod
    def tree_unflatten(cls, axis, children):
        q, scale = children
        return cls(q, scale, axis)

    @property
    def shape(self):
        return self.q.shape

    @property
    def ndim(self) -> int:
        return self.q.ndim

    @property
    def dtype(self):
        return self.q.dtype

    @property
    def nbytes(self) -> int:
        return self.q.nbytes + self.scale.nbytes


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass(frozen=True)
class Quantized4Tensor:
    """Packed int4 values + group-wise fp32 scales.

    Two signed 4-bit values per byte along the CONTRACTION axis (so
    unpack happens where the consumer contracts): `q_packed` has that
    axis halved. `scale` has the contraction axis replaced by the
    group count G = in/group — int4's 3-bit mantissa needs finer than
    per-channel scaling to stay useful, and group-wise (AWQ-style) is
    the standard accuracy/size point. Scales vary ALONG the
    contraction, so dequant happens on the matmul operand (XLA fuses
    the unpack+scale into the dot's operand read — weight HBM traffic
    stays int4) rather than in the epilogue like int8.
    """
    q_packed: jax.Array
    scale: jax.Array
    axis: int = -2
    group: int = 128

    def tree_flatten(self):
        return (self.q_packed, self.scale), (self.axis, self.group)

    @classmethod
    def tree_unflatten(cls, aux, children):
        q_packed, scale = children
        return cls(q_packed, scale, aux[0], aux[1])

    @property
    def shape(self):
        """LOGICAL shape (unpacked)."""
        s = list(self.q_packed.shape)
        s[self.axis] *= 2
        return tuple(s)

    @property
    def ndim(self) -> int:
        return self.q_packed.ndim

    @property
    def nbytes(self) -> int:
        return self.q_packed.nbytes + self.scale.nbytes


def _pack4(q: jax.Array, axis: int) -> jax.Array:
    """int8 values in [-8, 7] → packed bytes; `axis` (negative) halves.

    Byte b at pair index p holds (q[2p] & 0xF) | (q[2p+1] << 4)."""
    ax = q.ndim + axis
    pairs = q.reshape(q.shape[:ax] + (q.shape[ax] // 2, 2) +
                      q.shape[ax + 1:])
    lo = jax.lax.index_in_dim(pairs, 0, ax + 1, keepdims=False)
    hi = jax.lax.index_in_dim(pairs, 1, ax + 1, keepdims=False)
    return ((hi.astype(jnp.uint8) << 4) |
            (lo.astype(jnp.uint8) & 0xF)).astype(jnp.int8)


def _unpack4(packed: jax.Array, axis: int) -> jax.Array:
    """Packed bytes → int8 values in [-8, 7]; `axis` (negative)
    doubles. Arithmetic shifts recover the signed nibbles."""
    ax = packed.ndim + axis
    u = packed.astype(jnp.int8)
    lo = jax.lax.shift_right_arithmetic(
        jax.lax.shift_left(u, jnp.int8(4)), jnp.int8(4))
    hi = jax.lax.shift_right_arithmetic(u, jnp.int8(4))
    pair = jnp.stack([lo, hi], axis=ax + 1)   # [..., dim/2, 2, ...]
    return pair.reshape(packed.shape[:ax] + (packed.shape[ax] * 2,) +
                        packed.shape[ax + 1:])


def quantize4(w: jax.Array, axis: int = -2,
              group: int = 128) -> Quantized4Tensor:
    """Symmetric group-wise int4 over the contraction `axis`.

    Groups of `group` consecutive contraction rows share one fp32
    scale (amax/7). Falls back to one group when the axis is shorter
    than `group`; the axis length must be even (packing) and divisible
    by the effective group size.
    """
    if axis >= 0:
        axis = axis - w.ndim
    dim = w.shape[axis]
    group = min(group, dim)
    if dim % 2 or dim % group or group % 2:
        raise ValueError(f'int4 needs even, group-divisible contraction '
                         f'(dim={dim}, group={group})')
    ax = w.ndim + axis
    grouped = w.astype(jnp.float32).reshape(
        w.shape[:ax] + (dim // group, group) + w.shape[ax + 1:])
    amax = jnp.max(jnp.abs(grouped), axis=ax + 1)        # [..., G, ...]
    scale = jnp.maximum(amax, 1e-8) / 7.0
    q = jnp.clip(jnp.round(grouped / jnp.expand_dims(scale, ax + 1)),
                 -8, 7).astype(jnp.int8).reshape(w.shape)
    return Quantized4Tensor(_pack4(q, axis), scale, axis, group)


def dequantize4(w: Quantized4Tensor, dtype=jnp.bfloat16) -> jax.Array:
    q = _unpack4(w.q_packed, w.axis)
    ax = q.ndim + w.axis
    dim = q.shape[ax]
    grouped = q.astype(jnp.float32).reshape(
        q.shape[:ax] + (dim // w.group, w.group) + q.shape[ax + 1:])
    out = grouped * jnp.expand_dims(w.scale, ax + 1)
    return out.reshape(q.shape).astype(dtype)


def quantize(w: jax.Array, axis: int = -2) -> QuantizedTensor:
    """Symmetric per-output-channel int8 over the contraction `axis`."""
    if axis >= 0:
        axis = axis - w.ndim
    amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=axis)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.round(w.astype(jnp.float32) /
                  jnp.expand_dims(scale, axis)).astype(jnp.int8)
    return QuantizedTensor(q, scale, axis)


def dequantize(w: QuantizedTensor, dtype=jnp.bfloat16) -> jax.Array:
    return (w.q.astype(jnp.float32) *
            jnp.expand_dims(w.scale, w.axis)).astype(dtype)


def matmul(x: jax.Array, w, preferred_element_type=None) -> jax.Array:
    """`x @ w` for `w` a plain `[.., in, out]` array, a QuantizedTensor
    (dequant fused into the matmul epilogue), or a Quantized4Tensor
    (group scales vary along the contraction, so dequant fuses into the
    operand read instead — HBM still only carries the packed nibbles)."""
    if isinstance(w, QuantizedTensor):
        assert w.axis == -2, (
            f'matmul needs contraction at -2, got {w.axis}')
        out = jnp.matmul(x, w.q.astype(x.dtype),
                         preferred_element_type=preferred_element_type)
        return out * w.scale.astype(out.dtype)
    if isinstance(w, Quantized4Tensor):
        assert w.axis == -2, (
            f'matmul needs contraction at -2, got {w.axis}')
        return jnp.matmul(x, dequantize4(w, x.dtype),
                          preferred_element_type=preferred_element_type)
    return jnp.matmul(x, w, preferred_element_type=preferred_element_type)


def embed_rows(table, tokens: jax.Array) -> jax.Array:
    """`table[tokens]` for a plain or row-quantized (axis=-1) table."""
    if isinstance(table, QuantizedTensor):
        assert table.axis == -1, (
            f'embed_rows needs per-row scales (axis -1), got {table.axis}')
        rows = table.q[tokens]
        return rows.astype(table.scale.dtype) * table.scale[tokens][..., None]
    return table[tokens]


def tied_head(hidden: jax.Array, table,
              preferred_element_type=jnp.float32) -> jax.Array:
    """`einsum('...d,vd->...v')` against a (possibly row-quantized)
    embedding table used as a tied LM head (gemma)."""
    if isinstance(table, QuantizedTensor):
        assert table.axis == -1
        out = jnp.einsum('...d,vd->...v', hidden,
                         table.q.astype(hidden.dtype),
                         preferred_element_type=preferred_element_type)
        return out * table.scale.astype(out.dtype)
    return jnp.einsum('...d,vd->...v', hidden, table,
                      preferred_element_type=preferred_element_type)


def expert_einsum(spec: str, x: jax.Array, w,
                  preferred_element_type=None) -> jax.Array:
    """MoE expert einsum (`ecd,edf->ecf` / `ecf,efd->ecd`) where `w`
    may be quantized over its middle (contraction) axis: the [E, out]
    scale broadcasts as [E, 1, out] over the `e?out` result."""
    if isinstance(w, QuantizedTensor):
        assert w.axis == -2
        out = jnp.einsum(spec, x, w.q.astype(x.dtype),
                         preferred_element_type=preferred_element_type)
        return out * w.scale[:, None, :].astype(out.dtype)
    if isinstance(w, Quantized4Tensor):
        assert w.axis == -2
        return jnp.einsum(spec, x, dequantize4(w, x.dtype),
                          preferred_element_type=preferred_element_type)
    return jnp.einsum(spec, x, w,
                      preferred_element_type=preferred_element_type)


# Weight leaves quantized for serving, keyed by name. Contraction is
# -2 (matmul convention) except the embedding table, whose rows must
# dequantize independently for the token gather (and whose tied-head
# use contracts over d = its LAST axis — the same per-row scale
# serves both).
_QUANT_AXES = {
    'wq': -2, 'wk': -2, 'wv': -2, 'wo': -2,
    'w_gate': -2, 'w_up': -2, 'w_down': -2,
    'lm_head': -2,
    'embed': -1,
}


def quantize_params(params: Params) -> Params:
    """Quantize a family's weight matrices for serving.

    Norm vectors (and any leaf not in the known weight set) stay in
    their original dtype; already-quantized leaves pass through, so
    the transform is idempotent.
    """

    def walk(node):
        if isinstance(node, dict):
            out = {}
            for key, value in node.items():
                if isinstance(value, dict):
                    out[key] = walk(value)
                elif isinstance(value, QuantizedTensor):
                    out[key] = value
                elif key in _QUANT_AXES and value.ndim >= 2:
                    out[key] = quantize(value, _QUANT_AXES[key])
                else:
                    out[key] = value
            return out
        return node

    return walk(params)


def quantize_params_int4(params: Params, group: int = 128) -> Params:
    """int4 (group-scaled) for the dense matmul weights, int8 for the
    rest of the known weight set (the embedding's per-row gather and
    any contraction that cannot pack evenly). Idempotent like
    quantize_params."""

    def walk(node):
        if isinstance(node, dict):
            out = {}
            for key, value in node.items():
                if isinstance(value, dict):
                    out[key] = walk(value)
                elif isinstance(value, (QuantizedTensor,
                                        Quantized4Tensor)):
                    out[key] = value
                elif key in _QUANT_AXES and value.ndim >= 2:
                    axis = _QUANT_AXES[key]
                    if axis == -2:
                        try:
                            out[key] = quantize4(value, axis, group)
                            continue
                        except ValueError:
                            pass   # odd/indivisible contraction
                    out[key] = quantize(value, axis)
                else:
                    out[key] = value
            return out
        return node

    return walk(params)


def synthetic_quantized4_params(shapes: Params, key: jax.Array,
                                group: int = 128) -> Params:
    """synthetic_quantized_params at int4: packed nibbles are sampled
    directly (no full-precision or even int8 tree ever materializes) —
    an 8B lands at ~4.5 GB, inside even a partial-HBM chip."""

    def walk(node, key):
        if isinstance(node, dict):
            out = {}
            for name, value in sorted(node.items()):
                key, sub = jax.random.split(key)
                if isinstance(value, dict):
                    out[name] = walk(value, sub)
                elif (name in _QUANT_AXES and value.ndim >= 2
                        and _QUANT_AXES[name] == -2
                        and value.shape[-2] % 2 == 0
                        and value.shape[-2] % min(group,
                                                  value.shape[-2]) == 0):
                    fan_in = value.shape[-2]
                    g = min(group, fan_in)
                    packed_shape = value.shape[:-2] + (fan_in // 2,
                                                       value.shape[-1])
                    q = jax.lax.bitcast_convert_type(
                        jax.random.bits(sub, packed_shape, jnp.uint8),
                        jnp.int8)
                    scale_shape = value.shape[:-2] + (fan_in // g,
                                                      value.shape[-1])
                    scale = jnp.full(scale_shape,
                                     (fan_in ** -0.5) / 7.0, jnp.float32)
                    out[name] = Quantized4Tensor(q, scale, -2, g)
                elif name in _QUANT_AXES and value.ndim >= 2:
                    axis = _QUANT_AXES[name]
                    q = jax.lax.bitcast_convert_type(
                        jax.random.bits(sub, value.shape, jnp.uint8),
                        jnp.int8)
                    fan_in = value.shape[axis]
                    scale_shape = list(value.shape)
                    del scale_shape[axis % value.ndim]
                    scale = jnp.full(scale_shape,
                                     (fan_in ** -0.5) / 127.0,
                                     jnp.float32)
                    out[name] = QuantizedTensor(q, scale, axis)
                else:
                    out[name] = jnp.ones(value.shape, value.dtype)
            return out
        return node

    return walk(shapes, key)


def params_nbytes(params: Params) -> int:
    return sum(leaf.nbytes
               for leaf in jax.tree_util.tree_leaves(params))


def synthetic_quantized_params(shapes: Params, key: jax.Array) -> Params:
    """Random params born directly in quantized form.

    For throughput benchmarks of models whose bf16 init would not fit
    the chip (an 8B is 16 GB bf16 — exactly one v5e's HBM before
    quantizing): weights are sampled straight as int8 with fan-in
    scales, never materializing the full-precision tree. `shapes` is
    the `jax.eval_shape` of the family's `init`.
    """

    def walk(node, key):
        if isinstance(node, dict):
            out = {}
            for name, value in sorted(node.items()):
                key, sub = jax.random.split(key)
                if isinstance(value, dict):
                    out[name] = walk(value, sub)
                elif name in _QUANT_AXES and value.ndim >= 2:
                    axis = _QUANT_AXES[name]
                    # bits+bitcast, NOT randint: eager randint would
                    # materialize a 4x int32 transient per leaf (7.5 GB
                    # for an 8B's stacked w_gate) — defeating the whole
                    # point of sampling straight into int8.
                    q = jax.lax.bitcast_convert_type(
                        jax.random.bits(sub, value.shape, jnp.uint8),
                        jnp.int8)
                    fan_in = value.shape[axis]
                    scale_shape = list(value.shape)
                    del scale_shape[axis % value.ndim]
                    scale = jnp.full(scale_shape,
                                     (fan_in ** -0.5) / 127.0,
                                     jnp.float32)
                    out[name] = QuantizedTensor(q, scale, axis)
                else:
                    out[name] = jnp.ones(value.shape, value.dtype)
            return out
        return node

    return walk(shapes, key)
