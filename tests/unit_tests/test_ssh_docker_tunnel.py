"""SSH node pools, docker provisioner (mocked CLI), and the CONNECT
tunnel through the live API server."""
import json
import socket
import threading

import pytest
import yaml

from skypilot_tpu.clouds import ssh as ssh_cloud
from skypilot_tpu.provision import common
from skypilot_tpu.provision.docker import instance as docker_instance
from skypilot_tpu.provision.ssh import instance as ssh_instance
from skypilot_tpu.utils import command_runner


pytestmark = pytest.mark.slow  # heavy tier: subprocess e2e / jit compiles


@pytest.fixture
def ssh_pool(tmp_path, monkeypatch):
    pools = {
        'rack1': {
            'user': 'ubuntu',
            'identity_file': '~/.ssh/rack1_key',
            'hosts': [{'ip': '10.0.0.1'}, {'ip': '10.0.0.2'},
                      {'ip': '10.0.0.3', 'user': 'admin'}],
        }
    }
    path = tmp_path / 'pools.yaml'
    path.write_text(yaml.dump(pools))
    monkeypatch.setenv('XSKY_SSH_NODE_POOLS', str(path))
    monkeypatch.setenv('XSKY_SSH_ALLOCATIONS',
                       str(tmp_path / 'alloc.json'))
    return pools


class TestSshPool:

    def test_load_pools_defaults_and_overrides(self, ssh_pool):
        pools = ssh_cloud.load_pools()
        hosts = pools['rack1']['hosts']
        assert hosts[0]['user'] == 'ubuntu'
        assert hosts[2]['user'] == 'admin'
        assert hosts[0]['identity_file'].endswith('.ssh/rack1_key')

    def test_allocate_and_release(self, ssh_pool):
        config = common.ProvisionConfig(provider_config={},
                                        node_config={'pool': 'rack1'},
                                        count=2)
        record = ssh_instance.run_instances('rack1', None, 'c1', config)
        assert record.created_instance_ids == ['10.0.0.1', '10.0.0.2']
        # Second cluster gets the remaining host; a third is capacity-out.
        config1 = common.ProvisionConfig(provider_config={},
                                         node_config={'pool': 'rack1'},
                                         count=1)
        ssh_instance.run_instances('rack1', None, 'c2', config1)
        from skypilot_tpu import exceptions
        with pytest.raises(exceptions.CapacityError):
            ssh_instance.run_instances('rack1', None, 'c3', config1)
        ssh_instance.terminate_instances('c1', {})
        record3 = ssh_instance.run_instances('rack1', None, 'c3', config1)
        assert len(record3.created_instance_ids) == 1

    def test_cluster_info_and_runners(self, ssh_pool):
        config = common.ProvisionConfig(provider_config={},
                                        node_config={'pool': 'rack1'},
                                        count=2)
        ssh_instance.run_instances('rack1', None, 'c1', config)
        info = ssh_instance.get_cluster_info('rack1', 'c1', {})
        assert len(info.instances) == 2
        assert info.head_instance_id == '10.0.0.1'
        runners = command_runner.runners_from_cluster_info(info, 'fallback')
        assert all(isinstance(r, command_runner.SSHCommandRunner)
                   for r in runners)
        assert runners[0].ssh_private_key.endswith('rack1_key')

    def test_cloud_feasibility(self, ssh_pool):
        from skypilot_tpu import resources as resources_lib
        cloud = ssh_cloud.SSH()
        ok, _ = cloud.check_credentials()
        assert ok
        res = resources_lib.Resources(cloud='ssh')
        candidates, _ = cloud.get_feasible_launchable_resources(res)
        assert len(candidates) == 1
        assert cloud.instance_type_to_hourly_cost('byo', False) == 0
        regions = cloud.regions_with_offering('', None, False, None, None)
        assert [r.name for r in regions] == ['rack1']


class FakeDocker:
    def __init__(self):
        self.containers = {}

    def __call__(self, args, input_data=None, timeout=120.0):
        verb = args[0]
        if verb == 'run':
            name = args[args.index('--name') + 1]
            labels = dict(a.split('=', 1) for a in args
                          if '=' in a and not a.startswith('-'))
            self.containers[name] = {
                'Names': name, 'Status': 'Up 1 second',
                'labels': labels,
            }
            return ''
        if verb == 'ps':
            flt = [a for a in args if a.startswith('label=')]
            key, value = flt[0][len('label='):].split('=')
            return '\n'.join(
                json.dumps(c) for c in self.containers.values()
                if c['labels'].get(key) == value)
        if verb == 'inspect':
            c = self.containers[args[1]]
            return json.dumps([{
                'NetworkSettings': {'IPAddress': '172.17.0.5'},
                'Config': {'Labels': c['labels']},
                'State': {'Running': c['Status'].startswith('Up')},
            }])
        if verb == 'stop':
            self.containers[args[1]]['Status'] = 'Exited'
            return ''
        if verb == 'start':
            self.containers[args[1]]['Status'] = 'Up 1 second'
            return ''
        if verb == 'rm':
            self.containers.pop(args[-1], None)
            return ''
        raise AssertionError(f'FakeDocker: unhandled {args}')


@pytest.fixture
def fake_docker(monkeypatch):
    fake = FakeDocker()
    monkeypatch.setattr(docker_instance, '_run_docker', fake)
    return fake


class TestDockerProvisioner:

    def test_lifecycle(self, fake_docker):
        config = common.ProvisionConfig(provider_config={},
                                        node_config={}, count=2)
        record = docker_instance.run_instances('local', None, 'dev',
                                               config)
        assert len(record.created_instance_ids) == 2
        statuses = docker_instance.query_instances('dev', {})
        assert set(statuses.values()) == {'RUNNING'}
        info = docker_instance.get_cluster_info('local', 'dev', {})
        assert info.head_instance_id == 'xsky-dev-0'
        assert info.instances['xsky-dev-0'].internal_ip == '172.17.0.5'
        runners = command_runner.runners_from_cluster_info(info, 'k')
        assert all(isinstance(r, command_runner.DockerCommandRunner)
                   for r in runners)
        docker_instance.stop_instances('dev', {})
        assert set(docker_instance.query_instances('dev', {}).values()) \
            == {'STOPPED'}
        docker_instance.run_instances('local', None, 'dev', config)
        assert set(docker_instance.query_instances('dev', {}).values()) \
            == {'RUNNING'}
        docker_instance.terminate_instances('dev', {})
        assert docker_instance.query_instances('dev', {}) == {}


class TestConnectTunnel:

    def test_tunnel_roundtrip(self, tmp_path, monkeypatch):
        """CONNECT through the live API server to a local echo server."""
        from skypilot_tpu import state
        from skypilot_tpu.server import app as server_app
        from skypilot_tpu.server import requests_db
        from skypilot_tpu.templates import tunnel_proxy
        monkeypatch.setenv('XSKY_STATE_DB', str(tmp_path / 's.db'))
        monkeypatch.setenv('XSKY_SERVER_DB', str(tmp_path / 'r.db'))
        monkeypatch.delenv('XSKY_REQUIRE_AUTH', raising=False)
        monkeypatch.setenv('XSKY_TUNNEL_ALLOW_ANY', '1')
        state.reset_for_test()
        requests_db.reset_for_test()

        # Echo server standing in for a cluster host's sshd.
        echo = socket.socket()
        echo.bind(('127.0.0.1', 0))
        echo.listen(1)
        echo_port = echo.getsockname()[1]

        def echo_loop():
            conn, _ = echo.accept()
            while True:
                data = conn.recv(4096)
                if not data:
                    break
                conn.sendall(data.upper())
            conn.close()

        threading.Thread(target=echo_loop, daemon=True).start()
        server, port = server_app.run_in_thread()
        try:
            sock, leftover = tunnel_proxy.open_tunnel(
                f'http://127.0.0.1:{port}', '127.0.0.1', echo_port)
            assert leftover == b''
            sock.sendall(b'hello tunnel')
            out = sock.recv(4096)
            assert out == b'HELLO TUNNEL'
            sock.close()
        finally:
            server.shutdown()
            echo.close()
            state.reset_for_test()
            requests_db.reset_for_test()


    def test_tunnel_rejects_non_cluster_host(self, tmp_path, monkeypatch):
        from skypilot_tpu import state
        from skypilot_tpu.server import app as server_app
        from skypilot_tpu.server import requests_db
        from skypilot_tpu.templates import tunnel_proxy
        monkeypatch.setenv('XSKY_STATE_DB', str(tmp_path / 's.db'))
        monkeypatch.setenv('XSKY_SERVER_DB', str(tmp_path / 'r.db'))
        monkeypatch.delenv('XSKY_TUNNEL_ALLOW_ANY', raising=False)
        state.reset_for_test()
        requests_db.reset_for_test()
        server, port = server_app.run_in_thread()
        try:
            with pytest.raises(ConnectionError, match='refused'):
                tunnel_proxy.open_tunnel(f'http://127.0.0.1:{port}',
                                         '169.254.169.254', 80)
        finally:
            server.shutdown()
            state.reset_for_test()
            requests_db.reset_for_test()


class TestSshVerb:
    """`xsky ssh` command construction (twin of sky ssh)."""

    def test_local_cluster_gets_bash_at_host_root(self,
                                                  fake_cluster_env):
        from skypilot_tpu import Resources, Task, core, execution
        from skypilot_tpu.client import sdk
        task = Task('sshv', run='echo up')
        task.set_resources(Resources(accelerators='tpu-v5e-8'))
        execution.launch(task, cluster_name='ssh-c')
        argv, cwd = sdk.ssh_command('ssh-c')
        assert argv == ['bash']
        import os
        assert cwd and os.path.isdir(cwd)
        # Running a command through the verb's argv works.
        import subprocess
        out = subprocess.run(argv + ['-c', 'pwd'], cwd=cwd,
                             capture_output=True, text=True)
        assert out.stdout.strip() == os.path.realpath(cwd) or \
            out.stdout.strip() == cwd
        core.down('ssh-c', purge=True)

    def test_unknown_cluster_raises(self, fake_cluster_env):
        from skypilot_tpu import exceptions
        from skypilot_tpu.client import sdk
        with pytest.raises(exceptions.ClusterDoesNotExist):
            sdk.ssh_command('nope')

    def test_ssh_runner_argv_includes_proxy_when_remote(
            self, fake_cluster_env, monkeypatch):
        from skypilot_tpu.client import sdk
        from skypilot_tpu.utils import command_runner

        class FakeHandle:
            def head_runner(self):
                return command_runner.SSHCommandRunner(
                    '10.9.8.7', 'tpuuser', '~/.ssh/k', port=2222)

        from skypilot_tpu import state as state_lib
        monkeypatch.setattr(
            state_lib, 'get_cluster_from_name',
            lambda name: {'handle': FakeHandle(),
                          'status': state_lib.ClusterStatus.UP})
        monkeypatch.setenv('XSKY_API_SERVER', 'http://api:46580')
        argv, cwd = sdk.ssh_command('any')
        assert cwd is None
        assert argv[0] == 'ssh'
        # The destination appears exactly once and LAST: ssh stops
        # option parsing at the first non-option argument, so a
        # duplicate (or an option after it) would run as a remote
        # command instead of opening a shell.
        assert argv.count('tpuuser@10.9.8.7') == 1
        assert argv[-1] == 'tpuuser@10.9.8.7'
        assert '2222' in argv
        joined = ' '.join(argv)
        assert 'ProxyCommand=' in joined
        assert joined.index('ProxyCommand=') < joined.index('tpuuser@')
        assert 'tunnel_proxy' in joined
        assert 'http://api:46580' in joined
        # Without a remote endpoint: no proxy.
        monkeypatch.delenv('XSKY_API_SERVER')
        argv2, _ = sdk.ssh_command('any')
        assert 'ProxyCommand' not in ' '.join(argv2)
        # Command mode: one shell-quoted string after the destination,
        # so the remote shell sees literal words, not operators.
        argv3, _ = sdk.ssh_command('any',
                                   command=['echo', 'a b', '&&', 'pwd'])
        assert argv3[-2] == 'tpuuser@10.9.8.7'
        assert argv3[-1] == "echo 'a b' '&&' pwd"

    def test_command_mode_quotes_for_bash(self, fake_cluster_env):
        from skypilot_tpu import Resources, Task, core, execution
        from skypilot_tpu.client import sdk
        task = Task('sshc', run='echo up')
        task.set_resources(Resources(accelerators='tpu-v5e-8'))
        execution.launch(task, cluster_name='ssh-cmd')
        import subprocess
        argv, cwd = sdk.ssh_command('ssh-cmd',
                                    command=['echo', 'a b', '&&', 'pwd'])
        out = subprocess.run(argv, cwd=cwd, capture_output=True,
                             text=True)
        # Words are quoted: '&&' is a literal argument, not an operator.
        assert out.stdout.strip() == 'a b && pwd'
        core.down('ssh-cmd', purge=True)

    def test_jump_host_proxy_preserved(self, monkeypatch):
        from skypilot_tpu.client import sdk
        from skypilot_tpu import state as state_lib
        from skypilot_tpu.utils import command_runner

        class FakeHandle:
            def head_runner(self):
                return command_runner.SSHCommandRunner(
                    '10.0.0.2', 'u', '~/.ssh/k',
                    ssh_proxy_command='ssh -W %h:%p jump@bastion')

        monkeypatch.setattr(
            state_lib, 'get_cluster_from_name',
            lambda name: {'handle': FakeHandle(),
                          'status': state_lib.ClusterStatus.UP})
        monkeypatch.setenv('XSKY_API_SERVER', 'http://api:46580')
        argv, _ = sdk.ssh_command('j')
        joined = ' '.join(argv)
        # The provisioner's jump host wins; the API tunnel must not
        # clobber it.
        assert 'bastion' in joined
        assert 'tunnel_proxy' not in joined


class TestPoolUpDown:
    """`xsky ssh up/down` — pool bring-up probe + teardown release
    (twins of sky ssh up/down, sky/client/cli/command.py:5189,5212)."""

    def test_pool_up_probes_every_host(self, ssh_pool, monkeypatch):
        probed = []

        def fake_run(self, cmd, **kwargs):
            probed.append(self.ip)
            return 255 if self.ip == '10.0.0.2' else 0

        monkeypatch.setattr(command_runner.SSHCommandRunner, 'run',
                            fake_run)
        report = ssh_cloud.pool_up()
        assert sorted(probed) == ['10.0.0.1', '10.0.0.2', '10.0.0.3']
        assert report['rack1']['ok'] is False
        rows = {r['ip']: r for r in report['rack1']['hosts']}
        assert rows['10.0.0.1']['ok'] and rows['10.0.0.3']['ok']
        assert not rows['10.0.0.2']['ok']
        assert 'exited 255' in rows['10.0.0.2']['error']

    def test_pool_up_unknown_pool_and_no_pools(self, ssh_pool,
                                               monkeypatch, tmp_path):
        with pytest.raises(ValueError, match='Unknown SSH pool'):
            ssh_cloud.pool_up('nope')
        empty = tmp_path / 'none.yaml'
        empty.write_text('')
        monkeypatch.setenv('XSKY_SSH_NODE_POOLS', str(empty))
        with pytest.raises(ValueError, match='No SSH node pools'):
            ssh_cloud.pool_up()

    def test_pool_down_releases_allocations_and_state(
            self, ssh_pool, monkeypatch, tmp_path):
        from skypilot_tpu import state
        monkeypatch.setenv('XSKY_STATE_DB', str(tmp_path / 'state.db'))
        state.reset_for_test()
        try:
            config = common.ProvisionConfig(
                provider_config={}, node_config={'pool': 'rack1'},
                count=2)
            ssh_instance.run_instances('rack1', None, 'byo-c1', config)
            state.add_or_update_cluster('byo-c1', cluster_handle=object(),
                                        ready=True)
            cleaned = []
            monkeypatch.setattr(
                command_runner.SSHCommandRunner, 'run',
                lambda self, cmd, **kw: cleaned.append((self.ip, cmd))
                or 0)
            report = ssh_cloud.pool_down('rack1')
            assert report['rack1']['released_clusters'] == ['byo-c1']
            assert report['rack1']['hosts_cleaned'] == 3
            # pkill -f must not match its own carrying remote shell.
            assert all('[s]kypilot_tpu' in cmd for _, cmd in cleaned)
            # Allocation gone, hosts bookable again; DB row retired to
            # history (cost report still sees it).
            assert ssh_instance.query_instances('byo-c1', {}) == {}
            assert state.get_cluster_from_name('byo-c1') is None
            assert any(h['name'] == 'byo-c1'
                       for h in state.get_cluster_history())
        finally:
            state.reset_for_test()


    def test_pool_down_is_admin_only(self):
        from skypilot_tpu.users import rbac
        assert not rbac.check_permission('user', 'ssh.down')
        assert rbac.check_permission('admin', 'ssh.down')
        assert rbac.check_permission('user', 'ssh.up')


class TestApiInfo:
    """`xsky api info` — /health additive fields + SDK fallback."""

    def test_local_mode(self, monkeypatch):
        from skypilot_tpu.client import sdk
        monkeypatch.delenv('XSKY_API_SERVER', raising=False)
        info = sdk.api_info()
        assert info['mode'] == 'local'
        assert info['status'] == 'healthy'
        assert info['version']
        assert info['api_version'] >= 1

    def test_health_fields_over_http(self, monkeypatch, tmp_path):
        import json as json_lib
        import urllib.request
        from skypilot_tpu.server import app as server_app
        monkeypatch.setenv('XSKY_STATE_DB', str(tmp_path / 'state.db'))
        from skypilot_tpu import state
        state.reset_for_test()
        try:
            httpd, port = server_app.run_in_thread(port=0)
            try:
                with urllib.request.urlopen(
                        f'http://127.0.0.1:{port}/health') as resp:
                    payload = json_lib.loads(resp.read())
                assert payload['status'] == 'healthy'
                assert payload['version']
                assert payload['user'] is None
            finally:
                httpd.shutdown()
        finally:
            state.reset_for_test()
