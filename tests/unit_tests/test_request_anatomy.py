"""Request-scoped data-plane tracing tests: cross-hop trace headers
(inject/extract round-trip, survival across LB retries and update-mode
policy swaps), the replica-side anatomy recorder (seal math, ring
bounds, env gating), per-phase metrics rendering, the deadline
admission gate, the slow-request exemplar table + the SLO monitor's
cross-hop waterfall join, `/lb/requests` paging, the `xsky serve
trace` surface, and the tier-1 fake-cloud drill where a chaos-stalled
decode becomes a breach whose exemplar waterfall blames decode."""
import json
import os
import queue
import socket
import struct
import threading
import time
import types
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from skypilot_tpu.infer import anatomy as anatomy_lib
from skypilot_tpu.infer import metrics as infer_metrics
from skypilot_tpu.serve import load_balancer as lb_lib
from skypilot_tpu.serve import load_balancing_policies as lb_policies
from skypilot_tpu.serve import slo as slo_lib
from skypilot_tpu.serve.service_spec import SkyServiceSpec, SLOSpec
from skypilot_tpu.utils import chaos
from skypilot_tpu.utils import tracing

REPO_ROOT = os.path.abspath(
    os.path.join(os.path.dirname(__file__), '..', '..'))


@pytest.fixture(autouse=True)
def _clean_chaos_and_anatomy():
    chaos.clear()
    anatomy_lib.reset_for_test()
    yield
    chaos.clear()
    anatomy_lib.reset_for_test()


@pytest.fixture
def tmp_state(monkeypatch, tmp_path):
    from skypilot_tpu import state
    monkeypatch.setenv('XSKY_STATE_DB', str(tmp_path / 'state.db'))
    state.reset_for_test()
    yield state
    state.reset_for_test()


@pytest.fixture
def tmp_serve_db(monkeypatch, tmp_path):
    monkeypatch.setenv('XSKY_SERVE_DB', str(tmp_path / 'serve.db'))
    yield


def _upstream(handler_cls) -> ThreadingHTTPServer:
    server = ThreadingHTTPServer(('127.0.0.1', 0), handler_cls)
    threading.Thread(target=server.serve_forever,
                     name='xsky-test-upstream', daemon=True).start()
    return server


class _EchoUpstream(BaseHTTPRequestHandler):
    def log_message(self, *args):
        pass

    def do_GET(self):  # noqa: N802
        body = b'hello'
        self.send_response(200)
        self.send_header('Content-Length', str(len(body)))
        self.end_headers()
        self.wfile.write(body)


# ---- trace headers ----------------------------------------------------------


class TestTraceHeaders:

    def test_inject_extract_round_trip(self):
        headers = {}
        tracing.inject_headers(headers, trace_id='t1',
                               request_id='r1', deadline_s=2.5)
        assert headers == {'X-Xsky-Trace-Id': 't1',
                           'X-Xsky-Request-Id': 'r1',
                           'X-Xsky-Deadline-S': '2.500'}
        assert tracing.extract_headers(headers) == ('t1', 'r1', 2.5)

    def test_absent_context_degrades_to_none(self):
        # A direct (relay-less) caller carries no headers: the replica
        # must serve it untraced, not crash.
        assert tracing.extract_headers({}) == (None, None, None)
        headers = {}
        tracing.inject_headers(headers)  # no ambient trace either
        assert 'X-Xsky-Request-Id' not in headers

    def test_malformed_deadline_never_raises(self):
        trace_id, req_id, deadline = tracing.extract_headers(
            {'X-Xsky-Trace-Id': 'abc',
             'X-Xsky-Deadline-S': 'not-a-float'})
        assert (trace_id, req_id, deadline) == (None, None, None)
        # Non-dict garbage degrades the same way.
        assert tracing.extract_headers(None) == (None, None, None)
        # inject on an unwritable target is swallowed too.
        tracing.inject_headers(None, trace_id='t')  # no raise

    def test_negative_remaining_budget_still_relays(self):
        # The LB re-measures the budget per leg; a retried leg may see
        # a negative remainder. It must still reach the replica (whose
        # admission gate then rejects) — inject must not drop it.
        headers = {}
        tracing.inject_headers(headers, trace_id='t',
                               request_id='r', deadline_s=-0.25)
        assert tracing.extract_headers(headers)[2] == -0.25


# ---- anatomy recorder -------------------------------------------------------


def _finished_request(**overrides):
    base = time.perf_counter() - 1.0
    request = types.SimpleNamespace(
        submitted_at=base,
        taken_at=base + 0.1,          # replica_queue = 0.1
        deferred_wait=0.05,           # admit_deferred = 0.05
        first_token_at=base + 0.35,   # prefill = 0.35-0.1-0.05 = 0.2
        decode_s=0.4,
        commit_s=0.05,
        finished_at=base + 1.0,       # finish = 1.0 - 0.9 = 0.1
        kv_headroom_at_admit=0.75,
        prompt_tokens=[1, 2, 3],
        output_tokens=[4] * 16,
        request_id=7,
        client_request_id='lb-abc',
        trace_id='trace-abc')
    for key, value in overrides.items():
        setattr(request, key, value)
    return request


class TestAnatomyLog:

    def test_seal_phases_sum_to_total(self):
        log = anatomy_lib.AnatomyLog()
        rec = log.seal(_finished_request())
        phases = rec['phases']
        assert set(phases) == set(anatomy_lib.PHASES)
        assert phases['replica_queue'] == pytest.approx(0.1)
        assert phases['admit_deferred'] == pytest.approx(0.05)
        assert phases['prefill'] == pytest.approx(0.2)
        assert phases['decode'] == pytest.approx(0.4)
        assert phases['sampling_commit'] == pytest.approx(0.05)
        # The unattributed remainder closes the books exactly.
        assert sum(phases.values()) == pytest.approx(rec['total_s'])
        assert rec['request_id'] == 'lb-abc'
        assert rec['trace_id'] == 'trace-abc'
        assert rec['kv_headroom_at_admit'] == 0.75
        assert rec['output_tokens'] == 16

    def test_seal_without_timestamps_returns_none(self):
        log = anatomy_lib.AnatomyLog()
        assert log.seal(_finished_request(submitted_at=0.0)) is None
        assert log.seal(_finished_request(finished_at=None)) is None
        assert log.records() == []

    def test_untaken_request_is_all_queue(self):
        # Rejected before any admission attempt (e.g. deadline gate on
        # a queued request): the whole lifetime is replica_queue.
        rec = anatomy_lib.AnatomyLog().seal(_finished_request(
            taken_at=None, first_token_at=None, deferred_wait=0.0,
            decode_s=0.0, commit_s=0.0))
        assert rec['phases']['replica_queue'] == pytest.approx(
            rec['total_s'])
        assert rec['phases']['prefill'] == 0.0

    def test_ring_bounded_by_env(self, monkeypatch):
        monkeypatch.setenv(anatomy_lib.ENV_RING, '3')
        log = anatomy_lib.AnatomyLog()
        for i in range(10):
            log.seal(_finished_request(client_request_id=f'r{i}'))
        records = log.records()
        assert len(records) == 3
        # Newest-first.
        assert [r['request_id'] for r in records] == ['r9', 'r8', 'r7']

    def test_garbage_ring_env_defaults(self, monkeypatch):
        monkeypatch.setenv(anatomy_lib.ENV_RING, '2k')
        assert anatomy_lib.AnatomyLog()._ring.maxlen == 2048

    def test_records_filter_and_limit(self):
        log = anatomy_lib.AnatomyLog()
        for i in range(5):
            log.seal(_finished_request(client_request_id=f'r{i}'))
        assert len(log.records(limit=2)) == 2
        (rec,) = log.records(request_id='r3')
        assert rec['request_id'] == 'r3'
        assert log.records(request_id='nope') == []

    def test_numeric_id_fallback_for_direct_callers(self):
        rec = anatomy_lib.AnatomyLog().seal(
            _finished_request(client_request_id=None, request_id=42))
        assert rec['request_id'] == '42'

    def test_enabled_env_gate(self, monkeypatch):
        assert anatomy_lib.enabled()
        monkeypatch.setenv(anatomy_lib.ENV_ANATOMY, '0')
        assert not anatomy_lib.enabled()

    def test_get_log_reads_env_at_first_use(self, monkeypatch):
        monkeypatch.setenv(anatomy_lib.ENV_RING, '5')
        anatomy_lib.reset_for_test()
        log = anatomy_lib.get_log()
        assert log._ring.maxlen == 5
        assert anatomy_lib.get_log() is log


# ---- per-phase metrics ------------------------------------------------------


class TestPhaseMetrics:

    def test_labeled_phase_histograms_round_trip(self):
        metrics = infer_metrics.ServeMetrics()
        for _ in range(3):
            metrics.observe_phases({'decode': 0.4, 'prefill': 0.02})
        text = metrics.render()
        assert ('xsky_serve_phase_seconds_bucket{phase="decode",'
                'le="0.5"} 3') in text
        assert 'xsky_serve_phase_seconds_count{phase="decode"} 3' \
            in text
        assert 'xsky_serve_phase_seconds_sum{phase="prefill"} ' \
            '0.060000' in text
        # The scrape parser the SLO monitor uses reads it back.
        samples = slo_lib.parse_prometheus_text(text)
        buckets = [
            (labels, v) for labels, v in
            samples['xsky_serve_phase_seconds_bucket']
            if labels.get('phase') == 'decode']
        assert buckets and all(
            v == 3.0 for labels, v in buckets
            if labels['le'] in ('1.0', '+Inf'))

    def test_no_phases_no_series(self):
        assert 'xsky_serve_phase_seconds' not in \
            infer_metrics.ServeMetrics().render()

    def test_admission_gauges_from_orchestrator(self):
        orch = types.SimpleNamespace(
            _slot_req={}, _free_slots=[], _pending=queue.Queue(),
            engine=types.SimpleNamespace(prefix_cache_stats=None),
            last_admit_kv_headroom=0.25,
            _deferred=[types.SimpleNamespace(
                deferred_at=time.perf_counter() - 0.5)],
            deadline_rejects=3,
            wasted_decode_steps=0)
        text = infer_metrics.ServeMetrics().render(orch=orch)
        assert 'xsky_serve_kv_headroom_at_admit 0.2500' in text
        assert 'xsky_serve_deadline_rejects_total 3' in text
        wait = [ln for ln in text.splitlines()
                if ln.startswith('xsky_serve_deferred_wait_seconds ')]
        assert wait and float(wait[0].split()[1]) >= 0.5

    def test_gauges_absent_without_signal(self):
        orch = types.SimpleNamespace(
            _slot_req={}, _free_slots=[], _pending=queue.Queue(),
            engine=types.SimpleNamespace(prefix_cache_stats=None),
            last_admit_kv_headroom=None, _deferred=[],
            deadline_rejects=0, wasted_decode_steps=0)
        text = infer_metrics.ServeMetrics().render(orch=orch)
        assert 'xsky_serve_kv_headroom_at_admit' not in text
        assert 'xsky_serve_deferred_wait_seconds' not in text
        # The rejects counter always exports (a zero IS the signal).
        assert 'xsky_serve_deadline_rejects_total 0' in text


# ---- deadline admission -----------------------------------------------------


class _StubEngine:
    """Attribute-surface stub: enough for admission-path unit tests
    (no device, no jit)."""
    max_admit_len = 64

    def __init__(self):
        self.config = types.SimpleNamespace(max_slots=2,
                                            max_target_len=128)

    def init_decode_state(self):
        return None

    def kv_admissible(self, prompt_len, max_new):
        return True

    def reserve_kv(self, slot, prompt_len, max_new):
        return True


class TestDeadlineAdmission:

    def _orch(self):
        from skypilot_tpu.infer import orchestrator as orch_lib
        return orch_lib.Orchestrator(_StubEngine()), orch_lib

    def test_expired_deadline_rejected_at_take(self):
        orch, orch_lib = self._orch()
        request = orch_lib.Request(prompt_tokens=[1, 2],
                                   max_new_tokens=4)
        request.deadline_at = time.perf_counter() - 0.5
        orch.submit(request)
        assert orch._take_request() is None
        assert orch.deadline_rejects == 1
        assert request.done
        assert request.error.startswith('deadline exceeded')

    def test_no_deadline_never_rejected(self):
        orch, orch_lib = self._orch()
        orch._ewma_prefill_s = 10.0   # absurd budget, no deadline
        request = orch.submit(orch_lib.Request(prompt_tokens=[1],
                                               max_new_tokens=4))
        assert orch._take_request() is request
        assert orch.deadline_rejects == 0

    def test_budget_estimate_gates_admission(self):
        orch, orch_lib = self._orch()
        orch._ewma_prefill_s = 0.05
        orch._ewma_decode_per_token_s = 0.01
        # 100 tokens → ~1.05s reserved budget.
        tight = orch_lib.Request(prompt_tokens=[1],
                                 max_new_tokens=100)
        tight.deadline_at = time.perf_counter() + 0.5
        orch.submit(tight)
        assert orch._take_request() is None
        assert tight.error and 'estimated' in tight.error
        roomy = orch_lib.Request(prompt_tokens=[1],
                                 max_new_tokens=100)
        roomy.deadline_at = time.perf_counter() + 5.0
        orch.submit(roomy)
        assert orch._take_request() is roomy

    def test_deferred_request_rechecked_on_retry(self):
        # A KV-deferred request re-enters admission ahead of the
        # queue; its deadline is re-checked there, and the wait it
        # accrued lands in the admit_deferred accumulator.
        orch, orch_lib = self._orch()
        request = orch_lib.Request(prompt_tokens=[1],
                                   max_new_tokens=4)
        request.deadline_at = time.perf_counter() - 0.1
        request.deferred_at = time.perf_counter() - 0.2
        orch._deferred.append(request)
        assert orch._take_request() is None
        assert orch.deadline_rejects == 1
        assert request.deferred_wait >= 0.2

    def test_slospec_deadline_ms_validation_and_round_trip(self):
        with pytest.raises(ValueError, match='deadline_ms'):
            SLOSpec(deadline_ms=0)
        # A deadline alone is a valid SLO section.
        assert SLOSpec(deadline_ms=30000).deadline_ms == 30000.0
        spec = SkyServiceSpec.from_yaml_config({
            'readiness_probe': '/',
            'slo': {'ttft_p99_ms': 500, 'deadline_ms': 30000}})
        config = spec.to_yaml_config()
        assert config['slo']['deadline_ms'] == 30000.0
        again = SkyServiceSpec.from_yaml_config(config)
        assert again.slo.deadline_ms == 30000.0
        # The task-YAML schema must accept it too — the spec layer
        # round-tripping is not enough for a user-authored task file.
        from skypilot_tpu.utils import schemas
        schemas.validate_task_config({
            'name': 'svc', 'run': 'python serve.py',
            'service': config})


# ---- serving-handler trace adoption -----------------------------------------


class _SyncLoop:
    """ServingLoop stand-in: completes every request synchronously so
    the handler's trace-adoption + seal path runs without a device."""

    class orch:  # noqa: N801 — minimal attribute surface
        _pending = queue.Queue()
        _slot_req: dict = {}
        _free_slots: list = []

        class engine:  # noqa: N801
            prefix_cache_stats = None

        @staticmethod
        def _admit_limit():
            return 63

    def submit_and_wait(self, request):
        now = time.perf_counter()
        request.submitted_at = now - 0.2
        request.taken_at = now - 0.19
        request.first_token_at = now - 0.15
        request.decode_s = 0.12
        request.commit_s = 0.01
        if request.deadline_at is not None and \
                request.deadline_at < now:
            request.error = ('deadline exceeded at admit: -100 ms '
                             'remaining < 50 ms estimated '
                             'prefill+decode budget')
        else:
            request.output_tokens.extend([1, 2, 3])
        request.done = True
        request.finished_at = now


@pytest.fixture
def handler_server(tmp_state):
    from skypilot_tpu.infer import engine as engine_lib
    from skypilot_tpu.infer import server as server_lib
    from skypilot_tpu.models import llama
    anatomy_lib.reset_for_test()
    handler_cls = server_lib.build_handler(
        _SyncLoop(), engine_lib.EngineConfig(model=llama.LLAMA_TINY),
        model_id='anatomy-test')
    httpd = ThreadingHTTPServer(('127.0.0.1', 0), handler_cls)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    yield f'http://127.0.0.1:{httpd.server_address[1]}'
    httpd.shutdown()


def _post_json(url, path, body, headers=None):
    req = urllib.request.Request(
        url + path, data=json.dumps(body).encode(),
        headers={'Content-Type': 'application/json',
                 **(headers or {})})
    try:
        with urllib.request.urlopen(req, timeout=30) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


class TestHandlerTraceAdoption:

    def test_relay_headers_adopted_and_sealed(self, handler_server):
        relay_headers = {}
        tracing.inject_headers(relay_headers, trace_id='tr-1',
                               request_id='rq-1', deadline_s=60.0)
        status, _ = _post_json(handler_server, '/generate',
                               {'prompt_tokens': [1, 2, 3],
                                'max_new_tokens': 4},
                               headers=relay_headers)
        assert status == 200
        rows = json.loads(urllib.request.urlopen(
            f'{handler_server}/anatomy?request_id=rq-1').read())
        assert len(rows) == 1
        assert rows[0]['trace_id'] == 'tr-1'
        assert rows[0]['outcome'] == 'ok'
        assert rows[0]['phases']['decode'] == pytest.approx(0.12)
        assert sum(rows[0]['phases'].values()) == pytest.approx(
            rows[0]['total_s'])

    def test_anatomy_endpoint_pages(self, handler_server):
        for i in range(4):
            _post_json(handler_server, '/generate',
                       {'prompt_tokens': [1], 'max_new_tokens': 1},
                       headers={'X-Xsky-Request-Id': f'pg-{i}'})
        rows = json.loads(urllib.request.urlopen(
            f'{handler_server}/anatomy?limit=2').read())
        assert [r['request_id'] for r in rows] == ['pg-3', 'pg-2']

    def test_deadline_reject_journalled_with_trace(
            self, handler_server, tmp_state):
        relay_headers = {}
        tracing.inject_headers(relay_headers, trace_id='tr-dead',
                               request_id='rq-dead',
                               deadline_s=-1.0)
        status, payload = _post_json(
            handler_server, '/generate',
            {'prompt_tokens': [1, 2], 'max_new_tokens': 4},
            headers=relay_headers)
        assert status == 400
        assert 'deadline exceeded' in payload['error']
        events = tmp_state.get_recovery_events(
            event_type='serve.deadline_reject')
        assert len(events) == 1
        assert events[0]['trace_id'] == 'tr-dead'
        assert events[0]['detail']['request_id'] == 'rq-dead'

    def test_anatomy_disabled_skips_seal(self, handler_server,
                                         monkeypatch):
        monkeypatch.setenv(anatomy_lib.ENV_ANATOMY, '0')
        status, _ = _post_json(handler_server, '/generate',
                               {'prompt_tokens': [1],
                                'max_new_tokens': 1},
                               headers={'X-Xsky-Request-Id': 'off-1'})
        assert status == 200
        rows = json.loads(urllib.request.urlopen(
            f'{handler_server}/anatomy?request_id=off-1').read())
        assert rows == []


# ---- LB: paging, retry survival ---------------------------------------------


class TestLbPagingAndRetries:

    def test_lb_requests_paging(self):
        server = _upstream(_EchoUpstream)
        lb = lb_lib.SkyServeLoadBalancer()
        lb.set_ready_replicas(
            [f'127.0.0.1:{server.server_address[1]}'])
        port = lb.run_in_thread()
        for _ in range(6):
            urllib.request.urlopen(
                f'http://127.0.0.1:{port}/gen').read()
        page = json.loads(urllib.request.urlopen(
            f'http://127.0.0.1:{port}/lb/requests?limit=2&offset=1'
        ).read())
        # Garbage paging params degrade to defaults, not a 500.
        garbage = json.loads(urllib.request.urlopen(
            f'http://127.0.0.1:{port}/lb/requests?limit=zzz&offset=-'
        ).read())
        lb.shutdown()
        server.shutdown()
        assert len(page) == 2
        everything = lb.request_log.records()
        assert [r['request_id'] for r in page] == \
            [r['request_id'] for r in everything[1:3]]
        # Records are JSON-safe and carry the cross-hop identity.
        assert 't0' not in page[0]
        assert page[0]['trace_id'] and page[0]['request_id']
        assert page[0]['relay_start_s'] is not None
        assert len(garbage) >= 6

    def test_retried_legs_same_ids_shrinking_deadline(self):

        class FlakyOnce(BaseHTTPRequestHandler):
            seen: list = []
            failed = [False]

            def log_message(self, *args):
                pass

            def do_GET(self):  # noqa: N802
                type(self).seen.append(dict(self.headers))
                if not type(self).failed[0]:
                    type(self).failed[0] = True
                    # RST before any response bytes: the relay's
                    # urlopen raises an OSError and retries the leg.
                    self.connection.setsockopt(
                        socket.SOL_SOCKET, socket.SO_LINGER,
                        struct.pack('ii', 1, 0))
                    self.connection.close()
                    return
                body = b'ok'
                self.send_response(200)
                self.send_header('Content-Length', str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        server = _upstream(FlakyOnce)
        lb = lb_lib.SkyServeLoadBalancer()
        lb.deadline_ms = 500.0
        lb.set_ready_replicas(
            [f'127.0.0.1:{server.server_address[1]}'])
        port = lb.run_in_thread()
        assert urllib.request.urlopen(
            f'http://127.0.0.1:{port}/gen', timeout=30).read() == \
            b'ok'
        lb.shutdown()
        server.shutdown()
        (rec,) = lb.request_log.records()
        assert rec['outcome'] == 'ok'
        assert rec['retries'] == 1
        legs = [tracing.extract_headers(h) for h in FlakyOnce.seen]
        assert len(legs) == 2
        # Both legs carry the SAME minted identity...
        assert legs[0][0] == legs[1][0] == rec['trace_id']
        assert legs[0][1] == legs[1][1] == rec['request_id']
        # ...while the deadline budget is re-measured per leg, so the
        # retry's remaining budget can only shrink.
        assert legs[0][2] is not None and legs[1][2] is not None
        assert legs[1][2] <= legs[0][2] <= 0.5


# ---- update-mode policy swap ------------------------------------------------


class _HeaderCapture(BaseHTTPRequestHandler):
    seen: list = []

    def log_message(self, *args):
        pass

    def do_GET(self):  # noqa: N802
        type(self).seen.append(dict(self.headers))
        body = b'ok'
        self.send_response(200)
        self.send_header('Content-Length', str(len(body)))
        self.end_headers()
        self.wfile.write(body)


class TestPolicySwapSurvival:

    def test_trace_context_and_stats_survive_policy_swap(
            self, tmp_state, tmp_serve_db):
        from skypilot_tpu.serve import controller as controller_lib
        from skypilot_tpu.serve import state as serve_state

        def config(policy, deadline_ms):
            return {'service': {
                'readiness_probe': '/',
                'load_balancing_policy': policy,
                'slo': {'ttft_p99_ms': 100,
                        'deadline_ms': deadline_ms}}}

        _HeaderCapture.seen = []
        upstream = _upstream(_HeaderCapture)
        endpoint = f'127.0.0.1:{upstream.server_address[1]}'
        serve_state.add_service('swpsvc',
                                config('round_robin', 1000), 0)
        controller = controller_lib.SkyServeController('swpsvc')
        lb = controller.load_balancer
        assert isinstance(lb.policy, lb_policies.RoundRobinPolicy)
        assert lb.deadline_ms == 1000.0
        lb.set_ready_replicas([endpoint])
        port = lb.run_in_thread()
        try:
            urllib.request.urlopen(
                f'http://127.0.0.1:{port}/gen', timeout=30).read()
            stats_before = lb.replica_stats
            request_log_before = lb.request_log
            first_record = lb.request_log.records()[0]

            serve_state.bump_service_version(
                'swpsvc', config('least_load', 2000))
            controller._maybe_adopt_new_version()

            # The policy swapped, but the rolling stats tracker and
            # the lifecycle ring are the SAME objects — history (and
            # every persisted trace id) survives the update.
            assert isinstance(lb.policy, lb_policies.LeastLoadPolicy)
            assert lb.policy.stats is stats_before
            assert lb.replica_stats is stats_before
            assert lb.request_log is request_log_before
            assert lb.request_log.records()[0]['trace_id'] == \
                first_record['trace_id']
            # ...and the new deadline is threaded into the relay.
            assert lb.deadline_ms == 2000.0
            lb.set_ready_replicas([endpoint])
            urllib.request.urlopen(
                f'http://127.0.0.1:{port}/gen', timeout=30).read()
        finally:
            lb.shutdown()
            upstream.shutdown()
            controller.replica_manager._pool.shutdown(wait=False)

        legs = [tracing.extract_headers(h)
                for h in _HeaderCapture.seen]
        assert len(legs) == 2
        # Every leg (before AND after the swap) carried trace context;
        # the deadline header tracks the adopted spec.
        assert all(t and r for t, r, _ in legs)
        assert 0 < legs[0][2] <= 1.0
        assert 1.0 < legs[1][2] <= 2.0
        # Rolling stats accumulated across the swap.
        snap = lb.replica_stats.snapshot()[endpoint]
        assert snap['requests_total'] == 2
        # Distinct client requests mint distinct ids.
        assert legs[0][1] != legs[1][1]


# ---- exemplar table ---------------------------------------------------------


def _exemplar_row(i=0, **overrides):
    row = {
        'ts': time.time(),
        'request_id': f'req-{i}',
        'trace_id': f'tr-{i}',
        'replica': '3',
        'path': '/v1/completions',
        'outcome': 'ok',
        'e2e_s': 1.5,
        'ttft_s': 0.4,
        'phases': {'lb_queue': 0.1, 'relay_connect': 0.2,
                   'decode': 1.2},
        'detail': {'retries': 0, 'replica_id': 3},
    }
    row.update(overrides)
    return row


class TestExemplarTable:

    def test_round_trip_and_filters(self, tmp_state):
        tmp_state.record_serve_slo_exemplars(
            'svc', [_exemplar_row(0), _exemplar_row(1)])
        rows = tmp_state.get_serve_slo_exemplars(service='svc')
        assert len(rows) == 2
        assert rows[0]['request_id'] == 'req-1'   # newest-first
        assert rows[0]['phases']['decode'] == 1.2
        assert rows[0]['detail']['replica_id'] == 3
        (by_trace,) = tmp_state.get_serve_slo_exemplars(
            trace_id='tr-0')
        assert by_trace['request_id'] == 'req-0'
        (by_req,) = tmp_state.get_serve_slo_exemplars(
            request_id='req-1')
        assert by_req['trace_id'] == 'tr-1'
        assert tmp_state.get_serve_slo_exemplars(
            service='ghost') == []

    def test_retention_bound(self, tmp_state, monkeypatch):
        monkeypatch.setattr(tmp_state, '_MAX_SERVE_SLO_EXEMPLARS', 10)
        monkeypatch.setattr(tmp_state, '_serve_slo_exemplar_inserts',
                            0)
        tmp_state.record_serve_slo_exemplars(
            'svc', [_exemplar_row(i) for i in range(30)])
        rows = tmp_state.get_serve_slo_exemplars(service='svc',
                                                 limit=1000)
        assert len(rows) == 10
        assert {r['request_id'] for r in rows} == \
            {f'req-{i}' for i in range(20, 30)}

    def test_record_never_raises(self, tmp_state, monkeypatch):
        monkeypatch.setenv('XSKY_STATE_DB',
                           '/nonexistent/dir/state.db')
        tmp_state.reset_for_test()
        tmp_state.record_serve_slo_exemplars(
            'svc', [_exemplar_row()])  # no raise


# ---- cross-hop waterfall join -----------------------------------------------


def _lb_record(rid='r1', now=None, **overrides):
    now = time.time() if now is None else now
    rec = {'ts': now - 1, 'request_id': rid, 'trace_id': f'tr-{rid}',
           'replica': 'a:1', 'path': '/gen', 'outcome': 'ok',
           'e2e_s': 1.0, 'ttft_s': 0.5, 'relay_start_s': 0.2,
           'retries': 0, 'status': 200}
    rec.update(overrides)
    return rec


def _anatomy(rid='r1', **overrides):
    rec = {'request_id': rid, 'replica_id': 3, 'outcome': 'ok',
           'output_tokens': 16, 'kv_headroom_at_admit': 0.8,
           'phases': {'replica_queue': 0.05, 'admit_deferred': 0.0,
                      'prefill': 0.1, 'decode': 0.5,
                      'sampling_commit': 0.02, 'finish': 0.03}}
    rec.update(overrides)
    return rec


class TestExemplarJoin:

    def test_joined_phases_sum_to_client_e2e(self):
        now = time.time()
        records = [_lb_record(now=now)]
        monitor = slo_lib.SLOMonitor('svc', None,
                                     record_source=lambda: records)
        (ex,) = monitor._build_exemplars({'r1': _anatomy()}, now,
                                         [60.0])
        phases = ex['phases']
        assert phases['lb_queue'] == pytest.approx(0.2)
        # relay_connect is the remainder: e2e − lb_queue − replica.
        assert phases['relay_connect'] == pytest.approx(0.1)
        assert sum(phases.values()) == pytest.approx(ex['e2e_s'])
        assert ex['detail']['replica_id'] == 3
        assert ex['detail']['kv_headroom_at_admit'] == 0.8
        assert ex['trace_id'] == 'tr-r1'

    def test_relay_remainder_clamped_nonnegative(self):
        # Clock skew / replica phases exceeding the LB-observed e2e
        # must clamp, not go negative in a persisted waterfall.
        now = time.time()
        records = [_lb_record(now=now, e2e_s=0.3)]
        monitor = slo_lib.SLOMonitor('svc', None,
                                     record_source=lambda: records)
        (ex,) = monitor._build_exemplars({'r1': _anatomy()}, now,
                                         [60.0])
        assert ex['phases']['relay_connect'] == 0.0

    def test_missing_anatomy_keeps_lb_half(self):
        now = time.time()
        records = [_lb_record(now=now)]
        monitor = slo_lib.SLOMonitor('svc', None,
                                     record_source=lambda: records)
        (ex,) = monitor._build_exemplars({}, now, [60.0])
        assert ex['detail']['anatomy'] == 'missing'
        assert ex['phases'] == {'lb_queue': pytest.approx(0.2)}

    def test_dedup_across_ticks_and_top_k(self, monkeypatch):
        monkeypatch.setenv(slo_lib.ENV_EXEMPLAR_TOP_K, '2')
        now = time.time()
        records = [_lb_record(f'r{i}', now=now, e2e_s=1.0 + i)
                   for i in range(5)]
        monitor = slo_lib.SLOMonitor('svc', None,
                                     record_source=lambda: records)
        first = monitor._build_exemplars({}, now, [60.0])
        # Top-K slowest win.
        assert [e['request_id'] for e in first] == ['r4', 'r3']
        # The same slow requests stay in the burn window for the next
        # tick — they must not be re-persisted.
        second = monitor._build_exemplars({}, now, [60.0])
        assert [e['request_id'] for e in second] == ['r2', 'r1']

    def test_unfinished_and_stale_records_skipped(self):
        now = time.time()
        records = [_lb_record('live', now=now),
                   _lb_record('open', now=now, e2e_s=None),
                   _lb_record('old', now=now, ts=now - 7200)]
        monitor = slo_lib.SLOMonitor('svc', None,
                                     record_source=lambda: records)
        out = monitor._build_exemplars({}, now, [60.0])
        assert [e['request_id'] for e in out] == ['live']

    def test_breach_attaches_exemplar_trace_ids(self, tmp_state,
                                                monkeypatch):
        monkeypatch.setenv(slo_lib.ENV_SCRAPE_INTERVAL, '0')
        monkeypatch.setenv(slo_lib.ENV_BURN_WINDOWS, '60')
        now = time.time()
        records = [_lb_record(f'r{i}', now=now, ttft_s=0.5)
                   for i in range(20)]
        monitor = slo_lib.SLOMonitor(
            'svc', SLOSpec(ttft_p99_ms=100),
            record_source=lambda: records)
        result = monitor.maybe_tick([], now=now)
        assert result['verdict'] == 'breach'
        (breach,) = tmp_state.get_recovery_events(
            event_type='serve.slo_breach')
        linked = breach['detail']['exemplar_trace_ids']
        assert linked, 'breach carries no exemplar trace ids'
        # Every linked id resolves in the persisted exemplar table —
        # the `xsky serve trace --request` contract.
        for trace_id in linked:
            assert tmp_state.get_serve_slo_exemplars(
                service='svc', trace_id=trace_id)


# ---- `xsky serve trace` surface ---------------------------------------------


class TestServeTraceCli:

    def _seed(self, tmp_state):
        tmp_state.record_serve_slo_exemplars('svc', [
            _exemplar_row(0, e2e_s=0.9),
            _exemplar_row(1, e2e_s=2.0, phases={
                'lb_queue': 0.05, 'relay_connect': 0.05,
                'replica_queue': 0.1, 'decode': 1.8},
                detail={'retries': 2, 'replica_id': 7,
                        'kv_headroom_at_admit': 0.42}),
        ])

    def test_text_waterfall(self, tmp_state):
        from click.testing import CliRunner

        from skypilot_tpu.client import cli as cli_mod
        self._seed(tmp_state)
        result = CliRunner().invoke(
            cli_mod.cli, ['serve', 'trace', 'svc', '--slowest', '1'])
        assert result.exit_code == 0, result.output
        # Slowest-first: the decode-heavy request leads.
        assert 'request req-1' in result.output
        assert 'request req-0' not in result.output
        assert 'e2e=2000ms' in result.output
        decode_line = [ln for ln in result.output.splitlines()
                       if ln.strip().startswith('decode')][0]
        assert '1800.0ms' in decode_line
        assert '#' * 30 in decode_line   # decode dominates the bar
        assert 'kv_headroom_at_admit=0.42' in result.output
        assert 'retries=2' in result.output

    def test_request_lookup_accepts_trace_id(self, tmp_state):
        from click.testing import CliRunner

        from skypilot_tpu.client import cli as cli_mod
        self._seed(tmp_state)
        for ident in ('req-0', 'tr-0'):
            result = CliRunner().invoke(
                cli_mod.cli,
                ['serve', 'trace', 'svc', '--request', ident,
                 '--json'])
            assert result.exit_code == 0, result.output
            (row,) = [json.loads(ln) for ln in
                      result.output.strip().splitlines()]
            assert row['request_id'] == 'req-0'
            assert row['phases']['decode'] == 1.2

    def test_empty_service_message(self, tmp_state):
        from click.testing import CliRunner

        from skypilot_tpu.client import cli as cli_mod
        result = CliRunner().invoke(cli_mod.cli,
                                    ['serve', 'trace', 'ghost'])
        assert result.exit_code == 0
        assert 'No trace exemplars' in result.output


# ---- tier-1 fake-cloud anatomy drill ----------------------------------------


DRILL_REPLICA_SCRIPT = '''
import http.server, json, os, sys, time, types
sys.path.insert(0, {repo_root!r})
from skypilot_tpu.infer import anatomy as anatomy_lib
from skypilot_tpu.infer import metrics as metrics_lib
from skypilot_tpu.utils import chaos, tracing

# Chaos plan local to the replica process: every decode tick stalls —
# the latency the anatomy drill must attribute to decode, not queue.
chaos.load_plan(
    {{'points': {{'infer.decode_stall': {{'latency_s': 0.3}}}}}})
metrics = metrics_lib.ServeMetrics()
anatomy_log = anatomy_lib.get_log()


class H(http.server.BaseHTTPRequestHandler):
    def log_message(self, *a):
        pass

    def do_GET(self):
        if self.path == '/metrics':
            body = metrics.render().encode()
        elif self.path.startswith('/anatomy'):
            body = json.dumps(
                anatomy_log.records(limit=200)).encode()
        else:
            trace_id, req_id, _ = tracing.extract_headers(
                self.headers)
            sub = time.perf_counter()
            chaos.inject('infer.decode_stall')
            end = time.perf_counter()
            if req_id:   # relayed traffic only; probes stay unsealed
                anatomy_log.seal(types.SimpleNamespace(
                    submitted_at=sub, taken_at=sub + 1e-4,
                    deferred_wait=0.0,
                    first_token_at=sub + 2e-4, finished_at=end,
                    decode_s=end - sub - 3e-4, commit_s=1e-4,
                    kv_headroom_at_admit=0.9,
                    prompt_tokens=[1, 2, 3],
                    output_tokens=[4] * 16, request_id=0,
                    client_request_id=req_id, trace_id=trace_id))
            metrics.observe('/gen', 'ok', 3, 16, ttft_s=end - sub,
                            e2e_s=end - sub, tpot_s=(end - sub) / 16)
            body = b'ok'
        self.send_response(200)
        self.send_header('Content-Length', str(len(body)))
        self.end_headers()
        self.wfile.write(body)


http.server.ThreadingHTTPServer(
    ('127.0.0.1', int(os.environ['PORT'])), H).serve_forever()
'''


class TestAnatomyDrill:
    """Tier-1 acceptance: a chaos-stalled decode tick
    (`infer.decode_stall`) trips a `serve.slo_breach` whose
    `exemplar_trace_ids` resolve via `xsky serve trace --json` to a
    cross-hop waterfall that attributes the latency to decode — not
    to the LB or replica queues."""

    def test_decode_stall_breach_resolves_to_decode_waterfall(
            self, fake_cluster_env, monkeypatch, tmp_path):
        del fake_cluster_env
        import textwrap

        import yaml

        from click.testing import CliRunner

        from skypilot_tpu import state as state_lib
        from skypilot_tpu import task as task_lib
        from skypilot_tpu.client import cli as cli_mod
        from skypilot_tpu.serve import controller as controller_lib
        from skypilot_tpu.serve import core as serve_core
        from skypilot_tpu.serve import state as serve_state

        monkeypatch.setenv('XSKY_SERVE_DB',
                           str(tmp_path / 'serve.db'))
        monkeypatch.setenv('XSKY_SERVE_LOG_DIR',
                           str(tmp_path / 'serve_logs'))
        monkeypatch.setenv('XSKY_SERVE_INTERVAL', '0.5')
        monkeypatch.setenv(slo_lib.ENV_SCRAPE_INTERVAL, '1')
        monkeypatch.setenv(slo_lib.ENV_BURN_WINDOWS, '5,30')

        script = tmp_path / 'replica.py'
        script.write_text(
            DRILL_REPLICA_SCRIPT.format(repo_root=REPO_ROOT))
        config = yaml.safe_load(textwrap.dedent(f'''\
            name: anatsvc
            resources:
              accelerators: tpu-v5e-8
            service:
              readiness_probe: /
              replica_policy:
                min_replicas: 1
              slo:
                ttft_p99_ms: 100
                availability: 0.99
            run: |
              python {script}
        '''))
        task = task_lib.Task.from_yaml_config(config)
        with socket.socket() as s:
            s.bind(('127.0.0.1', 0))
            lb_port = s.getsockname()[1]
        serve_state.add_service('anatsvc', task.to_yaml_config(),
                                lb_port)
        controller = controller_lib.SkyServeController('anatsvc')
        thread = threading.Thread(
            target=controller.run,
            name='xsky-test-anatomy-controller', daemon=True)
        thread.start()
        try:
            deadline = time.time() + 120
            while time.time() < deadline:
                record = serve_state.get_service('anatsvc')
                if record['status'] == \
                        serve_state.ServiceStatus.READY:
                    break
                assert record['status'] != \
                    serve_state.ServiceStatus.FAILED, \
                    serve_core.controller_logs('anatsvc')
                time.sleep(0.3)
            else:
                pytest.fail('service never became READY')

            # Traffic whose decode tick the chaos plan stalls 300ms
            # against a 100ms TTFT target.
            for _ in range(15):
                urllib.request.urlopen(
                    f'http://127.0.0.1:{lb_port}/gen',
                    timeout=30).read()

            breach = None
            deadline = time.time() + 45
            while breach is None and time.time() < deadline:
                events = state_lib.get_recovery_events(
                    event_type='serve.slo_breach')
                breach = events[-1] if events else None
                time.sleep(0.3)
            assert breach is not None, \
                'serve.slo_breach never journalled'
            linked = breach['detail'].get('exemplar_trace_ids')
            assert linked, 'breach carries no exemplar trace ids'

            # The journalled trace id resolves to a full cross-hop
            # waterfall through the CLI.
            result = CliRunner().invoke(
                cli_mod.cli, ['serve', 'trace', 'anatsvc',
                              '--request', linked[0], '--json'])
            assert result.exit_code == 0, result.output
            rows = [json.loads(ln) for ln in
                    result.output.strip().splitlines()]
            assert rows, 'exemplar trace id resolved to nothing'
            phases = rows[0]['phases']
            # The waterfall blames the stalled decode tick, not the
            # queues on either side of the hop.
            assert phases.get('decode', 0.0) > 0.2
            assert phases['decode'] > 0.5 * rows[0]['e2e_s']
            assert phases['decode'] > (
                phases.get('lb_queue', 0.0) +
                phases.get('replica_queue', 0.0) +
                phases.get('admit_deferred', 0.0))
            assert rows[0]['detail']['replica_id'] is not None
        finally:
            controller.stop()
            thread.join(timeout=60)
            chaos.clear()
            try:
                serve_core.down('anatsvc')
            except Exception:  # pylint: disable=broad-except
                pass
        assert not thread.is_alive(), 'controller wedged'
