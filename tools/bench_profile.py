#!/usr/bin/env python3
"""Step-anatomy sampler overhead micro-benchmark (the PR's <2% gate).

``profiler.step_probe()`` sits on the training step loop
(``trainer.step``) and the serving decode tick — its cost must be
invisible next to real step work. The sampler has two cost classes:

  * **unsampled steps** (the common path, (N-1)/N of all steps): two
    dict lookups, an increment, a modulo — measured as a tight loop
    around ``step_probe()`` alone, stable to well under a microsecond;
  * **sampled steps** (1/N): a probe object, the anatomy EMA update,
    an HBM readout, and a telemetry emit (rate-limited spool write
    amortized in). The device sync a real sampled step pays is the
    device's own step time being waited out, not added work — the
    fake-profiler seam stands in for it here, so this tool measures
    the sampler's HOST cost, the part the gate owns.

The gated number is the **blended per-step cost** at the default
sampling cadence::

    blended_us = unsampled_us + sampled_us / sample_every
    gate:  blended_us / step_us < --max-overhead-pct   (default 2%)

against a ~4 ms synthetic step (median-of-N; a FAST real step —
production steps are 100 ms+), same gate pattern as
``bench_telemetry.py`` / ``bench_fanout.py --trace-overhead``. Prints
ONE JSON line; exit 1 on gate failure.

Usage:
    python tools/bench_profile.py [--calls 100000] [--smoke]
                                  [--max-overhead-pct 2.0]
"""
import argparse
import json
import os
import statistics
import sys
import tempfile
import time

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO_ROOT)

# Synthetic step work: ~4 ms of pure-python arithmetic — the least
# favorable realistic step size (small models on big chips).
_WORK_ITERS = 40000


def _step_work() -> int:
    x = 0
    for i in range(_WORK_ITERS):
        x += i * i
    return x


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('--calls', type=int, default=100000,
                        help='step_probe calls per measurement')
    parser.add_argument('--max-overhead-pct', type=float, default=2.0)
    parser.add_argument('--smoke', action='store_true',
                        help='Reduced counts for the tier-1 subprocess '
                             'gate (same gate, less wall-clock).')
    args = parser.parse_args()
    calls = 20000 if args.smoke else args.calls
    work_reps = 20 if args.smoke else 50

    from skypilot_tpu.agent import profiler
    from skypilot_tpu.agent import telemetry

    spool = tempfile.mkdtemp(prefix='xsky-bench-profile-')
    os.environ[telemetry.ENV_DIR] = spool
    # Fake seam: sampled probes must not need a device; the gate owns
    # the sampler's host cost (see module docstring).
    os.environ[profiler.ENV_FAKE] = '1'
    telemetry.reset_for_test()
    profiler.reset_for_test()

    def _probe_us(sample_every: int, n: int) -> float:
        os.environ[profiler.ENV_SAMPLE_EVERY] = str(sample_every)
        profiler.reset_for_test()
        # Warm: anatomy construction, first spool write, config cache.
        probe = profiler.step_probe()
        if probe is not None:
            probe.done()
        t0 = time.perf_counter()
        for _ in range(n):
            probe = profiler.step_probe()
            if probe is not None:
                probe.done()
        return (time.perf_counter() - t0) / n * 1e6

    # Unsampled path: cadence far beyond the loop length.
    unsampled_us = _probe_us(1 << 30, calls)
    # Sampled path: every call probes (upper bound on the 1/N cost).
    sampled_us = _probe_us(1, max(calls // 10, 1000))
    # Disabled path (XSKY_PROFILE=0): what every non-profiled process
    # pays.
    os.environ[profiler.ENV_ENABLED] = '0'
    profiler.reset_for_test()
    disabled_us = _probe_us(1 << 30, calls)
    del os.environ[profiler.ENV_ENABLED]
    profiler.reset_for_test()

    # Step work: median of N (jitters far more than the probe does).
    work_times = []
    for _ in range(work_reps):
        t0 = time.perf_counter()
        _step_work()
        work_times.append(time.perf_counter() - t0)
    step_us = statistics.median(work_times) * 1e6

    sample_every = profiler._DEFAULT_SAMPLE_EVERY  # pylint: disable=protected-access
    blended_us = unsampled_us + sampled_us / sample_every
    overhead_pct = blended_us / step_us * 100.0
    ok = overhead_pct < args.max_overhead_pct

    samples = telemetry.read_spool(spool)
    import shutil
    shutil.rmtree(spool, ignore_errors=True)

    print(json.dumps({
        'metric': 'profiler_step_probe_overhead',
        'unsampled_us': round(unsampled_us, 3),
        'sampled_us': round(sampled_us, 2),
        'disabled_us': round(disabled_us, 3),
        'sample_every': sample_every,
        'blended_us': round(blended_us, 3),
        'step_work_us_median': round(step_us, 1),
        'overhead_pct': round(overhead_pct, 3),
        'spool_profile_sampled': ((samples.get(0) or {}).get('profile')
                                  or {}).get('steps_sampled'),
        'max_overhead_pct': args.max_overhead_pct,
        'smoke': args.smoke,
        'pass': ok,
    }))
    return 0 if ok else 1


if __name__ == '__main__':
    sys.exit(main())
