"""Serving SLO plane: burn-rate math, replica /metrics scraping, and
the controller-tick monitor that persists both into the `serve_slo`
table.

The measurement substrate for SLO-driven serving (ROADMAP "Production
serve data plane"): objectives are declared in the service spec
(``slo: {ttft_p99_ms, availability, tpot_p50_ms}``,
:class:`~skypilot_tpu.serve.service_spec.SLOSpec`), observed at two
places —

  * the load balancer's per-request lifecycle records (user-facing
    TTFT/e2e/outcome, ``serve/load_balancer.py``), which feed the
    availability and TTFT objectives over multiple burn windows;
  * each ready replica's Prometheus ``/metrics`` text (the histograms
    ``infer/metrics.py`` already renders), scraped per controller tick
    for per-replica latency digests and the TPOT objective —

and folded into *burn rates*: observed bad fraction over the error
budget, per window (SRE error-budget methodology; burn >= 1 means the
budget is being spent exactly as fast as it accrues, >> 1 means an
incident). A breach (every window over threshold) is journalled as
``serve.slo_breach`` and surfaced via `xsky slo`, `xsky serve status`
and the control-plane ``/metrics`` gauges.
"""
from __future__ import annotations

import collections
import json
import os
import time
import urllib.request
from typing import (Any, Callable, Dict, List, Optional, Sequence,
                    Tuple)

from skypilot_tpu import sky_logging

logger = sky_logging.init_logger(__name__)

# Multi-window burn evaluation (short window catches fast burn, long
# window keeps the alert from flapping on one bad minute). Seconds,
# comma-separated.
ENV_BURN_WINDOWS = 'XSKY_SLO_BURN_WINDOWS'
DEFAULT_BURN_WINDOWS = '300,3600'
# Breach when EVERY window with data burns at or above this rate.
ENV_BURN_THRESHOLD = 'XSKY_SLO_BURN_THRESHOLD'
# Replica /metrics scrape cadence (the controller tick runs more often;
# scraping every tick would hammer replicas for no signal).
ENV_SCRAPE_INTERVAL = 'XSKY_SLO_SCRAPE_INTERVAL_S'
ENV_SCRAPE_TIMEOUT = 'XSKY_SLO_SCRAPE_TIMEOUT'
# Slow-request exemplars persisted per evaluation (0 disables). The
# table itself is retention-bounded in state.py; this only caps how
# many NEW waterfalls one tick may add.
ENV_EXEMPLAR_TOP_K = 'XSKY_SLO_EXEMPLAR_TOP_K'


def exemplar_top_k() -> int:
    try:
        return int(os.environ.get(ENV_EXEMPLAR_TOP_K, '8'))
    except ValueError:
        return 8


def burn_windows() -> List[float]:
    return parse_windows(
        os.environ.get(ENV_BURN_WINDOWS, DEFAULT_BURN_WINDOWS))


def parse_windows(value: str) -> List[float]:
    """'300,3600' → [300.0, 3600.0]; unparseable entries dropped, an
    empty/garbage value falls back to the default (a typo'd knob must
    not disable burn evaluation)."""
    out = []
    for part in str(value).split(','):
        part = part.strip()
        if not part:
            continue
        try:
            w = float(part)
        except ValueError:
            continue
        if w > 0:
            out.append(w)
    if not out:
        out = [float(p) for p in DEFAULT_BURN_WINDOWS.split(',')]
    return sorted(out)


def burn_threshold() -> float:
    try:
        return float(os.environ.get(ENV_BURN_THRESHOLD, '1.0'))
    except ValueError:
        return 1.0


# ---- histogram --------------------------------------------------------------


def fmt_le(le: float) -> str:
    return '+Inf' if le == float('inf') else f'{le:g}'


class Histogram:
    """Cumulative-bucket histogram rendering the Prometheus text
    format; the LB-side twin of infer/metrics._Histogram (kept public
    here so the SLO plane owns one copy of the bucket math)."""

    def __init__(self, buckets: Sequence[float]) -> None:
        self.les = tuple(buckets)
        self.counts = [0] * len(self.les)
        self.total = 0.0
        self.n = 0

    def observe(self, value: float) -> None:
        for i, le in enumerate(self.les):
            if value <= le:
                self.counts[i] += 1
        self.total += value
        self.n += 1

    def render(self, name: str) -> List[str]:
        lines = [f'# TYPE {name} histogram']
        for i, le in enumerate(self.les):
            lines.append(
                f'{name}_bucket{{le="{fmt_le(le)}"}} {self.counts[i]}')
        lines.append(f'{name}_sum {self.total:.6f}')
        lines.append(f'{name}_count {self.n}')
        return lines


# ---- prometheus text parsing ------------------------------------------------

Sample = Tuple[Dict[str, str], float]


def parse_prometheus_text(text: str) -> Dict[str, List[Sample]]:
    """Parse exposition-format text → {metric name: [(labels, value)]}.

    Handles exactly the subset our replicas render (``# TYPE``/``HELP``
    comments, ``name value`` and ``name{k="v",...} value`` lines);
    malformed lines are skipped, never fatal — a half-written scrape
    must not take the controller tick down."""
    out: Dict[str, List[Sample]] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith('#'):
            continue
        try:
            name, labels, value = _parse_sample_line(line)
        except ValueError:
            continue
        out.setdefault(name, []).append((labels, value))
    return out


def _parse_sample_line(line: str) -> Tuple[str, Dict[str, str], float]:
    labels: Dict[str, str] = {}
    if '{' in line:
        name, rest = line.split('{', 1)
        label_text, _, value_text = rest.rpartition('}')
        for pair in _split_labels(label_text):
            if '=' not in pair:
                continue
            k, v = pair.split('=', 1)
            labels[k.strip()] = _unescape_label(v.strip().strip('"'))
    else:
        name, _, value_text = line.partition(' ')
    return name.strip(), labels, float(value_text.strip())


def _split_labels(text: str) -> List[str]:
    """Split 'a="x",b="y,z"' on commas outside quotes."""
    parts, cur, in_quotes, escaped = [], [], False, False
    for ch in text:
        if escaped:
            cur.append(ch)
            escaped = False
            continue
        if ch == '\\':
            cur.append(ch)
            escaped = True
            continue
        if ch == '"':
            in_quotes = not in_quotes
        if ch == ',' and not in_quotes:
            parts.append(''.join(cur))
            cur = []
        else:
            cur.append(ch)
    if cur:
        parts.append(''.join(cur))
    return parts


def _unescape_label(value: str) -> str:
    return (value.replace('\\\\', '\\').replace('\\"', '"')
            .replace('\\n', '\n'))


def _parse_le(value: str) -> float:
    return float('inf') if value in ('+Inf', 'inf') else float(value)


Buckets = List[Tuple[float, float]]  # (le, cumulative count), sorted


def histogram_buckets(samples: Dict[str, List[Sample]],
                      name: str) -> Optional[Dict[str, Any]]:
    """Reassemble one histogram from parsed samples →
    {'buckets': [(le, cum_count)...], 'sum': float, 'count': int},
    or None when the metric is absent."""
    bucket_samples = samples.get(f'{name}_bucket')
    if not bucket_samples:
        return None
    buckets = []
    for labels, value in bucket_samples:
        if 'le' not in labels:
            continue
        try:
            buckets.append((_parse_le(labels['le']), value))
        except ValueError:
            continue
    if not buckets:
        return None
    buckets.sort(key=lambda b: b[0])
    total = sum(v for _, v in samples.get(f'{name}_sum', ())) or 0.0
    count = sum(v for _, v in samples.get(f'{name}_count', ())) or 0
    return {'buckets': buckets, 'sum': total, 'count': int(count)}


def quantile_from_buckets(buckets: Buckets,
                          q: float) -> Optional[float]:
    """Estimate the q-quantile from cumulative buckets (linear
    interpolation inside the landing bucket, the promql
    histogram_quantile estimator). None on an empty histogram; the
    +Inf bucket clamps to the last finite boundary."""
    if not buckets:
        return None
    total = buckets[-1][1]
    if total <= 0:
        return None
    rank = q * total
    prev_le, prev_count = 0.0, 0.0
    for le, count in buckets:
        if count >= rank:
            if le == float('inf'):
                return prev_le if prev_le > 0 else None
            if count == prev_count:
                return le
            frac = (rank - prev_count) / (count - prev_count)
            return prev_le + (le - prev_le) * frac
        prev_le, prev_count = le, count
    return prev_le if prev_le > 0 else None


def frac_over(buckets: Buckets, threshold: float) -> Optional[float]:
    """Fraction of observations above `threshold`, using the smallest
    bucket boundary >= threshold (conservative: observations between
    the threshold and that boundary count as under)."""
    if not buckets:
        return None
    total = buckets[-1][1]
    if total <= 0:
        return None
    for le, count in buckets:
        if le >= threshold:
            return (total - count) / total
    return 0.0


def delta_buckets(old: Optional[Buckets],
                  new: Buckets) -> Buckets:
    """new - old per bucket boundary (windowed view of a cumulative
    histogram). A replica restart (counts went backwards) returns
    `new` whole — its histogram restarted from zero."""
    if not old:
        return list(new)
    old_map = dict(old)
    out = []
    for le, count in new:
        prev = old_map.get(le, 0.0)
        if count < prev:
            return list(new)
        out.append((le, count - prev))
    return out


def merge_buckets(histograms: List[Buckets]) -> Buckets:
    """Sum several cumulative-bucket histograms boundary-wise (the
    fleet view of per-replica histograms). Boundaries are unioned; a
    histogram missing a boundary contributes its nearest lower cum
    count there (conservative, and exact when fleets share buckets —
    ours always do)."""
    merged: Dict[float, float] = {}
    for buckets in histograms:
        for le, count in buckets:
            merged[le] = merged.get(le, 0.0) + count
    return sorted(merged.items())


def pctl_ms(sorted_values: List[float], q: float) -> Optional[float]:
    """Index-based q-quantile of SORTED second-valued samples, in ms
    (the one copy — ReplicaStats.snapshot and the service row share
    it). None on empty."""
    if not sorted_values:
        return None
    idx = min(len(sorted_values) - 1, int(q * len(sorted_values)))
    return sorted_values[idx] * 1000.0


# ---- burn rate --------------------------------------------------------------


def burn_rate(bad: float, total: float,
              budget: float) -> Optional[float]:
    """Observed bad fraction over the error budget.

    None with no observations (an empty window says nothing). A zero
    budget (availability: 1.0) burns infinitely on the first bad
    request and 0 otherwise — the only consistent reading of "no
    errors allowed"."""
    if total <= 0:
        return None
    frac = bad / total
    if budget <= 0:
        return 0.0 if frac == 0 else float('inf')
    return frac / budget


# Outcomes that spend the availability error budget. client_gone is the
# client's own disconnect and spends nothing; no_replica/unreachable
# ARE unavailability even though no replica ever saw the request.
# 'draining' (503 + Retry-After while every routable replica drains)
# is deliberate load-shedding, but the client still got a 503 — it
# spends budget so a drain storm cannot hide from the SLO.
BAD_OUTCOMES = frozenset(
    {'error', 'unreachable', 'no_replica', 'truncated', 'draining'})


def burns_from_records(records: List[Dict[str, Any]],
                       slo,
                       now: Optional[float] = None,
                       windows: Optional[List[float]] = None,
                       ) -> Dict[str, Dict[str, Optional[float]]]:
    """Burn rates per window from LB request records →
    {window("300"): {objective: burn|None}}.

    availability counts BAD_OUTCOMES over all non-client-cancelled
    requests; ttft_p99_ms counts records whose relay-observed TTFT
    exceeded the target over all records that measured one (budget:
    the 1% a p99 objective concedes)."""
    now = time.time() if now is None else now
    windows = windows if windows is not None else burn_windows()
    out: Dict[str, Dict[str, Optional[float]]] = {}
    for window in windows:
        sel = [r for r in records
               if (r.get('ts') or 0) >= now - window and
               r.get('outcome') != 'client_gone']
        per: Dict[str, Optional[float]] = {}
        if slo is not None and slo.availability is not None:
            bad = len([r for r in sel
                       if r.get('outcome') in BAD_OUTCOMES])
            per['availability'] = burn_rate(
                bad, len(sel), 1.0 - slo.availability)
        if slo is not None and slo.ttft_p99_ms is not None:
            lat = [r['ttft_s'] for r in sel
                   if r.get('ttft_s') is not None]
            viol = len([t for t in lat
                        if t * 1000.0 > slo.ttft_p99_ms])
            per['ttft_p99_ms'] = burn_rate(viol, len(lat), 0.01)
        out[f'{window:g}'] = per
    return out


def verdict_from_burns(burns: Dict[str, Dict[str, Optional[float]]],
                       threshold: Optional[float] = None
                       ) -> Tuple[str, List[str]]:
    """('ok'|'breach'|'no_data', [breached objective names]).

    An objective breaches when EVERY window that has data for it burns
    at or above the threshold (the multi-window AND: fast burn alone
    flaps, slow burn alone pages a day late)."""
    threshold = burn_threshold() if threshold is None else threshold
    objectives: Dict[str, List[float]] = {}
    for per in burns.values():
        for name, burn in per.items():
            if burn is not None:
                objectives.setdefault(name, []).append(burn)
    if not objectives:
        return 'no_data', []
    breached = sorted(
        name for name, values in objectives.items()
        if values and all(b >= threshold for b in values))
    return ('breach' if breached else 'ok'), breached


# ---- replica scraping -------------------------------------------------------


def scrape_replica_metrics(endpoint: str,
                           timeout: Optional[float] = None
                           ) -> Dict[str, List[Sample]]:
    """GET http://<endpoint>/metrics and parse it. Raises on transport
    errors — the caller decides whether a dead scrape is a verdict."""
    if timeout is None:
        timeout = float(os.environ.get(ENV_SCRAPE_TIMEOUT, '5'))
    with urllib.request.urlopen(f'http://{endpoint}/metrics',
                                timeout=timeout) as resp:
        return parse_prometheus_text(
            resp.read().decode('utf-8', errors='replace'))


def fetch_replica_anatomy(endpoint: str,
                          timeout: Optional[float] = None,
                          limit: int = 256
                          ) -> List[Dict[str, Any]]:
    """GET http://<endpoint>/anatomy — the replica-side per-request
    phase records (infer/anatomy.py ring) the exemplar join matches
    against LB request ids. Raises on transport errors; callers treat
    a dead fetch as 'no anatomy this tick', not a verdict (a replica
    that can't narrate its latency is still serving)."""
    if timeout is None:
        timeout = float(os.environ.get(ENV_SCRAPE_TIMEOUT, '5'))
    with urllib.request.urlopen(
            f'http://{endpoint}/anatomy?limit={int(limit)}',
            timeout=timeout) as resp:
        rows = json.loads(resp.read().decode('utf-8',
                                             errors='replace'))
    return rows if isinstance(rows, list) else []


def replica_digest(samples: Dict[str, List[Sample]]
                   ) -> Dict[str, Any]:
    """Per-replica latency digest from one parsed scrape: TTFT/TPOT/
    e2e percentiles (ms), queue depth, request/error totals, generated
    tokens (cumulative — the monitor turns them into a rate)."""
    digest: Dict[str, Any] = {}

    def pct(name: str, q: float) -> Optional[float]:
        hist = histogram_buckets(samples, name)
        if hist is None:
            return None
        value = quantile_from_buckets(hist['buckets'], q)
        return None if value is None else value * 1000.0

    digest['ttft_p50_ms'] = pct('xsky_serve_ttft_seconds', 0.50)
    digest['ttft_p99_ms'] = pct('xsky_serve_ttft_seconds', 0.99)
    digest['tpot_p50_ms'] = pct('xsky_serve_tpot_seconds', 0.50)
    digest['e2e_p50_ms'] = pct('xsky_serve_e2e_latency_seconds', 0.50)
    digest['e2e_p99_ms'] = pct('xsky_serve_e2e_latency_seconds', 0.99)
    queue = samples.get('xsky_serve_queue_depth')
    digest['queue_depth'] = queue[0][1] if queue else None
    requests = samples.get('xsky_serve_requests_total', [])
    digest['requests_total'] = int(sum(v for _, v in requests))
    digest['errors_total'] = int(sum(
        v for labels, v in requests
        if labels.get('outcome') not in ('ok', 'cancelled')))
    tokens = samples.get('xsky_serve_generated_tokens_total')
    digest['generated_tokens'] = int(tokens[0][1]) if tokens else None
    tpot = histogram_buckets(samples, 'xsky_serve_tpot_seconds')
    digest['tpot_buckets'] = tpot['buckets'] if tpot else None
    return digest


# ---- monitor ----------------------------------------------------------------


class SLOMonitor:
    """Rides the serve controller tick: every scrape interval it pulls
    each ready replica's /metrics, folds in the LB's request records,
    computes multi-window burn rates against the service's SLO, writes
    the lot into the global `serve_slo` table, and journals
    ``serve.slo_breach`` / ``serve.slo_recovered`` on verdict
    transitions (trace-linked via the surrounding span)."""

    def __init__(self, service_name: str, slo,
                 record_source: Optional[
                     Callable[[], List[Dict[str, Any]]]] = None,
                 inflight_source: Optional[
                     Callable[[], Dict[str, int]]] = None) -> None:
        self.service_name = service_name
        self.slo = slo
        self._record_source = record_source or (lambda: [])
        self._inflight_source = inflight_source or (lambda: {})
        self._last_eval = 0.0
        self._breached: Optional[bool] = None
        # Cumulative-scrape memory for windowed deltas: per replica id,
        # bounded deques of (ts, tpot buckets) + (ts, generated tokens).
        self._tpot_prev: Dict[int, collections.deque] = {}
        self._tokens_prev: Dict[int, Tuple[float, int]] = {}
        # Request ids already persisted as exemplars: a slow request
        # stays inside the burn window for an hour — it must not be
        # re-written every scrape tick.
        self._exemplar_seen: collections.deque = collections.deque(
            maxlen=512)

    def update_slo(self, slo) -> None:
        self.slo = slo

    @property
    def interval_s(self) -> float:
        try:
            return float(os.environ.get(ENV_SCRAPE_INTERVAL, '15'))
        except ValueError:
            return 15.0

    def maybe_tick(self, replicas: List[Dict[str, Any]],
                   now: Optional[float] = None
                   ) -> Optional[Dict[str, Any]]:
        """Run one evaluation if the scrape interval elapsed. Never
        raises — SLO observation must not take the controller's scale
        loop down with it."""
        now = time.time() if now is None else now
        if now - self._last_eval < self.interval_s:
            return None
        self._last_eval = now
        try:
            return self._evaluate(replicas, now)
        except Exception as e:  # pylint: disable=broad-except
            logger.warning(f'SLO tick failed: {e}')
            return None

    # -- one evaluation ------------------------------------------------------

    def _evaluate(self, replicas: List[Dict[str, Any]],
                  now: float) -> Dict[str, Any]:
        from skypilot_tpu import state as global_state
        from skypilot_tpu.serve import state as serve_state
        from skypilot_tpu.utils import tracing
        # The span covers the scrape fan-out AND the record write, so
        # a slow replica scrape is attributable in `xsky trace` and
        # the journalled breach cross-links to this trace.
        with tracing.span('serve.slo_tick', service=self.service_name):
            windows = burn_windows()
            rows: List[Dict[str, Any]] = []
            inflight = self._inflight_source() or {}
            tpot_deltas: List[Buckets] = []
            # request_id → replica anatomy record, filled by the
            # scrape fan-out (dict.setdefault is atomic; same shared-
            # accumulator posture as tpot_deltas).
            anatomies: Dict[str, Dict[str, Any]] = {}
            ready = [
                r for r in replicas
                if r.get('endpoint') and
                r.get('status') == serve_state.ReplicaStatus.READY]
            # Scrape-snapshot caches are keyed by replica id; replica
            # churn (spot preemption mints fresh ids forever) must not
            # leak an hour of bucket history per dead id.
            live_ids = {r['replica_id'] for r in ready}
            for cache in (self._tpot_prev, self._tokens_prev):
                for rid in list(cache):
                    if rid not in live_ids:
                        del cache[rid]
            if ready:
                # Parallel scrape fan-out: N hung replicas must cost
                # ONE scrape timeout of controller tick, not N (the
                # scale loop rides this thread). _scrape_one never
                # raises (a dead scrape is a verdict, not an error).
                from skypilot_tpu.utils import parallelism
                results = parallelism.run_in_parallel(
                    lambda r: self._scrape_one(r, now, windows,
                                               inflight, tpot_deltas,
                                               anatomies),
                    ready, phase='slo_scrape',
                    what='replica SLO scrape')
                rows.extend(r for r in results if r is not None)
            tpot_delta = merge_buckets(tpot_deltas)
            service_row = self._service_row(rows, tpot_delta, now,
                                            windows, inflight)
            rows.append(service_row)
            global_state.record_serve_slo(self.service_name, rows,
                                          ts=now)
            exemplars = self._build_exemplars(anatomies, now, windows)
            if exemplars:
                global_state.record_serve_slo_exemplars(
                    self.service_name, exemplars, ts=now)
            self._journal_transition(service_row, global_state)
            return service_row

    def _scrape_one(self, replica: Dict[str, Any], now: float,
                    windows: List[float],
                    inflight: Dict[str, int],
                    tpot_deltas: List[Buckets],
                    anatomies: Dict[str, Dict[str, Any]]
                    ) -> Optional[Dict[str, Any]]:
        replica_id = replica['replica_id']
        endpoint = replica['endpoint']
        from skypilot_tpu.utils import tracing
        try:
            with tracing.span('serve.slo_scrape',
                              service=self.service_name,
                              replica=replica_id):
                samples = scrape_replica_metrics(endpoint)
                # Anatomy fetch failures downgrade to 'no waterfall
                # this tick', never to scrape_failed — the metrics
                # scrape above is the replica's health verdict.
                try:
                    for rec in fetch_replica_anatomy(endpoint):
                        rid = rec.get('request_id')
                        if rid:
                            rec['replica_id'] = replica_id
                            anatomies.setdefault(rid, rec)
                except Exception as e:  # pylint: disable=broad-except
                    logger.debug(f'replica {replica_id} anatomy '
                                 f'fetch failed: {e}')
        except Exception as e:  # pylint: disable=broad-except
            logger.debug(f'replica {replica_id} scrape failed: {e}')
            return {'kind': 'replica', 'replica_id': replica_id,
                    'endpoint': endpoint, 'verdict': 'scrape_failed'}
        digest = replica_digest(samples)
        tpot_buckets = digest.pop('tpot_buckets', None)
        if tpot_buckets:
            window_start = self._tpot_window_snapshot(
                replica_id, now, max(windows), tpot_buckets)
            tpot_deltas.append(
                delta_buckets(window_start, tpot_buckets))
        tokens = digest.pop('generated_tokens', None)
        digest['tokens_per_sec'] = self._tokens_rate(
            replica_id, now, tokens)
        digest['kind'] = 'replica'
        digest['replica_id'] = replica_id
        digest['endpoint'] = endpoint
        digest['inflight'] = inflight.get(endpoint)
        digest['verdict'] = 'ok'
        return digest

    def _tpot_window_snapshot(self, replica_id: int, now: float,
                              max_window: float,
                              buckets: Buckets) -> Optional[Buckets]:
        """Record this scrape's cumulative TPOT buckets and return the
        snapshot closest to (now - max_window) so the caller can delta
        against it. Deque is time-bounded by the longest window."""
        history = self._tpot_prev.setdefault(
            replica_id, collections.deque())
        history.append((now, [tuple(b) for b in buckets]))
        while history and history[0][0] < now - max_window - 1.0:
            history.popleft()
        return history[0][1] if len(history) > 1 else None

    def _tokens_rate(self, replica_id: int, now: float,
                     tokens: Optional[int]) -> Optional[float]:
        if tokens is None:
            return None
        prev = self._tokens_prev.get(replica_id)
        self._tokens_prev[replica_id] = (now, tokens)
        if prev is None or now <= prev[0] or tokens < prev[1]:
            return None
        return (tokens - prev[1]) / (now - prev[0])

    def _service_row(self, replica_rows: List[Dict[str, Any]],
                     tpot_delta: Buckets, now: float,
                     windows: List[float],
                     inflight: Dict[str, int]) -> Dict[str, Any]:
        records = [r for r in self._record_source()
                   if (r.get('ts') or 0) >= now - max(windows)]
        burns = burns_from_records(records, self.slo, now=now,
                                   windows=windows)
        self._fold_tpot_burn(burns, tpot_delta)
        verdict, breached = ('no_slo', []) if self.slo is None \
            else verdict_from_burns(burns)
        # Same population the availability burn sees (client_gone
        # spends no budget): requests/errors here must reproduce the
        # burn's observed availability, or `xsky slo` prints an
        # objective 'met' next to a breaching burn.
        short = [r for r in records
                 if (r.get('ts') or 0) >= now - windows[0] and
                 r.get('outcome') != 'client_gone']
        lat = sorted(r['ttft_s'] for r in short
                     if r.get('ttft_s') is not None)
        e2e = sorted(r['e2e_s'] for r in short
                     if r.get('e2e_s') is not None)
        bad = len([r for r in short
                   if r.get('outcome') in BAD_OUTCOMES])
        tokens = [r['tokens_per_sec'] for r in replica_rows
                  if r.get('tokens_per_sec') is not None]
        queue = [r['queue_depth'] for r in replica_rows
                 if r.get('queue_depth') is not None]
        tpot_p50 = quantile_from_buckets(tpot_delta, 0.50) \
            if tpot_delta else None
        return {
            'kind': 'service',
            'replica_id': None,
            'endpoint': None,
            'ttft_p50_ms': pctl_ms(lat, 0.50),
            'ttft_p99_ms': pctl_ms(lat, 0.99),
            'tpot_p50_ms': (tpot_p50 * 1000.0
                            if tpot_p50 is not None else None),
            'e2e_p50_ms': pctl_ms(e2e, 0.50),
            'e2e_p99_ms': pctl_ms(e2e, 0.99),
            'queue_depth': sum(queue) if queue else None,
            'tokens_per_sec': sum(tokens) if tokens else None,
            'requests_total': len(short),
            'errors_total': bad,
            'inflight': sum(inflight.values()) if inflight else None,
            'burns': burns,
            'verdict': verdict,
            'detail': {'breached_objectives': breached,
                       'windows': [f'{w:g}' for w in windows],
                       'threshold': burn_threshold(),
                       'slo': self.slo.to_config()
                       if self.slo is not None else None},
        }

    def _fold_tpot_burn(
            self, burns: Dict[str, Dict[str, Optional[float]]],
            tpot_delta: Buckets) -> None:
        """TPOT burn from the merged windowed replica histograms: the
        scrape cadence bounds the delta's resolution, so every window
        shares the max-window delta (documented approximation — the
        LB cannot see tokens, only replicas can)."""
        if self.slo is None or self.slo.tpot_p50_ms is None:
            return
        if not tpot_delta:
            for per in burns.values():
                per['tpot_p50_ms'] = None
            return
        frac = frac_over(tpot_delta, self.slo.tpot_p50_ms / 1000.0)
        total = tpot_delta[-1][1]
        burn = None
        if frac is not None and total > 0:
            burn = burn_rate(frac * total, total, 0.5)
        for per in burns.values():
            per['tpot_p50_ms'] = burn

    def _build_exemplars(self, anatomies: Dict[str, Dict[str, Any]],
                         now: float, windows: List[float]
                         ) -> List[Dict[str, Any]]:
        """Top-K slowest finished requests of the window, each joined
        with its replica-side anatomy by the LB-minted request id into
        one cross-hop waterfall:

          lb_queue       arrival → start of the winning relay leg
          relay_connect  client e2e − lb_queue − replica-side total
                         (connect + wire transfer on that leg)
          <replica phases from infer/anatomy.py>

        so the persisted phases sum to the client-observed e2e and a
        breach exemplar answers 'queue, relay, or decode?'."""
        k = exemplar_top_k()
        if k <= 0:
            return []
        records = [r for r in self._record_source()
                   if (r.get('ts') or 0) >= now - max(windows) and
                   r.get('e2e_s') is not None and
                   r.get('request_id') is not None]
        records.sort(key=lambda r: r['e2e_s'], reverse=True)
        out: List[Dict[str, Any]] = []
        for rec in records:
            if len(out) >= k:
                break
            rid = rec['request_id']
            if rid in self._exemplar_seen:
                continue
            lb_queue = rec.get('relay_start_s')
            phases: Dict[str, float] = {}
            if lb_queue is not None:
                phases['lb_queue'] = max(0.0, lb_queue)
            detail: Dict[str, Any] = {
                'retries': rec.get('retries'),
                'status': rec.get('status'),
            }
            anatomy = anatomies.get(rid)
            if anatomy is not None:
                replica_phases = {
                    str(p): max(0.0, float(v or 0.0))
                    for p, v in (anatomy.get('phases') or {}).items()}
                phases['relay_connect'] = max(
                    0.0, rec['e2e_s'] - (lb_queue or 0.0) -
                    sum(replica_phases.values()))
                phases.update(replica_phases)
                detail['replica_id'] = anatomy.get('replica_id')
                detail['kv_headroom_at_admit'] = anatomy.get(
                    'kv_headroom_at_admit')
                detail['output_tokens'] = anatomy.get('output_tokens')
                detail['replica_outcome'] = anatomy.get('outcome')
            else:
                # Replica restarted / ring rolled over / anatomy
                # disabled: the LB half still names queue vs relay.
                detail['anatomy'] = 'missing'
            self._exemplar_seen.append(rid)
            out.append({
                'ts': rec.get('ts'),
                'request_id': rid,
                'trace_id': rec.get('trace_id'),
                'replica': (None if rec.get('replica') is None
                            else str(rec['replica'])),
                'path': rec.get('path'),
                'outcome': rec.get('outcome'),
                'e2e_s': rec.get('e2e_s'),
                'ttft_s': rec.get('ttft_s'),
                'phases': phases,
                'detail': detail,
            })
        return out

    def _journal_transition(self, service_row: Dict[str, Any],
                            global_state) -> None:
        verdict = service_row.get('verdict')
        if verdict not in ('ok', 'breach'):
            # no_slo / no_data: the incident can no longer be
            # confirmed either way. Close an open breach (the journal
            # must not show one forever after the SLO is removed or
            # traffic stops) and reset, so a later re-breach journals
            # a fresh event instead of riding the stale True.
            if self._breached is True:
                global_state.record_recovery_event(
                    'serve.slo_recovered',
                    scope=f'service/{self.service_name}',
                    cause=f'evaluation became {verdict}')
            self._breached = None
            return
        breached_now = verdict == 'breach'
        was = self._breached
        self._breached = breached_now
        if breached_now and was is not True:
            detail = dict(service_row.get('detail') or {})
            detail['burns'] = json_safe_burns(
                service_row.get('burns') or {})
            # Breach → exemplar flow: the newest persisted slow-request
            # waterfalls ARE the incident's worked examples. Attach
            # their trace ids so `xsky serve trace <svc> --request ID`
            # resolves straight from the journal row.
            try:
                detail['exemplar_trace_ids'] = [
                    e['trace_id'] for e in
                    global_state.get_serve_slo_exemplars(
                        service=self.service_name, limit=5)
                    if e.get('trace_id')]
            except Exception:  # pylint: disable=broad-except
                pass
            global_state.record_recovery_event(
                'serve.slo_breach',
                scope=f'service/{self.service_name}',
                cause=('objectives over budget: ' + ', '.join(
                    detail.get('breached_objectives') or [])),
                detail=detail)
        elif not breached_now and was is True:
            global_state.record_recovery_event(
                'serve.slo_recovered',
                scope=f'service/{self.service_name}',
                cause='burn rate back under threshold')


def json_safe_burns(burns: Optional[
        Dict[str, Dict[str, Optional[float]]]]
        ) -> Dict[str, Dict[str, Any]]:
    """inf is not JSON (json.dumps emits `Infinity`, which stdlib
    accepts but nothing else does); stringify zero-budget burns."""
    out: Dict[str, Dict[str, Any]] = {}
    for window, per in (burns or {}).items():
        out[window] = {
            k: ('inf' if v == float('inf') else v)
            for k, v in per.items()
        }
    return out
