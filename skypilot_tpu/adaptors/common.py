"""LazyImport: cloud SDKs as optional, import-on-first-use dependencies.

Twin of sky/adaptors/common.py (80 LoC). No cloud SDK is a hard install
requirement; importing an adaptor module is free, and the underlying SDK
is imported only when an attribute is first touched — with a clear
install hint if it is missing.
"""
from __future__ import annotations

import importlib
import threading
from typing import Any, Optional, Tuple


class LazyImport:
    """Proxy that imports `module_name` on first attribute access."""

    def __init__(self, module_name: str,
                 import_error_message: Optional[str] = None) -> None:
        self._module_name = module_name
        self._module: Any = None
        self._error = import_error_message
        self._lock = threading.RLock()

    def load_module(self) -> Any:
        with self._lock:
            if self._module is None:
                try:
                    self._module = importlib.import_module(
                        self._module_name)
                except ImportError as e:
                    msg = self._error or (
                        f'Failed to import {self._module_name!r}: {e}')
                    raise ImportError(msg) from e
        return self._module

    def installed(self) -> bool:
        try:
            self.load_module()
            return True
        except ImportError:
            return False

    def __getattr__(self, name: str) -> Any:
        if name.startswith('_'):
            raise AttributeError(name)
        return getattr(self.load_module(), name)


def load_lazy_modules(modules: Tuple[LazyImport, ...]):
    """Decorator: touch all lazy modules before running the function."""

    def decorator(fn):
        def wrapper(*args, **kwargs):
            for m in modules:
                m.load_module()
            return fn(*args, **kwargs)

        wrapper.__name__ = getattr(fn, '__name__', 'wrapped')
        return wrapper

    return decorator
