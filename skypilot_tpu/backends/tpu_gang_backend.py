"""TpuGangBackend: the cluster-lifecycle + job-execution backend.

Twin of CloudVmRayBackend (sky/backends/cloud_vm_ray_backend.py:2715) with
the Ray substrate removed: jobs are queued in the head agent's sqlite and
gang-launched one-process-per-TPU-host with `jax.distributed`/libtpu env
(see skypilot_tpu/agent/gang.py). The handle is pickled into the state DB
(twin of CloudVmRayResourceHandle :2189) — but hosts are first-class here,
so there is no `num_ips_per_node` special-casing.
"""
from __future__ import annotations

import base64
import getpass
import json
import os
import shlex
import sys
import tempfile
import time
import typing
from typing import Any, Dict, List, Optional, Tuple

from skypilot_tpu import exceptions
from skypilot_tpu import provision as provision_lib
from skypilot_tpu import sky_logging
from skypilot_tpu import state
from skypilot_tpu.agent import job_lib
from skypilot_tpu.backends import backend as backend_lib
from skypilot_tpu.backends import failover
from skypilot_tpu.backends import wheel_utils
from skypilot_tpu.provision import common as provision_common
from skypilot_tpu.utils import command_runner as runner_lib
from skypilot_tpu.utils import parallelism
from skypilot_tpu.utils import registry
from skypilot_tpu.utils import tracing

if typing.TYPE_CHECKING:
    from skypilot_tpu import resources as resources_lib
    from skypilot_tpu import task as task_lib

logger = sky_logging.init_logger(__name__)

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


class ClusterHandle(backend_lib.ResourceHandle):
    """Everything needed to reconnect to a cluster."""

    def __init__(self, cluster_name: str,
                 launched_resources: 'resources_lib.Resources',
                 num_nodes: int,
                 cluster_info: provision_common.ClusterInfo) -> None:
        self.cluster_name = cluster_name
        self.launched_resources = launched_resources
        self.num_nodes = num_nodes
        self.cluster_info = cluster_info

    def get_cluster_name(self) -> str:
        return self.cluster_name

    @property
    def provider_name(self) -> str:
        return self.cluster_info.provider_name

    @property
    def is_local_provider(self) -> bool:
        return self.provider_name in ('fake', 'local')

    @property
    def head_runtime_root(self) -> str:
        """The cluster runtime dir on the head host."""
        if self.is_local_provider:
            head = self.cluster_info.get_head_instance()
            return os.path.join(head.tags['host_root'], '.xsky')
        return '~/.xsky'

    @property
    def head_ip(self) -> Optional[str]:
        head = self.cluster_info.get_head_instance()
        return head.get_feasible_ip() if head else None

    def get_command_runners(self) -> List[runner_lib.CommandRunner]:
        key = self.cluster_info.provider_config.get(
            'ssh_private_key', '~/.ssh/xsky-key')
        return runner_lib.runners_from_cluster_info(self.cluster_info, key)

    def head_runner(self) -> runner_lib.CommandRunner:
        return self.get_command_runners()[0]

    def __repr__(self) -> str:
        return (f'ClusterHandle({self.cluster_name}, '
                f'{self.launched_resources}, hosts='
                f'{self.cluster_info.num_instances})')


@registry.BACKEND_REGISTRY.register(name='tpu_gang', default=True)
class TpuGangBackend(backend_lib.Backend[ClusterHandle]):

    NAME = 'tpu_gang'

    # ---- provision ----

    def provision(self, task: 'task_lib.Task',
                  to_provision: Optional['resources_lib.Resources'],
                  dryrun: bool = False, stream_logs: bool = True,
                  cluster_name: Optional[str] = None,
                  retry_until_up: bool = False,
                  blocked_resources: Optional[List[
                      'resources_lib.Resources']] = None
                  ) -> Optional[ClusterHandle]:
        assert cluster_name is not None
        if dryrun:
            logger.info(f'Dryrun: would provision {cluster_name} with '
                        f'{to_provision or task.resources}')
            return None
        with tracing.span('backend.provision', cluster=cluster_name,
                          nodes=task.num_nodes):
            return self._provision(task, to_provision, cluster_name,
                                   retry_until_up, blocked_resources)

    def _provision(self, task: 'task_lib.Task',
                   to_provision: Optional['resources_lib.Resources'],
                   cluster_name: str, retry_until_up: bool,
                   blocked_resources: Optional[List[
                       'resources_lib.Resources']]
                   ) -> Optional[ClusterHandle]:
        if to_provision is not None:
            task = _pin_task(task, to_provision)
        from skypilot_tpu.workspaces import context as ws_context
        workspace = ws_context.get_active()
        had_record = state.get_cluster_from_name(cluster_name) is not None

        def record_attempt(resources: 'resources_lib.Resources',
                           config: provision_common.ProvisionConfig
                           ) -> None:
            # Provisional handle per attempt: if this process dies
            # mid-provision (job cancel SIGTERM, OOM), teardown can
            # still terminate-by-tag in the attempted region.
            provisional = ClusterHandle(
                cluster_name, resources, task.num_nodes,
                provision_common.ClusterInfo(
                    instances={}, head_instance_id=None,
                    provider_name=resources.cloud.provisioner_module,
                    provider_config=dict(config.provider_config)))
            state.add_or_update_cluster(
                cluster_name, provisional,
                requested_resources=task.resources, ready=False,
                workspace=workspace)

        provisioner = failover.RetryingProvisioner(
            task, cluster_name, task.num_nodes,
            attempt_observer=record_attempt)
        if blocked_resources:
            # Pre-seeded blocklist (jobs recovery: eager_next_region
            # skips the preempted region without a failed attempt).
            provisioner.blocked.extend(blocked_resources)
        try:
            result = failover.provision_with_retry_until_up(
                provisioner, retry_until_up=retry_until_up)
        except Exception:
            # The last attempt may have created instances before dying
            # (e.g. wait_instances timeout): terminate-by-tag via the
            # provisional handle before dropping the record, so nothing
            # keeps billing with no record pointing at it. Records that
            # predate this call (restarting a stopped cluster) are kept.
            if not had_record:
                leftover = state.get_cluster_from_name(cluster_name)
                if leftover is not None and \
                        leftover['handle'] is not None:
                    try:
                        self.teardown(leftover['handle'], terminate=True,
                                      purge=True)
                    except Exception as cleanup_err:  # pylint: disable=broad-except
                        logger.warning(
                            f'Cleanup after failed provision of '
                            f'{cluster_name!r} failed: {cleanup_err}')
                        state.remove_cluster(cluster_name,
                                             terminate=True)
                else:
                    state.remove_cluster(cluster_name, terminate=True)
            raise
        handle = ClusterHandle(cluster_name, result.resources,
                               result.num_nodes, result.cluster_info)
        state.add_or_update_cluster(cluster_name, handle,
                                    requested_resources=task.resources,
                                    ready=False, workspace=workspace)
        self._setup_runtime(handle)
        state.add_or_update_cluster(cluster_name, handle, ready=True,
                                    is_launch=False, workspace=workspace)
        return handle

    @staticmethod
    def _bootstrap_local_enabled() -> bool:
        """Local/fake hosts normally run straight off the repo checkout
        (fast tests); setting XSKY_BOOTSTRAP_LOCAL=1 makes them go through
        the full wheel-install path like real hosts do."""
        return os.environ.get('XSKY_BOOTSTRAP_LOCAL', '0') == '1'

    def _bootstraps(self, handle: ClusterHandle) -> bool:
        return (not handle.is_local_provider or
                self._bootstrap_local_enabled())

    def _host_runtime_root(self, handle: ClusterHandle,
                           runner: runner_lib.CommandRunner) -> str:
        del handle  # the runner class encodes the provider layout
        return runner.remote_runtime_root()

    def _python_for(self, handle: ClusterHandle,
                    runner: runner_lib.CommandRunner) -> str:
        """Python invocation for agent commands on one host.

        Resolved remotely at run time: clusters launched before the
        bootstrap era have no venv yet, so fall back to the host python
        rather than failing every status/logs/cancel against them.
        """
        if not self._bootstraps(handle):
            return 'python'  # repo on PYTHONPATH (see _agent_env)
        root = self._host_runtime_root(handle, runner)
        venv_py = f'{root}/venv/bin/python'
        return f'$([ -x {venv_py} ] && echo {venv_py} || echo python)'

    def _head_python(self, handle: ClusterHandle) -> str:
        return self._python_for(handle, handle.head_runner())

    def _agent_env(self, handle: ClusterHandle) -> Dict[str, str]:
        env = {'XSKY_CLUSTER_ROOT': handle.head_runtime_root}
        if handle.is_local_provider and not self._bootstraps(handle):
            env['PYTHONPATH'] = _REPO_ROOT
        return env

    def _setup_runtime(self, handle: ClusterHandle) -> None:
        """Install the runtime on every host; start the head agent daemon.

        (Twin of post_provision_runtime_setup,
        sky/provision/provisioner.py:671 — minus Ray cluster start. The
        wheel ship+install matches internal_file_mounts + runtime setup,
        sky/provision/instance_setup.py:540.)

        Every per-host step fans out through
        ``parallelism.run_in_parallel`` — at pod scale (64 hosts) the
        sequential loops made bring-up latency O(num_hosts).
        """
        runners = handle.get_command_runners()
        for cmd in handle.cluster_info.mount_commands:
            # Volume mounts (idempotent; provider-built). Every host
            # mounts before anything else lands on the cluster.
            def _mount(pair, cmd=cmd):
                rank, runner = pair
                rc, _, stderr = runner.run(cmd, require_outputs=True)
                if rc != 0:
                    raise exceptions.ClusterSetUpError(
                        f'Volume mount failed on host {rank}: '
                        f'{stderr.strip()} (cmd: {cmd})')

            with tracing.span('backend.mount',
                              cluster=handle.cluster_name):
                parallelism.run_in_parallel(
                    _mount, list(enumerate(runners)),
                    phase='mount', what='volume mount')
        if self._bootstraps(handle):
            wheel_path, content_hash = wheel_utils.build_wheel()

            def _bootstrap(pair):
                rank, runner = pair
                try:
                    self._bootstrap_host(handle, runner, wheel_path,
                                         content_hash)
                except exceptions.ClusterSetUpError as e:
                    raise exceptions.ClusterSetUpError(
                        f'Runtime bootstrap failed on host {rank}: '
                        f'{e}') from e

            with tracing.span('backend.bootstrap',
                              cluster=handle.cluster_name):
                parallelism.run_in_parallel(
                    _bootstrap, list(enumerate(runners)),
                    phase='bootstrap', what='runtime bootstrap')
        head = runners[0]
        root = handle.head_runtime_root
        # cluster_name rides along for the agent's self-teardown path
        # (agent/self_teardown.py); ClusterInfo.from_json ignores it.
        info_json = json.dumps({**handle.cluster_info.to_json(),
                                'cluster_name': handle.cluster_name})
        payload = base64.b64encode(info_json.encode()).decode()
        rc, _, stderr = head.run(
            f'mkdir -p {root}/logs && echo {payload} | base64 -d > '
            f'{root}/cluster_info.json',
            env=self._agent_env(handle), require_outputs=True)
        if rc != 0:
            raise exceptions.ClusterSetUpError(
                f'Failed to initialize cluster runtime: {stderr}')
        image = self._docker_image(handle)
        if image is not None:
            # Per-task container runtime (image_id: docker:…): install
            # docker, pull, start the keep-alive container on every
            # host. Task setup/run then execute inside it (docker_utils
            # module docstring has the layout contract).
            from skypilot_tpu.utils import docker_utils
            init = docker_utils.initialize_command(image)

            def _docker_init(pair):
                rank, runner = pair
                rc, _, stderr = runner.run(init, require_outputs=True)
                if rc != 0:
                    raise exceptions.ClusterSetUpError(
                        f'Docker runtime init failed on host {rank}: '
                        f'{stderr.strip()[:500]}')

            with tracing.span('backend.docker_init',
                              cluster=handle.cluster_name):
                parallelism.run_in_parallel(
                    _docker_init, list(enumerate(runners)),
                    phase='docker_init', what='docker runtime init')
        if not handle.is_local_provider:
            head.run_async(
                f'{self._head_python(handle)} -m skypilot_tpu.agent.daemon',
                env=self._agent_env(handle),
                log_path=None)

    @staticmethod
    def _docker_image(handle: ClusterHandle) -> Optional[str]:
        """The task container image, or None for host execution.

        Kubernetes/docker providers already ARE containers — the pod
        image handles `docker:` there, not a nested runtime.
        """
        from skypilot_tpu.utils import docker_utils
        if handle.provider_name in ('kubernetes', 'docker'):
            return None
        if handle.is_local_provider:
            # Fake/local hosts are plain processes — no docker daemon
            # to initialize; command construction is unit-tested.
            return None
        image_id = handle.launched_resources.image_id
        if docker_utils.is_docker_image(image_id):
            return docker_utils.image_of(image_id)
        return None

    def _bootstrap_host(self, handle: ClusterHandle,
                        runner: runner_lib.CommandRunner,
                        wheel_path, content_hash: str) -> None:
        """Ship the wheel and install it into {root}/venv on one host.

        Fully offline: venv + `pip install --no-index` of a dependency-free
        wheel; third-party deps (jax, yaml, ...) come from the host image
        via --system-site-packages plus a .pth pointing at the *invoking*
        python's site dir (needed when python3 is itself a venv, as on dev
        images — --system-site-packages alone would skip its packages).
        Idempotent: skips the install when {root}/wheel_hash matches.
        """
        root = self._host_runtime_root(handle, runner)
        wheel_name = os.path.basename(str(wheel_path))
        wheel_dst = f'{root}/wheels/{content_hash}'
        rc, _, err = runner.run(f'mkdir -p {wheel_dst}',
                                require_outputs=True)
        if rc != 0:
            raise exceptions.ClusterSetUpError(
                f'mkdir {wheel_dst} failed: {err}')
        if handle.is_local_provider:
            rsync_target = f'.xsky/wheels/{content_hash}/{wheel_name}'
        elif handle.provider_name in ('kubernetes', 'docker'):
            rsync_target = f'{wheel_dst}/{wheel_name}'
        else:
            # SSH: path relative to the remote home.
            rsync_target = f'.xsky/wheels/{content_hash}/{wheel_name}'
        runner.rsync(str(wheel_path), rsync_target, up=True)
        venv_py = f'{root}/venv/bin/python'
        script = (
            f'set -e; '
            f'if [ ! -x {venv_py} ]; then '
            f'python3 -m venv --system-site-packages {root}/venv; fi; '
            # .pth written unconditionally: a failure after venv creation
            # must be repairable by re-running this (idempotent) script.
            f'SITE=$({venv_py} -c "import sysconfig; '
            f'print(sysconfig.get_paths()[\'purelib\'])"); '
            f'python3 -c "import site; '
            f'print(chr(10).join(site.getsitepackages()))" '
            f'> "$SITE/_xsky_parent.pth"; '
            f'if [ "$(cat {root}/wheel_hash 2>/dev/null)" '
            f'!= "{content_hash}" ]; then '
            f'{venv_py} -m pip install --quiet --no-deps --no-index '
            f'--force-reinstall {wheel_dst}/{wheel_name}; '
            f'echo {content_hash} > {root}/wheel_hash; fi; '
            f'{venv_py} -c "import skypilot_tpu"')
        rc, out, err = runner.run(script, require_outputs=True)
        if rc != 0:
            raise exceptions.ClusterSetUpError(
                f'wheel install failed (rc={rc}): {err or out}')

    # ---- sync ----

    def run_module_on_head(self, handle: ClusterHandle, module: str,
                           *args: str,
                           extra_env: Optional[Dict[str, str]] = None
                           ) -> Tuple[int, str, str]:
        """Run ``python -m <module> <args...>`` on the cluster head.

        Public entry for controllers/recovery that need to execute
        framework code on a cluster (e.g. the remote jobs-controller
        relay) without reaching into backend privates. Uses the
        bootstrapped venv python when the host was wheel-installed.
        """
        cmd = ' '.join([self._head_python(handle), '-m', module] +
                       [shlex.quote(a) for a in args])
        env = self._agent_env(handle)
        if extra_env:
            env.update(extra_env)
        return handle.head_runner().run(cmd, env=env,
                                        require_outputs=True)

    def sync_workdir(self, handle: ClusterHandle, workdir: str) -> None:
        runners = handle.get_command_runners()
        src = os.path.join(os.path.expanduser(workdir), '')

        def _sync(pair):
            _, runner = pair
            runner.rsync(src, 'sky_workdir/', up=True, excludes=['.git'])

        with tracing.span('backend.sync_workdir',
                          cluster=handle.cluster_name):
            parallelism.run_in_parallel(
                _sync, list(enumerate(runners)),
                phase='sync_workdir', what=f'workdir sync ({workdir})')

    def sync_file_mounts(self, handle: ClusterHandle,
                         all_file_mounts: Optional[Dict[str, str]],
                         storage_mounts: Optional[Dict[str, Any]]) -> None:
        runners = handle.get_command_runners()
        for target, source in (all_file_mounts or {}).items():
            source = os.path.expanduser(source)
            if not os.path.exists(source):
                raise FileNotFoundError(
                    f'file_mount source {source} not found')

            def _push(pair, source=source, target=target):
                _, runner = pair
                if os.path.isdir(source):
                    runner.rsync(os.path.join(source, ''),
                                 target.rstrip('/') + '/', up=True)
                else:
                    runner.rsync(source, target, up=True)

            with tracing.span('backend.file_mounts',
                              cluster=handle.cluster_name,
                              target=target):
                parallelism.run_in_parallel(
                    _push, list(enumerate(runners)),
                    phase='file_mounts', what=f'file mount ({target})')
        if storage_mounts:
            from skypilot_tpu.data import storage_mounting
            storage_mounting.mount_storage_on_cluster(
                handle, storage_mounts)

    # ---- setup / execute ----

    @staticmethod
    def _job_cwd(handle: ClusterHandle,
                 task: 'task_lib.Task') -> Optional[str]:
        """Working dir for setup AND run (must match: setup artifacts like
        venvs must be visible to the run command)."""
        if handle.is_local_provider:
            return None  # local hosts run inside their host_root already
        return 'sky_workdir' if task.workdir else None

    def setup(self, handle: ClusterHandle, task: 'task_lib.Task',
              detach_setup: bool = False) -> None:
        if task.setup is None:
            return
        runners = handle.get_command_runners()
        env = dict(task.envs_and_secrets)
        cwd = self._job_cwd(handle, task)
        setup_cmd = task.setup
        image = self._docker_image(handle)
        if image is not None:
            from skypilot_tpu.utils import docker_utils
            setup_cmd = docker_utils.exec_wrap(setup_cmd, env, cwd=cwd)
            cwd = None   # cd happens inside the container

        def _setup(pair):
            rank, runner = pair
            rc, out, err = runner.run(setup_cmd, env=env, cwd=cwd,
                                      require_outputs=True)
            if rc != 0:
                raise exceptions.ClusterSetUpError(
                    f'Setup failed on host {rank} (rc={rc}): '
                    f'{err or out}')

        with tracing.span('backend.setup',
                          cluster=handle.cluster_name):
            parallelism.run_in_parallel(
                _setup, list(enumerate(runners)),
                phase='setup', what='task setup')

    def _job_spec(self, handle: ClusterHandle, task: 'task_lib.Task'
                  ) -> Dict[str, Any]:
        """The agent-side job spec for one task (shared by execute and
        the elastic resubmit path)."""
        run_cmd = task.run
        if callable(run_cmd):
            # Command generators get (node_rank, node_ips); materialize
            # per-node commands into a dispatch script.
            ips = handle.cluster_info.get_feasible_ips(internal=True)
            cmds = {r: run_cmd(r, ips) for r in range(task.num_nodes)}
            run_cmd = _dispatch_script(cmds)
        from skypilot_tpu.agent import checkpointd
        from skypilot_tpu.utils import docker_utils
        # Control-plane checkpoint knobs (cadence clamps, MTTF hint,
        # journal scope, master switch) reach the workload's env; task
        # envs (the jobs controller threads its own) win. The per-rank
        # dir/peer wiring stays with the gang launcher.
        envs = dict(task.envs_and_secrets)
        for key in checkpointd.FORWARD_ENV:
            if key in os.environ:
                envs.setdefault(key, os.environ[key])
        return {
            'run': run_cmd,
            'envs': envs,
            'num_nodes': task.num_nodes,
            'cwd': self._job_cwd(handle, task),
            # Container runtime: the on-host job runner wraps setup/run
            # with `docker exec` into this container (env forwarded by
            # name so per-rank gang env arrives intact).
            'docker_container': (docker_utils.CONTAINER_NAME
                                 if self._docker_image(handle) is not None
                                 else None),
        }

    def execute(self, handle: ClusterHandle, task: 'task_lib.Task',
                detach_run: bool = False,
                dryrun: bool = False,
                stream_logs: bool = True) -> Optional[int]:
        if dryrun:
            return None
        spec = self._job_spec(handle, task)
        with tracing.span('backend.submit',
                          cluster=handle.cluster_name):
            job_id = self._submit_job(handle, task.name, spec)
        state.update_last_use(handle.cluster_name)
        if not detach_run:
            self._wait_job(handle, job_id, stream_logs=stream_logs)
        return job_id

    def resubmit_gang(self, handle: ClusterHandle, task: 'task_lib.Task',
                      excluded_ranks: Optional[List[int]] = None,
                      cancel_job_id: Optional[int] = None,
                      extra_env: Optional[Dict[str, str]] = None) -> int:
        """Elastic shrink / grow-back: cancel the running cluster job
        and resubmit the task's run over the cluster's hosts MINUS
        ``excluded_ranks`` (empty = the full gang again). No
        reprovisioning — the cluster stays up; the agent-side gang
        launcher renumbers ranks contiguously over the survivors, so
        the workload's ``jax.distributed`` world comes up at the new
        size. Returns the new cluster job id.
        """
        if callable(task.run):
            # Per-node command generators bake the original node ranks
            # into a dispatch script; renumbered survivors would run
            # the wrong commands. Callers fall back to full relaunch.
            raise exceptions.NotSupportedError(
                'elastic resubmit requires a string run command')
        spec = self._job_spec(handle, task)
        excluded = sorted(set(int(r) for r in (excluded_ranks or ())))
        if excluded:
            spec['exclude_hosts'] = excluded
        if extra_env:
            spec['envs'] = {**(spec.get('envs') or {}), **extra_env}
        with tracing.span('backend.resubmit',
                          cluster=handle.cluster_name,
                          excluded=','.join(str(r) for r in excluded)):
            if cancel_job_id is not None:
                self.cancel_jobs(handle, [cancel_job_id])
            job_id = self._submit_job(handle, task.name, spec)
        state.update_last_use(handle.cluster_name)
        return job_id

    def _submit_job(self, handle: ClusterHandle, name: Optional[str],
                    spec: Dict[str, Any]) -> int:
        head = handle.head_runner()
        env = self._agent_env(handle)
        spec_b64 = base64.b64encode(json.dumps(spec).encode()).decode()
        user = getpass.getuser()
        rc, out, err = head.run(
            f'{self._head_python(handle)} -m skypilot_tpu.agent.job_cli '
            f'add {shlex.quote(name or "-")} {user} {spec_b64}',
            env=env, require_outputs=True)
        if rc != 0:
            # A concurrent down/preemption between provision and submit:
            # name the real condition instead of a generic shell error.
            if state.get_cluster_from_name(handle.cluster_name) is None:
                raise exceptions.ClusterDoesNotExist(
                    f'Cluster {handle.cluster_name!r} was torn down '
                    'before the job could be submitted.')
            raise exceptions.CommandError(rc, 'job_cli add', err)
        job_id = int(out.strip().splitlines()[-1])
        rc, out, err = head.run(
            f'{self._head_python(handle)} -m skypilot_tpu.agent.job_cli '
            f'run-detached {job_id}',
            env=env, require_outputs=True)
        if rc != 0:
            raise exceptions.CommandError(rc, 'job_cli run-detached', err)
        return job_id

    def _watch_job(self, handle: ClusterHandle, job_id: int,
                   offset: int) -> Optional[Dict[str, Any]]:
        """One remote exec → {'status', 'offset', 'log'(bytes)} or None
        on a failed probe (teardown race / transient ssh)."""
        head = handle.head_runner()
        rc, out, _ = head.run(
            f'{self._head_python(handle)} -m skypilot_tpu.agent.job_cli '
            f'watch {job_id} {offset}',
            env=self._agent_env(handle), require_outputs=True)
        if rc != 0:
            return None
        try:
            rec = json.loads(out.strip().splitlines()[-1])
            rec['log'] = base64.b64decode(rec.get('log', ''))
            return rec
        except (ValueError, KeyError, IndexError):
            # Includes rc==0 with empty stdout (transient runner hiccup).
            return None

    def watch_job_log(self, handle: ClusterHandle, job_id: int,
                      offset: int = 0) -> Dict[str, Any]:
        """Public incremental log poll: {'status', 'offset', 'log'(str)}.

        Same single-remote-exec hot path as the launch wait loop; the
        dashboard's live tail calls this through core.watch_job_log.
        """
        rec = self._watch_job(handle, job_id, offset)
        if rec is None:
            return {'status': 'UNKNOWN', 'offset': offset, 'log': ''}
        return {'status': rec['status'], 'offset': rec['offset'],
                'log': rec['log'].decode('utf-8', errors='replace')}

    def fetch_job_log_bytes(self, handle: ClusterHandle, job_id: int,
                            max_bytes: int = 64 << 20) -> bytes:
        """Byte-exact run.log fetch via the incremental watch channel.

        `tail_logs` goes through a text-mode login-shell capture that
        rewrites newlines (\\r from progress bars → \\n) and can prepend
        profile noise; archives made from it would break the live
        tail's byte-offset carry-over. The watch channel ships base64
        chunks of the raw file, so offsets stay true.
        """
        out = bytearray()
        offset = 0
        while len(out) < max_bytes:
            rec = self._watch_job(handle, job_id, offset)
            if rec is None or not rec['log']:
                break
            out += rec['log']
            offset = rec['offset']
        return bytes(out)

    # ---- workload telemetry ----

    def get_workload_telemetry(self, handle: ClusterHandle,
                               job_id: int
                               ) -> Dict[int, Dict[str, Any]]:
        """Pull every rank's telemetry spool sample in one host
        fan-out: {rank: sample}. Ranks with no spool yet (job not
        started, pre-telemetry workload) are simply absent; a partial
        fan-out failure costs the missing ranks, not the pull.

        Each host is read by GLOB, not by its fan-out index: after an
        elastic shrink the gang renumbers ranks contiguously over the
        surviving hosts, so host i may hold any rank's spool — the
        sample's own ``rank`` field keys the result.
        """
        from skypilot_tpu.agent import telemetry
        runners = handle.get_command_runners()
        samples: Dict[int, Dict[str, Any]] = {}

        def _pull(pair):
            _, runner = pair
            spool = telemetry.spool_dir(runner.remote_runtime_root(),
                                        job_id)
            # One-line JSON per file, no trailing newline — printf
            # separates them so concatenated spools stay parseable.
            rc, out, _ = runner.run(
                f'for f in {spool}/rank-*.json; do '
                'cat "$f" 2>/dev/null; printf "\\n"; done',
                require_outputs=True)
            if rc != 0 or not out.strip():
                return
            for line in out.strip().splitlines():
                sample = telemetry.parse_sample(line.strip())
                if sample is not None and \
                        isinstance(sample.get('rank'), int):
                    samples[sample['rank']] = sample

        try:
            with tracing.span('backend.pull_telemetry',
                              cluster=handle.cluster_name, job=job_id):
                parallelism.run_in_parallel(
                    _pull, list(enumerate(runners)),
                    phase='pull_telemetry', what='telemetry pull')
        except exceptions.MultiHostError:
            pass
        return samples

    def capture_device_profile(self, handle: ClusterHandle,
                               job_id: Optional[int] = None,
                               duration_s: float = 1.0
                               ) -> Dict[int, Dict[str, Any]]:
        """Run one on-demand deep device capture on EVERY host in one
        fan-out: {rank: capture summary}. Artifacts (jax.profiler
        trace, capture.json) stay on each host under
        ``<runtime_root>/profiles/``; the one-line JSON summary each
        agent prints comes back. A partial fan-out failure costs the
        missing ranks, not the capture.
        """
        from skypilot_tpu.agent import profiler as profiler_lib
        runners = handle.get_command_runners()
        results: Dict[int, Dict[str, Any]] = {}
        env = self._agent_env(handle)
        # The fake-profiler seam must reach the remote agent process:
        # the control plane's seam env rides along explicitly (SSH
        # hosts don't inherit our environment).
        for key, value in os.environ.items():
            if key.startswith('XSKY_PROFILER_'):
                env[key] = value

        def _capture(pair):
            rank, runner = pair
            root = runner.remote_runtime_root()
            out_dir = (f'{root}/profiles/job-{job_id or 0}/'
                       f'rank-{rank}-{int(time.time())}')
            cmd = (f'{self._python_for(handle, runner)} -m '
                   f'skypilot_tpu.agent.profiler capture '
                   f'--out {out_dir} --duration {duration_s}')
            rc, out, _ = runner.run(cmd, env=env, require_outputs=True)
            if rc != 0 or not out.strip():
                return
            try:
                summary = json.loads(out.strip().splitlines()[-1])
            except ValueError:
                return
            if isinstance(summary, dict):
                summary['rank'] = rank
                results[rank] = profiler_lib.capture_summary_row(summary)

        try:
            with tracing.span('backend.profile_capture',
                              cluster=handle.cluster_name, job=job_id):
                parallelism.run_in_parallel(
                    _capture, list(enumerate(runners)),
                    phase='profile_capture', what='profile capture')
        except exceptions.MultiHostError:
            pass
        return results

    def _maybe_pull_telemetry(self, handle: ClusterHandle, job_id: int,
                              pull_state: Dict[str, float]) -> None:
        """Rate-limited telemetry pull + heartbeat-staleness recording
        inside the wait loop (`pull_state['next']` carries the
        schedule). Never raises — observability must not break the
        wait."""
        from skypilot_tpu.agent import telemetry
        now = time.time()
        if now < pull_state['next']:
            return
        pull_state['next'] = now + telemetry.pull_interval_s()
        try:
            samples = self.get_workload_telemetry(handle, job_id)
            if samples:
                telemetry.record_samples(handle.cluster_name, job_id,
                                         samples)
        except Exception:  # pylint: disable=broad-except
            pass

    def _wait_job(self, handle: ClusterHandle, job_id: int,
                  timeout_s: float = 3600.0,
                  stream_logs: bool = True) -> job_lib.JobStatus:
        """Wait for a job, live-tailing run.log (rank-0) as it runs.

        Each poll is ONE remote exec (`job_cli watch`) returning status
        + the next log chunk, and the interval backs off 0.3 s → 3 s
        while the job is quiet — on a real cluster every probe is an
        ssh exec + interpreter start (seconds), so the old fixed 0.3 s
        status-only poll hammered the head and still showed no output
        until failure.
        """
        deadline = time.time() + timeout_s
        record_gone = 0
        offset = 0
        interval = 0.3
        # Workload telemetry rides the wait loop (rate-limited: one
        # host fan-out per pull interval, first pull one interval in so
        # short jobs never pay it) — `xsky top`/`xsky status` get live
        # rank state for plain launches, not just managed jobs.
        from skypilot_tpu.agent import telemetry
        pull_state = {'next': time.time() + telemetry.pull_interval_s()}
        status: Optional[job_lib.JobStatus] = None
        # Incremental decoder: a multibyte character split across chunk
        # boundaries must not decode to replacement garbage.
        import codecs
        decoder = codecs.getincrementaldecoder('utf-8')('replace')
        while time.time() < deadline:
            rec = self._watch_job(handle, job_id, offset)
            if rec is not None:
                offset = rec['offset']
                if rec['log'] and stream_logs:
                    sys.stdout.write(decoder.decode(rec['log']))
                    sys.stdout.flush()
                    # Output is flowing: stay snappier, but never the
                    # old hammer rate.
                    interval = min(interval, 1.0)
                status = (None if rec['status'] == 'NOT_FOUND'
                          else job_lib.JobStatus(rec['status']))
            if status is not None and status.is_terminal():
                # The job is terminal so run.log is finite: drain until
                # an empty chunk (sanity-capped far above any real log;
                # if ever hit, say so rather than dropping the tail).
                # A transient probe failure is NOT end-of-log — retry a
                # few times before giving up on the tail.
                probe_failures = 0
                for _ in range(4096):
                    rec = self._watch_job(handle, job_id, offset)
                    if rec is None:
                        probe_failures += 1
                        if probe_failures > 3:
                            break
                        time.sleep(0.5)
                        continue
                    probe_failures = 0
                    if not rec['log']:
                        break
                    offset = rec['offset']
                    if stream_logs:
                        sys.stdout.write(decoder.decode(rec['log']))
                        sys.stdout.flush()
                else:
                    if stream_logs:
                        sys.stdout.write(
                            '\n[xsky] log drain capped; full log via '
                            '`xsky logs`\n')
                        sys.stdout.flush()
                if stream_logs:
                    tail = decoder.decode(b'', final=True)
                    if tail:
                        sys.stdout.write(tail)
                        sys.stdout.flush()
                if status != job_lib.JobStatus.SUCCEEDED:
                    raise exceptions.JobExitNonZeroError(
                        f'Job {job_id} finished with {status.value}. '
                        f'Logs:\n{self.tail_logs(handle, job_id, False)}')
                return status
            # A gone cluster record (concurrent `down`, preemption
            # reconciliation) is decisive: stop polling — a job racing a
            # teardown can leave a recreated jobs.db claiming a frozen
            # non-terminal status (e.g. INIT whose runner never spawned
            # because its host dir died under it), so the status alone
            # must never keep this loop alive. A few grace probes only
            # to be safe against torn reads.
            if state.get_cluster_from_name(handle.cluster_name) is None:
                record_gone += 1
                if record_gone >= 3:
                    raise exceptions.ClusterDoesNotExist(
                        f'Cluster {handle.cluster_name!r} disappeared '
                        f'while waiting for job {job_id} (torn down or '
                        'preempted).')
            else:
                record_gone = 0
            self._maybe_pull_telemetry(handle, job_id, pull_state)
            time.sleep(interval)
            interval = min(interval * 1.5, 3.0)
        raise TimeoutError(f'Job {job_id} did not finish in {timeout_s}s')

    # ---- job ops ----

    def get_job_status(self, handle: ClusterHandle,
                       job_id: int) -> Optional[job_lib.JobStatus]:
        head = handle.head_runner()
        rc, out, _ = head.run(
            f'{self._head_python(handle)} -m skypilot_tpu.agent.job_cli '
            f'status {job_id}',
            env=self._agent_env(handle), require_outputs=True)
        if rc != 0:
            return None
        value = out.strip().splitlines()[-1]
        if value == 'NOT_FOUND':
            return None
        return job_lib.JobStatus(value)

    def get_job_queue(self, handle: ClusterHandle) -> List[Dict[str, Any]]:
        head = handle.head_runner()
        rc, out, err = head.run(
            f'{self._head_python(handle)} -m skypilot_tpu.agent.job_cli '
            f'queue',
            env=self._agent_env(handle), require_outputs=True)
        if rc != 0:
            raise exceptions.CommandError(rc, 'job_cli queue', err)
        return json.loads(out.strip().splitlines()[-1])

    def cancel_jobs(self, handle: ClusterHandle, job_ids) -> None:
        head = handle.head_runner()

        def _cancel(job_id):
            # Best-effort (rc ignored), matching the sequential loop.
            head.run(f'{self._head_python(handle)} -m '
                     f'skypilot_tpu.agent.job_cli cancel '
                     f'{job_id}', env=self._agent_env(handle))

        try:
            with tracing.span('backend.cancel_jobs',
                              cluster=handle.cluster_name):
                parallelism.run_in_parallel(
                    _cancel, list(job_ids),
                    phase='cancel_jobs', what='job cancel')
        except exceptions.MultiHostError as e:
            # A cancel exec raising (dead head mid-teardown) was never
            # fatal in the sequential loop either.
            logger.warning(f'Job cancel fan-out incomplete: {e}')

    def tail_logs(self, handle: ClusterHandle, job_id: Optional[int],
                  follow: bool = True, all_ranks: bool = False) -> str:
        """Job log text. Default: rank 0's run.log (the live-tail
        view); ``all_ranks`` returns the ``[rank N]``-tagged multiplex
        of every host's output, so interleaved pod logs stay
        attributable."""
        if job_id is None:
            jobs = self.get_job_queue(handle)
            if not jobs:
                return ''
            job_id = jobs[0]['job_id']
        head = handle.head_runner()
        mode = ' gang' if all_ranks else ''
        rc, out, _ = head.run(
            f'{self._head_python(handle)} -m skypilot_tpu.agent.job_cli '
            f'tail {job_id}{mode}',
            env=self._agent_env(handle), require_outputs=True)
        return out

    def sync_down_logs(self, handle: ClusterHandle,
                       job_id: Optional[int] = None,
                       local_dir: Optional[str] = None) -> str:
        """Copy job log directories from the head host to local disk.

        Twin of `sky logs --sync-down`
        (sky/backends/cloud_vm_ray_backend.py:3856). Pulls
        ``<runtime_root>/logs/job-<id>`` (or every job dir when job_id
        is None) into ``<local_dir>/<cluster>/``; returns the local
        path.
        """
        local_dir = os.path.expanduser(
            local_dir or f'~/.xsky/sync_down_logs/{handle.cluster_name}')
        os.makedirs(local_dir, exist_ok=True)
        head = handle.head_runner()
        # ssh/local runners resolve relative remote paths against
        # $HOME/host-root; kubectl-cp resolves against the container
        # working directory, so kubernetes/docker need the absolute
        # runtime root (same special-case as the wheel bootstrap).
        if handle.provider_name in ('kubernetes', 'docker'):
            remote_logs = f'{handle.head_runtime_root}/logs'
        else:
            remote_logs = '.xsky/logs'
        if job_id is not None:
            head.rsync(os.path.join(local_dir, f'job-{job_id}'),
                       f'{remote_logs}/job-{job_id}/', up=False)
            return local_dir
        # All jobs: one rsync per job dir, fanned out — a long-lived
        # cluster accumulates hundreds of job dirs and the single
        # recursive rsync serialized them behind one ssh stream.
        rc, out, _ = head.run(f'ls -1 {remote_logs} 2>/dev/null',
                              env=self._agent_env(handle),
                              require_outputs=True)
        job_dirs = [d for d in out.split() if d.startswith('job-')] \
            if rc == 0 else []
        if not job_dirs:
            # Listing failed or nothing job-shaped: the old recursive
            # pull still works and covers non-job log files.
            head.rsync(local_dir, f'{remote_logs}/', up=False)
            return local_dir

        def _pull(job_dir):
            head.rsync(os.path.join(local_dir, job_dir),
                       f'{remote_logs}/{job_dir}/', up=False)

        with tracing.span('backend.sync_down_logs',
                          cluster=handle.cluster_name):
            parallelism.run_in_parallel(
                _pull, job_dirs,
                phase='sync_down_logs', what='log sync-down')
        # A gang killed mid-run (preemption, stall recovery) never
        # wrote its merged log; regenerate the [rank N]-tagged
        # multiplex locally so synced-down pod logs stay attributable.
        from skypilot_tpu.agent import gang as gang_lib
        for job_dir in job_dirs:
            local_job = os.path.join(local_dir, job_dir)
            if os.path.isdir(local_job) and not os.path.exists(
                    os.path.join(local_job, 'gang.log')):
                try:
                    gang_lib.aggregate_logs(local_job)
                except OSError:
                    pass
        return local_dir

    # ---- teardown / autostop ----

    def teardown(self, handle: ClusterHandle, terminate: bool,
                 purge: bool = False) -> None:
        cloud = handle.launched_resources.cloud
        provider = cloud.provisioner_module
        try:
            if terminate:
                # Instance termination and port-rule cleanup are
                # independent per-cluster resources: overlap them
                # (each can be a slow cloud API round trip). A plain
                # side thread, NOT run_in_parallel: the purge /
                # NotSupportedError guards below key on the original
                # exception types, which a MultiHostError wrapper
                # would defeat.
                import threading
                ports_err: List[BaseException] = []

                def _cleanup_ports():
                    try:
                        provision_lib.cleanup_ports(
                            provider, handle.cluster_name,
                            handle.cluster_info.provider_config)
                    except Exception as e:  # pylint: disable=broad-except
                        ports_err.append(e)

                ports_thread = threading.Thread(
                    target=_cleanup_ports, daemon=True,
                    name=f'xsky-ports-{handle.cluster_name}')
                ports_thread.start()
                try:
                    provision_lib.terminate_instances(
                        provider, handle.cluster_name,
                        handle.cluster_info.provider_config)
                finally:
                    ports_thread.join()
                if ports_err:
                    raise ports_err[0]
            else:
                provision_lib.stop_instances(
                    provider, handle.cluster_name,
                    handle.cluster_info.provider_config)
        except exceptions.NotSupportedError:
            raise
        except Exception:
            if not purge:
                raise
        state.remove_cluster(handle.cluster_name, terminate=terminate)

    def check_autostop_trigger(
            self, handle: ClusterHandle) -> Optional[Dict[str, Any]]:
        """Read-and-clear the agent's autostop marker, if present.

        The head agent cannot call the cloud API itself (no credentials
        on-host); the control plane polls this during status refresh and
        performs the stop/teardown (pull model; the reference pushes from
        the skylet with per-cloud creds, sky/skylet/events.py:102).
        """
        head = handle.head_runner()
        root = handle.head_runtime_root
        marker = f'{root}/autostop_triggered.json'
        rc, out, _ = head.run(
            f'cat {marker} 2>/dev/null && rm -f {marker}',
            env=self._agent_env(handle), require_outputs=True)
        if rc != 0 or not out.strip():
            return None
        try:
            return json.loads(out.strip().splitlines()[-1])
        except (json.JSONDecodeError, IndexError):
            return None

    def set_autostop(self, handle: ClusterHandle, idle_minutes: int,
                     down: bool = False) -> None:
        head = handle.head_runner()
        py = self._head_python(handle)
        if idle_minutes < 0:
            cmd = (f'{py} -c "from skypilot_tpu.agent import '
                   f'autostop_lib; autostop_lib.clear_autostop()"')
        else:
            cmd = (f'{py} -c "from skypilot_tpu.agent import '
                   f'autostop_lib; autostop_lib.set_autostop('
                   f'{idle_minutes}, {down})"')
        rc, _, err = head.run(cmd, env=self._agent_env(handle),
                              require_outputs=True)
        if rc != 0:
            raise exceptions.CommandError(rc, 'set_autostop', err)
        state.set_cluster_autostop(handle.cluster_name, idle_minutes, down)


def _pin_task(task: 'task_lib.Task',
              resources: 'resources_lib.Resources') -> 'task_lib.Task':
    """Return a shallow task copy pinned to one concrete Resources."""
    import copy
    pinned = copy.copy(task)
    pinned.set_resources(resources)
    return pinned


def _dispatch_script(cmds: Dict[int, Optional[str]]) -> str:
    """Bash that runs the right per-node command based on XSKY_NODE_RANK."""
    lines = ['case "$XSKY_NODE_RANK" in']
    for rank, cmd in cmds.items():
        body = cmd if cmd else 'true'
        lines.append(f'{rank}) {body} ;;')
    lines.append('*) true ;;')
    lines.append('esac')
    return '\n'.join(lines)
