"""Kubernetes provisioner: pods as hosts, GKE TPU podslices native.

Twin of sky/provision/kubernetes/instance.py (~6k LoC with utils),
rebuilt lean on the zero-dep kube API client (rest.py) — no kubectl in
the control plane. Tests inject a recorded-response transport via
:func:`set_transport_factory` (same moto-style pattern as the GCP
provisioner).

TPU-first design:
  * One *host* = one pod. A `tpu-v6e-16` request becomes
    `num_hosts × num_slices` pods, each pinned to the podslice node pool
    via the GKE selectors (`cloud.google.com/gke-tpu-accelerator`,
    `gke-tpu-topology`) and requesting `google.com/tpu: chips_per_host` —
    GKE's scheduler then places them on the hosts of one slice.
  * A headless Service gives pods stable DNS for the gang launcher's
    coordinator address (jax.distributed) — the role Ray GCS played in
    the reference.
  * Pods cannot stop; stop_instances raises, matching multi-host TPU-VM
    semantics so autostop falls back to teardown uniformly.
  * Networking modes (twin of the reference's
    kubernetes.networking_mode): `nodeport` (default) exposes
    user-requested ports as a NodePort service on the head pod;
    `portforward` skips service creation — access rides the client-side
    tunnel (kubectl port-forward data plane), nothing to provision.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu import sky_logging
from skypilot_tpu.provision import common
from skypilot_tpu.provision.kubernetes import rest

logger = sky_logging.init_logger(__name__)

CLUSTER_LABEL = 'xsky-cluster'
HOST_INDEX_LABEL = 'xsky-host-index'    # per-slice (TPU_WORKER_ID)
GLOBAL_INDEX_LABEL = 'xsky-global-index'
SLICE_LABEL = 'xsky-slice'

_WAIT_TIMEOUT_S = 600.0
_POLL_INTERVAL_S = 2.0

# Pluggable transport for tests (recorded-response fake API).
_transport_factory = rest.KubeTransport

# One transport per context: building one parses the kubeconfig,
# writes client-cert temp files, and may run an exec credential
# plugin — a poll loop (dashboard, autostop) must not pay that (or
# leak temp files) on every lifecycle op.
_transport_cache: Dict[Optional[str], Any] = {}
# Concurrent lifecycle ops (status refresh fan-out, autostop ticks)
# race the cache fill; the lock guards the cache dict only — the
# expensive transport build happens OUTSIDE it, so one unreachable
# cluster's exec credential plugin cannot wedge every other context's
# poll. Losers of a duplicate build race just drop their transport.
_transport_lock = threading.Lock()


def set_transport_factory(factory) -> None:
    global _transport_factory
    with _transport_lock:
        _transport_factory = factory
        _transport_cache.clear()


def _client(context: Optional[str], namespace: str) -> rest.KubeClient:
    try:
        factory = _transport_factory
        with _transport_lock:
            cached = _transport_cache.get(context)
        # Entries pin the factory that built them, so swapping the
        # factory (tests monkeypatch it directly) never serves a
        # stale transport.
        if cached is None or cached[0] is not factory:
            built = (factory, factory(context))
            with _transport_lock:
                cached = _transport_cache.get(context)
                if cached is None or cached[0] is not factory:
                    _transport_cache[context] = built
                    cached = built
        return rest.KubeClient(cached[1], namespace)
    except ValueError as e:
        raise exceptions.ProvisionError(str(e)) from e


def _wrap_api_error(e: rest.KubeApiError) -> exceptions.ProvisionError:
    return exceptions.ProvisionError(f'Kubernetes API: {e}')


def _pod_name(cluster_name: str, index: int) -> str:
    return f'{cluster_name}-{index}'


def _build_pod_manifest(cluster_name: str, index: int, slice_index: int,
                        host_index: int,
                        node_config: Dict[str, Any]) -> Dict[str, Any]:
    cpus = node_config.get('cpus', 2)
    memory = node_config.get('memory_gib', 8)
    image = node_config.get('image_id') or 'python:3.11-slim'
    resources: Dict[str, Any] = {
        'cpu': str(cpus),
        'memory': f'{memory:g}Gi',
    }
    node_selector: Dict[str, str] = {}
    if node_config.get('tpu_podslice'):
        resources['google.com/tpu'] = str(
            node_config.get('tpu_chips_per_host', 4))
        node_selector['cloud.google.com/gke-tpu-accelerator'] = \
            node_config['tpu_gke_accelerator']
        node_selector['cloud.google.com/gke-tpu-topology'] = \
            node_config['tpu_gke_topology']
    elif node_config.get('gpu_type'):
        resources['nvidia.com/gpu'] = str(
            int(node_config.get('gpu_count', 1)))
    manifest = {
        'apiVersion': 'v1',
        'kind': 'Pod',
        'metadata': {
            'name': _pod_name(cluster_name, index),
            'labels': {
                CLUSTER_LABEL: cluster_name,
                # Per-slice host index (InstanceInfo.host_index contract;
                # TPU_WORKER_ID must restart at 0 on every slice).
                HOST_INDEX_LABEL: str(host_index),
                GLOBAL_INDEX_LABEL: str(index),
                SLICE_LABEL: f'{cluster_name}-slice-{slice_index}',
                **{str(k): str(v)
                   for k, v in (node_config.get('labels') or {}).items()
                   if '/' not in str(k)},
            },
        },
        'spec': {
            'restartPolicy': 'Never',
            'hostname': _pod_name(cluster_name, index),
            'subdomain': cluster_name,
            'containers': [{
                'name': 'xsky',
                'image': image,
                'command': ['/bin/sh', '-c', 'sleep infinity'],
                'resources': {'requests': dict(resources),
                              'limits': dict(resources)},
            }],
        },
    }
    if node_selector:
        manifest['spec']['nodeSelector'] = node_selector
    if node_config.get('tpu_podslice'):
        # Per-slice host identity for libtpu (the GKE device plugin
        # populates TPU_WORKER_ID/HOSTNAMES; we pin the hostnames via the
        # headless service subdomain above).
        manifest['spec']['containers'][0]['env'] = [
            {'name': 'TPU_WORKER_ID', 'value': str(host_index)},
        ]
    return manifest


def _build_service_manifest(cluster_name: str) -> Dict[str, Any]:
    """Headless service: stable DNS `<pod>.<cluster>.<ns>.svc` per host."""
    return {
        'apiVersion': 'v1',
        'kind': 'Service',
        'metadata': {
            'name': cluster_name,
            'labels': {CLUSTER_LABEL: cluster_name},
        },
        'spec': {
            'clusterIP': 'None',
            'selector': {CLUSTER_LABEL: cluster_name},
        },
    }


def _num_hosts(config: common.ProvisionConfig) -> int:
    node = config.node_config
    per_node = 1
    if node.get('tpu_podslice'):
        per_node = (int(node.get('tpu_num_hosts', 1)) *
                    int(node.get('tpu_num_slices', 1)))
    return config.count * per_node


def run_instances(region: str, zone: Optional[str], cluster_name: str,
                  config: common.ProvisionConfig) -> common.ProvisionRecord:
    del zone
    node = config.node_config
    context = node.get('context')
    namespace = node.get('namespace', 'default')
    total = _num_hosts(config)
    hosts_per_slice = int(node.get('tpu_num_hosts', 1)) if \
        node.get('tpu_podslice') else 1

    client = _client(context, namespace)
    try:
        existing = _list_pods(client, cluster_name)
        created: List[str] = []
        client.apply(_build_service_manifest(cluster_name))
        for i in range(total):
            name = _pod_name(cluster_name, i)
            if name in existing:
                continue
            client.apply(
                _build_pod_manifest(cluster_name, i,
                                    slice_index=i // hosts_per_slice,
                                    host_index=i % hosts_per_slice,
                                    node_config=node))
            created.append(name)
    except rest.KubeApiError as e:
        raise _wrap_api_error(e) from e
    return common.ProvisionRecord(
        provider_name='kubernetes',
        cluster_name=cluster_name,
        region=region,
        zone=None,
        resumed_instance_ids=[],
        created_instance_ids=created,
        head_instance_id=_pod_name(cluster_name, 0),
    )


def _list_pods(client: rest.KubeClient,
               cluster_name: str) -> Dict[str, Dict[str, Any]]:
    items = client.list('Pod', f'{CLUSTER_LABEL}={cluster_name}')
    return {p['metadata']['name']: p for p in items}


_STATUS_MAP = {
    'Pending': 'PENDING',
    'Running': 'RUNNING',
    'Succeeded': 'TERMINATED',
    'Failed': 'TERMINATED',
    'Unknown': 'PENDING',
}


def _scoped_client(provider_config: Dict[str, Any]) -> rest.KubeClient:
    return _client(provider_config.get('context'),
                   provider_config.get('namespace', 'default'))


def query_instances(cluster_name: str,
                    provider_config: Dict[str, Any]
                    ) -> Dict[str, Optional[str]]:
    try:
        pods = _list_pods(_scoped_client(provider_config), cluster_name)
    except rest.KubeApiError as e:
        raise _wrap_api_error(e) from e
    return {
        name: _STATUS_MAP.get(p.get('status', {}).get('phase', 'Unknown'),
                              'PENDING')
        for name, p in pods.items()
    }


def stop_instances(cluster_name: str,
                   provider_config: Dict[str, Any]) -> None:
    raise exceptions.NotSupportedError(
        'Kubernetes pods cannot be stopped; tear the cluster down instead.')


def terminate_instances(cluster_name: str,
                        provider_config: Dict[str, Any]) -> None:
    client = _scoped_client(provider_config)
    try:
        client.delete_by_selector('Pod',
                                  f'{CLUSTER_LABEL}={cluster_name}')
        client.delete_by_selector('Service',
                                  f'{CLUSTER_LABEL}={cluster_name}')
    except rest.KubeApiError as e:
        raise _wrap_api_error(e) from e


def wait_instances(region: str, cluster_name: str, state: str,
                   provider_config: Optional[Dict[str, Any]] = None,
                   timeout: float = _WAIT_TIMEOUT_S) -> None:
    provider_config = provider_config or {}
    # Contexts are this cloud's regions: fall back to the region argument
    # so a caller that lost provider_config still targets the right
    # cluster ('in-cluster' means "use the ambient service account").
    context = provider_config.get('context') or (
        None if region in (None, '', 'in-cluster') else region)
    namespace = provider_config.get('namespace', 'default')
    client = _client(context, namespace)
    deadline = time.time() + timeout
    while True:
        try:
            pods = _list_pods(client, cluster_name)
        except rest.KubeApiError as e:
            raise _wrap_api_error(e) from e
        phases = [p.get('status', {}).get('phase') for p in pods.values()]
        if state == 'RUNNING':
            if pods and all(ph == 'Running' for ph in phases):
                return
            # restartPolicy=Never: a Failed/Succeeded pod can never reach
            # Running again — fail fast so failover proceeds immediately.
            terminal = [
                name for name, p in pods.items()
                if p.get('status', {}).get('phase') in ('Failed',
                                                        'Succeeded')
            ]
            if terminal:
                raise exceptions.ProvisionError(
                    f'Pods terminally failed while waiting for RUNNING: '
                    f'{terminal}')
        if state == 'TERMINATED' and not pods:
            return
        if time.time() > deadline:
            raise exceptions.ProvisionError(
                f'Timed out waiting for {cluster_name} to reach {state}; '
                f'phases={phases}')
        time.sleep(_POLL_INTERVAL_S)


def get_cluster_info(region: str, cluster_name: str,
                     provider_config: Dict[str, Any]) -> common.ClusterInfo:
    del region
    context = provider_config.get('context')
    namespace = provider_config.get('namespace', 'default')
    try:
        pods = _list_pods(_client(context, namespace), cluster_name)
    except rest.KubeApiError as e:
        raise _wrap_api_error(e) from e
    instances: Dict[str, common.InstanceInfo] = {}
    for name, pod in sorted(pods.items()):
        labels = pod['metadata'].get('labels', {})
        instances[name] = common.InstanceInfo(
            instance_id=name,
            internal_ip=pod.get('status', {}).get('podIP', ''),
            external_ip=None,
            status=_STATUS_MAP.get(
                pod.get('status', {}).get('phase', 'Unknown'), 'PENDING'),
            tags={'namespace': namespace, 'context': context or ''},
            slice_id=labels.get(SLICE_LABEL),
            host_index=int(labels.get(HOST_INDEX_LABEL, 0)),
        )
    head = _pod_name(cluster_name, 0)
    return common.ClusterInfo(
        instances=instances,
        head_instance_id=head if head in instances else None,
        provider_name='kubernetes',
        provider_config=provider_config,
        ssh_user='root',
    )


def networking_mode(provider_config: Dict[str, Any]) -> str:
    mode = (provider_config.get('networking_mode') or 'nodeport').lower()
    if mode not in ('nodeport', 'portforward'):
        raise exceptions.InvalidSkyTpuConfigError(
            f'kubernetes networking_mode must be nodeport or '
            f'portforward, got {mode!r}')
    return mode


def open_ports(cluster_name: str, ports: List[str],
               provider_config: Dict[str, Any]) -> None:
    """Expose ports on the head pod via a NodePort service.

    In `portforward` networking mode nothing is provisioned: clients
    reach pod ports through the port-forward data plane instead of a
    node-level listener (the reference's portforward mode does the
    same — its endpoint command spawns the forward client-side).
    """
    if networking_mode(provider_config) == 'portforward':
        logger.debug(f'networking_mode=portforward: no NodePort service '
                     f'for {cluster_name} ports {ports}')
        return
    port_specs = []
    for p in ports:
        spec = str(p)
        if '-' in spec:
            lo, hi = (int(x) for x in spec.split('-', 1))
        else:
            lo = hi = int(spec)
        for port in range(lo, hi + 1):
            port_specs.append({'name': f'port-{port}', 'port': port,
                               'targetPort': port})
    if not port_specs:
        return
    manifest = {
        'apiVersion': 'v1',
        'kind': 'Service',
        'metadata': {
            'name': f'{cluster_name}-ports',
            'labels': {CLUSTER_LABEL: cluster_name},
        },
        'spec': {
            'type': 'NodePort',
            'selector': {CLUSTER_LABEL: cluster_name,
                         GLOBAL_INDEX_LABEL: '0'},
            'ports': port_specs,
        },
    }
    try:
        _scoped_client(provider_config).apply(manifest)
    except rest.KubeApiError as e:
        raise _wrap_api_error(e) from e


def cleanup_ports(cluster_name: str,
                  provider_config: Dict[str, Any]) -> None:
    try:
        _scoped_client(provider_config).delete('Service',
                                               f'{cluster_name}-ports')
    except rest.KubeApiError as e:
        logger.warning(f'cleanup_ports({cluster_name}): {e}')


def query_ports(cluster_name: str, ports, provider_config: Dict[str, Any],
                cluster_info) -> Dict[int, str]:
    """port → endpoint. NodePort mode reads the allocated nodePorts off
    the ports service and pairs them with the head pod's node IP;
    portforward mode returns the kubectl command the user runs (no
    cluster-side listener exists)."""
    del ports
    context = provider_config.get('context')
    namespace = provider_config.get('namespace', 'default')
    if networking_mode(provider_config) == 'portforward':
        # No cluster-side listener exists in this mode — nothing to
        # query (and no client to build): the forward command IS the
        # endpoint.
        head = cluster_info.get_head_instance()
        pod = head.instance_id if head else f'{cluster_name}-0'
        ctx = f'--context {context} ' if context else ''
        return {0: f'kubectl {ctx}-n {namespace} port-forward '
                   f'pod/{pod} <local>:<port>'}
    client = _client(context, namespace)
    try:
        svc = client.get('Service', f'{cluster_name}-ports')
        if svc is None:
            return {}
        node_ip = ''
        head = cluster_info.get_head_instance() if cluster_info else None
        if head is not None:
            pod = client.get('Pod', head.instance_id)
            if pod:
                node_ip = pod.get('status', {}).get('hostIP', '')
    except rest.KubeApiError as e:
        raise _wrap_api_error(e) from e
    out: Dict[int, str] = {}
    for entry in svc.get('spec', {}).get('ports', []):
        node_port = entry.get('nodePort')
        if node_port:
            out[int(entry['port'])] = (
                f'http://{node_ip or "<node-ip>"}:{node_port}')
    return out


# ---- fuse-proxy DaemonSet (privileged fusermount broker) -------------------

FUSE_PROXY_NAMESPACE = 'kube-system'
FUSE_PROXY_NAME = 'fusermount-server'


def fuse_proxy_daemonset(image: str = 'fusermount-server:latest'
                         ) -> Dict[str, Any]:
    """The addons/fuse-proxy DaemonSet as an API object (twin of the
    reference's fusermount-server manifest,
    sky/provision/kubernetes/manifests/): one privileged pod per node
    brokering fusermount for unprivileged task pods over
    /var/run/fusermount/server.sock."""
    labels = {'app': FUSE_PROXY_NAME}
    return {
        'apiVersion': 'apps/v1',
        'kind': 'DaemonSet',
        'metadata': {
            'name': FUSE_PROXY_NAME,
            'namespace': FUSE_PROXY_NAMESPACE,
            'labels': labels,
        },
        'spec': {
            'selector': {'matchLabels': labels},
            'template': {
                'metadata': {'labels': labels},
                'spec': {
                    'hostPID': True,
                    'containers': [{
                        'name': FUSE_PROXY_NAME,
                        'image': image,
                        'command': [
                            '/usr/local/bin/fusermount-server',
                            '/var/run/fusermount/server.sock'],
                        'securityContext': {'privileged': True},
                        'volumeMounts': [{
                            'mountPath': '/var/run/fusermount',
                            'name': 'fusermount-shared-dir'}],
                    }],
                    'volumes': [{
                        'name': 'fusermount-shared-dir',
                        'hostPath': {'path': '/var/run/fusermount',
                                     'type': 'DirectoryOrCreate'}}],
                },
            },
        },
    }


def deploy_fuse_proxy(provider_config: Dict[str, Any]) -> None:
    """Ensure the fusermount-server DaemonSet exists (idempotent).

    Called before running MOUNT-mode storage commands on a kubernetes
    cluster: unprivileged task pods need the per-node broker for FUSE
    mounts. Failures surface loudly — a missing broker means the mount
    command will sit failing in the pod."""
    client = _client(provider_config.get('context'),
                     FUSE_PROXY_NAMESPACE)
    image = provider_config.get('fuse_proxy_image',
                                'fusermount-server:latest')
    try:
        client.apply(fuse_proxy_daemonset(image))
    except rest.KubeApiError as e:
        raise exceptions.ProvisionError(
            f'Deploying the fuse-proxy DaemonSet failed: {e}') from e
