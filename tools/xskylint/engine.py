"""The xskylint engine: parse once, run every rule over the shared AST.

Replaces the eight ad-hoc AST lints that grew inside
``tests/unit_tests/test_chaos.py`` (each re-parsing and re-walking the
tree with its own skip-list and exemption syntax) with one framework:

  * **One parse per file.** ``ast.parse`` runs exactly once per
    scanned file; rules receive the shared tree. An engine unit test
    counts the calls, so the single-pass property is load-bearing, not
    aspirational.
  * **One shared walk.** The engine performs a single recursive walk
    maintaining the lexical state the legacy lints each recomputed —
    enclosing function, loop membership, ``with tracing.span(...)``
    coverage — and hands every node to every interested rule. Rules
    needing whole-function analysis (heartbeat loops, SELECT paging)
    do it from ``end_file`` on the same tree; nothing re-parses.
  * **One suppression syntax.** ``# xskylint: disable=<rule> -- <reason>``
    on the offending line or the line above. The reason is mandatory:
    a directive without one is itself a finding, as is a directive
    naming an unknown rule (a typo'd id would otherwise silently
    suppress nothing). Legacy markers keep working through
    :data:`LEGACY_MARKERS` so historical exemptions did not need a
    flag-day rewrite.

Rules live in ``tools/xskylint/rules/``; docs/static-analysis.md is
the catalog and how-to-add-a-rule guide.
"""
from __future__ import annotations

import argparse
import ast
import dataclasses
import json
import os
import re
import sys
from typing import Any, Callable, Dict, Iterable, List, Optional, Set

# Pre-engine exemption comments that must keep working (the legacy
# lints shipped them and the tree uses them): marker substring → the
# rule id it suppresses. Rules consult this via
# :func:`legacy_markers_for`; the marker's own comment carries the
# reason (e.g. ``# full-scan ok: one row per enabled cloud``), which
# is why no ``--`` reason is re-required.
LEGACY_MARKERS: Dict[str, str] = {
    '# full-scan ok': 'select-limit',
    # Registered single-writer exemption of the lock-discipline rule
    # (consumed during index construction, listed here so the marker
    # is discoverable alongside the other exemption comments).
    '# single-writer ok': 'lock-discipline',
    # Hot-path escape hatch of the hot-path-purity rule: an
    # interval-gated/atomic blocking site (the telemetry spool
    # pattern) declares its bound after the colon. Consumed during
    # call-graph harvest (tools/xskylint/callgraph.py).
    '# hotpath ok': 'hot-path-purity',
}

# Engine-minted finding ids (not registered rules; not suppressible —
# fixing the directive is the only way out).
SUPPRESSION_RULE = 'suppression-syntax'
PARSE_RULE = 'parse-error'

_SUPPRESS_RE = re.compile(
    r'#\s*xskylint:\s*disable=([A-Za-z0-9_,\-]+)'
    r'(?:\s+--\s*(\S.*))?')


@dataclasses.dataclass
class Finding:
    """One rule violation (or suppressed would-be violation)."""
    rule: str
    path: str          # repo-relative, posix separators
    line: int
    message: str
    suppressed: bool = False
    reason: Optional[str] = None   # the suppression's mandatory reason
    # Interprocedural evidence (the entry→violation call chain, a
    # lock cycle's edge witnesses): rendered by `xsky lint --why`,
    # carried through --json.
    detail: Optional[List[str]] = None

    def render(self) -> str:
        tail = f' (suppressed: {self.reason})' if self.suppressed else ''
        return f'{self.path}:{self.line}: [{self.rule}] ' \
               f'{self.message}{tail}'

    def to_json(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


@dataclasses.dataclass(frozen=True)
class WalkState:
    """Lexical state the shared walk maintains for every node.

    ``in_loop`` deliberately survives function boundaries (a helper
    defined inside a retry loop still runs per iteration) — the
    semantics the legacy no-raw-sleep lint shipped with.
    ``span_covered`` resets at function boundaries: a span enclosing
    only the *definition* of a nested function does not cover calls
    inside it (it runs when called, not where defined).
    """
    func: Optional[str] = None      # innermost enclosing function name
    in_loop: bool = False
    span_covered: bool = False


def is_span_with(node: ast.AST) -> bool:
    """A ``with`` whose context expression is a ``*span*(...)`` call —
    the tracing-coverage contract shared by three rules."""
    if not isinstance(node, ast.With):
        return False
    for item in node.items:
        expr = item.context_expr
        if isinstance(expr, ast.Call):
            func = expr.func
            name = func.attr if isinstance(func, ast.Attribute) \
                else getattr(func, 'id', '')
            if 'span' in (name or ''):
                return True
    return False


def call_name(node: ast.AST) -> str:
    """The called name of a Call node ('' for non-calls / exotic
    callees): ``foo()`` → 'foo', ``mod.foo()`` → 'foo'."""
    if not isinstance(node, ast.Call):
        return ''
    func = node.func
    if isinstance(func, ast.Attribute):
        return func.attr
    return getattr(func, 'id', '') or ''


class FileContext:
    """Everything a rule may need about one scanned file. ``tree`` is
    the single shared parse."""

    def __init__(self, rel_path: str, source: str,
                 tree: ast.Module) -> None:
        self.rel_path = rel_path
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        self.findings: List[Finding] = []

    def report(self, rule_id: str, line: int, message: str) -> None:
        self.findings.append(
            Finding(rule=rule_id, path=self.rel_path, line=line,
                    message=message))

    def function_source(self, node: ast.AST) -> str:
        """The raw source lines of a def (legacy marker scans)."""
        return '\n'.join(
            self.lines[node.lineno - 1:node.end_lineno])


class Rule:
    """Base class. Subclasses set ``id`` + ``rationale`` and override
    any of the hooks; all receive the shared tree, never re-parse.

    Hooks:
      * ``applies_to(rel_path)`` — file scope (path filters belong
        here, not inside visit logic).
      * ``begin_file(ctx)`` / ``end_file(ctx)`` — whole-file analyses
        over ``ctx.tree``.
      * ``visit(node, state, ctx)`` — called for every AST node during
        the shared walk with the lexical :class:`WalkState`.
      * ``finalize(run)`` — cross-file checks after every file ran.

    Rules that read ``run.index`` from ``finalize`` must set
    ``needs_index = True``: the engine only pays the whole-program
    harvesting pass when an active rule declares it.
    """

    id: str = ''
    rationale: str = ''
    needs_index: bool = False
    # Rule ids that must run WHENEVER this rule runs: a rule whose
    # soundness depends on a second rule verifying what it admits
    # (never-raise admits fallback-arm calls because
    # never-raise-transitive proves them) declares the dependency so
    # a --rule subset can't silently drop the verification half.
    companions: tuple = ()

    def applies_to(self, rel_path: str) -> bool:
        del rel_path
        return True

    def begin_file(self, ctx: FileContext) -> None:
        pass

    def visit(self, node: ast.AST, state: WalkState,
              ctx: FileContext) -> None:
        pass

    def end_file(self, ctx: FileContext) -> None:
        pass

    def finalize(self, run: 'RunContext') -> None:
        pass


class RunContext:
    """Cross-file state handed to ``finalize``. ``index`` is the
    whole-program :class:`tools.xskylint.index.ProjectIndex` built
    during pass 1 over the same shared trees (never re-parsed)."""

    def __init__(self, root: str) -> None:
        self.root = root
        self.scanned: Set[str] = set()
        self.findings: List[Finding] = []
        self.index = None

    def report(self, rule_id: str, path: str, line: int,
               message: str,
               detail: Optional[List[str]] = None) -> None:
        self.findings.append(
            Finding(rule=rule_id, path=path, line=line, message=message,
                    detail=detail))


def legacy_markers_for(rule_id: str) -> List[str]:
    return [marker for marker, rid in LEGACY_MARKERS.items()
            if rid == rule_id]


class _Suppressions:
    """Per-file ``# xskylint: disable=`` directives. A finding at line
    N is suppressed by a directive naming its rule on line N itself or
    anywhere in the contiguous comment block immediately above it
    (multi-line reasons are normal; the directive leads the block)."""

    def __init__(self, ctx: FileContext, known_rules: Set[str]) -> None:
        self._lines = ctx.lines
        # line → (rule ids, reason)
        self.by_line: Dict[int, Any] = {}
        self.syntax_findings: List[Finding] = []
        for lineno, text in enumerate(ctx.lines, 1):
            m = _SUPPRESS_RE.search(text)
            if not m:
                continue
            rules = {r.strip() for r in m.group(1).split(',') if r.strip()}
            reason = (m.group(2) or '').strip()
            if not reason:
                self.syntax_findings.append(Finding(
                    rule=SUPPRESSION_RULE, path=ctx.rel_path, line=lineno,
                    message='suppression without a reason — write '
                            '`# xskylint: disable=<rule> -- <why>`'))
                continue
            unknown = rules - known_rules
            for rid in sorted(unknown):
                self.syntax_findings.append(Finding(
                    rule=SUPPRESSION_RULE, path=ctx.rel_path, line=lineno,
                    message=f'suppression names unknown rule '
                            f'{rid!r} (typo? it would suppress '
                            'nothing)'))
            self.by_line[lineno] = (rules - unknown, reason)

    def match(self, finding: Finding) -> Optional[str]:
        """The suppression reason covering `finding`, or None."""
        entry = self.by_line.get(finding.line)
        if entry and finding.rule in entry[0]:
            return entry[1]
        lineno = finding.line - 1
        while 1 <= lineno <= len(self._lines) and \
                self._lines[lineno - 1].strip().startswith('#'):
            entry = self.by_line.get(lineno)
            if entry and finding.rule in entry[0]:
                return entry[1]
            lineno -= 1
        return None


class AstCache:
    """mtime+size+content-hash-keyed pickle cache of parsed trees
    under ``<root>/.xskylint_cache/`` — the engine's repeated-run
    accelerator (``--changed`` and pre-commit loops re-run the
    whole-program index every time; re-parsing ~350 files dominated).
    The source is already in memory for suppression matching, so the
    key includes its sha1 alongside (mtime_ns, size) — a same-size
    edit inside the filesystem's mtime granularity (1 s on several)
    can never serve a stale tree. A stale, corrupt, or cross-version
    entry silently degrades to a fresh parse — the cache can never
    change a verdict, only skip ``ast.parse`` calls (the parse-once
    counter test asserts hits)."""

    # Bump when the stored payload shape changes.
    FORMAT = 2

    def __init__(self, cache_dir: str) -> None:
        self.cache_dir = cache_dir
        self._stamp = (self.FORMAT, sys.version_info[:2])

    def _entry_path(self, rel_path: str) -> str:
        import hashlib
        digest = hashlib.sha1(rel_path.encode('utf-8')).hexdigest()
        return os.path.join(self.cache_dir, f'{digest}.pkl')

    @staticmethod
    def _key(rel_path: str, mtime_ns: int, size: int,
             source: str) -> tuple:
        import hashlib
        content = hashlib.sha1(source.encode('utf-8')).hexdigest()
        return (rel_path, mtime_ns, size, content)

    def get(self, rel_path: str, mtime_ns: int, size: int,
            source: str) -> Optional[ast.Module]:
        import pickle
        try:
            with open(self._entry_path(rel_path), 'rb') as f:
                payload = pickle.load(f)
            if payload.get('stamp') == self._stamp and \
                    payload.get('key') == self._key(
                        rel_path, mtime_ns, size, source):
                return payload['tree']
        except Exception:  # pylint: disable=broad-except
            pass   # miss/corrupt/unreadable: reparse
        return None

    def put(self, rel_path: str, mtime_ns: int, size: int,
            source: str, tree: ast.Module) -> None:
        import pickle
        try:
            os.makedirs(self.cache_dir, exist_ok=True)
            path = self._entry_path(rel_path)
            tmp = f'{path}.tmp.{os.getpid()}'
            with open(tmp, 'wb') as f:
                pickle.dump({'stamp': self._stamp,
                             'key': self._key(rel_path, mtime_ns,
                                              size, source),
                             'tree': tree}, f,
                            protocol=pickle.HIGHEST_PROTOCOL)
            os.replace(tmp, path)
        except Exception:  # pylint: disable=broad-except
            pass   # a read-only checkout still lints, uncached


class LintEngine:
    """Run a rule set over a tree of Python files, parsing each once."""

    def __init__(self, root: str, rules: List[Rule],
                 parse: Callable[..., ast.Module] = ast.parse,
                 cache_dir: Optional[str] = None) -> None:
        self.root = os.path.abspath(root)
        self.rules = rules
        self.rule_ids = {r.id for r in rules}
        # Directive validation is against every REGISTERED rule, not
        # just the active subset — a single-rule run must not flag
        # other rules' suppressions as typos.
        from tools.xskylint.rules import all_rules
        self.known_rule_ids = self.rule_ids | {
            r.id for r in all_rules()}
        # Injectable for the parse-once engine test.
        self._parse = parse
        self._cache = AstCache(cache_dir) if cache_dir else None

    # -- file discovery ------------------------------------------------------

    def iter_files(self, paths: Iterable[str]) -> List[str]:
        """Repo-relative posix paths of every .py under `paths`
        (files or directories, relative to root), sorted."""
        out: Set[str] = set()
        for p in paths:
            abs_p = p if os.path.isabs(p) else os.path.join(self.root, p)
            if os.path.isfile(abs_p):
                out.add(self._rel(abs_p))
                continue
            if not os.path.isdir(abs_p):
                # A typo'd path must not green-light as '0 files, 0
                # findings' in CI.
                raise FileNotFoundError(
                    f'lint path does not exist: {p} '
                    f'(resolved {abs_p})')
            for dirpath, dirnames, filenames in os.walk(abs_p):
                dirnames[:] = [d for d in dirnames
                               if not d.startswith('.')
                               and d != '__pycache__']
                for fname in filenames:
                    if fname.endswith('.py'):
                        out.add(self._rel(os.path.join(dirpath, fname)))
        return sorted(out)

    def _rel(self, abs_path: str) -> str:
        return os.path.relpath(abs_path, self.root).replace(os.sep, '/')

    # -- the shared walk -----------------------------------------------------

    def _walk(self, node: ast.AST, state: WalkState,
              active: List[Rule], ctx: FileContext) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # in_loop survives function boundaries by design (a
                # helper defined inside a retry loop runs per
                # iteration — legacy no-raw-sleep semantics).
                child_state = WalkState(
                    func=child.name,
                    in_loop=state.in_loop,
                    span_covered=False)
            else:
                child_state = WalkState(
                    func=state.func,
                    in_loop=state.in_loop or isinstance(
                        child, (ast.While, ast.For, ast.AsyncFor)),
                    span_covered=state.span_covered
                    or is_span_with(child))
            for rule in active:
                rule.visit(child, child_state, ctx)
            self._walk(child, child_state, active, ctx)

    # -- running -------------------------------------------------------------

    def run(self, paths: Iterable[str],
            focus: Optional[Set[str]] = None) -> 'RunResult':
        """Lint `paths`. With `focus` (the --changed contract), only
        files in the set get the per-file rule hooks; every file is
        still parsed ONCE into the whole-program index and its
        suppressions honored, so cross-file rules see the full
        program."""
        run_ctx = RunContext(self.root)
        build_index = any(r.needs_index for r in self.rules)
        if build_index:
            from tools.xskylint import index as index_mod
            run_ctx.index = index_mod.ProjectIndex(self.root)
        findings: List[Finding] = []
        suppressions: Dict[str, _Suppressions] = {}
        files = self.iter_files(paths)
        if focus is not None and not focus.intersection(files):
            # A changed file absent from the tree is a *deletion* — it
            # may have been part of the whole-program index, so the
            # cross-file verdict can move (a payloads verb now targets
            # a module that no longer exists). Fall through to the full
            # index pass; per-file rules still skip every file.
            if all(os.path.exists(os.path.join(self.root, rel))
                   for rel in focus):
                # Nothing in the linted tree changed and nothing was
                # deleted: no per-file rules to run and no reason to
                # rebuild the whole-program index.
                return RunResult(root=self.root, files_scanned=0,
                                 rule_ids=sorted(self.rule_ids),
                                 findings=[])
        for rel in files:
            abs_path = os.path.join(self.root, rel)
            try:
                st = os.stat(abs_path)
                with open(abs_path, encoding='utf-8') as f:
                    source = f.read()
                tree = None
                if self._cache is not None:
                    tree = self._cache.get(rel, st.st_mtime_ns,
                                           st.st_size, source)
                if tree is None:
                    tree = self._parse(source, filename=rel)
                    if self._cache is not None:
                        self._cache.put(rel, st.st_mtime_ns,
                                        st.st_size, source, tree)
            except (OSError, SyntaxError, ValueError) as e:
                findings.append(Finding(
                    rule=PARSE_RULE, path=rel, line=getattr(
                        e, 'lineno', 1) or 1,
                    message=f'cannot parse: {e}'))
                continue
            run_ctx.scanned.add(rel)
            if build_index:
                run_ctx.index.add_file(rel, tree, source)
            ctx = FileContext(rel, source, tree)
            active = [r for r in self.rules if r.applies_to(rel)]
            if focus is not None and rel not in focus:
                active = []
            if active:
                for rule in active:
                    rule.begin_file(ctx)
                self._walk(tree, WalkState(), active, ctx)
                for rule in active:
                    rule.end_file(ctx)
            sup = _Suppressions(ctx, self.known_rule_ids)
            suppressions[rel] = sup
            findings.extend(sup.syntax_findings)
            for finding in ctx.findings:
                reason = sup.match(finding)
                if reason is not None:
                    finding.suppressed = True
                    finding.reason = reason
                findings.append(finding)
        for rule in self.rules:
            rule.finalize(run_ctx)
        for finding in run_ctx.findings:
            # finalize()-phase findings land on scanned files too
            # (e.g. env-registry's per-use reports) — the suppression
            # contract must hold for them as well.
            sup = suppressions.get(finding.path)
            if sup is not None:
                reason = sup.match(finding)
                if reason is not None:
                    finding.suppressed = True
                    finding.reason = reason
            findings.append(finding)
        findings.sort(key=lambda f: (f.path, f.line, f.rule))
        return RunResult(root=self.root, files_scanned=len(files),
                         rule_ids=sorted(self.rule_ids),
                         findings=findings)


@dataclasses.dataclass
class RunResult:
    root: str
    files_scanned: int
    rule_ids: List[str]
    findings: List[Finding]

    @property
    def unsuppressed(self) -> List[Finding]:
        return [f for f in self.findings if not f.suppressed]

    def stats(self) -> Dict[str, Dict[str, Any]]:
        """Per-rule finding/suppression counts with the suppression
        reasons — `xsky lint --stats` renders this so suppression debt
        is visible instead of silently accumulating."""
        out: Dict[str, Dict[str, Any]] = {}
        for f in self.findings:
            row = out.setdefault(
                f.rule, {'findings': 0, 'suppressed': 0, 'reasons': []})
            if f.suppressed:
                row['suppressed'] += 1
                row['reasons'].append(
                    f'{f.path}:{f.line}: {f.reason}')
            else:
                row['findings'] += 1
        return out

    def to_json(self) -> Dict[str, Any]:
        # `version` is the output-schema version: bump it when a field
        # changes meaning so the CI job and downstream tooling can
        # parse the payload stably. v2 added version/abs_path/stats.
        return {
            'version': 2,
            'root': self.root,
            'files_scanned': self.files_scanned,
            'rules': self.rule_ids,
            'findings': [
                {**f.to_json(),
                 'abs_path': os.path.join(self.root, f.path)}
                for f in self.findings],
            'suppressed_count': sum(f.suppressed for f in self.findings),
            'unsuppressed_count': len(self.unsuppressed),
            'stats': self.stats(),
        }


def lint_paths(root: str, paths: Iterable[str],
               rule_ids: Optional[Iterable[str]] = None,
               parse: Callable[..., ast.Module] = ast.parse,
               focus: Optional[Set[str]] = None,
               cache_dir: Optional[str] = None) -> RunResult:
    """Convenience wrapper: run (a subset of) the registered rules
    over `paths` under `root`. The API tests and the migrated
    test_chaos.py wrappers call. ``cache_dir`` enables the
    mtime+size-keyed AST cache (off by default for API callers; the
    CLI turns it on)."""
    from tools.xskylint.rules import all_rules
    rules = all_rules()
    if rule_ids is not None:
        wanted = set(rule_ids)
        unknown = wanted - {r.id for r in rules}
        if unknown:
            raise ValueError(f'unknown rule id(s): {sorted(unknown)}')
        # Companion closure: a rule whose soundness depends on a
        # verifier rule pulls it in (a `--rule never-raise` run must
        # not accept arm calls nothing verifies).
        by_id = {r.id: r for r in rules}
        queue = list(wanted)
        while queue:
            for companion in by_id[queue.pop()].companions:
                if companion not in wanted:
                    wanted.add(companion)
                    queue.append(companion)
        rules = [r for r in rules if r.id in wanted]
    return LintEngine(root, rules, parse=parse,
                      cache_dir=cache_dir).run(paths, focus=focus)


# ---- suppression-debt baseline ---------------------------------------------

BASELINE_REL_PATH = 'tools/xskylint/suppressions_baseline.json'


def baseline_counts(result: 'RunResult') -> Dict[str, int]:
    return {rule: row['suppressed']
            for rule, row in sorted(result.stats().items())
            if row['suppressed']}


def write_baseline(root: str, result: 'RunResult') -> str:
    """(Re)generate the checked-in suppression-count baseline."""
    counts = baseline_counts(result)
    path = os.path.join(root, BASELINE_REL_PATH)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    payload = {
        'comment': 'Suppression-debt ratchet: CI fails when a rule\'s '
                   'suppression count exceeds this baseline. Fix '
                   'findings in-code; if a suppression is genuinely '
                   'warranted, update this file IN THE SAME DIFF '
                   '(python -m tools.xskylint --write-baseline).',
        'total': sum(counts.values()),
        'rules': counts,
    }
    with open(path, 'w', encoding='utf-8') as f:
        json.dump(payload, f, indent=2, sort_keys=True)
        f.write('\n')
    return path


def check_baseline(root: str, result: 'RunResult'
                   ) -> "tuple[bool, List[str]]":
    """The ratchet: growth beyond the checked-in counts fails;
    shrinkage passes with a nudge to ratchet the baseline down."""
    path = os.path.join(root, BASELINE_REL_PATH)
    try:
        with open(path, encoding='utf-8') as f:
            baseline = json.load(f)
    except (OSError, ValueError) as e:
        return False, [f'suppression baseline unreadable at '
                       f'{BASELINE_REL_PATH}: {e} — regenerate with '
                       '--write-baseline']
    base_rules: Dict[str, int] = baseline.get('rules', {})
    current = baseline_counts(result)
    messages: List[str] = []
    grew = False
    for rule in sorted(set(current) | set(base_rules)):
        cur, base = current.get(rule, 0), base_rules.get(rule, 0)
        if cur > base:
            grew = True
            messages.append(
                f'suppression debt grew for {rule}: {cur} > baseline '
                f'{base} — fix the finding in-code, or update '
                f'{BASELINE_REL_PATH} in the same diff with the '
                'justification')
        elif cur < base:
            messages.append(
                f'note: {rule} suppressions shrank ({cur} < baseline '
                f'{base}) — ratchet the baseline down with '
                '--write-baseline')
    return not grew, messages


def changed_files(root: str,
                  base: Optional[str] = None) -> Optional[Set[str]]:
    """Repo-relative .py files differing from the merge-base (plus
    untracked ones) — the --changed focus set. None when git is
    unavailable or errors (callers fall back to a full lint rather
    than green-lighting blind)."""
    import subprocess

    def git(*args: str) -> Optional[str]:
        try:
            proc = subprocess.run(
                ['git', '-C', root] + list(args), capture_output=True,
                text=True, timeout=30, check=False)
        except (OSError, subprocess.TimeoutExpired):
            return None
        return proc.stdout if proc.returncode == 0 else None

    if base is None:
        for candidate in ('origin/main', 'origin/master', 'main',
                          'master'):
            out = git('merge-base', 'HEAD', candidate)
            if out and out.strip():
                base = out.strip()
                break
        else:
            base = 'HEAD'
    else:
        # An explicit --base is a merge-base *ref*, same as the
        # default candidates: diff against merge-base(HEAD, ref), not
        # the ref tip, or files changed on an advanced upstream would
        # count as "changed" here. Fall back to the raw ref when
        # merge-base fails (detached SHAs outside the history).
        out = git('merge-base', 'HEAD', base)
        if out and out.strip():
            base = out.strip()
    diff = git('diff', '--name-only', base)
    if diff is None:
        return None
    diff_names = [n.strip().replace(os.sep, '/')
                  for n in diff.splitlines() if n.strip()]
    # `git diff --name-only` prints toplevel-relative paths; the
    # engine matches root-relative ones. Re-anchor when --root is a
    # subdirectory of the checkout (changes outside it drop out — they
    # are outside the linted tree by definition). `ls-files` below is
    # already cwd-relative thanks to -C root, so it needs no fixup.
    top = git('rev-parse', '--show-toplevel')
    if top and top.strip():
        rel = os.path.relpath(os.path.abspath(root),
                              top.strip()).replace(os.sep, '/')
        if rel not in ('.', ''):
            prefix = rel + '/'
            diff_names = [n[len(prefix):] for n in diff_names
                          if n.startswith(prefix)]
    untracked = git('ls-files', '--others', '--exclude-standard')
    names = diff_names + [n.strip().replace(os.sep, '/')
                          for n in (untracked or '').splitlines()]
    return {n for n in names if n.endswith('.py')}


def _default_root() -> str:
    """The repo root: cwd when it holds the tree, else up from here."""
    cwd = os.getcwd()
    if os.path.isdir(os.path.join(cwd, 'skypilot_tpu')):
        return cwd
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog='xskylint',
        description='Single-pass static analysis for the xsky tree.')
    parser.add_argument('paths', nargs='*',
                        default=['skypilot_tpu', 'tools'],
                        help='files or directories relative to --root '
                             '(default: skypilot_tpu tools)')
    parser.add_argument('--root', default=None,
                        help='repo root (default: auto-detected)')
    parser.add_argument('--rule', action='append', dest='rules',
                        help='run only this rule id (repeatable)')
    parser.add_argument('--json', action='store_true', dest='as_json',
                        help='machine-readable output (schema-'
                             'versioned, absolute paths included)')
    parser.add_argument('--changed', action='store_true',
                        help='per-file rules only on files differing '
                             'from the merge-base; whole-program '
                             'rules still see the full tree')
    parser.add_argument('--base', default=None,
                        help='merge-base ref for --changed (default: '
                             'merge-base with origin/main)')
    parser.add_argument('--stats', action='store_true', dest='stats',
                        help='per-rule finding + suppression counts '
                             '(with reasons)')
    parser.add_argument('--why', metavar='RULE:FILE:LINE', default=None,
                        help='explain one finding: re-run that rule '
                             'and print the shortest entry->violation '
                             'call chain (lock-order: the cycle\'s '
                             'edge witnesses)')
    parser.add_argument('--no-cache', action='store_true',
                        help='disable the mtime+size-keyed AST cache '
                             '(.xskylint_cache/)')
    parser.add_argument('--check-baseline', action='store_true',
                        help='fail when per-rule suppression counts '
                             'exceed the checked-in baseline '
                             '(suppression-debt ratchet)')
    parser.add_argument('--write-baseline', action='store_true',
                        help='regenerate the suppression-count '
                             'baseline from this run')
    parser.add_argument('--list-rules', action='store_true',
                        help='print the rule catalog and exit')
    args = parser.parse_args(argv)

    if args.list_rules:
        from tools.xskylint.rules import all_rules
        for rule in all_rules():
            print(f'{rule.id}: {rule.rationale}')
        return 0

    root = os.path.abspath(args.root) if args.root else _default_root()
    cache_dir = None
    if not args.no_cache and \
            os.environ.get('XSKY_LINT_CACHE', '1') != '0':
        cache_dir = os.environ.get(
            'XSKY_LINT_CACHE_DIR',
            os.path.join(root, '.xskylint_cache'))
    if args.why:
        return _explain_why(root, args.why, cache_dir)
    if args.write_baseline or args.check_baseline:
        # The baseline is a FULL-TREE statement: a --changed/--rule/
        # subtree run undercounts suppressions, which would gut a
        # written baseline and let growth slip past a check. Refuse
        # before doing any work.
        if args.changed or args.rules or \
                sorted(args.paths) != ['skypilot_tpu', 'tools']:
            print('xskylint: --write-baseline/--check-baseline need '
                  'a full default run (no --changed/--rule/path '
                  'subset) — the baseline counts the whole tree',
                  file=sys.stderr)
            return 2
    focus = None
    if args.changed:
        focus = changed_files(root, args.base)
        if focus is None:
            # git unavailable: a blind green run would defeat the CI
            # gate — fall back to the full lint and say so.
            print('xskylint: --changed could not consult git; '
                  'linting everything', file=sys.stderr)
        elif not focus:
            print('xskylint: no changed python files')
            return 0
    try:
        result = lint_paths(root, args.paths, rule_ids=args.rules,
                            focus=focus, cache_dir=cache_dir)
    except (ValueError, FileNotFoundError) as e:
        print(f'xskylint: {e}', file=sys.stderr)
        return 2

    baseline_rc = 0
    if args.write_baseline:
        path = write_baseline(root, result)
        print(f'xskylint: baseline written to {path}',
              file=sys.stderr)
    elif args.check_baseline:
        ok, messages = check_baseline(root, result)
        # stderr so `--json | tee` output stays parseable.
        for message in messages:
            print(f'xskylint: {message}', file=sys.stderr)
        if not ok:
            baseline_rc = 1

    if args.as_json:
        print(json.dumps(result.to_json(), indent=2))
    else:
        for finding in result.findings:
            if not finding.suppressed:
                print(finding.render())
        if args.stats:
            _print_stats(result)
        n = len(result.unsuppressed)
        suppressed = sum(f.suppressed for f in result.findings)
        print(f'xskylint: {result.files_scanned} files, '
              f'{n} finding(s), {suppressed} suppressed')
    return 1 if result.unsuppressed else baseline_rc


def _explain_why(root: str, spec: str,
                 cache_dir: Optional[str]) -> int:
    """``--why rule:file:line``: focused re-run of ONE rule, printing
    the finding plus its interprocedural evidence (the shortest
    entry→violation call chain / the lock cycle's edge witnesses) so
    builders can act without reading the engine."""
    try:
        head, line_s = spec.rsplit(':', 1)
        rule, path = head.split(':', 1)
        line = int(line_s)
    except ValueError:
        print('xskylint: --why wants RULE:FILE:LINE '
              '(e.g. hot-path-purity:skypilot_tpu/agent/'
              'telemetry.py:221)', file=sys.stderr)
        return 2
    path = path.replace(os.sep, '/')
    # The default tree, minus parts a fixture checkout may not have.
    lint_roots = [p for p in ('skypilot_tpu', 'tools')
                  if os.path.isdir(os.path.join(root, p))] or ['.']
    try:
        result = lint_paths(root, lint_roots,
                            rule_ids=[rule], cache_dir=cache_dir)
    except (ValueError, FileNotFoundError) as e:
        print(f'xskylint: {e}', file=sys.stderr)
        return 2
    matches = [f for f in result.findings
               if f.rule == rule and f.path == path and f.line == line]
    if not matches:
        near = [f for f in result.findings
                if f.rule == rule and f.path == path]
        print(f'xskylint: no {rule} finding at {path}:{line}'
              + (f' (rule fires in that file at line(s) '
                 f'{sorted({f.line for f in near})})' if near else ''),
              file=sys.stderr)
        return 1
    for finding in matches:
        print(finding.render())
        for entry in finding.detail or ['(no interprocedural detail '
                                        'recorded for this rule)']:
            print(f'    {entry}')
    return 0


def _print_stats(result: 'RunResult') -> None:
    stats = result.stats()
    if not stats:
        print('xskylint: no findings, no active suppressions')
        return
    width = max(len(r) for r in stats)
    print(f'{"rule".ljust(width)}  findings  suppressed')
    for rule in sorted(stats):
        row = stats[rule]
        print(f'{rule.ljust(width)}  '
              f'{str(row["findings"]).rjust(8)}  '
              f'{str(row["suppressed"]).rjust(10)}')
        for reason in row['reasons']:
            print(f'{" " * width}    - {reason}')
