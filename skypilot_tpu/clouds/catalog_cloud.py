"""Catalog-backed feasibility shared by concrete clouds.

The reference re-implements feasibility per cloud against pandas frames
(sky/clouds/gcp.py etc.); here the logic is factored once over
``CatalogEntry`` rows, and concrete clouds only override cloud-specific
bits (deploy variables, credentials, feature limits).
"""
from __future__ import annotations

import typing
from typing import Any, Dict, Iterator, List, Optional, Tuple

from skypilot_tpu import catalog
from skypilot_tpu.clouds import cloud as cloud_lib
from skypilot_tpu.utils import tpu_topology

if typing.TYPE_CHECKING:
    from skypilot_tpu import resources as resources_lib


def _spec_ok(spec: Optional[str], actual: float) -> bool:
    """Check a '4' / '4+' / None cpus-or-memory spec against a value."""
    if spec is None:
        return True
    s = str(spec).strip()
    if s.endswith('+'):
        return actual >= float(s[:-1])
    return actual == float(s)


class CatalogCloud(cloud_lib.Cloud):
    """Cloud whose offerings come entirely from its catalog CSV."""

    def _entries(self) -> List[catalog.CatalogEntry]:
        return catalog.common.load_catalog(self.name)

    # ---- placement ----

    def regions_with_offering(self, instance_type: str,
                              accelerators: Optional[Dict[str, Any]],
                              use_spot: bool, region: Optional[str],
                              zone: Optional[str]) -> List[cloud_lib.Region]:
        entries = self._match_entries(instance_type, accelerators, region,
                                      zone)
        if use_spot:
            entries = [e for e in entries if e.spot_price > 0]
        by_region: Dict[str, List[str]] = {}
        for e in entries:
            by_region.setdefault(e.region, [])
            if e.zone not in by_region[e.region]:
                by_region[e.region].append(e.zone)
        return [
            cloud_lib.Region(r, sorted(zs)) for r, zs in sorted(
                by_region.items(),
                key=lambda kv: min((e.spot_price if use_spot else e.price)
                                   for e in entries if e.region == kv[0]))
        ]

    def zones_provision_loop(self, region: str, num_nodes: int,
                             instance_type: str,
                             accelerators: Optional[Dict[str, Any]] = None,
                             use_spot: bool = False) -> Iterator[List[str]]:
        for r in self.regions_with_offering(instance_type, accelerators,
                                            use_spot, region, None):
            for z in r.zones:
                yield [z]

    def _match_entries(self, instance_type: str,
                       accelerators: Optional[Dict[str, Any]],
                       region: Optional[str],
                       zone: Optional[str]) -> List[catalog.CatalogEntry]:
        out = []
        acc_item: Optional[Tuple[str, float]] = None
        if accelerators:
            acc_item = next(iter(accelerators.items()))
        for e in self._entries():
            if instance_type and e.instance_type != instance_type:
                continue
            if acc_item is not None:
                name, count = acc_item
                if e.accelerator_name.lower() != name.lower():
                    continue
                if e.accelerator_count != count:
                    continue
            if region is not None and e.region != region:
                continue
            if zone is not None and e.zone != zone:
                continue
            out.append(e)
        return out

    def region_of_zone(self, zone: str) -> str:
        for e in self._entries():
            if e.zone == zone:
                return e.region
        return super().region_of_zone(zone)

    # ---- default instance type ----

    _DEFAULT_CPUS = '4+'

    def get_default_instance_type(
            self, cpus: Optional[str] = None,
            memory: Optional[str] = None) -> Optional[str]:
        cpus = cpus or self._DEFAULT_CPUS
        candidates = [
            e for e in self._entries()
            if not e.accelerator_name and e.instance_type and
            _spec_ok(cpus, e.vcpus) and _spec_ok(memory, e.memory_gib)
        ]
        if not candidates:
            return None
        return min(candidates, key=lambda e: e.price).instance_type

    # ---- feasibility ----

    def get_feasible_launchable_resources(
        self, resources: 'resources_lib.Resources'
    ) -> Tuple[List['resources_lib.Resources'], List[str]]:
        acc = resources.accelerators  # normalized {name: count} or None
        fuzzy: List[str] = []
        candidates: List['resources_lib.Resources'] = []

        if acc is None:
            if resources.instance_type:
                if self.instance_type_exists(resources.instance_type):
                    candidates = [resources.copy(cloud=self.name)]
            else:
                default = self.get_default_instance_type(
                    resources.cpus, resources.memory)
                if default is not None:
                    candidates = [
                        resources.copy(cloud=self.name, instance_type=default)
                    ]
            return self._finish(resources, candidates), fuzzy

        name, count = next(iter(acc.items()))
        entries = self._match_entries('', {name: count}, resources.region,
                                      resources.zone)
        if not entries:
            # Fuzzy hints: for TPUs match on the generation prefix so
            # 'tpu-v5e-16' suggests the sizes this cloud actually offers.
            needle = name.lower().split(':')[0]
            if tpu_topology.is_tpu(needle):
                needle = needle.rsplit('-', 1)[0]
            seen = set()
            for e in self._entries():
                if e.accelerator_name and needle in \
                        e.accelerator_name.lower():
                    key = f'{e.accelerator_name}:{e.accelerator_count:g}'
                    if key not in seen:
                        seen.add(key)
                        fuzzy.append(key)
            return [], sorted(fuzzy)

        # Respect cpus/memory specs for accelerator-bearing instance types.
        entries = [
            e for e in entries if _spec_ok(resources.cpus, e.vcpus) and
            _spec_ok(resources.memory, e.memory_gib)
        ]
        seen_itypes = set()
        for e in sorted(entries, key=lambda e: (e.price == 0, e.price)):
            if e.instance_type in seen_itypes:
                continue
            seen_itypes.add(e.instance_type)
            candidates.append(
                resources.copy(cloud=self.name,
                               instance_type=e.instance_type or None))
        return self._finish(resources, candidates), fuzzy

    def _finish(self, request, candidates):
        # Note: 0.0-priced offerings (unpublished pricing, e.g. v6e in some
        # regions — see fetch_gcp) stay launchable for both spot and
        # on-demand; the optimizer ranks them after all known prices.
        del request
        return candidates

    # ---- TPU helpers ----

    def tpu_topology_of(self, resources) -> Optional[tpu_topology.SliceTopology]:
        if resources.accelerators is None:
            return None
        name = next(iter(resources.accelerators))
        if not tpu_topology.is_tpu(name):
            return None
        return tpu_topology.parse(name, resources.accelerator_args)
