"""TLS socket wrapping shared by the API server and the serve LB."""
from __future__ import annotations

import os
import ssl
from typing import Optional


def wrap_server_socket(server, certfile: str,
                       keyfile: Optional[str]) -> None:
    """Wrap a ThreadingHTTPServer's listening socket for TLS.

    ``do_handshake_on_connect=False`` is load-bearing: ``accept()``
    runs in the server's single ``serve_forever`` thread (only request
    HANDLING is dispatched to workers), so a handshake there would let
    one stalled client — open TCP, never send a ClientHello — freeze
    every other connection. Deferred, the handshake happens on first
    read inside the per-connection handler thread, where a stalled
    client costs one worker like any plain-HTTP slowloris.
    """
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(
        certfile=os.path.expanduser(certfile),
        keyfile=os.path.expanduser(keyfile) if keyfile else None)
    server.socket = ctx.wrap_socket(server.socket, server_side=True,
                                    do_handshake_on_connect=False)
