"""OAuth device-flow login + per-workspace authz (VERDICT r3 #7).

Twin coverage of sky/client/oauth.py (device flow), the server OAuth
middlewares (sky/server/server.py:176-296 — here: OAuth bearer tokens
validated at the API boundary with auto-provisioning), and workspace
membership scoping (sky/users/rbac.py workspace policies).
"""
import base64
import io
import json
import urllib.error
import urllib.request

import pytest

from skypilot_tpu import state
from skypilot_tpu.server import app as server_app
from skypilot_tpu.server import requests_db
from skypilot_tpu.users import core as users_core
from skypilot_tpu.users import oauth as oauth_lib
from skypilot_tpu.workspaces import core as workspaces_core


@pytest.fixture(autouse=True)
def _config_isolation(monkeypatch):
    """Tests here point XSKY_CONFIG at tmp files and reload; restore
    the env FIRST, then reload, so no tmp config leaks into later
    modules (the loader caches process-wide)."""
    yield
    monkeypatch.undo()
    from skypilot_tpu import config as config_lib
    config_lib.reload_config()


@pytest.fixture
def clean_state(monkeypatch, tmp_path):
    monkeypatch.setenv('XSKY_STATE_DB', str(tmp_path / 'state.db'))
    state.reset_for_test()
    yield
    state.reset_for_test()


class _FakeIdP:
    """Scripted IdP: device-code + token + userinfo endpoints."""

    def __init__(self):
        self.pending_polls = 2   # approve after N polls
        self.tokens = {'oat_good': {'preferred_username': 'ada',
                                    'email': 'ada@example.com',
                                    'sub': 'idp|1234'}}
        self.requests = []

    def __call__(self, req, timeout=None):
        url = req.full_url
        self.requests.append(url)
        if '/oauth/device/code' in url:
            return _resp({'device_code': 'dev123',
                          'user_code': 'ABCD-EFGH',
                          'verification_uri': 'https://idp/activate',
                          'interval': 0, 'expires_in': 60})
        if '/oauth/token' in url:
            fields = urllib.parse.parse_qs(
                (req.data or b'').decode())
            if fields.get('grant_type') == ['refresh_token']:
                if fields.get('refresh_token') == ['rt_good']:
                    return _resp({'access_token': 'oat_refreshed',
                                  'refresh_token': 'rt_rotated',
                                  'token_type': 'Bearer'})
                raise _http_error(url, 400, {'error': 'invalid_grant'})
            if self.pending_polls > 0:
                self.pending_polls -= 1
                raise _http_error(url, 400, {
                    'error': 'authorization_pending'})
            return _resp({'access_token': 'oat_good',
                          'refresh_token': 'rt_good',
                          'token_type': 'Bearer'})
        if '/userinfo' in url:
            token = dict(req.header_items()).get(
                'Authorization', '').removeprefix('Bearer ')
            info = self.tokens.get(token)
            if info is None:
                raise _http_error(url, 401, {'error': 'invalid_token'})
            return _resp(info)
        raise AssertionError(f'unexpected IdP url {url}')


def _resp(payload):
    class _R:
        status = 200

        def read(self):
            return json.dumps(payload).encode()

        def __enter__(self):
            return self

        def __exit__(self, *exc):
            return False
    return _R()


def _http_error(url, code, payload):
    return urllib.error.HTTPError(
        url, code, 'err', {}, io.BytesIO(json.dumps(payload).encode()))


@pytest.fixture
def idp(monkeypatch):
    monkeypatch.setenv('XSKY_OAUTH_ISSUER', 'https://idp.example.com')
    monkeypatch.setenv('XSKY_OAUTH_CLIENT_ID', 'xsky-cli')
    oauth_lib.clear_userinfo_cache()
    fake = _FakeIdP()
    yield fake
    oauth_lib.clear_userinfo_cache()


class TestDeviceFlow:

    def test_full_device_login(self, idp):
        flow = oauth_lib.start_device_flow(opener=idp)
        assert flow['user_code'] == 'ABCD-EFGH'
        token = oauth_lib.poll_for_token(
            flow['device_code'], interval=0, opener=idp,
            sleep=lambda s: None)
        assert token == 'oat_good'
        # Pending polls actually happened before approval.
        assert sum('/oauth/token' in u for u in idp.requests) == 3

    def test_denied_login_raises(self, idp):
        idp.pending_polls = 0

        def deny(req, timeout=None):
            if '/oauth/token' in req.full_url:
                raise _http_error(req.full_url, 400,
                                  {'error': 'access_denied'})
            return idp(req, timeout)

        flow = oauth_lib.start_device_flow(opener=idp)
        with pytest.raises(oauth_lib.OAuthError, match='access_denied'):
            oauth_lib.poll_for_token(flow['device_code'], interval=0,
                                     opener=deny, sleep=lambda s: None)

    def test_disabled_without_issuer(self, monkeypatch):
        monkeypatch.delenv('XSKY_OAUTH_ISSUER', raising=False)
        assert not oauth_lib.enabled()
        with pytest.raises(oauth_lib.OAuthError):
            oauth_lib.start_device_flow()

    def test_device_flow_returns_refresh_token(self, idp):
        idp.pending_polls = 0
        flow = oauth_lib.start_device_flow(opener=idp)
        tokens = oauth_lib.poll_for_tokens(flow['device_code'],
                                           interval=0, opener=idp,
                                           sleep=lambda s: None)
        assert tokens['access_token'] == 'oat_good'
        assert tokens['refresh_token'] == 'rt_good'

    def test_refresh_access_token(self, idp):
        tokens = oauth_lib.refresh_access_token('rt_good', opener=idp)
        assert tokens['access_token'] == 'oat_refreshed'
        assert tokens['refresh_token'] == 'rt_rotated'
        with pytest.raises(oauth_lib.OAuthError, match='invalid_grant'):
            oauth_lib.refresh_access_token('rt_revoked', opener=idp)


class TestOAuthBearer:

    def test_access_token_autoprovisions_user(self, clean_state, idp,
                                              monkeypatch):
        monkeypatch.setattr(
            oauth_lib, 'validate_access_token',
            lambda token: idp.tokens.get(token) and
            dict(idp.tokens[token], name='ada'))
        assert state.get_user('ada') is None
        user = users_core.authenticate_bearer('Bearer oat_good')
        assert user is not None and user['name'] == 'ada'
        assert user['role'] == 'user'
        # Second call reuses the provisioned account.
        assert users_core.authenticate_bearer(
            'Bearer oat_good')['name'] == 'ada'
        # Invalid tokens stay anonymous.
        assert users_core.authenticate_bearer('Bearer oat_bad') is None
        # OAuth-only accounts have no usable password.
        assert users_core.verify_password('ada', '') is None

    def test_oauth_cannot_assume_local_account(self, clean_state, idp,
                                               monkeypatch):
        """An IdP user whose preferred_username collides with a LOCAL
        (password) account — e.g. 'admin' — must never authenticate as
        it (code-review r4: OIDC says preferred_username is not an
        identifier)."""
        users_core.create_user('admin', 'pw', role='admin')
        idp.tokens['oat_evil'] = {'preferred_username': 'admin',
                                  'sub': 'idp|9999'}
        monkeypatch.setattr(
            oauth_lib, 'validate_access_token',
            lambda token: idp.tokens.get(token) and dict(
                idp.tokens[token],
                name=idp.tokens[token]['preferred_username']))
        assert users_core.authenticate_bearer('Bearer oat_evil') is None

    def test_oauth_subject_binding(self, clean_state, idp, monkeypatch):
        """Two IdP subjects sharing a display name are different
        principals: the second must not inherit the first's account."""
        monkeypatch.setattr(
            oauth_lib, 'validate_access_token',
            lambda token: idp.tokens.get(token) and dict(
                idp.tokens[token],
                name=idp.tokens[token]['preferred_username']))
        assert users_core.authenticate_bearer(
            'Bearer oat_good')['name'] == 'ada'
        idp.tokens['oat_other'] = {'preferred_username': 'ada',
                                   'sub': 'idp|5678'}
        assert users_core.authenticate_bearer('Bearer oat_other') is None

    def test_oauth_disabled_rejects_foreign_bearer(self, clean_state,
                                                   monkeypatch):
        monkeypatch.delenv('XSKY_OAUTH_ISSUER', raising=False)
        assert users_core.authenticate_bearer('Bearer oat_good') is None

    def test_userinfo_cache(self, clean_state, idp):
        calls = sum('/userinfo' in u for u in idp.requests)
        info = oauth_lib.validate_access_token('oat_good', opener=idp)
        assert info['name'] == 'ada'
        oauth_lib.validate_access_token('oat_good', opener=idp)
        assert sum('/userinfo' in u
                   for u in idp.requests) == calls + 1   # cached


@pytest.fixture
def authz_server(clean_state, monkeypatch, tmp_path):
    monkeypatch.setenv('XSKY_SERVER_DB', str(tmp_path / 'requests.db'))
    monkeypatch.setenv('XSKY_REQUIRE_AUTH', '1')
    requests_db.reset_for_test()
    users_core.create_user('root', 'rootpw', role='admin')
    users_core.create_user('member', 'pw', role='user')
    users_core.create_user('outsider', 'pw', role='user')
    workspaces_core.create_workspace('team-a')
    workspaces_core.add_member('team-a', 'member')
    server, port = server_app.run_in_thread()
    yield f'http://127.0.0.1:{port}'
    server.shutdown()
    requests_db.reset_for_test()


def _post(url, verb, body=None, user=None, password=None):
    data = json.dumps(body or {}).encode()
    req = urllib.request.Request(f'{url}/api/{verb}', data=data,
                                 method='POST')
    if user is not None:
        token = base64.b64encode(f'{user}:{password}'.encode()).decode()
        req.add_header('Authorization', f'Basic {token}')
    with urllib.request.urlopen(req) as resp:
        return resp.status, json.loads(resp.read())


_TASK = {'task': {'name': 't', 'run': 'echo hi'}, 'dryrun': True}


class TestWorkspaceAuthz:

    def test_non_member_denied_launch(self, authz_server):
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(authz_server, 'launch',
                  dict(_TASK, workspace='team-a'),
                  user='outsider', password='pw')
        assert e.value.code == 403
        assert 'not a member' in e.value.read().decode()

    def test_member_allowed(self, authz_server):
        code, payload = _post(authz_server, 'launch',
                              dict(_TASK, workspace='team-a'),
                              user='member', password='pw')
        assert code == 200 and 'request_id' in payload

    def test_admin_allowed_everywhere(self, authz_server):
        code, _ = _post(authz_server, 'launch',
                        dict(_TASK, workspace='team-a'),
                        user='root', password='rootpw')
        assert code == 200

    def test_default_workspace_open(self, authz_server):
        code, _ = _post(authz_server, 'launch', dict(_TASK),
                        user='outsider', password='pw')
        assert code == 200

    def test_cluster_verbs_scoped_by_cluster_workspace(
            self, authz_server):
        state.add_or_update_cluster('c-team', {'h': 1},
                                    workspace='team-a')
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(authz_server, 'down', {'cluster_name': 'c-team'},
                  user='outsider', password='pw')
        assert e.value.code == 403
        code, _ = _post(authz_server, 'down',
                        {'cluster_name': 'c-team'},
                        user='member', password='pw')
        assert code == 200

    def test_launch_reuse_scoped_by_cluster_workspace(self,
                                                      authz_server):
        """Naming an existing private-workspace cluster in `launch`
        (with no workspace field) must be authorized against the
        CLUSTER's workspace — the reuse path would otherwise run the
        outsider's code on it (code-review r4 finding)."""
        state.add_or_update_cluster('c-team', {'h': 1},
                                    workspace='team-a')
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(authz_server, 'launch',
                  dict(_TASK, cluster_name='c-team'),
                  user='outsider', password='pw')
        assert e.value.code == 403

    def test_workspace_reads_member_scoped(self, authz_server):
        for verb, body in (
                ('workspaces.members', {'workspace': 'team-a'}),
                ('workspaces.get_config', {'workspace': 'team-a'})):
            with pytest.raises(urllib.error.HTTPError) as e:
                _post(authz_server, verb, body,
                      user='outsider', password='pw')
            assert e.value.code == 403, verb
            code, _ = _post(authz_server, verb, body,
                            user='member', password='pw')
            assert code == 200, verb

    def test_membership_admin_only(self, authz_server):
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(authz_server, 'workspaces.add_member',
                  {'workspace': 'team-a', 'user_name': 'outsider'},
                  user='member', password='pw')
        assert e.value.code == 403
        code, _ = _post(authz_server, 'workspaces.add_member',
                        {'workspace': 'team-a', 'user_name': 'outsider'},
                        user='root', password='rootpw')
        assert code == 200


class TestClientAutoRefresh:

    def test_client_refreshes_expired_token_on_401(
            self, authz_server, idp, monkeypatch, tmp_path):
        """A 401 (expired access token) triggers one refresh-token
        grant, a retry with the new bearer, and persists the rotated
        tokens — no fresh device login (advisor r4)."""
        import yaml

        from skypilot_tpu import config as config_lib
        from skypilot_tpu.client import remote_client
        # A real server-side token the refresh will rotate onto.
        from skypilot_tpu.users import core as users_core
        good = users_core.create_token('member', label='cli')['token']
        cfg = tmp_path / 'cfg.yaml'
        cfg.write_text(yaml.safe_dump({'api_server': {
            'endpoint': authz_server, 'token': 'oat_expired',
            'refresh_token': 'rt_good'}}))
        monkeypatch.setenv('XSKY_CONFIG', str(cfg))
        config_lib.reload_config()
        monkeypatch.setattr(
            oauth_lib, 'refresh_access_token',
            lambda rt, opener=None: {'access_token': good,
                                     'refresh_token': 'rt_rotated'}
            if rt == 'rt_good' else (_ for _ in ()).throw(
                oauth_lib.OAuthError('invalid_grant')))
        client = remote_client.RemoteClient(authz_server,
                                            poll_interval_s=0.05,
                                            timeout_s=30)
        assert client.list_api_requests(limit=1) is not None
        # The protected verb path succeeds after the refresh retry.
        client.status()
        saved = yaml.safe_load(cfg.read_text())['api_server']
        assert saved['token'] == good
        assert saved['refresh_token'] == 'rt_rotated'
        config_lib.reload_config()


class TestRefreshLifecycle:

    def test_refresh_rearms_after_success(self, monkeypatch, tmp_path):
        """A successful refresh must re-arm (long poll loops outlive
        one access token); a failed one latches off (code-review r5)."""
        import yaml

        from skypilot_tpu import config as config_lib
        from skypilot_tpu.client import remote_client
        cfg = tmp_path / 'cfg.yaml'
        cfg.write_text(yaml.safe_dump({'api_server': {
            'refresh_token': 'rt_good'}}))
        monkeypatch.setenv('XSKY_CONFIG', str(cfg))
        monkeypatch.setenv('XSKY_OAUTH_ISSUER', 'https://idp')
        config_lib.reload_config()
        calls = []
        monkeypatch.setattr(
            oauth_lib, 'refresh_access_token',
            lambda rt, opener=None: (calls.append(rt),
                                     {'access_token': f't{len(calls)}'}
                                     )[1])
        client = remote_client.RemoteClient.__new__(
            remote_client.RemoteClient)

        class _H:
            headers = {}
        client._client = _H()
        assert client._try_oauth_refresh()
        assert client._try_oauth_refresh()   # re-armed after success
        assert len(calls) == 2
        monkeypatch.setattr(
            oauth_lib, 'refresh_access_token',
            lambda rt, opener=None: (_ for _ in ()).throw(
                oauth_lib.OAuthError('revoked')))
        assert not client._try_oauth_refresh()
        assert not client._try_oauth_refresh()   # latched off
        config_lib.reload_config()

    def test_static_login_clears_stale_refresh_token(self, monkeypatch,
                                                     tmp_path):
        """Re-login with a static token must drop the old OAuth
        refresh token — it would silently rotate auth back to the
        previous identity on the next 401 (code-review r5)."""
        import yaml

        from skypilot_tpu import config as config_lib
        cfg = tmp_path / 'cfg.yaml'
        cfg.write_text(yaml.safe_dump({'api_server': {
            'endpoint': 'http://old', 'token': 'oat_old',
            'refresh_token': 'rt_old'}}))
        monkeypatch.setenv('XSKY_CONFIG', str(cfg))
        config_lib.reload_config()
        config_lib.update_user_config_section(
            'api_server',
            {'endpoint': 'http://new', 'token': 'xsky_static'},
            remove=('refresh_token',))
        saved = yaml.safe_load(cfg.read_text())['api_server']
        assert saved == {'endpoint': 'http://new',
                         'token': 'xsky_static'}
        config_lib.reload_config()


class TestJobsServeWorkspaceAuthz:
    """Managed-job and serve verbs are scoped to the owning workspace
    (advisor r4: jobs.cancel/jobs.logs/serve.down/serve.logs bypassed
    the per-workspace authz that cluster verbs enforce)."""

    @pytest.fixture(autouse=True)
    def _scoped_dbs(self, monkeypatch, tmp_path):
        monkeypatch.setenv('XSKY_JOBS_DB', str(tmp_path / 'jobs.db'))
        monkeypatch.setenv('XSKY_SERVE_DB', str(tmp_path / 'serve.db'))

    def test_jobs_verbs_scoped_by_job_workspace(self, authz_server):
        from skypilot_tpu.jobs import state as jobs_state
        job_id = jobs_state.add_job('j', {'run': 'echo'},
                                    workspace='team-a')
        for verb in ('jobs.cancel', 'jobs.logs'):
            with pytest.raises(urllib.error.HTTPError) as e:
                _post(authz_server, verb, {'job_id': job_id},
                      user='outsider', password='pw')
            assert e.value.code == 403, verb
            code, _ = _post(authz_server, verb, {'job_id': job_id},
                            user='member', password='pw')
            assert code == 200, verb

    def test_serve_verbs_scoped_by_service_workspace(self, authz_server):
        from skypilot_tpu.serve import state as serve_state
        serve_state.add_service('svc', {'run': 'echo'}, 12345,
                                workspace='team-a')
        for verb, body in (
                ('serve.down', {'service_name': 'svc'}),
                ('serve.logs', {'service_name': 'svc',
                                'replica_id': 0}),
                ('serve.controller_logs', {'service_name': 'svc'}),
                ('serve.update', {'service_name': 'svc',
                                  'task': {'name': 't',
                                           'run': 'echo'}})):
            with pytest.raises(urllib.error.HTTPError) as e:
                _post(authz_server, verb, body,
                      user='outsider', password='pw')
            assert e.value.code == 403, verb
        # A member's submit is accepted (the request itself runs
        # async; authz happens at admission).
        code, _ = _post(authz_server, 'serve.controller_logs',
                        {'service_name': 'svc'},
                        user='member', password='pw')
        assert code == 200

    def test_jobs_launch_scoped_by_requested_workspace(
            self, authz_server):
        body = {'task': {'name': 't', 'run': 'echo'},
                'workspace': 'team-a'}
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(authz_server, 'jobs.launch', body,
                  user='outsider', password='pw')
        assert e.value.code == 403

    def test_serve_up_scoped_by_requested_workspace(self, authz_server):
        body = {'task': {'name': 't', 'run': 'echo',
                         'service': {'readiness_probe': '/'}},
                'workspace': 'team-a'}
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(authz_server, 'serve.up', body,
                  user='outsider', password='pw')
        assert e.value.code == 403

    def test_controllers_inherit_job_service_workspace(
            self, authz_server, monkeypatch):
        """Spawned controllers must pin XSKY_WORKSPACE to the job's/
        service's workspace so the clusters THEY launch land there too
        (code-review r5: otherwise task clusters fall into 'default'
        and stay reachable cross-workspace)."""
        import subprocess as subprocess_lib
        from skypilot_tpu.jobs import scheduler as jobs_scheduler
        from skypilot_tpu.jobs import state as jobs_state
        from skypilot_tpu.serve import core as serve_core
        from skypilot_tpu.serve import state as serve_state
        captured = {}

        class _FakeProc:
            pid = 4242

        def fake_popen(cmd, env=None, **kwargs):
            captured['env'] = env
            return _FakeProc()

        monkeypatch.setattr(subprocess_lib, 'Popen', fake_popen)
        job_id = jobs_state.add_job('j', {'run': 'echo'},
                                    workspace='team-a')
        jobs_scheduler._spawn_controller(job_id)
        assert captured['env']['XSKY_WORKSPACE'] == 'team-a'
        serve_state.add_service('svc3', {'run': 'echo'}, 12347,
                                workspace='team-a')
        serve_core._spawn_controller('svc3')
        assert captured['env']['XSKY_WORKSPACE'] == 'team-a'

    def test_launch_records_active_workspace(self, authz_server):
        from skypilot_tpu.jobs import state as jobs_state
        from skypilot_tpu.serve import state as serve_state
        from skypilot_tpu.workspaces import context as ws_context
        with ws_context.active('team-a'):
            job_id = jobs_state.add_job(
                'j', {'run': 'echo'},
                workspace=ws_context.get_active())
            serve_state.add_service(
                'svc2', {'run': 'echo'}, 12346,
                workspace=ws_context.get_active())
        assert jobs_state.get_job(job_id)['workspace'] == 'team-a'
        assert serve_state.get_service('svc2')['workspace'] == 'team-a'


class TestWorkspaceConfigOverlay:

    def test_overlay_applied_at_launch(self, clean_state, monkeypatch):
        from skypilot_tpu import config as config_lib
        from skypilot_tpu import execution
        from skypilot_tpu import task as task_lib
        from skypilot_tpu.workspaces import context as ws_context
        workspaces_core.create_workspace('team-a')
        workspaces_core.set_config(
            'team-a', {'gcp': {'project_id': 'team-a-project'}})

        seen = {}

        def fake_execute_dag(*args, **kwargs):
            seen['project'] = config_lib.get_nested(
                ('gcp', 'project_id'))
            return None, None

        monkeypatch.setattr(execution, '_execute_dag', fake_execute_dag)
        task = task_lib.Task('t', run='echo hi')
        with ws_context.active('team-a'):
            execution.launch(task, cluster_name='c1')
        assert seen['project'] == 'team-a-project'
        # Outside the workspace the overlay must not leak.
        execution.launch(task, cluster_name='c2')
        assert config_lib.get_nested(('gcp', 'project_id')) is None

    def test_launch_refuses_cross_workspace_reuse(self, clean_state):
        """execution.launch onto an existing cluster from a different
        active workspace must raise, never silently re-home it."""
        from skypilot_tpu import exceptions
        from skypilot_tpu import execution
        from skypilot_tpu import task as task_lib
        from skypilot_tpu.workspaces import context as ws_context
        workspaces_core.create_workspace('team-a')
        state.add_or_update_cluster('c1', {'h': 1}, workspace='team-a')
        task = task_lib.Task('t', run='echo hi')
        with pytest.raises(
                exceptions.ClusterOwnerIdentityMismatchError):
            execution.launch(task, cluster_name='c1')
        with ws_context.active('team-a'), \
                pytest.raises(Exception) as e:
            # Same workspace: passes the guard (fails later on the
            # fake handle, which is fine for this unit).
            execution.launch(task, cluster_name='c1', dryrun=True)
        assert not isinstance(e.value,
                              exceptions.ClusterOwnerIdentityMismatchError)

    def test_get_config_roundtrip(self, clean_state):
        workspaces_core.create_workspace('team-b')
        assert workspaces_core.get_config('team-b') == {}
        workspaces_core.set_config('team-b', {'k': {'v': 1}})
        assert workspaces_core.get_config('team-b') == {'k': {'v': 1}}
        with pytest.raises(ValueError):
            workspaces_core.set_config('team-b', 'not-a-dict')
