"""AWS EC2 provisioner op-set (lean twin of sky/provision/aws/instance.py).

Dispatched by provider name 'aws'. Instances are tracked by the
``xsky-cluster`` tag (idempotent ops, like every provider here); the
head node carries ``xsky-head=true``. Spot capacity goes through
RunInstances' InstanceMarketOptions rather than the legacy spot-request
API. Security groups are left to the account default; open_ports issues
a best-effort AuthorizeSecurityGroupIngress.
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu import sky_logging
from skypilot_tpu.provision import common
from skypilot_tpu.provision.aws import rest

logger = sky_logging.init_logger(__name__)

CLUSTER_TAG = 'xsky-cluster'
HEAD_TAG = 'xsky-head'
NODE_INDEX_TAG = 'xsky-node-index'

# Pluggable transport for tests (scripted fake API).
_transport_factory = rest.Transport


def set_transport_factory(factory) -> None:
    global _transport_factory
    _transport_factory = factory


def _region_of(provider_config: Dict[str, Any]) -> str:
    region = provider_config.get('region')
    if not region:
        raise exceptions.InvalidSkyTpuConfigError(
            'AWS provider_config requires region.')
    return region


def _transport(provider_config: Dict[str, Any]) -> rest.Transport:
    return _transport_factory(_region_of(provider_config))


_STATE_MAP = {
    'pending': 'PENDING',
    'running': 'RUNNING',
    'stopping': 'STOPPING',
    'stopped': 'STOPPED',
    'shutting-down': 'STOPPING',
    'terminated': None,
}


def _describe(t: rest.Transport, cluster_name: str,
              include_terminated: bool = False,
              zone: Optional[str] = None) -> List[Dict[str, Any]]:
    params = {
        'Filter.1.Name': f'tag:{CLUSTER_TAG}',
        'Filter.1.Value.1': cluster_name,
    }
    if not include_terminated:
        for i, s in enumerate(('pending', 'running', 'stopping',
                               'stopped'), 1):
            params[f'Filter.2.Value.{i}'] = s
        params['Filter.2.Name'] = 'instance-state-name'
    if zone is not None:
        params['Filter.3.Name'] = 'availability-zone'
        params['Filter.3.Value.1'] = zone
    out: List[Dict[str, Any]] = []
    reply = t.call('DescribeInstances', params)
    for reservation in rest.as_list(reply.get('reservationSet')):
        out.extend(rest.as_list(reservation.get('instancesSet')))
    return out


def _tags_of(inst: Dict[str, Any]) -> Dict[str, str]:
    return {tag['key']: tag.get('value', '')
            for tag in rest.as_list(inst.get('tagSet'))}


def _state_of(inst: Dict[str, Any]) -> str:
    state = inst.get('instanceState')
    if isinstance(state, dict):
        return str(state.get('name', 'pending'))
    return 'pending'


def run_instances(region: str, zone: Optional[str], cluster_name: str,
                  config: common.ProvisionConfig) -> common.ProvisionRecord:
    node_cfg = config.node_config
    t = _transport(config.provider_config)
    created: List[str] = []
    resumed: List[str] = []
    try:
        # Count only this zone's instances: a failed attempt in another
        # zone must not make this one under-provision (gang clusters
        # cannot be split across zones).
        existing = _describe(t, cluster_name, zone=zone)
        # 'stopping' nodes can be neither started nor replaced: wait for
        # them to settle at 'stopped' (stop-then-relaunch race).
        deadline = time.time() + 300
        while any(_state_of(i) == 'stopping' for i in existing):
            if time.time() > deadline:
                raise exceptions.ProvisionError(
                    f'Instances of {cluster_name!r} stuck in '
                    "'stopping'; retry once they settle.")
            time.sleep(2.0)
            existing = _describe(t, cluster_name, zone=zone)
        # Resume stopped nodes first (restart path).
        if config.resume_stopped_nodes:
            stopped = [i['instanceId'] for i in existing
                       if _state_of(i) == 'stopped']
            if stopped:
                params = {f'InstanceId.{n}': iid
                          for n, iid in enumerate(stopped, 1)}
                t.call('StartInstances', params)
                resumed.extend(stopped)

        have = len(existing)
        missing = config.count - have
        has_head = any(_tags_of(i).get(HEAD_TAG) == 'true'
                       for i in existing)
        for node in range(missing):
            is_head = (not has_head and node == 0)
            params: Dict[str, str] = {
                'ImageId': node_cfg.get('image_id') or
                           'ami-xsky-default',
                'InstanceType': node_cfg['instance_type'],
                'MinCount': '1',
                'MaxCount': '1',
                'TagSpecification.1.ResourceType': 'instance',
                'TagSpecification.1.Tag.1.Key': CLUSTER_TAG,
                'TagSpecification.1.Tag.1.Value': cluster_name,
                'TagSpecification.1.Tag.2.Key': NODE_INDEX_TAG,
                'TagSpecification.1.Tag.2.Value': str(have + node),
            }
            if is_head:
                params['TagSpecification.1.Tag.3.Key'] = HEAD_TAG
                params['TagSpecification.1.Tag.3.Value'] = 'true'
            if zone:
                params['Placement.AvailabilityZone'] = zone
            if node_cfg.get('use_spot'):
                params['InstanceMarketOptions.MarketType'] = 'spot'
            if node_cfg.get('key_name'):
                params['KeyName'] = node_cfg['key_name']
            reply = t.call('RunInstances', params)
            for inst in rest.as_list(reply.get('instancesSet')):
                created.append(inst['instanceId'])
    except rest.AwsApiError as e:
        # Partial gang: terminate what this attempt created so the
        # failover retry (next zone/region) starts from zero instead of
        # leaking instances or splitting the cluster across zones.
        if created:
            try:
                t.call('TerminateInstances',
                       {f'InstanceId.{n}': iid
                        for n, iid in enumerate(created, 1)})
            except rest.AwsApiError as cleanup_err:
                logger.warning(
                    f'Cleanup of partial attempt failed: {cleanup_err}')
        raise rest.classify_error(e, zone) from e
    head = _head_instance_id(t, cluster_name)
    return common.ProvisionRecord(
        provider_name='aws', cluster_name=cluster_name, region=region,
        zone=zone, resumed_instance_ids=resumed,
        created_instance_ids=created, head_instance_id=head)


def _head_instance_id(t: rest.Transport,
                      cluster_name: str) -> Optional[str]:
    instances = _describe(t, cluster_name)
    for inst in instances:
        if _tags_of(inst).get(HEAD_TAG) == 'true':
            return inst['instanceId']
    if instances:
        return sorted(i['instanceId'] for i in instances)[0]
    return None


def wait_instances(region: str, cluster_name: str, state: str,
                   provider_config: Optional[Dict[str, Any]] = None,
                   timeout_s: float = 600.0,
                   poll_interval_s: float = 5.0) -> None:
    """Poll until every instance reaches `state` (EC2 creation is
    asynchronous, unlike the GCP op-wait path).

    An instance from the initial set that disappears or terminates
    mid-wait (spot preempted during boot) raises CapacityError instead
    of silently passing with a shrunken gang.
    """
    t = _transport(provider_config or {'region': region})
    want = state.lower() if state != 'RUNNING' else 'running'
    expected = {i['instanceId'] for i in _describe(t, cluster_name)}
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        instances = _describe(t, cluster_name)
        alive = {i['instanceId'] for i in instances}
        lost = expected - alive
        if lost:
            raise exceptions.CapacityError(
                f'Instance(s) {sorted(lost)} terminated while waiting '
                f'for {state} (spot preemption during boot?).')
        if instances and all(
                _state_of(i) == want for i in instances):
            return
        time.sleep(poll_interval_s)
    raise exceptions.ProvisionError(
        f'Cluster {cluster_name!r} did not reach {state} within '
        f'{timeout_s}s.')


def stop_instances(cluster_name: str,
                   provider_config: Dict[str, Any]) -> None:
    t = _transport(provider_config)
    ids = [i['instanceId'] for i in _describe(t, cluster_name)
           if _state_of(i) in ('pending', 'running')]
    if ids:
        t.call('StopInstances',
               {f'InstanceId.{n}': iid for n, iid in enumerate(ids, 1)})


def terminate_instances(cluster_name: str,
                        provider_config: Dict[str, Any]) -> None:
    t = _transport(provider_config)
    ids = [i['instanceId'] for i in _describe(t, cluster_name)]
    if ids:
        t.call('TerminateInstances',
               {f'InstanceId.{n}': iid for n, iid in enumerate(ids, 1)})


def query_instances(cluster_name: str, provider_config: Dict[str, Any]
                    ) -> Dict[str, Optional[str]]:
    t = _transport(provider_config)
    out: Dict[str, Optional[str]] = {}
    for inst in _describe(t, cluster_name, include_terminated=True):
        out[inst['instanceId']] = _STATE_MAP.get(_state_of(inst))
    return out


def get_cluster_info(region: str, cluster_name: str,
                     provider_config: Dict[str, Any]) -> common.ClusterInfo:
    del region
    t = _transport(provider_config)
    instances: Dict[str, common.InstanceInfo] = {}
    head_id: Optional[str] = None
    rows = _describe(t, cluster_name)

    def _sort_key(inst: Dict[str, Any]):
        idx = _tags_of(inst).get(NODE_INDEX_TAG, '')
        return (int(idx) if idx.isdigit() else 10**6,
                inst['instanceId'])

    rows.sort(key=_sort_key)
    for inst in rows:
        tags = _tags_of(inst)
        info = common.InstanceInfo(
            instance_id=inst['instanceId'],
            internal_ip=str(inst.get('privateIpAddress') or ''),
            external_ip=str(inst.get('ipAddress') or '') or None,
            status=_STATE_MAP.get(_state_of(inst)) or 'PENDING',
            tags=tags,
        )
        instances[info.instance_id] = info
        if tags.get(HEAD_TAG) == 'true' and head_id is None:
            head_id = info.instance_id
    if not instances:
        raise exceptions.ClusterDoesNotExist(cluster_name)
    if head_id is None:
        head_id = sorted(instances)[0]
    return common.ClusterInfo(
        instances=instances, head_instance_id=head_id,
        provider_name='aws',
        provider_config=dict(provider_config or {}),
        ssh_user=provider_config.get('ssh_user', 'ec2-user'))


def open_ports(cluster_name: str, ports: List[str],
               provider_config: Dict[str, Any]) -> None:
    """Best-effort ingress on the default security group."""
    t = _transport(provider_config)
    for port in ports:
        lo, _, hi = str(port).partition('-')
        try:
            t.call('AuthorizeSecurityGroupIngress', {
                'GroupName': provider_config.get('security_group',
                                                 'default'),
                'IpPermissions.1.IpProtocol': 'tcp',
                'IpPermissions.1.FromPort': lo,
                'IpPermissions.1.ToPort': hi or lo,
                'IpPermissions.1.IpRanges.1.CidrIp': '0.0.0.0/0',
            })
        except rest.AwsApiError as e:
            if e.code != 'InvalidPermission.Duplicate':
                logger.warning(f'open_ports({port}) failed: {e}')


def cleanup_ports(cluster_name: str,
                  provider_config: Dict[str, Any]) -> None:
    del cluster_name, provider_config  # default SG rules persist
