"""Executes one queued job: gang launch across all hosts, from the head.

Run as ``python -m skypilot_tpu.agent.job_runner <job_id>`` inside the
cluster runtime dir (XSKY_CLUSTER_ROOT). This is the twin of the generated
Ray driver program the reference submits per job
(sky/backends/cloud_vm_ray_backend.py:232-731), as a permanent module
instead of codegen.
"""
from __future__ import annotations

import json
import os
import sys

from skypilot_tpu.agent import checkpointd
from skypilot_tpu.agent import gang
from skypilot_tpu.agent import job_lib
from skypilot_tpu.agent import telemetry
from skypilot_tpu.provision import common as provision_common
from skypilot_tpu.utils import command_runner as runner_lib


def _load_cluster_info(root: str) -> provision_common.ClusterInfo:
    with open(os.path.join(root, 'cluster_info.json'),
              encoding='utf-8') as f:
        return provision_common.ClusterInfo.from_json(json.load(f))


def _build_runners(info: provision_common.ClusterInfo):
    # Head→worker traffic stays on the VPC: use internal IPs.
    return runner_lib.runners_from_cluster_info(
        info, info.provider_config.get('ssh_private_key',
                                       '~/.ssh/xsky-key'),
        internal_ips=True)


def _resolve_commands(spec, host_envs):
    """(setup_cmd, run_cmd, cwd) for the gang launch.

    With spec['docker_container'] (image_id: docker:…), the task's
    commands execute inside the keep-alive container via docker exec;
    per-host env values are exported on the host and forwarded by
    name, and the cd happens inside the container (the host cwd is
    meaningless there).
    """
    cwd = spec.get('cwd')  # same dir for setup and run
    setup_cmd = spec.get('setup')
    run_cmd = spec.get('run')
    if run_cmd:
        # A restarted/reused host may still hold a previous
        # incarnation's telemetry spool; a stale frozen sample would
        # read as a dead rank and re-trigger stall recovery. Each rank
        # clears its own spool file just before the workload starts
        # (before any container wrap, so the rm lands on the same
        # filesystem emit() writes to). The dir env value may start
        # with '~' (SSH hosts) — tilde NEVER expands out of a variable
        # expansion, so substitute $HOME explicitly (bash; every
        # runner wraps commands in bash -c).
        run_cmd = ('rm -f "${XSKY_TELEMETRY_DIR/#\\~/$HOME}/rank-'
                   '${XSKY_HOST_RANK}.json" 2>/dev/null; ' + run_cmd)
    container = spec.get('docker_container')
    if container:
        from skypilot_tpu.utils import docker_utils
        env_keys = list(host_envs[0]) if host_envs else []
        if setup_cmd:
            setup_cmd = docker_utils.exec_wrap(
                setup_cmd, env_keys, cwd=cwd, container=container)
        if run_cmd:
            run_cmd = docker_utils.exec_wrap(
                run_cmd, env_keys, cwd=cwd, container=container)
        cwd = None
    return setup_cmd, run_cmd, cwd


def run_job(job_id: int, root: str = None) -> int:
    root = root or job_lib.cluster_root()
    job = job_lib.get_job(job_id, root)
    if job is None:
        print(f'Job {job_id} not found', file=sys.stderr)
        return 1
    spec = job['spec']
    info = _load_cluster_info(root)
    runners = _build_runners(info)
    # Elastic shrink: the spec may exclude dead/hung hosts — the gang
    # launches over the survivors only, ranks renumbered contiguously
    # (runner order matches build_host_envs' sorted-host order).
    exclude = set(int(r) for r in spec.get('exclude_hosts') or ())
    if exclude:
        runners = [r for i, r in enumerate(runners) if i not in exclude]
    log_dir = job_lib.log_dir_for(job_id, root)

    try:
        host_envs = gang.build_host_envs(info, spec.get('envs') or {},
                                         exclude_hosts=exclude)
        roots = [r.remote_runtime_root() for r in runners]
        for rank, env in enumerate(host_envs):
            env['XSKY_JOB_ID'] = str(job_id)
            # Per-rank telemetry spool on the rank's OWN host: the
            # workload's telemetry.emit() writes here and the control
            # plane pulls the same path through this rank's runner
            # (runner.remote_runtime_root() keeps the two in
            # agreement). Task envs may override for tests.
            env.setdefault(
                telemetry.ENV_DIR,
                telemetry.spool_dir(runners[rank].remote_runtime_root(),
                                    job_id))
            # Checkpoint tiers (agent/checkpointd.py): the rank's own
            # local tier on its host root — job-id-AGNOSTIC, so a
            # relaunch/resubmit under a new cluster job id still finds
            # the previous incarnation's shards — plus the K next
            # hosts' roots as the peer tier (ring order, DCN
            # neighbours). Task envs may override for tests.
            env.setdefault(checkpointd.ENV_DIR,
                           f'{roots[rank]}/ckpt')
            # Replica count from the RANK's env (task/controller
            # knobs land there via the job spec) — this agent
            # process's own environment does not see them.
            try:
                k = int(env.get(checkpointd.ENV_REPLICAS) or
                        checkpointd.replicas())
            except ValueError:
                k = checkpointd.replicas()
            k = min(max(0, k), len(roots) - 1)
            if k > 0:
                env.setdefault(checkpointd.ENV_PEER_DIRS, '\n'.join(
                    f'{roots[(rank + i) % len(roots)]}/ckpt'
                    for i in range(1, k + 1)))

        setup_cmd, run_spec_cmd, cwd = _resolve_commands(spec, host_envs)
        if setup_cmd:
            job_lib.set_status(job_id, job_lib.JobStatus.SETTING_UP, root)
            result = gang.gang_launch(runners, host_envs, setup_cmd,
                                      os.path.join(log_dir, 'setup'),
                                      cwd=cwd)
            if not result.success:
                job_lib.set_status(job_id, job_lib.JobStatus.FAILED_SETUP,
                                   root)
                return 1

        run_cmd = run_spec_cmd
        if not run_cmd:
            job_lib.set_status(job_id, job_lib.JobStatus.SUCCEEDED, root)
            return 0
        job_lib.set_status(job_id, job_lib.JobStatus.RUNNING, root)
        result = gang.gang_launch(runners, host_envs, run_cmd, log_dir,
                                  timeout_s=spec.get('timeout_s'),
                                  cwd=cwd)
        status = (job_lib.JobStatus.SUCCEEDED
                  if result.success else job_lib.JobStatus.FAILED)
        job_lib.set_status(job_id, status, root)
        return 0 if result.success else 1
    except BaseException:
        # A SIGTERM (cancel / teardown) exits through here via
        # SystemExit after the handler already marked CANCELLED —
        # don't overwrite that with FAILED.
        current = job_lib.get_job(job_id, root)
        if current is None or not current['status'].is_terminal():
            job_lib.set_status(job_id, job_lib.JobStatus.FAILED, root)
        raise
    finally:
        _schedule_next(root)


def _schedule_next(root: str) -> None:
    """Event-driven FIFO tick (twin of JobSchedulerEvent)."""
    job_lib.claim_and_spawn(root)


def main() -> int:
    job_id = int(sys.argv[1])
    root = job_lib.cluster_root()
    job_lib.set_pid(job_id, os.getpid(), root)

    def _on_term(signum, frame):
        # Each gang child runs in its own session, so a signal to THIS
        # process group does not reach them — take the fleet down
        # explicitly (cancel_job / cluster teardown send us SIGTERM).
        del signum, frame
        gang.kill_active()
        # A SIGTERM arriving after the job already finished (teardown
        # racing completion) must not overwrite SUCCEEDED/FAILED.
        current = job_lib.get_job(job_id, root)
        if current is None or not current['status'].is_terminal():
            job_lib.set_status(job_id, job_lib.JobStatus.CANCELLED, root)
        sys.exit(143)

    import signal
    signal.signal(signal.SIGTERM, _on_term)
    return run_job(job_id, root)


if __name__ == '__main__':
    sys.exit(main())
