"""Zero-dependency Kubernetes API client.

Control-plane twin of the reference's `kubernetes` SDK usage
(sky/provision/kubernetes/utils.py:78-401 builds API clients with
exec-plugin auth; sky/adaptors/kubernetes.py wraps the SDK). This repo
owns its transports (same pattern as provision/gcp/rest.py,
provision/aws/rest.py), so the provisioner drives the kube API server
over plain HTTPS from the stdlib:

  * kubeconfig parsing — KUBECONFIG / ~/.kube/config: clusters
    (server, CA data), users (token, client certs, exec plugins),
    contexts; `context` selects one, else current-context.
  * in-cluster config — the pod service account
    (/var/run/secrets/kubernetes.io/serviceaccount) when no kubeconfig
    matches, mirroring client library fallback order.
  * exec-plugin auth — runs the user's credential plugin (GKE's
    gke-gcloud-auth-plugin, EKS's aws-iam-authenticator), parses the
    ExecCredential, caches the token until expirationTimestamp.

The pod EXEC data plane (command running / rsync) stays on kubectl:
exec rides a SPDY/websocket upgrade that buys nothing reimplemented,
while control-plane CRUD here removes the kubectl dependency from every
provisioner op and makes them unit-testable with a recorded-response
transport.
"""
from __future__ import annotations

import base64
import datetime
import json
import os
import ssl
import subprocess
import tempfile
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Dict, List, Optional

from skypilot_tpu import sky_logging

logger = sky_logging.init_logger(__name__)

_SA_DIR = '/var/run/secrets/kubernetes.io/serviceaccount'


class KubeApiError(Exception):

    def __init__(self, status: int, reason: str, message: str) -> None:
        super().__init__(f'{status} {reason}: {message}')
        self.status = status
        self.reason = reason
        self.message = message


def _load_kubeconfig() -> Optional[Dict[str, Any]]:
    import yaml
    path = os.path.expanduser(
        os.environ.get('KUBECONFIG', '~/.kube/config').split(os.pathsep)[0])
    if not os.path.exists(path):
        return None
    with open(path, encoding='utf-8') as f:
        return yaml.safe_load(f) or {}


def _by_name(entries: List[Dict[str, Any]], name: str,
             kind: str) -> Dict[str, Any]:
    for entry in entries or []:
        if entry.get('name') == name:
            return entry.get(kind, {})
    raise ValueError(f'kubeconfig has no {kind} named {name!r}')


def _write_temp(data: bytes, suffix: str) -> str:
    fd, path = tempfile.mkstemp(prefix='xsky-kube-', suffix=suffix)
    with os.fdopen(fd, 'wb') as f:
        f.write(data)
    os.chmod(path, 0o600)
    return path


class KubeTransport:
    """Authenticated HTTPS to one cluster's API server."""

    def __init__(self, context: Optional[str] = None) -> None:
        self.server: str = ''
        self._headers: Dict[str, str] = {}
        self._ssl: Optional[ssl.SSLContext] = None
        self._exec_spec: Optional[Dict[str, Any]] = None
        self._exec_token: Optional[str] = None
        self._exec_expiry: Optional[datetime.datetime] = None
        self._sa_token_path: Optional[str] = None
        config = _load_kubeconfig()
        if config and (context or config.get('current-context')):
            self._init_from_kubeconfig(config, context)
        elif os.path.exists(os.path.join(_SA_DIR, 'token')):
            self._init_in_cluster()
        else:
            raise ValueError(
                'No Kubernetes credentials: neither a kubeconfig '
                f'(KUBECONFIG / ~/.kube/config) nor an in-cluster '
                f'service account ({_SA_DIR}) is present.')

    # -- credential resolution ------------------------------------------

    def _init_in_cluster(self) -> None:
        host = os.environ.get('KUBERNETES_SERVICE_HOST', 'kubernetes.default.svc')
        port = os.environ.get('KUBERNETES_SERVICE_PORT', '443')
        self.server = f'https://{host}:{port}'
        # Re-read per request (see request()): bound service-account
        # tokens expire (~1h) and the kubelet rotates the projected
        # file — a token pinned at construction would start 401ing on
        # long-lived transports.
        self._sa_token_path: Optional[str] = os.path.join(_SA_DIR, 'token')
        ca = os.path.join(_SA_DIR, 'ca.crt')
        self._ssl = ssl.create_default_context(
            cafile=ca if os.path.exists(ca) else None)

    def _init_from_kubeconfig(self, config: Dict[str, Any],
                              context: Optional[str]) -> None:
        ctx_name = context or config.get('current-context')
        ctx = _by_name(config.get('contexts', []), ctx_name, 'context')
        cluster = _by_name(config.get('clusters', []),
                           ctx.get('cluster', ''), 'cluster')
        user = _by_name(config.get('users', []), ctx.get('user', ''),
                        'user')
        self.server = cluster['server'].rstrip('/')
        if cluster.get('insecure-skip-tls-verify'):
            self._ssl = ssl._create_unverified_context()  # pylint: disable=protected-access
        else:
            ca_pem: Optional[str] = None
            if cluster.get('certificate-authority-data'):
                ca_pem = base64.b64decode(
                    cluster['certificate-authority-data']).decode()
            self._ssl = ssl.create_default_context(
                cafile=cluster.get('certificate-authority'),
                cadata=ca_pem)
        if user.get('token'):
            self._headers['Authorization'] = f"Bearer {user['token']}"
        elif user.get('exec'):
            self._exec_spec = user['exec']
        elif user.get('username') and user.get('password'):
            basic = base64.b64encode(
                f"{user['username']}:{user['password']}".encode()).decode()
            self._headers['Authorization'] = f'Basic {basic}'
        cert = user.get('client-certificate')
        key = user.get('client-key')
        if user.get('client-certificate-data'):
            cert = _write_temp(
                base64.b64decode(user['client-certificate-data']), '.crt')
        if user.get('client-key-data'):
            key = _write_temp(
                base64.b64decode(user['client-key-data']), '.key')
        if cert and key and self._ssl is not None:
            self._ssl.load_cert_chain(cert, key)

    def _exec_credential(self) -> str:
        """Run the kubeconfig exec plugin → bearer token (cached until
        the plugin-reported expiry)."""
        now = datetime.datetime.now(datetime.timezone.utc)
        if self._exec_token and self._exec_expiry and now < self._exec_expiry:
            return self._exec_token
        spec = self._exec_spec or {}
        cmd = [spec.get('command', '')] + list(spec.get('args') or [])
        env = dict(os.environ)
        for pair in spec.get('env') or []:
            env[pair['name']] = pair['value']
        env.setdefault(
            'KUBERNETES_EXEC_INFO',
            json.dumps({'apiVersion': spec.get(
                'apiVersion', 'client.authentication.k8s.io/v1beta1'),
                'kind': 'ExecCredential', 'spec': {'interactive': False}}))
        try:
            out = subprocess.run(cmd, capture_output=True, text=True,
                                 env=env, timeout=60, check=True).stdout
            cred = json.loads(out)
        except (OSError, subprocess.SubprocessError,
                json.JSONDecodeError) as e:
            raise KubeApiError(
                401, 'ExecPluginFailed',
                f'credential plugin {cmd[0]!r} failed: {e}') from e
        status = cred.get('status', {})
        token = status.get('token')
        if not token:
            raise KubeApiError(401, 'ExecPluginFailed',
                               f'plugin {cmd[0]!r} returned no token')
        self._exec_token = token
        expiry = status.get('expirationTimestamp')
        if expiry:
            try:
                self._exec_expiry = datetime.datetime.fromisoformat(
                    expiry.replace('Z', '+00:00'))
            except ValueError:
                self._exec_expiry = None
        return token

    # -- HTTP -----------------------------------------------------------

    def request(self, method: str, path: str,
                params: Optional[Dict[str, str]] = None,
                body: Optional[Any] = None,
                content_type: str = 'application/json') -> Dict[str, Any]:
        url = self.server + path
        if params:
            url += '?' + urllib.parse.urlencode(params)
        headers = dict(self._headers)
        if self._exec_spec is not None:
            headers['Authorization'] = f'Bearer {self._exec_credential()}'
        elif getattr(self, '_sa_token_path', None):
            with open(self._sa_token_path, encoding='utf-8') as f:
                headers['Authorization'] = f'Bearer {f.read().strip()}'
        data = None
        if body is not None:
            data = json.dumps(body).encode()
            headers['Content-Type'] = content_type
        headers['Accept'] = 'application/json'
        req = urllib.request.Request(url, data=data, headers=headers,
                                     method=method)
        try:
            with urllib.request.urlopen(req, timeout=60,
                                        context=self._ssl) as resp:
                raw = resp.read()
        except urllib.error.HTTPError as e:
            raw = e.read()
            try:
                payload = json.loads(raw)
            except json.JSONDecodeError:
                payload = {}
            raise KubeApiError(
                e.code, payload.get('reason', e.reason or ''),
                payload.get('message',
                            raw.decode(errors='replace')[:300])) from e
        except (urllib.error.URLError, TimeoutError, OSError) as e:
            raise KubeApiError(0, 'Unreachable',
                               f'cannot reach {self.server}: {e}') from e
        return json.loads(raw) if raw else {}


def _api_prefix(api_version: str) -> str:
    """'v1' → /api/v1; 'apps/v1' → /apis/apps/v1."""
    if '/' in api_version:
        return f'/apis/{api_version}'
    return f'/api/{api_version}'


_KIND_PLURALS = {
    'Pod': 'pods',
    'Service': 'services',
    'DaemonSet': 'daemonsets',
    'ConfigMap': 'configmaps',
    'Node': 'nodes',
}


class KubeClient:
    """Typed CRUD over a transport; namespace-scoped unless noted."""

    def __init__(self, transport: KubeTransport,
                 namespace: str = 'default') -> None:
        self.t = transport
        self.namespace = namespace

    def _path(self, api_version: str, kind: str,
              name: Optional[str] = None,
              namespace: Optional[str] = None) -> str:
        plural = _KIND_PLURALS[kind]
        ns = namespace or self.namespace
        base = f'{_api_prefix(api_version)}/namespaces/{ns}/{plural}'
        return f'{base}/{name}' if name else base

    def list(self, kind: str, label_selector: str = '',
             api_version: str = 'v1',
             namespace: Optional[str] = None) -> List[Dict[str, Any]]:
        params = {}
        if label_selector:
            params['labelSelector'] = label_selector
        out = self.t.request(
            'GET', self._path(api_version, kind, namespace=namespace),
            params=params)
        return out.get('items', [])

    def get(self, kind: str, name: str, api_version: str = 'v1',
            namespace: Optional[str] = None) -> Optional[Dict[str, Any]]:
        try:
            return self.t.request(
                'GET', self._path(api_version, kind, name, namespace))
        except KubeApiError as e:
            if e.status == 404:
                return None
            raise

    def apply(self, obj: Dict[str, Any]) -> Dict[str, Any]:
        """Create-or-update (kubectl-apply semantics): POST, and on
        409 AlreadyExists fall back to a JSON merge-patch."""
        api_version = obj['apiVersion']
        kind = obj['kind']
        name = obj['metadata']['name']
        namespace = obj['metadata'].get('namespace')
        try:
            return self.t.request(
                'POST', self._path(api_version, kind, namespace=namespace),
                body=obj)
        except KubeApiError as e:
            if e.status != 409:
                raise
        return self.t.request(
            'PATCH', self._path(api_version, kind, name, namespace),
            body=obj, content_type='application/merge-patch+json')

    def delete(self, kind: str, name: str, api_version: str = 'v1',
               namespace: Optional[str] = None,
               ignore_missing: bool = True) -> None:
        try:
            self.t.request(
                'DELETE', self._path(api_version, kind, name, namespace))
        except KubeApiError as e:
            if not (ignore_missing and e.status == 404):
                raise

    def delete_by_selector(self, kind: str, label_selector: str,
                           api_version: str = 'v1',
                           namespace: Optional[str] = None) -> None:
        """DELETE collection (pods support it server-side); falls back
        to per-object deletes for kinds without a collection endpoint."""
        try:
            self.t.request(
                'DELETE', self._path(api_version, kind,
                                     namespace=namespace),
                params={'labelSelector': label_selector})
        except KubeApiError as e:
            if e.status not in (404, 405):
                raise
            for obj in self.list(kind, label_selector, api_version,
                                 namespace):
                self.delete(kind, obj['metadata']['name'], api_version,
                            namespace)
