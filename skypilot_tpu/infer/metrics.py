"""Prometheus-format metrics for the inference server.

The serving twin of ``server/metrics.py`` (API-server metrics): the
reference's serving recipes get engine observability from vLLM's
``/metrics`` (request counts, token throughput, TTFT); replicas here
expose the same signals so the serve controller, autoscaler dashboards
and operators can scrape them.

Exposed at GET /metrics on every replica:
  * xsky_serve_requests_total{endpoint,outcome}
  * xsky_serve_prompt_tokens_total / xsky_serve_generated_tokens_total
  * xsky_serve_ttft_seconds          (histogram)
  * xsky_serve_tpot_seconds          (histogram, inter-token latency)
  * xsky_serve_e2e_latency_seconds   (histogram)
  * xsky_serve_active_slots / xsky_serve_free_slots /
    xsky_serve_queue_depth           (gauges, read live)
  * xsky_serve_kv_pages_total / xsky_serve_kv_pages_free
    (gauges, paged-KV engines only)
  * xsky_serve_wasted_decode_steps_total  (counter: fused decode rows
    burned after a slot finished — legacy tick only, the masked fast
    tick holds it at 0)
  * xsky_serve_phase_seconds{phase=...}   (histogram per anatomy
    phase — replica_queue/admit_deferred/prefill/decode/
    sampling_commit/finish, fed by infer/anatomy.py seals)
  * xsky_serve_kv_headroom_at_admit       (gauge: free/total KV pages
    seen by the most recent successful admission)
  * xsky_serve_deferred_wait_seconds      (gauge: how long the oldest
    currently-deferred request has been parked for KV headroom)
  * xsky_serve_deadline_rejects_total     (counter: requests rejected
    at admit because the relayed SLO deadline could not cover the
    estimated prefill+decode budget)

The serve controller's SLO monitor (serve/slo.py) scrapes this text
each tick: TTFT/TPOT/e2e feed the per-replica latency digests in
`xsky slo`, and TPOT is the replica-side signal behind the
``slo.tpot_p50_ms`` objective (the LB can time bytes but cannot count
tokens).
"""
from __future__ import annotations

import threading
import time
from typing import Dict, Optional, Tuple

from skypilot_tpu.agent import telemetry
# The SLO plane owns the one cumulative-bucket histogram whose render
# its scrape parser round-trips (serve/slo.py); a second copy here
# would have to stay render-compatible by hand.
from skypilot_tpu.serve.slo import Histogram as _Histogram
from skypilot_tpu.serve.slo import fmt_le as _fmt_le

_TTFT_BUCKETS = (0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
                 float('inf'))
# Inter-token latency: decode steps are milliseconds on-device but
# 100ms+ when host dispatch dominates (BENCH_LOCAL_r03_serve) — the
# buckets must resolve both regimes.
_TPOT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                 0.5, 1.0, float('inf'))
_E2E_BUCKETS = (0.1, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 300.0,
                float('inf'))
# Anatomy phases span sub-ms (sampling_commit) to tens of seconds
# (decode totals, deferred waits) — one shared bucket ladder must
# resolve both ends.
_PHASE_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
                  5.0, 10.0, 30.0, float('inf'))




class ServeMetrics:
    """Per-replica serving metrics; thread-safe, stdlib-only."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._requests: Dict[Tuple[str, str], int] = {}
        self._prompt_tokens = 0
        self._generated_tokens = 0
        self._ttft = _Histogram(_TTFT_BUCKETS)
        self._tpot = _Histogram(_TPOT_BUCKETS)
        self._e2e = _Histogram(_E2E_BUCKETS)
        self._phase: Dict[str, _Histogram] = {}

    def observe(self, endpoint: str, outcome: str, prompt_tokens: int,
                generated_tokens: int, ttft_s: Optional[float],
                e2e_s: Optional[float],
                tpot_s: Optional[float] = None) -> None:
        with self._lock:
            key = (endpoint, outcome)
            self._requests[key] = self._requests.get(key, 0) + 1
            self._prompt_tokens += prompt_tokens
            self._generated_tokens += generated_tokens
            if ttft_s is not None:
                self._ttft.observe(ttft_s)
            if tpot_s is not None:
                self._tpot.observe(tpot_s)
            if e2e_s is not None:
                self._e2e.observe(e2e_s)
            n_requests = sum(self._requests.values())
        # Workload-telemetry heartbeat: each finished request is
        # progress; generated tokens feed the rank's tokens/s rate. A
        # replica that keeps heartbeating without completing requests
        # shows up hung in `xsky top`, same as a stalled train step.
        telemetry.emit(phase=telemetry.PHASE_STEP, step=n_requests,
                       tokens=generated_tokens)

    def observe_phases(self, phases: Dict[str, float]) -> None:
        """Fold one sealed anatomy record's phase breakdown into the
        per-phase histograms (called off the tick path, by the handler
        thread that sealed the record)."""
        with self._lock:
            for phase, seconds in phases.items():
                hist = self._phase.get(phase)
                if hist is None:
                    hist = self._phase[phase] = _Histogram(
                        _PHASE_BUCKETS)
                hist.observe(seconds)

    def observe_choice_tokens(self, request) -> None:
        """Token accounting for an n>1 sibling choice: its prompt AND
        generated tokens are real device work (each sibling prefills),
        but it is NOT another request — counting it through
        observe_request would inflate request counts and latency
        histograms n-fold."""
        with self._lock:
            self._prompt_tokens += len(request.prompt_tokens)
            self._generated_tokens += len(request.output_tokens)

    def observe_request(self, endpoint: str, request,
                        outcome: Optional[str] = None) -> None:
        """Record a finished orchestrator Request. Pass `outcome`
        explicitly when the handler knows better (a stop-sequence hit
        sets cancel_requested but is a successful 'ok' completion; a
        client disconnect is 'cancelled')."""
        if outcome is None:
            outcome = 'error' if request.error else 'ok'
        ttft = None
        if request.first_token_at is not None:
            ttft = request.first_token_at - request.submitted_at
        e2e = None
        if request.finished_at is not None:
            e2e = request.finished_at - request.submitted_at
        # TPOT (inter-token latency): decode wall time over the tokens
        # it emitted AFTER the first (the first token is prefill and
        # belongs to TTFT). One token has no inter-token gap.
        tpot = None
        n_out = len(request.output_tokens)
        if request.first_token_at is not None and \
                request.finished_at is not None and n_out > 1:
            tpot = max(0.0, request.finished_at -
                       request.first_token_at) / (n_out - 1)
        self.observe(endpoint, outcome, len(request.prompt_tokens),
                     len(request.output_tokens), ttft, e2e,
                     tpot_s=tpot)

    def render(self, orch=None) -> str:
        with self._lock:
            lines = ['# TYPE xsky_serve_requests_total counter']
            for (endpoint, outcome), n in sorted(self._requests.items()):
                lines.append(
                    f'xsky_serve_requests_total{{endpoint="{endpoint}",'
                    f'outcome="{outcome}"}} {n}')
            lines += [
                '# TYPE xsky_serve_prompt_tokens_total counter',
                f'xsky_serve_prompt_tokens_total {self._prompt_tokens}',
                '# TYPE xsky_serve_generated_tokens_total counter',
                f'xsky_serve_generated_tokens_total '
                f'{self._generated_tokens}',
            ]
            lines += self._ttft.render('xsky_serve_ttft_seconds')
            lines += self._tpot.render('xsky_serve_tpot_seconds')
            lines += self._e2e.render('xsky_serve_e2e_latency_seconds')
            if self._phase:
                # Labelled histogram family: slo.Histogram.render is
                # label-free, so the {phase=...} series are laid out
                # by hand — same bucket/sum/count shape the scrape
                # parser round-trips.
                name = 'xsky_serve_phase_seconds'
                lines.append(f'# TYPE {name} histogram')
                for phase in sorted(self._phase):
                    hist = self._phase[phase]
                    for i, le in enumerate(hist.les):
                        lines.append(
                            f'{name}_bucket{{phase="{phase}",'
                            f'le="{_fmt_le(le)}"}} {hist.counts[i]}')
                    lines.append(f'{name}_sum{{phase="{phase}"}} '
                                 f'{hist.total:.6f}')
                    lines.append(f'{name}_count{{phase="{phase}"}} '
                                 f'{hist.n}')
        if orch is not None:
            active = len(orch._slot_req)
            free = len(orch._free_slots)
            lines += [
                '# TYPE xsky_serve_active_slots gauge',
                f'xsky_serve_active_slots {active}',
                '# TYPE xsky_serve_free_slots gauge',
                f'xsky_serve_free_slots {free}',
                '# TYPE xsky_serve_queue_depth gauge',
                f'xsky_serve_queue_depth {orch._pending.qsize()}',
            ]
            headroom = getattr(orch, 'last_admit_kv_headroom', None)
            if headroom is not None:
                lines += [
                    '# TYPE xsky_serve_kv_headroom_at_admit gauge',
                    f'xsky_serve_kv_headroom_at_admit {headroom:.4f}',
                ]
            deferred = list(getattr(orch, '_deferred', None) or [])
            waits = [time.perf_counter() - r.deferred_at
                     for r in deferred
                     if getattr(r, 'deferred_at', None) is not None]
            if waits:
                lines += [
                    '# TYPE xsky_serve_deferred_wait_seconds gauge',
                    f'xsky_serve_deferred_wait_seconds '
                    f'{max(waits):.4f}',
                ]
            rejects = getattr(orch, 'deadline_rejects', None)
            if rejects is not None:
                lines += [
                    '# TYPE xsky_serve_deadline_rejects_total counter',
                    f'xsky_serve_deadline_rejects_total {rejects}',
                ]
            wasted = getattr(orch, 'wasted_decode_steps', None)
            if wasted is not None:
                lines += [
                    '# TYPE xsky_serve_wasted_decode_steps_total '
                    'counter',
                    f'xsky_serve_wasted_decode_steps_total {wasted}',
                ]
            pages = getattr(orch.engine, 'kv_page_stats', None)
            if pages is not None:
                lines += [
                    '# TYPE xsky_serve_kv_pages_total gauge',
                    f"xsky_serve_kv_pages_total {pages['total']}",
                    '# TYPE xsky_serve_kv_pages_free gauge',
                    f"xsky_serve_kv_pages_free {pages['free']}",
                ]
            accept = getattr(orch, 'accept_stats', None)
            if accept is not None:
                lines += [
                    '# TYPE xsky_serve_spec_rounds_total counter',
                    f"xsky_serve_spec_rounds_total {accept['rounds']}",
                    '# TYPE xsky_serve_spec_proposed_total counter',
                    f"xsky_serve_spec_proposed_total "
                    f"{accept['proposed']}",
                    '# TYPE xsky_serve_spec_accepted_total counter',
                    f"xsky_serve_spec_accepted_total "
                    f"{accept['accepted']}",
                ]
            stats = orch.engine.prefix_cache_stats
            if stats is not None:
                lines += [
                    '# TYPE xsky_serve_prefix_cache_hits_total counter',
                    f'xsky_serve_prefix_cache_hits_total '
                    f'{stats["hits"]}',
                    '# TYPE xsky_serve_prefix_cache_misses_total '
                    'counter',
                    f'xsky_serve_prefix_cache_misses_total '
                    f'{stats["misses"]}',
                    '# TYPE xsky_serve_prefix_cache_tokens_reused_total'
                    ' counter',
                    f'xsky_serve_prefix_cache_tokens_reused_total '
                    f'{stats["tokens_reused"]}',
                    '# TYPE xsky_serve_prefix_cache_entries gauge',
                    f'xsky_serve_prefix_cache_entries '
                    f'{stats["entries"]}',
                ]
        return '\n'.join(lines) + '\n'
