"""OpenAI-compatible request/response shaping for the serving endpoint.

The reference's serving recipes expose this exact wire surface through
vLLM/SGLang (llm/vllm/serve.yaml, llm/sglang/llama2.yaml:34 — both
serve ``/v1/completions`` + ``/v1/chat/completions``); the framework
owns its own engine here, so it implements the API natively. Pure
shaping logic lives in this module (testable without HTTP); the HTTP
routes are in ``infer/server.py``.

Supported: prompt as text / token list, ``max_tokens``, ``temperature``,
``top_p``/``top_k``, ``stop`` (string or list), ``stream`` (SSE),
``echo``, ``logprobs`` (completions int ≤ 5 / chat ``logprobs`` +
``top_logprobs``), ``n`` ≤ 8 (non-streamed). Rejected clearly:
batched prompts, ``n`` with ``stream``, ``logprobs`` with ``stream``.
"""
from __future__ import annotations

import dataclasses
import json
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

from skypilot_tpu.infer import orchestrator as orch_lib
from skypilot_tpu.infer import tokenizer as tokenizer_lib


class ApiError(Exception):
    """Maps to an OpenAI-style error body with an HTTP status."""

    def __init__(self, code: int, message: str) -> None:
        super().__init__(message)
        self.code = code

    def body(self) -> Dict[str, Any]:
        return {'error': {'message': str(self),
                          'type': 'invalid_request_error'}}


@dataclasses.dataclass
class RequestMeta:
    """Everything the response builders need beyond the orch Request."""
    kind: str                    # 'completion' | 'chat'
    model_id: str
    stream: bool
    stop: List[str]
    echo: bool
    prompt_text: str             # '' when prompt came as token ids
    prompt_tokens: List[int]
    n: int = 1                   # parallel choices (non-streamed)
    # None = logprobs off; else the requested ALTERNATIVE count (0..5 —
    # 0 means chosen-token logprob only, per the OpenAI shape):
    logprobs: Optional[int] = None
    response_id: str = ''
    created: int = 0

    def __post_init__(self) -> None:
        prefix = 'cmpl' if self.kind == 'completion' else 'chatcmpl'
        self.response_id = f'{prefix}-{uuid.uuid4().hex[:24]}'
        self.created = int(time.time())


def _parse_prompt(body: Dict[str, Any],
                  tokenizer: Any) -> Tuple[str, List[int]]:
    prompt = body.get('prompt')
    if isinstance(prompt, list) and len(prompt) == 1 and \
            isinstance(prompt[0], str):
        prompt = prompt[0]  # single-element batch: allowed
    if isinstance(prompt, str):
        return prompt, tokenizer.encode(prompt)
    if isinstance(prompt, list) and prompt and \
            all(isinstance(t, int) for t in prompt):
        return '', list(prompt)  # pre-tokenized (OpenAI allows this)
    if isinstance(prompt, list):
        raise ApiError(400, 'batched prompts are not supported; send '
                            'one request per prompt')
    raise ApiError(400, "'prompt' (string or token list) is required")


def _parse_chat_prompt(body: Dict[str, Any],
                       tokenizer: Any) -> Tuple[str, List[int]]:
    messages = body.get('messages')
    if not isinstance(messages, list) or not messages or not all(
            isinstance(m, dict) and isinstance(m.get('content'), str)
            for m in messages):
        raise ApiError(400, "'messages' must be a non-empty list of "
                            "{role, content} objects")
    text = tokenizer_lib.render_chat(messages, tokenizer)
    return text, tokenizer.encode(text)


def build_request(body: Dict[str, Any], tokenizer: Any,
                  engine_config: Any, model_id: str,
                  chat: bool,
                  admit_limit: Optional[int] = None
                  ) -> Tuple[orch_lib.Request, RequestMeta]:
    """Validate an API body into an orchestrator Request + meta.

    `admit_limit` overrides the prompt-length cap (servers whose engine
    has the chunked-prefill path admit beyond the largest bucket —
    pass orchestrator._admit_limit()). Raises ApiError on anything
    malformed or unsupported.
    """
    stream = bool(body.get('stream', False))
    try:
        n = int(body.get('n', 1))
    except (TypeError, ValueError):
        raise ApiError(400, "'n' must be an integer")
    if not 1 <= n <= 8:
        raise ApiError(400, "'n' must be between 1 and 8")
    if n > 1 and stream:
        raise ApiError(400, "'n' > 1 is not supported with streaming")
    logprobs = _parse_logprobs(body, chat)
    if logprobs is not None and stream:
        raise ApiError(400, "'logprobs' is not supported with "
                            'streaming')
    if chat:
        prompt_text, prompt_tokens = _parse_chat_prompt(body, tokenizer)
    else:
        prompt_text, prompt_tokens = _parse_prompt(body, tokenizer)

    limit = admit_limit if admit_limit is not None else min(
        engine_config.max_prompt_len, engine_config.max_target_len - 1)
    if len(prompt_tokens) > limit:
        raise ApiError(400, f'prompt is {len(prompt_tokens)} tokens; '
                            f'this server accepts at most {limit}')

    budget = engine_config.max_target_len - len(prompt_tokens)
    max_tokens = body.get('max_tokens')
    if max_tokens is None:
        # OpenAI defaults completions to 16; chat fills the budget.
        max_tokens = 16 if not chat else budget
    try:
        max_tokens = int(max_tokens)
    except (TypeError, ValueError):
        raise ApiError(400, "'max_tokens' must be an integer")
    if max_tokens < 1:
        raise ApiError(400, "'max_tokens' must be ≥ 1")
    max_tokens = min(max_tokens, budget)

    stop = body.get('stop') or []
    if isinstance(stop, str):
        stop = [stop]
    if not isinstance(stop, list) or not all(
            isinstance(s, str) and s for s in stop):
        raise ApiError(400, "'stop' must be a string or list of strings")
    if len(stop) > 4:
        raise ApiError(400, "at most 4 'stop' sequences")

    try:
        temperature = float(body.get('temperature', 1.0))
        top_p = float(body.get('top_p', 1.0))
        top_k = int(body.get('top_k', 0))
        presence = float(body.get('presence_penalty', 0.0))
        frequency = float(body.get('frequency_penalty', 0.0))
    except (TypeError, ValueError):
        raise ApiError(400, 'temperature/top_p/top_k/penalties must '
                            'be numbers')
    for name, value in (('presence_penalty', presence),
                        ('frequency_penalty', frequency)):
        if not -2.0 <= value <= 2.0:
            raise ApiError(400, f"'{name}' must be in [-2, 2]")

    request = orch_lib.Request(
        prompt_tokens=prompt_tokens,
        max_new_tokens=max_tokens,
        eos_token_id=getattr(tokenizer, 'eos_token_id', None),
        temperature=temperature,
        top_k=top_k,
        top_p=top_p,
        presence_penalty=presence,
        frequency_penalty=frequency,
        # The orchestrator records max(alts, 1) alternatives; the
        # response builder slices down to the exact requested count.
        logprobs=0 if logprobs is None else max(logprobs, 1))
    meta = RequestMeta(kind='chat' if chat else 'completion',
                       model_id=model_id,
                       stream=stream,
                       stop=stop,
                       echo=bool(body.get('echo', False)),
                       prompt_text=prompt_text,
                       prompt_tokens=prompt_tokens,
                       n=n,
                       logprobs=logprobs)
    return request, meta


def _parse_logprobs(body: Dict[str, Any], chat: bool) -> Optional[int]:
    """Completions: `logprobs: N` (int ≤ 5). Chat: `logprobs: true` +
    optional `top_logprobs: N`. Returns the requested ALTERNATIVE
    count (0..5), or None when logprobs are off — 0 is a valid request
    meaning chosen-token logprobs with no alternatives."""
    cap = orch_lib.LOGPROBS_K
    if chat:
        flag = body.get('logprobs', False)
        if not isinstance(flag, bool):
            raise ApiError(400, "chat 'logprobs' must be a boolean")
        if not flag:
            if body.get('top_logprobs'):
                raise ApiError(400, "'top_logprobs' needs "
                                    "'logprobs': true")
            return None
        top = body.get('top_logprobs', 0)
        try:
            top = int(top)
        except (TypeError, ValueError):
            raise ApiError(400, "'top_logprobs' must be an integer")
        if not 0 <= top <= cap:
            raise ApiError(400, f"'top_logprobs' must be 0..{cap}")
        return top
    lp = body.get('logprobs')
    if lp is None or lp is False:
        return None   # NOT `in (None, False)`: 0 == False is a hit
    try:
        lp = int(lp)
    except (TypeError, ValueError):
        raise ApiError(400, "'logprobs' must be an integer")
    if not 0 <= lp <= cap:
        raise ApiError(400, f"'logprobs' must be 0..{cap}")
    return lp


def clone_request(request: orch_lib.Request) -> orch_lib.Request:
    """A fresh Request with the same decoding parameters (for n > 1 —
    output bookkeeping must not be shared)."""
    return orch_lib.Request(
        prompt_tokens=request.prompt_tokens,
        max_new_tokens=request.max_new_tokens,
        eos_token_id=request.eos_token_id,
        temperature=request.temperature,
        top_k=request.top_k,
        top_p=request.top_p,
        presence_penalty=request.presence_penalty,
        frequency_penalty=request.frequency_penalty,
        logprobs=request.logprobs)


def find_stop(text: str, stops: List[str]) -> int:
    """Earliest index where any stop sequence begins, or -1."""
    best = -1
    for stop in stops:
        idx = text.find(stop)
        if idx != -1 and (best == -1 or idx < best):
            best = idx
    return best


def finalize_text(meta: RequestMeta, request: orch_lib.Request,
                  tokenizer: Any) -> Tuple[str, str]:
    """(text, finish_reason) for a finished non-streamed request."""
    text = tokenizer.decode(request.output_tokens)
    finish_reason = ('length' if len(request.output_tokens) >=
                     request.max_new_tokens else 'stop')
    idx = find_stop(text, meta.stop)
    if idx != -1:
        text, finish_reason = text[:idx], 'stop'
    if meta.echo and meta.kind == 'completion':
        # prompt_text is '' when the prompt arrived as token ids —
        # reconstruct it so echo still echoes.
        prompt_text = meta.prompt_text or \
            tokenizer.decode(meta.prompt_tokens)
        text = prompt_text + text
    return text, finish_reason


def _usage(meta: RequestMeta,
           request: orch_lib.Request) -> Dict[str, int]:
    return {'prompt_tokens': len(meta.prompt_tokens),
            'completion_tokens': len(request.output_tokens),
            'total_tokens': (len(meta.prompt_tokens) +
                             len(request.output_tokens))}


def _logprobs_block(meta: RequestMeta, request: orch_lib.Request,
                    tokenizer: Any, text: str
                    ) -> Optional[Dict[str, Any]]:
    """The per-choice `logprobs` object in the OpenAI shape.

    Completions: {tokens, token_logprobs, top_logprobs, text_offset}.
    Chat: {content: [{token, logprob, top_logprobs: [...]}]}. Token
    strings decode one token at a time (byte-exactness is not
    guaranteed across merges — standard for this field). Entries are
    truncated to the RETURNED `text` (stop sequences cut generation
    mid-list, and cancel latency can overshoot by a few tokens), and
    the alternative count is exactly meta.logprobs (the orchestrator
    records at least one alternative even for a 0-alternative ask).
    """
    alts = meta.logprobs
    if alts is None or not request.logprobs:
        return None
    n = len(request.token_logprobs)
    toks = request.output_tokens[:n]
    # Token strings as joint-decode diffs: their concatenation is
    # EXACTLY tokenizer.decode(toks) (per-token decode is not —
    # multi-byte characters split across tokens), so offsets and
    # stop-truncation line up with the returned text. Diffs use a
    # small sliding window (two ≤W+1-token decodes per token, O(n·W)
    # total — a full cumulative decode per token would be O(n²));
    # if windowing ever disagrees with the joint decode (a merge
    # spanning the window), fall back to the exact cumulative pass.
    full_join = tokenizer.decode(toks)
    window = 8
    tok_strs = []
    for i in range(n):
        lo = max(0, i + 1 - window)
        head = tokenizer.decode(toks[lo:i]) if i > lo else ''
        tok_strs.append(tokenizer.decode(toks[lo:i + 1])[len(head):])
    if ''.join(tok_strs) != full_join:
        tok_strs, prev = [], ''
        for i in range(n):
            cur = tokenizer.decode(toks[:i + 1])
            tok_strs.append(cur[len(prev):])
            prev = cur
    # Echoed completions prepend the prompt (reconstructed when it
    # arrived as token ids): offsets are relative to the full text.
    base = 0
    if meta.echo and meta.kind == 'completion':
        base = len(meta.prompt_text or
                   tokenizer.decode(meta.prompt_tokens))
    gen_text = text[base:]
    if gen_text == full_join:
        # Untruncated: every recorded token is returned (a trailing
        # empty diff — incomplete UTF-8 tail — must not be dropped).
        keep = n
        offsets, pos = [], 0
        for ts in tok_strs:
            offsets.append(base + pos)
            pos += len(ts)
    else:
        keep, pos = 0, 0
        offsets = []
        for ts in tok_strs:
            if pos >= len(gen_text):
                break
            offsets.append(base + pos)
            pos += len(ts)
            keep += 1
    tok_strs = tok_strs[:keep]
    token_lps = request.token_logprobs[:keep]
    top_lps = request.top_logprobs[:keep]
    if meta.kind == 'chat':
        content = []
        for ts, lp, top in zip(tok_strs, token_lps, top_lps):
            ranked = sorted(top.items(), key=lambda kv: -kv[1])[:alts]
            content.append({
                'token': ts, 'logprob': lp,
                'top_logprobs': [
                    {'token': tokenizer.decode([tid]), 'logprob': v}
                    for tid, v in ranked],
            })
        return {'content': content}
    tops = []
    for top in top_lps:
        merged: Dict[str, float] = {}
        for tid, v in sorted(top.items(), key=lambda kv: -kv[1])[:alts]:
            key = tokenizer.decode([tid])
            # Distinct ids can decode to the same string (specials,
            # unmapped ids); keep the most probable one.
            merged[key] = max(v, merged.get(key, v))
        tops.append(merged)
    return {
        'tokens': tok_strs,
        'token_logprobs': token_lps,
        'top_logprobs': tops,
        'text_offset': offsets,
    }


def response_body(meta: RequestMeta, request: orch_lib.Request,
                  text: str, finish_reason: str,
                  tokenizer: Any = None,
                  extra_choices: Optional[List[Tuple[
                      orch_lib.Request, str, str]]] = None
                  ) -> Dict[str, Any]:
    """One response document; extra_choices carries the n>1 siblings
    as (request, text, finish_reason) for indices 1..n-1."""
    all_choices = [(request, text, finish_reason)]
    all_choices += extra_choices or []

    choices = []
    for idx, (req, txt, reason) in enumerate(all_choices):
        if meta.kind == 'chat':
            choice: Dict[str, Any] = {
                'index': idx,
                'message': {'role': 'assistant', 'content': txt},
                'finish_reason': reason,
            }
        else:
            choice = {'index': idx, 'text': txt,
                      'finish_reason': reason}
        if req.logprobs and tokenizer is not None:
            choice['logprobs'] = _logprobs_block(meta, req, tokenizer,
                                                 txt)
        choices.append(choice)
    obj = 'chat.completion' if meta.kind == 'chat' else 'text_completion'
    usage = _usage(meta, request)
    for req, _, _ in all_choices[1:]:
        usage['completion_tokens'] += len(req.output_tokens)
        usage['total_tokens'] += len(req.output_tokens)
    return {'id': meta.response_id, 'object': obj,
            'created': meta.created, 'model': meta.model_id,
            'choices': choices, 'usage': usage}


def chunk_body(meta: RequestMeta, text: str,
               finish_reason: Optional[str],
               first: bool = False) -> Dict[str, Any]:
    if meta.kind == 'chat':
        delta: Dict[str, Any] = {}
        if first:
            delta['role'] = 'assistant'
        if text:
            delta['content'] = text
        choice: Dict[str, Any] = {'index': 0, 'delta': delta,
                                  'finish_reason': finish_reason}
        obj = 'chat.completion.chunk'
    else:
        choice = {'index': 0, 'text': text,
                  'finish_reason': finish_reason}
        obj = 'text_completion'
    return {'id': meta.response_id, 'object': obj,
            'created': meta.created, 'model': meta.model_id,
            'choices': [choice]}


def sse(payload: Dict[str, Any]) -> bytes:
    return f'data: {json.dumps(payload)}\n\n'.encode()


SSE_DONE = b'data: [DONE]\n\n'


class StreamEmitter:
    """Incremental text emission with stop-sequence hold-back.

    Deltas are only released once they can no longer be a prefix of a
    stop sequence still in flight; on a stop hit, the text before the
    stop is emitted and ``finished`` flips so the caller can cancel
    the underlying request.
    """

    def __init__(self, tokenizer: Any, stops: List[str]) -> None:
        self._decoder = tokenizer_lib.IncrementalDecoder(tokenizer)
        self._stops = stops
        self._holdback = max((len(s) for s in stops), default=1) - 1
        self._text = ''
        self._sent = 0
        self.finished = False
        self.finish_reason: Optional[str] = None

    def push(self, tokens: List[int], final: bool = False) -> str:
        """Feed the full token list so far; returns newly safe text."""
        if self.finished:
            return ''
        self._text += self._decoder.delta(tokens, final=final)
        idx = find_stop(self._text, self._stops)
        if idx != -1:
            self.finished = True
            self.finish_reason = 'stop'
            out = self._text[self._sent:idx]
            self._sent = idx
            return out
        safe_upto = len(self._text) if final else \
            max(self._sent, len(self._text) - self._holdback)
        out = self._text[self._sent:safe_upto]
        self._sent = safe_upto
        return out
