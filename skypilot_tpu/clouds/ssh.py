"""SSH cloud: BYO machine pools (twin of sky/clouds/ssh.py + provision/ssh).

Pools are declared in ``~/.xsky/ssh_node_pools.yaml``:

    my-pool:
      user: ubuntu                  # pool-wide defaults
      identity_file: ~/.ssh/id_rsa
      hosts:
        - ip: 10.0.0.1
        - ip: 10.0.0.2
          user: other               # per-host override

A pool is a "region"; provisioning allocates hosts from the pool (no
cloud API — reachability is the only health check). Cost is 0, like
Kubernetes: the optimizer prefers BYO capacity when it fits.
"""
from __future__ import annotations

import os
import typing
from typing import Any, Dict, Iterator, List, Optional, Tuple

import yaml

from skypilot_tpu.clouds import cloud as cloud_lib
from skypilot_tpu.utils import registry

if typing.TYPE_CHECKING:
    from skypilot_tpu import resources as resources_lib

_Features = cloud_lib.CloudImplementationFeatures

POOLS_PATH = '~/.xsky/ssh_node_pools.yaml'


def load_pools(path: Optional[str] = None) -> Dict[str, Dict[str, Any]]:
    path = os.path.expanduser(path or
                              os.environ.get('XSKY_SSH_NODE_POOLS',
                                             POOLS_PATH))
    try:
        with open(path, encoding='utf-8') as f:
            data = yaml.safe_load(f) or {}
    except FileNotFoundError:
        return {}
    pools: Dict[str, Dict[str, Any]] = {}
    for name, spec in data.items():
        spec = dict(spec or {})
        hosts = []
        for h in spec.get('hosts', []):
            if isinstance(h, str):
                h = {'ip': h}
            hosts.append({
                'ip': h['ip'],
                'user': h.get('user', spec.get('user', 'root')),
                'identity_file': os.path.expanduser(
                    h.get('identity_file',
                          spec.get('identity_file', '~/.ssh/id_rsa'))),
                'ssh_port': int(h.get('ssh_port', spec.get('ssh_port',
                                                           22))),
            })
        pools[name] = {'hosts': hosts}
    return pools


def _select_pools(infra: Optional[str]) -> Dict[str, Dict[str, Any]]:
    """Resolve `--infra` (None means every declared pool)."""
    pools = load_pools()
    if infra is not None:
        if infra not in pools:
            raise ValueError(f'Unknown SSH pool {infra!r}; known: '
                             f'{sorted(pools)}')
        pools = {infra: pools[infra]}
    if not pools:
        raise ValueError(f'No SSH node pools defined in {POOLS_PATH}.')
    return pools


def _host_runner(host: Dict[str, Any]):
    from skypilot_tpu.utils import command_runner
    return command_runner.SSHCommandRunner(
        host['ip'], host['user'], host['identity_file'],
        port=host['ssh_port'])


def pool_up(infra: Optional[str] = None,
            probe_timeout_s: float = 10.0) -> Dict[str, Any]:
    """Bring up SSH node pool(s): probe every host over ssh.

    Twin of ``sky ssh up`` (sky/client/cli/command.py:5189). The
    reference bootstraps Kubernetes onto the pool machines; here the
    pool itself is the launch substrate, so bring-up = validate that
    every declared host is reachable with the declared credentials (and
    warm the ssh ControlMaster, so the first ``xsky launch`` against
    the pool skips the connection setup cost).

    Returns ``{pool: {'ok': bool, 'hosts': [{'ip', 'ok', 'error'}]}}``.
    A pool with no hosts is not-ok (nothing can launch on it).
    """
    report: Dict[str, Any] = {}
    for name, spec in sorted(_select_pools(infra).items()):
        rows: List[Dict[str, Any]] = []
        for host in spec['hosts']:
            runner = _host_runner(host)
            try:
                returncode = runner.run('true', timeout=probe_timeout_s)
                ok = returncode == 0
                error = None if ok else f'probe exited {returncode}'
            except Exception as e:  # pylint: disable=broad-except
                ok, error = False, str(e)
            rows.append({'ip': host['ip'], 'ok': ok, 'error': error})
        report[name] = {'ok': bool(rows) and all(r['ok'] for r in rows),
                        'hosts': rows}
    return report


def pool_down(infra: Optional[str] = None,
              probe_timeout_s: float = 10.0) -> Dict[str, Any]:
    """Tear down SSH node pool(s): twin of ``sky ssh down``
    (sky/client/cli/command.py:5212).

    The reference removes its Kubernetes install from the machines.
    Here teardown means: terminate the state-DB records of clusters
    allocated from the pool, release their host allocations, and
    best-effort kill any lingering framework agent daemons on each
    host (the machines themselves are BYO and never touched further).

    Returns ``{pool: {'released_clusters': [...], 'hosts_cleaned': N}}``.
    """
    from skypilot_tpu import state
    from skypilot_tpu.provision.ssh import instance as ssh_instance
    report: Dict[str, Any] = {}
    for name, spec in sorted(_select_pools(infra).items()):
        released = ssh_instance.release_pool(name)
        for cluster_name in released:
            # The hosts under the cluster are being reclaimed: the
            # cluster record is unrecoverable, mirror that in the DB.
            state.remove_cluster(cluster_name, terminate=True)
        cleaned = 0
        for host in spec['hosts']:
            runner = _host_runner(host)
            try:
                # [s]kypilot: the bracket trick keeps pkill -f from
                # matching the remote shell that carries this very
                # command line (it would SIGTERM itself otherwise).
                returncode = runner.run(
                    "pkill -f '[s]kypilot_tpu.agent' || true",
                    timeout=probe_timeout_s)
                cleaned += int(returncode == 0)
            except Exception:  # pylint: disable=broad-except
                pass  # unreachable host: nothing to clean
        report[name] = {'released_clusters': released,
                        'hosts_cleaned': cleaned}
    return report


@registry.CLOUD_REGISTRY.register()
class SSH(cloud_lib.Cloud):
    _REPR = 'SSH'

    @property
    def is_free_capacity(self) -> bool:
        return True  # BYO capacity: $0 means free, rank first

    def unsupported_features_for_resources(
        self, resources: 'resources_lib.Resources'
    ) -> Dict[_Features, str]:
        del resources
        return {
            _Features.STOP: 'BYO machines are never stopped by us.',
            _Features.AUTOSTOP: 'Autostop releases the hosts instead.',
            _Features.SPOT_INSTANCE: 'No spot market for BYO machines.',
            _Features.OPEN_PORTS: 'Manage firewalls on your own hosts.',
            _Features.CUSTOM_DISK_TIER: 'BYO disks.',
        }

    # ---- placement: pools are regions ----

    def regions_with_offering(self, instance_type: str,
                              accelerators: Optional[Dict[str, Any]],
                              use_spot: bool, region: Optional[str],
                              zone: Optional[str]) -> List[cloud_lib.Region]:
        del instance_type, accelerators, zone
        if use_spot:
            return []
        pools = load_pools()
        names = [region] if region else sorted(pools)
        return [cloud_lib.Region(n, [n]) for n in names if n in pools]

    def zones_provision_loop(self, region: str, num_nodes: int,
                             instance_type: str,
                             accelerators: Optional[Dict[str, Any]] = None,
                             use_spot: bool = False) -> Iterator[List[str]]:
        del num_nodes, instance_type, accelerators, use_spot
        yield [region]

    # ---- pricing ----

    def instance_type_to_hourly_cost(self, instance_type, use_spot,
                                     region=None, zone=None) -> float:
        return 0.0

    def accelerators_to_hourly_cost(self, accelerators, use_spot,
                                    region=None, zone=None) -> float:
        return 0.0

    # ---- feasibility ----

    def instance_type_exists(self, instance_type: str) -> bool:
        return True  # free-form: hosts are whatever the user racked

    def validate_region_zone(self, region, zone) -> None:
        if region is not None and region not in load_pools():
            raise ValueError(f'Unknown SSH pool {region!r}; known: '
                             f'{sorted(load_pools())}')

    def get_default_instance_type(self, cpus=None, memory=None):
        return 'byo'

    def get_feasible_launchable_resources(
        self, resources: 'resources_lib.Resources'
    ) -> Tuple[List['resources_lib.Resources'], List[str]]:
        if resources.use_spot or not load_pools():
            return [], []
        return [resources.copy(cloud=self.name,
                               instance_type=resources.instance_type or
                               'byo')], []

    # ---- provisioner handoff ----

    def make_deploy_resources_variables(
            self, resources: 'resources_lib.Resources', cluster_name: str,
            region: str, zone: Optional[str]) -> Dict[str, Any]:
        return {
            'cluster_name': cluster_name,
            'pool': region,
            'num_hosts_per_node': 1,
        }

    def check_credentials(self) -> Tuple[bool, Optional[str]]:
        pools = load_pools()
        if not pools:
            return False, (f'No SSH node pools defined in {POOLS_PATH}.')
        return True, None
