"""Managed-job recovery tests: real controller subprocesses + fake cloud.

Preemption is simulated by terminating the task cluster out-of-band,
exactly like the reference smoke tests do with real instances
(tests/smoke_tests/test_managed_job.py; smoke_tests_utils.py:33-36) —
but hermetic.
"""
import time

import pytest

from skypilot_tpu import Resources, Task
from skypilot_tpu.jobs import core as jobs_core
from skypilot_tpu.jobs import state as jobs_state


pytestmark = pytest.mark.slow  # heavy tier: subprocess e2e / jit compiles


@pytest.fixture
def jobs_env(fake_cluster_env, monkeypatch, tmp_path):
    monkeypatch.setenv('XSKY_JOBS_DB', str(tmp_path / 'managed_jobs.db'))
    monkeypatch.setenv('XSKY_JOBS_POLL_INTERVAL', '0.3')
    monkeypatch.setenv('XSKY_JOBS_LOG_DIR', str(tmp_path / 'jobs_logs'))
    yield fake_cluster_env


def _wait_for(job_id, statuses, timeout=60):
    deadline = time.time() + timeout
    while time.time() < deadline:
        record = jobs_state.get_job(job_id)
        if record and record['status'] in statuses:
            return record
        time.sleep(0.2)
    record = jobs_state.get_job(job_id)
    raise TimeoutError(
        f'job {job_id} stuck at '
        f'{record["status"] if record else None}')


def _wait_reaped(env, cluster_name, timeout=20):
    """Terminal status lands BEFORE cleanup by design (waiters must not
    see RUNNING while teardown runs), so reap checks must poll."""
    deadline = time.time() + timeout
    while time.time() < deadline and env.cluster_exists(cluster_name):
        time.sleep(0.2)
    assert not env.cluster_exists(cluster_name)


def _tpu_task(run, **recovery):
    t = Task('mjob', run=run)
    r = Resources(accelerators='tpu-v5e-8', use_spot=True,
                  job_recovery=recovery or None)
    t.set_resources(r)
    return t


class TestManagedJobs:

    def test_job_succeeds(self, jobs_env):
        job_id = jobs_core.launch(_tpu_task('echo managed-ok'))
        record = _wait_for(
            job_id, [jobs_state.ManagedJobStatus.SUCCEEDED])
        assert record['recovery_count'] == 0
        # Task cluster cleaned up after success.
        _wait_reaped(jobs_env, record['cluster_name'])

    def test_preemption_recovery(self, jobs_env):
        """THE spot story: preempt mid-run → recover → complete."""
        job_id = jobs_core.launch(
            _tpu_task('sleep 4; echo survived'))
        record = _wait_for(job_id,
                           [jobs_state.ManagedJobStatus.RUNNING])
        cluster = record['cluster_name']
        # Let the job actually start, then preempt out-of-band.
        time.sleep(1.0)
        jobs_env.preempt_cluster(cluster)
        record = _wait_for(
            job_id, [jobs_state.ManagedJobStatus.SUCCEEDED], timeout=90)
        assert record['recovery_count'] >= 1

    def test_user_failure_restart_budget(self, jobs_env):
        """exit 1 with max_restarts_on_errors=1: restart once, then FAILED."""
        job_id = jobs_core.launch(
            _tpu_task('exit 1', strategy='failover',
                      max_restarts_on_errors=1))
        record = _wait_for(job_id,
                           [jobs_state.ManagedJobStatus.FAILED],
                           timeout=90)
        assert 'FAILED' in record['status'].value

    def test_infeasible_fails_fast(self, jobs_env):
        task = Task('ghost', run='echo x')
        task.set_resources(Resources(accelerators={'H999': 8}))
        job_id = jobs_core.launch(task)
        record = _wait_for(
            job_id, [jobs_state.ManagedJobStatus.FAILED_NO_RESOURCE],
            timeout=60)
        assert record['failure_reason']

    def test_cancel_running(self, jobs_env):
        job_id = jobs_core.launch(_tpu_task('sleep 120'))
        record = _wait_for(job_id,
                           [jobs_state.ManagedJobStatus.RUNNING])
        jobs_core.cancel(job_id)
        record = jobs_state.get_job(job_id)
        assert record['status'] == jobs_state.ManagedJobStatus.CANCELLED
        # Cluster reaped.
        _wait_reaped(jobs_env, record['cluster_name'])

    def test_watch_logs_streams_and_reports_epoch(self, jobs_env):
        """Incremental managed-job tail: data arrives while RUNNING,
        epoch pins the (cluster, cluster-job) pair, the persisted
        cluster_job_id powers it, and terminal status ends the tail."""
        job_id = jobs_core.launch(
            _tpu_task('echo watch-me; sleep 3; echo done-watching'))
        _wait_for(job_id, [jobs_state.ManagedJobStatus.RUNNING])
        record = jobs_state.get_job(job_id)
        assert record['cluster_job_id'] is not None

        offset, seen, epoch = 0, '', None
        deadline = time.time() + 60
        while time.time() < deadline and 'watch-me' not in seen:
            poll = jobs_core.watch_logs(job_id, offset=offset)
            seen += poll['data']
            offset = poll['offset']
            epoch = poll.get('epoch') or epoch
            time.sleep(0.3)
        assert 'watch-me' in seen
        assert epoch == (f"{record['cluster_name']}#task0"
                         f"#{record['cluster_job_id']}")

        _wait_for(job_id, [jobs_state.ManagedJobStatus.SUCCEEDED])
        _wait_reaped(jobs_env, record['cluster_name'])
        # The cluster is gone, but the controller archived the log
        # before teardown: the tail continues from the SAME offset and
        # the final chunk is never lost to the reap race.
        final = jobs_core.watch_logs(job_id, offset=offset)
        assert final['status'] == 'SUCCEEDED'
        assert 'done-watching' in (seen + final['data'])
        # One-shot logs serve the full archive after teardown too.
        full = jobs_core.tail_logs(job_id)
        assert 'watch-me' in full and 'done-watching' in full
        # Unknown job: tail stops via NOT_FOUND, no exception.
        assert jobs_core.watch_logs(99999)['status'] == 'NOT_FOUND'

    def test_queue_listing(self, jobs_env):
        job_id = jobs_core.launch(_tpu_task('echo q'))
        _wait_for(job_id, [jobs_state.ManagedJobStatus.SUCCEEDED])
        rows = jobs_core.queue()
        assert rows[0]['job_id'] == job_id
        assert rows[0]['status'] == 'SUCCEEDED'


class TestPipelines:
    """Chain-of-tasks managed jobs (twin of the reference's chain-DAG
    pipelines, sky/jobs/controller.py:68-95)."""

    def test_pipeline_runs_tasks_sequentially(self, jobs_env, tmp_path):
        marker = tmp_path / 'order.txt'
        tasks = [
            _tpu_task(f'echo one >> {marker}'),
            _tpu_task(f'echo two >> {marker}'),
        ]
        tasks[0].name, tasks[1].name = 'prep', 'train'
        job_id = jobs_core.launch(tasks, name='pipe')
        record = _wait_for(
            job_id, [jobs_state.ManagedJobStatus.SUCCEEDED], timeout=90)
        assert record['num_tasks'] == 2
        assert marker.read_text().split() == ['one', 'two']
        # Each task's cluster is torn down.
        _wait_reaped(jobs_env, record['cluster_name'])
        # Queue surfaces chain progress.
        row = [r for r in jobs_core.queue() if r['job_id'] == job_id][0]
        assert row['task'] == '2/2'

    def test_pipeline_failure_stops_chain(self, jobs_env, tmp_path):
        marker = tmp_path / 'never.txt'
        tasks = [
            _tpu_task('exit 3'),
            _tpu_task(f'touch {marker}'),
        ]
        job_id = jobs_core.launch(tasks)
        record = _wait_for(
            job_id, [jobs_state.ManagedJobStatus.FAILED], timeout=90)
        assert record['current_task'] == 0     # died on the first link
        assert not marker.exists()             # second task never ran
        _wait_reaped(jobs_env, record['cluster_name'])

    def test_single_task_yaml_unchanged(self, jobs_env):
        """A one-task job keeps task=None in queue (no pipeline UI)."""
        job_id = jobs_core.launch(_tpu_task('echo solo'))
        _wait_for(job_id, [jobs_state.ManagedJobStatus.SUCCEEDED])
        row = [r for r in jobs_core.queue() if r['job_id'] == job_id][0]
        assert row['task'] is None


class TestChainYaml:

    def test_load_chain_multi_doc(self, tmp_path):
        path = tmp_path / 'pipe.yaml'
        path.write_text(
            'name: my-pipe\n'
            '---\n'
            'name: a\nrun: echo a\n'
            '---\n'
            'name: b\nrun: echo b\n')
        name, tasks = Task.load_chain(str(path))
        assert name == 'my-pipe'
        assert [t.name for t in tasks] == ['a', 'b']

    def test_load_chain_single_doc(self, tmp_path):
        path = tmp_path / 'one.yaml'
        path.write_text('name: solo\nrun: echo x\n')
        name, tasks = Task.load_chain(str(path))
        assert name is None
        assert len(tasks) == 1 and tasks[0].name == 'solo'


class TestJobsScheduler:
    """Bounded controller parallelism (twin of sky/jobs/scheduler.py
    caps, :295-315)."""

    def test_parallelism_cap_honored(self, jobs_env, monkeypatch):
        """20 jobs, launching cap 4: never >4 launching at once, all
        complete."""
        monkeypatch.setenv('XSKY_JOBS_MAX_LAUNCHING', '4')
        monkeypatch.setenv('XSKY_JOBS_MAX_PARALLEL', '64')
        job_ids = [jobs_core.launch(_tpu_task('echo n')) for _ in range(20)]

        max_launching = 0
        deadline = time.time() + 240
        pending = set(job_ids)
        while pending and time.time() < deadline:
            counts = jobs_state.schedule_state_counts()
            max_launching = max(
                max_launching,
                counts.get(jobs_state.ScheduleState.LAUNCHING, 0))
            for jid in list(pending):
                record = jobs_state.get_job(jid)
                if record and record['status'].is_terminal():
                    pending.discard(jid)
            time.sleep(0.1)
        assert not pending, f'jobs never finished: {sorted(pending)}'
        assert max_launching <= 4, max_launching
        assert max_launching >= 2, 'no parallelism observed'
        for jid in job_ids:
            record = jobs_state.get_job(jid)
            assert record['status'] == \
                jobs_state.ManagedJobStatus.SUCCEEDED, record
        # schedule_state flips to DONE when the controller process
        # exits — AFTER the terminal status (cleanup archives the task
        # log and tears the cluster down first), so poll.
        deadline = time.time() + 20
        while time.time() < deadline:
            states = {jid: jobs_state.get_job(jid)['schedule_state']
                      for jid in job_ids}
            if all(s == jobs_state.ScheduleState.DONE
                   for s in states.values()):
                break
            time.sleep(0.2)
        assert all(s == jobs_state.ScheduleState.DONE
                   for s in states.values()), states

    def test_waiting_jobs_queue_behind_cap(self, jobs_env, monkeypatch):
        """With cap 1, the second job stays WAITING until the first
        controller frees the slot."""
        monkeypatch.setenv('XSKY_JOBS_MAX_LAUNCHING', '1')
        monkeypatch.setenv('XSKY_JOBS_MAX_PARALLEL', '1')
        first = jobs_core.launch(_tpu_task('sleep 3'))
        second = jobs_core.launch(_tpu_task('echo late'))
        record = jobs_state.get_job(second)
        assert record['schedule_state'] == jobs_state.ScheduleState.WAITING
        _wait_for(first, [jobs_state.ManagedJobStatus.SUCCEEDED],
                  timeout=90)
        _wait_for(second, [jobs_state.ManagedJobStatus.SUCCEEDED],
                  timeout=90)

    def test_cancel_waiting_job_frees_nothing_but_terminates(
            self, jobs_env, monkeypatch):
        monkeypatch.setenv('XSKY_JOBS_MAX_LAUNCHING', '1')
        monkeypatch.setenv('XSKY_JOBS_MAX_PARALLEL', '1')
        first = jobs_core.launch(_tpu_task('sleep 5'))
        second = jobs_core.launch(_tpu_task('echo never'))
        jobs_core.cancel(second)
        record = jobs_state.get_job(second)
        assert record['status'] == jobs_state.ManagedJobStatus.CANCELLED
        _wait_for(first, [jobs_state.ManagedJobStatus.SUCCEEDED],
                  timeout=90)


class TestRemoteController:
    """Controller-as-cluster mode (twin of jobs-controller.yaml.j2)."""

    def test_launch_via_remote_controller(self, jobs_env, monkeypatch):
        monkeypatch.setenv('XSKY_JOBS_CONTROLLER_REMOTE', '1')
        job_id = jobs_core.launch(_tpu_task('echo remote-ok'), wait=True,
                                  timeout_s=120)
        # The controller cluster itself was provisioned.
        from skypilot_tpu import state as state_lib
        record = state_lib.get_cluster_from_name('xsky-jobs-controller')
        assert record is not None
        assert record['status'] == state_lib.ClusterStatus.UP
        # Verbs round-trip through the remote relay.
        rows = jobs_core.queue()
        row = [r for r in rows if r['job_id'] == job_id][0]
        assert row['status'] == 'SUCCEEDED'


class TestEagerNextRegion:

    def test_recovery_avoids_preempted_region(self, jobs_env):
        """eager_next_region seeds the preempted region into the
        failover blocklist through execution.launch (no backend-private
        calls)."""
        job_id = jobs_core.launch(
            _tpu_task('sleep 6', strategy='eager_next_region'))
        record = _wait_for(job_id,
                           [jobs_state.ManagedJobStatus.RUNNING])
        cluster = record['cluster_name']
        from skypilot_tpu import state as state_lib
        first_region = state_lib.get_cluster_from_name(
            cluster)['handle'].launched_resources.region
        time.sleep(1.0)
        jobs_env.preempt_cluster(cluster)
        record = _wait_for(
            job_id, [jobs_state.ManagedJobStatus.SUCCEEDED], timeout=90)
        assert record['recovery_count'] >= 1
        # The relaunch must have landed outside the preempted region.
        events = jobs_env.provision_regions(cluster)
        assert events and events[0] == first_region, events
        assert any(r != first_region for r in events[1:]), events


class TestControllerHA:
    """HA controller recovery (VERDICT r3 #9): a managed job survives
    its controller process dying (server/pod restart) — the scheduler
    re-execs a controller that resumes from persisted state."""

    def test_job_survives_controller_kill(self, jobs_env):
        import os
        import signal

        from skypilot_tpu.jobs import scheduler

        job_id = jobs_core.launch(_tpu_task('sleep 5; echo survived'))
        record = _wait_for(job_id,
                           [jobs_state.ManagedJobStatus.RUNNING])
        pid = record['controller_pid']
        assert pid
        os.kill(pid, signal.SIGKILL)
        deadline = time.time() + 10
        while time.time() < deadline:
            try:
                os.kill(pid, 0)
                time.sleep(0.1)
            except ProcessLookupError:
                break
        # The restart trigger: any scheduler tick (API-server startup
        # runs one).
        scheduler.maybe_schedule_next_jobs()
        record = _wait_for(
            job_id, [jobs_state.ManagedJobStatus.SUCCEEDED], timeout=90)
        new_pid = record['controller_pid']
        assert new_pid != pid
        # Reaching steady state again cleared the respawn budget.
        assert record['controller_respawns'] == 0

    def test_respawn_budget_bounds_crash_loops(self, jobs_env,
                                               monkeypatch):
        """A controller that keeps dying must not re-exec forever."""
        import subprocess

        from skypilot_tpu.jobs import scheduler

        monkeypatch.setenv('XSKY_JOBS_MAX_CONTROLLER_RESPAWNS', '1')
        real_popen = subprocess.Popen

        def crashy_popen(cmd, **kwargs):
            if 'skypilot_tpu.jobs.controller' in ' '.join(cmd):
                cmd = ['sh', '-c', 'exit 1']
            return real_popen(cmd, **kwargs)

        monkeypatch.setattr(subprocess, 'Popen', crashy_popen)
        job_id = jobs_core.launch(_tpu_task('echo never-runs'))
        deadline = time.time() + 30
        while time.time() < deadline:
            scheduler.maybe_schedule_next_jobs()
            record = jobs_state.get_job(job_id)
            if record['status'] == \
                    jobs_state.ManagedJobStatus.FAILED_CONTROLLER:
                break
            time.sleep(0.3)
        record = jobs_state.get_job(job_id)
        assert record['status'] == \
            jobs_state.ManagedJobStatus.FAILED_CONTROLLER
        assert 'respawn budget' in (record['failure_reason'] or '')
        assert record['schedule_state'] is jobs_state.ScheduleState.DONE


class TestPipelineHA:

    def test_pipeline_resumes_from_current_task_after_kill(
            self, jobs_env, tmp_path):
        """Adversarial HA (VERDICT r4 weak #2): SIGKILL the controller
        while chain task 0 runs; the respawned controller must resume
        from current_task — task 0 must NOT rerun (its side effect
        stays single-shot) and the chain must complete."""
        import os
        import signal

        from skypilot_tpu.jobs import scheduler

        marker = tmp_path / 'task0_runs'
        t0 = _tpu_task(f'echo run >> {marker}; sleep 6')
        t1 = _tpu_task('echo second done')
        job_id = jobs_core.launch([t0, t1])
        record = _wait_for(job_id,
                           [jobs_state.ManagedJobStatus.RUNNING])
        # Let task 0 actually start (marker written), then kill.
        deadline = time.time() + 30
        while time.time() < deadline and not marker.exists():
            time.sleep(0.2)
        assert marker.exists(), 'task 0 never started'
        pid = record['controller_pid']
        os.kill(pid, signal.SIGKILL)
        try:
            os.waitpid(pid, 0)   # reap: a zombie child never
        except ChildProcessError:  # raises ProcessLookupError
            pass
        scheduler.maybe_schedule_next_jobs()
        record = _wait_for(
            job_id, [jobs_state.ManagedJobStatus.SUCCEEDED],
            timeout=120)
        assert record['num_tasks'] == 2
        assert record['current_task'] == 1
        # Task 0's command ran exactly once across the kill/resume...
        runs = marker.read_text().strip().splitlines()
        assert len(runs) >= 1
