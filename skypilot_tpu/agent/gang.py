"""Gang launcher: all-or-nothing job start across every TPU host.

Replaces the reference's RayCodeGen + STRICT_SPREAD placement group
(sky/backends/cloud_vm_ray_backend.py:394-538) with a direct per-host
launcher driven from the head:

  * one process per TPU host (a "node" that is a pod slice contributes
    all its hosts — `InstanceInfo.slice_id`/`host_index`);
  * rank env injection (XSKY_* twins of SKYPILOT_NODE_RANK/... from
    cloud_vm_ray_backend.py:606-670 and constants.py:350-353), plus the
    JAX/libtpu coordinator env (`jax.distributed` over ICI, megascale
    vars across slices) the reference leaves to user recipes;
  * gang semantics: if any host fails to start or exits non-zero, every
    other host's process is killed (twin of the placement-group barrier +
    Ray task failure propagation).
"""
from __future__ import annotations

import dataclasses
import os
import re
import subprocess
import time
from typing import Dict, List, Optional, Sequence

from skypilot_tpu import sky_logging
from skypilot_tpu.provision import common as provision_common
from skypilot_tpu.utils import chaos
from skypilot_tpu.utils import command_runner as runner_lib
from skypilot_tpu.utils import resilience

logger = sky_logging.init_logger(__name__)

COORDINATOR_PORT = 8476
MEGASCALE_PORT = 8477


@dataclasses.dataclass
class HostSpec:
    """One gang participant."""
    runner: runner_lib.CommandRunner
    env: Dict[str, str]
    host_rank: int


def build_host_envs(
        cluster_info: provision_common.ClusterInfo,
        job_envs: Optional[Dict[str, str]] = None,
        exclude_hosts: Optional[Sequence[int]] = None
        ) -> List[Dict[str, str]]:
    """Per-host environment for gang launch, in rank order.

    Derives node ranks, host ranks, and the JAX/libtpu coordinator wiring
    from the host inventory alone. ``exclude_hosts`` (elastic shrink:
    positions in the sorted-host order) drops those hosts and renumbers
    ranks contiguously over the survivors — the gang comes up as a
    smaller world (new coordinator = surviving host 0), which is
    exactly the reconfiguration ``jax.distributed`` needs to remesh
    over the surviving ranks.
    """
    hosts = cluster_info.sorted_instances()
    if exclude_hosts:
        dropped = set(int(r) for r in exclude_hosts)
        hosts = [h for i, h in enumerate(hosts) if i not in dropped]
    num_hosts = len(hosts)

    # Logical nodes (for XSKY_NODE_RANK): group by node_index tag.
    node_ids: List[str] = []
    node_of_host: List[int] = []
    node_head_ip: Dict[int, str] = {}
    for h in hosts:
        node_key = h.tags.get('node_index', '0')
        if node_key not in node_ids:
            node_ids.append(node_key)
        node_idx = node_ids.index(node_key)
        node_of_host.append(node_idx)
        node_head_ip.setdefault(node_idx, h.internal_ip)

    # Slices (for megascale): group by slice_id.
    slice_ids: List[Optional[str]] = []
    for h in hosts:
        if h.slice_id not in slice_ids:
            slice_ids.append(h.slice_id)
    num_slices = len([s for s in slice_ids if s is not None]) or 1
    slice_hosts: Dict[Optional[str], List[provision_common.InstanceInfo]] = {}
    for h in hosts:
        slice_hosts.setdefault(h.slice_id, []).append(h)

    coordinator_ip = hosts[0].internal_ip
    envs: List[Dict[str, str]] = []
    for rank, h in enumerate(hosts):
        env = dict(job_envs or {})
        env.update({
            'XSKY_NODE_RANK': str(node_of_host[rank]),
            'XSKY_NUM_NODES': str(len(node_ids)),
            'XSKY_NODE_IPS': '\n'.join(
                node_head_ip[i] for i in range(len(node_ids))),
            'XSKY_HOST_RANK': str(rank),
            'XSKY_NUM_HOSTS': str(num_hosts),
            'XSKY_COORDINATOR_ADDRESS':
                f'{coordinator_ip}:{COORDINATOR_PORT}',
        })
        if h.slice_id is not None:
            peers = slice_hosts[h.slice_id]
            # Worker id = position among SURVIVING slice peers, not the
            # provision-time host_index: after an elastic shrink the
            # hostnames list below only names survivors, and libtpu
            # requires worker ids to index into it contiguously.
            env.update({
                'TPU_WORKER_ID': str(peers.index(h)),
                'TPU_WORKER_HOSTNAMES': ','.join(
                    p.internal_ip for p in peers),
            })
            if num_slices > 1:
                slice_index = [s for s in slice_ids
                               if s is not None].index(h.slice_id)
                env.update({
                    'MEGASCALE_COORDINATOR_ADDRESS':
                        f'{coordinator_ip}:{MEGASCALE_PORT}',
                    'MEGASCALE_NUM_SLICES': str(num_slices),
                    'MEGASCALE_SLICE_ID': str(slice_index),
                })
        envs.append(env)
    return envs


@dataclasses.dataclass
class GangResult:
    returncodes: List[int]

    @property
    def success(self) -> bool:
        return all(rc == 0 for rc in self.returncodes)

    @property
    def first_failure_rank(self) -> Optional[int]:
        """The host that *caused* the failure: positive exit codes
        (command failures) outrank negative ones (hosts we killed in
        response)."""
        for i, rc in enumerate(self.returncodes):
            if rc > 0:
                return i
        for i, rc in enumerate(self.returncodes):
            if rc != 0:
                return i
        return None


# ssh transport failure exit code (the client's, not the command's).
_SSH_EXIT_CODE = 255
# A host start failing with ssh-transport rc inside this window is
# retried once (transient drop during fan-out at scale).
START_RETRY_WINDOW_S = 10.0


# Live per-host launcher processes of the in-flight gang_launch. Each
# child runs in its own session (so ITS grandchildren die with it), which
# means a signal to the job_runner's process group does NOT reach them —
# kill_active() is how a SIGTERM'd runner takes its gang down with it.
# xskylint: disable=lock-discipline -- kill_active runs inside signal
# handlers, where acquiring a lock the interrupted main thread may hold
# deadlocks the runner at the exact moment it must die; every mutation
# is a single GIL-atomic list op (append/remove/clear) and iteration
# snapshots via list(ACTIVE_PROCS) first.
ACTIVE_PROCS: List[subprocess.Popen] = []


def kill_active() -> None:
    """Kill every live gang child (called from signal handlers)."""
    for p in list(ACTIVE_PROCS):
        if p.poll() is None:
            _kill_tree(p, sig_kill=True)
    ACTIVE_PROCS.clear()


def _kill_tree(p: subprocess.Popen, sig_kill: bool = False) -> None:
    """Signal the host process's whole session (runners start each
    command with start_new_session=True), falling back to the direct
    child."""
    import signal as signal_lib
    sig = signal_lib.SIGKILL if sig_kill else signal_lib.SIGTERM
    try:
        os.killpg(os.getpgid(p.pid), sig)
    except (ProcessLookupError, PermissionError, OSError):
        try:
            if sig_kill:
                p.kill()
            else:
                p.terminate()
        except ProcessLookupError:
            pass


def detect_num_hosts(log_dir: str) -> int:
    """Host count from the per-host log files present (max rank + 1) —
    lets log consumers (job_cli tail, sync-down regeneration) rebuild
    gang.log without knowing the gang's original size."""
    highest = -1
    try:
        for name in os.listdir(log_dir):
            if name.startswith('host-') and name.endswith('.log'):
                try:
                    highest = max(highest, int(name[5:-4]))
                except ValueError:
                    continue
    except OSError:
        pass
    return highest + 1


def aggregate_logs(log_dir: str, num_hosts: Optional[int] = None,
                   max_bytes_per_host: int = 64 * 1024) -> str:
    """Bounded multiplex of per-host logs into one ``gang.log``.

    At v5p-512 scale (64 hosts) unbounded concatenation would produce
    gigabytes; each host contributes at most its log tail, prefixed
    ``[rank N]`` per line so interleaved pod output stays attributable
    (the tag matches the rank vocabulary of `xsky top` and the trace
    waterfall). ``num_hosts=None`` detects the gang size from the
    host-N.log files present.
    """
    if num_hosts is None:
        num_hosts = detect_num_hosts(log_dir)
    out_path = os.path.join(log_dir, 'gang.log')
    with open(out_path, 'w', encoding='utf-8', errors='replace') as out:
        for rank in range(num_hosts):
            path = os.path.join(log_dir, f'host-{rank}.log')
            if not os.path.exists(path):
                continue
            size = os.path.getsize(path)
            with open(path, 'rb') as f:
                if size > max_bytes_per_host:
                    f.seek(size - max_bytes_per_host)
                    f.readline()  # drop the partial first line
                    out.write(f'[rank {rank}] ... '
                              f'({size - max_bytes_per_host} bytes '
                              'truncated)\n')
                for line in f:
                    out.write(f'[rank {rank}] '
                              f'{line.decode(errors="replace")}')
    return out_path


def gang_launch(runners: Sequence[runner_lib.CommandRunner],
                host_envs: Sequence[Dict[str, str]],
                command: str,
                log_dir: str,
                poll_interval_s: float = 0.2,
                timeout_s: Optional[float] = None,
                cwd: Optional[str] = None) -> GangResult:
    """Start `command` on all hosts; kill everyone on first failure.

    Logs go to ``{log_dir}/host-{rank}.log`` (rank 0 additionally to
    ``run.log`` for `tail_logs` compatibility), with a bounded
    multiplexed ``gang.log`` written at the end. An ssh-transport
    failure (rc 255) within the start window retries that host once
    before it counts as a gang failure.
    """
    assert len(runners) == len(host_envs)
    os.makedirs(log_dir, exist_ok=True)
    # Symlink rank-0's log as run.log BEFORE the gang starts: the live
    # tails (job_cli watch → dashboard / `logs --follow`) poll run.log
    # while the job runs — created only at gang end, every mid-run poll
    # read an empty tail and the whole log arrived in one chunk at
    # completion (or never, when a managed controller reaped the
    # cluster first).
    run_log = os.path.join(log_dir, 'run.log')
    if not os.path.lexists(run_log):   # lexists: catch dangling links
        try:
            os.symlink('host-0.log', run_log)
        except OSError:
            pass
    procs: List[subprocess.Popen] = []

    def _start(rank: int) -> subprocess.Popen:
        log_path = os.path.join(log_dir, f'host-{rank}.log')
        # Chaos point: a rule may raise (start failure) or carry a
        # `returncode` — the host's launcher then exits with that code
        # without running the job, indistinguishable from an ssh
        # transport drop (rc 255 exercises the fan-out retry below).
        rule = chaos.inject('gang.host_start', rank=rank)
        cmd = command
        if rule is not None and rule.get('returncode') is not None:
            cmd = f'exit {int(rule["returncode"])}'
        p = runners[rank].run_async(cmd, env=host_envs[rank],
                                    log_path=log_path, cwd=cwd)
        ACTIVE_PROCS.append(p)
        return p

    try:
        for rank in range(len(runners)):
            procs.append(_start(rank))
    except Exception:
        for p in procs:
            _kill_tree(p, sig_kill=True)
            # _start registered p in ACTIVE_PROCS before the fan-out
            # died; without this, the killed procs stay registered for
            # the life of the runner and every later kill_active()
            # re-signals their (recycled) pids.
            try:
                ACTIVE_PROCS.remove(p)
            except ValueError:
                pass
        raise

    start_time = time.time()
    deadline = start_time + timeout_s if timeout_s else None
    retried = [False] * len(procs)
    returncodes: List[Optional[int]] = [None] * len(procs)
    try:
        _poll_gang(procs, returncodes, retried, _start, start_time,
                   deadline, poll_interval_s)
    finally:
        for p in procs:
            try:
                ACTIVE_PROCS.remove(p)
            except ValueError:
                pass

    # Retry if the start-of-gang symlink attempt failed (transient
    # OSError): by now host-0.log certainly exists.
    if not os.path.lexists(run_log):
        try:
            os.symlink('host-0.log', run_log)
        except OSError:
            pass
    try:
        aggregate_logs(log_dir, len(runners))
    except OSError as e:
        logger.warning(f'gang.log aggregation failed: {e}')
    return GangResult([rc if rc is not None else -1
                       for rc in returncodes])


def _chaos_mid_run_exit(procs, returncodes) -> None:
    """`gang.mid_run_exit` chaos point: kill one live host's process
    tree mid-run (rule may pin `rank`), simulating a worker dying on a
    flaky host — the gang barrier must then take everyone down."""
    try:
        rule = chaos.inject('gang.mid_run_exit')
    except Exception as e:  # pylint: disable=broad-except
        # A rule configured with `error` would otherwise abort the poll
        # loop and orphan every live host process — for this point the
        # fault *is* the kill below, so demote a raise to a fire.
        logger.warning(f'gang.mid_run_exit chaos rule raised ({e}); '
                       'treating as a plain fire.')
        rule = {}
    if rule is None:
        return
    victim = rule.get('rank')
    if victim is None:
        alive = [i for i, rc in enumerate(returncodes)
                 if rc is None and procs[i].poll() is None]
        victim = alive[0] if alive else None
    if victim is not None and 0 <= victim < len(returncodes) and \
            returncodes[victim] is None:
        logger.warning(f'Host {victim}: chaos mid-run kill.')
        _kill_tree(procs[victim], sig_kill=True)


def _poll_gang(procs, returncodes, retried, _start, start_time, deadline,
               poll_interval_s) -> None:
    while True:
        now = time.time()
        _chaos_mid_run_exit(procs, returncodes)
        for i, p in enumerate(procs):
            if returncodes[i] is not None:
                continue
            rc = p.poll()
            if rc == _SSH_EXIT_CODE and not retried[i] and \
                    now - start_time < START_RETRY_WINDOW_S:
                # Transient ssh drop during fan-out: one retry.
                retried[i] = True
                logger.warning(f'Host {i}: ssh start failed (rc 255); '
                               'retrying once.')
                # The dead Popen is being replaced: drop it from
                # ACTIVE_PROCS now, or it leaks there for the life of
                # the runner (the finally block only removes the
                # *current* procs).
                try:
                    ACTIVE_PROCS.remove(p)
                except ValueError:
                    pass
                try:
                    procs[i] = _start(i)
                except Exception as e:  # pylint: disable=broad-except
                    logger.warning(f'Host {i}: retry failed: {e}')
                    returncodes[i] = _SSH_EXIT_CODE
                continue
            returncodes[i] = rc
        failed = [rc for rc in returncodes if rc not in (None, 0)]
        if failed:
            # Gang semantics: one non-zero exit kills the whole job —
            # including each host's process tree, not just the launcher.
            for i, p in enumerate(procs):
                if returncodes[i] is None:
                    _kill_tree(p)
            for i, p in enumerate(procs):
                if returncodes[i] is None:
                    try:
                        returncodes[i] = p.wait(timeout=10)
                    except subprocess.TimeoutExpired:
                        _kill_tree(p, sig_kill=True)
                        returncodes[i] = -9
            break
        if all(rc is not None for rc in returncodes):
            break
        if deadline and time.time() > deadline:
            for i, p in enumerate(procs):
                if returncodes[i] is None:
                    _kill_tree(p, sig_kill=True)
            # In-place: the caller owns this list.
            returncodes[:] = [rc if rc is not None else -15
                              for rc in returncodes]
            break
        resilience.sleep(poll_interval_s)
