"""Catalog core: offering rows + CSV load/save + query helpers.

Twin of the reference's pandas/CSV catalog (sky/catalog/common.py:30-99,
sky/catalog/__init__.py:57-357), redesigned:

  * Plain dataclass rows + list comprehensions instead of pandas (the
    catalogs are a few thousand rows; no heavy dependency needed).
  * TPU offerings are *generated* from the topology database
    (`skypilot_tpu/utils/tpu_topology.py`) by the fetcher, so slice shape /
    host count / HBM are always consistent with what the provisioner and
    mesh builder will use — in the reference these live in disconnected
    CSVs patched by hand (sky/catalog/data_fetchers/fetch_gcp.py:48-83).

Catalog files live in ``skypilot_tpu/catalog/data/<cloud>/catalog.csv`` and
may be refreshed by ``skypilot_tpu/catalog/data_fetchers/fetch_<cloud>.py``
(offline generators with embedded public price snapshots; the reference
downloads hosted CSVs instead, sky/catalog/common.py:30).
"""
from __future__ import annotations

import csv
import dataclasses
import functools
import os
from typing import Callable, Dict, List, Optional

_DATA_DIR = os.path.join(os.path.dirname(__file__), 'data')

CSV_FIELDS = [
    'InstanceType', 'AcceleratorName', 'AcceleratorCount', 'vCPUs',
    'MemoryGiB', 'AcceleratorMemoryGiB', 'Price', 'SpotPrice', 'Region',
    'AvailabilityZone'
]


@dataclasses.dataclass(frozen=True)
class CatalogEntry:
    """One (instance type | TPU slice) × zone offering."""
    instance_type: str          # '' for bare TPU-VM slices
    accelerator_name: str       # '' | 'A100' | 'tpu-v5e-8' (full slice name)
    accelerator_count: float
    vcpus: float
    memory_gib: float
    accelerator_memory_gib: float  # total HBM of the offering
    price: float                # $/hr on-demand (whole offering)
    spot_price: float
    region: str
    zone: str

    @property
    def is_tpu(self) -> bool:
        return self.accelerator_name.startswith('tpu-')

    def to_row(self) -> Dict[str, str]:
        return {
            'InstanceType': self.instance_type,
            'AcceleratorName': self.accelerator_name,
            'AcceleratorCount': f'{self.accelerator_count:g}',
            'vCPUs': f'{self.vcpus:g}',
            'MemoryGiB': f'{self.memory_gib:g}',
            'AcceleratorMemoryGiB': f'{self.accelerator_memory_gib:g}',
            'Price': f'{self.price:.4f}',
            'SpotPrice': f'{self.spot_price:.4f}',
            'Region': self.region,
            'AvailabilityZone': self.zone,
        }

    @classmethod
    def from_row(cls, row: Dict[str, str]) -> 'CatalogEntry':
        return cls(
            instance_type=row['InstanceType'],
            accelerator_name=row['AcceleratorName'],
            accelerator_count=float(row['AcceleratorCount'] or 0),
            vcpus=float(row['vCPUs'] or 0),
            memory_gib=float(row['MemoryGiB'] or 0),
            accelerator_memory_gib=float(row.get('AcceleratorMemoryGiB') or 0),
            price=float(row['Price'] or 0),
            spot_price=float(row['SpotPrice'] or 0),
            region=row['Region'],
            zone=row['AvailabilityZone'],
        )


def catalog_path(cloud: str) -> str:
    return os.path.join(_DATA_DIR, cloud, 'catalog.csv')


def read_catalog_csv(path: str) -> List[CatalogEntry]:
    """Parse one catalog CSV file (shared by the hosted, in-tree and
    live-price readers)."""
    with open(path, newline='', encoding='utf-8') as f:
        return [CatalogEntry.from_row(row) for row in csv.DictReader(f)]


def save_catalog(cloud: str, entries: List[CatalogEntry]) -> str:
    path = catalog_path(cloud)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, 'w', newline='', encoding='utf-8') as f:
        writer = csv.DictWriter(f, fieldnames=CSV_FIELDS)
        writer.writeheader()
        for entry in entries:
            writer.writerow(entry.to_row())
    return path


@functools.lru_cache(maxsize=None)
def load_catalog(cloud: str) -> List[CatalogEntry]:
    """Load a cloud's catalog.

    Resolution: hosted catalog (downloaded + cached, when
    XSKY_CATALOG_URL_BASE is set — catalog/hosted.py) → in-tree CSV →
    auto-generated via the cloud's offline fetcher.
    """
    from skypilot_tpu.catalog import hosted
    hosted_path = hosted.fetch(cloud)
    if hosted_path is not None:
        try:
            return read_catalog_csv(hosted_path)
        except (KeyError, ValueError, OSError) as e:
            # A malformed hosted/cached file must degrade to the
            # in-tree catalog, not break every status/launch.
            import logging
            logging.getLogger(__name__).warning(
                f'Hosted catalog for {cloud} unparseable ({e}); '
                'falling back to the in-tree catalog')
    path = catalog_path(cloud)
    if not os.path.exists(path):
        _maybe_generate(cloud)
    if not os.path.exists(path):
        return []
    return read_catalog_csv(path)


def _maybe_generate(cloud: str) -> None:
    try:
        import importlib
        fetcher = importlib.import_module(
            f'skypilot_tpu.catalog.data_fetchers.fetch_{cloud}')
    except ImportError:
        return
    if hasattr(fetcher, 'generate'):
        save_catalog(cloud, fetcher.generate())


@functools.lru_cache(maxsize=None)
def instance_type_index(cloud: str) -> Dict[str, List[CatalogEntry]]:
    """``{instance_type: [entries]}`` for one cloud's catalog.

    The per-instance-type query helpers below are called per candidate
    inside the optimizer's feasibility/pricing loops; rescanning the
    full entry list each call made those loops O(catalog) per lookup.
    Built lazily from :func:`load_catalog`; invalidated together with
    it by :func:`clear_cache`.
    """
    index: Dict[str, List[CatalogEntry]] = {}
    for e in load_catalog(cloud):
        index.setdefault(e.instance_type, []).append(e)
    return index


def clear_cache() -> None:
    load_catalog.cache_clear()
    instance_type_index.cache_clear()


# --- generic query helpers (used by per-cloud catalog modules) -------------


def filter_entries(cloud: str,
                   predicate: Callable[[CatalogEntry], bool]) -> List[CatalogEntry]:
    return [e for e in load_catalog(cloud) if predicate(e)]


def instance_type_exists(cloud: str, instance_type: str) -> bool:
    return instance_type in instance_type_index(cloud)


def get_vcpus_mem_from_instance_type(
        cloud: str, instance_type: str) -> Optional[tuple]:
    entries = instance_type_index(cloud).get(instance_type)
    if not entries:
        return None
    e = entries[0]
    return (e.vcpus, e.memory_gib)


def get_hourly_cost(cloud: str,
                    instance_type: str,
                    use_spot: bool,
                    region: Optional[str] = None,
                    zone: Optional[str] = None) -> float:
    candidates = [
        e for e in instance_type_index(cloud).get(instance_type, [])
        if (region is None or e.region == region) and
        (zone is None or e.zone == zone)
    ]
    if not candidates:
        raise ValueError(
            f'Instance type {instance_type!r} not found in {cloud} catalog'
            f' (region={region}, zone={zone}).')
    prices = [(e.spot_price if use_spot else e.price) for e in candidates]
    prices = [p for p in prices if p > 0]
    if not prices:
        return 0.0
    return min(prices)


def validate_region_zone(cloud: str, region: Optional[str],
                         zone: Optional[str]) -> None:
    entries = load_catalog(cloud)
    if region is not None and not any(e.region == region for e in entries):
        regions = sorted({e.region for e in entries})
        raise ValueError(f'Region {region!r} not found for {cloud}. '
                         f'Valid: {regions}')
    if zone is not None and not any(
            e.zone == zone and (region is None or e.region == region)
            for e in entries):
        raise ValueError(f'Zone {zone!r} not found for {cloud}'
                         f' (region={region}).')
