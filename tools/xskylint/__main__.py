"""``python -m tools.xskylint`` — see engine.main for flags."""
import sys

from tools.xskylint.engine import main

if __name__ == '__main__':
    sys.exit(main())
