"""Layered YAML configuration (twin of sky/skypilot_config.py:88-113).

Layering, lowest precedence first:
  1. server config   (``/etc/xsky/config.yaml`` or $XSKY_SERVER_CONFIG)
  2. user config     (``~/.xsky/config.yaml`` or $XSKY_CONFIG)
  3. project config  (``.xsky.yaml`` in CWD)
  4. task overrides  (``config:`` section of a task YAML / SDK kwargs)

Dict values merge recursively; scalars and lists override wholesale (matching
the reference's override semantics). Access is by dotted path via
:func:`get_nested`. An override context manager supports the API server's
per-request config isolation (reference: sky/server/requests/executor.py:244).
"""
from __future__ import annotations

import contextlib
import contextvars
import copy
import os
import threading
from typing import Any, Dict, Iterator, List, Optional, Tuple

import yaml

from skypilot_tpu import exceptions

ENV_VAR_USER_CONFIG = 'XSKY_CONFIG'
ENV_VAR_SERVER_CONFIG = 'XSKY_SERVER_CONFIG'
USER_CONFIG_PATH = '~/.xsky/config.yaml'
SERVER_CONFIG_PATH = '/etc/xsky/config.yaml'
PROJECT_CONFIG_NAME = '.xsky.yaml'

_lock = threading.Lock()
_loaded = False
_base_config: Dict[str, Any] = {}

# Per-request overlay (API server isolates each request's config).
_override_config: contextvars.ContextVar[Optional[Dict[str, Any]]] = (
    contextvars.ContextVar('xsky_config_override', default=None))


def _load_yaml_file(path: str) -> Dict[str, Any]:
    path = os.path.expanduser(path)
    if not os.path.exists(path):
        return {}
    with open(path, 'r', encoding='utf-8') as f:
        try:
            content = yaml.safe_load(f)
        except yaml.YAMLError as e:
            raise exceptions.InvalidSkyTpuConfigError(
                f'Invalid YAML in {path}: {e}') from e
    if content is None:
        return {}
    if not isinstance(content, dict):
        raise exceptions.InvalidSkyTpuConfigError(
            f'Config {path} must be a YAML mapping, got '
            f'{type(content).__name__}.')
    return content


def merge_dicts(base: Dict[str, Any], override: Dict[str, Any]) -> Dict[str, Any]:
    """Recursive dict merge; non-dict values in `override` win wholesale."""
    result = copy.deepcopy(base)
    for key, value in override.items():
        if (key in result and isinstance(result[key], dict) and
                isinstance(value, dict)):
            result[key] = merge_dicts(result[key], value)
        else:
            result[key] = copy.deepcopy(value)
    return result


def _layer_paths() -> List[str]:
    return [
        os.environ.get(ENV_VAR_SERVER_CONFIG, SERVER_CONFIG_PATH),
        os.environ.get(ENV_VAR_USER_CONFIG, USER_CONFIG_PATH),
        os.path.join(os.getcwd(), PROJECT_CONFIG_NAME),
    ]


def update_user_config_section(section: str, updates: Dict[str, Any],
                               remove: Tuple[str, ...] = ()) -> None:
    """Read-modify-write one section of the user config file (0600 —
    it can carry bearer tokens). Shared by `xsky api login` and the
    client's OAuth refresh persistence so the atomic-write details
    cannot drift. Raises OSError/yaml.YAMLError to the caller (login
    wants loud failure; token refresh treats it best-effort)."""
    path = os.path.expanduser(
        os.environ.get(ENV_VAR_USER_CONFIG, USER_CONFIG_PATH))
    doc: Dict[str, Any] = {}
    if os.path.exists(path):
        with open(path, encoding='utf-8') as f:
            doc = yaml.safe_load(f) or {}
    target = doc.setdefault(section, {})
    target.update(updates)
    for key in remove:
        target.pop(key, None)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
    with os.fdopen(fd, 'w', encoding='utf-8') as f:
        yaml.safe_dump(doc, f)
    os.chmod(path, 0o600)
    reload_config()


def reload_config() -> None:
    global _base_config, _loaded
    with _lock:
        from skypilot_tpu.utils import schemas
        config: Dict[str, Any] = {}
        for path in _layer_paths():
            layer = _load_yaml_file(path)
            if layer:
                schemas.validate_config(layer, source=path)
            config = merge_dicts(config, layer)
        _base_config = config
        _loaded = True


def _effective() -> Dict[str, Any]:
    if not _loaded:
        reload_config()
    override = _override_config.get()
    if override:
        return merge_dicts(_base_config, override)
    return _base_config


def to_dict() -> Dict[str, Any]:
    return copy.deepcopy(_effective())


def get_nested(keys: Tuple[str, ...],
               default_value: Any = None,
               override_configs: Optional[Dict[str, Any]] = None) -> Any:
    """Get a dotted-path config value, e.g. ``get_nested(('gcp', 'project_id'))``."""
    config = _effective()
    if override_configs:
        config = merge_dicts(config, override_configs)
    cur: Any = config
    for key in keys:
        if not isinstance(cur, dict) or key not in cur:
            return default_value
        cur = cur[key]
    return cur


def set_nested(keys: Tuple[str, ...], value: Any) -> Dict[str, Any]:
    """Return a copy of the effective config with keys set to value."""
    config = to_dict()
    cur = config
    for key in keys[:-1]:
        cur = cur.setdefault(key, {})
    cur[keys[-1]] = value
    return config


@contextlib.contextmanager
def override(config_overrides: Optional[Dict[str, Any]]) -> Iterator[None]:
    """Apply per-request overrides for the current (async) context."""
    existing = _override_config.get() or {}
    merged = merge_dicts(existing, config_overrides or {})
    token = _override_config.set(merged)
    try:
        yield
    finally:
        _override_config.reset(token)


@contextlib.contextmanager
def replace_for_test(config: Dict[str, Any]) -> Iterator[None]:
    """Testing hook: wholesale-replace the base config."""
    global _base_config, _loaded
    with _lock:
        saved, saved_loaded = _base_config, _loaded
        _base_config, _loaded = copy.deepcopy(config), True
    try:
        yield
    finally:
        with _lock:
            _base_config, _loaded = saved, saved_loaded
