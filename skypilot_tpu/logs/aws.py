"""Fluent Bit → AWS CloudWatch Logs agent (twin of sky/logs/aws.py)."""
from __future__ import annotations

from typing import Dict

from skypilot_tpu.logs.agent import DEFAULT_LOG_GLOB, LoggingAgent

_CONFIG_TEMPLATE = """\
[SERVICE]
    flush        5
    daemon       On

[INPUT]
    name         tail
    path         {log_glob}
    tag          xsky.{cluster_name}

[OUTPUT]
    name               cloudwatch_logs
    match              *
    region             {region}
    log_group_name     {log_group}
    log_stream_prefix  {cluster_name}-
    auto_create_group  On
"""


class AwsLoggingAgent(LoggingAgent):
    """Ships job logs to CloudWatch via fluent-bit's cloudwatch_logs
    output (uses the host's instance profile / env credentials)."""

    def get_setup_command(self, cluster_name: str) -> str:
        config = _CONFIG_TEMPLATE.format(
            log_glob=self.config.get('log_glob', DEFAULT_LOG_GLOB),
            cluster_name=cluster_name,
            region=self.config.get('region', 'us-east-1'),
            log_group=self.config.get('log_group', 'xsky-logs'))
        return self._render_setup(config)

    def get_credential_file_mounts(self) -> Dict[str, str]:
        import os
        path = '~/.aws/credentials'
        if os.path.exists(os.path.expanduser(path)):
            return {path: path}
        return {}
