"""Goodput attribution ledger: every wall-clock second, by cause.

`telemetry.goodput_for_cluster` answers *how much* of a job's wall time
was productive; this module answers *where the rest went*. The fold
attributes every second of a job's lifetime to exactly one category —

  ===================  =======================================================
  category             meaning
  ===================  =======================================================
  ``queue_wait``       admission queue (the ``fleet.queue_wait`` span)
  ``provision``        cloud provisioning incl. failover retries
  ``setup_bootstrap``  mounts, runtime bootstrap, setup, workdir/file sync
  ``init_barrier``     ranks up but pre-first-step (jax.distributed, compile)
  ``productive``       steps that advanced NEW work
  ``stalled``          a rank flagged hung/dead by the telemetry verdicts
  ``restart_replay``   productive time RE-DONE below the prior incarnation's
                       max committed step (the no-checkpoint tax)
  ``shrunk_capacity``  chips missing while a gang runs elastically shrunk
  ``recovery``         journalled recovery work not covered by a finer span
  ``idle``             declared no-work (drained replica, finished run)
  ``unattributed``     no plane left evidence (the honesty bucket)
  ===================  =======================================================

— chip-weighted across **elastic incarnations** (arxiv 2502.06982's
fleet decomposition): an incarnation running m of N ranks contributes
m/N of each second to its per-rank categories and the missing
(N−m)/N to ``shrunk_capacity`` (inside a journalled shrink window)
or to the control-plane attribution.

The fold is a NEVER-RAISE pure read over data the planes already
record — nothing new is measured:

  * liveness leases (PR 2)       → the job's wall-clock origin;
  * telemetry history (PR 5/10)  → per-rank pull rows split into
    incarnations by each sample's own ``started_ts``
    (:func:`telemetry.split_incarnations` — the same split
    ``tools/bench_fleet.py`` uses, so bench and runtime agree);
  * recovery journal (PR 1/10)   → recovery windows, elastic
    shrink/regrow windows with their excluded-rank fractions;
  * trace spans (PR 4)           → queue-wait/provision/bootstrap
    windows for the seconds no rank was alive to report.

``restart_replay`` is computed from the workload-declared
``resume_step`` (emitted at init; absent ⇒ the incarnation restarted
from step 0): steps executed at-or-below the prior incarnations' max
committed step are re-bought work. With no checkpointing every
relaunch rebuys all prior progress — the number the async-checkpoint
arc must drive down.

Rolled-up ledgers persist into the bounded ``goodput_ledger`` state
table (one ``kind='job'`` roll-up + one ``kind='incarnation'`` row per
incarnation per fold) from the jobs controller's monitor loop, rate
limited by ``XSKY_GOODPUT_RECORD_INTERVAL_S``. Surfaces: ``xsky
goodput``, the ``xsky top`` summary line, and the
``xsky_goodput_loss_seconds_total{cluster,cause}`` scrape counters.
"""
from __future__ import annotations

import os
import time
from typing import Any, Dict, List, Optional, Tuple

QUEUE_WAIT = 'queue_wait'
PROVISION = 'provision'
SETUP_BOOTSTRAP = 'setup_bootstrap'
INIT_BARRIER = 'init_barrier'
PRODUCTIVE = 'productive'
STALLED = 'stalled'
RESTART_REPLAY = 'restart_replay'
SHRUNK_CAPACITY = 'shrunk_capacity'
RECOVERY = 'recovery'
IDLE = 'idle'
UNATTRIBUTED = 'unattributed'

CATEGORIES = (QUEUE_WAIT, PROVISION, SETUP_BOOTSTRAP, INIT_BARRIER,
              PRODUCTIVE, STALLED, RESTART_REPLAY, SHRUNK_CAPACITY,
              RECOVERY, IDLE, UNATTRIBUTED)
# Loss = everything that was neither new work nor declared no-work.
LOSS_CATEGORIES = tuple(c for c in CATEGORIES
                        if c not in (PRODUCTIVE, IDLE))

ENV_RECORD_INTERVAL = 'XSKY_GOODPUT_RECORD_INTERVAL_S'
ENV_HISTORY_ROWS = 'XSKY_GOODPUT_HISTORY_ROWS'

# Controller-side fold cadence. The fold reads (not scans) four bounded
# tables; at the default 30 s it amortizes to well under 2 % of a 2 s
# controller tick (gated by `tools/bench_fleet.py --decompose`).
_DEFAULT_RECORD_INTERVAL_S = 30.0
# Telemetry-history rows one fold consumes (the table's own retention
# bound; a fold can never see more anyway).
_DEFAULT_HISTORY_ROWS = 20000

# Span name → category for the seconds no rank was alive to report.
# Priority is the tuple order below: a queue-wait second inside a
# recovery window is queue wait, not generic recovery.
_SPAN_CATEGORIES: Dict[str, str] = {
    'fleet.queue_wait': QUEUE_WAIT,
    'backend.provision': PROVISION,
    'failover.provision': PROVISION,
    'backend.mount': SETUP_BOOTSTRAP,
    'backend.bootstrap': SETUP_BOOTSTRAP,
    'backend.docker_init': SETUP_BOOTSTRAP,
    'backend.setup': SETUP_BOOTSTRAP,
    'backend.sync_workdir': SETUP_BOOTSTRAP,
    'backend.file_mounts': SETUP_BOOTSTRAP,
    'backend.storage_mount': SETUP_BOOTSTRAP,
    'backend.submit': SETUP_BOOTSTRAP,
    'backend.resubmit': RECOVERY,
    'jobs.stall_recover': RECOVERY,
    'jobs.shrink_gang': RECOVERY,
    'jobs.grow_gang': RECOVERY,
    'jobs.recover': RECOVERY,
    # Checkpoint-restore latency (agent/checkpointd.py): the tier walk
    # a fresh incarnation pays before its first step is recovery work,
    # not init barrier.
    'jobs.ckpt_restore': RECOVERY,
}
_SPAN_PRIORITY = (QUEUE_WAIT, PROVISION, SETUP_BOOTSTRAP, RECOVERY)

# Journal events that CLOSE a shrink window (capacity restored or the
# whole gang relaunched).
_SHRINK_CLOSERS = ('job.gang_regrown', 'job.recovered', 'job.restarted')
# Journal events whose latency_s measures a recovery window ending at
# the event's own timestamp.
_RECOVERY_EVENTS = ('job.recovered', 'job.restarted', 'job.gang_shrunk',
                    'job.gang_regrown')


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


def record_interval_s() -> float:
    return _env_float(ENV_RECORD_INTERVAL, _DEFAULT_RECORD_INTERVAL_S)


def history_rows() -> int:
    return int(_env_float(ENV_HISTORY_ROWS, _DEFAULT_HISTORY_ROWS))


def _job_id_for_cluster(cluster: str) -> Optional[int]:
    prefix = 'xsky-jobs-'
    if cluster.startswith(prefix) and cluster[len(prefix):].isdigit():
        return int(cluster[len(prefix):])
    return None


def empty_ledger(cluster: str) -> Dict[str, Any]:
    """Shape-compatible empty answer (CLI/scrape callers read the
    keys): attribution is observability, never an outage."""
    return {
        'cluster': cluster,
        'job_id': None,
        'window': None,
        'wall_s': 0.0,
        'full_ranks': 0,
        'incarnations': [],
        'totals': {c: 0.0 for c in CATEGORIES},
        'productive_s': 0.0,
        'loss_s': 0.0,
        'loss_by_cause': {},
        'goodput': None,
        'attributed_s': 0.0,
    }


# ---- interval helpers -------------------------------------------------------


def _overlap(a0: float, a1: float, b0: float, b1: float) -> float:
    return max(0.0, min(a1, b1) - max(a0, b0))


def _covering(intervals: List[Tuple[float, float]], t: float) -> bool:
    return any(lo <= t < hi for lo, hi in intervals)


# ---- the fold ---------------------------------------------------------------


class _Fold:
    """One ledger computation. Split out of :func:`build_ledger` so the
    never-raise wrapper stays trivially checkable."""

    def __init__(self, cluster: str, now: float,
                 window: Optional[Tuple[float, float]]) -> None:
        self.cluster = cluster
        self.now = now
        self.explicit_window = window
        self.job_id = _job_id_for_cluster(cluster)
        self.scope = (f'job/{self.job_id}'
                      if self.job_id is not None else None)

    # -- data pulls (each degrades to empty: a missing plane costs its
    # -- categories, never the fold) --

    def _telemetry_rows(self) -> List[Dict[str, Any]]:
        try:
            from skypilot_tpu import state
            return state.get_workload_telemetry(
                cluster=self.cluster, latest_only=False,
                limit=history_rows())
        except Exception:  # pylint: disable=broad-except
            return []

    def _journal(self) -> List[Dict[str, Any]]:
        if self.scope is None:
            return []
        try:
            from skypilot_tpu import state
            return state.get_recovery_events(scope=self.scope,
                                             limit=1000)
        except Exception:  # pylint: disable=broad-except
            return []

    def _lease_started(self) -> Optional[float]:
        if self.scope is None:
            return None
        try:
            from skypilot_tpu import state
            lease = state.get_lease(self.scope)
            if lease is not None:
                return lease.get('started_at')
        except Exception:  # pylint: disable=broad-except
            pass
        return None

    def _spans(self, since: float) -> Dict[str, List[Tuple[float, float]]]:
        """Category → control-plane windows, for this cluster/job
        only. In-process spans are flushed first so a fold right after
        the activity it attributes sees it."""
        out: Dict[str, List[Tuple[float, float]]] = {}
        try:
            from skypilot_tpu import state
            from skypilot_tpu.utils import tracing
            tracing.flush()
            rows = state.get_spans_by_name(
                list(_SPAN_CATEGORIES), since=since, limit=4000)
        except Exception:  # pylint: disable=broad-except
            return out
        for row in rows:
            attrs = row.get('attrs') or {}
            if not (attrs.get('cluster') == self.cluster or
                    (self.job_id is not None and
                     attrs.get('job') == self.job_id)):
                continue
            start, end = row.get('start_ts'), row.get('end_ts')
            if start is None or end is None or end <= start:
                continue
            category = _SPAN_CATEGORIES[row['name']]
            out.setdefault(category, []).append((start, end))
        return out

    # -- window bookkeeping --

    def _shrink_windows(self, events, wall_end: float
                        ) -> List[Tuple[float, float, float]]:
        """[(start, end, missing_fraction)] from the elastic journal.
        Fractions are backfill-tolerant: a shrink row without
        excluded/survivors detail scores nothing."""
        windows = []
        open_at: Optional[float] = None
        frac = 0.0
        for event in events:
            if event['event_type'] == 'job.gang_shrunk':
                detail = event.get('detail') or {}
                excluded = detail.get('excluded') or []
                survivors = detail.get('survivors')
                total = (len(excluded) + survivors
                         if survivors is not None else 0)
                open_at = event['ts']
                frac = len(excluded) / total if total else 0.0
            elif event['event_type'] in _SHRINK_CLOSERS and \
                    open_at is not None:
                if frac > 0:
                    windows.append((open_at, event['ts'], frac))
                open_at = None
        if open_at is not None and frac > 0:
            windows.append((open_at, wall_end, frac))
        return windows

    def _recovery_windows(self, events) -> List[Tuple[float, float]]:
        return [(e['ts'] - e['latency_s'], e['ts']) for e in events
                if e['event_type'] in _RECOVERY_EVENTS
                and e.get('latency_s')]

    # -- per-rank attribution (L1) --

    @staticmethod
    def _resume_step(rank_rows: List[Dict[str, Any]],
                     first_incarnation: bool) -> int:
        """The incarnation's declared resume point. Absent ⇒ restarted
        from 0 — exactly the no-checkpoint case restart_replay must
        charge for (the first incarnation has nothing to replay)."""
        del first_incarnation
        for row in rank_rows:
            if row.get('resume_step') is not None:
                return int(row['resume_step'])
        return 0

    def _walk_rank(self, rank_rows, inc_seconds, w0, w1, prior_max,
                   resume, weight):
        """Attribute one rank-incarnation's pull-to-pull windows.
        Returns (coverage interval or None, max step seen,
        replayed steps)."""
        max_step = None
        replayed = 0
        cover_lo = cover_hi = None
        prev_row = None
        prev_step: Optional[int] = None
        for row in rank_rows:
            t1 = row.get('ts')
            started = row.get('started_ts')
            if t1 is None:
                continue
            t0 = (prev_row['ts'] if prev_row is not None
                  else (started if started is not None else t1))
            if row.get('step') is not None:
                step = int(row['step'])
                max_step = step if max_step is None else max(max_step,
                                                             step)
            dt = _overlap(t0, t1, w0, w1)
            if dt > 0:
                cover_lo = min(t for t in (cover_lo, max(t0, w0))
                               if t is not None)
                cover_hi = max(t for t in (cover_hi, min(t1, w1))
                               if t is not None)
                category, frac_replay, steps_replayed = \
                    self._categorize(prev_step, row, prior_max, resume)
                if category == PRODUCTIVE and frac_replay > 0:
                    inc_seconds[RESTART_REPLAY] += \
                        dt * frac_replay * weight
                    inc_seconds[PRODUCTIVE] += \
                        dt * (1.0 - frac_replay) * weight
                    replayed += steps_replayed
                else:
                    inc_seconds[category] += dt * weight
            if row.get('step') is not None:
                prev_step = int(row['step'])
            prev_row = row
        if cover_lo is None or cover_hi is None or cover_hi <= cover_lo:
            return None, max_step, replayed
        return (cover_lo, cover_hi), max_step, replayed

    @staticmethod
    def _categorize(prev_step, row, prior_max, resume):
        """One pull-to-pull window's category for one rank: rank-local
        evidence (verdict, phase, step progress) — a stall inside a
        provision window is still a stall, the rank outranks the
        control plane for the seconds it covers."""
        if (row.get('verdict') or 'ok') != 'ok':
            return STALLED, 0.0, 0
        phase = row.get('phase')
        if phase == 'idle':
            return IDLE, 0.0, 0
        if phase == 'init' or row.get('step') is None:
            return INIT_BARRIER, 0.0, 0
        step = int(row['step'])
        base = prev_step if prev_step is not None else int(resume)
        advanced = step - base
        if advanced <= 0:
            # Stepping, verdict ok, no visible advance: a step longer
            # than the pull window — productive, not a stall (the
            # verdicts own stall calls).
            return PRODUCTIVE, 0.0, 0
        if prior_max is None:
            return PRODUCTIVE, 0.0, 0
        replay_steps = max(0, min(step, int(prior_max)) - base)
        return PRODUCTIVE, min(1.0, replay_steps / advanced), \
            replay_steps

    # -- the ledger --

    def run(self) -> Dict[str, Any]:
        from skypilot_tpu.agent import telemetry
        rows = self._telemetry_rows()
        incarnations = telemetry.split_incarnations(rows)
        events = self._journal()
        lease_started = self._lease_started()

        if self.explicit_window is not None:
            w0, w1 = self.explicit_window
        else:
            starts = [lease_started] + \
                [inc['start_ts'] for inc in incarnations]
            starts = [s for s in starts if s]
            if not starts:
                return empty_ledger(self.cluster)
            w0 = min(starts)
            w1 = self.now if self._cluster_live() else max(
                [w0] + [inc['end_ts'] for inc in incarnations
                        if inc.get('end_ts')])
        if w1 <= w0:
            return empty_ledger(self.cluster)

        full_ranks = max(
            [len(inc['ranks']) for inc in incarnations] +
            [self._journal_full_ranks(events)] + [1])
        spans = self._spans(w0 - 60.0)
        shrink_windows = self._shrink_windows(events, w1)
        recovery_windows = self._recovery_windows(events)

        weight = 1.0 / full_ranks
        inc_records: List[Dict[str, Any]] = []
        coverage: List[Tuple[float, float, int]] = []  # (lo, hi, inc#)
        prior_max: Optional[int] = None
        for index, inc in enumerate(incarnations):
            seconds = {c: 0.0 for c in CATEGORIES}
            inc_max: Optional[int] = None
            inc_replayed = 0
            inc_resume: Optional[int] = None
            for _, rank_rows in sorted(inc['ranks'].items()):
                resume = self._resume_step(rank_rows, index == 0)
                inc_resume = (resume if inc_resume is None
                              else min(inc_resume, resume))
                cover, max_step, replayed = self._walk_rank(
                    rank_rows, seconds, w0, w1, prior_max, resume,
                    weight)
                if cover is not None:
                    coverage.append((cover[0], cover[1], index))
                if max_step is not None:
                    inc_max = (max_step if inc_max is None
                               else max(inc_max, max_step))
                inc_replayed += replayed
            inc_records.append({
                'incarnation': index,
                'start_ts': inc['start_ts'],
                'end_ts': inc['end_ts'],
                'ranks': len(inc['ranks']),
                'resume_step': inc_resume or 0,
                'max_step': inc_max,
                'replayed_steps': inc_replayed,
                'seconds': seconds,
            })
            if inc_max is not None:
                prior_max = (inc_max if prior_max is None
                             else max(prior_max, inc_max))

        self._attribute_uncovered(w0, w1, full_ranks, coverage, spans,
                                  shrink_windows, recovery_windows,
                                  inc_records)

        totals = {c: 0.0 for c in CATEGORIES}
        for record in inc_records:
            for cat, value in record['seconds'].items():
                totals[cat] += value
            record['seconds'] = {k: round(v, 3)
                                 for k, v in record['seconds'].items()}
        wall = w1 - w0
        productive = totals[PRODUCTIVE]
        loss = sum(totals[c] for c in LOSS_CATEGORIES)
        return {
            'cluster': self.cluster,
            'job_id': self.job_id,
            'window': [w0, w1],
            # Stable incarnation origin of this job's run, for keying
            # monotone counters across CONTROL-PLANE churn: w0 derives
            # from the job lease's started_at, which a lease takeover
            # (server death → reconciler respawn) resets, while the
            # first incarnation's telemetry start survives — a scraper
            # keying its high-water floors on origin_ts keeps loss
            # counters monotone through a takeover. Falls back to w0
            # with no telemetry yet; drifts only when history
            # retention prunes the first incarnation.
            'origin_ts': (incarnations[0]['start_ts']
                          if incarnations else w0),
            'wall_s': round(wall, 3),
            'full_ranks': full_ranks,
            'incarnations': inc_records,
            'totals': {k: round(v, 3) for k, v in totals.items()},
            'productive_s': round(productive, 3),
            'loss_s': round(loss, 3),
            'loss_by_cause': {c: round(totals[c], 3)
                              for c in LOSS_CATEGORIES
                              if totals[c] > 0},
            'goodput': (round(min(1.0, productive / wall), 4)
                        if wall > 0 else None),
            'attributed_s': round(sum(totals.values()), 3),
        }

    def _cluster_live(self) -> bool:
        try:
            from skypilot_tpu import state
            return state.get_cluster_from_name(self.cluster) is not None
        except Exception:  # pylint: disable=broad-except
            return False

    @staticmethod
    def _journal_full_ranks(events) -> int:
        """Full gang size as the shrink journal knew it (evidence even
        when the shrunk incarnation's telemetry is all we have)."""
        best = 0
        for event in events:
            detail = event.get('detail') or {}
            if event['event_type'] == 'job.gang_shrunk':
                survivors = detail.get('survivors')
                excluded = detail.get('excluded') or []
                if survivors is not None:
                    best = max(best, survivors + len(excluded))
            elif event['event_type'] == 'job.gang_regrown':
                if detail.get('hosts'):
                    best = max(best, int(detail['hosts']))
        return best

    def _attribute_uncovered(self, w0, w1, full_ranks, coverage, spans,
                             shrink_windows, recovery_windows,
                             inc_records) -> None:
        """L2: the chip-fraction no rank covered, swept over elementary
        intervals and attributed from control-plane evidence. Each
        uncovered second goes to exactly one cause: shrink windows take
        their missing fraction first, then the finest covering span
        (queue wait > provision > setup > recovery), then a journalled
        recovery window, then ``unattributed``. Every gap is charged to
        the FOLLOWING incarnation (the cost of bringing it up)."""
        edges = {w0, w1}
        for lo, hi, _ in coverage:
            edges.update((max(w0, lo), min(w1, hi)))
        for windows in spans.values():
            for lo, hi in windows:
                edges.update((max(w0, min(lo, w1)), max(w0, min(hi, w1))))
        for lo, hi, _ in shrink_windows:
            edges.update((max(w0, min(lo, w1)), max(w0, min(hi, w1))))
        for lo, hi in recovery_windows:
            edges.update((max(w0, min(lo, w1)), max(w0, min(hi, w1))))
        ordered = sorted(edges)
        inc_starts = [(rec['start_ts'], rec['incarnation'])
                      for rec in inc_records]
        if not inc_records:
            inc_records.append({
                'incarnation': 0, 'start_ts': w0, 'end_ts': w1,
                'ranks': 0, 'resume_step': 0, 'max_step': None,
                'replayed_steps': 0,
                'seconds': {c: 0.0 for c in CATEGORIES}})
            inc_starts = [(w0, 0)]
        for a, b in zip(ordered, ordered[1:]):
            length = b - a
            if length <= 0:
                continue
            mid = (a + b) / 2.0
            covered = sum(1 for lo, hi, _ in coverage if lo <= mid < hi)
            remaining = max(0.0, 1.0 - min(covered, full_ranks)
                            / full_ranks)
            if remaining <= 0:
                continue
            target = inc_records[self._incarnation_for(inc_starts, mid)]
            seconds = target['seconds']
            for lo, hi, frac in shrink_windows:
                if lo <= mid < hi:
                    take = min(remaining, frac)
                    seconds[SHRUNK_CAPACITY] += take * length
                    remaining -= take
                    break
            if remaining <= 0:
                continue
            for category in _SPAN_PRIORITY:
                if _covering(spans.get(category, ()), mid):
                    seconds[category] += remaining * length
                    remaining = 0.0
                    break
            if remaining <= 0:
                continue
            if _covering(recovery_windows, mid):
                seconds[RECOVERY] += remaining * length
            else:
                seconds[UNATTRIBUTED] += remaining * length

    @staticmethod
    def _incarnation_for(inc_starts, t: float) -> int:
        """A gap belongs to the incarnation it paid to bring up: the
        first one starting after t (or the last one)."""
        for start, index in inc_starts:
            if start > t:
                return index
        return inc_starts[-1][1]


# ---- public API -------------------------------------------------------------


def build_ledger(cluster: str, now: Optional[float] = None,
                 window: Optional[Tuple[float, float]] = None
                 ) -> Dict[str, Any]:
    """Fold the attribution ledger for one cluster. NEVER raises —
    a broken plane costs its categories (they land in
    ``unattributed``), a broken fold returns the empty ledger.

    ``window`` restricts attribution to an explicit [start, end]
    (``tools/bench_fleet.py --decompose`` measures exactly its
    goodput window); default spans lease start → now (live) or the
    last recorded evidence (torn down).
    """
    try:
        now = now if now is not None else time.time()
        return _Fold(cluster, now, window).run()
    except Exception:  # pylint: disable=broad-except
        # empty_ledger is provably non-raising — verified through the
        # call graph by the never-raise-transitive lint (the old
        # pre-computed `fallback` hoist predates that rule).
        return empty_ledger(cluster)


def record_ledger(cluster: str, job_id: Optional[int] = None,
                  now: Optional[float] = None) -> Dict[str, Any]:
    """Fold + persist the rolled-up ledger into the bounded
    ``goodput_ledger`` table (one ``kind='job'`` roll-up + one
    ``kind='incarnation'`` row per incarnation). NEVER raises — rides
    the jobs controller's monitor loop. Returns the ledger."""
    try:
        return _record_ledger(cluster, job_id=job_id, now=now)
    except Exception:  # pylint: disable=broad-except
        # Same never-raise-transitive-verified fallback as
        # build_ledger.
        return empty_ledger(cluster)


def _record_ledger(cluster: str, job_id: Optional[int],
                   now: Optional[float]) -> Dict[str, Any]:
    from skypilot_tpu import state
    now = now if now is not None else time.time()
    ledger = build_ledger(cluster, now=now)
    if not ledger['incarnations'] and ledger['wall_s'] <= 0:
        return ledger
    owner = job_id if job_id is not None else ledger.get('job_id')
    window = ledger.get('window') or [None, None]
    rows = [{
        'kind': 'job',
        'incarnation': None,
        'start_ts': window[0],
        'end_ts': window[1],
        'ranks': ledger['full_ranks'],
        'full_ranks': ledger['full_ranks'],
        'resume_step': None,
        'max_step': max((r['max_step'] for r in ledger['incarnations']
                         if r['max_step'] is not None), default=None),
        'replayed_steps': sum(r['replayed_steps']
                              for r in ledger['incarnations']),
        'wall_s': ledger['wall_s'],
        'productive_s': ledger['productive_s'],
        'loss_s': ledger['loss_s'],
        'goodput': ledger['goodput'],
        'seconds': ledger['totals'],
        'detail': {'incarnations': len(ledger['incarnations']),
                   # Scrapers key goodput floors on this (see
                   # origin_ts in build_ledger): start_ts moves on a
                   # lease takeover, origin_ts does not.
                   'origin_ts': ledger.get('origin_ts')},
    }]
    for record in ledger['incarnations']:
        seconds = record['seconds']
        productive = seconds.get(PRODUCTIVE, 0.0)
        inc_wall = sum(seconds.values())
        rows.append({
            'kind': 'incarnation',
            'incarnation': record['incarnation'],
            'start_ts': record['start_ts'],
            'end_ts': record['end_ts'],
            'ranks': record['ranks'],
            'full_ranks': ledger['full_ranks'],
            'resume_step': record['resume_step'],
            'max_step': record['max_step'],
            'replayed_steps': record['replayed_steps'],
            'wall_s': round(inc_wall, 3),
            'productive_s': round(productive, 3),
            'loss_s': round(sum(seconds.get(c, 0.0)
                                for c in LOSS_CATEGORIES), 3),
            'goodput': (round(min(1.0, productive / inc_wall), 4)
                        if inc_wall > 0 else None),
            'seconds': seconds,
            'detail': None,
        })
    state.record_goodput_ledger(cluster, owner, rows, ts=now)
    return ledger


def fleet_report(limit: int = 1000) -> Dict[str, Any]:
    """Fleet roll-up of the latest persisted per-job ledgers: loss by
    cause across every LIVE cluster (the same liveness filter the
    scrape gauges apply). NEVER raises — shape-compatible empty report
    on any failure."""
    try:
        return _fleet_report(limit)
    except Exception:  # pylint: disable=broad-except
        return {'clusters': [], 'totals': {}, 'loss_by_cause': {},
                'wall_s': 0.0, 'productive_s': 0.0, 'goodput': None}


def _fleet_report(limit: int) -> Dict[str, Any]:
    from skypilot_tpu import state
    clusters: List[Dict[str, Any]] = []
    totals = {c: 0.0 for c in CATEGORIES}
    live = set(state.get_cluster_names())
    rows = [r for r in state.get_goodput_ledger(kind='job',
                                                limit=limit)
            if r['cluster'] in live]
    for row in rows:
        seconds = row.get('seconds') or {}
        for cat, value in seconds.items():
            if cat in totals and value:
                totals[cat] += value
        clusters.append(row)
    wall = sum(totals.values())
    productive = totals[PRODUCTIVE]
    return {
        'clusters': clusters,
        'totals': {k: round(v, 3) for k, v in totals.items()},
        'loss_by_cause': {c: round(totals[c], 3)
                          for c in LOSS_CATEGORIES if totals[c] > 0},
        'wall_s': round(wall, 3),
        'productive_s': round(productive, 3),
        'goodput': round(productive / wall, 4) if wall > 0 else None,
    }


def loss_summary(seconds: Dict[str, Any], top: int = 2) -> str:
    """Compact top-loss-causes digest for one ledger's seconds map
    (the `xsky top` summary line): 'replay 31%/provision 12%'."""
    try:
        total = sum(float(seconds.get(c) or 0.0) for c in CATEGORIES)
        if total <= 0:
            return '-'
        short = {RESTART_REPLAY: 'replay', SETUP_BOOTSTRAP: 'setup',
                 SHRUNK_CAPACITY: 'shrunk', INIT_BARRIER: 'init',
                 QUEUE_WAIT: 'queue', UNATTRIBUTED: 'unattr'}
        losses = sorted(((float(seconds.get(c) or 0.0), c)
                         for c in LOSS_CATEGORIES), reverse=True)
        parts = [f'{short.get(c, c)} {v / total:.0%}'
                 for v, c in losses[:top] if v > 0]
        return '/'.join(parts) if parts else '-'
    except Exception:  # pylint: disable=broad-except
        return '-'
