"""tools/xskylint: engine mechanics (parse-once, suppression syntax,
JSON), a positive/negative synthetic fixture pair for EVERY registered
rule (a self-check fails if a rule ships without one), and the tier-1
gate that runs the full engine over the real tree and asserts zero
unsuppressed findings."""
import ast
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.abspath(
    os.path.join(os.path.dirname(__file__), '..', '..'))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.xskylint import all_rules  # noqa: E402
from tools.xskylint import engine  # noqa: E402

# ---- fixtures: one (bad, clean) tree per rule ------------------------------
# Each is {repo-relative path: source}; paths matter — rules scope by
# file (e.g. no-raw-sleep only watches the instrumented modules).

_MINI_ENV_REGISTRY = '''\
import dataclasses


@dataclasses.dataclass(frozen=True)
class EnvVar:
    name: str
    default: object
    doc: str


REGISTRY = {{
{entries}
}}


def render_markdown():
    return 'unused in fixtures'
'''


def _registry(*names):
    entries = '\n'.join(
        f"    '{n}': EnvVar('{n}', '1', 'A test variable.'),"
        for n in names)
    return _MINI_ENV_REGISTRY.format(entries=entries)


_MINI_NAMES_REGISTRY = '''\
import dataclasses


@dataclasses.dataclass(frozen=True)
class ObsName:
    kind: str
    name: str
    doc: str


REGISTRY = {{
{entries}
}}


def declared_names(kind):
    return {{name for (k, name) in REGISTRY if k == kind}}


def render_markdown():
    return 'unused in fixtures'
'''


def _names_registry(*pairs):
    entries = '\n'.join(
        f"    ('{k}', '{n}'): ObsName('{k}', '{n}', 'A test name.'),"
        for k, n in pairs)
    return _MINI_NAMES_REGISTRY.format(entries=entries)


# Shared by the verb-wiring fixture pair: the factory shape the index
# parses (mirrors payloads._core_verb).
_VERB_FACTORY = (
    'def _core_verb(fn_name, *fields, **defaults):\n'
    '    def resolver(body):\n'
    '        return fn_name, {}\n'
    '    return resolver\n')


FIXTURES = {
    'no-raw-sleep': (
        {'skypilot_tpu/jobs/controller.py':
            'import time\n'
            'def poll():\n'
            '    while True:\n'
            '        time.sleep(1)\n'},
        {'skypilot_tpu/jobs/controller.py':
            'from skypilot_tpu.utils import resilience\n'
            'def poll():\n'
            '    while True:\n'
            '        resilience.sleep(1)\n'},
    ),
    'no-sequential-runner-loop': (
        {'skypilot_tpu/backends/setup.py':
            'def setup(runners):\n'
            '    for rank, runner in enumerate(runners):\n'
            '        runner.run("true")\n'},
        {'skypilot_tpu/backends/setup.py':
            'def setup(runners):\n'
            '    def _one(pair):\n'
            '        rank, runner = pair\n'
            '        runner.run("true")\n'
            '    run_in_parallel(_one, list(enumerate(runners)))\n'},
    ),
    'thread-hygiene': (
        {'skypilot_tpu/jobs/spawn.py':
            'import subprocess\n'
            'import threading\n'
            'def go(f):\n'
            '    threading.Thread(target=f, daemon=True).start()\n'
            'def launch(cmd):\n'
            '    return subprocess.Popen(cmd)\n'},
        {'skypilot_tpu/jobs/spawn.py':
            'import subprocess\n'
            'import threading\n'
            'def go(f):\n'
            '    threading.Thread(target=f, name="xsky-go",\n'
            '                     daemon=True).start()\n'
            'def launch(cmd, job_id):\n'
            '    proc = subprocess.Popen(cmd)\n'
            '    set_controller_pid(job_id, proc.pid)\n'
            '    return proc\n'},
    ),
    'span-fanout': (
        {'skypilot_tpu/backends/fan.py':
            'def setup(runners):\n'
            '    parallelism.run_in_parallel(f, runners)\n'},
        {'skypilot_tpu/backends/fan.py':
            'def setup(runners):\n'
            '    with tracing.span("setup"):\n'
            '        parallelism.run_in_parallel(f, runners)\n'},
    ),
    'span-failover': (
        {'skypilot_tpu/backends/failover.py':
            'def provision(self):\n'
            '    for _ in range(3):\n'
            '        self._try_resources(r)\n'},
        {'skypilot_tpu/backends/failover.py':
            'def provision(self):\n'
            '    with tracing.span("failover.provision"):\n'
            '        for _ in range(3):\n'
            '            self._try_resources(r)\n'},
    ),
    'span-profiler': (
        {'skypilot_tpu/core.py':
            'def cap(backend, handle):\n'
            '    backend.capture_device_profile(handle)\n'},
        {'skypilot_tpu/core.py':
            'def cap(backend, handle):\n'
            '    with tracing.span("profile.capture"):\n'
            '        backend.capture_device_profile(handle)\n'},
    ),
    'retention-bound': (
        {'skypilot_tpu/state.py':
            'CREATE = """CREATE TABLE IF NOT EXISTS foo_telemetry '
            '(x INT);"""\n'},
        {'skypilot_tpu/state.py':
            '_MAX_SPANS = 100\n'
            'CREATE = """CREATE TABLE IF NOT EXISTS spans (x INT);"""\n'
            'PRUNE = "DELETE FROM spans WHERE 1"\n'},
    ),
    'lease-heartbeat': (
        {'skypilot_tpu/jobs/scheduler.py':
            'def acquire_launch_slot(job_id):\n'
            '    while True:\n'
            '        tick()\n'},
        {'skypilot_tpu/jobs/scheduler.py':
            'def acquire_launch_slot(job_id):\n'
            '    while True:\n'
            '        lease_heartbeat(job_id)\n'
            '        tick()\n'},
    ),
    'telemetry-poll': (
        {'skypilot_tpu/backends/tpu_gang_backend.py':
            'def _wait_job(self):\n'
            '    while True:\n'
            '        self._job_status()\n'},
        {'skypilot_tpu/backends/tpu_gang_backend.py':
            'def _wait_job(self):\n'
            '    while True:\n'
            '        self._pull_workload_telemetry()\n'},
    ),
    'never-raise': (
        {'skypilot_tpu/utils/metrics.py':
            'def inc_counter(name, help_text, value=1.0, **labels):\n'
            '    try:\n'
            '        _bump(name, value, labels)\n'
            '    except Exception:\n'
            '        pass\n'
            'def observe(name, help_text, value, **labels):\n'
            '    _record(name, value, labels)\n'},
        {'skypilot_tpu/utils/metrics.py':
            'def inc_counter(name, help_text, value=1.0, **labels):\n'
            '    try:\n'
            '        _bump(name, value, labels)\n'
            '    except Exception:\n'
            '        pass\n'
            'def observe(name, help_text, value, **labels):\n'
            '    try:\n'
            '        _record(name, value, labels)\n'
            '    except Exception:\n'
            '        pass\n'},
    ),
    'select-limit': (
        {'skypilot_tpu/state.py':
            'def list_things():\n'
            "    return _read('SELECT x FROM t')\n"},
        {'skypilot_tpu/state.py':
            'def list_paged():\n'
            "    return _read('SELECT x FROM t LIMIT 5')\n"
            'def list_helper(limit):\n'
            "    q = 'SELECT x FROM t' + _page_sql(limit)\n"
            '    return _read(q)\n'
            'def list_exempt():\n'
            '    # full-scan ok: one row per enabled cloud.\n'
            "    return _read('SELECT x FROM t')\n"
            'def get_thing(conn):\n'
            "    return conn.execute('SELECT x FROM t').fetchone()\n"},
    ),
    'db-discipline': (
        {'skypilot_tpu/jobs/state.py':
            'import sqlite3\n'
            'def _db(path):\n'
            '    return sqlite3.connect(path)\n'},
        {'skypilot_tpu/jobs/state.py':
            'from skypilot_tpu.utils import db_utils\n'
            'def _db(path):\n'
            '    return db_utils.connect(path)\n'},
    ),
    'env-registry': (
        {'skypilot_tpu/utils/env_registry.py': _registry('XSKY_KNOWN'),
         'skypilot_tpu/conf.py':
            'import os\n'
            "A = os.environ.get('XSKY_KNOWN', '1')\n"
            "B = os.environ.get('XSKY_MYSTERY')\n"},
        {'skypilot_tpu/utils/env_registry.py':
            _registry('XSKY_KNOWN', 'XSKY_MYSTERY'),
         'skypilot_tpu/conf.py':
            'import os\n'
            "A = os.environ.get('XSKY_KNOWN', '1')\n"
            "B = os.environ.get('XSKY_MYSTERY')\n"},
    ),
    'verb-wiring': (
        {'skypilot_tpu/server/payloads.py':
            _VERB_FACTORY +
            "_VERBS = {'status': _core_verb('status', 'cluster'),\n"
            "          'ghost': _core_verb('no_such_fn')}\n",
         'skypilot_tpu/core.py':
            'def status(cluster_names=None):\n'
            '    return []\n',
         'skypilot_tpu/client/remote_client.py':
            'class Client:\n'
            '    def _call(self, verb, body):\n'
            '        return verb, body\n'
            '    def status(self):\n'
            "        return self._call('status', {})\n"
            '    def stop(self):\n'
            "        return self._call('stop', {})\n",
         'skypilot_tpu/client/sdk.py':
            'def status(remote):\n'
            '    return remote.status()\n'},
        {'skypilot_tpu/server/payloads.py':
            _VERB_FACTORY +
            "_VERBS = {'status': _core_verb('status',\n"
            "                               'cluster_names')}\n",
         'skypilot_tpu/core.py':
            'def status(cluster_names=None):\n'
            '    return []\n',
         'skypilot_tpu/client/remote_client.py':
            'class Client:\n'
            '    def _call(self, verb, body):\n'
            '        return verb, body\n'
            '    def status(self):\n'
            "        return self._call('status', {})\n",
         'skypilot_tpu/client/sdk.py':
            'def status(remote):\n'
            '    return remote.status()\n'},
    ),
    'name-registry': (
        {'skypilot_tpu/utils/names_registry.py':
            _names_registry(('chaos', 'known.point')),
         'skypilot_tpu/m.py':
            'from skypilot_tpu.utils import chaos\n'
            'def f():\n'
            "    chaos.inject('mystery.point')\n"},
        {'skypilot_tpu/utils/names_registry.py':
            _names_registry(('chaos', 'known.point'),
                            ('chaos', 'mystery.point')),
         'skypilot_tpu/m.py':
            'from skypilot_tpu.utils import chaos\n'
            'def f():\n'
            "    chaos.inject('mystery.point')\n"},
    ),
    'lock-discipline': (
        {'skypilot_tpu/reg.py':
            '_CACHE = {}\n'
            'def put(k, v):\n'
            '    _CACHE[k] = v\n'
            'def clear():\n'
            '    _CACHE.clear()\n'},
        {'skypilot_tpu/reg.py':
            'import threading\n'
            '_LOCK = threading.Lock()\n'
            '_CACHE = {}\n'
            '# single-writer ok: only the controller tick writes.\n'
            '_SINGLE = {}\n'
            'def put(k, v):\n'
            '    with _LOCK:\n'
            '        _CACHE[k] = v\n'
            'def clear():\n'
            '    with _LOCK:\n'
            '        _CACHE.clear()\n'
            'def tick(k):\n'
            '    _SINGLE[k] = 1\n'
            'def tock(k):\n'
            '    _SINGLE.pop(k, None)\n'},
    ),
    'server-singleton': (
        {'skypilot_tpu/server/reg.py':
            '_PENDING = {}\n'
            'def flush(state):\n'
            '    for key, rows in _PENDING.items():\n'
            '        state.record_rows(key, rows)\n'},
        {'skypilot_tpu/server/reg.py':
            'from skypilot_tpu.utils import ownership\n'
            '# single-writer ok: flushed only by the elected '
            'recorder tick.\n'
            '_PENDING = {}\n'
            '_CURSOR = {}\n'
            'def flush(state):\n'
            '    for key, rows in _PENDING.items():\n'
            '        state.record_rows(key, rows)\n'
            'def fold(state):\n'
            "    if not ownership.owns('role/recorder'):\n"
            '        return\n'
            "    _CURSOR['x'] = state.record_rows('x', [])\n"},
    ),
    'schema-consistency': (
        {'skypilot_tpu/state.py':
            'SCHEMA = """CREATE TABLE IF NOT EXISTS widgets (\n'
            '    row_id INTEGER PRIMARY KEY,\n'
            '    name TEXT\n'
            ');"""\n'
            'def add(conn, name):\n'
            "    conn.execute('INSERT INTO widgets (name, color) '\n"
            "                 'VALUES (?, ?)', (name, 1))\n"
            'def list_widgets(limit, offset):\n'
            "    return ('SELECT name FROM widgets ORDER BY name'\n"
            '            + page_sql(limit, offset))\n'},
        {'skypilot_tpu/state.py':
            'SCHEMA = """CREATE TABLE IF NOT EXISTS widgets (\n'
            '    row_id INTEGER PRIMARY KEY,\n'
            '    name TEXT,\n'
            '    color TEXT\n'
            ');\n'
            'CREATE INDEX IF NOT EXISTS idx_widgets_name\n'
            '    ON widgets (name);"""\n'
            'def add(conn, name):\n'
            "    conn.execute('INSERT INTO widgets (name, color) '\n"
            "                 'VALUES (?, ?)', (name, 1))\n"
            'def list_widgets(limit, offset):\n'
            "    return ('SELECT name FROM widgets ORDER BY name'\n"
            '            + page_sql(limit, offset))\n'},
    ),
    'chaos-coverage': (
        {'skypilot_tpu/provision/probe.py':
            'def call(self):\n'
            '    def attempt():\n'
            '        return do_request()\n'
            '    return resilience.retry_transient(attempt)\n'},
        {'skypilot_tpu/provision/probe.py':
            'def call(self):\n'
            '    def attempt():\n'
            "        chaos.inject('probe.api')\n"
            '        return do_request()\n'
            '    return resilience.retry_transient(attempt)\n'},
    ),
    # A blocking primitive one call deep below a declared hot-path
    # entry point; the clean twin declares the interval-gated escape.
    'hot-path-purity': (
        {'skypilot_tpu/agent/telemetry.py':
            'import time\n'
            'def emit(**kw):\n'
            '    _flush()\n'
            'def _flush():\n'
            '    time.sleep(1)\n'},
        {'skypilot_tpu/agent/telemetry.py':
            'import time\n'
            'def emit(**kw):\n'
            '    _flush()\n'
            'def _flush():\n'
            '    # hotpath ok: interval-gated, one write per 2 s\n'
            '    time.sleep(1)\n'},
    ),
    # Opposite-order nesting (one side through a call) is a cycle;
    # the clean twin acquires in one global order.
    'lock-order': (
        {'skypilot_tpu/coord.py':
            'import threading\n'
            '_A = threading.Lock()\n'
            '_B = threading.Lock()\n'
            'def f():\n'
            '    with _A:\n'
            '        _grab_b()\n'
            'def _grab_b():\n'
            '    with _B:\n'
            '        pass\n'
            'def g():\n'
            '    with _B:\n'
            '        with _A:\n'
            '            pass\n'},
        {'skypilot_tpu/coord.py':
            'import threading\n'
            '_A = threading.Lock()\n'
            '_B = threading.Lock()\n'
            'def f():\n'
            '    with _A:\n'
            '        _grab_b()\n'
            'def _grab_b():\n'
            '    with _B:\n'
            '        pass\n'
            'def g():\n'
            '    with _A:\n'
            '        with _B:\n'
            '            pass\n'},
    ),
    # A fallback arm calling a helper that can raise (subscript)
    # escapes the guard; the clean twin's helper is provably safe.
    'never-raise-transitive': (
        {'skypilot_tpu/utils/metrics.py':
            'def inc_counter(name, help_text, value=1.0, **labels):\n'
            '    try:\n'
            '        _bump(name, value, labels)\n'
            '    except Exception:\n'
            '        return _fallback(labels)\n'
            'def observe(name, help_text, value, **labels):\n'
            '    try:\n'
            '        _record(name, value, labels)\n'
            '    except Exception:\n'
            '        pass\n'
            'def _fallback(labels):\n'
            "    return labels['x']\n"
            'def _bump(name, value, labels):\n'
            '    pass\n'
            'def _record(name, value, labels):\n'
            '    pass\n'},
        {'skypilot_tpu/utils/metrics.py':
            'def inc_counter(name, help_text, value=1.0, **labels):\n'
            '    try:\n'
            '        _bump(name, value, labels)\n'
            '    except Exception:\n'
            '        return _fallback()\n'
            'def observe(name, help_text, value, **labels):\n'
            '    try:\n'
            '        _record(name, value, labels)\n'
            '    except Exception:\n'
            '        pass\n'
            'def _fallback():\n'
            "    return {'ok': False}\n"
            'def _bump(name, value, labels):\n'
            '    pass\n'
            'def _record(name, value, labels):\n'
            '    pass\n'},
    ),
    'cross-hop-context': (
        {'skypilot_tpu/serve/load_balancer.py':
            'def _proxy(self, replica, path):\n'
            '    headers = {}\n'
            '    return relay(replica, path, headers)\n',
         'skypilot_tpu/infer/server.py':
            'def _attach_trace(request, headers):\n'
            '    request.trace_id = None\n'},
        {'skypilot_tpu/serve/load_balancer.py':
            'from skypilot_tpu.utils import tracing\n'
            'def _proxy(self, replica, path):\n'
            '    headers = {}\n'
            '    tracing.inject_headers(headers, trace_id="t",\n'
            '                           request_id="r")\n'
            '    return relay(replica, path, headers)\n',
         'skypilot_tpu/infer/server.py':
            'from skypilot_tpu.utils import tracing\n'
            'def _attach_trace(request, headers):\n'
            '    trace_id, request_id, deadline_s = \\\n'
            '        tracing.extract_headers(headers)\n'
            '    request.trace_id = trace_id\n'},
    ),
}


def _write_tree(root, files):
    for rel, source in files.items():
        path = os.path.join(root, rel)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, 'w', encoding='utf-8') as f:
            f.write(source)


def _run(root, rule_id=None, **kwargs):
    rule_ids = [rule_id] if rule_id else None
    return engine.lint_paths(str(root), ['.'], rule_ids=rule_ids,
                             **kwargs)


class TestRuleFixtures:
    """Every registered rule catches its synthetic violation and stays
    quiet on the clean twin."""

    def test_every_rule_has_a_fixture_pair(self):
        registered = {r.id for r in all_rules()}
        assert registered == set(FIXTURES), (
            'rules without fixtures (add a (bad, clean) pair to '
            f'FIXTURES): {sorted(registered ^ set(FIXTURES))}')

    @pytest.mark.parametrize('rule_id', sorted(FIXTURES))
    def test_rule_catches_its_violation(self, rule_id, tmp_path):
        bad, _ = FIXTURES[rule_id]
        _write_tree(tmp_path, bad)
        result = _run(tmp_path, rule_id)
        assert [f for f in result.unsuppressed if f.rule == rule_id], \
            f'{rule_id} missed its synthetic violation'

    @pytest.mark.parametrize('rule_id', sorted(FIXTURES))
    def test_rule_passes_the_clean_twin(self, rule_id, tmp_path):
        _, clean = FIXTURES[rule_id]
        _write_tree(tmp_path, clean)
        result = _run(tmp_path, rule_id)
        assert not result.unsuppressed, [
            f.render() for f in result.unsuppressed]


class TestEngine:

    def test_parses_each_file_exactly_once(self, tmp_path):
        """The acceptance criterion: ALL rules share one parse per
        file (the scattered legacy lints each re-parsed the tree)."""
        files = {
            'skypilot_tpu/a.py': 'x = 1\n',
            'skypilot_tpu/backends/b.py': 'def f():\n    pass\n',
            'skypilot_tpu/utils/env_registry.py': _registry('XSKY_A'),
        }
        _write_tree(tmp_path, files)
        calls = []

        def counting_parse(source, filename='<unknown>', **kw):
            calls.append(filename)
            return ast.parse(source, filename=filename, **kw)

        result = _run(tmp_path, rule_id=None, parse=counting_parse)
        assert result.files_scanned == len(files)
        assert sorted(calls) == sorted(files), (
            'ast.parse must run exactly once per file for ALL rules '
            f'combined; saw {calls}')

    def test_suppression_same_line_and_comment_block(self, tmp_path):
        src = (
            'import threading\n'
            'def a(f):\n'
            '    threading.Thread(target=f).start()'
            '  # xskylint: disable=thread-hygiene -- fixture thread\n'
            'def b(f):\n'
            '    # A longer explanation of why this one is exempt.\n'
            '    # xskylint: disable=thread-hygiene -- fixture thread\n'
            '    # (directive sits inside the comment block above).\n'
            '    threading.Thread(target=f).start()\n')
        _write_tree(tmp_path, {'skypilot_tpu/t.py': src})
        result = _run(tmp_path, 'thread-hygiene')
        assert not result.unsuppressed, [
            f.render() for f in result.unsuppressed]
        assert sum(f.suppressed for f in result.findings) == 2
        assert all(f.reason == 'fixture thread'
                   for f in result.findings if f.suppressed)

    def test_suppression_without_reason_is_a_finding(self, tmp_path):
        src = ('import threading\n'
               'def a(f):\n'
               '    threading.Thread(target=f).start()'
               '  # xskylint: disable=thread-hygiene\n')
        _write_tree(tmp_path, {'skypilot_tpu/t.py': src})
        result = _run(tmp_path, 'thread-hygiene')
        rules = {f.rule for f in result.unsuppressed}
        # The reasonless directive suppresses nothing AND is itself
        # flagged.
        assert rules == {engine.SUPPRESSION_RULE, 'thread-hygiene'}

    def test_suppression_of_unknown_rule_is_a_finding(self, tmp_path):
        src = ('x = 1  # xskylint: disable=no-such-rule -- oops\n')
        _write_tree(tmp_path, {'skypilot_tpu/t.py': src})
        result = _run(tmp_path)
        assert [f for f in result.unsuppressed
                if f.rule == engine.SUPPRESSION_RULE and
                'no-such-rule' in f.message]

    def test_suppressing_a_different_rule_does_not_mask(self, tmp_path):
        src = ('import threading\n'
               'def a(f):\n'
               '    threading.Thread(target=f).start()'
               '  # xskylint: disable=select-limit -- wrong rule\n')
        _write_tree(tmp_path, {'skypilot_tpu/t.py': src})
        result = _run(tmp_path, 'thread-hygiene')
        assert [f for f in result.unsuppressed
                if f.rule == 'thread-hygiene']

    def test_finalize_phase_findings_honor_suppressions(self, tmp_path):
        """env-registry reports from finalize(); its findings must
        still be suppressible at the use site like any other rule's."""
        files = {
            'skypilot_tpu/utils/env_registry.py': _registry('XSKY_A'),
            'skypilot_tpu/conf.py':
                'import os\n'
                '# xskylint: disable=env-registry -- fixture-only var\n'
                "B = os.environ.get('XSKY_MYSTERY')\n",
        }
        _write_tree(tmp_path, files)
        result = _run(tmp_path, 'env-registry')
        assert not result.unsuppressed, [
            f.render() for f in result.unsuppressed]
        assert any(f.suppressed and f.rule == 'env-registry'
                   for f in result.findings)

    def test_nonexistent_path_is_an_error_not_a_green_run(self,
                                                          tmp_path):
        """A typo'd path must not report '0 files, 0 findings'."""
        with pytest.raises(FileNotFoundError):
            engine.lint_paths(str(tmp_path), ['no_such_dir'])
        proc = subprocess.run(
            [sys.executable, '-m', 'tools.xskylint', 'no_such_dir'],
            cwd=REPO, capture_output=True, text=True, check=False)
        assert proc.returncode == 2
        assert 'no_such_dir' in proc.stderr

    def test_never_raise_rejects_risky_else_and_finally(self, tmp_path):
        """else:/finally: bodies run outside the handlers' protection
        — raising code there must not pass the composed never-raise
        check. Bare calls are now lexically admitted (fallback-arm
        calls are the transitive rule's job), so an UNPROVABLE call
        is flagged by never-raise-transitive instead; a non-call
        risky statement still fails the lexical rule."""
        src = (
            'def inc_counter(name, help_text, value=1.0, **labels):\n'
            '    try:\n'
            '        pass\n'
            '    except Exception:\n'
            '        pass\n'
            '    else:\n'
            '        do_risky_thing()\n'
            'def observe(name, help_text, value, **labels):\n'
            '    try:\n'
            '        pass\n'
            '    except Exception:\n'
            '        pass\n'
            '    finally:\n'
            "        labels['x'] += 1\n")
        _write_tree(tmp_path, {'skypilot_tpu/utils/metrics.py': src})
        # The subscript in finally: fails lexically (not a call —
        # nothing to defer).
        lexical = [f for f in _run(tmp_path, 'never-raise').unsuppressed
                   if f.rule == 'never-raise']
        assert len(lexical) == 1 and 'observe' in lexical[0].message
        # The unresolvable call in else: fails the transitive proof.
        transitive = [
            f for f in _run(tmp_path,
                            'never-raise-transitive').unsuppressed]
        assert len(transitive) == 1
        assert 'inc_counter' in transitive[0].message
        assert 'do_risky_thing' in transitive[0].message

    def test_risky_handler_call_caught_transitively(self, tmp_path):
        """The except body is the fallback path — an exception thrown
        FROM it escapes (the exact hole env_for_child's original
        dict(env) fallback fell through). The lexical rule now ADMITS
        calls in the arms; the transitive rule must prove them, and
        `dict(labels)` (external, can raise on a bad arg) fails the
        proof."""
        src = (
            'def inc_counter(name, help_text, value=1.0, **labels):\n'
            '    try:\n'
            '        _bump(name, value, labels)\n'
            '    except Exception:\n'
            '        return dict(labels)\n'
            'def observe(name, help_text, value, **labels):\n'
            '    try:\n'
            '        _record(name, value, labels)\n'
            '    except Exception:\n'
            '        pass\n'
            'def _bump(name, value, labels):\n'
            '    pass\n'
            'def _record(name, value, labels):\n'
            '    pass\n')
        _write_tree(tmp_path, {'skypilot_tpu/utils/metrics.py': src})
        result = _run(tmp_path, 'never-raise')
        # Lexically conforming now...
        assert not [f for f in result.unsuppressed
                    if f.rule == 'never-raise']
        # ...but the composed contract still rejects it — and the
        # verifier rule rides along automatically (companion
        # expansion), so even a `--rule never-raise` subset run
        # cannot accept an unverified arm call.
        findings = [f for f in result.unsuppressed
                    if f.rule == 'never-raise-transitive']
        assert len(findings) == 1
        assert 'inc_counter' in findings[0].message
        assert 'dict' in findings[0].message

    def test_arm_call_with_risky_arguments_fails_lexically(
            self, tmp_path):
        """A fallback-arm call whose ARGUMENT can raise
        (`_helper(d['k'])`) fails the lexical rule — the argument
        expression evaluates in the arm before the callee runs, so no
        transitive proof of the callee covers it."""
        src = (
            "FALLBACK = {'a': 1}\n"
            'def inc_counter(name, help_text, value=1.0, **labels):\n'
            '    try:\n'
            '        _bump(name, value, labels)\n'
            '    except Exception:\n'
            "        return _helper(FALLBACK['missing'])\n"
            'def observe(name, help_text, value, **labels):\n'
            '    try:\n'
            '        _record(name, value, labels)\n'
            '    except Exception:\n'
            '        pass\n'
            'def _helper(x):\n'
            '    return x\n'
            'def _bump(name, value, labels):\n'
            '    pass\n'
            'def _record(name, value, labels):\n'
            '    pass\n')
        _write_tree(tmp_path, {'skypilot_tpu/utils/metrics.py': src})
        findings = [f for f in _run(tmp_path, 'never-raise').unsuppressed
                    if f.rule == 'never-raise']
        assert len(findings) == 1
        assert 'inc_counter' in findings[0].message

    def test_env_for_child_never_raises_on_malformed_env(self):
        """Live form of the review repro: a non-dict env argument must
        not escape the never-raise guard."""
        from skypilot_tpu.utils import tracing
        out = tracing.env_for_child('PATH=1')   # dict() rejects this
        assert out == {}
        assert isinstance(tracing.env_for_child(), dict)

    def test_chaos_coverage_accepts_transitive_inject(self, tmp_path):
        """A failover loop is covered when its attempt helper reaches
        chaos.inject through same-file calls — the points live INSIDE
        the helpers' failure handling on purpose (an inject lexically
        in the loop body would abort the whole walk)."""
        src = (
            'def provision(self):\n'
            '    for _ in range(3):\n'
            '        self._try_resources(r)\n'
            'def _try_resources(self, r):\n'
            '    for zone in zones:\n'
            '        self._try_zone(r, zone)\n'
            'def _try_zone(self, r, zone):\n'
            "    chaos.inject('failover.wait_instances')\n")
        _write_tree(tmp_path, {'skypilot_tpu/backends/failover.py': src})
        result = _run(tmp_path, 'chaos-coverage')
        assert not result.unsuppressed, [
            f.render() for f in result.unsuppressed]

    def test_syntax_error_is_reported_not_raised(self, tmp_path):
        _write_tree(tmp_path, {'skypilot_tpu/broken.py': 'def f(:\n'})
        result = _run(tmp_path)
        assert [f for f in result.unsuppressed
                if f.rule == engine.PARSE_RULE]

    def test_unknown_rule_id_rejected(self, tmp_path):
        with pytest.raises(ValueError):
            engine.lint_paths(str(tmp_path), ['.'],
                              rule_ids=['no-such-rule'])

    def test_json_round_trip(self, tmp_path):
        bad, _ = FIXTURES['span-fanout']
        _write_tree(tmp_path, bad)
        result = _run(tmp_path, 'span-fanout')
        payload = json.loads(json.dumps(result.to_json()))
        assert payload['unsuppressed_count'] == 1
        (finding,) = payload['findings']
        assert finding['rule'] == 'span-fanout'
        assert finding['path'] == 'skypilot_tpu/backends/fan.py'
        assert finding['line'] == 2
        assert not finding['suppressed']


def _build_index(paths=('skypilot_tpu',)):
    """The pass-1 index over the real tree (test-only re-parse; the
    engine itself reuses its shared trees)."""
    from tools.xskylint import index as index_mod
    idx = index_mod.ProjectIndex(REPO)
    for rel in engine.LintEngine(REPO, []).iter_files(paths):
        with open(os.path.join(REPO, rel), encoding='utf-8') as f:
            src = f.read()
        idx.add_file(rel, ast.parse(src), src)
    return idx


class TestProjectIndex:
    """Pass-1 harvesting proven against the real tree: the verb map
    matches payloads, schemas include migration-added columns, and
    the observability-name harvest sees every plane."""

    @pytest.fixture(scope='class')
    def idx(self):
        return _build_index()

    def test_verb_map_matches_payloads(self, idx):
        from skypilot_tpu.server import payloads
        assert set(idx.verbs) == set(payloads._VERBS)
        status = idx.verbs['status']
        assert status.targets == [('skypilot_tpu.core', 'status')]
        assert 'cluster_names' in status.fields
        assert idx.verbs['launch'].custom    # hand-written resolver
        assert ('skypilot_tpu.execution', 'launch') in \
            idx.verbs['launch'].targets

    def test_every_verb_posted_and_sdk_reachable(self, idx):
        from tools.xskylint import index as index_mod
        for verb in idx.verbs:
            assert verb in idx.posts, f'{verb} never posted'
            assert idx.sdk_reaches(verb), f'{verb} unreachable from sdk'
        assert idx.posted_from('status',
                               index_mod.REMOTE_CLIENT_PATH)

    def test_schema_harvest_includes_migrations(self, idx):
        clusters = idx.schemas[('skypilot_tpu/state.py', 'clusters')]
        assert 'launched_at' in clusters.columns
        # ALTER TABLE migration column:
        assert 'workspace' in clusters.columns
        assert clusters.indexes['idx_clusters_launched'] == \
            ('launched_at',)
        # The (table, 'col TYPE') tuple-loop migration pattern:
        services = idx.schemas[('skypilot_tpu/serve/state.py',
                                'services')]
        assert 'qps' in services.columns

    def test_name_harvest_sees_every_plane(self, idx):
        assert 'xsky_chaos_fires_total' in idx.names['metric']
        assert 'backend.provision' in idx.names['span']
        assert 'fake.preempt' in idx.names['chaos']
        assert 'job.preempted' in idx.names['journal']
        # Sites are (path, line) pairs pointing into the tree.
        path, line = idx.names['chaos']['fake.preempt'][0]
        assert path.startswith('skypilot_tpu/') and line > 0

    def test_container_harvest_tracks_guards(self, idx):
        mod = idx.modules['skypilot_tpu/utils/metrics.py']
        counters = mod.containers['_counters']
        assert len(counters.mutating_functions()) >= 2
        assert not counters.unguarded()   # every site under _lock
        assert '_lock' in mod.locks


class TestCrossfilePass:

    def test_second_pass_keeps_the_parse_counter(self, tmp_path):
        """The whole-program index AND call graph are built from the
        SAME shared trees: a tree exercising every harvest (payloads,
        schema, names, containers, call sites/locks/primitives) still
        parses each file exactly once with all rules (all three
        passes) active."""
        files = {}
        for rule_id in ('verb-wiring', 'name-registry',
                        'lock-discipline', 'schema-consistency',
                        'hot-path-purity', 'lock-order',
                        'never-raise-transitive'):
            files.update(FIXTURES[rule_id][1])   # the clean twins
        _write_tree(tmp_path, files)
        calls = []

        def counting_parse(source, filename='<unknown>', **kw):
            calls.append(filename)
            return ast.parse(source, filename=filename, **kw)

        result = _run(tmp_path, rule_id=None, parse=counting_parse)
        assert result.files_scanned == len(files)
        assert sorted(calls) == sorted(files), (
            'the cross-file pass must reuse the shared trees, never '
            f're-parse; saw {calls}')

    def test_focus_limits_per_file_rules_not_crossfile(self, tmp_path):
        """--changed semantics: per-file rules run only on the focus
        set, but whole-program rules still see (and report on) the
        full tree."""
        files = dict(FIXTURES['lock-discipline'][0])   # reg.py bad
        files['skypilot_tpu/a.py'] = (
            'import threading\n'
            'def go(f):\n'
            '    threading.Thread(target=f).start()\n')
        files['skypilot_tpu/b.py'] = (
            'import threading\n'
            'def go(f):\n'
            '    threading.Thread(target=f).start()\n')
        _write_tree(tmp_path, files)
        result = engine.lint_paths(str(tmp_path), ['.'],
                                   focus={'skypilot_tpu/b.py'})
        by_rule = {}
        for f in result.unsuppressed:
            by_rule.setdefault(f.rule, set()).add(f.path)
        # thread-hygiene (per-file) fired only on the focus file...
        assert by_rule.get('thread-hygiene') == {'skypilot_tpu/b.py'}
        # ...while lock-discipline (whole-program) still reported the
        # unfocused reg.py.
        assert by_rule.get('lock-discipline') == {'skypilot_tpu/reg.py'}

    def test_disjoint_focus_skips_everything(self, tmp_path):
        # The changed file exists but is outside the linted tree: no
        # per-file rules, no index rebuild, no findings.
        _write_tree(tmp_path, FIXTURES['lock-discipline'][0])
        _write_tree(tmp_path, {'other/zzz.py': 'X = 1\n'})
        result = engine.lint_paths(str(tmp_path), ['skypilot_tpu'],
                                   focus={'other/zzz.py'})
        assert result.files_scanned == 0
        assert not result.findings

    def test_deleted_focus_file_still_runs_crossfile_pass(self, tmp_path):
        # A focus path absent from disk is a deletion — deleting an
        # indexed file can move the cross-file verdict, so the
        # whole-program pass must run even though no surviving file
        # changed. The fixture's unguarded singleton proves it ran.
        _write_tree(tmp_path, FIXTURES['lock-discipline'][0])
        result = engine.lint_paths(str(tmp_path), ['.'],
                                   focus={'skypilot_tpu/deleted.py'})
        assert result.files_scanned > 0
        assert any(f.rule == 'lock-discipline' for f in result.findings)

    def test_changed_files_consults_git(self, tmp_path):
        """`xsky lint --changed` file discovery on a throwaway repo:
        committed-and-modified plus untracked .py files are in, the
        untouched one is out."""
        import subprocess

        def git(*args):
            return subprocess.run(
                ['git', '-C', str(tmp_path)] + list(args),
                capture_output=True, text=True, check=False)

        if git('init').returncode != 0:
            pytest.skip('git unavailable')
        git('config', 'user.email', 't@t')
        git('config', 'user.name', 't')
        _write_tree(tmp_path, {'a.py': 'x = 1\n', 'b.py': 'y = 1\n'})
        git('add', '.')
        assert git('commit', '-m', 'seed').returncode == 0
        _write_tree(tmp_path, {'a.py': 'x = 2\n',
                               'new.py': 'z = 1\n'})
        changed = engine.changed_files(str(tmp_path), base='HEAD')
        assert changed == {'a.py', 'new.py'}

    def test_changed_files_reanchors_subdir_root(self, tmp_path):
        """git diff prints toplevel-relative paths; with --root a
        subdirectory of the checkout they must come back root-relative
        (and changes outside the root must drop out), or focus never
        matches and --changed silently lints nothing."""
        import subprocess

        def git(*args):
            return subprocess.run(
                ['git', '-C', str(tmp_path)] + list(args),
                capture_output=True, text=True, check=False)

        if git('init').returncode != 0:
            pytest.skip('git unavailable')
        git('config', 'user.email', 't@t')
        git('config', 'user.name', 't')
        _write_tree(tmp_path, {'sub/a.py': 'x = 1\n',
                               'other.py': 'y = 1\n'})
        git('add', '.')
        assert git('commit', '-m', 'seed').returncode == 0
        _write_tree(tmp_path, {'sub/a.py': 'x = 2\n',
                               'other.py': 'y = 2\n'})
        changed = engine.changed_files(str(tmp_path / 'sub'),
                                       base='HEAD')
        assert changed == {'a.py'}

    def test_index_skipped_without_crossfile_rules(self, tmp_path):
        # A per-file-rule-only run must not pay the whole-program
        # harvesting pass: no active rule declares needs_index, so
        # run.index stays None.
        _write_tree(tmp_path, FIXTURES['lock-discipline'][0])
        from tools.xskylint.rules import all_rules
        rules = [r for r in all_rules() if r.id == 'thread-hygiene']
        eng = engine.LintEngine(str(tmp_path), rules)
        captured = {}
        orig = rules[0].finalize

        def spy(run):
            captured['index'] = run.index
            return orig(run)

        rules[0].finalize = spy
        eng.run(['.'])
        assert captured['index'] is None

    def test_stats_counts_findings_and_suppressions(self, tmp_path):
        _write_tree(tmp_path, {'skypilot_tpu/t.py': (
            'import threading\n'
            'def a(f):\n'
            '    threading.Thread(target=f).start()\n'
            'def b(f):\n'
            '    # xskylint: disable=thread-hygiene -- fixture\n'
            '    threading.Thread(target=f).start()\n')})
        result = _run(tmp_path, 'thread-hygiene')
        stats = result.stats()
        row = stats['thread-hygiene']
        assert row['findings'] == 1
        assert row['suppressed'] == 1
        assert row['reasons'] == ['skypilot_tpu/t.py:6: fixture']

    def test_json_v2_schema(self, tmp_path):
        """The CI contract: schema version + absolute paths so the
        static-analysis job and future tooling parse stably."""
        bad, _ = FIXTURES['span-fanout']
        _write_tree(tmp_path, bad)
        payload = json.loads(json.dumps(
            _run(tmp_path, 'span-fanout').to_json()))
        assert payload['version'] == 2
        assert 'stats' in payload
        (finding,) = payload['findings']
        assert os.path.isabs(finding['abs_path'])
        assert finding['abs_path'].endswith(finding['path'])


class TestCallGraph:
    """Pass-3 call-graph construction proven against the real tree."""

    @pytest.fixture(scope='class')
    def graph(self):
        from tools.xskylint import callgraph
        return callgraph.CallGraph.for_index(_build_index())

    def test_trainer_step_closure(self, graph):
        """The declared training hot path resolves deep enough to be
        useful: Trainer.step transitively reaches the profiler probe
        and the telemetry emit hook."""
        entry = ('skypilot_tpu/train/trainer.py', 'Trainer.step')
        parents = graph.closure([entry])
        assert len(parents) > 10
        assert ('skypilot_tpu/agent/profiler.py',
                'step_probe') in parents
        assert ('skypilot_tpu/agent/telemetry.py', 'emit') in parents
        # BFS chains are shortest entry->node paths and start at the
        # entry.
        chain = graph.chain(
            parents, ('skypilot_tpu/agent/telemetry.py', 'emit'))
        assert chain[0][0] == entry
        assert chain[-1][0] == ('skypilot_tpu/agent/telemetry.py',
                                'emit')

    def test_self_and_module_attr_resolution(self, graph):
        """self-method and imported-module-attr calls resolve."""
        key = ('skypilot_tpu/train/trainer.py', 'Trainer.step')
        targets = {t for t, _ in graph.edges(key)}
        assert ('skypilot_tpu/train/trainer.py',
                'Trainer.compile_step') in targets       # self.
        assert ('skypilot_tpu/agent/profiler.py',
                'step_probe') in targets                 # profiler.
        assert ('skypilot_tpu/train/trainer.py',
                'Trainer._note_step') in targets

    def test_unknown_edges_are_counted_not_silent(self, graph):
        """Dynamic calls the heuristics cannot resolve are an explicit
        per-node budget (the fast decode tick dispatches through
        self.engine.* handles; _decode_tick itself is now a pure
        fast/legacy dispatcher with fully-resolvable edges)."""
        key = ('skypilot_tpu/infer/orchestrator.py',
               'Orchestrator._decode_tick_fast')
        graph.edges(key)   # populate the counter
        assert graph.unknown[key] > 0

    def test_spool_write_is_exempt_not_unreachable(self, graph):
        """The telemetry spool writer is REACHED by the emit closure
        (via the unique-local-method fallback) and carries the
        `# hotpath ok:` def-line exemption — reachable-but-exempt, not
        invisible."""
        parents = graph.closure(
            [('skypilot_tpu/agent/telemetry.py', 'emit')])
        key = ('skypilot_tpu/agent/telemetry.py',
               '_Emitter._write_locked')
        assert key in parents
        node = graph.functions[key]
        assert node.exempt_all
        assert any(p.kind == 'fs-write' for p in node.primitives)

    def test_known_lock_pair_has_no_cycle(self, graph):
        """state.py's journal-buffer lock and write lock are acquired
        SEQUENTIALLY, never nested — no order edge in either
        direction (the lock-order gate for the whole tree is the
        repo-clean test; this pins the canonical pair)."""
        a = 'skypilot_tpu/state.py::_journal_buf_lock'
        b = 'skypilot_tpu/state.py::_lock'
        nested = set()
        for node in graph.functions.values():
            for acq in node.lock_acqs:
                for held in acq.held:
                    nested.add((held, acq.lock))
        assert (a, b) not in nested and (b, a) not in nested
        # The locks themselves ARE harvested (the check is not
        # vacuous).
        acquired = {acq.lock for node in graph.functions.values()
                    for acq in node.lock_acqs}
        assert a in acquired and b in acquired

    def test_no_raise_fixpoint_on_real_helpers(self, graph):
        safe = graph.no_raise_safe()
        gp = 'skypilot_tpu/agent/goodput.py'
        assert safe[(gp, 'empty_ledger')][0]
        # The fold itself is (correctly) not provably safe.
        ok, reason = safe[(gp, 'build_ledger')]
        del reason
        # build_ledger's guarded body may or may not prove out; what
        # matters is the HANDLER call is the proven-safe helper.
        node = graph.functions[(gp, 'build_ledger')]
        calls = node.handler_calls()
        assert [c.name for c in calls] == ['empty_ledger']


class TestInterprocRules:

    def test_hot_path_finding_carries_the_chain(self, tmp_path):
        bad, _ = FIXTURES['hot-path-purity']
        _write_tree(tmp_path, bad)
        result = _run(tmp_path, 'hot-path-purity')
        (finding,) = [f for f in result.unsuppressed
                      if f.rule == 'hot-path-purity']
        assert finding.detail, 'interprocedural finding without chain'
        assert 'emit' in finding.detail[0]
        assert '_flush' in ' '.join(finding.detail)
        # The chain survives the JSON round trip (the --json contract
        # the dashboard and --why share).
        payload = json.loads(json.dumps(result.to_json()))
        (jf,) = [f for f in payload['findings']
                 if f['rule'] == 'hot-path-purity']
        assert jf['detail'] == finding.detail

    def test_lock_order_cycle_names_both_witnesses(self, tmp_path):
        bad, _ = FIXTURES['lock-order']
        _write_tree(tmp_path, bad)
        result = _run(tmp_path, 'lock-order')
        cycles = [f for f in result.unsuppressed
                  if 'cycle' in f.message]
        assert len(cycles) == 1
        detail = ' '.join(cycles[0].detail)
        assert 'nests `with` blocks' in detail
        assert 'calls _grab_b while holding' in detail

    def test_blocking_under_own_db_lock_is_designed(self, tmp_path):
        """A state module's own write lock wrapping its DB work (via
        the db_utils facade) is the serialization point, not a
        finding; a sleep under the same lock IS one."""
        _write_tree(tmp_path, {
            'skypilot_tpu/state.py':
                'import threading\n'
                'import time\n'
                'from skypilot_tpu.utils import db_utils\n'
                '_lock = threading.Lock()\n'
                'def write(conn):\n'
                '    with _lock:\n'
                "        conn.execute('UPDATE t SET x=1')\n"
                'def bad(conn):\n'
                '    with _lock:\n'
                '        time.sleep(1)\n'})
        result = _run(tmp_path, 'lock-order')
        findings = [f for f in result.unsuppressed]
        assert len(findings) == 1
        assert 'sleep' in findings[0].message

    def test_why_chain_round_trip(self, tmp_path, capsys):
        """`xsky lint --why rule:file:line` prints the shortest
        entry->violation chain for a focused re-run."""
        bad, _ = FIXTURES['hot-path-purity']
        _write_tree(tmp_path, bad)
        result = _run(tmp_path, 'hot-path-purity')
        (finding,) = result.unsuppressed
        spec = f'hot-path-purity:{finding.path}:{finding.line}'
        rc = engine.main(['--root', str(tmp_path), '--why', spec,
                          '--no-cache'])
        out = capsys.readouterr().out
        assert rc == 0
        assert 'blocking sleep' in out
        assert 'emit' in out and '_flush' in out
        # A miss is an error, with a hint at the rule's real lines.
        rc = engine.main(['--root', str(tmp_path), '--why',
                          f'hot-path-purity:{finding.path}:9999',
                          '--no-cache'])
        assert rc == 1

    def test_proof_never_trusts_the_unique_method_guess(self,
                                                        tmp_path):
        """The unique-local-method heuristic over-approximates, which
        is safe for purity/lock CLOSURES but unsound as a never-raise
        PROOF: a fallback-arm `obj.get()` that happens to collide
        with the one safe local method must stay UNPROVEN (flagged),
        because obj may be any imported class whose get() raises."""
        src = (
            'class _LocalSafe:\n'
            '    def get(self):\n'
            '        return None\n'
            'def inc_counter(name, help_text, value=1.0, **labels):\n'
            '    try:\n'
            '        _bump(name)\n'
            '    except Exception:\n'
            '        return spool.get()\n'
            'def observe(name, help_text, value, **labels):\n'
            '    try:\n'
            '        _bump(name)\n'
            '    except Exception:\n'
            '        pass\n'
            'def _bump(name):\n'
            '    pass\n')
        _write_tree(tmp_path, {'skypilot_tpu/utils/metrics.py': src})
        findings = [f for f in _run(
            tmp_path, 'never-raise-transitive').unsuppressed]
        assert len(findings) == 1
        assert 'cannot resolve' in findings[0].message

    def test_cross_module_db_witness_not_shadowed(self, tmp_path):
        """A helper whose closure holds BOTH its own-module db work
        (designed, exempt) and a cross-module db primitive must still
        yield the cross-module blocking-under-lock finding — one
        witness per kind alone would let the benign site shadow it."""
        _write_tree(tmp_path, {
            'skypilot_tpu/state.py':
                'import threading\n'
                'from skypilot_tpu.serve import state as serve_state\n'
                '_lock = threading.Lock()\n'
                'def write(conn):\n'
                '    with _lock:\n'
                '        _both(conn)\n'
                'def _both(conn):\n'
                "    conn.execute('UPDATE t SET x=1')\n"
                '    serve_state.touch(conn)\n',
            'skypilot_tpu/serve/state.py':
                'def touch(conn):\n'
                "    conn.execute('UPDATE s SET y=1')\n"})
        findings = _run(tmp_path, 'lock-order').unsuppressed
        assert len(findings) == 1
        assert 'serve/state.py' in findings[0].message

    def test_match_case_bodies_are_harvested(self, tmp_path):
        """match-statement case bodies are lists of match_case, not
        stmt — the harvester must walk them explicitly or a blocking
        call there goes silently invisible (the decode-loop rewrite
        this lint referees will use match dispatch)."""
        _write_tree(tmp_path, {
            'skypilot_tpu/agent/telemetry.py':
                'import time\n'
                'def emit(**kw):\n'
                "    match kw.get('kind'):\n"
                "        case 'slow':\n"
                '            time.sleep(1)\n'
                '        case _:\n'
                '            pass\n'})
        findings = _run(tmp_path, 'hot-path-purity').unsuppressed
        assert len(findings) == 1
        assert 'sleep' in findings[0].message

    def test_hot_path_entry_table_staleness(self, tmp_path):
        """A listed module that exists WITHOUT its entry function is a
        stale contract — the table must not rot silently."""
        _write_tree(tmp_path, {
            'skypilot_tpu/agent/telemetry.py':
                'def some_other_function():\n    pass\n'})
        result = _run(tmp_path, 'hot-path-purity')
        assert any('stale' in f.message
                   for f in result.unsuppressed)


class TestAstCache:

    def _counting(self, calls):
        def counting_parse(source, filename='<unknown>', **kw):
            calls.append(filename)
            return ast.parse(source, filename=filename, **kw)
        return counting_parse

    def test_cache_hits_skip_the_parser(self, tmp_path):
        """Second run with the same tree: ZERO ast.parse calls, same
        verdicts — the cache accelerates, never decides."""
        _write_tree(tmp_path, FIXTURES['hot-path-purity'][0])
        cache_dir = str(tmp_path / '.xskylint_cache')
        calls = []
        r1 = engine.lint_paths(str(tmp_path), ['skypilot_tpu'],
                               parse=self._counting(calls),
                               cache_dir=cache_dir)
        assert len(calls) == 1
        calls.clear()
        r2 = engine.lint_paths(str(tmp_path), ['skypilot_tpu'],
                               parse=self._counting(calls),
                               cache_dir=cache_dir)
        assert calls == [], 'warm cache must not re-parse'
        assert [f.render() for f in r1.findings] == \
            [f.render() for f in r2.findings]

    def test_cache_invalidates_on_mtime_or_size(self, tmp_path):
        _write_tree(tmp_path, {'skypilot_tpu/a.py': 'x = 1\n'})
        cache_dir = str(tmp_path / '.xskylint_cache')
        calls = []
        engine.lint_paths(str(tmp_path), ['skypilot_tpu'],
                          parse=self._counting(calls),
                          cache_dir=cache_dir)
        # Content (and size/mtime) change: must re-parse.
        _write_tree(tmp_path, {'skypilot_tpu/a.py': 'x = 22\n'})
        calls.clear()
        engine.lint_paths(str(tmp_path), ['skypilot_tpu'],
                          parse=self._counting(calls),
                          cache_dir=cache_dir)
        assert calls == ['skypilot_tpu/a.py']

    def test_cache_invalidates_on_content_despite_same_mtime(
            self, tmp_path):
        """A same-size edit with a restored mtime (coarse-granularity
        filesystems make this a real race) must still re-parse — the
        key includes the source sha1, so the cache can never serve a
        stale tree."""
        path = tmp_path / 'skypilot_tpu' / 'a.py'
        _write_tree(tmp_path, {'skypilot_tpu/a.py': 'x = 1\n'})
        st = path.stat()
        cache_dir = str(tmp_path / '.xskylint_cache')
        calls = []
        engine.lint_paths(str(tmp_path), ['skypilot_tpu'],
                          parse=self._counting(calls),
                          cache_dir=cache_dir)
        path.write_text('x = 2\n')   # same byte count
        os.utime(path, ns=(st.st_atime_ns, st.st_mtime_ns))
        calls.clear()
        engine.lint_paths(str(tmp_path), ['skypilot_tpu'],
                          parse=self._counting(calls),
                          cache_dir=cache_dir)
        assert calls == ['skypilot_tpu/a.py']

    def test_corrupt_cache_degrades_to_parse(self, tmp_path):
        _write_tree(tmp_path, {'skypilot_tpu/a.py': 'x = 1\n'})
        cache_dir = tmp_path / '.xskylint_cache'
        calls = []
        engine.lint_paths(str(tmp_path), ['skypilot_tpu'],
                          parse=self._counting(calls),
                          cache_dir=str(cache_dir))
        for entry in cache_dir.iterdir():
            entry.write_bytes(b'not a pickle')
        calls.clear()
        result = engine.lint_paths(str(tmp_path), ['skypilot_tpu'],
                                   parse=self._counting(calls),
                                   cache_dir=str(cache_dir))
        assert calls == ['skypilot_tpu/a.py']
        assert result.files_scanned == 1


class TestSuppressionBaseline:

    def _result(self, tmp_path, n_suppressed):
        src = 'import threading\n'
        for i in range(n_suppressed):
            src += (
                f'def f{i}(f):\n'
                '    # xskylint: disable=thread-hygiene -- fixture\n'
                '    threading.Thread(target=f).start()\n')
        _write_tree(tmp_path, {'skypilot_tpu/t.py': src})
        return _run(tmp_path, 'thread-hygiene')

    def test_ratchet_passes_at_or_below_baseline(self, tmp_path):
        result = self._result(tmp_path, 2)
        engine.write_baseline(str(tmp_path), result)
        ok, messages = engine.check_baseline(str(tmp_path), result)
        assert ok and not messages
        # Shrinking passes with a ratchet-down nudge.
        shrunk = self._result(tmp_path, 1)
        ok, messages = engine.check_baseline(str(tmp_path), shrunk)
        assert ok
        assert any('ratchet the baseline down' in m for m in messages)

    def test_ratchet_fails_on_growth(self, tmp_path):
        result = self._result(tmp_path, 1)
        engine.write_baseline(str(tmp_path), result)
        grown = self._result(tmp_path, 2)
        ok, messages = engine.check_baseline(str(tmp_path), grown)
        assert not ok
        assert any('suppression debt grew' in m for m in messages)

    def test_missing_baseline_is_an_error(self, tmp_path):
        result = self._result(tmp_path, 1)
        ok, messages = engine.check_baseline(str(tmp_path), result)
        assert not ok
        assert any('--write-baseline' in m for m in messages)

    def test_baseline_flags_refuse_partial_runs(self, tmp_path,
                                                capsys):
        """--write-baseline/--check-baseline on a --changed/--rule/
        subtree run would count a SUBSET of suppressions — the CLI
        refuses rather than gutting the baseline or passing growth."""
        self._result(tmp_path, 1)   # writes the fixture tree
        for extra in (['--changed'], ['--rule', 'thread-hygiene'],
                      ['skypilot_tpu']):
            rc = engine.main(['--root', str(tmp_path), '--no-cache',
                              '--write-baseline'] + extra)
            assert rc == 2, extra
            assert 'full default run' in capsys.readouterr().err

    def test_exempt_primitive_still_counts_under_a_lock(self,
                                                        tmp_path):
        """`# hotpath ok:` bounds a site's hot-path cost, not the
        time a lock stays held over it — lock-order reports the
        marked sleep identically whether it sits in the locked
        function or in a helper called under the lock."""
        src_inline = (
            'import threading\n'
            'import time\n'
            '_L = threading.Lock()\n'
            'def f():\n'
            '    with _L:\n'
            '        # hotpath ok: bounded to one tick\n'
            '        time.sleep(1)\n')
        src_helper = (
            'import threading\n'
            'import time\n'
            '_L = threading.Lock()\n'
            'def f():\n'
            '    with _L:\n'
            '        _nap()\n'
            'def _nap():\n'
            '    # hotpath ok: bounded to one tick\n'
            '    time.sleep(1)\n')
        for src in (src_inline, src_helper):
            tree = tmp_path / ('a' if src is src_inline else 'b')
            _write_tree(tree, {'skypilot_tpu/m.py': src})
            findings = _run(tree, 'lock-order').unsuppressed
            assert len(findings) == 1, src
            assert 'sleep' in findings[0].message

    def test_checked_in_baseline_matches_the_tree(self):
        """The tier-1 ratchet: current suppression counts must not
        exceed tools/xskylint/suppressions_baseline.json. (Shrinkage
        is allowed at runtime but the baseline should then be
        ratcheted down in the same diff — CI prints the nudge.)"""
        result = engine.lint_paths(REPO, ['skypilot_tpu', 'tools'])
        ok, messages = engine.check_baseline(REPO, result)
        assert ok, messages


class TestTier1Gate:
    """`xsky lint` as a pytest gate: the real tree must be clean."""

    def test_repo_is_lint_clean(self):
        result = engine.lint_paths(REPO, ['skypilot_tpu', 'tools'])
        assert not result.unsuppressed, (
            'xskylint findings in the tree (fix them or suppress '
            'with `# xskylint: disable=<rule> -- <reason>`):\n  ' +
            '\n  '.join(f.render() for f in result.unsuppressed))
        # The three genuine exemptions (agent-local DBs, the
        # replica-local requests DB) stay suppressed WITH reasons.
        assert all(f.reason for f in result.findings if f.suppressed)

    def test_cli_json_gate(self):
        """The subprocess entry point: exit 0 on the clean tree and
        parseable --json (the dashboard contract)."""
        proc = subprocess.run(
            [sys.executable, '-m', 'tools.xskylint', 'skypilot_tpu',
             'tools', '--json'],
            cwd=REPO, capture_output=True, text=True, check=False)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        payload = json.loads(proc.stdout)
        assert payload['unsuppressed_count'] == 0
        assert payload['files_scanned'] > 200
        assert set(payload['rules']) == {r.id for r in all_rules()}

    def test_env_docs_regenerate_and_diff(self):
        """docs/reference/environment.md is byte-identical to the
        registry rendering (the env-registry rule's staleness check,
        asserted directly so a drift names THIS test)."""
        from skypilot_tpu.utils import env_registry
        with open(os.path.join(REPO, 'docs', 'reference',
                               'environment.md'),
                  encoding='utf-8') as f:
            committed = f.read()
        assert committed == env_registry.render_markdown(), (
            'docs/reference/environment.md is stale — regenerate with '
            '`python -m skypilot_tpu.utils.env_registry > '
            'docs/reference/environment.md`')

    def test_env_registry_covers_every_read(self):
        """Direct form of the env-registry contract (the lint gate
        covers it too; this failure message is more specific)."""
        from skypilot_tpu.utils import env_registry
        result = engine.lint_paths(REPO, ['skypilot_tpu'],
                                   rule_ids=['env-registry'])
        assert not result.unsuppressed, [
            f.render() for f in result.unsuppressed]
        # And the registry itself is well-formed: every entry
        # documents a name that matches its key.
        for name, var in env_registry.REGISTRY.items():
            assert name == var.name
            assert var.doc.strip()

    def test_names_docs_regenerate_and_diff(self):
        """docs/reference/observability-names.md is byte-identical to
        the names-registry rendering (the name-registry rule's
        staleness check, asserted directly so a drift names THIS
        test)."""
        from skypilot_tpu.utils import names_registry
        with open(os.path.join(REPO, 'docs', 'reference',
                               'observability-names.md'),
                  encoding='utf-8') as f:
            committed = f.read()
        assert committed == names_registry.render_markdown(), (
            'docs/reference/observability-names.md is stale — '
            'regenerate with `python -m '
            'skypilot_tpu.utils.names_registry > '
            'docs/reference/observability-names.md`')

    def test_names_registry_covers_every_mint(self):
        """Direct form of the name-registry contract (the lint gate
        covers it too; this failure message is more specific)."""
        from skypilot_tpu.utils import names_registry
        result = engine.lint_paths(REPO, ['skypilot_tpu'],
                                   rule_ids=['name-registry'])
        assert not result.unsuppressed, [
            f.render() for f in result.unsuppressed]
        for (kind, name), obs in names_registry.REGISTRY.items():
            assert (kind, name) == (obs.kind, obs.name)
            assert obs.doc.strip()
