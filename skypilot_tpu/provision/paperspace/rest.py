"""Paperspace (DigitalOcean Gradient) REST transport.

Role twin of sky/provision/paperspace/utils.py on this repo's
transport pattern. Key from $PAPERSPACE_API_KEY or
~/.paperspace/config.json ({"apiKey": "..."}).
"""
from __future__ import annotations

import json
import os
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Dict, Optional

from skypilot_tpu import exceptions

API_ENDPOINT = 'https://api.paperspace.com/v1'
CREDENTIALS_PATH = '~/.paperspace/config.json'
_MAX_ATTEMPTS = 4
_BACKOFF_S = 2.0


class PaperspaceApiError(Exception):

    def __init__(self, status: int, message: str) -> None:
        super().__init__(f'{status}: {message}')
        self.status = status
        self.message = message


def load_api_key() -> Optional[str]:
    key = os.environ.get('PAPERSPACE_API_KEY')
    if key:
        return key
    path = os.path.expanduser(CREDENTIALS_PATH)
    if not os.path.exists(path):
        return None
    try:
        with open(path, encoding='utf-8') as f:
            return json.load(f).get('apiKey')
    except (OSError, ValueError):
        return None


def classify_error(e: PaperspaceApiError,
                   region: Optional[str] = None) -> Exception:
    text = e.message.lower()
    where = f' in {region}' if region else ''
    if 'out of capacity' in text or 'no machine available' in text or \
            'insufficient capacity' in text:
        return exceptions.CapacityError(f'Paperspace capacity{where}: {e}')
    if 'quota' in text or 'limit' in text:
        return exceptions.QuotaExceededError(
            f'Paperspace quota{where}: {e}')
    if e.status in (401, 403):
        return exceptions.PermissionError_(f'Paperspace auth: {e}')
    if e.status in (400, 422):
        return exceptions.InvalidRequestError(f'Paperspace request: {e}')
    return exceptions.ProvisionError(f'Paperspace API{where}: {e}')


class Transport:

    def __init__(self, api_key: Optional[str] = None) -> None:
        key = api_key or load_api_key()
        if not key:
            raise exceptions.PermissionError_(
                'Paperspace API key not found (set $PAPERSPACE_API_KEY '
                f'or populate {CREDENTIALS_PATH}).')
        self._key = key

    def call(self, method: str, path: str,
             body: Optional[Dict[str, Any]] = None,
             query: Optional[Dict[str, Any]] = None) -> Any:
        url = f'{API_ENDPOINT}{path}'
        if query:
            url += '?' + urllib.parse.urlencode(query)
        data = json.dumps(body).encode() if body is not None else None
        for attempt in range(_MAX_ATTEMPTS):
            req = urllib.request.Request(
                url, data=data, method=method,
                headers={'Authorization': f'Bearer {self._key}',
                         'Content-Type': 'application/json'})
            try:
                with urllib.request.urlopen(req, timeout=60) as resp:
                    payload = resp.read()
                    return json.loads(payload) if payload else {}
            except urllib.error.HTTPError as e:
                if e.code == 429 and attempt < _MAX_ATTEMPTS - 1:
                    time.sleep(_BACKOFF_S * (attempt + 1))
                    continue
                try:
                    err = json.loads(e.read() or b'{}')
                    message = err.get('message') or str(e)
                    raise PaperspaceApiError(e.code, str(message))
                except (ValueError, AttributeError):
                    raise PaperspaceApiError(e.code, str(e)) from e
            except urllib.error.URLError as e:
                raise exceptions.ProvisionError(
                    f'Paperspace API unreachable: {e}') from e
        # Unreachable: every iteration returns or raises.
