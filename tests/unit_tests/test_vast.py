"""Vast.ai provisioner tests against an in-memory marketplace fake.

Same pattern as the Lambda/RunPod fakes: scripted offer inventory and
rent-time races, no network.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import pytest

from skypilot_tpu import exceptions
from skypilot_tpu.provision import common
from skypilot_tpu.provision.vast import instance as vast_instance
from skypilot_tpu.provision.vast import rest


class FakeVast:
    """Minimal in-memory Vast marketplace + instances API."""

    def __init__(self) -> None:
        self.offers: List[Dict[str, Any]] = [
            {'id': 100, 'gpu_name': 'H100 PCIE', 'num_gpus': 1,
             'dph_total': 1.93, 'min_bid': 0.97, 'geolocation':
             'Dallas, TX, US'},
            {'id': 101, 'gpu_name': 'H100 PCIE', 'num_gpus': 1,
             'dph_total': 2.10, 'min_bid': 1.00, 'geolocation':
             'Sofia, BG'},
        ]
        self.instances: Dict[int, Dict[str, Any]] = {}
        self.gone_offers: set = set()
        self.queries: List[Dict[str, Any]] = []
        self.rents: List[Dict[str, Any]] = []
        self._next_id = 1000

    def call(self, method: str, path: str,
             body: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        if path == '/bundles/' and method == 'PUT':
            q = body['q']
            self.queries.append(q)
            cc = q.get('geolocation', {}).get('eq')
            matches = [
                o for o in self.offers
                if o['gpu_name'] == q['gpu_name']['eq']
                and o['num_gpus'] == q['num_gpus']['eq']
                and (cc is None or o['geolocation'].endswith(cc))
            ]
            return {'offers': sorted(matches,
                                     key=lambda o: o['dph_total'])}
        if path.startswith('/asks/') and method == 'PUT':
            ask_id = int(path.split('/')[2])
            if ask_id in self.gone_offers:
                return {'success': False, 'msg': 'no_such_ask'}
            self.rents.append(dict(body))
            offer = next(o for o in self.offers if o['id'] == ask_id)
            self._next_id += 1
            iid = self._next_id
            self.instances[iid] = {
                'id': iid, 'label': body['label'],
                'actual_status': 'running',
                'ssh_host': f'ssh{iid}.vast.ai',
                'ssh_port': 20000 + iid,
                'num_gpus': offer['num_gpus'],
            }
            return {'success': True, 'new_contract': iid}
        if path == '/instances/' and method == 'GET':
            return {'instances': list(self.instances.values())}
        if path.startswith('/instances/') and method == 'PUT':
            iid = int(path.split('/')[2])
            state = body['state']
            self.instances[iid]['actual_status'] = (
                'running' if state == 'running' else 'stopped')
            return {'success': True}
        if path.startswith('/instances/') and method == 'DELETE':
            self.instances.pop(int(path.split('/')[2]), None)
            return {'success': True}
        raise AssertionError(f'unhandled Vast call {method} {path}')


@pytest.fixture()
def fake_vast(monkeypatch):
    fake = FakeVast()
    monkeypatch.setattr(vast_instance, '_transport_factory', lambda: fake)
    yield fake


PROVIDER: Dict[str, Any] = {}


def _config(count=1, spot=False):
    node_config = {'instance_type': '1x_H100',
                   'gpu_name': 'H100 PCIE', 'gpu_count': 1,
                   'memory_gb': 64, 'disk_size': 50,
                   'image_name': 'vastai/base-image:cuda-12.4.1-auto',
                   'use_spot': spot, 'public_key': 'ssh-ed25519 AAAA'}
    if spot:
        node_config['bid'] = 0.97
    return common.ProvisionConfig(provider_config=dict(PROVIDER),
                                  node_config=node_config, count=count)


def test_launch_picks_cheapest_offer_in_region(fake_vast):
    record = vast_instance.run_instances('US', None, 'v1', _config())
    assert len(record.created_instance_ids) == 1
    # Offer 100 ($1.93, US) beats 101 ($2.10, BG) and matches region.
    q = fake_vast.queries[-1]
    assert q['geolocation'] == {'eq': 'US'}
    info = vast_instance.get_cluster_info('US', 'v1', PROVIDER)
    hosts = info.sorted_instances()
    assert hosts[0].ssh_port > 20000
    assert hosts[0].external_ip.endswith('vast.ai')
    assert info.ssh_user == 'root'
    vast_instance.terminate_instances('v1', PROVIDER)
    assert vast_instance.query_instances('v1', PROVIDER) == {}


def test_rent_race_classified_as_capacity(fake_vast):
    fake_vast.gone_offers.add(100)
    # Offer 100 matches the search but is rented out from under us at
    # rent time; the failure must surface as CapacityError so failover
    # walks on.
    with pytest.raises(exceptions.CapacityError):
        vast_instance.run_instances('US', None, 'v2', _config())


def test_no_offer_is_capacity_error(fake_vast):
    fake_vast.offers.clear()
    with pytest.raises(exceptions.CapacityError):
        vast_instance.run_instances('US', None, 'v3', _config())


def test_stop_resume_cycle(fake_vast):
    vast_instance.run_instances('US', None, 'v4', _config())
    vast_instance.stop_instances('v4', PROVIDER)
    assert set(vast_instance.query_instances('v4', PROVIDER).values()) \
        == {'STOPPED'}
    record = vast_instance.run_instances('US', None, 'v4', _config())
    assert record.created_instance_ids == []
    assert len(record.resumed_instance_ids) == 1
    assert set(vast_instance.query_instances('v4', PROVIDER).values()) \
        == {'RUNNING'}


def test_spot_rent_carries_bid(fake_vast):
    vast_instance.run_instances('US', None, 'v5', _config(spot=True))
    # Bid search asked the marketplace for interruptible offers.
    assert fake_vast.queries[-1]['type'] == 'bid'


def test_wait_instances(fake_vast):
    vast_instance.run_instances('US', None, 'v6', _config())
    vast_instance.wait_instances('US', 'v6', 'RUNNING', PROVIDER,
                                 timeout_s=5, poll_interval_s=0.01)
    for inst in fake_vast.instances.values():
        inst['actual_status'] = 'offline'
    with pytest.raises(exceptions.CapacityError):
        vast_instance.wait_instances('US', 'v6', 'RUNNING', PROVIDER,
                                     timeout_s=5, poll_interval_s=0.01)


def test_cloud_feasibility_and_pricing():
    from skypilot_tpu import resources as resources_lib
    from skypilot_tpu.utils import registry
    cloud = registry.CLOUD_REGISTRY.from_str('vast')
    r = resources_lib.Resources(accelerators='H100:1')
    feasible, _ = cloud.get_feasible_launchable_resources(r)
    assert feasible
    assert feasible[0].instance_type == '1x_H100'
    assert feasible[0].get_hourly_cost() == pytest.approx(1.93)
    spot = resources_lib.Resources(accelerators='H100:1', use_spot=True)
    feasible, _ = cloud.get_feasible_launchable_resources(spot)
    assert feasible[0].get_hourly_cost() == pytest.approx(0.97)


def test_deploy_variables(monkeypatch, tmp_path):
    from skypilot_tpu import authentication
    from skypilot_tpu import resources as resources_lib
    from skypilot_tpu.utils import registry
    key = tmp_path / 'key.pub'
    key.write_text('ssh-ed25519 AAAA test\n')
    monkeypatch.setattr(authentication, 'get_or_generate_keys',
                        lambda: (str(tmp_path / 'key'), str(key)))
    cloud = registry.CLOUD_REGISTRY.from_str('vast')
    r = resources_lib.Resources(cloud=cloud, instance_type='1x_H100',
                                accelerators='H100:1')
    vars = cloud.make_deploy_resources_variables(r, 'c', 'US', None)
    assert vars['gpu_name'] == 'H100 PCIE'
    assert vars['disk_size'] == r.disk_size
    assert vars['public_key'].startswith('ssh-ed25519')
    # An unreadable key fails BEFORE anything is rented.
    key.unlink()
    with pytest.raises(OSError):
        cloud.make_deploy_resources_variables(r, 'c', 'US', None)


def test_spot_bid_never_below_catalog(fake_vast):
    # Offer 100's min_bid is 0.97. A 1.10 catalog bid must be placed
    # as-is (bidding exactly min_bid is instantly outbid)...
    cfg = _config(spot=True)
    cfg.node_config['bid'] = 1.10
    vast_instance.run_instances('US', None, 'v7', cfg)
    assert fake_vast.rents[-1]['price'] == pytest.approx(1.10)
    # ...and a stale catalog bid below min_bid is raised to min_bid.
    cfg2 = _config(spot=True)
    cfg2.node_config['bid'] = 0.50
    vast_instance.run_instances('US', None, 'v8', cfg2)
    assert fake_vast.rents[-1]['price'] == pytest.approx(0.97)


def test_check_credentials(monkeypatch, tmp_path):
    from skypilot_tpu.utils import registry
    cloud = registry.CLOUD_REGISTRY.from_str('vast')
    monkeypatch.delenv('VAST_API_KEY', raising=False)
    monkeypatch.setattr(rest, 'CREDENTIALS_PATH',
                        str(tmp_path / 'vast_key'))
    ok, reason = cloud.check_credentials()
    assert not ok and 'VAST_API_KEY' in reason
    (tmp_path / 'vast_key').write_text('vast_secret\n')
    assert rest.load_api_key() == 'vast_secret'
    ok, _ = cloud.check_credentials()
    assert ok
