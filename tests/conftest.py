"""Shared test fixtures.

JAX tests run on a virtual 8-device CPU mesh (multi-chip sharding logic is
testable without TPUs); orchestration tests enable the fake cloud.
"""
import os

import sys


def _tpu_tier_invocation() -> bool:
    """True only for a run that actually targets the on-silicon tier.

    Both the env opt-in AND a tpu-targeting argument must be present
    (a path under tests/tpu, or `-m tpu`): XSKY_TPU_TESTS=1 on a broad
    `pytest tests/` run must NOT silently strip the 8-device virtual
    CPU mesh from every other test.
    """
    if not os.environ.get('XSKY_TPU_TESTS'):
        return False
    args = sys.argv
    if any('tests/tpu' in a or a.rstrip('/').endswith('/tpu')
           or a.rstrip('/') == 'tpu' for a in args):
        return True
    for i, a in enumerate(args):
        if a == '-m' and i + 1 < len(args) and 'tpu' in args[i + 1]:
            return True
        if a.startswith('-m=') and 'tpu' in a:
            return True
    return False


# Must be set before jax import anywhere in the test process.
if _tpu_tier_invocation():
    # On-silicon kernel tier (`XSKY_TPU_TESTS=1 pytest tests/tpu -m tpu`):
    # keep the real TPU backend — Mosaic lowering + numerics on the chip
    # are exactly what this tier exists to catch (VERDICT r3 #3: the
    # decode kernel shipped un-lowerable for two sessions because only
    # interpret mode ever ran it).
    import jax  # noqa: E402
else:
    _xla_flags = os.environ.get('XLA_FLAGS', '')
    if '--xla_force_host_platform_device_count' not in _xla_flags:
        os.environ['XLA_FLAGS'] = (
            _xla_flags + ' --xla_force_host_platform_device_count=8'
        ).strip()
    # Tests run on the virtual CPU mesh, even when a TPU is attached
    # (the real chip is for bench.py and the tpu tier). The axon
    # sitecustomize force-registers the TPU backend and overrides
    # JAX_PLATFORMS, so the env var alone is not enough — set the config
    # knob before any jax computation.
    os.environ['JAX_PLATFORMS'] = 'cpu'
    import jax  # noqa: E402

    jax.config.update('jax_platforms', 'cpu')

import pytest

from skypilot_tpu import check as check_lib


@pytest.fixture(autouse=True, scope='module')
def _clear_jax_caches_between_modules():
    """Drop compiled executables between test modules.

    A full single-process slow-tier run accumulates hundreds of
    compiled programs; around the ~190th jit-heavy test XLA's CPU
    backend segfaults inside backend_compile_and_load (observed
    deterministically in round 4, with >100 GB RAM free — native
    compile-state buildup, not OOM). Modules rarely share shapes, so
    per-module cache clearing costs little and keeps the one-process
    suite viable.
    """
    yield
    jax.clear_caches()


@pytest.fixture
def enable_fake_cloud(monkeypatch):
    """Enable only the fake cloud (twin of reference enable_all_clouds,
    tests/common_test_fixtures.py:191-253)."""
    monkeypatch.setenv('XSKY_ENABLE_FAKE_CLOUD', '1')
    check_lib.set_enabled_clouds_for_test(['fake'])
    yield
    check_lib.set_enabled_clouds_for_test(None)


@pytest.fixture
def fake_cluster_env(monkeypatch, tmp_path):
    """Fake cloud + isolated state DB + clean fake provisioner store.

    The full launch-stack harness: twin of the reference's _mock_db_conn +
    moto pattern (tests/test_failover.py:21-60).
    """
    from skypilot_tpu import state
    from skypilot_tpu.provision.fake import instance as fake_instance
    monkeypatch.setenv('XSKY_ENABLE_FAKE_CLOUD', '1')
    monkeypatch.setenv('XSKY_STATE_DB', str(tmp_path / 'state.db'))
    monkeypatch.setenv('XSKY_FAKE_CLOUD_DIR', str(tmp_path / 'fake_cloud'))
    check_lib.set_enabled_clouds_for_test(['fake'])
    state.reset_for_test()
    fake_instance.reset()
    yield fake_instance
    check_lib.set_enabled_clouds_for_test(None)
    fake_instance.reset()
    state.reset_for_test()


@pytest.fixture
def enable_gcp_and_fake(monkeypatch):
    """Pretend GCP credentials exist alongside the fake cloud."""
    monkeypatch.setenv('XSKY_ENABLE_FAKE_CLOUD', '1')
    check_lib.set_enabled_clouds_for_test(['gcp', 'fake'])
    yield
    check_lib.set_enabled_clouds_for_test(None)
