"""Lazy cloud-SDK adaptors (twin of sky/adaptors/, 2,109 LoC).

Deliberately small here. The reference needs ~15 adaptor modules because
every cloud is driven through its heavyweight SDK (boto3,
azure-mgmt-*, googleapiclient, ibm_*, oci, ...) which must stay an
optional dependency; the LazyImport proxy (common.py) is the mechanism.

This rebuild drives clouds through hand-rolled REST transports instead
(`provision/gcp/rest.py`, `provision/aws/rest.py` SigV4,
`provision/azure/rest.py` ARM+OAuth2): stdlib-only, no SDK to defer, so
there is nothing for an adaptor to lazily import. The pattern is kept
for the places a real SDK *is* optionally used:

  * gcp.py — googleapiclient discovery builders for APIs the lean REST
    client does not cover (storage transfer service);
  * common.LazyImport — reused by data/ for optional storage SDKs.

Adding a cloud via its SDK? Create its adaptor here with LazyImport and
point `clouds/<name>.py` at it — the reference's layering applies
unchanged.
"""
from skypilot_tpu.adaptors.common import LazyImport

__all__ = ['LazyImport']
