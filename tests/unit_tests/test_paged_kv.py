"""Paged-KV page allocator + decode-tick bench gate (host-only tier).

Model-level paged/dense parity and device-side finish masking live in
test_inference.py (slow tier — jit compiles); this file covers the
pure-host allocator invariants the serving fast path leans on, and
runs the decode-tick host-cost bench as a subprocess acceptance gate.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from skypilot_tpu.infer import paged_kv

_REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))


class TestPageAllocator:

    def test_allocate_reserves_ceil_div_pages(self):
        pa = paged_kv.PageAllocator(num_pages=8, page_size=16,
                                    blocks_per_slot=8)
        assert pa.pages_for(1) == 1
        assert pa.pages_for(16) == 1
        assert pa.pages_for(17) == 2
        assert pa.allocate(0, 33)          # 3 pages
        assert pa.free_pages == 5
        assert pa.used_pages == 3

    def test_table_row_pages_then_sentinel(self):
        pa = paged_kv.PageAllocator(num_pages=4, page_size=8,
                                    blocks_per_slot=3)
        assert pa.allocate(1, 12)          # 2 pages
        row = pa.table_row(1)
        assert row.shape == (3,) and row.dtype == np.int32
        assert all(0 <= p < 4 for p in row[:2])
        assert row[2] == pa.sentinel == 4
        # Unallocated slots get all-sentinel rows.
        assert list(pa.table_row(0)) == [4, 4, 4]

    def test_exhaustion_fails_without_state_change(self):
        pa = paged_kv.PageAllocator(num_pages=4, page_size=8,
                                    blocks_per_slot=4)
        assert pa.allocate(0, 24)          # 3 of 4 pages
        free_before = pa.free_pages
        assert not pa.allocate(1, 16)      # needs 2, only 1 left
        assert pa.free_pages == free_before
        assert list(pa.table_row(1)) == [pa.sentinel] * 4

    def test_double_allocate_same_slot_raises(self):
        pa = paged_kv.PageAllocator(num_pages=4, page_size=8,
                                    blocks_per_slot=4)
        assert pa.allocate(0, 8)
        with pytest.raises(ValueError):
            pa.allocate(0, 8)

    def test_release_returns_pages_and_is_idempotent(self):
        pa = paged_kv.PageAllocator(num_pages=4, page_size=8,
                                    blocks_per_slot=4)
        assert pa.allocate(0, 32)
        assert pa.free_pages == 0
        pa.release(0)
        assert pa.free_pages == 4
        pa.release(0)                      # second release: no-op
        assert pa.free_pages == 4

    def test_released_pages_reused_lifo(self):
        pa = paged_kv.PageAllocator(num_pages=4, page_size=8,
                                    blocks_per_slot=4)
        assert pa.allocate(0, 16)
        first = list(pa.table_row(0)[:2])
        pa.release(0)
        assert pa.allocate(1, 16)
        # LIFO free list: the just-released pages come back first —
        # the warmest HBM pages get reused.
        assert list(pa.table_row(1)[:2]) == first

    def test_can_admit_tracks_headroom(self):
        pa = paged_kv.PageAllocator(num_pages=4, page_size=8,
                                    blocks_per_slot=4)
        assert pa.can_admit(32)
        assert pa.allocate(0, 24)
        assert pa.can_admit(8)
        assert not pa.can_admit(16)

    def test_release_all(self):
        pa = paged_kv.PageAllocator(num_pages=6, page_size=8,
                                    blocks_per_slot=3)
        assert pa.allocate(0, 20) and pa.allocate(1, 8)
        pa.release_all()
        assert pa.free_pages == 6
        assert list(pa.table_row(0)) == [pa.sentinel] * 3

    def test_distinct_slots_get_distinct_pages(self):
        pa = paged_kv.PageAllocator(num_pages=8, page_size=8,
                                    blocks_per_slot=4)
        assert pa.allocate(0, 32) and pa.allocate(1, 32)
        p0 = set(pa.table_row(0).tolist())
        p1 = set(pa.table_row(1).tolist())
        assert not (p0 & p1)


def test_bench_decode_smoke_gate():
    """tools/bench_decode.py --smoke must pass its own acceptance
    gate: fused masked tick >= 1.5x cheaper per token than the legacy
    tick, identical outputs, zero wasted fused decode rows — and the
    anatomy recorder rung must stay under its per-token overhead
    gate with byte-identical outputs."""
    bench = os.path.join(_REPO_ROOT, 'tools', 'bench_decode.py')
    proc = subprocess.run(
        [sys.executable, bench, '--smoke'],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, 'JAX_PLATFORMS': 'cpu'})
    assert proc.returncode == 0, proc.stdout + proc.stderr
    result = json.loads(proc.stdout.strip().splitlines()[-1])
    assert result['pass'] is True
    assert result['identical_outputs'] is True
    assert result['fast_wasted_steps'] == 0
    assert result['legacy_wasted_steps'] > 0
    assert result['speedup'] >= result['threshold']
    assert result['anatomy_pass'] is True
    assert result['anatomy_identical_outputs'] is True
