"""Hyperbolic provisioner op-set (via the nodepool base).

Behavioral twin of sky/provision/hyperbolic/instance.py. Platform
facts: a marketplace — renting creates an "instance" on some host
node offering the GPU model; terminate-only (no stop/resume), ssh via
a mapped public port on the host, flat placement (no regions — the
catalog uses a single 'marketplace' region). The rented instance name
is server-assigned; our cluster name rides the user-metadata field.
"""
from __future__ import annotations

import re
from typing import Any, Dict, List, Optional

from skypilot_tpu.provision import common
from skypilot_tpu.provision import nodepool
from skypilot_tpu.provision.hyperbolic import rest

_transport_factory = rest.Transport


def set_transport_factory(factory) -> None:
    global _transport_factory
    _transport_factory = factory


class HyperbolicApi(nodepool.NodeApi):
    provider_name = 'hyperbolic'
    ssh_user = 'ubuntu'
    supports_stop = False
    state_map = {
        'starting': 'PENDING',
        'creating': 'PENDING',
        'online': 'RUNNING',
        'running': 'RUNNING',
        'ready': 'RUNNING',
        'terminated': None,
        'failed': None,
        'offline': None,
    }

    def __init__(self) -> None:
        self.t = _transport_factory()

    @staticmethod
    def _row(inst: Dict[str, Any]) -> Dict[str, Any]:
        # Rented instances: {'id', 'instance': {'status', ...},
        # 'sshCommand': 'ssh ubuntu@<host> -p <port>'}
        body = inst.get('instance') or {}
        ssh = inst.get('sshCommand') or ''
        host = None
        if '@' in ssh:
            host = ssh.split('@', 1)[1].split()[0]
        # `-p <port>` as a flag only — a '-p' inside the hostname
        # (gpu-prod-3...) must not be mistaken for it.
        port_match = re.search(r'\s-p\s+(\d+)', ssh)
        return {'id': inst.get('id'),
                'name': (inst.get('userMetadata') or {}).get('name', ''),
                'status': body.get('status', ''),
                'public_ip': host,
                'private_ip': None,
                'ssh_port': int(port_match.group(1))
                if port_match else 22}

    def list_nodes(self) -> List[Dict[str, Any]]:
        reply = self.t.call('GET', '/v1/marketplace/instances')
        return [self._row(i) for i in reply.get('instances', [])]

    def create_node(self, name: str, region: str, zone: Optional[str],
                    node_config: Dict[str, Any]) -> str:
        del region, zone  # marketplace scheduling
        itype = node_config['instance_type']
        # Grammar `<count>x-<MODEL>` (e.g. 8x-H100-SXM).
        count_s, _, model = itype.partition('x-')
        reply = self.t.call('POST', '/v1/marketplace/instances/create', {
            'gpuModel': model,
            'gpuCount': int(count_s),
            'userMetadata': {'name': name},
        })
        return str(reply.get('instanceId') or reply.get('id'))

    def delete_node(self, node_id: str) -> None:
        self.t.call('POST', '/v1/marketplace/instances/terminate',
                    {'id': node_id})

    def classify(self, e: Exception,
                 region: Optional[str] = None) -> Exception:
        if isinstance(e, rest.HyperbolicApiError):
            return rest.classify_error(e, region)
        return e


def _api(provider_config: Dict[str, Any]) -> HyperbolicApi:
    del provider_config
    return HyperbolicApi()


def run_instances(region: str, zone: Optional[str], cluster_name: str,
                  config: common.ProvisionConfig) -> common.ProvisionRecord:
    return nodepool.run_instances(_api(config.provider_config), region,
                                  zone, cluster_name, config)


def wait_instances(region: str, cluster_name: str, state: str,
                   provider_config: Optional[Dict[str, Any]] = None,
                   timeout_s: float = 900.0,
                   poll_interval_s: float = 5.0) -> None:
    del region
    nodepool.wait_instances(_api(provider_config or {}), cluster_name,
                            state, timeout_s, poll_interval_s)


def stop_instances(cluster_name: str,
                   provider_config: Dict[str, Any]) -> None:
    nodepool.stop_instances(_api(provider_config), cluster_name)


def terminate_instances(cluster_name: str,
                        provider_config: Dict[str, Any]) -> None:
    nodepool.terminate_instances(_api(provider_config), cluster_name)


def query_instances(cluster_name: str, provider_config: Dict[str, Any]
                    ) -> Dict[str, Optional[str]]:
    return nodepool.query_instances(_api(provider_config), cluster_name)


def get_cluster_info(region: str, cluster_name: str,
                     provider_config: Dict[str, Any]
                     ) -> common.ClusterInfo:
    del region
    return nodepool.get_cluster_info(_api(provider_config), cluster_name,
                                     provider_config)


def open_ports(cluster_name: str, ports: List[str],
               provider_config: Dict[str, Any]) -> None:
    # Host port mappings are fixed at rent time on the marketplace.
    del cluster_name, ports, provider_config


def cleanup_ports(cluster_name: str,
                  provider_config: Dict[str, Any]) -> None:
    del cluster_name, provider_config
