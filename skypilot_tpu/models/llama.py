"""Llama-family decoder-only transformer, pure JAX, sharding-annotated.

The flagship model of the in-tree compute path — the JAX/MaxText twin of the
reference's recipe-level models (examples/tpu/v6e/train-llama3-8b.yaml runs
PyTorch/XLA Llama-3-8B; llm/ recipes serve Llama with vLLM/SGLang).

Design (TPU-first):
  * Params are a pytree of arrays with a parallel pytree of *logical axis*
    names; `parallel.mesh` maps them to any MeshPlan (fsdp/tp/sp/...).
  * Layers are stacked and scanned (`lax.scan`) — one compiled layer body,
    O(1) compile time in depth.
  * bf16 compute, fp32 RMSNorm/softmax/rope; `jax.checkpoint` per layer
    with dots-saveable policy to trade FLOPs for HBM.
  * GQA (n_kv_heads <= n_heads), RoPE, SwiGLU, untied LM head.
"""
from __future__ import annotations

import dataclasses
import os
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from skypilot_tpu.ops import attention as attention_ops
from skypilot_tpu.ops import decode_attention as decode_ops
from skypilot_tpu.ops import quantization as qops
from skypilot_tpu.parallel import mesh as mesh_lib

Params = Dict[str, Any]

# Sequence-chunk size for the scanned cross-entropy head (see _chunked_ce).
LOSS_CHUNK = 1024


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128_256
    d_model: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    d_ff: int = 14_336
    max_seq_len: int = 8192
    rope_theta: float = 500_000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    remat: bool = True
    # What the per-layer jax.checkpoint keeps for the backward pass:
    #   'qkvo_gup'     — q/k/v/o + mlp gate+up: backward recomputes only
    #                    elementwise ops + the flash-attn forward
    #                    (fastest; most HBM — the batch-1 long-seq pick)
    #   'dots'         — every no-batch-dim matmul output
    #   'qkvo_up'      — q/k/v/o projections + mlp up (recompute gate)
    #   'qkvo'         — q/k/v/o projections only (recompute gate+up)
    #   'none'         — full per-layer rematerialization (least HBM)
    # Long-seq configs on small-HBM chips want 'qkvo_up'/'qkvo' — the
    # saved 'dots' set costs ~770 MB/layer at 16k tokens on a 1B model.
    remat_policy: str = 'dots'
    attention_impl: str = 'auto'
    # Mistral-style sliding-window attention: each token attends to at
    # most this many recent positions. None = full causal attention.
    sliding_window: Optional[int] = None
    # Llama-3.1-style RoPE frequency scaling, as a hashable tuple
    # (factor, low_freq_factor, high_freq_factor, original_ctx) — set
    # by the HF converter when the checkpoint carries
    # rope_scaling={rope_type: 'llama3', ...}. None = unscaled.
    rope_scaling: Optional[Tuple[float, float, float, int]] = None
    # Packed-sequence training: when set to the corpus EOS token id,
    # the training loss derives segment ids from EOS positions inside
    # the jitted step — attention is blocked across document
    # boundaries AND RoPE positions restart at each boundary, so
    # concatenated-document batches train as if each document were
    # alone in the sequence. None = classic GPT-style packing
    # (cross-document attention allowed).
    packing_reset_eos: Optional[int] = None

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    def num_params(self) -> int:
        d, f, v = self.d_model, self.d_ff, self.vocab_size
        hd = self.head_dim
        attn = d * (self.n_heads * hd) * 2 + d * (self.n_kv_heads * hd) * 2
        mlp = 3 * d * f
        per_layer = attn + mlp + 2 * d
        return v * d * 2 + self.n_layers * per_layer + d

    def train_flops_per_token(self) -> float:
        """~6N + attention flops (per token, fwd+bwd)."""
        attn_flops = 12 * self.n_layers * self.d_model * self.max_seq_len
        return 6 * self.num_params() + attn_flops


# Canonical configs (sizes match public Llama-3 family).
LLAMA3_8B = LlamaConfig()
LLAMA3_70B = LlamaConfig(d_model=8192, n_layers=80, n_heads=64, n_kv_heads=8,
                         d_ff=28_672)
LLAMA3_1B = LlamaConfig(vocab_size=32_768, d_model=2048, n_layers=16,
                        n_heads=16, n_kv_heads=8, d_ff=8192,
                        max_seq_len=8192)
LLAMA_TINY = LlamaConfig(vocab_size=256, d_model=64, n_layers=2, n_heads=4,
                         n_kv_heads=2, d_ff=128, max_seq_len=128,
                         remat=False)
# Mistral-7B: the Llama architecture + a 4096-token sliding window
# (public config). The flash kernels skip out-of-window blocks, so long
# contexts run in O(S·W).
MISTRAL_7B = LlamaConfig(vocab_size=32_000, d_model=4096, n_layers=32,
                         n_heads=32, n_kv_heads=8, d_ff=14_336,
                         max_seq_len=32_768, rope_theta=10_000.0,
                         sliding_window=4096)
MISTRAL_TINY = LlamaConfig(vocab_size=256, d_model=64, n_layers=2,
                           n_heads=4, n_kv_heads=2, d_ff=128,
                           max_seq_len=128, remat=False,
                           sliding_window=8)

CONFIGS = {
    'llama3-8b': LLAMA3_8B,
    'llama3-70b': LLAMA3_70B,
    'llama3-1b': LLAMA3_1B,
    'mistral-7b': MISTRAL_7B,
    'mistral-tiny': MISTRAL_TINY,
    'tiny': LLAMA_TINY,
}


def logical_axes(config: LlamaConfig) -> Params:
    """Logical sharding axes pytree, mirroring init() structure."""
    del config
    layer = {
        'wq': ('layers', 'embed', 'heads'),
        'wk': ('layers', 'embed', 'kv'),
        'wv': ('layers', 'embed', 'kv'),
        'wo': ('layers', 'heads', 'embed'),
        'w_gate': ('layers', 'embed', 'mlp'),
        'w_up': ('layers', 'embed', 'mlp'),
        'w_down': ('layers', 'mlp', 'embed'),
        'attn_norm': ('layers', 'embed'),
        'mlp_norm': ('layers', 'embed'),
    }
    return {
        'embed': ('vocab', 'embed'),
        'layers': layer,
        'final_norm': ('embed',),
        'lm_head': ('embed', 'vocab'),
    }


def init(config: LlamaConfig, key: jax.Array) -> Params:
    """Initialize parameters (truncated-normal fan-in scaling)."""
    c = config
    hd = c.head_dim
    keys = jax.random.split(key, 8)

    def dense(k, shape, fan_in):
        return (jax.random.truncated_normal(k, -2, 2, shape, jnp.float32) *
                (fan_in ** -0.5)).astype(c.dtype)

    def stack(k, shape, fan_in):
        return dense(k, (c.n_layers,) + shape, fan_in)

    params: Params = {
        'embed': dense(keys[0], (c.vocab_size, c.d_model), c.d_model),
        'layers': {
            'wq': stack(keys[1], (c.d_model, c.n_heads * hd), c.d_model),
            'wk': stack(keys[2], (c.d_model, c.n_kv_heads * hd), c.d_model),
            'wv': stack(keys[3], (c.d_model, c.n_kv_heads * hd), c.d_model),
            'wo': stack(keys[4], (c.n_heads * hd, c.d_model),
                        c.n_heads * hd),
            'w_gate': stack(keys[5], (c.d_model, c.d_ff), c.d_model),
            'w_up': stack(keys[6], (c.d_model, c.d_ff), c.d_model),
            'w_down': stack(keys[7], (c.d_ff, c.d_model), c.d_ff),
            'attn_norm': jnp.ones((c.n_layers, c.d_model), c.dtype),
            'mlp_norm': jnp.ones((c.n_layers, c.d_model), c.dtype),
        },
        'final_norm': jnp.ones((c.d_model,), c.dtype),
        'lm_head': dense(keys[0], (c.d_model, c.vocab_size), c.d_model),
    }
    return params


def _ckpt_name(x: jax.Array, name: str) -> jax.Array:
    """Tag an intermediate for name-based remat policies (no-op otherwise)."""
    from jax.ad_checkpoint import checkpoint_name
    return checkpoint_name(x, name)


_REMAT_SAVE_NAMES = {
    'qkvo': ('attn_q', 'attn_k', 'attn_v', 'attn_o'),
    'qkvo_up': ('attn_q', 'attn_k', 'attn_v', 'attn_o', 'mlp_up'),
    # Save every big matmul output: the backward then recomputes only
    # elementwise ops (norm/rope/silu) and the flash-attention forward
    # (its custom_vjp re-runs for residuals regardless). Costs the most
    # HBM per token — the batch-1 long-sequence sweet spot.
    'qkvo_gup': ('attn_q', 'attn_k', 'attn_v', 'attn_o', 'mlp_gate',
                 'mlp_up'),
}


def _remat_policy(config: LlamaConfig):
    """Map config.remat_policy to a jax.checkpoint policy callable."""
    p = config.remat_policy
    if p == 'none':
        return jax.checkpoint_policies.nothing_saveable
    if p in _REMAT_SAVE_NAMES:
        return jax.checkpoint_policies.save_only_these_names(
            *_REMAT_SAVE_NAMES[p])
    if p != 'dots':
        raise ValueError(
            f'Unknown remat_policy {p!r}; expected one of: dots, none, '
            f'{", ".join(sorted(_REMAT_SAVE_NAMES))}.')
    return jax.checkpoint_policies.dots_with_no_batch_dims_saveable


def _embed_lookup(table: jax.Array, tokens: jax.Array,
                  mesh: Optional[mesh_lib.Mesh]) -> jax.Array:
    """Token-embedding gather that stays SPMD-friendly.

    The stored table is sharded ('vocab'→tensor, 'embed'→fsdp); gathering
    straight from it makes XLA derive the output sharding from the table's
    *embed* dim and then reshard to the batch-sharded activation layout
    via involuntary full rematerialization. Constraining the lookup copy
    to ('vocab', None) — vocab stays tensor-sharded, embed un-sharded —
    keeps at most 1/tp of the table resident per device (the transient
    embed-dim all-gather is the same weight traffic ZeRO-3 pays for every
    layer) while letting the gather output inherit the *index* sharding
    (batch, seq): no activation reshard, and the backward scatter lands on
    an embed-replicated operand followed by a cheap reduce instead of a
    sharded scatter-add.
    """
    if mesh is None:
        return qops.embed_rows(table, tokens)
    if isinstance(table, qops.QuantizedTensor):
        tbl = qops.QuantizedTensor(
            mesh_lib.shard_logical(table.q, mesh, ('vocab', None)),
            mesh_lib.shard_logical(table.scale, mesh, ('vocab',)),
            table.axis)
    else:
        tbl = mesh_lib.shard_logical(table, mesh, ('vocab', None))
    idx = mesh_lib.shard_logical(tokens, mesh,
                                 ('batch', 'activation_length'))
    return qops.embed_rows(tbl, idx)


def _token_nll(logits: jax.Array, targets: jax.Array) -> jax.Array:
    """Per-token next-token NLL without a vocab-dim gather.

    `take_along_axis` on vocab-sharded (tensor-parallel) logits lowers to
    a gather whose backward is a sharded scatter; the one-hot dot fuses
    into an elementwise multiply + reduction that SPMD partitions cleanly
    (local partial sum + psum over the tensor axis).
    """
    logz = jax.scipy.special.logsumexp(logits, axis=-1)
    onehot = jax.nn.one_hot(targets, logits.shape[-1], dtype=logits.dtype)
    tgt = jnp.sum(logits * onehot, axis=-1)
    return logz - tgt


def _chunked_ce(x: jax.Array, lm_head: jax.Array, targets: jax.Array,
                loss_mask: Optional[jax.Array], chunk: int) -> jax.Array:
    """Mean CE with the lm_head projection scanned over sequence chunks.

    fp32 logits for a full [B, S, vocab] batch dominate HBM at long seq
    (B2·S8192·V32768·4B ≈ 2.1 GiB, doubled in the backward). Scanning a
    checkpointed chunk body materializes only [B, chunk, vocab] at a time
    and recomputes each chunk's logits during the backward — the standard
    large-vocab CE pattern on TPU.
    """
    b, s, d = x.shape
    if s <= chunk:
        logits = jnp.einsum('bsd,dv->bsv', x, lm_head,
                            preferred_element_type=jnp.float32)
        nll = _token_nll(logits, targets)
        if loss_mask is not None:
            return jnp.sum(nll * loss_mask) / jnp.maximum(
                jnp.sum(loss_mask), 1.0)
        return jnp.mean(nll)

    if loss_mask is None:
        loss_mask = jnp.ones((b, s), jnp.float32)
    pad = (-s) % chunk
    if pad:
        x = jnp.pad(x, ((0, 0), (0, pad), (0, 0)))
        targets = jnp.pad(targets, ((0, 0), (0, pad)))
        loss_mask = jnp.pad(loss_mask, ((0, 0), (0, pad)))
        s += pad
    n = s // chunk
    xs = x.reshape(b, n, chunk, d).transpose(1, 0, 2, 3)
    ts = targets.reshape(b, n, chunk).transpose(1, 0, 2)
    ms = loss_mask.reshape(b, n, chunk).transpose(1, 0, 2).astype(
        jnp.float32)

    def body(carry, xt):
        xc, tc, mc = xt
        logits = jnp.einsum('bsd,dv->bsv', xc, lm_head,
                            preferred_element_type=jnp.float32)
        nll = _token_nll(logits, tc)
        tot, cnt = carry
        return (tot + jnp.sum(nll * mc), cnt + jnp.sum(mc)), None

    (tot, cnt), _ = jax.lax.scan(jax.checkpoint(body), (0.0, 0.0),
                                 (xs, ts, ms))
    return tot / jnp.maximum(cnt, 1.0)


def _rms_norm(x: jax.Array, scale: jax.Array, eps: float) -> jax.Array:
    x32 = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    return (x32 * jax.lax.rsqrt(var + eps)).astype(x.dtype) * scale


def _rope(x: jax.Array, positions: jax.Array, theta: float,
          scaling=None) -> jax.Array:
    """Rotary embeddings; x [B, S, H, D], positions [B, S].

    `scaling` = (factor, low_freq_factor, high_freq_factor, orig_ctx)
    applies Llama-3.1's piecewise frequency remap: wavelengths beyond
    orig_ctx/low divide by `factor`, those under orig_ctx/high stay
    raw, and the band between interpolates smoothly — matching HF's
    rope_type='llama3' exactly (converted 3.1 checkpoints depend on
    it; unscaled frequencies would silently change attention).
    """
    d = x.shape[-1]
    freqs = theta ** (-jnp.arange(0, d, 2, dtype=jnp.float32) / d)
    if scaling is not None:
        factor, low_f, high_f, orig_ctx = scaling
        wavelen = 2.0 * jnp.pi / freqs
        low_wl = orig_ctx / low_f
        high_wl = orig_ctx / high_f
        smooth = jnp.clip((orig_ctx / wavelen - low_f) /
                          (high_f - low_f), 0.0, 1.0)
        mid = (1.0 - smooth) * freqs / factor + smooth * freqs
        freqs = jnp.where(wavelen > low_wl, freqs / factor,
                          jnp.where(wavelen < high_wl, freqs, mid))
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B,S,D/2]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin],
                          axis=-1)
    return out.astype(x.dtype)


def segments_from_eos(tokens: jax.Array, eos: int
                      ) -> Tuple[jax.Array, jax.Array]:
    """Derive (segment_ids, positions) [B, S] from EOS boundaries.

    A new segment starts at index 0 and right after every EOS token
    (the EOS itself closes its document). Positions restart at 0 per
    segment (RoPE sees per-document offsets). All cumulative ops — a
    prefix sum and a prefix max — lower to O(log S) XLA scans; nothing
    here is data-dependent control flow.
    """
    is_start = jnp.concatenate(
        [jnp.ones_like(tokens[:, :1], jnp.bool_),
         tokens[:, :-1] == eos], axis=1)
    segment_ids = jnp.cumsum(is_start.astype(jnp.int32), axis=1)
    idx = jnp.broadcast_to(jnp.arange(tokens.shape[1])[None, :],
                           tokens.shape)
    seg_start = jax.lax.associative_scan(
        jax.numpy.maximum, jnp.where(is_start, idx, 0), axis=1)
    return segment_ids, idx - seg_start


def positions_and_segments(config, tokens: jax.Array, serving: bool
                           ) -> Tuple[Optional[jax.Array], jax.Array]:
    """Default (segment_ids, positions) for a trunk given no explicit
    positions. Training trunks with `packing_reset_eos` set get
    EOS-derived document segments + per-document positions; serving
    trunks (one document per slot) and unpacked training get plain
    arange and no segments. One helper shared by all four families —
    per-family copies of this branch were already drifting."""
    if config.packing_reset_eos is not None and not serving:
        return segments_from_eos(tokens, config.packing_reset_eos)
    return None, jnp.broadcast_to(
        jnp.arange(tokens.shape[1])[None, :], tokens.shape)


def quantize_kv(x: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Per-(position, head) int8 symmetric quantization over head_dim.

    → (int8 values, fp32 scale with a trailing 1-dim). Halves KV-cache
    HBM vs bf16; the dequant multiply fuses into the attention reads.
    """
    scale = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1,
                    keepdims=True) / 127.0
    scale = jnp.maximum(scale, 1e-8)
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale), -127, 127)
    return q.astype(jnp.int8), scale


def dequantize_kv(q: jax.Array, scale: jax.Array,
                  dtype) -> jax.Array:
    return (q.astype(jnp.float32) * scale).astype(dtype)


def write_cache_slots(cache_entry, values: jax.Array,
                      slots: jax.Array) -> Any:
    """Write full K (or V) prefixes into cache slots.

    cache_entry: [L, n_slots, len, KVH, HD] array, or the quantized
    (int8, scale) pair; values: [L, B, len, KVH, HD] scattered into
    slots [B]. Owns the quantized representation together with
    slot_cache_attend so the engine never touches the layout.
    Out-of-range slot indices are dropped (JAX scatter semantics) —
    the batched-prefill pad rows rely on that.
    """
    if isinstance(cache_entry, (tuple, list)):
        data, scale = cache_entry
        q_vals, q_scale = quantize_kv(values)
        return (data.at[:, slots].set(q_vals),
                scale.at[:, slots].set(q_scale))
    return cache_entry.at[:, slots].set(
        values.astype(cache_entry.dtype))


def last_token_hidden(x: jax.Array, true_len) -> jax.Array:
    """x [B, S, D] → [B, D] rows at position true_len-1.

    true_len: scalar (shared) or [B] (per-row — the batched-prefill
    path, where every row has its own prompt length).
    """
    idx = jnp.broadcast_to(jnp.asarray(true_len).reshape(-1),
                           (x.shape[0],))
    return jnp.take_along_axis(x, (idx - 1)[:, None, None],
                               axis=1)[:, 0]


def slot_cache_attend(q: jax.Array, k: jax.Array, v: jax.Array,
                      kv_cache, cache_index=None, cache_positions=None,
                      window=None, mesh=None, logit_softcap=None,
                      scale=None):
    """Write this step's K/V into the slot cache and attend over it.

    The decode-path cache contract shared by every family (llama, qwen,
    gemma, moe): with ``cache_positions`` [B] each slot writes at its
    own length (continuous batching); with scalar ``cache_index`` the
    whole batch appends at one offset (shared-prefix prefill insert).

    Cache entries may be plain arrays, or ``(int8_values, fp32_scale)``
    pairs (EngineConfig.kv_dtype = int8): new rows are quantized on
    write and the whole cache dequantizes into the attention reads.
    Families pass the entries through opaquely, so the quantization
    scheme lives entirely here. Returns (attn, (new_k, new_v)) with the
    same representation that came in.
    """
    b, s = q.shape[0], q.shape[1]
    ck, cv = kv_cache
    quantized = isinstance(ck, (tuple, list))
    if quantized:
        ck, ck_scale = ck
        cv, cv_scale = cv
        k_write, k_scale_write = quantize_kv(k)
        v_write, v_scale_write = quantize_kv(v)
    else:
        k_write, v_write = k, v
    if cache_positions is not None and cache_positions.ndim == 2:
        # Multi-token per-slot write [B, S] (speculative verify: each
        # slot scores S proposed tokens at its own offsets in one pass).
        slots = jnp.arange(b)[:, None]
        # Explicit cast: a mixed-dtype scatter (f32 model into a bf16
        # cache) is a FutureWarning today and an error in future JAX.
        ck = ck.at[slots, cache_positions].set(
            k_write.astype(ck.dtype))
        cv = cv.at[slots, cache_positions].set(
            v_write.astype(cv.dtype))
        if quantized:
            ck_scale = ck_scale.at[slots, cache_positions].set(
                k_scale_write)
            cv_scale = cv_scale.at[slots, cache_positions].set(
                v_scale_write)
        q_pos = cache_positions                         # [b, s]
    elif cache_positions is not None:
        slots = jnp.arange(b)
        ck = ck.at[slots, cache_positions].set(
            k_write[:, 0].astype(ck.dtype))
        cv = cv.at[slots, cache_positions].set(
            v_write[:, 0].astype(cv.dtype))
        if quantized:
            ck_scale = ck_scale.at[slots, cache_positions].set(
                k_scale_write[:, 0])
            cv_scale = cv_scale.at[slots, cache_positions].set(
                v_scale_write[:, 0])
        q_pos = cache_positions[:, None]                # [b, 1]
    else:
        ck = jax.lax.dynamic_update_slice_in_dim(
            ck, k_write.astype(ck.dtype), cache_index, axis=1)
        cv = jax.lax.dynamic_update_slice_in_dim(
            cv, v_write.astype(cv.dtype), cache_index, axis=1)
        if quantized:
            ck_scale = jax.lax.dynamic_update_slice_in_dim(
                ck_scale, k_scale_write, cache_index, axis=1)
            cv_scale = jax.lax.dynamic_update_slice_in_dim(
                cv_scale, v_scale_write, cache_index, axis=1)
        q_pos = cache_index + jnp.arange(s)[None, :]    # [1, s]
    if quantized:
        new_cache = ((ck, ck_scale), (cv, cv_scale))
        cache_k: Any = (ck, ck_scale)
        cache_v: Any = (cv, cv_scale)
    else:
        new_cache = (ck, cv)
        cache_k, cache_v = ck, cv

    if (cache_positions is not None and s == 1
            and cache_positions.ndim == 1
            and ck.shape[1] % min(decode_ops.DEFAULT_BLOCK_KV,
                                  ck.shape[1]) == 0
            and (mesh is None or decode_ops.shardable_on(
                mesh, b, ck.shape[2]))
            and os.environ.get('XSKY_DECODE_ATTN') != 'xla'):
        # The serving hot path: Pallas kernel reads only each slot's
        # live blocks (per-slot length bound via scalar prefetch) and
        # dequantizes int8 entries in VMEM — the padded-cache XLA path
        # below reads max_len rows per slot regardless of true length.
        # Gemma-2's softcap/scale apply in-kernel.
        attn = decode_ops.decode_attention(
            q, cache_k, cache_v, lengths=cache_positions + 1,
            window=window, mesh=mesh, logit_softcap=logit_softcap,
            scale=scale)
        return attn, new_cache

    # Per-QUERY validity (a multi-token step's earlier rows must not
    # see later rows, and each row carries its own window).
    kv_pos = jnp.arange(ck.shape[1])[None, None, :]     # [1, 1, K]
    valid = kv_pos <= q_pos[..., None]
    if window is not None:
        # Sliding window: only the W most recent rows are live per query.
        valid = valid & (kv_pos > q_pos[..., None] - window)
    if quantized:
        k_full = dequantize_kv(ck, ck_scale, q.dtype)
        v_full = dequantize_kv(cv, cv_scale, q.dtype)
    else:
        k_full, v_full = ck, cv
    attn = attention_ops.xla_attention_with_mask(
        q, k_full, v_full, valid[:, None],
        logit_softcap=logit_softcap, scale=scale)
    return attn, new_cache


def paged_cache_attend(q: jax.Array, k: jax.Array, v: jax.Array,
                       kv_cache, cache_positions: jax.Array,
                       block_tables: jax.Array, window=None,
                       logit_softcap=None, scale=None):
    """Paged-cache twin of the decode branch of slot_cache_attend.

    kv_cache: (k_pages, v_pages), each [P, page, KVH, HD] shared page
    arenas; block_tables [B, nblk] maps each slot's logical KV blocks
    to physical pages (sentinel == P beyond the reservation);
    cache_positions [B] is the write position per slot — the engine
    points finished/inactive slots past the table (positions >=
    nblk*page), which resolves to the sentinel page here so their
    writes are DROPPED by JAX scatter semantics. s must be 1 (paged
    serving is decode-only; prefill inserts go through the engine's
    reshape-scatter path). Returns (attn, (new_k_pages, new_v_pages)).
    """
    b, s = q.shape[0], q.shape[1]
    if s != 1:
        raise NotImplementedError('paged_cache_attend is single-token')
    if window is not None:
        raise NotImplementedError(
            'sliding_window is not supported with the paged KV cache')
    ck, cv = kv_cache
    if isinstance(ck, (tuple, list)):
        raise NotImplementedError(
            'int8 KV is not supported with the paged KV cache')
    num_pages, page = ck.shape[0], ck.shape[1]
    nblk = block_tables.shape[1]
    pos = cache_positions.astype(jnp.int32)
    blk = pos // page
    off = pos % page
    # Route the write through the block table; a position past the
    # table (inactive slot) or a sentinel table entry both resolve to
    # page index P, whose scatter is dropped.
    page_idx = jnp.where(
        blk < nblk,
        jnp.take_along_axis(block_tables,
                            jnp.minimum(blk, nblk - 1)[:, None],
                            axis=1)[:, 0],
        num_pages)
    ck = ck.at[page_idx, off].set(k[:, 0].astype(ck.dtype))
    cv = cv.at[page_idx, off].set(v[:, 0].astype(cv.dtype))
    new_cache = (ck, cv)

    if os.environ.get('XSKY_DECODE_ATTN') != 'xla':
        attn = decode_ops.paged_decode_attention(
            q, ck, cv, lengths=pos + 1, block_tables=block_tables,
            logit_softcap=logit_softcap, scale=scale)
        return attn, new_cache

    # XLA reference path: gather each slot's pages into a dense [B, K]
    # view and reuse the masked-attention reference. Sentinel entries
    # clamp to an arbitrary live page; the q_pos bound masks them
    # (every sentinel block sits past the slot's reservation, hence
    # past its length).
    safe = jnp.clip(block_tables, 0, num_pages - 1)
    k_full = ck[safe].reshape(b, nblk * page, *ck.shape[2:])
    v_full = cv[safe].reshape(b, nblk * page, *cv.shape[2:])
    kv_pos = jnp.arange(nblk * page)[None, None, :]
    valid = kv_pos <= pos[:, None, None]
    attn = attention_ops.xla_attention_with_mask(
        q, k_full, v_full, valid[:, None],
        logit_softcap=logit_softcap, scale=scale)
    return attn, new_cache


def _layer(config: LlamaConfig, mesh: Optional[mesh_lib.Mesh],
           x: jax.Array, layer_params: Params, positions: jax.Array,
           kv_cache: Optional[Tuple[jax.Array, jax.Array]] = None,
           cache_index: Optional[jax.Array] = None,
           cache_positions: Optional[jax.Array] = None,
           return_kv: bool = False,
           segment_ids: Optional[jax.Array] = None,
           block_tables: Optional[jax.Array] = None):
    """One transformer block. Returns (x, new_kv_cache).

    Decode: with kv_cache set, the new K/V (s==1) is written either at a
    shared ``cache_index`` (scalar) or per-slot ``cache_positions`` [B]
    (continuous batching: every slot sits at its own length).
    """
    c = config
    hd = c.head_dim
    b, s, _ = x.shape

    def shard(arr, axes):
        if mesh is None:
            return arr
        return mesh_lib.shard_logical(arr, mesh, axes)

    h = _rms_norm(x, layer_params['attn_norm'], c.norm_eps)
    q = _ckpt_name(qops.matmul(h, layer_params['wq']), 'attn_q').reshape(
        b, s, c.n_heads, hd)
    k = _ckpt_name(qops.matmul(h, layer_params['wk']), 'attn_k').reshape(
        b, s, c.n_kv_heads, hd)
    v = _ckpt_name(qops.matmul(h, layer_params['wv']), 'attn_v').reshape(
        b, s, c.n_kv_heads, hd)
    q = shard(q, ('batch', 'activation_length', 'activation_heads', None))
    k = shard(k, ('batch', 'activation_length', 'activation_kv', None))
    q = _rope(q, positions, c.rope_theta, c.rope_scaling)
    k = _rope(k, positions, c.rope_theta, c.rope_scaling)

    if kv_cache is not None and block_tables is not None:
        attn, new_cache = paged_cache_attend(
            q, k, v, kv_cache, cache_positions=cache_positions,
            block_tables=block_tables, window=c.sliding_window)
    elif kv_cache is not None:
        attn, new_cache = slot_cache_attend(
            q, k, v, kv_cache, cache_index=cache_index,
            cache_positions=cache_positions, window=c.sliding_window,
            mesh=mesh)
    elif c.attention_impl in ('ring', 'ulysses') and mesh is not None:
        # Context parallelism: sequence stays sharded through attention
        # (K/V ring over ICI neighbors or all-to-all head scatter).
        if c.sliding_window is not None:
            raise NotImplementedError(
                'sliding_window is not implemented for ring/ulysses '
                'context parallelism (a windowed model rarely needs '
                'sequence sharding: its attention is already O(S·W)).')
        if segment_ids is not None:
            raise NotImplementedError(
                'packing_reset_eos is not implemented for ring/ulysses '
                'context parallelism (segment masks would have to ride '
                'the K/V ring).')
        from skypilot_tpu.ops import ring_attention as ring_ops
        new_cache = (k, v) if return_kv else None
        attn = ring_ops.sequence_parallel_attention(
            q, k, v, mesh, implementation=c.attention_impl, causal=True)
    else:
        new_cache = (k, v) if return_kv else None
        attn = attention_ops.dot_product_attention(
            q, k, v, causal=True, implementation=c.attention_impl,
            window=c.sliding_window, segment_ids=segment_ids)

    attn = attn.reshape(b, s, c.n_heads * hd)
    x = x + shard(_ckpt_name(qops.matmul(attn, layer_params['wo']),
                             'attn_o'),
                  ('batch', 'activation_length', 'activation_embed'))

    h = _rms_norm(x, layer_params['mlp_norm'], c.norm_eps)
    gate = jax.nn.silu(
        _ckpt_name(qops.matmul(h, layer_params['w_gate']),
                   'mlp_gate').astype(jnp.float32))
    up = _ckpt_name(qops.matmul(h, layer_params['w_up']),
                    'mlp_up').astype(jnp.float32)
    ff = shard((gate * up).astype(c.dtype),
               ('batch', 'activation_length', 'activation_mlp'))
    x = x + shard(qops.matmul(ff, layer_params['w_down']),
                  ('batch', 'activation_length', 'activation_embed'))
    return x, new_cache


def _trunk(config: LlamaConfig,
           params: Params,
           tokens: jax.Array,
           positions: Optional[jax.Array],
           mesh: Optional[mesh_lib.Mesh],
           return_kv: bool,
           segment_ids: Optional[jax.Array] = None):
    """Embed → scanned layers → final RMSNorm. Returns (x [B,S,D], kv)."""
    c = config
    if positions is None:
        segment_ids, positions = positions_and_segments(
            c, tokens, serving=return_kv)
    x = _embed_lookup(params['embed'], tokens, mesh).astype(c.dtype)
    if mesh is not None:
        x = mesh_lib.shard_logical(
            x, mesh, ('batch', 'activation_length', 'activation_embed'))

    def layer_fn(x, lp):
        x, kv = _layer(c, mesh, x, lp, positions, return_kv=return_kv,
                       segment_ids=segment_ids)
        return x, ({'k': kv[0], 'v': kv[1]} if return_kv else None)

    if c.remat and not return_kv:
        layer_fn = jax.checkpoint(layer_fn, policy=_remat_policy(c))
    x, kv = jax.lax.scan(layer_fn, x, params['layers'])
    return _rms_norm(x, params['final_norm'], c.norm_eps), kv


def forward(config: LlamaConfig,
            params: Params,
            tokens: jax.Array,
            mesh: Optional[mesh_lib.Mesh] = None,
            positions: Optional[jax.Array] = None,
            return_kv: bool = False):
    """Training/prefill forward pass → logits [B, S, vocab] (fp32).

    With return_kv=True also returns per-layer K/V for the decode cache
    ({'k','v': [L,B,S,KVH,HD]}) — the serving prefill stage (JetStream
    twin; BASELINE: examples/tpu/v6e/README.md:119-121).
    """
    x, kv = _trunk(config, params, tokens, positions, mesh, return_kv)
    logits = qops.matmul(x, params['lm_head'],
                         preferred_element_type=jnp.float32)
    return (logits, kv) if return_kv else logits


def lm_logits(config: LlamaConfig, params: Params,
              hidden: jax.Array) -> jax.Array:
    """Untied LM head; hidden [..., D] -> fp32 logits [..., V]."""
    del config
    return qops.matmul(hidden, params['lm_head'],
                       preferred_element_type=jnp.float32)


def prefill_hidden(config: LlamaConfig,
                   params: Params,
                   tokens: jax.Array,
                   true_len: jax.Array,
                   mesh: Optional[mesh_lib.Mesh] = None
                   ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Prefill trunk returning only the hidden state at true_len-1.

    → (last_hidden [B, D] in model dtype, per-layer KV). The caller does
    the single-row lm_head projection — avoids materializing fp32 logits
    for the whole padded prefill bucket. true_len may be scalar or [B]
    (batched prefill: one padded bucket, per-row prompt lengths).
    """
    x, kv = _trunk(config, params, tokens, None, mesh, return_kv=True)
    return last_token_hidden(x, true_len), kv


def decode_forward(config: LlamaConfig,
                   params: Params,
                   last_tokens: jax.Array,
                   positions: jax.Array,
                   kv: Dict[str, jax.Array],
                   mesh: Optional[mesh_lib.Mesh] = None
                   ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """One decode step for a batch of slots.

    last_tokens [B], positions [B] (index each new token lands at),
    kv {'k','v': [L,B,MAX_LEN,KVH,HD]}. Returns (logits [B,V], new kv).
    The layer scan carries x and threads each layer's cache through as
    scan xs/ys — one compiled layer body, O(1) compile time in depth.
    """
    c = config
    x = qops.embed_rows(params['embed'],
                        last_tokens[:, None]).astype(c.dtype)  # [B,1,D]
    pos = positions[:, None]                                    # [B,1]

    def layer_fn(x, scanned):
        lp, ck, cv = scanned
        x, new_cache = _layer(c, mesh, x, lp, pos,
                              kv_cache=(ck, cv),
                              cache_index=None,
                              cache_positions=positions)
        return x, {'k': new_cache[0], 'v': new_cache[1]}

    x, new_kv = jax.lax.scan(layer_fn, x, (params['layers'],
                                           kv['k'], kv['v']))
    x = _rms_norm(x, params['final_norm'], c.norm_eps)
    logits = qops.matmul(x, params['lm_head'],
                         preferred_element_type=jnp.float32)
    return logits[:, 0], new_kv


def paged_decode_forward(config: LlamaConfig,
                         params: Params,
                         last_tokens: jax.Array,
                         positions: jax.Array,
                         kv: Dict[str, jax.Array],
                         block_tables: jax.Array,
                         mesh: Optional[mesh_lib.Mesh] = None
                         ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """decode_forward over the paged cache.

    kv {'k','v': [L, P, page, KVH, HD]} page arenas; block_tables
    [B, nblk] physical page per logical block, shared by every layer
    (loop-invariant — closed over by the scan body, not threaded).
    positions [B] is each slot's write position; the engine points
    inactive slots past the table so their KV writes drop on-device.
    """
    if mesh is not None:
        raise NotImplementedError(
            'mesh sharding is not supported with the paged KV cache')
    c = config
    x = qops.embed_rows(params['embed'],
                        last_tokens[:, None]).astype(c.dtype)  # [B,1,D]
    pos = positions[:, None]                                    # [B,1]

    def layer_fn(x, scanned):
        lp, ck, cv = scanned
        x, new_cache = _layer(c, None, x, lp, pos,
                              kv_cache=(ck, cv),
                              cache_index=None,
                              cache_positions=positions,
                              block_tables=block_tables)
        return x, {'k': new_cache[0], 'v': new_cache[1]}

    x, new_kv = jax.lax.scan(layer_fn, x, (params['layers'],
                                           kv['k'], kv['v']))
    x = _rms_norm(x, params['final_norm'], c.norm_eps)
    logits = qops.matmul(x, params['lm_head'],
                         preferred_element_type=jnp.float32)
    return logits[:, 0], new_kv


def verify_forward(config: LlamaConfig,
                   params: Params,
                   tokens: jax.Array,
                   positions: jax.Array,
                   kv: Dict[str, jax.Array],
                   mesh: Optional[mesh_lib.Mesh] = None
                   ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Multi-token decode for speculative verification.

    tokens [B, S] (the last accepted token followed by S-1 draft
    proposals), positions [B, S] (each slot writes at its own offsets),
    kv as in decode_forward. Returns (logits [B, S, V], new kv): logits
    at step i score the token FOLLOWING tokens[:, i], so one pass
    yields every accept/reject decision plus the bonus token. The
    weights are read once for S tokens — on a bandwidth-bound decode
    that is the whole point of speculation.
    """
    c = config
    x = qops.embed_rows(params['embed'], tokens).astype(c.dtype)  # [B,S,D]

    def layer_fn(x, scanned):
        lp, ck, cv = scanned
        x, new_cache = _layer(c, mesh, x, lp, positions,
                              kv_cache=(ck, cv),
                              cache_index=None,
                              cache_positions=positions)
        return x, {'k': new_cache[0], 'v': new_cache[1]}

    x, new_kv = jax.lax.scan(layer_fn, x, (params['layers'],
                                           kv['k'], kv['v']))
    x = _rms_norm(x, params['final_norm'], c.norm_eps)
    logits = qops.matmul(x, params['lm_head'],
                         preferred_element_type=jnp.float32)
    return logits, new_kv


def pipelined_loss_fn(config: LlamaConfig,
                      params: Params,
                      tokens: jax.Array,
                      targets: jax.Array,
                      mesh: mesh_lib.Mesh,
                      n_microbatches: int,
                      loss_mask: Optional[jax.Array] = None) -> jax.Array:
    """loss_fn with the layer stack pipelined over the 'stage' mesh axis.

    Embed / final-norm / lm_head / CE run as ordinary SPMD outside the
    pipeline region; only the scanned layer block runs under the GPipe
    schedule (parallel.pipeline). Params must be sharded with
    mesh.PIPELINE_RULES ('layers' → 'stage').
    """
    from skypilot_tpu.parallel import pipeline as pipeline_lib
    c = config
    x = _embed_lookup(params['embed'], tokens, mesh).astype(c.dtype)

    def one_layer(x_mb: jax.Array, lp: Params) -> jax.Array:
        b, s, _ = x_mb.shape
        pos = jnp.broadcast_to(jnp.arange(s)[None, :], (b, s))
        # mesh=None: inside the stage-manual region sharding hints are
        # owned by the auto axes; XLA keeps batch/tensor layouts.
        y, _ = _layer(c, None, x_mb, lp, pos)
        return y

    x = pipeline_lib.pipeline_apply(one_layer, params['layers'], x, mesh,
                                    n_microbatches, remat=c.remat)
    x = _rms_norm(x, params['final_norm'], c.norm_eps)
    return _chunked_ce(x, params['lm_head'], targets, loss_mask,
                       chunk=LOSS_CHUNK)


def loss_fn(config: LlamaConfig,
            params: Params,
            tokens: jax.Array,
            targets: jax.Array,
            mesh: Optional[mesh_lib.Mesh] = None,
            loss_mask: Optional[jax.Array] = None) -> jax.Array:
    """Mean next-token cross-entropy (fp32)."""
    x, _ = _trunk(config, params, tokens, None, mesh, return_kv=False)
    return _chunked_ce(x, params['lm_head'], targets, loss_mask,
                       chunk=LOSS_CHUNK)
