"""Continuous-batching orchestrator over the slot engine.

Host-side scheduler (JetStream-style): a queue of requests feeds free
slots via prefill+insert; one jitted decode step advances all active
slots together. Device work stays dense and static-shaped; all dynamic
bookkeeping (EOS, budgets, queues) lives host-side.
"""
from __future__ import annotations

import dataclasses
import os
import queue
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from skypilot_tpu import sky_logging
from skypilot_tpu.agent import profiler
from skypilot_tpu.infer import engine as engine_lib
from skypilot_tpu.infer import sampling as sampling_lib
from skypilot_tpu.utils import chaos

logger = sky_logging.init_logger(__name__)


# Fixed device-side top-k for logprobs-requesting batches: one extra
# compiled decode variant total (per-request k is sliced host-side),
# matching the OpenAI completions cap.
LOGPROBS_K = 5


@dataclasses.dataclass
class Request:
    prompt_tokens: List[int]
    max_new_tokens: int = 128
    eos_token_id: Optional[int] = None
    temperature: float = 0.0
    top_k: int = 0               # 0 → disabled
    top_p: float = 1.0           # 1 → disabled
    # 0 = off; 1..LOGPROBS_K = record each generated token's logprob
    # plus that many top alternatives per step:
    logprobs: int = 0
    # OpenAI repetition penalties over this request's GENERATED tokens
    # (0 = off): presence subtracts once per seen token, frequency per
    # occurrence.
    presence_penalty: float = 0.0
    frequency_penalty: float = 0.0
    # set by the caller (any thread) to stop generation early — e.g. a
    # stop-sequence hit or client disconnect in the streaming API; the
    # orchestrator honors it at the next token boundary:
    cancel_requested: bool = False
    # filled by the orchestrator:
    request_id: int = -1
    output_tokens: List[int] = dataclasses.field(default_factory=list)
    # parallel to output_tokens when logprobs > 0:
    token_logprobs: List[float] = dataclasses.field(default_factory=list)
    top_logprobs: List[Dict[int, float]] = dataclasses.field(
        default_factory=list)
    done: bool = False
    error: Optional[str] = None
    submitted_at: float = 0.0
    first_token_at: Optional[float] = None
    finished_at: Optional[float] = None
    # Cross-hop trace context, set by the serving handler from the
    # X-Xsky-* relay headers before submit (None for direct callers):
    trace_id: Optional[str] = None
    client_request_id: Optional[str] = None
    # Absolute perf_counter deadline (submitted_at + the remaining
    # budget the relay's deadline header carried). None = no deadline.
    deadline_at: Optional[float] = None
    # Anatomy phase accumulators (seconds), maintained by the
    # orchestrator as pure float adds and sealed into an AnatomyLog
    # record by the handler once the request finishes:
    taken_at: Optional[float] = None
    deferred_at: Optional[float] = None
    deferred_wait: float = 0.0
    decode_s: float = 0.0
    commit_s: float = 0.0
    kv_headroom_at_admit: Optional[float] = None


class Orchestrator:
    """Runs requests to completion with continuous batching."""

    def __init__(self, engine: engine_lib.InferenceEngine,
                 seed: int = 0, decode_steps: int = 1) -> None:
        if decode_steps < 1:
            raise ValueError(f'decode_steps must be >= 1, '
                             f'got {decode_steps}')
        self.engine = engine
        self.state = engine.init_decode_state()
        self._slot_req: Dict[int, Request] = {}
        self._free_slots = list(range(engine.config.max_slots))
        self._pending: 'queue.Queue[Request]' = queue.Queue()
        self._next_id = 0
        self._lock = threading.Lock()
        self._key = jax.random.PRNGKey(seed)
        # > 1 fuses that many decode steps into one device dispatch
        # (engine.decode_steps): the host sees tokens in batches of n,
        # so EOS/cancel latency grows by ≤ n-1 tokens and a finishing
        # slot wastes ≤ n-1 garbage steps — the trade that wins
        # whenever dispatch latency rivals per-step compute. Admission
        # still happens every tick, so TTFT is unaffected.
        self.decode_steps = decode_steps
        # Long prompts run their chunked prefill interleaved with
        # decode (see _advance_partials); slot →
        # (request, ChunkedPrefill) for admissions mid-prefill, with a
        # per-tick chunk budget so concurrent long prompts cannot
        # multiply running streams' inter-token latency.
        self.interleave_prefill = True
        self.prefill_chunks_per_tick = 1
        self._partials: Dict[int, Any] = {}
        # Requests that passed validation but found no KV-page headroom
        # (paged engines): retried ahead of the queue next tick.
        self._deferred: List[Request] = []
        # Fused decode with DEVICE-SIDE finish masking (the serving
        # fast path): finished slots stop sampling and writing KV
        # in-loop, the host commits from one device_get per tick, and
        # the per-slot sampling params live on device, rebuilt only
        # when occupancy changes. '0' falls back to the legacy
        # host-per-row tick (the paired-difference bench's baseline
        # arm).
        self._fast_tick = (
            os.environ.get('XSKY_DECODE_FAST_TICK', '1') != '0')
        self._params_dirty = True
        self._d_temps = None
        self._d_topk = None
        self._d_topp = None
        self._d_pen = None
        self._d_eos = None
        self._d_remaining = None
        self._lp_k = 0
        # Pre-split step keys, refilled every _KEY_POOL_TICKS ticks:
        # one jax.random.split per pool instead of per tick.
        self._key_pool: List[Any] = []
        self._key_pool_n = 0
        # Decode steps a slot burned after finishing mid-fused-batch
        # (legacy tick only; the masked loop stops the slot in-loop, so
        # its arm contributes zero by construction).
        self.wasted_decode_steps = 0
        # Per-request anatomy: when on, ticks amortize ONE timestamp
        # pair per fused batch into the resident requests' decode /
        # commit accumulators (pure float adds — the hot-path-purity
        # closure stays clean). XSKY_ANATOMY=0 is the bench_decode
        # paired-difference baseline arm.
        self._anatomy = os.environ.get('XSKY_ANATOMY', '1') != '0'
        # KV free-page fraction observed at the last successful admit
        # (paged engines; the xsky_serve_kv_headroom_at_admit gauge).
        self.last_admit_kv_headroom: Optional[float] = None
        # Deadline admission: requests rejected because their remaining
        # deadline could not cover the estimated prefill+decode budget.
        self.deadline_rejects = 0
        # EWMA budget estimators feeding the deadline gate (seconds);
        # None until the first completed prefill / decode tick.
        self._ewma_prefill_s: Optional[float] = None
        self._ewma_decode_per_token_s: Optional[float] = None

    # ---- submission ----

    def submit(self, request: Request) -> Request:
        with self._lock:
            request.request_id = self._next_id
            self._next_id += 1
        request.submitted_at = time.perf_counter()
        self._pending.put(request)
        return request

    # ---- scheduling ----

    def _admit_limit(self) -> int:
        return self.engine.max_admit_len

    def _validate_admit(self, request: Request) -> bool:
        """Cancel/length checks + KV-budget clamp. False ⇒ the request
        was finished (cancelled/rejected) and must not be admitted."""
        if request.cancel_requested:
            # Cancelled while still queued: finish without a prefill.
            request.done = True
            request.finished_at = time.perf_counter()
            return False
        prompt_len = len(request.prompt_tokens)
        # The prompt must leave room for at least one generated token in
        # the per-slot KV budget; families with a chunked-prefill path
        # admit beyond the largest bucket (engine.max_admit_len).
        limit = self._admit_limit()
        if prompt_len == 0 or prompt_len > limit:
            # Reject rather than crash the serving loop (the slot has not
            # been claimed yet, so capacity is unaffected).
            request.error = (
                f'Prompt length {prompt_len} outside (0, {limit}].')
            request.done = True
            request.finished_at = time.perf_counter()
            logger.warning(f'Rejected request {request.request_id}: '
                           f'{request.error}')
            return False
        budget = prompt_len + request.max_new_tokens
        if budget > self.engine.config.max_target_len:
            request.max_new_tokens = (self.engine.config.max_target_len -
                                      prompt_len)
        if not self.engine.kv_admissible(prompt_len,
                                         request.max_new_tokens):
            # Paged engine whose whole arena cannot hold this budget:
            # deferring would deadlock the drain loop, so reject.
            request.error = (
                f'Request KV budget {prompt_len + request.max_new_tokens}'
                f' tokens exceeds the paged-cache capacity.')
            request.done = True
            request.finished_at = time.perf_counter()
            logger.warning(f'Rejected request {request.request_id}: '
                           f'{request.error}')
            return False
        return True

    def _estimated_budget_s(self, request: Request) -> Optional[float]:
        """EWMA estimate of the request's remaining serving cost:
        one prefill plus max_new_tokens decode steps. None until any
        request has completed a prefill or a decode tick has run."""
        p = self._ewma_prefill_s
        d = self._ewma_decode_per_token_s
        if p is None and d is None:
            return None
        est = p or 0.0
        if d is not None:
            est += d * request.max_new_tokens
        return est

    def _deadline_reject(self, request: Request, now: float) -> bool:
        """Deadline admission gate (pure host float math): a request
        whose remaining deadline cannot cover the reserved
        prefill+decode budget is finished here instead of parking
        forever. With no EWMA sample yet only an already-expired
        deadline rejects. The handler thread journals the trace-linked
        ``serve.deadline_reject`` — no DB write on the tick path."""
        if request.deadline_at is None:
            return False
        remaining = request.deadline_at - now
        budget = self._estimated_budget_s(request) or 0.0
        if remaining > budget:
            return False
        request.error = (
            f'deadline exceeded at admit: {remaining * 1e3:.0f} ms '
            f'remaining < {budget * 1e3:.0f} ms estimated '
            f'prefill+decode budget')
        request.done = True
        request.finished_at = now
        self.deadline_rejects += 1
        return True

    def _take_request(self) -> Optional[Request]:
        """Next admission candidate: headroom-deferred requests retry
        ahead of the queue (FIFO within each). Expired-deadline
        candidates are rejected here — admission time, off the decode
        commit loop."""
        now = time.perf_counter()
        while self._deferred:
            request = self._deferred.pop(0)
            if request.deferred_at is not None:
                request.deferred_wait += now - request.deferred_at
                request.deferred_at = None
            if self._deadline_reject(request, now):
                continue
            return request
        while True:
            try:
                request = self._pending.get_nowait()
            except queue.Empty:
                return None
            if request.taken_at is None:
                request.taken_at = now
            if not self._deadline_reject(request, now):
                return request

    def _reserve_or_defer(self, request: Request, slot: int) -> bool:
        """Reserve KV capacity for the request's full budget against
        the claimed slot. On a paged engine with no page headroom the
        slot goes back, the request parks in the deferred list, and
        the caller stops admitting this tick (headroom only appears
        when a running stream finishes)."""
        if self.engine.reserve_kv(slot, len(request.prompt_tokens),
                                  request.max_new_tokens):
            if self._anatomy:
                pages = getattr(self.engine, 'kv_page_stats', None)
                if pages and pages.get('total'):
                    headroom = pages['free'] / pages['total']
                    request.kv_headroom_at_admit = headroom
                    self.last_admit_kv_headroom = headroom
            return True
        self._free_slots.append(slot)
        if request.deferred_at is None:
            request.deferred_at = time.perf_counter()
        self._deferred.append(request)
        return False

    def _admit_claimed(self, request: Request, slot: int) -> None:
        """Single-request admission into an already-claimed slot."""
        prompt_len = len(request.prompt_tokens)
        sp = sampling_lib.SamplingParams(
            temperature=request.temperature, top_k=request.top_k,
            top_p=request.top_p)
        lp_k = LOGPROBS_K if request.logprobs else 0
        if (self.interleave_prefill
                and prompt_len > self.engine.config.max_prompt_len
                and self.engine.supports_chunked_prefill):
            # Long prompt: claim the slot but run its prefill one chunk
            # per tick interleaved with decode (vLLM-style chunked
            # scheduling) — running streams keep emitting instead of
            # stalling for the whole multi-chunk prefill.
            self._partials[slot] = (
                request, self.engine.start_chunked_prefill(
                    request.prompt_tokens, sp, lp_k))
            return
        # Key omitted: the engine owns sampling-key state (split per call).
        # prefill_any == prefill for in-bucket prompts with no cached
        # prefix; beyond that it chunks and reuses cached prefixes.
        out = self.engine.prefill_any(request.prompt_tokens,
                                      sampling_params=sp,
                                      logprobs_k=lp_k)
        self._finish_admit(slot, request, out)

    def _admit_one(self) -> bool:
        """Prefill + insert one pending request into a free slot."""
        if not self._free_slots:
            return False
        request = self._take_request()
        if request is None:
            return False
        if not self._validate_admit(request):
            return True
        slot = self._free_slots.pop()
        if not self._reserve_or_defer(request, slot):
            return False   # no KV headroom: stop admitting this tick
        self._admit_claimed(request, slot)
        return True

    #: Subclasses with per-request admission hooks (speculation mirrors
    #: each prefill into a draft cache) keep the single path.
    _batched_admit = True

    def _admit_wave(self) -> None:
        """Admit pending requests, batching plain-bucket prefills into
        one forward + one scatter-insert dispatch per bucket group.

        Per-prompt prefill costs one device dispatch each; on
        dispatch-bound links the RTT per prefill dominates TTFT when a
        wave of requests arrives. Logprobs requests, long prompts
        (chunked path), and prefix-cached engines use the single path.
        """
        if not (self._batched_admit
                and getattr(self.engine.config, 'batched_admission',
                            True)
                and getattr(self.engine, 'supports_batched_prefill',
                            False)):
            while self._admit_one():
                pass
            return
        batch: List = []       # (request, claimed slot)
        while self._free_slots:
            request = self._take_request()
            if request is None:
                break
            if not self._validate_admit(request):
                continue
            slot = self._free_slots.pop()
            if not self._reserve_or_defer(request, slot):
                break      # no KV headroom: stop admitting this tick
            if (not request.logprobs
                    and len(request.prompt_tokens)
                    <= self.engine.config.max_prompt_len):
                batch.append((request, slot))
            else:
                self._admit_claimed(request, slot)
        groups: Dict[int, List] = {}
        for request, slot in batch:
            bucket = self.engine.bucket_for(len(request.prompt_tokens))
            groups.setdefault(bucket, []).append((request, slot))
        for group in groups.values():
            if len(group) == 1:
                request, slot = group[0]
                self._admit_claimed(request, slot)
                continue
            args = [(r.prompt_tokens, sampling_lib.SamplingParams(
                temperature=r.temperature, top_k=r.top_k,
                top_p=r.top_p)) for r, _ in group]
            slots = [s for _, s in group]
            try:
                self.state, first_tokens = \
                    self.engine.prefill_insert_batch(self.state, args,
                                                     slots)
            except Exception as e:  # pylint: disable=broad-except
                # Fail the group, fail_all-style, and RESTORE its
                # claimed slots + KV reservations — before this guard a
                # raising batched prefill leaked every popped slot in
                # the group, permanently shrinking the pool.
                logger.exception(
                    f'Batched prefill failed for {len(group)} '
                    f'requests: {e}')
                for request, slot in group:
                    request.error = f'Prefill failed: {e}'
                    request.done = True
                    request.finished_at = time.perf_counter()
                    self.engine.release_kv(slot)
                    self._free_slots.append(slot)
                continue
            for (request, slot), token in zip(group, first_tokens):
                self._post_insert(slot, request, token)

    def _finish_admit(self, slot: int, request: Request, out) -> None:
        if request.logprobs:
            first_token, kv, true_len, lp = out
            self._record_logprobs(request, lp, row=0)
        else:
            first_token, kv, true_len = out
        self.state = self.engine.insert(self.state, kv, first_token,
                                        true_len, slot)
        self._post_insert(slot, request, int(first_token))

    def _post_insert(self, slot: int, request: Request,
                     first_token: int) -> None:
        """Host-side bookkeeping once a prefill is in the slot cache
        (shared by single and batched admission)."""
        request.output_tokens.append(int(first_token))
        request.first_token_at = time.perf_counter()
        if request.taken_at is not None:
            # Prefill EWMA sample for the deadline admission gate
            # (take → first token, minus any headroom-deferred wait).
            sample = max(0.0, request.first_token_at -
                         request.taken_at - request.deferred_wait)
            prev = self._ewma_prefill_s
            self._ewma_prefill_s = (sample if prev is None
                                    else 0.8 * prev + 0.2 * sample)
        self._slot_req[slot] = request
        self._params_dirty = True
        self._maybe_finish(slot, int(first_token))

    def _advance_partials(self) -> None:
        """Advance in-flight chunked admissions, oldest first, up to
        prefill_chunks_per_tick chunks total — the budget bounds how
        much prefill work can delay each decode wave (the stall class
        interleaving exists to fix would otherwise return when many
        long prompts arrive at once); on a request's last chunk it
        joins the decode batch this tick. Cancelled partials are
        always reaped regardless of budget."""
        budget = self.prefill_chunks_per_tick
        for slot in list(self._partials):
            request, cp = self._partials[slot]
            if request.cancel_requested:
                del self._partials[slot]
                # The claimed slot's KV reservation goes back too — the
                # slot never reached release_slot (nothing inserted).
                self.engine.release_kv(slot)
                self._free_slots.append(slot)
                request.done = True
                request.finished_at = time.perf_counter()
                continue
            if budget <= 0:
                continue
            budget -= 1
            if cp.step():
                del self._partials[slot]
                self._finish_admit(slot, request, cp.finalize())

    def _record_logprobs(self, request: Request, lp, row) -> None:
        """Append one generated token's logprob + top-k alternatives.
        lp = (chosen, top_vals, top_ids) host- or device-side; `row`
        indexes the batch dim (0 for prefill, the slot for decode)."""
        chosen, vals, ids = (np.asarray(jax.device_get(a)) for a in lp)
        k = min(request.logprobs, vals.shape[-1])
        request.token_logprobs.append(float(chosen[row]))
        request.top_logprobs.append(
            {int(t): float(v)
             for t, v in zip(ids[row][:k], vals[row][:k])})

    def _maybe_finish(self, slot: int, token: int) -> None:
        request = self._slot_req[slot]
        hit_eos = (request.eos_token_id is not None and
                   token == request.eos_token_id)
        exhausted = len(request.output_tokens) >= request.max_new_tokens
        if hit_eos or exhausted or request.cancel_requested:
            if hit_eos:
                request.output_tokens.pop()
                if request.token_logprobs:
                    request.token_logprobs.pop()
                    request.top_logprobs.pop()
            request.done = True
            request.finished_at = time.perf_counter()
            self.state = self.engine.release_slot(self.state, slot)
            del self._slot_req[slot]
            self._free_slots.append(slot)
            self._params_dirty = True

    def step(self) -> None:
        """One scheduler tick: admit while possible (batching same-
        bucket prefills into one dispatch), advance in-flight chunked
        prefills by one chunk, then decode."""
        self._admit_wave()
        self._advance_partials()
        self._decode_tick()

    def _decode_tick(self) -> None:
        """The decode half of a tick — subclasses' mixed-batch
        fallbacks call this directly so admission and the partials
        budget run exactly once per tick. Dispatches to the fused
        masked fast path unless XSKY_DECODE_FAST_TICK=0 pins the
        legacy host-per-row tick."""
        # Chaos drill: `infer.decode_stall` slows one decode tick — a
        # latency rule here is how the anatomy drill proves a slow
        # DECODE (not queueing) shows up as the dominant waterfall
        # phase behind an SLO breach. The chaos module is purity-skip
        # listed: it only acts under an explicit fault plan.
        chaos.inject('infer.decode_stall')
        if self._fast_tick:
            self._decode_tick_fast()
        else:
            self._decode_tick_legacy()

    def _attribute_tick(self, residents: List[Request], decode_share: float,
                        commit_share: float, tokens: int) -> None:
        """Fold one fused batch's decode/commit wall time into the
        resident requests' anatomy accumulators — batch-amortized
        (one timestamp pair per tick, never per token) and pure float
        adds, so the hot-path-purity closure stays clean. Also feeds
        the per-token decode EWMA behind the deadline admission gate."""
        for request in residents:
            request.decode_s += decode_share
            request.commit_s += commit_share
        if tokens > 0:
            sample = (decode_share + commit_share) / tokens
            prev = self._ewma_decode_per_token_s
            self._ewma_decode_per_token_s = (
                sample if prev is None else 0.8 * prev + 0.2 * sample)

    # ---- fast tick: device-resident params + device-side finish ----

    _KEY_POOL_TICKS = 16

    def _rebuild_device_params(self) -> None:
        """Push the per-slot sampling/finish params to device — ONLY
        when occupancy changed (admit/release), not per tick. The
        legacy tick rebuilt five [max_slots] numpy arrays and re-made
        the None-folding decision every tick; steady-state fast ticks
        reuse these arrays untouched."""
        slots = self.engine.config.max_slots
        temps = np.zeros((slots,), np.float32)
        top_k = np.zeros((slots,), np.int32)
        top_p = np.ones((slots,), np.float32)
        pres = np.zeros((slots,), np.float32)
        freq = np.zeros((slots,), np.float32)
        eos = np.full((slots,), -1, np.int32)
        remaining = np.zeros((slots,), np.int32)
        need_lp = False
        for slot, r in self._slot_req.items():
            temps[slot] = r.temperature
            top_k[slot] = r.top_k
            top_p[slot] = r.top_p
            pres[slot] = r.presence_penalty
            freq[slot] = r.frequency_penalty
            if r.eos_token_id is not None:
                eos[slot] = r.eos_token_id
            remaining[slot] = max(
                r.max_new_tokens - len(r.output_tokens), 0)
            need_lp = need_lp or bool(r.logprobs)
        self._d_temps = jnp.asarray(temps)
        # None-folding (a cheaper compiled variant with the [slots,
        # vocab] sorts dead-coded out) decided host-side on the dirty
        # tick, not re-derived from device values every tick.
        self._d_topk = jnp.asarray(top_k) if (top_k > 0).any() else None
        self._d_topp = (jnp.asarray(top_p) if (top_p < 1.0).any()
                        else None)
        self._d_pen = ((jnp.asarray(pres), jnp.asarray(freq))
                       if (pres.any() or freq.any()) else None)
        self._d_eos = jnp.asarray(eos)
        self._d_remaining = jnp.asarray(remaining)
        self._lp_k = LOGPROBS_K if need_lp else 0
        self._params_dirty = False

    def _next_keys(self, n: int):
        """One [n]-key batch from the pool (refilled every
        _KEY_POOL_TICKS ticks — amortizes jax.random.split, which is
        itself a device dispatch, off the per-tick path)."""
        if not self._key_pool or self._key_pool_n != n:
            self._key, sub = jax.random.split(self._key)
            flat = jax.random.split(sub, n * self._KEY_POOL_TICKS)
            self._key_pool = [flat[i * n:(i + 1) * n]
                              for i in range(self._KEY_POOL_TICKS)]
            self._key_pool_n = n
        return self._key_pool.pop()

    def _decode_tick_fast(self) -> None:
        """Fused masked decode tick.

        One engine dispatch runs decode_steps steps with per-slot
        EOS/budget finish masking ON DEVICE; one device_get brings back
        (tokens, valid[, logprobs]) and the host commits only rows the
        mask kept — no per-row re-scan of all slots, no per-tick param
        rebuild, no post-EOS garbage steps for finished slots.
        """
        if not self._slot_req:
            return
        anatomy = self._anatomy
        t_tick = time.perf_counter() if anatomy else 0.0
        residents = list(self._slot_req.values()) if anatomy else None
        if self._params_dirty:
            self._rebuild_device_params()
        n = self.decode_steps
        keys = self._next_keys(n)
        probe = profiler.step_probe()
        out = self.engine.decode_steps_masked(
            self.state, n, self._d_temps, self._d_topk, self._d_topp,
            self._d_eos, self._d_remaining, keys,
            logprobs_k=self._lp_k, penalties=self._d_pen)
        if probe is not None:
            probe.dispatched()
        self.state, self._d_remaining, tokens, valid, lp = out
        if self._lp_k:
            tokens_np, valid_np, lp_np = jax.device_get(
                (tokens, valid, lp))
        else:
            tokens_np, valid_np = jax.device_get((tokens, valid))
            lp_np = None
        if probe is not None:
            probe.done()
        now = time.perf_counter()
        committed = 0
        for slot in list(self._slot_req):
            request = self._slot_req[slot]
            vm = valid_np[:, slot]
            emitted_before = len(request.output_tokens)
            for i in range(n):
                if not vm[i]:
                    break
                request.output_tokens.append(int(tokens_np[i, slot]))
                if self._lp_k and request.logprobs:
                    self._record_logprobs(
                        request,
                        (lp_np[0][i], lp_np[1][i], lp_np[2][i]), slot)
            committed += len(request.output_tokens) - emitted_before
            # An invalid row means the device deactivated the slot
            # (EOS — its token was never emitted, so there is nothing
            # to pop — or budget exhaustion after the last kept row).
            finished = (
                not vm.all()
                or len(request.output_tokens) >= request.max_new_tokens
                or request.cancel_requested)
            if finished:
                request.done = True
                request.finished_at = now
                self.state = self.engine.release_slot(self.state, slot)
                del self._slot_req[slot]
                self._free_slots.append(slot)
                self._params_dirty = True
        if anatomy:
            # One timestamp pair for the WHOLE fused batch: dispatch +
            # device wait before `now`, host commit after it. The
            # token count rides the commit loop's length bookkeeping —
            # a ufunc reduction over the valid mask here costs more
            # than the rest of the recorder combined.
            self._attribute_tick(residents, max(0.0, now - t_tick),
                                 max(0.0, time.perf_counter() - now),
                                 committed)

    # ---- legacy tick: host-side finish scan (bench baseline arm) ----

    def _decode_tick_legacy(self) -> None:
        if not self._slot_req:
            return
        anatomy = self._anatomy
        t_tick = time.perf_counter() if anatomy else 0.0
        residents = list(self._slot_req.values()) if anatomy else None
        slots = self.engine.config.max_slots
        temps = np.zeros((slots,), np.float32)
        top_k = np.zeros((slots,), np.int32)
        top_p = np.ones((slots,), np.float32)
        pres = np.zeros((slots,), np.float32)
        freq = np.zeros((slots,), np.float32)
        for slot, request in self._slot_req.items():
            temps[slot] = request.temperature
            top_k[slot] = request.top_k
            top_p[slot] = request.top_p
            pres[slot] = request.presence_penalty
            freq[slot] = request.frequency_penalty
        self._key, step_key = jax.random.split(self._key)
        k = (LOGPROBS_K if any(r.logprobs
                               for r in self._slot_req.values()) else 0)
        penalties = ((pres, freq) if (pres.any() or freq.any())
                     else None)
        # Step-anatomy probe (sampled): the engine call returning marks
        # the end of the host dispatch gap; the device_get below IS the
        # device wait — exactly the split the host-bound verdict needs
        # (113 ms dispatch vs 3 ms HBM on the tunneled serve bench).
        probe = profiler.step_probe()
        if self.decode_steps == 1:
            out = self.engine.decode_step(
                self.state, temperatures=temps, top_k=top_k, top_p=top_p,
                key=step_key, logprobs_k=k, penalties=penalties)
            if probe is not None:
                probe.dispatched()
            self.state, tokens = out[0], out[1]
            batches = np.asarray(jax.device_get(tokens))[None, :]
            lp = tuple(np.asarray(jax.device_get(a))[None]
                       for a in out[2]) if k else None
        else:
            out = self.engine.decode_steps(
                self.state, self.decode_steps, temperatures=temps,
                top_k=top_k, top_p=top_p, key=step_key, logprobs_k=k,
                penalties=penalties)
            if probe is not None:
                probe.dispatched()
            self.state, tokens = out[0], out[1]
            batches = np.asarray(jax.device_get(tokens))    # [n, slots]
            lp = tuple(np.asarray(jax.device_get(a))
                       for a in out[2]) if k else None
        if probe is not None:
            probe.done()
        t_commit = time.perf_counter() if anatomy else 0.0
        committed = 0
        for i, row in enumerate(batches):
            for slot in list(self._slot_req):
                request = self._slot_req[slot]
                request.output_tokens.append(int(row[slot]))
                committed += 1
                if request.logprobs and lp is not None:
                    self._record_logprobs(
                        request, (lp[0][i], lp[1][i], lp[2][i]), slot)
                self._maybe_finish(slot, int(row[slot]))
                if slot not in self._slot_req:
                    # The fused dispatch already sampled rows i+1..n-1
                    # for this slot; the fast tick's device mask makes
                    # these structurally zero.
                    self.wasted_decode_steps += len(batches) - 1 - i
        if anatomy:
            self._attribute_tick(residents,
                                 max(0.0, t_commit - t_tick),
                                 max(0.0,
                                     time.perf_counter() - t_commit),
                                 committed)

    def _verify_round(self, active_before, proposals) -> None:
        """One greedy verify pass over [slots, γ] proposals: append the
        accepted tokens + bonus per slot and update accept_stats.
        Shared by the draft-model and prompt-lookup speculators (which
        own the accept_stats dict this updates)."""
        gamma = proposals.shape[1]
        self.state, emitted, n_emitted = self.engine.verify_step(
            self.state, proposals)
        emitted = np.asarray(jax.device_get(emitted))
        n_emitted = np.asarray(jax.device_get(n_emitted))
        for slot, request in active_before.items():
            for i in range(int(n_emitted[slot])):
                if slot not in self._slot_req:
                    break  # finished mid-round: drop the tail
                request.output_tokens.append(int(emitted[slot, i]))
                self._maybe_finish(slot, int(emitted[slot, i]))
        self.accept_stats['rounds'] += 1
        self.accept_stats['proposed'] += gamma * len(active_before)
        self.accept_stats['accepted'] += int(
            sum(n_emitted[s] - 1 for s in active_before))

    def fail_all(self, error: str) -> None:
        """Finish every active and pending request with `error` and
        free their slots — never hand back silently-truncated outputs,
        and leave no stale queue behind to leak into a later batch."""
        for slot in list(self._partials):
            request, _ = self._partials.pop(slot)
            request.error = error
            request.done = True
            request.finished_at = time.perf_counter()
            self.engine.release_kv(slot)
            self._free_slots.append(slot)
        for request in self._deferred:
            request.error = error
            request.done = True
            request.finished_at = time.perf_counter()
        self._deferred.clear()
        for slot in list(self._slot_req):
            request = self._slot_req.pop(slot)
            request.error = error
            request.done = True
            request.finished_at = time.perf_counter()
            self.state = self.engine.release_slot(self.state, slot)
            self._free_slots.append(slot)
        while True:
            try:
                request = self._pending.get_nowait()
            except queue.Empty:
                break
            request.error = error
            request.done = True
            request.finished_at = time.perf_counter()

    def run_until_drained(self, max_steps: int = 100_000) -> None:
        steps = 0
        while (self._slot_req or self._partials or self._deferred
               or not self._pending.empty()) and steps < max_steps:
            self.step()
            steps += 1
        if (self._slot_req or self._partials or self._deferred
                or not self._pending.empty()):
            logger.warning(f'run_until_drained hit max_steps={max_steps} '
                           f'with {len(self._slot_req)} active, '
                           f'{len(self._partials)} mid-prefill, '
                           f'{len(self._deferred)} deferred and '
                           f'~{self._pending.qsize()} pending requests.')
            self.fail_all(f'Truncated at max_steps={max_steps}.')

    # ---- convenience ----

    def generate(self, prompts: List[List[int]],
                 max_new_tokens: int = 128,
                 eos_token_id: Optional[int] = None,
                 temperature: float = 0.0) -> List[List[int]]:
        requests = [
            self.submit(Request(prompt_tokens=p,
                                max_new_tokens=max_new_tokens,
                                eos_token_id=eos_token_id,
                                temperature=temperature))
            for p in prompts
        ]
        self.run_until_drained()
        return [r.output_tokens for r in requests]

    def benchmark(self, prompts: List[List[int]],
                  max_new_tokens: int = 64) -> Dict[str, Any]:
        """Throughput numbers in BASELINE's JetStream terms."""
        t0 = time.perf_counter()
        requests = [self.submit(Request(prompt_tokens=p,
                                        max_new_tokens=max_new_tokens))
                    for p in prompts]
        self.run_until_drained()
        dt = time.perf_counter() - t0
        in_tokens = sum(len(p) for p in prompts)
        out_tokens = sum(len(r.output_tokens) for r in requests)
        ttfts = [r.first_token_at - r.submitted_at for r in requests
                 if r.first_token_at is not None]
        return {
            'duration_s': dt,
            'request_throughput_rps': len(prompts) / dt,
            'input_token_throughput_tps': in_tokens / dt,
            'output_token_throughput_tps': out_tokens / dt,
            'mean_ttft_s': float(np.mean(ttfts)) if ttfts else 0.0,
        }


class SpeculativeOrchestrator(Orchestrator):
    """Continuous batching with draft-model speculative decoding.

    A small draft engine proposes γ tokens per slot (γ+1 cheap decode
    steps); the target engine verifies them in ONE multi-token pass
    (engine.verify_step) — the target's weights stream from HBM once
    per round instead of once per token, which is the win on
    bandwidth-bound decode. Greedy acceptance keeps outputs EXACTLY
    equal to plain greedy decoding regardless of draft quality; a bad
    draft only lowers the accepted-token rate (tracked in
    `accept_stats`).

    v1 scope: speculation applies to rounds where every active slot is
    greedy (temperature 0). Mixed batches fall back to plain per-token
    decoding for that round; the draft's bookkeeping is re-synced each
    round either way, and a stale draft cache can only cost acceptance
    rate, never correctness.
    """

    # Admission mirrors every prefill into the draft cache per
    # request (_finish_admit hook) — keep the single path.
    _batched_admit = False

    def __init__(self, engine: engine_lib.InferenceEngine,
                 draft_engine: engine_lib.InferenceEngine,
                 gamma: int = 4, seed: int = 0) -> None:
        if draft_engine.config.max_slots != engine.config.max_slots:
            raise ValueError('draft/target max_slots must match')
        if draft_engine.config.max_target_len != \
                engine.config.max_target_len:
            raise ValueError('draft/target max_target_len must match')
        if draft_engine.config.model.vocab_size != \
                engine.config.model.vocab_size:
            raise ValueError('draft/target vocab_size must match')
        if gamma < 1:
            raise ValueError(f'gamma must be >= 1, got {gamma}')
        if not engine.supports_verify:
            raise NotImplementedError(
                'target model family has no verify_forward')
        super().__init__(engine, seed)
        self.draft = draft_engine
        self.draft_state = draft_engine.init_decode_state()
        self.gamma = gamma
        self.accept_stats = {'rounds': 0, 'proposed': 0, 'accepted': 0}
        # slot → (request, ChunkedPrefill) for draft mirrors of long
        # prompts still prefilling (see _advance_draft_partials).
        self._draft_partials: Dict[int, Any] = {}

    def _admit_limit(self) -> int:
        # Both engines prefill every admitted prompt, so the admit gate
        # is the tighter of the two (the draft may lack chunked prefill
        # or have smaller buckets).
        return min(self.engine.max_admit_len, self.draft.max_admit_len)

    def _finish_admit(self, slot, request, out) -> None:
        # Mirror every completed admission (direct or interleaved
        # chunked) into the draft cache so its proposals have context —
        # hooking here rather than _admit_one keeps interleaved
        # prefills speculation-safe.
        super()._finish_admit(slot, request, out)
        if slot not in self._slot_req:
            return   # finished during admit (eos on first token)
        if (len(request.prompt_tokens) > self.draft.config.max_prompt_len
                and self.draft.supports_chunked_prefill):
            # A long prompt's DRAFT prefill is chunked+budgeted across
            # ticks too — running it whole here would stall every
            # stream for the draft's multi-chunk prefill in one tick.
            # Until it lands, rounds fall back to plain decoding; the
            # late mirror only costs acceptance on the tokens emitted
            # meanwhile (their draft cache rows are absent), never
            # correctness.
            self._draft_partials[slot] = (
                request, self.draft.start_chunked_prefill(
                    request.prompt_tokens))
            return
        _, draft_kv, true_len = self.draft.prefill_any(
            request.prompt_tokens)
        # The draft chain continues from the TARGET's sampled first
        # token (insert() records it as the slot's pending token).
        self.draft_state = self.draft.insert(
            self.draft_state, draft_kv,
            np.int32(request.output_tokens[-1]), true_len, slot)

    def _advance_draft_partials(self) -> None:
        budget = self.prefill_chunks_per_tick
        for slot in list(self._draft_partials):
            request, cp = self._draft_partials[slot]
            # Identity check, not just occupancy: if the owning request
            # finished and the slot was re-admitted in the same tick, a
            # stale partial's finalize() would overwrite the NEW
            # request's draft cache with the old prompt's KV.
            if request.done or self._slot_req.get(slot) is not request:
                del self._draft_partials[slot]   # finished/cancelled
                continue
            if budget <= 0:
                continue
            budget -= 1
            if cp.step():
                del self._draft_partials[slot]
                _, draft_kv, true_len = cp.finalize()
                self.draft_state = self.draft.insert(
                    self.draft_state, draft_kv,
                    np.int32(request.output_tokens[-1]), true_len, slot)
                # Bookkeeping catches up to the target's frontier; the
                # generated-token cache rows stay absent (acceptance
                # cost only).
                self.draft_state = self.draft.sync_slots_from(
                    self.draft_state, self.state)

    def step(self) -> None:
        while self._admit_one():
            pass
        self._advance_partials()
        self._advance_draft_partials()
        if not self._slot_req:
            return
        all_greedy = all(r.temperature == 0.0 and not r.logprobs
                         and not r.presence_penalty
                         and not r.frequency_penalty
                         for r in self._slot_req.values())
        if not all_greedy or self._draft_partials:
            # Mixed batch (sampled slots, slots wanting logprobs —
            # verify_forward does not surface per-token logprobs — or
            # penalized slots, whose counts only plain rounds update),
            # or a slot whose draft mirror is still prefilling: plain
            # round; keep the draft's bookkeeping aligned (cache rows
            # for these tokens are missing in the draft — acceptance
            # pays, not correctness).
            self._decode_tick()
            self.draft_state = self.draft.sync_slots_from(
                self.draft_state, self.state)
            return
        active_before = dict(self._slot_req)
        # γ draft proposals (+1 ingest step so a fully-accepted round
        # leaves no hole in the draft cache), all greedy.
        proposals = []
        for _ in range(self.gamma):
            self.draft_state, toks = self.draft.decode_step(
                self.draft_state)
            proposals.append(toks)  # stays on device: no sync barrier
        self.draft_state, _ = self.draft.decode_step(self.draft_state)
        # All γ+1 draft steps and the verify dispatch asynchronously;
        # the only host sync per round is fetching emitted/n_emitted.
        self._verify_round(active_before, jnp.stack(proposals, axis=1))
        # Draft follows the target's accepted frontier.
        self.draft_state = self.draft.sync_slots_from(
            self.draft_state, self.state)


class NgramSpeculator(Orchestrator):
    """Draft-model-free speculation: prompt-lookup (n-gram) proposals.

    The last `match_len` tokens of each slot's history (prompt +
    generated so far) are matched against the most recent earlier
    occurrence in that same history; the γ tokens that followed it
    become the proposals, verified in ONE multi-token target pass
    (engine.verify_step) exactly like draft-model speculation. Greedy
    acceptance keeps outputs equal to plain greedy decoding — a failed
    lookup only wastes the round's extra verify columns. Wins on
    copy-heavy generation (quoting the prompt, code, RAG answers)
    with no second model and no extra HBM.
    """

    # Keep per-request admission: gram indexes key off request
    # state at admit time.
    _batched_admit = False

    def __init__(self, engine: engine_lib.InferenceEngine,
                 gamma: int = 4, match_len: int = 2,
                 seed: int = 0) -> None:
        if gamma < 1:
            raise ValueError(f'gamma must be >= 1, got {gamma}')
        if match_len < 1:
            raise ValueError(f'match_len must be >= 1, got {match_len}')
        if not engine.supports_verify:
            raise NotImplementedError(
                'target model family has no verify_forward')
        super().__init__(engine, seed)
        self.gamma = gamma
        self.match_len = match_len
        self.accept_stats = {'rounds': 0, 'proposed': 0, 'accepted': 0}
        # slot → (request_id, gram → most recent start pos, tokens
        # indexed so far): maintained incrementally, so each round's
        # lookup is O(new tokens), not an O(history) backward scan per
        # slot per round. Keyed by request_id so a slot reused by a
        # new request never inherits a stale index.
        self._grams: Dict[int, Tuple[int, Dict[tuple, int], int]] = {}

    def _propose(self, slot: int, request: Request) -> List[int]:
        """γ proposals from the most recent earlier occurrence of the
        history's trailing match_len-gram; repeats of the last token
        when nothing matches (wrong proposals cost only acceptance)."""
        history = request.prompt_tokens + request.output_tokens
        k = self.match_len
        fallback = [history[-1]] * self.gamma
        if len(history) <= k:
            return fallback
        entry = self._grams.get(slot)
        if entry is None or entry[0] != request.request_id:
            entry = (request.request_id, {}, 0)
        _, index, upto = entry
        # Index every gram STARTING before the trailing one (the
        # trailing gram itself must not match in place).
        for j in range(upto, len(history) - k):
            index[tuple(history[j:j + k])] = j
        self._grams[slot] = (request.request_id, index,
                             len(history) - k)
        j = index.get(tuple(history[-k:]))
        if j is None:
            return fallback
        return (history[j + k:j + k + self.gamma] +
                fallback)[:self.gamma]

    def step(self) -> None:
        while self._admit_one():
            pass
        self._advance_partials()
        # Drop gram indexes of released slots (memory hygiene; staleness
        # itself is prevented by the request_id key).
        for slot in list(self._grams):
            if slot not in self._slot_req:
                del self._grams[slot]
        if not self._slot_req:
            return
        all_greedy = all(r.temperature == 0.0 and not r.logprobs
                         and not r.presence_penalty
                         and not r.frequency_penalty
                         for r in self._slot_req.values())
        if not all_greedy:
            self._decode_tick()
            return
        active_before = dict(self._slot_req)
        slots = self.engine.config.max_slots
        proposals = np.zeros((slots, self.gamma), np.int32)
        for slot, request in active_before.items():
            proposals[slot] = self._propose(slot, request)
        self._verify_round(active_before, jnp.asarray(proposals))
