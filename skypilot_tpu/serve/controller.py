"""Serve controller process: autoscaler loop + LB in one process.

Twin of sky/serve/service.py:155 (_start forks controller + LB) and
sky/serve/controller.py:36 (autoscaler loop :65). Run as
``python -m skypilot_tpu.serve.controller <service_name>``.

The tick also hosts the serving side of the anomaly→remediation
engine (utils/remediation.py): journalled metric anomalies bind to
graded actions — dispatch-gap trend deprioritizes the replica in
routing and captures a device profile, heartbeat-age drift starts a
pre-emptive graceful drain (the scale loop launches the replacement),
burn-rate acceleration fast-paths the burn autoscaler past its
cooldown.
"""
from __future__ import annotations

import os
import sys
import threading
import time
from typing import Any, Dict, Optional, Tuple

from skypilot_tpu import sky_logging
from skypilot_tpu import state as global_state
from skypilot_tpu.serve import autoscalers as autoscalers_lib
from skypilot_tpu.serve import load_balancer as lb_lib
from skypilot_tpu.serve import load_balancing_policies as lb_policies
from skypilot_tpu.serve import replica_managers
from skypilot_tpu.serve import service_spec as spec_lib
from skypilot_tpu.serve import slo as slo_lib
from skypilot_tpu.serve import state as serve_state
from skypilot_tpu.utils import chaos
from skypilot_tpu.utils import remediation

logger = sky_logging.init_logger(__name__)

CONTROLLER_INTERVAL_S = float(
    os.environ.get('XSKY_SERVE_INTERVAL', '2.0'))


class SkyServeController:

    def __init__(self, service_name: str) -> None:
        record = serve_state.get_service(service_name)
        assert record is not None, service_name
        self.service_name = service_name
        self.version = record['version']
        # HA: a respawned controller resumes a mid-flight blue_green
        # cutover from the persisted mode, not a default.
        self.update_mode = record.get('update_mode') or 'rolling'
        task_config = record['task_config']
        self.spec = spec_lib.SkyServiceSpec.from_yaml_config(
            task_config.get('service', {}))
        self.replica_manager = replica_managers.ReplicaManager(
            service_name, task_config, self.spec,
            version=self.version)
        self.autoscaler = autoscalers_lib.make_autoscaler(self.spec)
        self.load_balancer = lb_lib.SkyServeLoadBalancer(
            policy=lb_policies.make_policy(
                self.spec.load_balancing_policy),
            on_request=lambda: self.autoscaler
            .collect_request_information(1, 0.0))
        # Per-request deadline (slo.deadline_ms): the LB relays each
        # request's remaining budget downstream so the orchestrator's
        # admit gate can shed work that can no longer finish in time.
        self.load_balancer.deadline_ms = (
            self.spec.slo.deadline_ms
            if self.spec.slo is not None else None)
        # SLO plane: every scrape interval the monitor pulls replica
        # /metrics, folds in the LB's request records, and persists
        # burn rates + latency digests into the serve_slo table.
        self.slo_monitor = slo_lib.SLOMonitor(
            service_name, self.spec.slo,
            record_source=self.load_balancer.request_log.records,
            inflight_source=self.load_balancer.replica_stats
            .inflight_by_replica)
        self._wire_autoscaler()
        # Anomaly→remediation engine: detector → graded action. Each
        # arm is a named method carrying a `remediation.apply` chaos
        # point (chaos-coverage lint), idempotent and flap-suppressed
        # by the engine itself.
        self.remediator = remediation.RemediationEngine(
            scope=f'service/{service_name}')
        self.remediator.register(
            'dispatch_gap_trend', 'deprioritize_replica',
            self._remediate_dispatch_gap_trend,
            resolver=self._undeprioritize)
        self.remediator.register(
            'heartbeat_age_drift', 'drain_replica',
            self._remediate_heartbeat_age_drift)
        self.remediator.register(
            'burn_rate_accel', 'autoscale_fastpath',
            self._remediate_burn_rate_accel)
        self._stop = threading.Event()
        self._respawn_budget_cleared = False

    def _wire_autoscaler(self) -> None:
        # Burn autoscalers journal scored decisions under the service
        # name; specs don't know it, so the controller injects it.
        if isinstance(self.autoscaler,
                      autoscalers_lib.BurnRateAutoscaler):
            self.autoscaler.service_name = self.service_name

    def run(self) -> None:
        lb_port = serve_state.get_service(self.service_name)['lb_port']
        certfile = keyfile = None
        if self.spec.tls_enabled:
            certfile = os.path.expanduser(self.spec.tls_certfile)
            keyfile = os.path.expanduser(self.spec.tls_keyfile)
        actual_port = self.load_balancer.run_in_thread(
            port=lb_port, certfile=certfile, keyfile=keyfile)
        scheme = 'https' if certfile else 'http'
        logger.info(f'Service {self.service_name}: LB on '
                    f'{scheme}://:{actual_port}')
        serve_state.set_service_status(
            self.service_name, serve_state.ServiceStatus.REPLICA_INIT)
        self._apply_scale(self.spec.min_replicas)

        while not self._stop.is_set():
            self._heartbeat()
            try:
                self._tick()
            except Exception as e:  # pylint: disable=broad-except
                logger.warning(f'controller tick failed: {e}')
            self._stop.wait(CONTROLLER_INTERVAL_S)

    def _heartbeat(self) -> None:
        """Renew this service's liveness lease (reconciler
        crash-safety: an expired lease marks this controller dead)."""
        global_state.heartbeat_lease(f'service/{self.service_name}',
                                     owner='serve-controller')

    def _maybe_adopt_new_version(self) -> None:
        """Pick up `serve update`: reload spec + task at the new version.

        The rolling semantics live in the replica manager — new-version
        replicas launch alongside the old fleet, which drains only after
        the new one passes readiness (reconcile_versions in _tick).
        """
        record = serve_state.get_service(self.service_name)
        if record is None or record['version'] == self.version:
            return
        self.version = record['version']
        self.update_mode = record.get('update_mode') or 'rolling'
        task_config = record['task_config']
        self.spec = spec_lib.SkyServiceSpec.from_yaml_config(
            task_config.get('service', {}))
        new_autoscaler = autoscalers_lib.make_autoscaler(self.spec)
        new_autoscaler.inherit_state(self.autoscaler)
        self.autoscaler = new_autoscaler
        self._wire_autoscaler()
        # The update may change the LB policy. Swap only on an actual
        # change — rebuilding needlessly would zero LeastLoad's
        # in-flight counters mid-traffic. Seed the new policy with the
        # current fleet so no request hits an empty replica set between
        # now and the next tick.
        wanted = lb_policies.POLICIES[self.spec.load_balancing_policy]
        if type(self.load_balancer.policy) is not wanted:
            new_policy = wanted()
            new_policy.set_ready_replicas(
                self.replica_manager.ready_endpoints())
            # Keep the rolling-stats handoff across the swap (a
            # telemetry-routing policy reads .stats).
            new_policy.stats = self.load_balancer.replica_stats
            self.load_balancer.policy = new_policy
        self.replica_manager.apply_update(task_config, self.spec,
                                          self.version)
        self.slo_monitor.update_slo(self.spec.slo)
        self.load_balancer.deadline_ms = (
            self.spec.slo.deadline_ms
            if self.spec.slo is not None else None)
        logger.info(f'Service {self.service_name}: rolling update to '
                    f'v{self.version}.')

    def _resolve_replica(self, anomaly: Dict[str, Any]
                         ) -> Tuple[Optional[Dict[str, Any]],
                                    Optional[str]]:
        """(replica record, endpoint) an anomaly points at.

        A real finding's ident is its metric's canonical label string
        (``cluster=...,rank=...``) — match on the cluster label. A
        forced (chaos-injected) finding carries no labels, so fall back
        to the worst replica the routing telemetry can name: highest
        rolling p99 TTFT, ties to highest error rate.
        """
        replicas = [r for r in self.replica_manager.replicas()
                    if r['status'] == serve_state.ReplicaStatus.READY
                    and not r['draining']]
        labels = dict(
            part.split('=', 1) for part in anomaly['ident'].split(',')
            if '=' in part)
        cluster = labels.get('cluster')
        if cluster is not None:
            for r in replicas:
                if r['cluster_name'] == cluster:
                    return r, r['endpoint']
        snap = self.load_balancer.replica_stats.snapshot()
        scored = [
            (s['ttft_p99_ms'], s.get('error_rate') or 0.0, endpoint)
            for endpoint, s in snap.items()
            if s.get('ttft_p99_ms') is not None]
        for _, _, endpoint in sorted(scored, reverse=True):
            for r in replicas:
                if r['endpoint'] == endpoint:
                    return r, endpoint
        return None, None

    def _remediate_dispatch_gap_trend(
            self, anomaly: Dict[str, Any]
    ) -> Optional[Dict[str, Any]]:
        """Dispatch-gap trend → capture a device profile on the
        replica's cluster + deprioritize it in routing (weight capped
        at the policy floor until the anomaly clears)."""
        chaos.inject(remediation.APPLY_CHAOS_POINT,
                     detector=anomaly['detector'],
                     action='deprioritize_replica')
        record, endpoint = self._resolve_replica(anomaly)
        if endpoint is None:
            return None   # nothing serving to act on; retry next tick
        policy = self.load_balancer.policy
        if hasattr(policy, 'deprioritize'):
            # Cap at the cooldown so a dead engine can't pin the
            # weight down forever; the resolver lifts it sooner.
            policy.deprioritize(endpoint,
                                duration_s=self.remediator.cooldown)
        detail: Dict[str, Any] = {'endpoint': endpoint}
        profile_captured = False
        if record is not None:
            detail['replica_id'] = record['replica_id']
            detail['cluster'] = record['cluster_name']
            try:
                from skypilot_tpu import core
                core.profile_capture(record['cluster_name'])
                profile_captured = True
            except Exception as e:  # pylint: disable=broad-except
                logger.debug(f'profile capture failed: {e}')
        detail['profile_captured'] = profile_captured
        return detail

    def _undeprioritize(self, meta: Dict[str, Any]) -> None:
        """Resolver: restore the replica's routing share when the
        dispatch-gap anomaly clears."""
        endpoint = (meta.get('detail') or {}).get('endpoint')
        policy = self.load_balancer.policy
        if endpoint and hasattr(policy, 'undeprioritize'):
            policy.undeprioritize(endpoint)

    def _remediate_heartbeat_age_drift(
            self, anomaly: Dict[str, Any]
    ) -> Optional[Dict[str, Any]]:
        """Heartbeat-age drift → pre-emptive graceful drain: the
        replica stops admitting, finishes inflight under the deadline,
        and the scale loop launches its replacement (draining replicas
        don't count toward the target)."""
        chaos.inject(remediation.APPLY_CHAOS_POINT,
                     detector=anomaly['detector'],
                     action='drain_replica')
        record, endpoint = self._resolve_replica(anomaly)
        if record is None:
            return None
        healthy = [r for r in self.replica_manager.replicas()
                   if r['status'] == serve_state.ReplicaStatus.READY
                   and not r['draining']]
        if len(healthy) <= 1:
            # Never drain the fleet dark on a telemetry hunch; wait
            # for the replacement capacity a scale-out brings.
            return None
        drained = self.replica_manager.drain_replica(
            record['replica_id'], reason='heartbeat_age_drift',
            detector=anomaly['detector'], ident=anomaly['ident'])
        if not drained:
            return None
        return {'replica_id': record['replica_id'],
                'cluster': record['cluster_name'],
                'endpoint': endpoint}

    def _remediate_burn_rate_accel(
            self, anomaly: Dict[str, Any]
    ) -> Optional[Dict[str, Any]]:
        """Burn-rate acceleration → let the burn autoscaler's next
        evaluation bypass its upscale cooldown once."""
        chaos.inject(remediation.APPLY_CHAOS_POINT,
                     detector=anomaly['detector'],
                     action='autoscale_fastpath')
        if not hasattr(self.autoscaler, 'request_fastpath'):
            return None   # not a burn autoscaler: nothing to fast-path
        self.autoscaler.request_fastpath()
        return {'target_before': self.autoscaler.target_num_replicas}

    def _apply_scale(self, target: int) -> None:
        """Scale the fleet to `target`, splitting spot vs on-demand when
        the mixed-fleet knobs are on. Controller restarts count the
        live READY spot replicas (not zero) so a healthy fleet never
        triggers a spurious on-demand launch wave."""
        manager = self.replica_manager
        if (self.spec.base_ondemand_fallback_replicas or
                self.spec.dynamic_ondemand_fallback):
            spot_target, od_target = self.autoscaler.split_targets(
                target, manager.ready_spot_count())
            manager.scale_to(spot_target, target_ondemand=od_target)
        else:
            manager.scale_to(target)

    def _tick(self) -> None:
        self._maybe_adopt_new_version()
        manager = self.replica_manager
        ready = manager.probe_all()
        if ready == 0 and \
                manager.launch_failures >= manager.max_launch_failures:
            # Launch budget exhausted with nothing serving: the service
            # is broken (infeasible resources / bad run cmd). Stop
            # burning launches.
            logger.warning(
                f'Service {self.service_name}: '
                f'{manager.launch_failures} consecutive replica launch '
                'failures; marking FAILED.')
            serve_state.set_service_status(
                self.service_name, serve_state.ServiceStatus.FAILED)
            self._stop.set()
            return
        manager.recover_preempted()
        decision = self.autoscaler.evaluate(ready)
        qps_fn = getattr(self.autoscaler, 'current_qps', None)
        serve_state.set_service_metrics(
            self.service_name, qps_fn() if qps_fn else None,
            decision.target_num_replicas, ready_replicas=ready)
        self._apply_scale(decision.target_num_replicas)
        # Cut the LB over BEFORE draining old versions: in blue_green
        # the pre-drain LB holds only OLD endpoints, and
        # reconcile_versions tears those clusters down (minutes on real
        # clouds) — draining first would serve terminated endpoints for
        # the whole window.
        self.load_balancer.set_ready_replicas(
            manager.serving_endpoints(self.update_mode,
                                      decision.target_num_replicas),
            draining=manager.draining_endpoints())
        manager.reconcile_versions(decision.target_num_replicas)
        # Finish graceful drains whose inflight emptied (or whose
        # deadline passed) — the LB's own counters say when.
        manager.tick_drains(
            self.load_balancer.replica_stats.inflight_by_replica())
        # SLO evaluation rides the tick but rate-limits itself to the
        # scrape interval; never raises (the scale loop must survive
        # a torn scrape or a locked state DB). Each evaluation's burn
        # rates feed the burn autoscaler's next decision.
        service_row = self.slo_monitor.maybe_tick(manager.replicas())
        if service_row and hasattr(self.autoscaler,
                                   'collect_burn_info'):
            self.autoscaler.collect_burn_info(service_row.get('burns'))
        # Remediation engine pass: bind journalled anomalies to the
        # graded actions registered above. Never raises.
        remediation.maybe_tick(self.remediator)
        if ready > 0:
            serve_state.set_service_status(
                self.service_name, serve_state.ServiceStatus.READY)
            if not self._respawn_budget_cleared:
                # Steady state clears the HA respawn budget ONCE per
                # controller run: it bounds crash loops, not how many
                # restarts a long-lived service may outlive (same
                # semantics as the jobs controller's reset).
                serve_state.reset_controller_respawns(self.service_name)
                self._respawn_budget_cleared = True
        else:
            current = serve_state.get_service(self.service_name)
            if current and current['status'] == \
                    serve_state.ServiceStatus.READY:
                serve_state.set_service_status(
                    self.service_name,
                    serve_state.ServiceStatus.NO_REPLICA)

    def stop(self) -> None:
        self._stop.set()
        self.load_balancer.shutdown()


def main() -> int:
    service_name = sys.argv[1]
    serve_state.set_service_controller_pid(service_name, os.getpid())
    controller = SkyServeController(service_name)
    try:
        controller.run()
        return 0
    except KeyboardInterrupt:
        return 0
    finally:
        controller.stop()
        # Clean exit drops the lease; a SIGKILL leaves it for the
        # reconciler to expire and repair.
        global_state.release_lease(f'service/{service_name}')


if __name__ == '__main__':
    sys.exit(main())
