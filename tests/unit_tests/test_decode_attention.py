"""Pallas decode-attention kernel vs the masked XLA reference.

The kernel is the serving hot path (per-slot length-bounded reads,
in-kernel int8 dequant); these tests pin its numerics against the
padded-cache XLA path it replaces, across cache representations,
group factors, windows, and ragged slot lengths.
"""
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu.models import llama
from skypilot_tpu.ops import attention as attention_ops
from skypilot_tpu.ops import decode_attention as decode_ops

pytestmark = pytest.mark.slow  # interpret-mode kernels are minutes-scale



def _rand(shape, seed, dtype=jnp.float32):
    return jax.random.normal(jax.random.PRNGKey(seed), shape, dtype)


def _xla_reference(q, k_cache, v_cache, lengths, window=None):
    if isinstance(k_cache, (tuple, list)):
        k_cache = llama.dequantize_kv(*k_cache, q.dtype)
        v_cache = llama.dequantize_kv(*v_cache, q.dtype)
    kv_pos = jnp.arange(k_cache.shape[1])[None, None, :]
    q_pos = (lengths - 1)[:, None]
    valid = kv_pos <= q_pos[..., None]
    if window is not None:
        valid = valid & (kv_pos > q_pos[..., None] - window)
    return attention_ops.xla_attention_with_mask(
        q, k_cache, v_cache, valid[:, None])


@pytest.mark.parametrize('groups', [1, 4])
@pytest.mark.parametrize('window', [None, 48])
def test_matches_reference_ragged_lengths(groups, window):
    b, h_kv, d, max_len = 4, 2, 64, 256
    h = h_kv * groups
    q = _rand((b, 1, h, d), 0)
    ck = _rand((b, max_len, h_kv, d), 1)
    cv = _rand((b, max_len, h_kv, d), 2)
    # Ragged: one slot nearly empty, one full, two mid-block.
    lengths = jnp.array([1, max_len, 100, 129], jnp.int32)
    out = decode_ops.decode_attention(q, ck, cv, lengths, window=window,
                                      block_kv=64)
    ref = _xla_reference(q, ck, cv, lengths, window=window)
    np.testing.assert_allclose(out, ref, atol=2e-5)


def test_int8_cache_in_kernel_dequant():
    b, h_kv, groups, d, max_len = 3, 2, 2, 64, 128
    h = h_kv * groups
    q = _rand((b, 1, h, d), 3)
    ck = llama.quantize_kv(_rand((b, max_len, h_kv, d), 4))
    cv = llama.quantize_kv(_rand((b, max_len, h_kv, d), 5))
    lengths = jnp.array([5, 128, 64], jnp.int32)
    out = decode_ops.decode_attention(q, ck, cv, lengths, block_kv=64)
    ref = _xla_reference(q, ck, cv, lengths)
    np.testing.assert_allclose(out, ref, atol=2e-4)


def test_bf16_query():
    b, h_kv, d, max_len = 2, 2, 64, 128
    q = _rand((b, 1, h_kv * 4, d), 6, jnp.bfloat16)
    ck = _rand((b, max_len, h_kv, d), 7, jnp.bfloat16)
    cv = _rand((b, max_len, h_kv, d), 8, jnp.bfloat16)
    lengths = jnp.array([33, 90], jnp.int32)
    out = decode_ops.decode_attention(q, ck, cv, lengths, block_kv=64)
    ref = _xla_reference(q, ck, cv, lengths)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(out.astype(jnp.float32),
                               ref.astype(jnp.float32), atol=3e-2)


def test_shard_map_island_matches_plain_kernel():
    """The mesh path (slots on data/fsdp, KV heads on tensor) must
    bit-match the single-device kernel: per-(slot, head) programs are
    independent, so sharding only relocates them."""
    from skypilot_tpu.parallel import mesh as mesh_lib
    mesh = mesh_lib.build_mesh(mesh_lib.MeshPlan(data=4, tensor=2))
    b, h_kv, groups, d, max_len = 4, 2, 2, 16, 32
    q = _rand((b, 1, h_kv * groups, d), 20, jnp.bfloat16)
    ck = _rand((b, max_len, h_kv, d), 21, jnp.bfloat16)
    cv = _rand((b, max_len, h_kv, d), 22, jnp.bfloat16)
    lengths = jnp.array([6, 1, 32, 17], jnp.int32)
    assert decode_ops.shardable_on(mesh, b, h_kv)
    plain = decode_ops.decode_attention(q, ck, cv, lengths, block_kv=32)
    sharded = decode_ops.decode_attention(q, ck, cv, lengths,
                                          block_kv=32, mesh=mesh)
    np.testing.assert_array_equal(np.asarray(plain), np.asarray(sharded))


def test_shardable_on_rejects_indivisible():
    from skypilot_tpu.parallel import mesh as mesh_lib
    mesh = mesh_lib.build_mesh(mesh_lib.MeshPlan(data=4, tensor=2))
    assert not decode_ops.shardable_on(mesh, b=3, h_kv=2)   # slots %
    assert not decode_ops.shardable_on(mesh, b=4, h_kv=1)   # heads %


@pytest.mark.parametrize('kv_dtype', ['bf16', 'int8'])
def test_slot_cache_attend_dispatches_to_kernel(kv_dtype, monkeypatch):
    """The family-shared decode contract must produce identical logits
    whether the Pallas kernel or the XLA fallback runs."""
    b, h_kv, groups, d, max_len = 2, 2, 2, 64, 64
    h = h_kv * groups
    q = _rand((b, 1, h, d), 9)
    k_new = _rand((b, 1, h_kv, d), 10)
    v_new = _rand((b, 1, h_kv, d), 11)
    if kv_dtype == 'int8':
        ck = llama.quantize_kv(_rand((b, max_len, h_kv, d), 12))
        cv = llama.quantize_kv(_rand((b, max_len, h_kv, d), 13))
    else:
        ck = _rand((b, max_len, h_kv, d), 12)
        cv = _rand((b, max_len, h_kv, d), 13)
    positions = jnp.array([7, 40], jnp.int32)

    monkeypatch.setenv('XSKY_DECODE_ATTN', 'xla')
    ref, _ = llama.slot_cache_attend(q, k_new, v_new, (ck, cv),
                                     cache_positions=positions)
    monkeypatch.delenv('XSKY_DECODE_ATTN')
    out, _ = llama.slot_cache_attend(q, k_new, v_new, (ck, cv),
                                     cache_positions=positions)
    np.testing.assert_allclose(out, ref, atol=2e-4)


def test_kernel_used_under_jit_in_decode_path():
    """Smoke: the dispatch condition holds inside jit (static s==1)."""
    b, h_kv, d, max_len = 2, 1, 64, 128
    q = _rand((b, 1, 4, d), 14)
    k_new = _rand((b, 1, h_kv, d), 15)
    v_new = _rand((b, 1, h_kv, d), 16)
    ck = _rand((b, max_len, h_kv, d), 17)
    cv = _rand((b, max_len, h_kv, d), 18)
    positions = jnp.array([3, 99], jnp.int32)

    @jax.jit
    def step(q, k_new, v_new, ck, cv, positions):
        attn, cache = llama.slot_cache_attend(
            q, k_new, v_new, (ck, cv), cache_positions=positions)
        return attn, cache

    out, _ = step(q, k_new, v_new, ck, cv, positions)
    assert out.shape == (b, 1, 4, d)
    assert bool(jnp.all(jnp.isfinite(out)))


def test_softcap_and_scale_match_reference():
    """Gemma-2's cap*tanh(s/cap) + explicit scale in-kernel vs the
    masked XLA reference."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from skypilot_tpu.ops import attention as att
    from skypilot_tpu.ops import decode_attention as da
    b, h, hkv, d, maxlen = 4, 8, 4, 16, 64
    ks = jax.random.split(jax.random.PRNGKey(0), 4)
    q = jax.random.normal(ks[0], (b, 1, h, d), jnp.float32) * 3
    k = jax.random.normal(ks[1], (b, maxlen, hkv, d), jnp.float32)
    v = jax.random.normal(ks[2], (b, maxlen, hkv, d), jnp.float32)
    lengths = jax.random.randint(ks[3], (b,), 1, maxlen + 1)
    cap, scale = 20.0, 24.0 ** -0.5
    out = da.decode_attention(q, k, v, lengths, logit_softcap=cap,
                              scale=scale)
    kv_pos = jnp.arange(maxlen)[None, None, :]
    valid = kv_pos < lengths[:, None, None]
    ref = att.xla_attention_with_mask(q, k, v, valid[:, None],
                                      logit_softcap=cap, scale=scale)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5)
    # And the capped result genuinely differs from uncapped (the cap
    # is live, not a no-op).
    plain = da.decode_attention(q, k, v, lengths, scale=scale)
    assert float(jnp.abs(out - plain).max()) > 1e-4
