"""Schema validation for task YAML + layered config.

Twin of the reference's jsonschema layer (sky/utils/schemas.py, 1,456
LoC): every user-supplied YAML is validated *before* object construction
so a typo'd key or mistyped value surfaces as one actionable line naming
the bad key — with a did-you-mean suggestion — instead of a deep
AttributeError.

Errors raise exceptions.InvalidSchemaError (a ValueError) whose message
is a single line per problem, e.g.::

    task YAML: unknown field 'setupp' (did you mean 'setup'?)
    task YAML: resources.cpus: expected number or string, got list
"""
from __future__ import annotations

import difflib
from typing import Any, Dict, List, Optional

import jsonschema

from skypilot_tpu import exceptions

# ---- schema fragments ------------------------------------------------------

_STR = {'type': 'string'}
_BOOL = {'type': 'boolean'}
_NUM = {'type': 'number'}
_INT = {'type': 'integer'}
_STR_OR_NUM = {'type': ['string', 'number']}
_STR_MAP = {'type': 'object', 'additionalProperties': {
    'type': ['string', 'number', 'boolean', 'null']}}

#: accelerator_args keys are the full set the TPU deploy path reads
#: (clouds/gcp.py:111-173, utils/tpu_topology.py:161-238).
_ACCELERATOR_ARGS_SCHEMA: Dict[str, Any] = {
    'type': 'object',
    'additionalProperties': False,
    'properties': {
        'topology': _STR,
        'num_slices': _INT,
        'runtime_version': _STR,
        'use_queued_resources': _BOOL,
        # Keep in lockstep with clouds/gcp.py _apply_capacity_model.
        'provisioning_model': {
            'enum': ['standard', 'spot', 'reserved', 'flex-start',
                     'auto']},
        'reservation': _STR,
        'provision_timeout': _NUM,
        'dws_run_duration': _NUM,
        'tpu_vm': _BOOL,
    },
}

#: resources.autostop: 10 / true / {idle_minutes, down}
#: (resources.py _canonical_autostop).
_AUTOSTOP_SCHEMA: Dict[str, Any] = {
    'type': ['boolean', 'integer', 'object'],
    'additionalProperties': False,
    'properties': {
        'idle_minutes': _INT,
        'down': _BOOL,
    },
}

_JOB_RECOVERY_SCHEMA: Dict[str, Any] = {
    'type': ['string', 'object'],
    'additionalProperties': False,
    'properties': {
        'strategy': _STR,
        'max_restarts_on_errors': _INT,
    },
}

_RESOURCES_FIELDS: Dict[str, Any] = {
    'cloud': _STR,
    'instance_type': _STR,
    'cpus': _STR_OR_NUM,
    'memory': _STR_OR_NUM,
    # Object form maps accelerator name → count.
    'accelerators': {'type': ['string', 'object'],
                     'additionalProperties': _NUM},
    'accelerator_args': _ACCELERATOR_ARGS_SCHEMA,
    'use_spot': _BOOL,
    'job_recovery': _JOB_RECOVERY_SCHEMA,
    'region': _STR,
    'zone': _STR,
    'image_id': _STR,
    'disk_size': _INT,
    'disk_tier': {'enum': ['low', 'medium', 'high', 'ultra', 'best']},
    'ports': {'type': ['integer', 'string', 'array'],
              'items': {'type': ['integer', 'string']}},
    'labels': _STR_MAP,
    'autostop': _AUTOSTOP_SCHEMA,
    'volumes': {'type': 'array', 'items': {
        'type': 'object', 'additionalProperties': False,
        'properties': {
            'name': _STR,
            'path': _STR,
            'size': _INT,
            'disk_tier': {'enum': ['low', 'medium', 'high', 'ultra',
                                   'best']},
            'attach_mode': {'enum': ['read_write', 'read_only']},
            'auto_delete': _BOOL,
        },
        'required': ['name', 'path']}},
}

_RESOURCES_SCHEMA: Dict[str, Any] = {
    'type': 'object',
    'additionalProperties': False,
    'properties': {
        **_RESOURCES_FIELDS,
        'any_of': {'type': 'array', 'items': {
            'type': 'object', 'additionalProperties': False,
            'properties': _RESOURCES_FIELDS}},
        'ordered': {'type': 'array', 'items': {
            'type': 'object', 'additionalProperties': False,
            'properties': _RESOURCES_FIELDS}},
    },
}

_REPLICA_POLICY_SCHEMA: Dict[str, Any] = {
    'type': 'object',
    'additionalProperties': False,
    'properties': {
        'min_replicas': _INT,
        'max_replicas': {'type': ['integer', 'null']},
        'target_qps_per_replica': _NUM,
        'upscale_delay_seconds': _NUM,
        'downscale_delay_seconds': _NUM,
        'use_ondemand_fallback': _BOOL,
        'base_ondemand_fallback_replicas': _INT,
        'dynamic_ondemand_fallback': _BOOL,
        # Which autoscaler drives the target (service_spec.py):
        # burn_rate scales on SLO burn instead of raw QPS.
        'autoscaler': {'enum': ['request_rate', 'burn_rate']},
    },
}

#: readiness_probe: a path string, or {path, initial_delay_seconds}
#: (serve/service_spec.py:60-68).
_READINESS_PROBE_SCHEMA: Dict[str, Any] = {
    'type': ['string', 'object'],
    'additionalProperties': False,
    'properties': {
        'path': _STR,
        'initial_delay_seconds': _NUM,
    },
}

_SERVICE_SCHEMA: Dict[str, Any] = {
    'type': 'object',
    'additionalProperties': False,
    'properties': {
        'readiness_probe': _READINESS_PROBE_SCHEMA,
        'replica_policy': _REPLICA_POLICY_SCHEMA,
        'replicas': _INT,
        'port': _INT,
        # Keep in lockstep with serve/load_balancing_policies.POLICIES
        # (not imported here: schemas must stay dependency-free of the
        # serve package; test_serve pins the two lists together).
        'load_balancing_policy': {
            'enum': ['round_robin', 'least_load',
                     'telemetry_routed']},
        # TLS termination at the load balancer (service_spec.py tls).
        'tls': {
            'type': 'object',
            'additionalProperties': False,
            'properties': {
                'certfile': _STR,
                'keyfile': _STR,
            },
        },
        # Serving SLO objectives (service_spec.py SLOSpec; burn-rate
        # evaluation in serve/slo.py).
        'slo': {
            'type': 'object',
            'additionalProperties': False,
            'properties': {
                'ttft_p99_ms': _NUM,
                'availability': _NUM,
                'tpot_p50_ms': _NUM,
                'deadline_ms': _NUM,
            },
        },
    },
}

# file_mounts values: plain path string, or a storage-mount dict.
_MOUNT_SCHEMA: Dict[str, Any] = {
    'type': ['string', 'object'],
    'properties': {
        'name': _STR,
        'source': _STR,
        'store': _STR,
        'mode': {'enum': ['COPY', 'MOUNT', 'MOUNT_CACHED']},
        'persistent': _BOOL,
    },
    'additionalProperties': False,
}

TASK_SCHEMA: Dict[str, Any] = {
    'type': 'object',
    'additionalProperties': False,
    'properties': {
        'name': _STR,
        'workdir': _STR,
        'num_nodes': _INT,
        'setup': _STR,
        'run': _STR,
        'envs': _STR_MAP,
        'secrets': _STR_MAP,
        'file_mounts': {'type': 'object',
                        'additionalProperties': _MOUNT_SCHEMA},
        'resources': _RESOURCES_SCHEMA,
        'service': _SERVICE_SCHEMA,
        'config': {'type': 'object'},
    },
}

CONFIG_SCHEMA: Dict[str, Any] = {
    'type': 'object',
    'additionalProperties': False,
    'properties': {
        'admin_policy': _STR,
        'api_server': {
            'type': 'object', 'additionalProperties': False,
            'properties': {'endpoint': _STR, 'token': _STR,
                           'refresh_token': _STR}},
        'gcp': {
            'type': 'object', 'additionalProperties': False,
            'properties': {'project_id': _STR,
                           'service_account': _STR,
                           'labels': _STR_MAP}},
        'jobs': {
            'type': 'object', 'additionalProperties': False,
            'properties': {'controller': {
                'type': 'object', 'additionalProperties': False,
                'properties': {'resources': _RESOURCES_SCHEMA}}}},
        'serve': {
            'type': 'object', 'additionalProperties': False,
            'properties': {'controller': {
                'type': 'object', 'additionalProperties': False,
                'properties': {'resources': _RESOURCES_SCHEMA}}}},
        'logs': {
            'type': 'object', 'additionalProperties': False,
            'properties': {
                'store': {'enum': ['gcp', 'aws']},
                # Agent-specific knobs (logs/gcp.py, logs/aws.py).
                'labels': _STR_MAP,
                'log_glob': _STR,
                'region': _STR,
                'log_group': _STR,
            }},
        'usage': {
            'type': 'object', 'additionalProperties': False,
            'properties': {'enabled': _BOOL, 'endpoint': _STR}},
        'kubernetes': {
            'type': 'object', 'additionalProperties': False,
            'properties': {
                'networking_mode': {'enum': ['nodeport', 'portforward']},
                'fuse_proxy_image': _STR,
            }},
        'ssh': {
            'type': 'object', 'additionalProperties': False,
            'properties': {'pools_file': _STR}},
        'docker': {
            'type': 'object', 'additionalProperties': False,
            'properties': {'run_options': {
                'type': ['string', 'array'], 'items': _STR}}},
        'aws': {
            'type': 'object', 'additionalProperties': False,
            'properties': {
                'security_group': _STR,
                'labels': _STR_MAP,
            }},
    },
}


# ---- error rendering -------------------------------------------------------


def _path_str(error: jsonschema.ValidationError) -> str:
    return '.'.join(str(p) for p in error.absolute_path)


def _known_keys(schema: Dict[str, Any]) -> List[str]:
    return list(schema.get('properties', {}))


def _one_line(error: jsonschema.ValidationError) -> str:
    path = _path_str(error)
    where = f'{path}: ' if path else ''
    if error.validator == 'additionalProperties':
        # Name the offending key(s) and suggest close matches.
        known = _known_keys(error.schema)
        offending = sorted(
            set(error.instance) - set(known)) if isinstance(
                error.instance, dict) else []
        msgs = []
        for key in offending:
            hint = difflib.get_close_matches(key, known, n=1, cutoff=0.6)
            suffix = f" (did you mean '{hint[0]}'?)" if hint else (
                f' (known fields: {", ".join(sorted(known))})')
            msgs.append(f"{where}unknown field '{key}'{suffix}")
        return '; '.join(msgs) if msgs else f'{where}{error.message}'
    if error.validator == 'type':
        expected = error.validator_value
        if isinstance(expected, list):
            expected = ' or '.join(expected)
        actual = type(error.instance).__name__
        actual = {'str': 'string', 'dict': 'object', 'list': 'array',
                  'NoneType': 'null', 'bool': 'boolean',
                  'float': 'number', 'int': 'integer'}.get(actual, actual)
        return f'{where}expected {expected}, got {actual}'
    if error.validator == 'enum':
        allowed = ', '.join(repr(v) for v in error.validator_value)
        return f'{where}invalid value {error.instance!r} ' \
               f'(allowed: {allowed})'
    return f'{where}{error.message}'


def _validate(config: Dict[str, Any], schema: Dict[str, Any],
              what: str) -> None:
    if config is None:
        return
    if not isinstance(config, dict):
        raise exceptions.InvalidSchemaError(
            f'{what}: expected a mapping at the top level, got '
            f'{type(config).__name__}.')
    validator = jsonschema.Draft7Validator(schema)
    errors = sorted(validator.iter_errors(config),
                    key=lambda e: list(e.absolute_path))
    if errors:
        lines = [f'{what}: {_one_line(e)}' for e in errors]
        raise exceptions.InvalidSchemaError('\n'.join(dict.fromkeys(lines)))


def validate_task_config(config: Optional[Dict[str, Any]]) -> None:
    """Validate a task YAML dict; raises InvalidSchemaError on problems."""
    _validate(config or {}, TASK_SCHEMA, 'task YAML')


def validate_config(config: Optional[Dict[str, Any]],
                    source: str = 'config') -> None:
    """Validate a layered-config dict (user/server/project file)."""
    _validate(config or {}, CONFIG_SCHEMA, source)
