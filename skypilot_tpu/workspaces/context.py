"""Active-workspace context for the executing request.

Thread-local (the executor runs each request in a worker thread) with an
env fallback so CLI/local SDK use can pin a workspace via XSKY_WORKSPACE.
"""
from __future__ import annotations

import contextlib
import os
import threading
from typing import Iterator, Optional

DEFAULT_WORKSPACE = 'default'

_local = threading.local()


def get_active() -> str:
    ws = getattr(_local, 'workspace', None)
    if ws:
        return ws
    return os.environ.get('XSKY_WORKSPACE', DEFAULT_WORKSPACE)


def set_active(workspace: Optional[str]) -> None:
    _local.workspace = workspace


def controller_env(workspace: Optional[str]) -> dict:
    """os.environ copy with XSKY_WORKSPACE pinned to `workspace`.

    For detached controller processes (jobs/serve): the clusters they
    launch must land in the owning job's/service's workspace, not
    whatever the server process happens to have active. A None
    workspace (legacy rows) leaves the env untouched.
    """
    env = dict(os.environ)
    if workspace:
        env['XSKY_WORKSPACE'] = workspace
    return env


@contextlib.contextmanager
def active(workspace: Optional[str]) -> Iterator[None]:
    prev = getattr(_local, 'workspace', None)
    _local.workspace = workspace
    try:
        yield
    finally:
        _local.workspace = prev
