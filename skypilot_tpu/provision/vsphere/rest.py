"""vSphere (vCenter Automation REST) transport.

Role twin of the reference's pyvmomi/vsphere-automation SDK stack
(sky/adaptors/vsphere.py, sky/provision/vsphere/) on this repo's
stdlib pattern: session auth (POST /api/session with basic auth →
``vmware-api-session-id`` header) against the vCenter 7+ REST API.
Credentials from the reference-compatible
``~/.vsphere/credential.yaml`` (hostname/username/password per
vCenter; the first entry is used).
"""
from __future__ import annotations

import base64
import json
import ssl
import urllib.error
import urllib.request
from typing import Any, Dict, Optional

from skypilot_tpu import exceptions

CREDENTIALS_PATH = '~/.vsphere/credential.yaml'


class VsphereApiError(Exception):

    def __init__(self, status: int, message: str) -> None:
        super().__init__(f'{status}: {message}')
        self.status = status
        self.message = message


def load_credentials() -> Optional[Dict[str, str]]:
    import os
    path = os.path.expanduser(CREDENTIALS_PATH)
    if not os.path.exists(path):
        return None
    try:
        import yaml
        with open(path, encoding='utf-8') as f:
            doc = yaml.safe_load(f)
    except Exception:  # pylint: disable=broad-except
        return None
    entries = doc.get('vcenters') if isinstance(doc, dict) else doc
    if isinstance(entries, list) and entries:
        entry = entries[0]
    elif isinstance(doc, dict) and 'hostname' in doc:
        entry = doc
    else:
        return None
    needed = ('hostname', 'username', 'password')
    if not all(k in entry for k in needed):
        return None
    return {k: str(entry[k]) for k in entry}


def classify_error(e: VsphereApiError,
                   region: Optional[str] = None) -> Exception:
    text = e.message.lower()
    where = f' in {region}' if region else ''
    if 'insufficient' in text or 'no host is compatible' in text or \
            'out of resources' in text:
        return exceptions.CapacityError(f'vSphere capacity{where}: {e}')
    if e.status in (401, 403):
        return exceptions.PermissionError_(f'vSphere auth: {e}')
    if e.status == 400:
        return exceptions.InvalidRequestError(f'vSphere request: {e}')
    return exceptions.ProvisionError(f'vSphere API{where}: {e}')


class Transport:

    def __init__(self) -> None:
        creds = load_credentials()
        if creds is None:
            raise exceptions.PermissionError_(
                f'vSphere credentials not found (populate '
                f'{CREDENTIALS_PATH} with hostname/username/password).')
        self.host = creds['hostname']
        self._user = creds['username']
        self._password = creds['password']
        # Secure by default: TLS verification stays ON unless the site
        # explicitly opts out (`skip_verification: true` for the
        # self-signed certs common on-prem) — credentials ride basic
        # auth, so silently accepting any cert would hand them to an
        # on-path attacker.
        self._ctx = ssl.create_default_context()
        if str(creds.get('skip_verification', 'false')).lower() in \
                ('1', 'true', 'yes'):
            self._ctx.check_hostname = False
            self._ctx.verify_mode = ssl.CERT_NONE
        self._session: Optional[str] = None

    def _login(self) -> str:
        if self._session is None:
            token = base64.b64encode(
                f'{self._user}:{self._password}'.encode()).decode()
            req = urllib.request.Request(
                f'https://{self.host}/api/session', method='POST',
                headers={'Authorization': f'Basic {token}'})
            try:
                with urllib.request.urlopen(req, timeout=30,
                                            context=self._ctx) as resp:
                    self._session = json.loads(resp.read())
            except urllib.error.HTTPError as e:
                raise exceptions.PermissionError_(
                    f'vCenter login failed: {e}') from e
            except urllib.error.URLError as e:
                raise exceptions.ProvisionError(
                    f'vCenter unreachable: {e}') from e
        return self._session

    def call(self, method: str, path: str,
             body: Optional[Dict[str, Any]] = None,
             query: Optional[str] = None) -> Any:
        url = f'https://{self.host}{path}'
        if query:
            url += f'?{query}'
        data = json.dumps(body).encode() if body is not None else None
        # Two attempts: a 401 means the session expired — drop it,
        # re-login, and replay ONCE with a fresh Request (mutating the
        # old one would carry both the stale and new session headers).
        for attempt in (1, 2):
            req = urllib.request.Request(
                url, data=data, method=method,
                headers={'vmware-api-session-id': self._login(),
                         'Content-Type': 'application/json'})
            try:
                with urllib.request.urlopen(req, timeout=60,
                                            context=self._ctx) as resp:
                    payload = resp.read()
                    return json.loads(payload) if payload else {}
            except urllib.error.HTTPError as e:
                if e.code == 401 and attempt == 1:
                    self._session = None
                    continue
                try:
                    err = json.loads(e.read() or b'{}')
                    messages = err.get('messages') or []
                    message = (messages[0].get('default_message')
                               if messages else err.get('error_type',
                                                        str(e)))
                    raise VsphereApiError(e.code, str(message))
                except (ValueError, AttributeError, IndexError):
                    raise VsphereApiError(e.code, str(e)) from e
            except urllib.error.URLError as e:
                raise exceptions.ProvisionError(
                    f'vCenter unreachable: {e}') from e
        # Unreachable: every iteration returns or raises.
