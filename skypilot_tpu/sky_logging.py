"""Logging setup (twin of sky/sky_logging.py).

Env controls: XSKY_DEBUG=1 for debug level, XSKY_MINIMIZE_LOGGING=1 to quiet.
"""
from __future__ import annotations

import contextlib
import logging
import os
import sys
import threading

_FORMAT = '%(levelname).1s %(asctime)s %(filename)s:%(lineno)d] %(message)s'
_DATE_FORMAT = '%m-%d %H:%M:%S'

_setup_lock = threading.Lock()
_root_name = 'skypilot_tpu'


def _default_level() -> int:
    if os.environ.get('XSKY_DEBUG') == '1':
        return logging.DEBUG
    if os.environ.get('XSKY_MINIMIZE_LOGGING') == '1':
        return logging.WARNING
    return logging.INFO


class _LateBoundStdout:
    """Resolve `sys.stdout` at WRITE time, not handler-creation time.

    The API server's executor routes each request thread's stdout into
    that request's log by swapping `sys.stdout` (and pytest's capture
    does the same per test); a StreamHandler bound to the original
    stream object would silently bypass both.
    """

    def write(self, data: str) -> int:
        return sys.stdout.write(data)

    def flush(self) -> None:
        sys.stdout.flush()


def init_logger(name: str) -> logging.Logger:
    with _setup_lock:
        root = logging.getLogger(_root_name)
        if not root.handlers:
            handler = logging.StreamHandler(_LateBoundStdout())
            handler.setFormatter(logging.Formatter(_FORMAT, _DATE_FORMAT))
            root.addHandler(handler)
            root.setLevel(_default_level())
            root.propagate = False
    if name == '__main__':
        # `python -m skypilot_tpu.x` imports the module as __main__;
        # keep its logger under the configured root so INFO still shows.
        name = f'{_root_name}.__main__'
    return logging.getLogger(name)


def set_verbosity(level: int) -> None:
    logging.getLogger(_root_name).setLevel(level)


@contextlib.contextmanager
def silent():
    root = logging.getLogger(_root_name)
    prev = root.level
    root.setLevel(logging.ERROR)
    try:
        yield
    finally:
        root.setLevel(prev)
