"""Per-cluster job queue (sqlite), FIFO-scheduled on the head host.

Twin of sky/skylet/job_lib.py (JobStatus:147, JobScheduler:230,
FIFOScheduler:309). The cluster runtime dir (``~/.xsky`` on the head; an
arbitrary root for fake clusters, via XSKY_CLUSTER_ROOT) holds jobs.db,
cluster_info.json and logs/.
"""
from __future__ import annotations

import enum
import json
import os
import sqlite3
import time
from typing import Any, Dict, List, Optional


class JobStatus(enum.Enum):
    INIT = 'INIT'
    PENDING = 'PENDING'
    SETTING_UP = 'SETTING_UP'
    RUNNING = 'RUNNING'
    SUCCEEDED = 'SUCCEEDED'
    FAILED = 'FAILED'
    FAILED_SETUP = 'FAILED_SETUP'
    CANCELLED = 'CANCELLED'

    def is_terminal(self) -> bool:
        return self in (JobStatus.SUCCEEDED, JobStatus.FAILED,
                        JobStatus.FAILED_SETUP, JobStatus.CANCELLED)


TERMINAL_STATUSES = [s.value for s in JobStatus if s.is_terminal()]


def cluster_root() -> str:
    return os.path.expanduser(
        os.environ.get('XSKY_CLUSTER_ROOT', '~/.xsky'))


def _db(root: Optional[str] = None) -> sqlite3.Connection:
    root = root or cluster_root()
    os.makedirs(root, exist_ok=True)
    # xskylint: disable=db-discipline -- agent-side per-cluster jobs.db:
    # lives on the cluster host, never behind the control plane's WAL
    # pool or postgres routing, and needs the bespoke WAL-retry below.
    conn = sqlite3.connect(os.path.join(root, 'jobs.db'), timeout=30,
                           check_same_thread=False)
    # Converting a FRESH db to WAL needs a moment of exclusive access;
    # two job_cli subprocesses racing the first-ever connection (two
    # concurrent `exec`s against a new cluster) can hit 'database is
    # locked' here despite the busy timeout. Retry briefly, then fall
    # back to the default journal — WAL is a concurrency optimization,
    # not a correctness requirement.
    for attempt in range(10):
        try:
            conn.execute('PRAGMA journal_mode=WAL')
            # Checkpoint-time fsync (WAL contract): per-commit fsync
            # was measured at ~29 ms on overlayfs — one fsync per job
            # status poll. Same knob as the control-plane DBs.
            from skypilot_tpu.utils import db_utils
            conn.execute(
                f'PRAGMA synchronous={db_utils.sqlite_synchronous()}')
            break
        except sqlite3.OperationalError:
            if attempt == 9:
                break
            time.sleep(0.05 * (attempt + 1))
    conn.execute("""
        CREATE TABLE IF NOT EXISTS jobs (
            job_id INTEGER PRIMARY KEY AUTOINCREMENT,
            name TEXT,
            username TEXT,
            submitted_at REAL,
            started_at REAL,
            ended_at REAL,
            status TEXT,
            spec TEXT,
            pid INTEGER
        )""")
    conn.commit()
    return conn


def add_job(name: Optional[str], username: str, spec: Dict[str, Any],
            root: Optional[str] = None) -> int:
    conn = _db(root)
    cur = conn.execute(
        'INSERT INTO jobs (name, username, submitted_at, status, spec) '
        'VALUES (?, ?, ?, ?, ?)',
        (name, username, time.time(), JobStatus.PENDING.value,
         json.dumps(spec)))
    conn.commit()
    job_id = cur.lastrowid
    conn.close()
    return job_id


def set_status(job_id: int, status: JobStatus,
               root: Optional[str] = None) -> None:
    conn = _db(root)
    now = time.time()
    if status == JobStatus.RUNNING:
        conn.execute('UPDATE jobs SET status=?, started_at=? '
                     'WHERE job_id=?', (status.value, now, job_id))
    elif status.is_terminal():
        conn.execute('UPDATE jobs SET status=?, ended_at=? WHERE job_id=?',
                     (status.value, now, job_id))
    else:
        conn.execute('UPDATE jobs SET status=? WHERE job_id=?',
                     (status.value, job_id))
    conn.commit()
    conn.close()


def set_pid(job_id: int, pid: int, root: Optional[str] = None) -> None:
    conn = _db(root)
    conn.execute('UPDATE jobs SET pid=? WHERE job_id=?', (pid, job_id))
    conn.commit()
    conn.close()


def get_job(job_id: int, root: Optional[str] = None
            ) -> Optional[Dict[str, Any]]:
    conn = _db(root)
    row = conn.execute('SELECT * FROM jobs WHERE job_id=?',
                       (job_id,)).fetchone()
    conn.close()
    return _row_to_dict(row) if row else None


def get_jobs(root: Optional[str] = None) -> List[Dict[str, Any]]:
    conn = _db(root)
    rows = conn.execute(
        'SELECT * FROM jobs ORDER BY job_id DESC').fetchall()
    conn.close()
    return [_row_to_dict(r) for r in rows]


def _row_to_dict(row) -> Dict[str, Any]:
    (job_id, name, username, submitted_at, started_at, ended_at, status,
     spec, pid) = row
    return {
        'job_id': job_id,
        'job_name': name,
        'username': username,
        'submitted_at': submitted_at,
        'started_at': started_at,
        'ended_at': ended_at,
        'status': JobStatus(status),
        'spec': json.loads(spec or '{}'),
        'pid': pid,
    }


def next_job_to_run(root: Optional[str] = None) -> Optional[int]:
    """FIFO: earliest PENDING job, but only if nothing is active.

    Read-only peek; use :func:`claim_next_job` to actually take it.
    """
    conn = _db(root)
    active = conn.execute(
        "SELECT COUNT(*) FROM jobs WHERE status IN "
        "('SETTING_UP', 'RUNNING', 'INIT')").fetchone()[0]
    if active:
        conn.close()
        return None
    row = conn.execute(
        "SELECT job_id FROM jobs WHERE status='PENDING' "
        'ORDER BY job_id LIMIT 1').fetchone()
    conn.close()
    return row[0] if row else None


def claim_next_job(root: Optional[str] = None,
                   job_id: Optional[int] = None) -> Optional[int]:
    """Atomically claim the next runnable job (PENDING → INIT).

    Multiple schedulers race here (daemon tick, run-detached, the
    post-job tick); BEGIN IMMEDIATE serializes them so a job is spawned
    exactly once. With `job_id`, claim only that specific job.
    """
    conn = _db(root)
    try:
        conn.execute('BEGIN IMMEDIATE')
        active = conn.execute(
            "SELECT COUNT(*) FROM jobs WHERE status IN "
            "('SETTING_UP', 'RUNNING', 'INIT')").fetchone()[0]
        if active:
            conn.execute('ROLLBACK')
            return None
        row = conn.execute(
            "SELECT job_id FROM jobs WHERE status='PENDING' "
            'ORDER BY job_id LIMIT 1').fetchone()
        if row is None or (job_id is not None and row[0] != job_id):
            conn.execute('ROLLBACK')
            return None
        job_id = row[0]
        cur = conn.execute(
            "UPDATE jobs SET status='INIT' WHERE job_id=? AND "
            "status='PENDING'", (job_id,))
        if cur.rowcount != 1:
            conn.execute('ROLLBACK')
            return None
        conn.execute('COMMIT')
        return job_id
    finally:
        conn.close()


def claim_and_spawn(root: Optional[str] = None,
                    job_id: Optional[int] = None) -> Optional[int]:
    """Claim the next runnable job and spawn a detached job_runner for it.

    The single spawn path shared by the daemon tick, `job_cli
    run-detached` and the post-job scheduler tick.
    """
    import subprocess
    import sys
    root = root or cluster_root()
    claimed = claim_next_job(root, job_id)
    if claimed is None:
        return None
    env = dict(os.environ, XSKY_CLUSTER_ROOT=root)
    subprocess.Popen(
        [sys.executable, '-m', 'skypilot_tpu.agent.job_runner',
         str(claimed)],
        env=env, start_new_session=True,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    return claimed


def cancel_job(job_id: int, root: Optional[str] = None) -> bool:
    job = get_job(job_id, root)
    if job is None or job['status'].is_terminal():
        return False
    if job['pid']:
        try:
            os.killpg(os.getpgid(job['pid']), 15)
        except (ProcessLookupError, PermissionError, OSError):
            try:
                os.kill(job['pid'], 15)
            except (ProcessLookupError, PermissionError, OSError):
                pass
    set_status(job_id, JobStatus.CANCELLED, root)
    return True


def is_cluster_idle(root: Optional[str] = None) -> bool:
    """No pending or active job (twin of job_lib.is_cluster_idle:817)."""
    conn = _db(root)
    active = conn.execute(
        "SELECT COUNT(*) FROM jobs WHERE status NOT IN (%s)" %
        ','.join('?' * len(TERMINAL_STATUSES)),
        TERMINAL_STATUSES).fetchone()[0]
    conn.close()
    return active == 0


def last_activity_time(root: Optional[str] = None) -> float:
    conn = _db(root)
    row = conn.execute(
        'SELECT MAX(COALESCE(ended_at, started_at, submitted_at)) '
        'FROM jobs').fetchone()
    conn.close()
    return row[0] or 0.0


def log_dir_for(job_id: int, root: Optional[str] = None) -> str:
    root = root or cluster_root()
    return os.path.join(root, 'logs', f'job-{job_id}')
