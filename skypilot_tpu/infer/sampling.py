"""Token sampling: greedy / temperature / top-k / top-p, jit-friendly.

`sample_batched` is the single implementation; it handles per-row
parameters so the engine's one compiled decode step can serve a mixed
batch of greedy/sampled slots. `sample` is the scalar-params convenience
wrapper used for single requests.
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    temperature: float = 0.0     # 0 → greedy
    top_k: int = 0               # 0 → disabled
    top_p: float = 1.0           # 1 → disabled


def sample_batched(logits: jax.Array,
                   key: jax.Array,
                   temperature: jax.Array,
                   top_k: Optional[jax.Array] = None,
                   top_p: Optional[jax.Array] = None) -> jax.Array:
    """Per-row sampling. logits [B, V]; temperature/top_k/top_p [B].

    Rows with temperature <= 0 are greedy; top_k == 0 / top_p >= 1 disable
    the respective filter for that row. Branch-free: safe inside jit with
    traced parameter arrays.
    """
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    safe_t = jnp.maximum(temperature, 1e-6)[:, None]
    scaled = logits / safe_t
    v = logits.shape[-1]

    if top_k is not None:
        top_k = jnp.asarray(top_k, jnp.int32)
        sorted_desc = jnp.sort(scaled, axis=-1)[:, ::-1]
        k_idx = jnp.clip(top_k - 1, 0, v - 1)[:, None]
        kth = jnp.take_along_axis(sorted_desc, k_idx, axis=-1)
        mask = (top_k[:, None] > 0) & (scaled < kth)
        scaled = jnp.where(mask, -jnp.inf, scaled)

    if top_p is not None:
        top_p = jnp.asarray(top_p, jnp.float32)
        # Sort after the top-k mask (-inf rows sort last, prob 0) so the
        # nucleus is taken from the already-filtered distribution.
        sorted_desc = jnp.sort(scaled, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_desc, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # Smallest prefix with cumulative prob >= top_p (first always kept).
        cutoff_idx = jnp.sum(cum < top_p[:, None], axis=-1)
        cutoff_logit = jnp.take_along_axis(sorted_desc,
                                           cutoff_idx[:, None], axis=-1)
        active = top_p[:, None] < 1.0
        scaled = jnp.where(active & (scaled < cutoff_logit), -jnp.inf,
                           scaled)

    sampled = jax.random.categorical(key, scaled, axis=-1).astype(jnp.int32)
    return jnp.where(temperature > 0.0, sampled, greedy)


def sample(logits: jax.Array, key: Optional[jax.Array],
           params: SamplingParams) -> jax.Array:
    """logits [B, V] → token ids [B] (one SamplingParams for all rows)."""
    b = logits.shape[0]
    if params.temperature <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    # Disabled filters pass None so their full-vocab sorts are skipped.
    return sample_batched(
        logits, key,
        jnp.full((b,), params.temperature, jnp.float32),
        jnp.full((b,), params.top_k, jnp.int32) if params.top_k > 0
        else None,
        jnp.full((b,), params.top_p, jnp.float32) if params.top_p < 1.0
        else None)
