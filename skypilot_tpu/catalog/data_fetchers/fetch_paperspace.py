"""Generate the Paperspace catalog CSV (twin of
sky/catalog/data_fetchers/fetch_paperspace.py in role).

Static published on-demand prices for the GPU machine types in the
three public regions. No spot market.

Run: python -m skypilot_tpu.catalog.data_fetchers.fetch_paperspace
"""
from __future__ import annotations

import csv
import os
from typing import List, Tuple

# (machineType, acc_name, acc_count, vcpus, mem_gib, acc_mem, price)
_SKUS: List[Tuple[str, str, float, float, float, float, float]] = [
    ('H100', 'H100', 1, 20, 250, 80, 5.95),
    ('H100x8', 'H100', 8, 128, 1638, 640, 47.60),
    ('A100-80G', 'A100-80GB', 1, 12, 90, 80, 3.18),
    ('A100-80Gx8', 'A100-80GB', 8, 96, 720, 640, 25.44),
    ('A100', 'A100', 1, 12, 90, 40, 3.09),
    ('V100-32G', 'V100-32GB', 1, 8, 30, 32, 2.30),
    ('V100', 'V100', 1, 8, 30, 16, 2.30),
    ('RTX5000', 'RTX5000', 1, 8, 30, 16, 0.82),
    ('A4000', 'RTXA4000', 1, 8, 45, 16, 0.76),
    ('A6000', 'RTXA6000', 1, 8, 45, 48, 1.89),
    ('P4000', 'P4000', 1, 8, 30, 8, 0.51),
    ('C5', '', 0, 4, 8, 0, 0.08),
    ('C7', '', 0, 12, 30, 0, 0.30),
]

_REGIONS = ['ny2', 'ca1', 'ams1']

HEADER = ['InstanceType', 'AcceleratorName', 'AcceleratorCount', 'vCPUs',
          'MemoryGiB', 'AcceleratorMemoryGiB', 'Price', 'SpotPrice',
          'Region', 'AvailabilityZone']


def rows_static() -> List[List[str]]:
    out = []
    for itype, acc, count, vcpus, mem, acc_mem, price in _SKUS:
        for region in _REGIONS:
            out.append([itype, acc, f'{count:g}', f'{vcpus:g}',
                        f'{mem:g}', f'{acc_mem:g}', f'{price:.4f}', '0',
                        region, region])
    return out


def main() -> None:
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(here, 'data', 'paperspace', 'catalog.csv')
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, 'w', newline='', encoding='utf-8') as f:
        writer = csv.writer(f)
        writer.writerow(HEADER)
        writer.writerows(rows_static())
    print(f'Wrote {path} (static snapshot)')


if __name__ == '__main__':
    main()
