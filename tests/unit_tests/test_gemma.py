"""Gemma model family: forward/loss correctness, tied head, softcap,
trainer integration on the 8-device mesh."""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu import models
from skypilot_tpu.models import gemma
from skypilot_tpu.parallel import mesh as mesh_lib


pytestmark = pytest.mark.slow  # heavy tier: subprocess e2e / jit compiles


@pytest.fixture(scope='module')
def tiny():
    return gemma.GEMMA_TINY


@pytest.fixture(scope='module')
def params(tiny):
    return gemma.init(tiny, jax.random.PRNGKey(0))


class TestGemmaForward:

    def test_logits_shape_and_dtype(self, tiny, params):
        tokens = jnp.zeros((2, 16), jnp.int32)
        logits = gemma.forward(tiny, params, tokens)
        assert logits.shape == (2, 16, tiny.vocab_size)
        assert logits.dtype == jnp.float32

    def test_softcap_bounds_logits(self, tiny, params):
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                                    tiny.vocab_size)
        logits = gemma.forward(tiny, params, tokens)
        assert float(jnp.abs(logits).max()) <= tiny.final_logit_softcap

    def test_tied_head_no_separate_lm_head(self, params):
        assert 'lm_head' not in params
        # Tied: changing the embedding changes the head projection.

    def test_causality(self, tiny, params):
        """Changing a future token must not affect earlier logits."""
        t1 = jnp.zeros((1, 8), jnp.int32)
        t2 = t1.at[0, 7].set(5)
        l1 = gemma.forward(tiny, params, t1)
        l2 = gemma.forward(tiny, params, t2)
        np.testing.assert_allclose(np.asarray(l1[0, :7]),
                                   np.asarray(l2[0, :7]), atol=1e-5)

    def test_identity_norm_at_init(self, tiny, params):
        """(1+w) RMSNorm with zero-init weights == plain normalization;
        the forward must produce finite, non-degenerate logits."""
        tokens = jax.random.randint(jax.random.PRNGKey(2), (1, 8), 0,
                                    tiny.vocab_size)
        logits = gemma.forward(tiny, params, tokens)
        assert bool(jnp.isfinite(logits).all())
        assert float(jnp.std(logits)) > 0

    def test_loss_decreases_under_sgd(self, tiny):
        params = gemma.init(tiny, jax.random.PRNGKey(3))
        tokens = jax.random.randint(jax.random.PRNGKey(4), (2, 16), 0,
                                    tiny.vocab_size)
        targets = jnp.roll(tokens, -1, axis=1)

        loss0, grads = jax.value_and_grad(
            lambda p: gemma.loss_fn(tiny, p, tokens, targets))(params)
        params2 = jax.tree.map(
            lambda p, g: (p - 0.5 * g.astype(p.dtype)), params, grads)
        loss1 = gemma.loss_fn(tiny, params2, tokens, targets)
        assert float(loss1) < float(loss0)

    def test_registry_dispatch(self, tiny):
        assert models.module_for(tiny) is gemma
        assert models.get_config('gemma-tiny') is gemma.GEMMA_TINY
        # Llama configs are NOT claimed by gemma (distinct types).
        from skypilot_tpu.models import llama
        assert models.module_for(llama.LLAMA_TINY) is llama


class TestGemmaSharded:

    def test_trainer_step_on_mesh(self, tiny):
        """Full trainer step over a dp×tp mesh (fsdp on embed)."""
        from skypilot_tpu.train import trainer as trainer_lib
        plan = mesh_lib.MeshPlan(data=2, fsdp=2, tensor=2)
        config = trainer_lib.TrainConfig(
            model=dataclasses.replace(tiny, remat=True),
            global_batch_size=4, seq_len=32,
            optimizer='adafactor', warmup_steps=1,
            mesh_plan=plan)
        trainer = trainer_lib.Trainer(config)
        state = trainer.init_state()
        batch = trainer.synthetic_batch(0)
        # Step 1 burns the zero-LR warmup step; learning shows from
        # step 2 on.
        state, metrics = trainer.step(state, batch)
        state, metrics = trainer.step(state, batch)
        loss_a = float(metrics['loss'])
        state, metrics = trainer.step(state, batch)
        assert float(metrics['loss']) < loss_a  # learns on repeat batch

    def test_sharded_matches_single_device(self, tiny, params):
        tokens = jax.random.randint(jax.random.PRNGKey(5), (4, 16), 0,
                                    tiny.vocab_size)
        targets = jnp.roll(tokens, -1, axis=1)
        ref = gemma.loss_fn(tiny, params, tokens, targets)
        mesh = mesh_lib.build_mesh(
            mesh_lib.MeshPlan(data=2, fsdp=2, tensor=2).resolve(8))
        sharded = gemma.loss_fn(tiny, params, tokens, targets, mesh=mesh)
        np.testing.assert_allclose(float(ref), float(sharded),
                                   rtol=2e-3)


class TestGemma2:

    def test_sharded_train_step(self):
        """Gemma-2's pair scan (alternating windows + post norms)
        trains under dp/fsdp/tp sharding."""
        import numpy as np
        from skypilot_tpu.parallel import mesh as mesh_lib
        from skypilot_tpu.train import trainer as trainer_lib
        cfg = trainer_lib.TrainConfig(
            model=gemma.GEMMA2_TINY, global_batch_size=8, seq_len=32,
            optimizer='adafactor',
            mesh_plan=mesh_lib.MeshPlan(data=2, fsdp=2, tensor=2))
        tr = trainer_lib.Trainer(cfg)
        state, metrics = tr.step(tr.init_state(), tr.synthetic_batch())
        assert np.isfinite(float(metrics['loss']))

    def test_window_and_softcap_change_logits(self):
        """The gemma2 structural pieces are live: dropping the window
        or the softcap moves the logits."""
        import dataclasses as dc
        import numpy as np
        c = gemma.GEMMA2_TINY
        params = gemma.init(c, jax.random.PRNGKey(0))
        tokens = jnp.asarray([[(i * 7 + 3) % 256 for i in range(16)]],
                             jnp.int32)
        base = gemma.forward(c, params, tokens)
        no_window = gemma.forward(dc.replace(c, sliding_window=None),
                                  params, tokens)
        no_cap = gemma.forward(dc.replace(c, attn_logit_softcap=None),
                               params, tokens)
        assert float(jnp.abs(base - no_window).max()) > 1e-4
        assert float(jnp.abs(base - no_cap).max()) > 1e-4

    def test_cached_decode_matches_full_forward(self):
        """Gemma-2 serving: the pair-scan decode path (alternating
        windows + softcap in the masked attend) must reproduce
        full-forward greedy token-for-token."""
        from skypilot_tpu.infer import engine as engine_lib
        from skypilot_tpu.infer import orchestrator as orch_lib
        c = gemma.GEMMA2_TINY
        params = gemma.init(c, jax.random.PRNGKey(0))
        prompt = [5, 17, 3, 99, 42, 7, 8, 9, 10, 11, 12, 13]
        n_new = 6
        tokens = list(prompt)
        for _ in range(n_new):
            logits = gemma.forward(c, params,
                                   jnp.asarray([tokens], jnp.int32))
            tokens.append(int(jnp.argmax(logits[0, -1])))
        expected = tokens[len(prompt):]
        engine = engine_lib.InferenceEngine(
            engine_lib.EngineConfig(model=c, max_slots=2,
                                    max_target_len=32,
                                    prefill_buckets=(16,)), params)
        out = orch_lib.Orchestrator(engine).generate(
            [prompt], max_new_tokens=n_new)
        assert out[0] == expected

    def test_odd_layer_count_rejected(self):
        import dataclasses as dc
        with pytest.raises(ValueError, match='even'):
            dc.replace(gemma.GEMMA2_TINY, n_layers=3)
