"""Remote jobs-controller mode: controllers on a provisioned cluster.

Twin of the reference's jobs-controller-as-a-cluster
(sky/templates/jobs-controller.yaml.j2:1-30 + sky/jobs/utils.py
ManagedJobCodeGen): the API server provisions a dedicated controller
cluster once, then forwards every jobs verb to it by running
``python -m skypilot_tpu.jobs.remote_exec <verb>`` on the controller
head over the backend command runner (shared relay:
utils/controller_relay.py). The managed-jobs DB, the scheduler, and all
controller processes live on that cluster; the local host only relays
requests.

Enabled with XSKY_JOBS_CONTROLLER_REMOTE=1 (or =<cluster-name>).
Controller sizing comes from config key jobs.controller.resources.
"""
from __future__ import annotations

import json
import os
import tempfile
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import sky_logging
from skypilot_tpu import task as task_lib
from skypilot_tpu.utils import controller_relay

logger = sky_logging.init_logger(__name__)

_relay = controller_relay.ControllerRelay(
    env_var='XSKY_JOBS_CONTROLLER_REMOTE',
    default_cluster='xsky-jobs-controller',
    config_key=('jobs', 'controller', 'resources'),
    exec_module='skypilot_tpu.jobs.remote_exec',
    task_name='jobs-controller',
    payload_dir='.xsky/managed_tasks',
    not_up_hint='launch a managed job first.')

cluster_name = _relay.cluster_name
ensure_controller_cluster = _relay.ensure_controller_cluster


def launch(task, name: Optional[str] = None,
           wait: bool = False, timeout_s: float = 600.0,
           priority: int = 0) -> int:
    config = task_lib.Task.chain_to_config(task)
    with tempfile.NamedTemporaryFile(
            'w', suffix='.yaml', prefix='xsky-mjob-',
            delete=False) as f:
        f.write(json.dumps(config))
        local_path = f.name
    try:
        flags = (['--name', name] if name else []) + \
            (['--priority', str(int(priority))] if priority else [])
        reply = _relay.call('submit', *flags,
                            payload_file=local_path, provision=True)
    finally:
        os.unlink(local_path)
    job_id = int(reply['job_id'])
    if wait:
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            row = _relay.call('get', str(job_id))
            if row and row.get('terminal'):
                return job_id
            time.sleep(1.0)
        raise TimeoutError(f'Managed job {job_id} not terminal '
                           f'after {timeout_s}s')
    return job_id


def queue() -> List[Dict[str, Any]]:
    return _relay.call('queue')


def cancel(job_id: int) -> None:
    _relay.call('cancel', str(job_id))


def tail_logs(job_id: int) -> str:
    return _relay.call('logs', str(job_id))['logs']


def watch_logs(job_id: int, offset: int) -> Dict[str, Any]:
    return _relay.call('watch-logs', str(job_id), str(int(offset)))
