"""Autostop config + idle tracking on the head (twin of
sky/skylet/autostop_lib.py)."""
from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, Optional

from skypilot_tpu.agent import job_lib

_CONFIG_FILE = 'autostop.json'


def _path(root: Optional[str] = None) -> str:
    return os.path.join(root or job_lib.cluster_root(), _CONFIG_FILE)


def set_autostop(idle_minutes: int, down: bool,
                 root: Optional[str] = None) -> None:
    os.makedirs(root or job_lib.cluster_root(), exist_ok=True)
    with open(_path(root), 'w', encoding='utf-8') as f:
        json.dump({'idle_minutes': idle_minutes, 'down': down,
                   'set_at': time.time()}, f)


def get_autostop(root: Optional[str] = None) -> Optional[Dict[str, Any]]:
    try:
        with open(_path(root), encoding='utf-8') as f:
            return json.load(f)
    except (FileNotFoundError, json.JSONDecodeError):
        return None


def clear_autostop(root: Optional[str] = None) -> None:
    try:
        os.remove(_path(root))
    except FileNotFoundError:
        pass


def set_last_active_time_to_now(root: Optional[str] = None) -> None:
    config = get_autostop(root)
    if config is not None:
        config['set_at'] = time.time()
        with open(_path(root), 'w', encoding='utf-8') as f:
            json.dump(config, f)


def should_autostop(root: Optional[str] = None) -> bool:
    """True when the idle deadline passed with no active/pending jobs."""
    config = get_autostop(root)
    if config is None or config['idle_minutes'] < 0:
        return False
    if not job_lib.is_cluster_idle(root):
        return False
    last_active = max(job_lib.last_activity_time(root), config['set_at'])
    return time.time() - last_active >= config['idle_minutes'] * 60
