"""Storage-mount bridge between the backend and the data layer.

Placeholder until the storage subsystem lands (SURVEY §2.9 twin): raises a
clear error instead of ModuleNotFoundError mid-launch.
"""
from __future__ import annotations

from typing import Any, Dict

from skypilot_tpu import exceptions


def mount_storage_on_cluster(handle: Any,
                             storage_mounts: Dict[str, Any]) -> None:
    raise exceptions.NotSupportedError(
        'storage_mounts are not wired into the backend yet; use '
        'file_mounts, or track skypilot_tpu.data.storage.')
