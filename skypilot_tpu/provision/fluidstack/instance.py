"""Fluidstack provisioner op-set (via the nodepool base).

Behavioral twin of sky/provision/fluidstack/instance.py. Platform
facts: GPU instances by gpu_type (H100_PCIE_80GB etc.), flat regions
chosen by the scheduler (region is advisory), stop/start supported,
one public IP, all ports open, no spot market. SSH key content is
passed inline at create.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

from skypilot_tpu.provision import common
from skypilot_tpu.provision import nodepool
from skypilot_tpu.provision.fluidstack import rest

_transport_factory = rest.Transport


def set_transport_factory(factory) -> None:
    global _transport_factory
    _transport_factory = factory


class FluidstackApi(nodepool.NodeApi):
    provider_name = 'fluidstack'
    ssh_user = 'ubuntu'
    supports_stop = True
    state_map = {
        'pending': 'PENDING',
        'provisioning': 'PENDING',
        'customizing': 'PENDING',
        'starting': 'PENDING',
        'running': 'RUNNING',
        'stopping': 'STOPPING',
        'stopped': 'STOPPED',
        'terminated': None,
        'failed': None,
    }

    def __init__(self) -> None:
        self.t = _transport_factory()

    @staticmethod
    def _row(inst: Dict[str, Any]) -> Dict[str, Any]:
        return {'id': inst['id'], 'name': inst.get('name', ''),
                'status': inst.get('status', ''),
                'public_ip': inst.get('ip_address'),
                'private_ip': None}

    def list_nodes(self) -> List[Dict[str, Any]]:
        return [self._row(i)
                for i in self.t.call('GET', '/instances') or []]

    def create_node(self, name: str, region: str, zone: Optional[str],
                    node_config: Dict[str, Any]) -> str:
        del region, zone  # the platform schedules placement
        import os
        from skypilot_tpu import authentication
        _, public_key_path = authentication.get_or_generate_keys()
        with open(os.path.expanduser(public_key_path),
                  encoding='utf-8') as f:
            public_key = f.read().strip()
        reply = self.t.call('POST', '/instances', {
            'name': name,
            'gpu_type': node_config['instance_type'],
            'ssh_key': public_key,
            'operating_system_label': 'ubuntu_22_04_lts_nvidia',
        })
        return str(reply['id'])

    def delete_node(self, node_id: str) -> None:
        self.t.call('DELETE', f'/instances/{node_id}')

    def stop_node(self, node_id: str) -> None:
        self.t.call('POST', f'/instances/{node_id}/stop')

    def start_node(self, node_id: str) -> None:
        self.t.call('POST', f'/instances/{node_id}/start')

    def classify(self, e: Exception,
                 region: Optional[str] = None) -> Exception:
        if isinstance(e, rest.FluidstackApiError):
            return rest.classify_error(e, region)
        return e


def _api(provider_config: Dict[str, Any]) -> FluidstackApi:
    del provider_config
    return FluidstackApi()


def run_instances(region: str, zone: Optional[str], cluster_name: str,
                  config: common.ProvisionConfig) -> common.ProvisionRecord:
    return nodepool.run_instances(_api(config.provider_config), region,
                                  zone, cluster_name, config)


def wait_instances(region: str, cluster_name: str, state: str,
                   provider_config: Optional[Dict[str, Any]] = None,
                   timeout_s: float = 900.0,
                   poll_interval_s: float = 5.0) -> None:
    del region
    nodepool.wait_instances(_api(provider_config or {}), cluster_name,
                            state, timeout_s, poll_interval_s)


def stop_instances(cluster_name: str,
                   provider_config: Dict[str, Any]) -> None:
    nodepool.stop_instances(_api(provider_config), cluster_name)


def terminate_instances(cluster_name: str,
                        provider_config: Dict[str, Any]) -> None:
    nodepool.terminate_instances(_api(provider_config), cluster_name)


def query_instances(cluster_name: str, provider_config: Dict[str, Any]
                    ) -> Dict[str, Optional[str]]:
    return nodepool.query_instances(_api(provider_config), cluster_name)


def get_cluster_info(region: str, cluster_name: str,
                     provider_config: Dict[str, Any]
                     ) -> common.ClusterInfo:
    del region
    return nodepool.get_cluster_info(_api(provider_config), cluster_name,
                                     provider_config)


def open_ports(cluster_name: str, ports: List[str],
               provider_config: Dict[str, Any]) -> None:
    # Fluidstack instances expose all ports on their public IP.
    del cluster_name, ports, provider_config


def cleanup_ports(cluster_name: str,
                  provider_config: Dict[str, Any]) -> None:
    del cluster_name, provider_config
