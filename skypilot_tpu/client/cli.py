"""`xsky` CLI (twin of sky/client/cli/command.py click groups).

Verbs: launch, exec, status, start, stop, down, autostop, queue, logs,
cancel, ssh, check, show-gpus, cost-report,
jobs (launch/queue/cancel/logs),
serve (up/update/status/logs/down), storage (ls/delete),
api (start/stop/status/logs/cancel), users, workspaces.
"""
from __future__ import annotations

import datetime
import json
import os
import sys
import time
from typing import List, Optional, Tuple

import click

from skypilot_tpu import task as task_lib


def _parse_kv(items: Tuple[str, ...], what: str) -> dict:
    out = {}
    for item in items:
        if '=' in item:
            k, _, v = item.partition('=')
        else:
            k, v = item, os.environ.get(item)
            if v is None:
                raise click.UsageError(
                    f'{what} {item!r} has no value and is not set in the '
                    'local environment.')
        out[k] = v
    return out


def _apply_task_flags(t: task_lib.Task, name, num_nodes,
                      accelerators=None, cloud=None,
                      use_spot=None) -> task_lib.Task:
    """Apply shared CLI task-override flags to an already-built task
    (one place, so `launch` / `exec` / `jobs launch` never diverge)."""
    if name:
        t.name = name
    if num_nodes:
        t.num_nodes = num_nodes
    overrides = {}
    if accelerators:
        overrides['accelerators'] = accelerators
    if cloud:
        overrides['cloud'] = cloud
    if use_spot is not None:
        overrides['use_spot'] = use_spot
    if overrides:
        t.set_resources([r.copy(**overrides) for r in t.resources],
                        ordered=t.resources_ordered)
    return t


def _parse_env_file(path: Optional[str]) -> dict:
    """dotenv format: KEY=VALUE lines; blank lines and #-comments
    skipped; values may be single- or double-quoted."""
    if not path:
        return {}
    out = {}
    with open(path, encoding='utf-8') as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line or line.startswith('#'):
                continue
            if line.startswith('export '):
                # Shell-sourceable .env files are common; python-dotenv
                # accepts the prefix too.
                line = line[len('export '):].lstrip()
            if '=' not in line:
                raise click.UsageError(
                    f'{path}:{lineno}: expected KEY=VALUE, got '
                    f'{line!r}')
            k, _, v = line.partition('=')
            v = v.strip()
            if len(v) >= 2 and v[0] == v[-1] and v[0] in ('"', "'"):
                v = v[1:-1]
            elif ' #' in v:
                # Unquoted values lose inline comments (dotenv
                # semantics); quoted values keep their # literally.
                v = v.split(' #', 1)[0].rstrip()
            out[k.strip()] = v
    return out


def _merged_envs(envs, env_file) -> dict:
    """File entries first; explicit --env flags win (reference
    _merge_env_vars semantics)."""
    merged = _parse_env_file(env_file)
    merged.update(_parse_kv(envs, 'env'))
    return merged


def _load_task(entrypoint: str, envs, secrets, name, num_nodes,
               accelerators=None, cloud=None, use_spot=None,
               env_file=None) -> task_lib.Task:
    env_overrides = _merged_envs(envs, env_file)
    if os.path.exists(entrypoint) and entrypoint.endswith(
            ('.yaml', '.yml')):
        t = task_lib.Task.from_yaml(entrypoint,
                                    env_overrides=env_overrides,
                                    secret_overrides=_parse_kv(
                                        secrets, 'secret'))
    else:
        t = task_lib.Task(run=entrypoint, envs=env_overrides,
                          secrets=_parse_kv(secrets, 'secret'))
    return _apply_task_flags(t, name, num_nodes, accelerators, cloud,
                             use_spot)


@click.group()
@click.version_option(package_name=None,
                      version=__import__(
                          'skypilot_tpu.version',
                          fromlist=['__version__']).__version__,
                      prog_name='xsky')
def cli():
    """xsky: TPU-native multi-cloud AI workload orchestrator."""


_task_options = [
    click.option('--env', 'envs', multiple=True,
                 help='Env override KEY=VALUE (or KEY to inherit).'),
    click.option('--env-file', 'env_file', default=None,
                 type=click.Path(exists=True, dir_okay=False),
                 help='dotenv file of KEY=VALUE lines; explicit --env '
                      'flags override entries from the file.'),
    click.option('--secret', 'secrets', multiple=True,
                 help='Secret override KEY=VALUE.'),
    click.option('--name', '-n', default=None, help='Task name.'),
    click.option('--num-nodes', type=int, default=None),
    click.option('--gpus', '--accelerators', 'accelerators', default=None,
                 help="Accelerator spec, e.g. 'tpu-v5e-8' or 'A100:8'."),
    click.option('--cloud', default=None),
    click.option('--use-spot/--no-use-spot', 'use_spot', default=None),
]


def _apply(options):
    def wrap(fn):
        for option in reversed(options):
            fn = option(fn)
        return fn
    return wrap


@cli.command()
@click.argument('entrypoint')
@_apply(_task_options)
@click.option('--cluster', '-c', default=None, help='Cluster name.')
@click.option('--retry-until-up', is_flag=True, default=False)
@click.option('--idle-minutes-to-autostop', '-i', type=int, default=None)
@click.option('--down', is_flag=True, default=False,
              help='Tear down (not stop) on idle autostop.')
@click.option('--dryrun', is_flag=True, default=False)
@click.option('--detach-run', '-d', is_flag=True, default=False)
@click.option('--fast', is_flag=True, default=False,
              help='If the cluster is already UP, skip the setup phase '
                   '(twin of `sky launch --fast`).')
@click.option('--yes', '-y', is_flag=True, default=False)
def launch(entrypoint, envs, env_file, secrets, name, num_nodes,
           accelerators, cloud, use_spot, cluster, retry_until_up,
           idle_minutes_to_autostop, down, dryrun, detach_run, fast,
           yes):
    """Launch a task (provision a cluster if needed)."""
    from skypilot_tpu.client import sdk
    t = _load_task(entrypoint, envs, secrets, name, num_nodes,
                   accelerators, cloud, use_spot, env_file=env_file)
    if not yes and not dryrun:
        click.confirm(f'Launching task on cluster {cluster or "<new>"}. '
                      'Proceed?', default=True, abort=True)
    job_id, handle = sdk.launch(
        t, cluster_name=cluster, retry_until_up=retry_until_up,
        idle_minutes_to_autostop=idle_minutes_to_autostop, down=down,
        dryrun=dryrun, detach_run=detach_run, no_setup=fast)
    if dryrun:
        click.echo('Dryrun complete.')
        return
    click.echo(f'Job {job_id} on cluster '
               f'{handle.get_cluster_name()}: submitted.')


@cli.command(name='exec')
@click.argument('cluster')
@click.argument('entrypoint')
@_apply(_task_options)
@click.option('--detach-run', '-d', is_flag=True, default=False)
def exec_cmd(cluster, entrypoint, envs, env_file, secrets, name,
             num_nodes, accelerators, cloud, use_spot, detach_run):
    """Run a task on an existing cluster (no provisioning)."""
    from skypilot_tpu.client import sdk
    t = _load_task(entrypoint, envs, secrets, name, num_nodes,
                   accelerators, cloud, use_spot, env_file=env_file)
    job_id, _ = sdk.exec(t, cluster, detach_run=detach_run)
    click.echo(f'Job {job_id} on cluster {cluster}: submitted.')


def _age_str(seconds: Optional[float]) -> str:
    """Compact age: 3s / 2m / 5h / 1d (heartbeat + top columns)."""
    if seconds is None or seconds < 0:
        return '-'
    for unit, div in (('s', 1), ('m', 60), ('h', 3600), ('d', 86400)):
        if seconds < 100 * div or unit == 'd':
            return f'{seconds / div:.0f}{unit}'
    return '-'


def _cluster_heartbeats() -> dict:
    """cluster → newest hb_ts across its ranks (from the local state
    DB's workload-telemetry table; empty against a remote server)."""
    out = {}
    try:
        from skypilot_tpu import state as state_lib
        for row in state_lib.get_workload_telemetry():
            prev = out.get(row['cluster'])
            hb = row['hb_ts'] or 0
            if prev is None or hb > prev:
                out[row['cluster']] = hb
    except Exception:  # pylint: disable=broad-except
        pass
    return out


@cli.command()
@click.argument('clusters', nargs=-1)
@click.option('--refresh', '-r', is_flag=True, default=False)
@click.option('--limit', '-n', type=int, default=None,
              help='Page size (newest launches first; server-side — '
                   'a 5k-cluster fleet is not shipped to render 20 '
                   'rows).')
@click.option('--offset', type=int, default=0,
              help='Rows to skip before the page (use with --limit).')
def status(clusters, refresh, limit, offset):
    """Show clusters."""
    import time as time_lib

    from skypilot_tpu.client import sdk
    records = sdk.status(list(clusters) or None, refresh=refresh,
                         limit=limit, offset=offset)
    if not records:
        click.echo('No existing clusters.')
        return
    heartbeats = _cluster_heartbeats()
    now = time_lib.time()
    fmt = '{:<18} {:<40} {:<9} {:<10} {:<9}'
    click.echo(fmt.format('NAME', 'RESOURCES', 'STATUS', 'AUTOSTOP',
                          'HEARTBEAT'))
    for r in records:
        # Records may be local (enums/handles) or jsonified (remote API).
        handle = r.get('handle')
        if isinstance(handle, dict):
            resources = handle.get('resources') or '-'
        elif handle is not None:
            resources = str(handle.launched_resources)
        else:
            resources = '-'
        status_v = getattr(r['status'], 'value', r['status'])
        autostop_s = (f'{r["autostop"]}m' +
                      ('(down)' if r['to_down'] else '')
                      if r['autostop'] >= 0 else '-')
        hb = heartbeats.get(r['name'])
        hb_s = _age_str(now - hb) if hb else '-'
        click.echo(fmt.format(r['name'], resources[:40], status_v,
                              autostop_s, hb_s))


@cli.command()
@click.argument('cluster')
@click.option('--idle-minutes-to-autostop', '-i', type=int, default=None)
@click.option('--down', is_flag=True, default=False)
def start(cluster, idle_minutes_to_autostop, down):
    """Restart a stopped cluster."""
    from skypilot_tpu.client import sdk
    sdk.start(cluster, idle_minutes_to_autostop=idle_minutes_to_autostop,
              down=down)
    click.echo(f'Cluster {cluster} started.')


@cli.command()
@click.argument('clusters', nargs=-1, required=True)
@click.option('--yes', '-y', is_flag=True, default=False)
def stop(clusters, yes):
    """Stop cluster(s) (preserves disk; not supported for TPU pods)."""
    from skypilot_tpu.client import sdk
    for c in clusters:
        if not yes:
            click.confirm(f'Stop cluster {c}?', default=True, abort=True)
        sdk.stop(c)
        click.echo(f'Cluster {c} stopped.')


@cli.command()
@click.argument('clusters', nargs=-1, required=True)
@click.option('--yes', '-y', is_flag=True, default=False)
@click.option('--purge', is_flag=True, default=False)
def down(clusters, yes, purge):
    """Tear down cluster(s)."""
    from skypilot_tpu.client import sdk
    for c in clusters:
        if not yes:
            click.confirm(f'Tear down cluster {c}?', default=True,
                          abort=True)
        sdk.down(c, purge=purge)
        click.echo(f'Cluster {c} terminated.')


@cli.command()
@click.argument('cluster')
@click.option('--idle-minutes', '-i', type=int, default=None,
              help='Idle minutes before autostop; -1 cancels.')
@click.option('--cancel', is_flag=True, default=False,
              help='Cancel a scheduled autostop (same as -i -1; twin '
                   'of `sky autostop --cancel`).')
@click.option('--down', is_flag=True, default=False)
def autostop(cluster, idle_minutes, cancel, down):
    """Schedule (or cancel) autostop/autodown for a cluster."""
    from skypilot_tpu.client import sdk
    if cancel:
        if idle_minutes is not None:
            raise click.UsageError(
                '--cancel and --idle-minutes are mutually exclusive.')
        if down:
            raise click.UsageError(
                '--down has no effect with --cancel.')
        idle_minutes = -1
    elif idle_minutes is None:
        raise click.UsageError(
            'one of --idle-minutes/-i or --cancel is required.')
    sdk.autostop(cluster, idle_minutes, down=down)
    if idle_minutes < 0:
        click.echo(f'Autostop cancelled on {cluster}.')
    else:
        click.echo(f'Autostop set on {cluster}: {idle_minutes}m'
                   f'{" (down)" if down else ""}.')


@cli.command()
@click.argument('cluster')
def queue(cluster):
    """Show a cluster's job queue."""
    from skypilot_tpu.client import sdk
    jobs = sdk.queue(cluster)
    fmt = '{:<6} {:<16} {:<12} {:<10}'
    click.echo(fmt.format('ID', 'NAME', 'STATUS', 'USER'))
    for j in jobs:
        click.echo(fmt.format(j['job_id'], str(j['job_name'])[:16],
                              j['status'], j['username']))


@cli.command()
@click.argument('cluster')
@click.argument('port', required=False, type=int)
def endpoints(cluster, port):
    """Show reachable URLs for a cluster's opened ports."""
    from skypilot_tpu.client import sdk
    out = sdk.endpoints(cluster, port=port)
    if not out:
        click.echo('(no opened ports — set resources.ports)')
        return
    for p, url in sorted(out.items()):
        click.echo(f'{p}\t{url}' if p else url)


@cli.command()
@click.argument('cluster')
def hosts(cluster):
    """Show a cluster's per-host inventory (slice, IPs, live status)."""
    from skypilot_tpu.client import sdk
    rows = sdk.cluster_hosts(cluster)
    if not rows:
        click.echo('(no host records)')
        return
    fmt = '{:<24} {:<22} {:<6} {:<15} {:<15} {:<12}'
    click.echo(fmt.format('HOST', 'SLICE', 'INDEX', 'INTERNAL_IP',
                          'EXTERNAL_IP', 'STATUS'))
    for h in rows:
        click.echo(fmt.format(
            str(h['instance_id'])[:24], str(h['slice_id'] or '-')[:22],
            h['host_index'], h['internal_ip'] or '-',
            h['external_ip'] or '-', h['status']))


def _parse_since(value: Optional[str]) -> Optional[float]:
    """--since accepts a relative window (30s, 15m, 2h, 1d), a unix
    timestamp, or an ISO date/datetime; returns a unix-ts lower bound.
    The relative branch rides the ONE shared duration parser
    (common_utils.parse_duration_s — the same one `xsky metrics
    --since/--step` uses)."""
    if not value:
        return None
    import time as time_lib

    from skypilot_tpu.utils import common_utils
    v = value.strip()
    if v and v[-1].lower() in common_utils.DURATION_UNITS and \
            v[:-1].replace('.', '', 1).isdigit():
        return time_lib.time() - common_utils.parse_duration_s(v)
    try:
        return float(v)
    except ValueError:
        pass
    for fmt in ('%Y-%m-%dT%H:%M:%S', '%Y-%m-%d %H:%M:%S', '%Y-%m-%d'):
        try:
            return datetime.datetime.strptime(v, fmt).timestamp()
        except ValueError:
            continue
    raise click.UsageError(
        f'--since {value!r}: expected 30s/15m/2h/1d, a unix '
        'timestamp, or YYYY-MM-DD[THH:MM:SS].')


def _parse_step(value: Optional[str]) -> Optional[float]:
    """--step: a duration ('30s', '1m', '10m', bare seconds) via the
    shared parser."""
    if not value:
        return None
    from skypilot_tpu.utils import common_utils
    try:
        return common_utils.parse_duration_s(value)
    except ValueError:
        raise click.UsageError(
            f'--step {value!r}: expected a duration like 30s/1m/10m.')


@cli.command()
@click.option('--scope', default=None,
              help='Filter by scope path prefix (e.g. job/3, '
                   'cluster/my-train, service/svc, chaos).')
@click.option('--type', 'event_type', default=None,
              help='Filter by event type (e.g. job.recovered, '
                   'failover.blocked, chaos.injected).')
@click.option('--limit', '-n', type=int, default=50,
              help='Newest N events (shown oldest-first).')
@click.option('--since', default=None,
              help='Only events after this point: 30s/15m/2h/1d ago, '
                   'a unix timestamp, or an ISO date.')
@click.option('--json', 'as_json', is_flag=True, default=False,
              help='One JSON object per line (joinable with '
                   '`xsky trace --json` on trace_id).')
def events(scope, event_type, limit, since, as_json):
    """Show the recovery-event journal (preemption→recovery timeline).

    Every fault and recovery — failover blocks, managed-job preemptions
    and relaunches, serve replica churn, injected chaos — lands here
    with its scope, cause, recovery latency, and the trace it happened
    under (see `xsky trace`).
    """
    import datetime

    from skypilot_tpu import state as state_lib
    rows = state_lib.get_recovery_events(scope=scope,
                                         event_type=event_type,
                                         limit=limit,
                                         since=_parse_since(since))
    if as_json:
        for r in rows:
            click.echo(json.dumps(r, default=str))
        return
    if not rows:
        click.echo('No recovery events recorded.')
        return
    fmt = '{:<19} {:<22} {:<30} {:<20} {:>9} {:<16}'
    click.echo(fmt.format('TIME', 'EVENT', 'SCOPE', 'CAUSE', 'LATENCY',
                          'TRACE'))
    for r in rows:
        ts = datetime.datetime.fromtimestamp(
            r['ts']).strftime('%Y-%m-%d %H:%M:%S')
        latency = (f'{r["latency_s"]:.2f}s'
                   if r['latency_s'] is not None else '-')
        click.echo(fmt.format(ts, r['event_type'][:22], r['scope'][:30],
                              (r['cause'] or '-')[:20], latency,
                              (r.get('trace_id') or '-')[:16]))


@cli.group(name='metrics')
def metrics_group():
    """Metrics history: recorded time series and trend queries.

    The recorder tick samples every /metrics series (registry counters
    and histograms plus the scrape-time gauges) into a bounded
    multi-resolution store: raw points at the record interval, 1m and
    10m avg/min/max rollups. `list` shows what has been recorded;
    `query` folds one metric into a bucketed trend (counter-aware
    rate, windowed histogram quantiles) with a sparkline.
    """


@metrics_group.command(name='list')
@click.option('--prefix', default=None,
              help='Only metric names starting with this prefix.')
@click.option('--since', default=None,
              help='Only series sampled after this point '
                   '(30s/15m/2h/1d ago, a unix timestamp, or an ISO '
                   'date).')
@click.option('--limit', '-n', type=int, default=100,
              help='Series to show.')
@click.option('--json', 'as_json', is_flag=True, default=False,
              help='One JSON object per series.')
def metrics_list_cmd(prefix, since, limit, as_json):
    """List recorded metric series (name, labels, points, freshness)."""
    import time as time_lib

    from skypilot_tpu.client import sdk
    rows = sdk.metrics_list(prefix=prefix, since=_parse_since(since),
                            limit=limit)
    if as_json:
        for r in rows:
            click.echo(json.dumps(r, default=str))
        return
    if not rows:
        click.echo('No metric points recorded yet (the recorder runs '
                   'on the API server tick; see xsky metrics query).')
        return
    now = time_lib.time()
    fmt = '{:<44} {:<34} {:<9} {:>7} {:>8}'
    click.echo(fmt.format('NAME', 'LABELS', 'KIND', 'POINTS', 'AGE'))
    for r in rows:
        labels = ','.join(f'{k}={v}' for k, v in
                          sorted(r['labels'].items()))
        click.echo(fmt.format(
            r['name'][:44], (labels or '-')[:34], r['kind'] or '-',
            r['points'], _age_str(now - (r['newest_ts'] or 0))))


@metrics_group.command(name='query')
@click.argument('name')
@click.option('--label', 'label_filters', multiple=True,
              help='Series filter k=v (subset match; repeatable — '
                   'e.g. --label cluster=train --label rank=0).')
@click.option('--since', default='1h',
              help='Window start: 30s/15m/2h/1d ago, a unix '
                   'timestamp, or an ISO date (default: 1h).')
@click.option('--until', default=None,
              help='Window end (same forms; default: now).')
@click.option('--step', default=None,
              help='Bucket width (30s/1m/10m or bare seconds; '
                   'default: the tier\'s native step).')
@click.option('--agg', default='avg',
              type=click.Choice(['avg', 'min', 'max', 'sum', 'count',
                                 'last', 'rate', 'p50', 'p90', 'p95',
                                 'p99']),
              help='Bucket aggregation; rate is counter-aware, '
                   'p* are windowed histogram quantiles.')
@click.option('--res', default=None,
              type=click.Choice(['raw', '1m', '10m']),
              help='Resolution tier (default: finest tier covering '
                   'the window).')
@click.option('--json', 'as_json', is_flag=True, default=False,
              help='The full query result as one JSON object.')
def metrics_query_cmd(name, label_filters, since, until, step, agg,
                      res, as_json):
    """Trend-query one metric: bucketed values plus a sparkline.

    Examples:

        xsky metrics query xsky_dispatch_gap_ratio --label rank=0

        xsky metrics query xsky_requests_total --agg rate --step 1m

        xsky metrics query xsky_workload_step_seconds --agg p99
    """
    from skypilot_tpu.client import sdk
    from skypilot_tpu.utils import metrics_history
    labels = _parse_kv(label_filters, '--label')
    result = sdk.metrics_query(name, labels=labels or None,
                               since=_parse_since(since),
                               until=_parse_since(until),
                               step=_parse_step(step), agg=agg,
                               res=res)
    if as_json:
        click.echo(json.dumps(result, default=str))
        return
    points = result.get('points') or []
    values = [v for _, v in points if v is not None]
    span = result['until'] - result['since']
    click.echo(f'{result["name"]} agg={result["agg"]} '
               f'res={result["res"]} step={result["step"]:g}s '
               f'window={span:.0f}s '
               + (f'labels={labels} ' if labels else ''))
    if not values:
        click.echo('  (no points in window — is the recorder '
                   'running? `xsky metrics list` shows coverage)')
        return
    spark = metrics_history.sparkline([v for _, v in points],
                                      width=60)
    click.echo(f'  {spark}')
    click.echo(f'  min={min(values):g} avg='
               f'{sum(values) / len(values):g} max={max(values):g} '
               f'last={values[-1]:g} '
               f'({len(values)} points, '
               f'{len(points) - len(values)} empty buckets)')


@cli.command(name='fleet')
@click.option('--decisions', '-n', 'decision_limit', type=int,
              default=10,
              help='Recent fleet decisions to show (admissions, '
                   'elastic shrinks/grow-backs).')
@click.option('--json', 'as_json', is_flag=True, default=False,
              help='One JSON object (queue, shares, pressure, '
                   'decisions).')
def fleet_cmd(decision_limit, as_json):
    """Fleet scheduler state: fair-share queue, placement pressure,
    recent decisions.

    The queue section shows the scheduler's schedule-state depths and
    each workspace's fair-share position (weight from
    XSKY_FLEET_SHARES, running = controllers holding capacity, waiting
    = queued). The pressure section is the shared placement scorer's
    current view — recency-decayed preemption/capacity pressure per
    journalled (cloud, region, zone, sku); entries at or above the
    block threshold are avoided by job launches, serve spot placement,
    and elastic grow-back probes alike. Decisions come from the
    bounded fleet_decisions table.
    """
    from skypilot_tpu import state as state_lib
    from skypilot_tpu.jobs import fleet as fleet_lib
    from skypilot_tpu.jobs import state as jobs_state
    counts = {s.value.lower(): n for s, n in
              jobs_state.schedule_state_counts().items()}
    shares = fleet_lib.workspace_shares()
    running = jobs_state.active_counts_by_workspace()
    waiting_rows = jobs_state.get_waiting_jobs()
    waiting: dict = {}
    for row in waiting_rows:
        waiting[row['workspace']] = waiting.get(row['workspace'], 0) + 1
    workspaces = sorted(set(shares) | set(running) | set(waiting))
    pressure = fleet_lib.pressure_map()
    hot = [{**keys, 'pressure': round(pressure.at(**keys), 4)}
           for keys in pressure.keys_over(0.0)[:20]]
    decisions = state_lib.get_fleet_decisions(limit=decision_limit)
    if as_json:
        click.echo(json.dumps({
            'queue': counts,
            'workspaces': [{
                'workspace': ws,
                'weight': shares.get(ws, 1.0),
                'running': running.get(ws, 0),
                'waiting': waiting.get(ws, 0),
            } for ws in workspaces],
            'pressure': hot,
            'block_threshold': fleet_lib.block_threshold(),
            'decisions': decisions,
        }, default=str))
        return
    click.echo('Queue: ' + '  '.join(
        f'{name}={counts.get(name, 0)}'
        for name in ('waiting', 'launching', 'alive', 'done')))
    if workspaces:
        fmt = '{:<16} {:>7} {:>8} {:>8}'
        click.echo(fmt.format('WORKSPACE', 'WEIGHT', 'RUNNING',
                              'WAITING'))
        for ws in workspaces:
            click.echo(fmt.format(ws[:16], f'{shares.get(ws, 1.0):g}',
                                  running.get(ws, 0),
                                  waiting.get(ws, 0)))
    if hot:
        click.echo(f'\nPlacement pressure (decayed; blocked at '
                   f'>= {fleet_lib.block_threshold():g}):')
        fmt = '{:<10} {:<14} {:<18} {:<14} {:>9}'
        click.echo(fmt.format('CLOUD', 'REGION', 'ZONE', 'SKU',
                              'PRESSURE'))
        for row in hot:
            click.echo(fmt.format(
                (row.get('cloud') or '-')[:10],
                (row.get('region') or '-')[:14],
                (row.get('zone') or '-')[:18],
                (row.get('sku') or '-')[:14],
                f"{row['pressure']:.3f}"))
    if decisions:
        import datetime
        click.echo('\nRecent decisions:')
        fmt = '{:<19} {:<7} {:<6} {:<12} {:<18} {:>7}'
        click.echo(fmt.format('TIME', 'KIND', 'JOB', 'WORKSPACE',
                              'ZONE', 'SCORE'))
        for d in decisions:
            ts = datetime.datetime.fromtimestamp(
                d['ts']).strftime('%Y-%m-%d %H:%M:%S')
            click.echo(fmt.format(
                ts, (d['kind'] or '-')[:7],
                str(d['job_id']) if d['job_id'] is not None else '-',
                (d['workspace'] or '-')[:12],
                (d['zone'] or '-')[:18],
                f"{d['score']:.2f}" if d['score'] is not None else '-'))


def _trace_children(spans):
    """span_id → [child spans] (children ordered by start time), plus
    the roots/orphans list. An orphan (parent recorded but missing —
    pruned, or the parent never finished) renders as a root, marked."""
    by_id = {s['span_id']: s for s in spans}
    children = {}
    roots = []
    for s in spans:
        parent = s['parent_span_id']
        if parent and parent in by_id:
            children.setdefault(parent, []).append(s)
        else:
            s['orphan'] = bool(parent)
            roots.append(s)
    return children, roots


def _critical_path(roots, children):
    """Span ids on the critical path: from each root, repeatedly
    descend into the child that finished last — the chain that gated
    the trace's wall-clock."""
    marked = set()
    for root in roots:
        node = root
        while node is not None:
            marked.add(node['span_id'])
            kids = children.get(node['span_id'])
            node = max(kids, key=lambda s: s['end_ts'] or 0) \
                if kids else None
    return marked


def _sibling_stragglers(children):
    """Span ids slower than 1.5x their sibling-group median (groups =
    same parent + same name, ≥3 members: fan-out ranks)."""
    straggler_ids = set()
    for kids in children.values():
        groups = {}
        for s in kids:
            groups.setdefault(s['name'], []).append(s)
        for group in groups.values():
            if len(group) < 3:
                continue
            durs = sorted(
                (s['end_ts'] or 0) - (s['start_ts'] or 0)
                for s in group)
            median = durs[len(durs) // 2]
            for s in group:
                if median > 0 and ((s['end_ts'] or 0) -
                                   (s['start_ts'] or 0)) > 1.5 * median:
                    straggler_ids.add(s['span_id'])
    return straggler_ids


@cli.command(name='trace')
@click.argument('target')
@click.option('--json', 'as_json', is_flag=True, default=False,
              help='Raw span rows as JSON (joinable with `xsky events '
                   '--json` on trace_id).')
@click.option('--limit', type=int, default=5000,
              help='Max spans to load.')
def trace_cmd(target, as_json, limit):
    """Render a trace's span waterfall (request id, cluster, or trace
    id).

    Shows where a launch/request spent its time: per-phase durations,
    parent/child nesting, the critical path (marked `*`), failed spans
    (`!`), and per-phase straggler ranks. Trace ids come from `xsky
    events` rows, `/metrics` drill-downs, or the request id returned
    by any API verb.
    """
    from skypilot_tpu import state as state_lib
    spans = state_lib.get_spans(target, limit=limit)
    trace_id = target
    if not spans:
        # A request id resolves through the request row's minted
        # trace_id — valid the moment the POST returns, even while
        # the request is still running (its root span lands only at
        # completion).
        ids = []
        try:
            from skypilot_tpu.server import requests_db
            minted = requests_db.get_trace_id(target)
            if minted:
                ids = [minted]
        except Exception:  # pylint: disable=broad-except
            pass
        ids = ids or state_lib.find_trace_ids(target)
        if not ids:
            raise click.ClickException(
                f'No trace matches {target!r} (searched trace ids, '
                'request ids, cluster names and span attributes).')
        if len(ids) > 1:
            # stderr, so `--json | jq` pipelines stay parseable.
            click.echo(f'{len(ids)} traces match {target!r}; '
                       'showing the newest. Others: '
                       + ', '.join(ids[1:]), err=True)
        trace_id = ids[0]
        spans = state_lib.get_spans(trace_id, limit=limit)
        if not spans:
            # A just-accepted request: trace minted, no span finished
            # (the buffer flushes per phase / at completion).
            click.echo(f'Trace {trace_id}: no finished spans yet '
                       '(request still in its first phase?). '
                       'Re-run in a moment.')
            return
    if as_json:
        for s in spans:
            click.echo(json.dumps(s, default=str))
        return
    children, roots = _trace_children(spans)
    critical = _critical_path(roots, children)
    stragglers = _sibling_stragglers(children)
    t0 = min(s['start_ts'] for s in spans)
    t1 = max(s['end_ts'] or s['start_ts'] for s in spans)
    total = max(t1 - t0, 1e-9)
    errors = sum(1 for s in spans if s['status'] != 'OK')
    click.echo(f'TRACE {trace_id} — {len(spans)} span(s), '
               f'{total:.2f}s wall-clock'
               + (f', {errors} error(s)' if errors else ''))
    click.echo('(`*` critical path, `!` error, `~` straggler rank '
               '>1.5x phase median)')
    width = 30
    fmt = '{:>9} {:<32} {}'
    click.echo(fmt.format('DUR', 'WATERFALL', 'SPAN'))

    def render(span, depth):
        start = span['start_ts'] - t0
        dur = max((span['end_ts'] or span['start_ts']) -
                  span['start_ts'], 0.0)
        lead = min(int(start / total * width), width - 1)
        bar_len = max(1, min(int(round(dur / total * width)),
                             width - lead))
        bar = ' ' * lead + '#' * bar_len
        flags = ''
        if span['span_id'] in critical:
            flags += ' *'
        if span['status'] != 'OK':
            flags += ' !'
        if span['span_id'] in stragglers:
            flags += ' ~'
        attrs = span.get('attrs') or {}
        note = ''
        if span.get('orphan'):
            note = ' (orphan)'
        elif 'rank' in attrs:
            note = f' [rank {attrs["rank"]}]'
        elif 'slowest_rank' in attrs:
            note = (f' [slowest rank {attrs["slowest_rank"]}: '
                    f'{attrs.get("slowest_s", 0):.2f}s]')
        click.echo(fmt.format(
            f'{dur:.3f}s', bar,
            '  ' * depth + span['name'] + note + flags))
        for child in children.get(span['span_id'], []):
            render(child, depth + 1)

    for root in roots:
        render(root, 0)
    # Per-phase slowest-rank digest: the tuning table for fan-out
    # phases (which host gated each phase).
    fanouts = [s for s in spans
               if (s.get('attrs') or {}).get('slowest_rank')
               is not None]
    if fanouts:
        click.echo('')
        click.echo('Fan-out phases (slowest rank gates the phase):')
        pfmt = '  {:<28} {:>12} {:>10} {:>10}  {}'
        click.echo(pfmt.format('PHASE', 'SLOWEST RANK', 'SLOWEST',
                               'MEDIAN', 'STRAGGLERS'))
        for s in fanouts:
            attrs = s['attrs']
            lagging = attrs.get('stragglers') or []
            click.echo(pfmt.format(
                s['name'][:28], attrs['slowest_rank'],
                f"{attrs.get('slowest_s', 0):.3f}s",
                f"{attrs.get('median_s', 0):.3f}s",
                ','.join(str(r) for r in lagging) or '-'))


def _trend_spark(name: str, labels: dict, width: int = 12,
                 window_s: float = 1800.0) -> Optional[str]:
    """Sparkline of one series' recent history (the --trend columns),
    or None when nothing was recorded. Local read: trends come from
    this host's metric_points table, like the rest of the top/slo
    row data."""
    import time as time_lib

    from skypilot_tpu.utils import metrics_history
    from skypilot_tpu.utils import tracing
    with tracing.span('metrics.query', kind='trend', metric=name):
        points = metrics_history.series(
            name, labels=labels, since=time_lib.time() - window_s)
    values = [v for _, v in points]
    if not any(v is not None for v in values):
        return None
    return metrics_history.sparkline(values, width=width)


def _rank_trend_maps(names: List[str], window_s: float = 1800.0
                     ) -> dict:
    """ONE metric_points read per metric name →
    {name: {(cluster, job, rank): sparkline}} — `xsky top --trend`
    must not rescan the table twice per rank per refresh (a --watch
    loop over N ranks would pay 2N full window scans every 2 s)."""
    import time as time_lib

    from skypilot_tpu import state as state_lib
    from skypilot_tpu.utils import metrics_history
    from skypilot_tpu.utils import tracing
    out: dict = {}
    with tracing.span('metrics.query', kind='trend'):
        for name in names:
            groups: dict = {}
            for row in state_lib.get_metric_points(
                    name=name, res='raw',
                    since=time_lib.time() - window_s):
                labels = row['labels']
                key = (labels.get('cluster'), labels.get('job'),
                       labels.get('rank'))
                groups.setdefault(key, []).append(row['value'])
            out[name] = {key: metrics_history.sparkline(values,
                                                        width=12)
                         for key, values in groups.items()}
    return out


def _top_rows(cluster: Optional[str],
              trend: bool = False) -> List[dict]:
    """Latest per-rank telemetry rows annotated with ages + straggler
    flags + the rank's step-anatomy profile block (shared by the table
    and --json renderers)."""
    from skypilot_tpu import state as state_lib
    from skypilot_tpu.agent import goodput as goodput_lib
    from skypilot_tpu.agent import telemetry
    rows = state_lib.get_workload_telemetry(cluster=cluster)
    profs = {(p['cluster'], p['job_id'], p['rank']): p
             for p in state_lib.get_profiles(cluster=cluster,
                                             kind='summary')}
    # Flight-recorder anatomy (newest-first): per-rank data-wait share
    # plus the gang's cross-rank step skew for the DATA%/SKEW columns.
    anat_by_gang: dict = {}
    share_by_rank: dict = {}
    try:
        for arow in state_lib.get_train_anatomy(cluster=cluster,
                                                limit=512):
            anat_by_gang.setdefault(
                (arow['cluster'], arow['job_id']), []).append(arow)
            key = (arow['cluster'], arow['job_id'], arow['rank'])
            bucket = share_by_rank.setdefault(key, [])
            if len(bucket) < 32:
                bucket.append(arow)
        for key, recs in share_by_rank.items():
            wall = sum(r.get('wall_s') or 0.0 for r in recs)
            data = sum((r.get('phases') or {}).get('data_wait', 0.0)
                       for r in recs)
            share_by_rank[key] = (min(1.0, data / wall)
                                  if wall > 0 else None)
    except Exception:  # pylint: disable=broad-except
        anat_by_gang, share_by_rank = {}, {}
    trend_maps = _rank_trend_maps(
        ['xsky_dispatch_gap_ratio',
         'xsky_workload_last_heartbeat_age_seconds']) if trend else {}
    by_cluster: dict = {}
    for row in rows:
        by_cluster.setdefault((row['cluster'], row['job_id']),
                              {})[row['rank']] = row
    out = []
    for (cl, job_id), ranks in sorted(by_cluster.items()):
        lagging = telemetry.stragglers(ranks)
        skew = telemetry.rank_skew(ranks)
        goodput = telemetry.goodput_for_cluster(cl, ranks)
        # Decomposed loss digest from the newest persisted ledger
        # roll-up (written by the jobs controller's monitor loop):
        # WHERE the non-productive time went, next to the ratio.
        ledger_rows = state_lib.get_goodput_ledger(cluster=cl,
                                                   kind='job', limit=1)
        loss = (goodput_lib.loss_summary(ledger_rows[0]['seconds'])
                if ledger_rows else '-')
        anat_skew = None
        anat = anat_by_gang.get((cl, job_id))
        if anat:
            try:
                from skypilot_tpu.agent import flight_recorder
                digest = flight_recorder.waterfall_digest(
                    flight_recorder.gang_waterfall(anat))
                anat_skew = digest.get('mean_skew_s')
            except Exception:  # pylint: disable=broad-except
                anat_skew = None
        for rank, row in sorted(ranks.items()):
            pulled = row['ts'] or 0
            prof = profs.get((cl, job_id, rank))
            spark = None
            if trend:
                # Dispatch-gap history is the host-bound trend; ranks
                # without a profiler fall back to heartbeat-age drift
                # (the dead-rank signature).
                key = (cl, str(job_id), str(rank))
                spark = trend_maps[
                    'xsky_dispatch_gap_ratio'].get(key) or \
                    trend_maps[
                        'xsky_workload_last_heartbeat_age_seconds'
                    ].get(key)
            out.append(dict(
                row,
                # Checkpoint freshness at pull time (None when the
                # rank never snapshotted): the replay exposure.
                ckpt_age_s=(round(pulled - row['ckpt_ts'], 1)
                            if row.get('ckpt_ts') else None),
                # Ages at PULL time: the spool truth when last read
                # (age_s says how stale the row itself is).
                hb_age_s=round(pulled - (row['hb_ts'] or 0), 1),
                progress_age_s=round(
                    pulled - (row['last_progress_ts'] or 0), 1),
                straggler=rank in lagging,
                rank_skew=skew,
                goodput=goodput.get('goodput'),
                goodput_loss=loss,
                dispatch_gap_ratio=(prof or {}).get(
                    'dispatch_gap_ratio'),
                # Flight-recorder anatomy: input-pipeline share of
                # recent step wall (data starvation) + the gang's mean
                # cross-rank compute skew.
                data_share=share_by_rank.get((cl, job_id, rank)),
                anatomy_skew_s=anat_skew,
                trend=spark,
                # Full step-anatomy block for --json consumers.
                profile=prof))
    return out


@cli.command(name='top')
@click.argument('cluster', required=False)
@click.option('--watch', '-w', is_flag=True, default=False,
              help='Refresh continuously (Ctrl-C to stop).')
@click.option('--interval', type=float, default=2.0,
              help='Refresh interval with --watch (seconds).')
@click.option('--trend', 'show_trend', is_flag=True, default=False,
              help='Add a TREND sparkline per rank from the metrics '
                   'history plane (dispatch-gap ratio; heartbeat age '
                   'when no profiler runs).')
@click.option('--json', 'as_json', is_flag=True, default=False,
              help='One JSON object per rank row (joinable with '
                   '`xsky events --json` / `xsky trace --json`).')
def top(cluster, watch, interval, show_trend, as_json):
    """Live per-rank workload view: phase, step, step time, tokens/s,
    heartbeat age, and the stall verdict for every gang rank.

    Rows come from the workload-telemetry table (agents spool samples
    on each host; the gang backend and jobs controller pull them every
    poll interval). A `hung` verdict means the rank heartbeats without
    progressing (the backend_init failure mode); `dead` means the
    heartbeat itself went stale. `~` marks stragglers (step-time >1.5x
    the gang median).
    """
    import time as time_lib

    from skypilot_tpu.agent import profiler as profiler_lib

    def render_once():
        rows = _top_rows(cluster, trend=show_trend)
        if as_json:
            for row in rows:
                click.echo(json.dumps(row, default=str))
            return
        if not rows:
            click.echo('No workload telemetry recorded'
                       + (f' for {cluster!r}.' if cluster else '.'))
            return
        now = time_lib.time()
        fmt = ('{:<20} {:>4} {:>5} {:<6} {:>8} {:>10} {:>9} {:>9} '
               '{:>5} {:>8} {:>7} {:>8} {:<7}')
        if show_trend:
            fmt += ' {:<12}'
        header = ['CLUSTER', 'JOB', 'RANK', 'PHASE', 'STEP',
                  'STEP_TIME', 'TOK/S', 'DISPATCH%', 'DATA%',
                  'SKEW', 'MEM_MB', 'HB_AGE', 'VERDICT']
        if show_trend:
            header.append('TREND')
        click.echo(fmt.format(*header))
        for row in rows:
            step_time = (f'{row["step_time_ema_s"]:.3f}s'
                         if row['step_time_ema_s'] else '-')
            if row['straggler']:
                step_time += '~'
            tps = (f'{row["tokens_per_sec"]:,.0f}'
                   if row['tokens_per_sec'] else '-')
            disp = (f'{row["dispatch_gap_ratio"]:.0%}'
                    if row.get('dispatch_gap_ratio') is not None
                    else '-')
            data = (f'{row["data_share"]:.0%}'
                    if row.get('data_share') is not None else '-')
            skew_s = (f'{row["anatomy_skew_s"] * 1e3:.1f}ms'
                      if row.get('anatomy_skew_s') is not None
                      else '-')
            mem = (f'{row["host_mem_mb"]:.0f}'
                   if row['host_mem_mb'] else '-')
            cells = [
                row['cluster'][:20], str(row['job_id'] or '-'),
                row['rank'], (row['phase'] or '-')[:6],
                str(row['step'] if row['step'] is not None else '-'),
                step_time, tps, disp, data, skew_s, mem,
                _age_str(row['hb_age_s']),
                row['verdict'] or '-']
            if show_trend:
                cells.append(row.get('trend') or '-')
            click.echo(fmt.format(*cells))
        # Per-gang summary: skew + goodput + HBM + data freshness.
        gangs = sorted({(r['cluster'], r['job_id']) for r in rows},
                       key=str)
        for key in gangs:
            group = [r for r in rows
                     if (r['cluster'], r['job_id']) == key]
            first = group[0]
            stalls = sum(1 for r in group if r['verdict'] != 'ok')
            goodput = (f'{first["goodput"]:.1%}'
                       if first.get('goodput') is not None else '-')
            peaks = [profiler_lib.hbm_watermark(r.get('profile') or {})
                     for r in group]
            peaks = [p for p in peaks if p]
            hbm = (f'{max(peaks) / (1 << 30):.1f}GiB'
                   if peaks else '-')
            # Newest snapshot across the gang: step @ age (the gang's
            # replay exposure on the next failure); '-' = no rank has
            # checkpointed yet.
            snaps = [(r['ckpt_step'], r['ckpt_age_s']) for r in group
                     if r.get('ckpt_step') is not None]
            ckpt = (f'{max(snaps)[0]}@{_age_str(max(snaps)[1])}'
                    if snaps else '-')
            click.echo(
                f'  {first["cluster"]} job {first["job_id"]}: '
                f'{len(group)} rank(s), skew={first["rank_skew"]}, '
                f'goodput={goodput}, '
                f'loss={first.get("goodput_loss") or "-"}, '
                f'ckpt={ckpt}, hbm={hbm}, stalled={stalls}, '
                f'pulled {_age_str(now - (first["ts"] or 0))} ago')

    if not watch:
        render_once()
        return
    try:
        while True:
            click.clear()
            render_once()
            time_lib.sleep(max(interval, 0.2))
    except KeyboardInterrupt:
        pass


# Waterfall glyph per attribution category (`xsky goodput`): one
# character of bar per share of wall time.
_GOODPUT_GLYPHS = (
    ('productive', '#'), ('restart_replay', 'R'),
    ('shrunk_capacity', 'c'), ('stalled', 'x'), ('queue_wait', 'q'),
    ('provision', 'p'), ('setup_bootstrap', 'b'), ('init_barrier', 'i'),
    ('recovery', 'r'), ('idle', '.'), ('unattributed', '?'),
)


def _goodput_bar(seconds: dict, total: float, width: int = 44) -> str:
    """Stacked category bar: glyphs proportional to each category's
    share of `total`, largest-remainder rounded so the bar length is
    stable."""
    if total <= 0:
        return ''
    shares = [(glyph, (seconds.get(cat) or 0.0) / total * width)
              for cat, glyph in _GOODPUT_GLYPHS]
    cells = [(glyph, int(share)) for glyph, share in shares]
    rest = sorted(((share - int(share), i)
                   for i, (_, share) in enumerate(shares)),
                  reverse=True)
    short = width - sum(n for _, n in cells)
    for _, i in rest[:max(0, short)]:
        cells[i] = (cells[i][0], cells[i][1] + 1)
    return ''.join(glyph * n for glyph, n in cells)


def _render_goodput_ledger(ledger: dict) -> None:
    from skypilot_tpu.agent import goodput as goodput_lib
    wall = ledger.get('wall_s') or 0.0
    ratio = ledger.get('goodput')
    click.echo(
        f'GOODPUT {ledger["cluster"]} — wall {wall:.1f}s, '
        f'{ledger.get("full_ranks") or 0} rank(s), '
        f'{len(ledger.get("incarnations") or ())} incarnation(s), '
        f'goodput=' + (f'{ratio:.1%}' if ratio is not None else '-'))
    legend = ' '.join(f'{glyph}={cat}'
                      for cat, glyph in _GOODPUT_GLYPHS)
    click.echo(f'({legend})')
    incs = ledger.get('incarnations') or []
    if incs:
        fmt = '{:>4} {:>5} {:>11} {:>7} {:>8} {:>8} {:>8}  {}'
        click.echo(fmt.format('INC', 'RANKS', 'WINDOW', 'RESUME',
                              'MAXSTEP', 'REPLAYED', 'GOODPUT',
                              'WATERFALL'))
        w0 = (ledger.get('window') or [0])[0] or 0
        for inc in incs:
            seconds = inc.get('seconds') or {}
            inc_total = sum(seconds.values())
            productive = seconds.get('productive', 0.0)
            ratio = (f'{productive / inc_total:.0%}'
                     if inc_total > 0 else '-')
            start = (inc.get('start_ts') or w0) - w0
            end_ts = inc.get('end_ts')
            window = (f'{start:.0f}-{end_ts - w0:.0f}s'
                      if end_ts else f'{start:.0f}s-')
            click.echo(fmt.format(
                inc['incarnation'], inc.get('ranks') or 0, window,
                inc.get('resume_step')
                if inc.get('resume_step') is not None else '-',
                inc.get('max_step')
                if inc.get('max_step') is not None else '-',
                inc.get('replayed_steps') or 0, ratio,
                _goodput_bar(seconds, inc_total)))
    totals = ledger.get('totals') or {}
    attributed = sum(totals.values())
    if attributed > 0:
        click.echo('')
        fmt = '  {:<16} {:>10} {:>7}  {}'
        click.echo(fmt.format('CAUSE', 'SECONDS', 'SHARE', ''))
        for cat in goodput_lib.CATEGORIES:
            value = totals.get(cat) or 0.0
            if value <= 0:
                continue
            share = value / attributed
            click.echo(fmt.format(cat, f'{value:.1f}',
                                  f'{share:.1%}',
                                  '#' * max(1, int(share * 30))))


@cli.command(name='goodput')
@click.argument('cluster', required=False)
@click.option('--fleet', 'fleet_view', is_flag=True, default=False,
              help='Fleet rollup of the latest persisted per-job '
                   'ledgers (loss-by-cause across live clusters).')
@click.option('--json', 'as_json', is_flag=True, default=False,
              help='One JSON object (the ledger, or the fleet '
                   'report).')
def goodput_cmd(cluster, fleet_view, as_json):
    """Goodput attribution ledger: every wall-clock second, by cause.

    With CLUSTER: a live fold over the planes' history — per-rank
    telemetry split into elastic incarnations, the recovery journal's
    shrink/recovery windows, and the launch-path trace spans —
    rendered as a per-incarnation waterfall. `restart_replay` is
    productive time re-done below the prior incarnation's max
    committed step (the no-checkpoint tax); `shrunk_capacity` is the
    chip-fraction missing while a gang runs elastically shrunk;
    `unattributed` means no plane left evidence.

    Without CLUSTER (or with --fleet): loss-by-cause rolled up across
    every live cluster's newest persisted ledger — the fleet number
    the ML-productivity-goodput decomposition optimizes.
    """
    from skypilot_tpu.agent import goodput as goodput_lib
    from skypilot_tpu.client import sdk
    report = sdk.goodput_report(cluster, fleet=fleet_view)
    if as_json:
        click.echo(json.dumps(report.get('ledger') or
                              report.get('report') or {},
                              default=str))
        return
    if report.get('kind') == 'cluster':
        ledger = report.get('ledger') or {}
        if not ledger.get('wall_s'):
            click.echo(f'No goodput evidence for {cluster!r} yet '
                       '(no telemetry, lease, or ledger rows).')
            return
        _render_goodput_ledger(ledger)
        return
    fleet_report = report.get('report') or {}
    clusters = fleet_report.get('clusters') or []
    if not clusters:
        click.echo('No persisted goodput ledgers for live clusters.')
        return
    wall = fleet_report.get('wall_s') or 0.0
    ratio = fleet_report.get('goodput')
    click.echo(f'FLEET GOODPUT — {len(clusters)} job(s), '
               f'{wall:.1f} attributed rank-seconds, goodput=' +
               (f'{ratio:.1%}' if ratio is not None else '-'))
    loss = fleet_report.get('loss_by_cause') or {}
    total_loss = sum(loss.values())
    if total_loss > 0:
        fmt = '  {:<16} {:>10} {:>7}  {}'
        click.echo(fmt.format('LOSS CAUSE', 'SECONDS', 'SHARE', ''))
        for cat, value in sorted(loss.items(), key=lambda kv: -kv[1]):
            share = value / total_loss
            click.echo(fmt.format(cat, f'{value:.1f}',
                                  f'{share:.1%}',
                                  '#' * max(1, int(share * 30))))
    fmt = '{:<24} {:>8} {:>9} {:>9} {:>9}  {}'
    click.echo(fmt.format('CLUSTER', 'GOODPUT', 'WALL', 'PRODUCTIVE',
                          'REPLAYED', 'TOP LOSSES'))
    for row in clusters:
        ratio = row.get('goodput')
        click.echo(fmt.format(
            row['cluster'][:24],
            f'{ratio:.1%}' if ratio is not None else '-',
            f'{row.get("wall_s") or 0:.0f}s',
            f'{row.get("productive_s") or 0:.0f}s',
            row.get('replayed_steps')
            if row.get('replayed_steps') is not None else '-',
            goodput_lib.loss_summary(row.get('seconds') or {})))


def _profile_digest(group: List[dict]) -> str:
    """One gang's cross-rank step-anatomy digest: dispatch skew,
    slowest rank, verdict roll-up."""
    ratios = {r['rank']: r['dispatch_gap_ratio'] for r in group
              if r.get('dispatch_gap_ratio') is not None}
    devices = {r['rank']: r['device_ema_s'] for r in group
               if r.get('device_ema_s') is not None}
    parts = [f'{len(group)} rank(s)']
    if ratios:
        skew = max(ratios.values()) - min(ratios.values())
        parts.append(f'dispatch skew={skew:.0%}')
    if devices:
        slowest = max(devices, key=devices.get)
        parts.append(f'slowest rank {slowest}: '
                     f'{devices[slowest] * 1000:.1f}ms device')
    verdicts = sorted({v for r in group for v in (r['verdicts'] or [])})
    parts.append('verdicts=' + (','.join(verdicts) if verdicts
                                else 'none'))
    return ', '.join(parts)


@cli.command(name='profile')
@click.argument('cluster', required=False)
@click.option('--job', type=int, default=None,
              help='Only this job id.')
@click.option('--rank', type=int, default=None,
              help='Only this rank.')
@click.option('--capture', is_flag=True, default=False,
              help='Trigger an on-demand deep device capture on every '
                   'host first (dispatch RTT, device step time, '
                   'compile probe, HBM; jax.profiler trace left on '
                   'each host).')
@click.option('--duration', type=float, default=1.0,
              help='Capture budget per host (seconds), with '
                   '--capture.')
@click.option('--json', 'as_json', is_flag=True, default=False,
              help='One JSON object per profile row (joinable with '
                   '`xsky top --json` / `xsky events --json`).')
def profile(cluster, job, rank, capture, duration, as_json):
    """Per-rank device step anatomy: dispatch gap vs device compute,
    compile count/seconds, HBM watermarks, and the derived verdicts.

    Rows come from the profiles table (each rank's always-on sampler
    spools a summary next to its telemetry sample; the control plane
    pulls both together). Verdicts: `host-bound` — the host dispatch
    gap dominates device compute (the per-token-dispatch serving
    case); `recompile-storm` — XLA compiles still firing after warmup
    (a shape leak); `hbm-pressure` — peak bytes-in-use near the device
    limit; `stale` — the summary lags the rank's own heartbeat.
    """
    from skypilot_tpu import state as state_lib
    from skypilot_tpu.agent import profiler as profiler_lib
    if capture:
        if not cluster:
            raise click.UsageError('--capture needs a CLUSTER.')
        from skypilot_tpu.client import sdk
        summaries = sdk.profile_capture(cluster, job_id=job,
                                        duration_s=duration)
        if not as_json:
            click.echo(f'Captured {len(summaries)} rank(s).')
    rows = state_lib.get_profiles(cluster=cluster, job_id=job)
    if rank is not None:
        rows = [r for r in rows if r['rank'] == rank]
    if as_json:
        for row in rows:
            click.echo(json.dumps(row, default=str))
        return
    summaries = [r for r in rows if r['kind'] == 'summary']
    captures = [r for r in rows if r['kind'] == 'capture']
    if not rows:
        click.echo('No profile data recorded'
                   + (f' for {cluster!r}.' if cluster else '.'))
        return
    if summaries:
        fmt = ('{:<20} {:>4} {:>5} {:>7} {:>9} {:>9} {:>6} {:>8} '
               '{:>9} {:>8}  {}')
        click.echo(fmt.format('CLUSTER', 'JOB', 'RANK', 'SAMPLED',
                              'DISPATCH', 'DEVICE', 'DISP%',
                              'COMPILES', 'COMPILE_S', 'HBM_GIB',
                              'VERDICTS'))
        for row in summaries:
            gap = (f'{row["dispatch_gap_ema_s"] * 1000:.1f}ms'
                   if row['dispatch_gap_ema_s'] is not None else '-')
            dev = (f'{row["device_ema_s"] * 1000:.1f}ms'
                   if row['device_ema_s'] is not None else '-')
            ratio = (f'{row["dispatch_gap_ratio"]:.0%}'
                     if row['dispatch_gap_ratio'] is not None else '-')
            peak = profiler_lib.hbm_watermark(row)
            hbm = f'{peak / (1 << 30):.2f}' if peak else '-'
            click.echo(fmt.format(
                row['cluster'][:20], str(row['job_id'] or '-'),
                row['rank'],
                str(row['steps_sampled']
                    if row['steps_sampled'] is not None else '-'),
                gap, dev, ratio,
                str(row['compiles_total']
                    if row['compiles_total'] is not None else '-'),
                (f'{row["compile_seconds_total"]:.2f}'
                 if row['compile_seconds_total'] is not None else '-'),
                hbm, ','.join(row['verdicts'] or []) or '-'))
        # Per-gang digest: the cross-rank view (which rank gates the
        # gang, how skewed the anatomy is, what the verdicts agree on).
        gangs = sorted({(r['cluster'], r['job_id'])
                        for r in summaries}, key=str)
        for key in gangs:
            group = [r for r in summaries
                     if (r['cluster'], r['job_id']) == key]
            click.echo(f'  {key[0]} job {key[1]}: '
                       f'{_profile_digest(group)}')
    if captures:
        click.echo('')
        click.echo('Deep captures (latest per rank; artifacts stay on '
                   'each host):')
        cfmt = '  {:<20} {:>4} {:>5} {:>9} {:>10} {:>10}  {}'
        click.echo(cfmt.format('CLUSTER', 'JOB', 'RANK', 'RTT',
                               'DEVICE', 'COMPILE', 'OUT'))
        for row in captures:
            detail = row['detail'] or {}
            rtt = detail.get('dispatch_rtt_ms')
            mm = detail.get('device_matmul_ms')
            click.echo(cfmt.format(
                row['cluster'][:20], str(row['job_id'] or '-'),
                row['rank'],
                f'{rtt:.1f}ms' if rtt is not None else '-',
                f'{mm:.1f}ms' if mm is not None else '-',
                (f'{row["compile_seconds_total"]:.2f}s'
                 if row['compile_seconds_total'] is not None else '-'),
                detail.get('out_dir') or '-'))


def _fmt_ms(value) -> str:
    return f'{value:.0f}ms' if value is not None else '-'


def _fmt_burn(value) -> str:
    if value is None:
        return '-'
    if value == 'inf' or value == float('inf'):
        return 'inf'
    return f'{value:.2f}'


def _slo_service_report(service: str) -> Optional[dict]:
    """One service's SLO view: objectives vs actuals, per-window/
    per-objective burns, verdict, per-replica digests. None when the
    service is unknown."""
    from skypilot_tpu import state as state_lib
    from skypilot_tpu.serve import service_spec as spec_lib
    from skypilot_tpu.serve import state as serve_state
    record = serve_state.get_service(service)
    if record is None:
        return None
    slo_config = (record.get('task_config') or {}).get(
        'service', {}).get('slo')
    try:
        slo_spec = spec_lib.SLOSpec.from_config(slo_config)
    except ValueError:
        slo_spec = None
    rows = state_lib.get_serve_slo(service=service)
    service_rows = [r for r in rows if r['kind'] == 'service']
    latest = service_rows[0] if service_rows else None
    # Only replicas from the newest evaluation (same ts as its
    # service row): a drained replica's last digest stays latest for
    # its id and must not render next to the live fleet.
    replica_rows = sorted(
        (r for r in rows if r['kind'] == 'replica' and
         latest is not None and r['ts'] == latest['ts']),
        key=lambda r: r['replica_id'] or 0)
    return {
        'service': service,
        'status': record['status'].value,
        'slo': slo_spec.to_config() if slo_spec else None,
        'actual': ({k: latest.get(k) for k in
                    ('ttft_p50_ms', 'ttft_p99_ms', 'tpot_p50_ms',
                     'e2e_p50_ms', 'e2e_p99_ms', 'requests_total',
                     'errors_total', 'queue_depth', 'tokens_per_sec',
                     'inflight', 'ts')}
                   if latest else None),
        'burns': latest.get('burns') if latest else None,
        'verdict': latest.get('verdict') if latest else None,
        'detail': latest.get('detail') if latest else None,
        'replicas': replica_rows,
    }


@cli.command(name='slo')
@click.argument('service', required=False)
@click.option('--trend', 'show_trend', is_flag=True, default=False,
              help='Add TREND sparklines from the metrics history '
                   'plane: burn rate per window and per-replica p99 '
                   'TTFT.')
@click.option('--json', 'as_json', is_flag=True, default=False,
              help='One JSON object per service (joinable with '
                   '`xsky events --json` on the breach events).')
def slo_cmd(service, show_trend, as_json):
    """Serving SLO health: declared objectives vs observed latency,
    multi-window error-budget burn rates, and the breach verdict.

    Rows come from the serve_slo table, written by each service
    controller's SLO monitor (replica /metrics scrapes + the load
    balancer's per-request records). A `breach` verdict means every
    burn window is spending its error budget faster than it accrues
    — the same evaluation that journals `serve.slo_breach` (see
    `xsky events --type serve.slo_breach`).
    """
    from skypilot_tpu.serve import state as serve_state
    names = [service] if service else \
        [s['name'] for s in serve_state.get_services()]
    reports = []
    for name in names:
        report = _slo_service_report(name)
        if report is None:
            raise click.UsageError(f'Service {name!r} not found.')
        reports.append(report)
    if as_json:
        for report in reports:
            click.echo(json.dumps(report, default=str))
        return
    if not reports:
        click.echo('No services.')
        return
    for report in reports:
        objectives = report['slo'] or {}
        actual = report['actual'] or {}
        click.echo(f"Service {report['service']} "
                   f"({report['status']}): "
                   f"verdict={report['verdict'] or 'no data yet'}")
        if not objectives:
            click.echo('  (no slo: declared; latency digest only)')
        fmt = '  {:<16} {:>12} {:>12}'
        click.echo(fmt.format('OBJECTIVE', 'TARGET', 'OBSERVED'))
        rows = [
            ('ttft_p99_ms', objectives.get('ttft_p99_ms'),
             actual.get('ttft_p99_ms')),
            ('tpot_p50_ms', objectives.get('tpot_p50_ms'),
             actual.get('tpot_p50_ms')),
        ]
        for name, target, observed in rows:
            click.echo(fmt.format(
                name,
                _fmt_ms(target), _fmt_ms(observed)))
        reqs = actual.get('requests_total')
        errs = actual.get('errors_total')
        observed_avail = '-'
        if reqs:
            observed_avail = f'{1.0 - (errs or 0) / reqs:.4f}'
        click.echo(fmt.format(
            'availability',
            (f"{objectives['availability']:.4f}"
             if objectives.get('availability') is not None else '-'),
            observed_avail))
        if report['burns']:
            bfmt = '  {:<16}' + ' {:>12}' * len(report['burns'])
            windows = sorted(report['burns'],
                             key=lambda w: float(w))
            click.echo(bfmt.format(
                'BURN RATE', *[f'{w}s window' for w in windows]))
            names_seen = sorted({obj for w in windows
                                 for obj in report['burns'][w]})
            for obj in names_seen:
                click.echo(bfmt.format(
                    obj, *[_fmt_burn(report['burns'][w].get(obj))
                           for w in windows]))
            if show_trend:
                sparks = [
                    _trend_spark('xsky_serve_slo_burn_rate',
                                 {'service': report['service'],
                                  'window': w}, width=12) or '-'
                    for w in windows]
                click.echo(bfmt.format('TREND', *sparks))
        if report['replicas']:
            rfmt = ('  {:<8} {:<22} {:>10} {:>10} {:>10} {:>8} '
                    '{:>7} {:>8}')
            header = ['REPLICA', 'ENDPOINT', 'TTFT_P50', 'TTFT_P99',
                      'TPOT_P50', 'QUEUE', 'REQS', 'ERRORS']
            if show_trend:
                rfmt += ' {:<12}'
                header.append('TREND')
            click.echo(rfmt.format(*header))
            for row in report['replicas']:
                cells = [
                    str(row['replica_id']),
                    (row['endpoint'] or '-')[:22],
                    _fmt_ms(row.get('ttft_p50_ms')),
                    _fmt_ms(row.get('ttft_p99_ms')),
                    _fmt_ms(row.get('tpot_p50_ms')),
                    (f"{row['queue_depth']:.0f}"
                     if row.get('queue_depth') is not None else '-'),
                    str(row.get('requests_total')
                        if row.get('requests_total') is not None
                        else '-'),
                    str(row.get('errors_total')
                        if row.get('errors_total') is not None
                        else '-')]
                if show_trend:
                    cells.append(_trend_spark(
                        'xsky_serve_replica_ttft_p99_seconds',
                        {'service': report['service'],
                         'replica': row['replica_id']},
                        width=12) or '-')
                click.echo(rfmt.format(*cells))


@cli.command(name='remediations')
@click.option('--scope', default=None,
              help='Filter by scope prefix (e.g. service/my-svc, '
                   'job/3).')
@click.option('--detector', default=None,
              help='Filter by triggering detector (e.g. '
                   'dispatch_gap_trend, preemption).')
@click.option('--status', default=None,
              type=click.Choice(['applied', 'resolved', 'suppressed']),
              help='Filter by current status.')
@click.option('--all', 'show_all', is_flag=True, default=False,
              help='Full history instead of the latest state per '
                   '(scope, detector, ident, action).')
@click.option('--limit', default=100, show_default=True,
              help='Max rows.')
@click.option('--json', 'as_json', is_flag=True, default=False,
              help='One JSON object per row (trace_id joins `xsky '
                   'trace`).')
def remediations_cmd(scope, detector, status, show_all, limit,
                     as_json):
    """Closed-loop remediations: what the anomaly→remediation engine
    did and why.

    Each row is one remediation keyed by (scope, detector, ident,
    action): `applied` while the action holds, `resolved` once the
    triggering anomaly cleared (with the applied→resolved latency in
    detail), `suppressed` when a flapping anomaly re-fired inside the
    cooldown and was deduped. The trace id is shared with the
    triggering anomaly's journal entry — `xsky trace <trace_id>` walks
    fault → detection → action → resolution.
    """
    from skypilot_tpu import state as state_lib
    rows = state_lib.get_remediations(
        scope=scope, detector=detector, status=status,
        latest_only=not show_all, limit=limit)
    if as_json:
        for row in rows:
            click.echo(json.dumps(row, default=str))
        return
    if not rows:
        click.echo('No remediations.')
        return
    now = time.time()
    fmt = '{:<5} {:<20} {:<20} {:<22} {:<20} {:<10} {:<16}'
    click.echo(fmt.format('AGE', 'SCOPE', 'DETECTOR', 'IDENT',
                          'ACTION', 'STATUS', 'TRACE'))
    for row in rows:
        click.echo(fmt.format(
            _age_str(now - row['ts'] if row['ts'] else None),
            (row['scope'] or '-')[:20],
            (row['detector'] or '-')[:20],
            (row['ident'] or '-')[:22],
            (row['action'] or '-')[:20],
            row['status'] or '-',
            row['trace_id'] or '-'))


@cli.command()
@click.option('--fix', is_flag=True, default=False,
              help='Run the reconciler: repair every unhealthy scope '
                   '(requeue/fail stranded requests, respawn dead '
                   'controllers, tear down orphan clusters).')
def doctor(fix):
    """Control-plane crash-safety health: liveness leases + ownership.

    Reports every liveness lease (who holds it, whether its pid is
    alive, when it expires), the multi-server ownership map (which
    live server the rendezvous hash assigns each controller scope to,
    who holds the recorder lease, leases within a third of their TTL
    of expiry), in-flight API requests stranded by a dead server,
    non-terminal jobs/services whose controller process is gone, and
    task clusters whose owning record is already terminal. With
    --fix, runs the reconciler on the spot — the same claim-arbitrated
    takeover path a server's own reconcile pass uses — and prints each
    repair (every repair also lands in `xsky events` as a reconcile.*
    row).
    """
    import datetime as datetime_lib

    from skypilot_tpu import reconciler
    report = reconciler.health_report()
    leases = report['leases']
    click.echo(f'Liveness leases ({len(leases)}):')
    if leases:
        fmt = '  {:<30} {:<22} {:>8} {:<6} {:>10} {:<8}'
        click.echo(fmt.format('SCOPE', 'OWNER', 'PID', 'ALIVE',
                              'EXPIRES', 'STATE'))
        for l in leases:
            expires = f"{l['expires_in_s']:.0f}s" \
                if l['expires_in_s'] > 0 else 'expired'
            click.echo(fmt.format(
                l['scope'][:30], (l['owner'] or '-')[:22],
                l['pid'] or '-', 'yes' if l['pid_alive'] else 'NO',
                expires, 'live' if l['live'] else 'STALE'))
    else:
        click.echo('  (none — no long-lived actors running)')
    own = report.get('ownership') or {}
    servers = own.get('servers') or []
    if servers:
        click.echo(f'Server ownership ({len(servers)} live '
                   f'server{"s" if len(servers) != 1 else ""}):')
        assignments = own.get('assignments') or {}
        by_server: dict = {}
        for scope, owner in sorted(assignments.items()):
            by_server.setdefault(owner, []).append(scope)
        for sid in servers:
            scopes = by_server.get(sid, [])
            suffix = ', '.join(scopes) if scopes else '(no controllers)'
            click.echo(f'  {sid}: {suffix}')
        recorder = own.get('recorder')
        if recorder:
            state_str = ('live' if own.get('recorder_live')
                         else 'STALE — next hold_recorder_lease() '
                              'takes over')
            click.echo(f"  recorder lease: {recorder['owner']} "
                       f"(pid {recorder['pid']}, {state_str})")
        else:
            click.echo('  recorder lease: unheld')
        expiring = own.get('expiring') or []
        if expiring:
            click.echo(f'  Leases nearing expiry ({len(expiring)}) — '
                       'renewal overdue, takeover imminent unless the '
                       'holder heartbeats:')
            for l in expiring:
                click.echo(f"    {l['scope']} ({l['owner']}, "
                           f"{l['expires_in_s']:.0f}s left)")
    if report['suspect_leases']:
        click.echo(f"Suspect holders ({len(report['suspect_leases'])}) "
                   '— lease expired but pid alive (wedged, or blocked '
                   'in a long provisioning step); not auto-repaired:')
        for l in report['suspect_leases']:
            click.echo(f"  {l['scope']} (pid {l['pid']}, expired "
                       f"{-l['expires_in_s']:.0f}s ago)")
    problems = [
        ('Stranded in-flight requests', report['stranded_requests'],
         lambda r: f"{r['request_id']} ({r['verb']}, {r['status']})"),
        ('Dead jobs controllers', report['dead_job_controllers'],
         lambda r: f"job {r['job_id']} (pid {r['pid']}, {r['status']})"),
        ('Dead serve controllers', report['dead_serve_controllers'],
         lambda r: f"{r['service']} (pid {r['pid']}, {r['status']})"),
        ('Orphaned task clusters', report['orphan_clusters'],
         lambda r: f"{r['cluster']} (job {r['job_id']} terminal/gone)"),
    ]
    for title, rows, render in problems:
        if rows:
            click.echo(f'{title} ({len(rows)}):')
            for row in rows:
                click.echo(f'  {render(row)}')
    if report['healthy']:
        click.echo('Control plane healthy: every in-flight scope has '
                   'a live owner.')
        if not fix:
            return
    elif not fix:
        click.echo('Run `xsky doctor --fix` to reconcile.')
        raise SystemExit(1)
    if fix:
        # No request requeue from the CLI: a requeued request would
        # run inside this short-lived doctor process and be orphaned
        # again at exit — fail-abort is the honest repair here.
        repairs = reconciler.reconcile(requeue_requests=False)
        if not repairs:
            click.echo('Reconciler: nothing to repair.')
            return
        now = datetime_lib.datetime.now().strftime('%H:%M:%S')
        for r in repairs:
            click.echo(f"[{now}] {r['action']}: {r['scope']} "
                       f"({r['cause']})")


class _SSHGroup(click.Group):
    """`xsky ssh CLUSTER [CMD...]` keeps working next to the node-pool
    subcommands: an unknown first token routes to `connect`."""

    def parse_args(self, ctx, args):
        if args and not args[0].startswith('-') and \
                args[0] not in self.commands:
            args = ['connect'] + list(args)
        return super().parse_args(ctx, args)


@cli.group(cls=_SSHGroup)
def ssh():
    """Shell into a cluster head; manage SSH node pools (up/down).

    `xsky ssh CLUSTER [CMD...]` opens a shell on the cluster head. A
    cluster whose name collides with a subcommand (`up`, `down`,
    `connect`) is reachable via the explicit form:
    `xsky ssh connect CLUSTER`.
    """


@ssh.command(name='connect', hidden=True)
@click.argument('cluster')
@click.argument('command', nargs=-1)
def ssh_connect(cluster, command):
    """Open a shell (or run COMMAND) on the cluster head.

    With a remote API server configured, the connection tunnels
    through it (HTTP CONNECT), so heads without public IPs work.
    """
    import subprocess
    from skypilot_tpu.client import sdk
    argv, cwd = sdk.ssh_command(cluster, command=list(command) or None)
    raise SystemExit(subprocess.call(argv, cwd=cwd))


@ssh.command(name='up')
@click.option('--infra', default=None,
              help='Pool name from ~/.xsky/ssh_node_pools.yaml '
                   '(default: all pools).')
def ssh_up(infra):
    """Probe and warm SSH node pool(s) (twin of `sky ssh up`)."""
    from skypilot_tpu.client import sdk
    try:
        report = sdk.ssh_up(infra)
    except ValueError as e:
        raise click.ClickException(str(e))
    for pool, info in sorted(report.items()):
        mark = 'ready' if info['ok'] else 'DEGRADED'
        click.echo(f'{pool}: {mark}')
        if not info['hosts']:
            click.echo('  (no hosts declared)')
        for row in info['hosts']:
            state = 'ok' if row['ok'] else f"FAIL ({row['error']})"
            click.echo(f"  {row['ip']}: {state}")
    bad_pools = sorted(p for p, info in report.items() if not info['ok'])
    if bad_pools:
        raise click.ClickException(
            f"pool(s) not ready: {', '.join(bad_pools)}")


@ssh.command(name='down')
@click.option('--infra', default=None,
              help='Pool name (default: all pools).')
@click.option('--yes', '-y', is_flag=True, default=False)
def ssh_down(infra, yes):
    """Release pool allocations + clean agents (twin of `sky ssh down`)."""
    from skypilot_tpu.client import sdk
    if not yes:
        # Validate before the destructive prompt when the pool config
        # is local (remote servers resolve their own pools file).
        if sdk.api_server_endpoint() is None:
            from skypilot_tpu.clouds import ssh as ssh_cloud_lib
            try:
                ssh_cloud_lib._select_pools(infra)  # unknown/empty check
            except ValueError as e:
                raise click.ClickException(str(e))
        target = f'pool {infra!r}' if infra else 'ALL pools'
        click.confirm(
            f'Terminate all clusters allocated from {target}?',
            abort=True)
    try:
        report = sdk.ssh_down(infra)
    except ValueError as e:
        raise click.ClickException(str(e))
    for pool, info in sorted(report.items()):
        released = ', '.join(info['released_clusters']) or 'none'
        click.echo(f'{pool}: released clusters: {released}; '
                   f"cleaned {info['hosts_cleaned']} host(s)")


@cli.command()
@click.argument('paths', nargs=-1)
@click.option('--root', 'root_dir', default=None,
              help='Repo root holding tools/xskylint (default: '
                   'auto-detected from the working directory).')
@click.option('--rule', 'rules', multiple=True,
              help='Run only this rule id (repeatable).')
@click.option('--json', 'as_json', is_flag=True, default=False,
              help='Machine-readable findings (schema-versioned, '
                   'absolute paths included).')
@click.option('--changed', 'changed', is_flag=True, default=False,
              help='Per-file rules only on files differing from the '
                   'merge-base; whole-program rules still see the '
                   'full tree.')
@click.option('--base', 'base', default=None,
              help='Merge-base ref for --changed (default: '
                   'origin/main).')
@click.option('--stats', 'stats', is_flag=True, default=False,
              help='Per-rule finding + suppression counts with '
                   'reasons (suppression-debt report).')
@click.option('--why', 'why', default=None,
              metavar='RULE:FILE:LINE',
              help='Explain one finding: focused re-run printing the '
                   'shortest entry->violation call chain (lock-order: '
                   'the cycle\'s edge witnesses).')
@click.option('--no-cache', 'no_cache', is_flag=True, default=False,
              help='Disable the mtime+size-keyed AST cache '
                   '(.xskylint_cache/).')
@click.option('--check-baseline', 'check_baseline', is_flag=True,
              default=False,
              help='Fail when suppression counts exceed the '
                   'checked-in baseline (debt ratchet).')
@click.option('--list-rules', 'list_rules', is_flag=True, default=False,
              help='Print the rule catalog and exit.')
def lint(paths, root_dir, rules, as_json, changed, base, stats, why,
         no_cache, check_baseline, list_rules):
    """Static analysis over the tree (tools/xskylint).

    Parses each file once, builds a whole-program index AND call
    graph over the shared ASTs, and runs every registered rule:
    concurrency contracts (raw sleeps, sequential runner loops,
    thread/process hygiene), observability contracts (span coverage,
    retention bounds, never-raise recording paths, lease heartbeats),
    state-DB discipline (SELECT paging, connection routing), the
    env-var and observability-name registries, chaos coverage, the
    cross-file contracts (verb wiring, lock discipline, schema
    consistency), and the interprocedural contracts (hot-path purity,
    lock-order deadlock detection, transitive never-raise).
    Exits 1 on any unsuppressed finding. Suppress with
    `# xskylint: disable=<rule> -- <reason>` (reason mandatory); rule
    catalog in docs/static-analysis.md.
    """
    root = os.path.abspath(root_dir) if root_dir else None
    if root is None:
        probe = os.getcwd()
        while True:
            if os.path.isdir(os.path.join(probe, 'tools', 'xskylint')):
                root = probe
                break
            parent = os.path.dirname(probe)
            if parent == probe:
                raise click.ClickException(
                    'no tools/xskylint found here or above — run from '
                    'a repo checkout or pass --root.')
            probe = parent
    if root not in sys.path:
        sys.path.insert(0, root)
    from tools.xskylint import engine as lint_engine
    argv = list(paths) + ['--root', root]
    for rule in rules:
        argv += ['--rule', rule]
    if as_json:
        argv.append('--json')
    if changed:
        argv.append('--changed')
    if base:
        argv += ['--base', base]
    if stats:
        argv.append('--stats')
    if why:
        argv += ['--why', why]
    if no_cache:
        argv.append('--no-cache')
    if check_baseline:
        argv.append('--check-baseline')
    if list_rules:
        argv.append('--list-rules')
    sys.exit(lint_engine.main(argv))


@cli.command()
@click.argument('cluster')
@click.argument('job_id', type=int, required=False)
@click.option('--sync-down', is_flag=True, default=False,
              help='Download the job log directories instead of '
                   'printing (to ~/.xsky/sync_down_logs/<cluster>).')
@click.option('--all-ranks', is_flag=True, default=False,
              help='Print every rank interleaved with [rank N] tags '
                   '(default: rank 0 only).')
def logs(cluster, job_id, sync_down, all_ranks):
    """Print (or download) a job's logs."""
    from skypilot_tpu.client import sdk
    if sync_down:
        path = sdk.sync_down_logs(cluster, job_id)
        click.echo(f'Logs synced to {path}')
        return
    click.echo(sdk.tail_logs(cluster, job_id, all_ranks=all_ranks),
               nl=False)


@cli.command()
@click.argument('cluster')
@click.argument('job_ids', nargs=-1, type=int)
@click.option('--all', '-a', 'all_jobs', is_flag=True, default=False)
def cancel(cluster, job_ids, all_jobs):
    """Cancel job(s)."""
    from skypilot_tpu.client import sdk
    sdk.cancel(cluster, list(job_ids) or None, all_jobs=all_jobs)
    click.echo('Cancelled.')


@cli.command()
def check():
    """Probe cloud credentials and enable clouds."""
    from skypilot_tpu.client import sdk
    results = sdk.check()
    for name, info in sorted(results.items()):
        mark = 'enabled' if info['enabled'] else \
            f"disabled ({info['reason']})"
        click.echo(f'  {name}: {mark}')


@cli.command(name='show-gpus')
@click.argument('accelerator_filter', required=False)
@click.option('--all', '-a', 'show_all', is_flag=True, default=False)
def show_gpus(accelerator_filter, show_all):
    """List accelerators (GPUs and TPU slices) with prices.

    Goes through the SDK so a configured remote API server answers
    from ITS catalogs (the reference's show-gpus is server-side too);
    falls back to the local catalog otherwise."""
    from skypilot_tpu.client import sdk
    rows = sdk.accelerators(name_filter=accelerator_filter)
    fmt = '{:<16} {:<8} {:<12} {:<11} {:<11} {:<10}'
    click.echo(fmt.format('ACCELERATOR', 'COUNT', 'CLOUD', '$/HR',
                          'SPOT $/HR', 'MEM(GB)'))
    shown = set()
    for o in rows:   # name-sorted, cheapest offering first per name
        if not show_all and o['accelerator_name'] in shown:
            continue
        shown.add(o['accelerator_name'])
        click.echo(fmt.format(
            o['accelerator_name'], f"{o['accelerator_count']:g}",
            o['cloud'],
            f"{o['price']:.2f}" if o['price'] else '-',
            f"{o['spot_price']:.2f}" if o['spot_price'] else '-',
            f"{o['memory_gib']:g}"))


@cli.command(name='cost-report')
def cost_report():
    """Billable cost of live and torn-down clusters."""
    from skypilot_tpu.client import sdk
    rows = sdk.cost_report()
    fmt = '{:<18} {:<28} {:<11} {:>9} {:>8} {:>10}'
    click.echo(fmt.format('NAME', 'RESOURCES', 'STATUS', 'UPTIME_H',
                          '$/HR', 'TOTAL $'))
    for r in rows:
        click.echo(fmt.format(r['name'], r['resources'][:28],
                              r.get('status', '-'),
                              f"{r['uptime_hours']:.2f}",
                              f"{r['hourly_cost']:.2f}",
                              f"{r['total_cost']:.2f}"))


@cli.command()
@click.option('--kill', is_flag=True, default=False,
              help='Kill the targeted framework daemons (default: '
                   'report only).')
@click.option('--leaked-only', is_flag=True, default=False,
              help='Only processes no cluster/job/service/server '
                   'record owns.')
def reap(kill, leaked_only):
    """Audit/kill framework daemons (round-end hygiene sweep).

    Lists every live job runner, jobs/serve controller, and API
    server, annotating each as `owned` (a live record claims it) or
    `leaked` (nothing in the control plane knows it exists). With
    --kill, TERMs each targeted process group and escalates to KILL: a
    scorched-earth sweep for round boundaries, because a surviving
    chip-holding process turns the next benchmark run into
    `UNAVAILABLE`. Do not --kill while workloads you care about run —
    or pass --leaked-only to spare everything a record owns.
    """
    from skypilot_tpu.utils import reaper
    if kill:
        swept = reaper.reap(leaked_only=leaked_only)
        survivors = 0
        for rec in swept:
            if rec.get('killed'):
                click.echo(f"killed {rec['pid']}: {rec['cmdline']}")
            else:
                survivors += 1
                click.echo(
                    f"SURVIVED {rec['pid']}: {rec['cmdline']}")
        if survivors:
            raise SystemExit(1)
    else:
        found = reaper.classify()
        if leaked_only:
            found = [r for r in found if not r['owned']]
        if not found:
            click.echo('no framework processes running.')
        for rec in found:
            tag = ('owned by ' + str(rec['owner'])
                   if rec['owned'] else 'LEAKED')
            click.echo(f"{rec['pid']} [{tag}]: {rec['cmdline']}")


@cli.group()
def local():
    """Local docker cluster (dev; twin of `sky local up/down`)."""


@local.command(name='up')
def local_up():
    """Enable the local docker cloud (containers as cluster hosts)."""
    from skypilot_tpu.clouds import docker as docker_cloud
    ok, reason = docker_cloud.Docker.daemon_available()
    if not ok and os.environ.get('XSKY_ENABLE_DOCKER_CLOUD') != '1':
        raise click.ClickException(f'docker unavailable: {reason}')
    marker = os.path.expanduser(docker_cloud.Docker.MARKER_PATH)
    os.makedirs(os.path.dirname(marker), exist_ok=True)
    with open(marker, 'w', encoding='utf-8') as f:
        f.write('enabled by `xsky local up`\n')
    click.echo('Local docker cloud enabled. Launch with '
               '`xsky launch task.yaml` (cloud: docker), tear down '
               'clusters with `xsky down`, disable with '
               '`xsky local down`.')


@local.command(name='down')
@click.option('--yes', '-y', is_flag=True, default=False)
def local_down(yes):
    """Disable the local docker cloud and tear down its clusters."""
    from skypilot_tpu import core as core_lib
    from skypilot_tpu.clouds import docker as docker_cloud
    records = [r for r in core_lib.status()
               if getattr(r.get('handle'), 'provider_name', None) ==
               'docker']
    if records and not yes:
        names = ', '.join(r['name'] for r in records)
        click.confirm(f'Tear down local cluster(s) {names}?', abort=True)
    for r in records:
        try:
            core_lib.down(r['name'])
            click.echo(f"Cluster {r['name']} terminated.")
        except Exception as e:  # pylint: disable=broad-except
            click.echo(f"Cluster {r['name']}: {e}")
    marker = os.path.expanduser(docker_cloud.Docker.MARKER_PATH)
    if os.path.exists(marker):
        os.remove(marker)
    click.echo('Local docker cloud disabled.')


# ---- jobs / serve / storage / api groups (wired as they land) -------------


@cli.group()
def jobs():
    """Managed jobs with auto-recovery."""


@jobs.command(name='launch')
@click.argument('entrypoint')
@_apply(_task_options)
@click.option('--priority', type=int, default=0,
              help='Fleet-scheduler admission priority (higher '
                   'schedules first; weighted fair-share across '
                   'workspaces and queue-age aging apply on top).')
@click.option('--yes', '-y', is_flag=True, default=False)
def jobs_launch(entrypoint, envs, env_file, secrets, name, num_nodes,
                accelerators, cloud, use_spot, priority, yes):
    """Launch a managed job (controller recovers preemptions).

    A `---`-separated multi-document YAML is a PIPELINE: tasks run as
    a sequential chain, each on its own cluster; an optional leading
    `name:`-only document names the pipeline.
    """
    from skypilot_tpu.client import sdk
    if os.path.exists(entrypoint) and entrypoint.endswith(
            ('.yaml', '.yml')):
        chain_name, tasks = task_lib.Task.load_chain(
            entrypoint, env_overrides=_merged_envs(envs, env_file),
            secret_overrides=_parse_kv(secrets, 'secret'))
        if len(tasks) > 1:
            # Per-task resource flags are ambiguous across a chain.
            if (num_nodes or accelerators or cloud
                    or use_spot is not None):
                raise click.UsageError(
                    'Resource flags (--num-nodes/--accelerators/'
                    '--cloud/--use-spot) are not supported for '
                    'pipelines; set resources per task in the YAML.')
            job_id = sdk.jobs_launch(tasks, name=name or chain_name,
                                     priority=priority)
            click.echo(f'Managed pipeline {job_id} submitted '
                       f'({len(tasks)} tasks).')
            return
        # A single task (possibly behind a leading name:-only doc —
        # which plain from_yaml cannot parse): apply the flags here
        # instead of re-reading the file via _load_task.
        t = _apply_task_flags(tasks[0], name or chain_name, num_nodes,
                              accelerators, cloud, use_spot)
    else:
        t = _load_task(entrypoint, envs, secrets, name, num_nodes,
                       accelerators, cloud, use_spot,
                       env_file=env_file)
    job_id = sdk.jobs_launch(t, priority=priority)
    click.echo(f'Managed job {job_id} submitted.')


def _open_dashboard(view: str) -> None:
    from skypilot_tpu.client import sdk
    endpoint = sdk.api_server_endpoint()
    if endpoint is None:
        raise click.ClickException(
            'No API server configured. Start one with `xsky api start` '
            'or set XSKY_API_SERVER.')
    if not endpoint.startswith(('http://', 'https://')):
        endpoint = f'http://{endpoint}'
    url = f'{endpoint.rstrip("/")}/dashboard#/{view}'
    click.echo(url)
    import webbrowser
    try:
        webbrowser.open(url)
    except Exception:  # pylint: disable=broad-except
        pass


@cli.command(name='dashboard')
def dashboard_cmd():
    """Print (and try to open) the web dashboard (twin of
    `sky dashboard`)."""
    _open_dashboard('clusters')


@jobs.command(name='dashboard')
def jobs_dashboard():
    """Print (and try to open) the dashboard's managed-jobs view."""
    _open_dashboard('jobs')


@jobs.command(name='queue')
def jobs_queue():
    """The managed-job queue: status plus the fleet scheduler's view
    (PRIO = admission priority, SCHED = schedule state, GANG =
    survivors/full while elastically shrunk)."""
    from skypilot_tpu.client import sdk
    rows = sdk.jobs_queue()
    fmt = '{:<6} {:<16} {:<7} {:<14} {:>5} {:<10} {:<6} {:<8}'
    click.echo(fmt.format('ID', 'NAME', 'TASK', 'STATUS', 'PRIO',
                          'SCHED', 'GANG', 'RECOVERIES'))
    for r in rows:
        click.echo(fmt.format(r['job_id'], str(r['name'])[:16],
                              r.get('task') or '-', r['status'],
                              r.get('priority') or 0,
                              (r.get('schedule_state') or '-')[:10],
                              r.get('gang') or '-',
                              r.get('recovery_count', 0)))


@jobs.command(name='cancel')
@click.argument('job_ids', nargs=-1, type=int, required=True)
def jobs_cancel(job_ids):
    from skypilot_tpu.client import sdk
    for jid in job_ids:
        sdk.jobs_cancel(jid)
    click.echo('Cancelled.')


@jobs.command(name='logs')
@click.argument('job_id', type=int)
@click.option('--follow', '-f', is_flag=True, default=False,
              help='Stream the task log until the job reaches a '
                   'terminal state (survives recovery cluster swaps).')
def jobs_logs(job_id, follow):
    from skypilot_tpu.client import sdk
    if not follow:
        click.echo(sdk.jobs_logs(job_id), nl=False)
        return
    _follow_logs(lambda off: sdk.jobs_watch_logs(job_id, offset=off),
                 what='job')


def _follow_logs(poll_fn, what: str) -> None:
    """Generic incremental-tail loop over a {status, offset, data,
    epoch, done} poll function (jobs_watch_logs / serve_watch_logs):
    error backoff, epoch-reset on recovery swaps, terminal drain."""
    import time as time_lib
    offset, epoch, errors = 0, None, 0
    while True:
        try:
            poll = poll_fn(offset)
        except Exception as e:  # pylint: disable=broad-except
            # Transient API-server / remote-exec blips must not kill a
            # follow that exists to survive recovery windows. Back off;
            # give up only when the source stays dead.
            errors += 1
            if errors >= 8:
                raise click.ClickException(
                    f'log source unavailable after {errors} '
                    f'consecutive poll failures: {e}')
            time_lib.sleep(min(2 * errors, 15))
            continue
        errors = 0
        if epoch is not None and poll.get('epoch') not in (None, epoch):
            # Recovery swapped the task cluster: its fresh log starts
            # over at 0.
            click.echo(f'\n--- {what} recovered; log restarted ---')
            offset, epoch = 0, poll.get('epoch')
            continue
        if poll.get('epoch') is not None:
            epoch = poll['epoch']
        if poll.get('data'):
            click.echo(poll['data'], nl=False)
        offset = poll.get('offset', offset)
        if poll.get('done'):
            # Drain: polls cap at 256 KB, so a finished source may
            # still have backlog — keep reading until a dry poll.
            if poll.get('data'):
                continue
            click.echo(f"\n({what} {poll['status']})")
            return
        time_lib.sleep(2)


@cli.group()
def serve():
    """SkyServe-style autoscaled serving."""


@serve.command(name='up')
@click.argument('entrypoint')
@click.option('--service-name', '-n', default=None)
@click.option('--yes', '-y', is_flag=True, default=False)
def serve_up(entrypoint, service_name, yes):
    from skypilot_tpu.client import sdk
    t = task_lib.Task.from_yaml(entrypoint)
    name = sdk.serve_up(t, service_name)
    click.echo(f'Service {name} is up.')


@serve.command(name='update')
@click.argument('service_name')
@click.argument('entrypoint')
@click.option('--mode', type=click.Choice(['rolling', 'blue_green']),
              default='rolling',
              help='rolling: mixed old+new traffic while the fleet '
                   'turns over. blue_green: old fleet keeps all '
                   'traffic until the new fleet is READY, then one '
                   'cutover (no mixed-version responses).')
@click.option('--yes', '-y', is_flag=True, default=False)
def serve_update(service_name, entrypoint, mode, yes):
    """Update a live service (twin of `sky serve update --mode`)."""
    from skypilot_tpu.client import sdk
    t = task_lib.Task.from_yaml(entrypoint)
    version = sdk.serve_update(t, service_name, mode=mode)
    click.echo(f'Service {service_name} updating to v{version} '
               f'({mode}).')


@serve.command(name='status')
@click.argument('service_names', nargs=-1)
@click.option('--json', 'as_json', is_flag=True, default=False,
              help='One JSON object per service (the full record, '
                   'replicas included).')
def serve_status(service_names, as_json):
    """Service fleet health, latency and SLO burn at a glance
    (`xsky slo SERVICE` has the full per-replica/per-window view)."""
    from skypilot_tpu.client import sdk
    records = sdk.serve_status(list(service_names) or None)
    if as_json:
        for record in records:
            click.echo(json.dumps(record, default=str))
        return
    fmt = ('{:<16} {:<12} {:>3} {:>8} {:>9} {:>9} {:>6} '
           '{:<8}  {}')
    click.echo(fmt.format('NAME', 'STATUS', 'VER', 'REPLICAS',
                          'QPS', 'TTFT_P99', 'BURN', 'SLO',
                          'ENDPOINT'))
    for r in records:
        slo_info = r.get('slo') or {}
        ready = len([rep for rep in r.get('replicas', ())
                     if rep['status'] == 'READY'])
        qps = r.get('qps')
        click.echo(fmt.format(
            r['name'][:16], r['status'], str(r.get('version') or 1),
            f"{ready}/{len(r.get('replicas', ()))}",
            f'{qps:.2f}' if qps is not None else '-',
            _fmt_ms(slo_info.get('ttft_p99_ms')),
            _fmt_burn(slo_info.get('burn_rate')),
            slo_info.get('verdict') or '-',
            r['endpoint']))


@serve.command(name='logs')
@click.argument('service_name')
@click.argument('replica_id', type=int, required=False)
@click.option('--job-id', type=int, default=None)
@click.option('--controller', is_flag=True, default=False,
              help="The service controller's own log (diagnostics for "
                   'a crashed control loop).')
@click.option('--follow', '-f', is_flag=True, default=False,
              help="Stream the replica's task log until it reaches a "
                   'terminal state.')
def serve_logs(service_name, replica_id, job_id, controller, follow):
    """Tail one replica's logs (twin of `sky serve logs`)."""
    from skypilot_tpu.client import sdk
    if controller:
        if follow:
            raise click.UsageError(
                '--controller logs have no follow mode.')
        click.echo(sdk.serve_controller_logs(service_name), nl=False)
        return
    if replica_id is None:
        raise click.UsageError('REPLICA_ID is required unless '
                               '--controller is given.')
    if follow:
        if job_id is not None:
            raise click.UsageError(
                '--follow tails the replica task log; --job-id is '
                'only for one-shot reads.')
        _follow_logs(
            lambda off: sdk.serve_watch_logs(service_name, replica_id,
                                             offset=off),
            what='replica')
        return
    click.echo(sdk.serve_logs(service_name, replica_id, job_id=job_id),
               nl=False)


@serve.command(name='down')
@click.argument('service_names', nargs=-1, required=True)
@click.option('--yes', '-y', is_flag=True, default=False)
def serve_down(service_names, yes):
    from skypilot_tpu.client import sdk
    for name in service_names:
        sdk.serve_down(name)
        click.echo(f'Service {name} torn down.')


@serve.command(name='history')
@click.argument('service_name')
@click.option('--limit', type=int, default=30,
              help='Most recent controller ticks to show.')
def serve_history(service_name, limit):
    """QPS / autoscaler-target / ready-replica trend per tick."""
    from skypilot_tpu.client import sdk
    rows = sdk.serve_history(service_name, limit=limit)
    fmt = '{:<20} {:>8} {:>8} {:>7}'
    click.echo(fmt.format('TICK', 'QPS', 'TARGET', 'READY'))
    import datetime
    for r in rows:
        tick = datetime.datetime.fromtimestamp(
            r['ts']).strftime('%m-%d %H:%M:%S')
        qps = f"{r['qps']:.2f}" if r['qps'] is not None else '-'
        click.echo(fmt.format(
            tick, qps,
            r['target_replicas'] if r['target_replicas'] is not None
            else '-',
            r['ready_replicas'] if r['ready_replicas'] is not None
            else '-'))


# Cross-hop waterfall order: LB-side phases first, then the replica
# anatomy taxonomy (infer/anatomy.py PHASES — repeated here because
# the CLI must not import the jax-loading infer package).
_TRACE_PHASE_ORDER = ('lb_queue', 'relay_connect', 'replica_queue',
                      'admit_deferred', 'prefill', 'decode',
                      'sampling_commit', 'finish')


@serve.command(name='trace')
@click.argument('service_name')
@click.option('--request', 'request_id', default=None,
              help='One request: the LB-minted request id or the '
                   'exemplar trace id a serve.slo_breach journal row '
                   'names.')
@click.option('--slowest', type=int, default=5,
              help='Show the N slowest persisted exemplars.')
@click.option('--json', 'as_json', is_flag=True, default=False,
              help='One JSON object per exemplar (full phase map).')
def serve_trace(service_name, request_id, slowest, as_json):
    """Per-request latency anatomy: where one slow request's time went,
    LB relay to decode tick.

    Reads the bounded slow-request exemplar table the SLO monitor
    persists each evaluation (LB lifecycle record joined with the
    replica-side anatomy by request id). `serve.slo_breach` journal
    rows carry `exemplar_trace_ids` that resolve here via --request.
    """
    from skypilot_tpu import state as state_lib
    limit = max(1, slowest)
    if request_id:
        rows = state_lib.get_serve_slo_exemplars(
            service=service_name, request_id=request_id, limit=limit)
        if not rows:
            # Breach journal rows name trace ids, not request ids —
            # accept either spelling.
            rows = state_lib.get_serve_slo_exemplars(
                service=service_name, trace_id=request_id,
                limit=limit)
    else:
        rows = state_lib.get_serve_slo_exemplars(
            service=service_name, limit=200)
        rows.sort(key=lambda r: r.get('e2e_s') or 0.0, reverse=True)
        rows = rows[:limit]
    if as_json:
        for row in rows:
            click.echo(json.dumps(row, default=str))
        return
    if not rows:
        click.echo('No trace exemplars persisted for '
                   f'{service_name!r} yet (the SLO monitor writes '
                   'them each scrape tick).')
        return
    import datetime
    for row in rows:
        when = datetime.datetime.fromtimestamp(
            row['ts']).strftime('%m-%d %H:%M:%S') \
            if row.get('ts') else '-'
        e2e = row.get('e2e_s')
        ttft = row.get('ttft_s')
        click.echo(
            f"request {row.get('request_id')}  "
            f"trace {row.get('trace_id')}  {when}")
        line = (f"  {row.get('path') or '-'}  "
                f"outcome={row.get('outcome') or '-'}")
        if e2e is not None:
            line += f'  e2e={e2e * 1e3:.0f}ms'
        if ttft is not None:
            line += f'  ttft={ttft * 1e3:.0f}ms'
        click.echo(line)
        phases = row.get('phases') or {}
        detail = row.get('detail') or {}
        if detail.get('anatomy') == 'missing':
            click.echo('  (replica anatomy missing — LB-side phases '
                       'only)')
        ordered = [p for p in _TRACE_PHASE_ORDER if p in phases]
        ordered += sorted(p for p in phases
                          if p not in _TRACE_PHASE_ORDER)
        total = e2e or sum(phases.values()) or 1.0
        for phase in ordered:
            seconds = float(phases[phase] or 0.0)
            bar = '#' * min(40, int(round(40 * seconds / total))) \
                if total > 0 else ''
            click.echo(f'  {phase:<16} {seconds * 1e3:>9.1f}ms  '
                       f'{bar}')
        extras = []
        if detail.get('kv_headroom_at_admit') is not None:
            extras.append('kv_headroom_at_admit='
                          f"{detail['kv_headroom_at_admit']:.2f}")
        if detail.get('retries'):
            extras.append(f"retries={detail['retries']}")
        if detail.get('replica_id') is not None:
            extras.append(f"replica={detail['replica_id']}")
        if extras:
            click.echo('  ' + '  '.join(extras))
        click.echo('')


@cli.group()
def train():
    """Training observability: flight-recorder step anatomy."""


# Waterfall glyph per step phase (`xsky train trace`): one character of
# bar per share of the rank's step wall time. Order matches
# agent/flight_recorder.PHASES (repeated here so the CLI needs no
# agent import just to render).
_TRAIN_PHASE_GLYPHS = (
    ('data_wait', 'd'), ('h2d', 'h'), ('dispatch', '>'),
    ('device_compute', '#'), ('ckpt_copy', 'c'), ('other', '.'),
)


def _train_phase_bar(phases: dict, total: float,
                     width: int = 40) -> str:
    """Stacked per-phase bar, largest-remainder rounded so the bar
    length is stable (the goodput waterfall's rounding)."""
    if total <= 0:
        return ''
    shares = [(glyph, (phases.get(p) or 0.0) / total * width)
              for p, glyph in _TRAIN_PHASE_GLYPHS]
    cells = [(glyph, int(share)) for glyph, share in shares]
    rest = sorted(((share - int(share), i)
                   for i, (_, share) in enumerate(shares)),
                  reverse=True)
    short = width - sum(n for _, n in cells)
    for _, i in rest[:max(0, short)]:
        cells[i] = (cells[i][0], cells[i][1] + 1)
    return ''.join(glyph * n for glyph, n in cells)


@train.command(name='trace')
@click.argument('cluster')
@click.option('--job', 'job_id', type=int, default=None,
              help='Restrict to one managed job id.')
@click.option('--step', 'step', type=int, default=None,
              help='One step: the cross-rank waterfall for step N.')
@click.option('--slowest', type=int, default=5,
              help='Show the N slowest gang steps on record.')
@click.option('--json', 'as_json', is_flag=True, default=False,
              help='One JSON object per gang step (full per-rank '
                   'phase maps), then a {"digest": ...} summary row.')
def train_trace(cluster, job_id, step, slowest, as_json):
    """Cross-rank training step anatomy: where each rank's step time
    went and who held the gang back.

    Reads the bounded train_anatomy table (flight-recorder rings ride
    the telemetry spool pull) and joins records by step index. The
    slowest rank's compute IS the others' barrier wait: per step the
    skew, the straggler rank, and each rank's implied wait are derived
    from the join, and a stacked phase bar shows each rank's own
    decomposition (d=data_wait h=h2d >=dispatch #=device_compute
    c=ckpt_copy .=other).
    """
    from skypilot_tpu import state as state_lib
    from skypilot_tpu.agent import flight_recorder
    rows = state_lib.get_train_anatomy(cluster=cluster, job_id=job_id,
                                       limit=2000)
    waterfalls = flight_recorder.gang_waterfall(rows)
    if step is not None:
        waterfalls = [w for w in waterfalls if w['step'] == step]
    else:
        waterfalls = sorted(waterfalls,
                            key=lambda w: w.get('gang_wall_s') or 0.0,
                            reverse=True)[:max(1, slowest)]
    digest = flight_recorder.waterfall_digest(waterfalls)
    if as_json:
        for entry in waterfalls:
            click.echo(json.dumps(entry, default=str))
        click.echo(json.dumps({'digest': digest}, default=str))
        return
    if not waterfalls:
        click.echo(f'No step anatomy recorded for {cluster!r}'
                   + (f' job {job_id}' if job_id is not None else '')
                   + ' yet (rings ride the telemetry pull).')
        return
    click.echo(
        f'TRAIN TRACE {cluster} — {digest["steps"]} step(s), '
        f'mean skew {digest["mean_skew_s"] * 1e3:.1f}ms, '
        f'data share {digest["data_share"]:.0%}, top straggler '
        + (f'rank {digest["top_straggler"]}'
           if digest.get('top_straggler') is not None else '-'))
    legend = ' '.join(f'{glyph}={p}'
                      for p, glyph in _TRAIN_PHASE_GLYPHS)
    click.echo(f'({legend})')
    for entry in waterfalls:
        straggler = entry.get('straggler_rank')
        click.echo(
            f"step {entry['step']}  "
            f"gang {entry['gang_wall_s'] * 1e3:>8.1f}ms  "
            f"skew {entry['skew_s'] * 1e3:>7.1f}ms  "
            f"data {entry['data_share']:.0%}  "
            + (f'straggler rank {straggler}'
               if straggler is not None else ''))
        waits = entry.get('barrier_wait_s') or {}
        for rank in sorted(entry['ranks']):
            rec = entry['ranks'][rank]
            wall = rec.get('wall_s') or 0.0
            wait = waits.get(rank)
            mark = '~' if rank == straggler else ' '
            line = (f'  rank {rank:>3}{mark} '
                    f'{wall * 1e3:>8.1f}ms  '
                    f'{_train_phase_bar(rec.get("phases") or {}, wall)}')
            if wait:
                line += f'  +wait {wait * 1e3:.1f}ms'
            click.echo(line)
        click.echo('')


@cli.group()
def api():
    """API server management."""


@api.command(name='start')
@click.option('--host', default='127.0.0.1')
@click.option('--port', type=int, default=46580)
@click.option('--foreground', is_flag=True, default=False)
@click.option('--tls-certfile', default=None,
              help='Serve HTTPS with this certificate (with '
                   '--tls-keyfile). Production: prefer TLS at the '
                   'ingress (helm chart).')
@click.option('--tls-keyfile', default=None)
def api_start(host, port, foreground, tls_certfile, tls_keyfile):
    if bool(tls_certfile) != bool(tls_keyfile):
        raise click.UsageError(
            '--tls-certfile and --tls-keyfile go together')
    from skypilot_tpu.server import app as server_app
    tls_args = []
    if tls_certfile:
        tls_args = ['--tls-certfile', tls_certfile,
                    '--tls-keyfile', tls_keyfile]
    if foreground:
        server_app.run(host=host, port=port,
                       tls_certfile=tls_certfile,
                       tls_keyfile=tls_keyfile)
    else:
        import subprocess
        import time as time_lib
        log_path = server_app.log_file()
        os.makedirs(os.path.dirname(log_path), exist_ok=True)
        # Give the detached child its own stdout/stderr: inheriting the
        # parent's pipes would keep them open forever (any
        # `xsky api start | ...` would hang waiting for EOF).
        with open(log_path, 'ab') as log:
            proc = subprocess.Popen(
                [sys.executable, '-m', 'skypilot_tpu.server.app',
                 '--host', host, '--port', str(port)] + tls_args,
                stdout=log, stderr=subprocess.STDOUT,
                stdin=subprocess.DEVNULL,
                start_new_session=True)
        # Don't report success for a child that died on arrival (port
        # in use, bad import): wait for its pidfile or early exit.
        deadline = time_lib.time() + 15
        while time_lib.time() < deadline:
            if proc.poll() is not None:
                raise click.ClickException(
                    f'API server exited immediately '
                    f'(rc {proc.returncode}); see {log_path}.')
            if os.path.exists(server_app.pid_file()):
                break
            time_lib.sleep(0.2)
        else:
            raise click.ClickException(
                f'API server did not come up within 15s; '
                f'see {log_path}.')
        with open(server_app.pid_file(), encoding='utf-8') as f:
            f.readline()
            endpoint = f.readline().strip() or f'{host}:{port}'
        scheme = 'https' if tls_certfile else 'http'
        click.echo(f'API server starting at {scheme}://{endpoint} '
                   f'(logs: {log_path})')


@api.command(name='login')
@click.option('--endpoint', '-e', required=True,
              help='API server URL, e.g. http://host:46580')
@click.option('--token', '-t', default=None,
              help='Bearer token from `xsky users token-create`.')
@click.option('--oauth', is_flag=True, default=False,
              help='Log in via OAuth2 device flow against the IdP '
                   'configured by XSKY_OAUTH_ISSUER / '
                   'XSKY_OAUTH_CLIENT_ID (twin of sky api login '
                   'browser auth).')
def api_login(endpoint, token, oauth):
    """Point this client at a remote API server (twin of `sky api
    login`): persists api_server.endpoint (and token) in the user
    config, so every verb talks to it from now on."""
    from skypilot_tpu import config as config_lib
    from skypilot_tpu.client import remote_client
    if not endpoint.startswith(('http://', 'https://')):
        endpoint = f'http://{endpoint}'
    if oauth:
        if token:
            raise click.ClickException('--oauth and --token are '
                                       'mutually exclusive.')
        from skypilot_tpu.users import oauth as oauth_lib
        try:
            flow = oauth_lib.start_device_flow()
            uri = flow.get('verification_uri_complete') or \
                flow['verification_uri']
            click.echo(f'To log in, visit: {uri}')
            click.echo(f'and enter code: {flow["user_code"]}')
            tokens = oauth_lib.poll_for_tokens(
                flow['device_code'],
                interval=float(flow.get('interval', 5)),
                timeout=float(flow.get('expires_in', 600)))
            token = tokens['access_token']
            refresh_token = tokens.get('refresh_token')
        except oauth_lib.OAuthError as e:
            raise click.ClickException(str(e)) from e
        click.echo('Device login approved.')
    else:
        refresh_token = None
    # Probe before persisting: a typo'd endpoint should fail HERE.
    try:
        client = remote_client.RemoteClient(endpoint, token=token)
        client.list_api_requests(limit=1)
    except Exception as e:  # pylint: disable=broad-except
        raise click.ClickException(
            f'Could not reach {endpoint}: {e}') from e
    # The same file the config loader reads: honor $XSKY_CONFIG.
    path = os.path.expanduser(
        os.environ.get(config_lib.ENV_VAR_USER_CONFIG,
                       config_lib.USER_CONFIG_PATH))
    had_file = os.path.exists(path)
    updates = {'endpoint': endpoint}
    if token:
        updates['token'] = token
    if refresh_token:
        # The client renews expired access tokens with this instead of
        # forcing a fresh device login (remote_client 401 handling).
        updates['refresh_token'] = refresh_token
    config_lib.update_user_config_section(
        'api_server', updates,
        # Static-token (or token-less) re-login: a stale OAuth refresh
        # token would silently rotate auth back to the previous OAuth
        # identity on the next 401.
        remove=() if refresh_token else ('refresh_token',))
    click.echo(f'Logged in to {endpoint} (config: {path}).')
    if had_file:
        click.echo('Note: the config file was rewritten as plain YAML '
                   '(comments/ordering not preserved).')


@api.command(name='stop')
def api_stop():
    """Stop the local API server started with `xsky api start`."""
    import signal

    from skypilot_tpu.server import app as server_app
    path = server_app.pid_file()
    if not os.path.exists(path):
        raise click.ClickException('No local API server is running '
                                   '(no pid file).')
    try:
        with open(path, encoding='utf-8') as f:
            pid = int(f.readline().strip())
    except (OSError, ValueError):
        try:
            os.remove(path)
        except OSError:
            pass
        raise click.ClickException(
            f'Corrupt pid file {path} (removed); stop the server '
            'manually if it is still running.')
    # Guard against PID reuse after an unclean shutdown: only SIGTERM
    # a process that is actually the xsky API server.
    try:
        with open(f'/proc/{pid}/cmdline', 'rb') as f:
            cmdline = f.read().decode(errors='replace')
    except OSError:
        cmdline = ''
    if 'skypilot_tpu.server.app' in cmdline:
        try:
            os.kill(pid, signal.SIGTERM)
        except ProcessLookupError:
            pass
        click.echo(f'API server (pid {pid}) stopped.')
    else:
        click.echo(f'Stale pid file (pid {pid} is not the API '
                   'server); removed.')
    try:
        os.remove(path)
    except OSError:
        pass


def _api_remote():
    """RemoteClient when XSKY_API_SERVER points at a server, else None
    — the api verbs must inspect THAT server's request DB, not the
    local file (same transport split as every other verb)."""
    from skypilot_tpu.client import sdk as sdk_lib
    return sdk_lib._remote()


@api.command(name='status')
@click.option('--limit', type=int, default=30)
def api_status(limit):
    """List recent API requests (twin of `sky api status`)."""
    remote = _api_remote()
    if remote is not None:
        rows = remote.list_api_requests(limit=limit)
    else:
        from skypilot_tpu.server import requests_db
        rows = requests_db.list_requests(limit=limit)
    fmt = '{:<34} {:<14} {:<11} {:<10}'
    click.echo(fmt.format('ID', 'VERB', 'STATUS', 'USER'))
    for r in rows:
        status = r['status']
        click.echo(fmt.format(r['request_id'], r['name'],
                              getattr(status, 'value', status),
                              r.get('user') or '-'))


@api.command(name='logs')
@click.argument('request_id')
def api_logs(request_id):
    """Show one request's captured output and outcome."""
    import json as json_lib
    remote = _api_remote()
    if remote is not None:
        record = remote.get_api_request(request_id, include_log=True)
        log = (record or {}).get('log', '')
    else:
        from skypilot_tpu.server import requests_db
        record = requests_db.get(request_id)
        log = requests_db.read_log(request_id)
    if record is None:
        raise click.ClickException(f'Unknown request {request_id}.')
    if log:
        click.echo(log, nl=False)
    status = record['status']
    click.echo(f"status: {getattr(status, 'value', status)}")
    if record.get('error'):
        click.echo(f"error: {record['error']}")
    elif record.get('result') is not None:
        click.echo(json_lib.dumps(record['result'], indent=2,
                                  default=str))


@api.command(name='cancel')
@click.argument('request_id')
def api_cancel(request_id):
    """Cancel a queued/running request."""
    remote = _api_remote()
    if remote is not None:
        ok = remote.cancel_api_request(request_id)
    else:
        from skypilot_tpu.server import requests_db
        ok = requests_db.mark_cancelled(request_id)
    if ok:
        click.echo(f'Request {request_id} cancelled.')
    else:
        raise click.ClickException(
            f'Request {request_id} not found or already terminal.')


@api.command(name='info')
def api_info():
    """Show the API server URL, health and user (twin of `sky api info`)."""
    from skypilot_tpu.client import sdk
    info = sdk.api_info()
    url = info['url'] or '(local, in-process)'
    click.echo(f'Using xsky API server: {url}')
    click.echo(f"  Status: {info.get('status')}, "
               f"version: {info.get('version')}, "
               f"api_version: {info.get('api_version')}")
    user = info.get('user')
    if user:
        click.echo(f"  User: {user['name']} (role: {user['role']})")
    elif info.get('auth_required'):
        click.echo('  User: UNAUTHENTICATED (server requires auth — '
                   'set XSKY_API_TOKEN or `xsky api login`)')
    else:
        click.echo('  User: anonymous (auth not required)')


@cli.group()
def storage():
    """Object-storage management (twin of `sky storage`)."""


@storage.command(name='ls')
@click.argument('name', required=False)
@click.option('--prefix', default='', help='Object-key prefix filter.')
@click.option('--limit', type=int, default=100)
def storage_ls(name, prefix, limit):
    """List storages, or one storage's objects when NAME is given."""
    from skypilot_tpu import exceptions as exc
    from skypilot_tpu.client import sdk
    if name:
        try:
            keys = sdk.storage_ls_objects(name, prefix=prefix,
                                          limit=limit)
        except exc.StorageError as e:
            raise click.ClickException(str(e)) from e
        for key in keys:
            click.echo(key)
        return
    records = sdk.storage_ls()
    if not records:
        click.echo('No storage.')
        return
    click.echo(f'{"NAME":<28}{"STATUS":<16}{"STORES":<20}')
    for r in records:
        stores = ','.join(r['stores']) or '-'
        click.echo(f'{r["name"]:<28}{r["status"]:<16}{stores:<20}')


@storage.command(name='delete')
@click.argument('names', nargs=-1, required=True)
@click.option('--yes', '-y', is_flag=True, default=False)
def storage_delete(names, yes):
    from skypilot_tpu import exceptions as exc
    from skypilot_tpu.client import sdk
    for name in names:
        if not yes and not click.confirm(
                f'Delete storage {name!r} and its managed bucket(s)?'):
            click.echo(f'Skipped {name}.')
            continue
        try:
            sdk.storage_delete(name)
        except exc.StorageError as e:
            click.echo(str(e))
            continue
        click.echo(f'Storage {name} deleted.')


@cli.group()
def users():
    """User management (twin of `sky users`; admin-only on auth servers)."""


@users.command(name='ls')
def users_ls():
    from skypilot_tpu.client import sdk
    records = sdk.users_list()
    if not records:
        click.echo('No users.')
        return
    click.echo(f'{"NAME":<24}{"ROLE":<10}')
    for r in records:
        click.echo(f'{r["name"]:<24}{r["role"]:<10}')


@users.command(name='create')
@click.argument('name')
@click.argument('password')
@click.option('--role', default='user', type=click.Choice(
    ['admin', 'user']))
def users_create(name, password, role):
    from skypilot_tpu.client import sdk
    sdk.users_create(name, password, role)
    click.echo(f'User {name} ({role}) created.')


@users.command(name='delete')
@click.argument('name')
def users_delete(name):
    from skypilot_tpu.client import sdk
    sdk.users_delete(name)
    click.echo(f'User {name} deleted.')


@users.command(name='token-create')
@click.argument('name')
@click.option('--label', default='default',
              help='Revocation handle; unique per user.')
def users_token_create(name, label):
    """Mint a bearer API token (plaintext shown ONCE — save it)."""
    from skypilot_tpu.client import sdk
    record = sdk.users_token_create(name, label)
    click.echo(f"Token for {name} (label {label!r}):")
    click.echo(record['token'])
    click.echo('Use it as:  Authorization: Bearer <token>')


@users.command(name='token-ls')
@click.option('--name', default=None, help='Filter by user.')
def users_token_ls(name):
    from skypilot_tpu.client import sdk
    records = sdk.users_token_list(name)
    if not records:
        click.echo('No tokens.')
        return
    click.echo(f'{"USER":<24}{"LABEL":<16}{"LAST USED":<20}')
    for r in records:
        last = r.get('last_used_at')
        last_str = (datetime.datetime.fromtimestamp(last).strftime(
            '%Y-%m-%d %H:%M:%S') if last else '-')
        click.echo(f'{r["user_name"]:<24}{r["label"]:<16}{last_str:<20}')


@users.command(name='token-revoke')
@click.argument('name')
@click.argument('label')
def users_token_revoke(name, label):
    from skypilot_tpu.client import sdk
    result = sdk.users_token_revoke(name, label)
    click.echo('Revoked.' if result.get('revoked') else 'No such token.')


@users.command(name='set-role')
@click.argument('name')
@click.argument('role', type=click.Choice(['admin', 'user']))
def users_set_role(name, role):
    from skypilot_tpu.client import sdk
    sdk.users_set_role(name, role)
    click.echo(f'User {name} role set to {role}.')


@cli.group()
def workspaces():
    """Workspace management (multi-tenant cluster namespaces)."""


@workspaces.command(name='ls')
def workspaces_ls():
    from skypilot_tpu.client import sdk
    for name in sdk.workspaces_list():
        click.echo(name)


@workspaces.command(name='create')
@click.argument('name')
def workspaces_create(name):
    from skypilot_tpu.client import sdk
    sdk.workspaces_create(name)
    click.echo(f'Workspace {name} created.')


@workspaces.command(name='delete')
@click.argument('name')
def workspaces_delete(name):
    from skypilot_tpu.client import sdk
    sdk.workspaces_delete(name)
    click.echo(f'Workspace {name} deleted.')


@workspaces.command(name='add-member')
@click.argument('workspace')
@click.argument('user_name')
def workspaces_add_member(workspace, user_name):
    """Grant USER_NAME access to WORKSPACE (admin only)."""
    from skypilot_tpu.client import sdk
    sdk.workspaces_add_member(workspace, user_name)
    click.echo(f'{user_name} added to {workspace}.')


@workspaces.command(name='remove-member')
@click.argument('workspace')
@click.argument('user_name')
def workspaces_remove_member(workspace, user_name):
    """Revoke USER_NAME's access to WORKSPACE (admin only)."""
    from skypilot_tpu.client import sdk
    result = sdk.workspaces_remove_member(workspace, user_name)
    if result.get('removed'):
        click.echo(f'{user_name} removed from {workspace}.')
    else:
        raise click.ClickException(
            f'{user_name} was not a member of {workspace}.')


@workspaces.command(name='members')
@click.argument('workspace')
def workspaces_members(workspace):
    """List WORKSPACE's members."""
    from skypilot_tpu.client import sdk
    for name in sdk.workspaces_members(workspace):
        click.echo(name)


@workspaces.command(name='set-config')
@click.argument('workspace')
@click.argument('config_yaml', type=click.Path(exists=True))
def workspaces_set_config(workspace, config_yaml):
    """Store CONFIG_YAML as WORKSPACE's launch config overlay."""
    import yaml

    from skypilot_tpu.client import sdk
    with open(config_yaml, encoding='utf-8') as f:
        config = yaml.safe_load(f) or {}
    sdk.workspaces_set_config(workspace, config)
    click.echo(f'Config overlay set for {workspace}.')


@workspaces.command(name='get-config')
@click.argument('workspace')
def workspaces_get_config(workspace):
    """Print WORKSPACE's launch config overlay."""
    import yaml

    from skypilot_tpu.client import sdk
    click.echo(yaml.safe_dump(sdk.workspaces_get_config(workspace)))


def main() -> None:
    cli()


if __name__ == '__main__':
    main()
