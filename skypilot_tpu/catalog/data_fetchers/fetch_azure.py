"""Generate the Azure catalog CSV (twin of
sky/catalog/data_fetchers/fetch_azure.py).

The reference queries the Azure Retail Prices API per region; this
environment is zero-egress, so the checked-in CSV is generated from a
static table of the GPU/CPU SKUs the optimizer needs for cross-cloud
ranking (ND A100/H100, NC A100 v4, NCsv3 V100, NVads A10, D-series
CPU). Prices are representative public on-demand/spot rates (eastus,
2024-era); regenerate against the live Retail Prices API when egress
exists.

Run: python -m skypilot_tpu.catalog.data_fetchers.fetch_azure
"""
from __future__ import annotations

import csv
import os
from typing import List, Tuple

# (instance_type, acc_name, acc_count, vcpus, mem_gib, acc_mem_gib,
#  price, spot_price)
_SKUS: List[Tuple[str, str, float, float, float, float, float, float]] = [
    # CPU-only tiers (controllers / default instance type).
    ('Standard_D2s_v5', '', 0, 2, 8, 0, 0.0960, 0.0251),
    ('Standard_D4s_v5', '', 0, 4, 16, 0, 0.1920, 0.0502),
    ('Standard_D8s_v5', '', 0, 8, 32, 0, 0.3840, 0.1004),
    ('Standard_D16s_v5', '', 0, 16, 64, 0, 0.7680, 0.2008),
    ('Standard_D32s_v5', '', 0, 32, 128, 0, 1.5360, 0.4016),
    # V100 (NCsv3).
    ('Standard_NC6s_v3', 'V100', 1, 6, 112, 16, 3.0600, 0.6732),
    ('Standard_NC12s_v3', 'V100', 2, 12, 224, 32, 6.1200, 1.3464),
    ('Standard_NC24s_v3', 'V100', 4, 24, 448, 64, 12.2400, 2.6928),
    # A100 80GB (NC A100 v4 / ND A100 v4).
    ('Standard_NC24ads_A100_v4', 'A100-80GB', 1, 24, 220, 80,
     3.6730, 1.4692),
    ('Standard_NC48ads_A100_v4', 'A100-80GB', 2, 48, 440, 160,
     7.3460, 2.9384),
    ('Standard_NC96ads_A100_v4', 'A100-80GB', 4, 96, 880, 320,
     14.6920, 5.8768),
    ('Standard_ND96asr_v4', 'A100', 8, 96, 900, 320, 27.1970, 10.8788),
    ('Standard_ND96amsr_A100_v4', 'A100-80GB', 8, 96, 1900, 640,
     32.7700, 13.1080),
    # H100 (ND H100 v5).
    ('Standard_ND96isr_H100_v5', 'H100', 8, 96, 1900, 640,
     98.3200, 39.3280),
    # A10 (NVadsA10 v5) — the budget tier.
    ('Standard_NV6ads_A10_v5', 'A10', 0.167, 6, 55, 4, 0.4540, 0.0999),
    ('Standard_NV36ads_A10_v5', 'A10', 1, 36, 440, 24, 3.2000, 0.7040),
    ('Standard_NV72ads_A10_v5', 'A10', 2, 72, 880, 48, 6.5200, 1.4344),
    # T4 (NCasT4 v3).
    ('Standard_NC4as_T4_v3', 'T4', 1, 4, 28, 16, 0.5260, 0.1157),
    ('Standard_NC64as_T4_v3', 'T4', 4, 64, 440, 64, 4.3520, 0.9574),
]

# Region multipliers approximate real cross-region price spreads.
_REGIONS: List[Tuple[str, List[str], float]] = [
    ('eastus', ['eastus-1', 'eastus-2'], 1.00),
    ('westus2', ['westus2-1', 'westus2-2'], 1.00),
    ('westeurope', ['westeurope-1', 'westeurope-2'], 1.15),
]

HEADER = ['InstanceType', 'AcceleratorName', 'AcceleratorCount', 'vCPUs',
          'MemoryGiB', 'AcceleratorMemoryGiB', 'Price', 'SpotPrice',
          'Region', 'AvailabilityZone']


def rows() -> List[List[str]]:
    out = []
    for (itype, acc, count, vcpus, mem, acc_mem, price,
         spot) in _SKUS:
        for region, zones, mult in _REGIONS:
            for zone in zones:
                out.append([
                    itype, acc, f'{count:g}', f'{vcpus:g}', f'{mem:g}',
                    f'{acc_mem:g}', f'{price * mult:.4f}',
                    f'{spot * mult:.4f}', region, zone,
                ])
    return out


def main() -> None:
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(here, 'data', 'azure', 'catalog.csv')
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, 'w', newline='', encoding='utf-8') as f:
        writer = csv.writer(f)
        writer.writerow(HEADER)
        writer.writerows(rows())
    print(f'Wrote {path}')


if __name__ == '__main__':
    main()
