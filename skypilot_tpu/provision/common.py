"""Provisioner data model (twin of sky/provision/common.py:305).

TPU-first change: an *instance* here is always one **host**. A multi-host
TPU slice surfaces as N InstanceInfos sharing a `slice_id`, so higher
layers (gang launcher, rsync fan-out, rank math) iterate hosts uniformly —
the reference instead threads `num_ips_per_node` through the backend
(sky/backends/cloud_vm_ray_backend.py:2613) as a special case.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional


@dataclasses.dataclass
class InstanceInfo:
    instance_id: str
    internal_ip: str
    external_ip: Optional[str]
    status: str                      # PENDING | RUNNING | STOPPED | ...
    tags: Dict[str, str] = dataclasses.field(default_factory=dict)
    slice_id: Optional[str] = None   # TPU slice this host belongs to
    host_index: int = 0              # index within its slice
    ssh_port: int = 22

    def get_feasible_ip(self) -> str:
        return self.external_ip or self.internal_ip


@dataclasses.dataclass
class ProvisionConfig:
    """Input to run_instances for one cluster."""
    provider_config: Dict[str, Any]    # cloud-specific (project, etc.)
    node_config: Dict[str, Any]        # deploy vars from the Cloud
    count: int                         # logical nodes
    tags: Dict[str, str] = dataclasses.field(default_factory=dict)
    resume_stopped_nodes: bool = True
    ports_to_open_on_launch: Optional[List[str]] = None


@dataclasses.dataclass
class ProvisionRecord:
    """Output of a successful run_instances."""
    provider_name: str
    cluster_name: str
    region: str
    zone: Optional[str]
    resumed_instance_ids: List[str]
    created_instance_ids: List[str]
    head_instance_id: Optional[str] = None

    def is_instance_just_booted(self, instance_id: str) -> bool:
        return (instance_id in self.created_instance_ids or
                instance_id in self.resumed_instance_ids)


@dataclasses.dataclass
class ClusterInfo:
    """Full host inventory of a cluster (possibly multiple TPU slices)."""
    instances: Dict[str, InstanceInfo]
    head_instance_id: Optional[str]
    provider_name: str
    provider_config: Dict[str, Any] = dataclasses.field(default_factory=dict)
    ssh_user: str = 'root'
    custom_ray_options: Optional[Dict[str, Any]] = None  # unused (no Ray)
    # Idempotent per-host commands the backend runs at runtime setup
    # (volume mkfs/mount; provider-specific, built by get_cluster_info).
    mount_commands: List[str] = dataclasses.field(default_factory=list)

    def get_head_instance(self) -> Optional[InstanceInfo]:
        if self.head_instance_id is None:
            return None
        return self.instances.get(self.head_instance_id)

    def sorted_instances(self) -> List[InstanceInfo]:
        """Stable host order: head first, then by (slice_id, host_index).

        This ordering defines global host ranks for gang launch.
        """
        infos = list(self.instances.values())

        def key(i: InstanceInfo):
            is_head = (i.instance_id == self.head_instance_id)
            return (not is_head, i.slice_id or '', i.host_index,
                    i.instance_id)

        return sorted(infos, key=key)

    def get_feasible_ips(self, internal: bool = False) -> List[str]:
        return [
            i.internal_ip if internal else i.get_feasible_ip()
            for i in self.sorted_instances()
        ]

    @property
    def num_instances(self) -> int:
        return len(self.instances)

    # JSON (the head agent reads cluster_info.json to build worker runners)

    def to_json(self) -> Dict[str, Any]:
        return {
            'instances': {k: dataclasses.asdict(v)
                          for k, v in self.instances.items()},
            'head_instance_id': self.head_instance_id,
            'provider_name': self.provider_name,
            'provider_config': self.provider_config,
            'ssh_user': self.ssh_user,
            'mount_commands': self.mount_commands,
        }

    @classmethod
    def from_json(cls, data: Dict[str, Any]) -> 'ClusterInfo':
        return cls(
            instances={k: InstanceInfo(**v)
                       for k, v in data['instances'].items()},
            head_instance_id=data.get('head_instance_id'),
            provider_name=data['provider_name'],
            provider_config=data.get('provider_config', {}),
            ssh_user=data.get('ssh_user', 'root'),
            mount_commands=data.get('mount_commands', []),
        )
