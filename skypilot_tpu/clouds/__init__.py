"""Cloud implementations. Importing this package registers all clouds."""
from skypilot_tpu.clouds.cloud import Cloud
from skypilot_tpu.clouds.cloud import CloudImplementationFeatures
from skypilot_tpu.clouds.cloud import Region
from skypilot_tpu.clouds.aws import AWS
from skypilot_tpu.clouds.azure import Azure
from skypilot_tpu.clouds.cudo import Cudo
from skypilot_tpu.clouds.do import DO
from skypilot_tpu.clouds.docker import Docker
from skypilot_tpu.clouds.fake import Fake
from skypilot_tpu.clouds.fluidstack import Fluidstack
from skypilot_tpu.clouds.gcp import GCP
from skypilot_tpu.clouds.hyperbolic import Hyperbolic
from skypilot_tpu.clouds.ibm import IBM
from skypilot_tpu.clouds.kubernetes import Kubernetes
from skypilot_tpu.clouds.lambda_cloud import Lambda
from skypilot_tpu.clouds.nebius import Nebius
from skypilot_tpu.clouds.oci import OCI
from skypilot_tpu.clouds.paperspace import Paperspace
from skypilot_tpu.clouds.runpod import RunPod
from skypilot_tpu.clouds.scp import SCP
from skypilot_tpu.clouds.ssh import SSH
from skypilot_tpu.clouds.vast import Vast
from skypilot_tpu.clouds.vsphere import Vsphere

__all__ = ['Cloud', 'CloudImplementationFeatures', 'Region', 'GCP', 'Fake',
           'AWS', 'Azure', 'Cudo', 'DO', 'Docker', 'Fluidstack',
           'Hyperbolic', 'IBM', 'Kubernetes', 'Lambda', 'Nebius', 'OCI',
           'Paperspace', 'RunPod', 'SCP', 'SSH', 'Vast', 'Vsphere']
