"""RunPod provisioner tests against an in-memory GraphQL fake.

Same pattern as the Lambda/GCP/Azure fakes (role of the reference's
mocked runpod SDK): scripted capacity errors, no network.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import pytest

from skypilot_tpu import exceptions
from skypilot_tpu.provision import common
from skypilot_tpu.provision.runpod import instance as runpod_instance
from skypilot_tpu.provision.runpod import rest


class FakeRunPod:
    """Minimal in-memory RunPod GraphQL API."""

    def __init__(self) -> None:
        self.pods: Dict[str, Dict[str, Any]] = {}
        self.fail_deploy: Optional[rest.RunPodApiError] = None
        self.deploys: List[Dict[str, Any]] = []
        self._next_id = 0

    def _runtime(self, n: int) -> Dict[str, Any]:
        return {'ports': [{'ip': f'38.1.0.{n}', 'isIpPublic': True,
                           'privatePort': 22, 'publicPort': 10000 + n}]}

    def call(self, query: str,
             variables: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        variables = variables or {}
        if 'myself' in query:
            return {'myself': {'pods': list(self.pods.values())}}
        if 'podFindAndDeployOnDemand' in query or \
                'podRentInterruptable' in query:
            if self.fail_deploy is not None:
                err, self.fail_deploy = self.fail_deploy, None
                raise err
            payload = variables['input']
            self.deploys.append(payload)
            self._next_id += 1
            pid = f'pod-{self._next_id}'
            self.pods[pid] = {
                'id': pid, 'name': payload['name'],
                'desiredStatus': 'RUNNING',
                'gpuCount': payload['gpuCount'],
                'runtime': self._runtime(self._next_id),
            }
            field = ('podRentInterruptable' if 'podRentInterruptable'
                     in query else 'podFindAndDeployOnDemand')
            return {field: {'id': pid}}
        if 'podResume' in query:
            pod = self.pods[variables['podId']]
            pod['desiredStatus'] = 'RUNNING'
            pod['runtime'] = self._runtime(int(pod['id'].split('-')[1]))
            return {'podResume': {'id': pod['id']}}
        if 'podStop' in query:
            pod = self.pods[variables['podId']]
            pod['desiredStatus'] = 'EXITED'
            pod['runtime'] = None
            return {'podStop': {'id': pod['id'],
                                'desiredStatus': 'EXITED'}}
        if 'podTerminate' in query:
            self.pods.pop(variables['podId'], None)
            return {'podTerminate': None}
        raise AssertionError(f'unhandled RunPod query: {query[:60]}')


@pytest.fixture()
def fake_runpod(monkeypatch, tmp_path):
    fake = FakeRunPod()
    monkeypatch.setattr(runpod_instance, '_transport_factory',
                        lambda: fake)
    from skypilot_tpu import authentication
    monkeypatch.setattr(authentication, 'PRIVATE_KEY_PATH',
                        str(tmp_path / 'key'))
    monkeypatch.setattr(authentication, 'PUBLIC_KEY_PATH',
                        str(tmp_path / 'key.pub'))
    yield fake


PROVIDER: Dict[str, Any] = {}


def _config(count=1, spot=False):
    node_config = {'instance_type': '1x_H100', 'gpu_type_id':
                   'NVIDIA H100 PCIe', 'gpu_count': 1,
                   'image_name': 'runpod/base:0.6.2-cuda12.4.1',
                   'use_spot': spot}
    if spot:
        node_config['bid_per_gpu'] = 1.20
    return common.ProvisionConfig(provider_config=dict(PROVIDER),
                                  node_config=node_config, count=count)


def test_launch_lifecycle(fake_runpod):
    record = runpod_instance.run_instances('US-GA-1', None, 'c1',
                                           _config(count=2))
    assert len(record.created_instance_ids) == 2
    assert record.head_instance_id is not None
    info = runpod_instance.get_cluster_info('US-GA-1', 'c1', PROVIDER)
    assert info.num_instances == 2
    hosts = info.sorted_instances()
    assert info.head_instance_id == hosts[0].instance_id
    # SSH rides the mapped public port, not 22.
    assert all(h.ssh_port >= 10000 for h in hosts)
    assert all(h.external_ip for h in hosts)
    assert info.ssh_user == 'root'
    runpod_instance.terminate_instances('c1', PROVIDER)
    assert runpod_instance.query_instances('c1', PROVIDER) == {}


def test_stop_resume_cycle(fake_runpod):
    runpod_instance.run_instances('US-GA-1', None, 'c2', _config())
    runpod_instance.stop_instances('c2', PROVIDER)
    statuses = runpod_instance.query_instances('c2', PROVIDER)
    assert set(statuses.values()) == {'STOPPED'}
    # run_instances on a stopped cluster resumes in place: same pod id,
    # no new deploys.
    deploys_before = len(fake_runpod.deploys)
    record = runpod_instance.run_instances('US-GA-1', None, 'c2',
                                           _config())
    assert record.created_instance_ids == []
    assert len(record.resumed_instance_ids) == 1
    assert len(fake_runpod.deploys) == deploys_before
    statuses = runpod_instance.query_instances('c2', PROVIDER)
    assert set(statuses.values()) == {'RUNNING'}


def test_spot_launch_carries_bid(fake_runpod):
    runpod_instance.run_instances('US-GA-1', None, 'c3',
                                  _config(spot=True))
    assert fake_runpod.deploys[-1]['bidPerGpu'] == pytest.approx(1.20)


def test_gap_fill_relaunch(fake_runpod):
    runpod_instance.run_instances('US-GA-1', None, 'c4',
                                  _config(count=3))
    # Node 1 reclaimed out-of-band.
    gone = [pid for pid, p in fake_runpod.pods.items()
            if p['name'] == 'c4-1']
    fake_runpod.pods.pop(gone[0])
    runpod_instance.run_instances('US-GA-1', None, 'c4',
                                  _config(count=3))
    names = sorted(p['name'] for p in fake_runpod.pods.values())
    assert names == ['c4-0', 'c4-1', 'c4-2']


def test_capacity_error_classified(fake_runpod):
    fake_runpod.fail_deploy = rest.RunPodApiError(
        200, 'There are no longer any instances available with the '
        'requested specifications.')
    with pytest.raises(exceptions.CapacityError):
        runpod_instance.run_instances('US-GA-1', None, 'c5', _config())


def test_wait_instances_needs_ssh_port(fake_runpod):
    runpod_instance.run_instances('US-GA-1', None, 'c6', _config())
    runpod_instance.wait_instances('US-GA-1', 'c6', 'RUNNING', PROVIDER,
                                   timeout_s=5, poll_interval_s=0.01)
    # RUNNING without a port mapping is NOT ready (container booting).
    for pod in fake_runpod.pods.values():
        pod['runtime'] = None
    with pytest.raises(exceptions.ProvisionError):
        runpod_instance.wait_instances('US-GA-1', 'c6', 'RUNNING',
                                       PROVIDER, timeout_s=0.2,
                                       poll_interval_s=0.01)


def test_cloud_feasibility_and_pricing():
    """Catalog-backed: spot offerings priced off the community rate."""
    from skypilot_tpu import resources as resources_lib
    from skypilot_tpu.utils import registry
    cloud = registry.CLOUD_REGISTRY.from_str('runpod')
    r = resources_lib.Resources(accelerators='H100:1')
    feasible, _ = cloud.get_feasible_launchable_resources(r)
    assert feasible
    assert feasible[0].instance_type == '1x_H100'
    assert feasible[0].get_hourly_cost() == pytest.approx(2.39)
    spot = resources_lib.Resources(accelerators='H100:1', use_spot=True)
    feasible, _ = cloud.get_feasible_launchable_resources(spot)
    assert feasible
    assert feasible[0].get_hourly_cost() == pytest.approx(1.20)


def test_deploy_variables_spot_bid():
    from skypilot_tpu import resources as resources_lib
    from skypilot_tpu.utils import registry
    cloud = registry.CLOUD_REGISTRY.from_str('runpod')
    r = resources_lib.Resources(cloud=cloud, instance_type='2x_H100',
                                accelerators='H100:2', use_spot=True)
    vars = cloud.make_deploy_resources_variables(r, 'c', 'US-GA-1', None)
    assert vars['gpu_type_id'] == 'NVIDIA H100 PCIe'
    assert vars['gpu_count'] == 2
    # Bid is per GPU: the 2-GPU spot price halved.
    assert vars['bid_per_gpu'] == pytest.approx(1.20)
    # The requested disk must reach the provisioner (it defaults its
    # own fallback otherwise).
    assert vars['disk_size'] == r.disk_size


def test_check_credentials(monkeypatch, tmp_path):
    from skypilot_tpu.utils import registry
    cloud = registry.CLOUD_REGISTRY.from_str('runpod')
    monkeypatch.delenv('RUNPOD_API_KEY', raising=False)
    monkeypatch.setattr(rest, 'CONFIG_PATH', str(tmp_path / 'config.toml'))
    ok, reason = cloud.check_credentials()
    assert not ok and 'RUNPOD_API_KEY' in reason
    (tmp_path / 'config.toml').write_text('api_key = "rp_secret"\n')
    assert rest.load_api_key() == 'rp_secret'
    ok, _ = cloud.check_credentials()
    assert ok
