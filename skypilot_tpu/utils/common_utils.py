"""Small shared helpers (ids, validation, unit parsing, user identity)."""
from __future__ import annotations

import functools
import getpass
import hashlib
import os
import random
import re
import socket
import time
import uuid
from typing import Any, Dict, Optional, Union

_CLUSTER_NAME_RE = re.compile(r'^[a-z]([a-z0-9-]*[a-z0-9])?$')

# Relative-duration suffixes. ONE parser for every surface that takes
# a human duration (`xsky events --since 5m`, `xsky metrics query
# --since 1h --step 1m`) — two parsers with different unit tables is
# exactly the drift the env/names registries exist to prevent.
DURATION_UNITS = {'s': 1.0, 'm': 60.0, 'h': 3600.0, 'd': 86400.0}


def parse_duration_s(value: Union[str, int, float]) -> float:
    """Duration → seconds: bare numbers are seconds ('90', 90, 1.5),
    a trailing unit scales ('30s', '15m', '2h', '1d'; case-
    insensitive). Raises ValueError on anything else."""
    if isinstance(value, (int, float)):
        return float(value)
    v = str(value).strip()
    if v and v[-1].lower() in DURATION_UNITS:
        return float(v[:-1]) * DURATION_UNITS[v[-1].lower()]
    return float(v)

_run_id: Optional[str] = None


def pid_alive(pid: Optional[int]) -> bool:
    """Is a process with this pid running (signal-0 probe)?

    A zombie counts as DEAD: it no longer executes anything (a killed
    controller whose parent hasn't reaped it yet would otherwise look
    alive and block HA re-exec).
    """
    if not pid:
        return False
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    try:
        with open(f'/proc/{pid}/stat', encoding='utf-8',
                  errors='replace') as f:
            # Field 3 (after the parenthesized comm, which may itself
            # contain spaces) is the state; 'Z' = zombie.
            return f.read().rpartition(')')[2].split()[0] != 'Z'
    except (OSError, IndexError):
        return True   # no procfs (macOS): keep the signal-0 answer


def get_usage_run_id() -> str:
    """Stable id for one client invocation (log correlation)."""
    global _run_id
    if _run_id is None:
        _run_id = str(uuid.uuid4())
    return _run_id


def get_user_hash() -> str:
    """Stable 8-hex id of the local user, overridable for tests."""
    forced = os.environ.get('XSKY_USER_HASH')
    if forced:
        return forced
    ident = f'{getpass.getuser()}@{socket.gethostname()}'
    return hashlib.md5(ident.encode()).hexdigest()[:8]


def get_global_job_id(job_timestamp: str, cluster_name: str,
                      job_id: Union[int, str]) -> str:
    return f'{job_timestamp}_{cluster_name}_id-{job_id}'


def base36_encode(num: int) -> str:
    chars = '0123456789abcdefghijklmnopqrstuvwxyz'
    if num == 0:
        return '0'
    out = []
    while num:
        num, rem = divmod(num, 36)
        out.append(chars[rem])
    return ''.join(reversed(out))


def fresh_cluster_suffix(length: int = 4) -> str:
    return base36_encode(int(time.time() * 1e6))[-length:]


def check_cluster_name_is_valid(name: Optional[str]) -> None:
    """Cluster names must be valid DNS-ish labels (cloud resource names)."""
    if name is None:
        return
    if len(name) > 63 or not _CLUSTER_NAME_RE.match(name):
        raise ValueError(
            f'Cluster name {name!r} is invalid: must match '
            "[a-z]([a-z0-9-]*[a-z0-9])? and be <= 63 chars.")


def parse_memory_gb(mem: Union[str, int, float, None]) -> Optional[float]:
    """Parse '16', '16+', '16GB', 16 → 16.0 (the '+' is handled by caller)."""
    if mem is None:
        return None
    if isinstance(mem, (int, float)):
        return float(mem)
    s = str(mem).strip().lower().rstrip('+')
    for suffix in ('gib', 'gb', 'g'):
        if s.endswith(suffix):
            s = s[:-len(suffix)]
            break
    return float(s)


def format_float(x: Union[int, float], precision: int = 2) -> str:
    if isinstance(x, int) or float(x).is_integer():
        return str(int(x))
    return f'{x:.{precision}f}'


def truncate_long_string(s: str, max_length: int = 35) -> str:
    if len(s) <= max_length:
        return s
    return s[:max_length - 3] + '...'


def dump_yaml_str(config: Dict[str, Any]) -> str:
    import yaml
    return yaml.safe_dump(config, sort_keys=False, default_flow_style=False)


def read_yaml(path: str) -> Dict[str, Any]:
    import yaml
    with open(os.path.expanduser(path), 'r', encoding='utf-8') as f:
        return yaml.safe_load(f) or {}


def make_decorator(check_fn):
    """Build a decorator that runs check_fn() before the wrapped call."""

    def decorator(fn):

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            check_fn()
            return fn(*args, **kwargs)

        return wrapper

    return decorator


class Backoff:
    """Capped exponential backoff, optionally jittered.

    jitter=0 (the default) keeps the old fully-deterministic sequence;
    jitter=j spreads each value uniformly over [v*(1-j), v*(1+j)] so
    synchronized retriers (a preemption storm's worth of recovering
    controllers) don't stampede in lockstep. Pass a seed to make the
    jittered sequence deterministic too (tests).
    """

    def __init__(self, initial: float = 1.0, factor: float = 1.6,
                 cap: float = 30.0, jitter: float = 0.0,
                 seed: Optional[int] = None) -> None:
        assert 0.0 <= jitter < 1.0, jitter
        self._next = initial
        self._factor = factor
        self._cap = cap
        self._jitter = jitter
        self._rng = random.Random(seed) if jitter else None

    def current_backoff(self) -> float:
        value = self._next
        self._next = min(self._next * self._factor, self._cap)
        if self._rng is not None:
            # Jitter AFTER capping, unclamped: retriers parked at the
            # cap must keep their full ±j spread, or a preemption
            # storm's worth of them re-synchronize on exactly `cap`.
            value *= 1.0 + self._jitter * (2.0 * self._rng.random() - 1.0)
        return value
