"""Workspace operations (twin of sky/workspaces/core.py, 679 LoC).

A workspace is a namespace over clusters: every cluster record carries a
workspace tag; status filters by workspace when one is pinned (request
body or XSKY_WORKSPACE) and shows all otherwise, and a workspace cannot
be deleted while it still owns clusters. The reference additionally
scopes config overlays per workspace; here the task `config:` overlay
plays that role.
"""
from __future__ import annotations

import re
from typing import Any, Dict, List

from skypilot_tpu import state

_NAME_RE = re.compile(r'^[a-z0-9][a-z0-9-]{0,48}$')
DEFAULT_WORKSPACE = 'default'


def get_workspaces() -> List[str]:
    return state.list_workspaces()


def create_workspace(name: str) -> Dict[str, Any]:
    if not _NAME_RE.match(name):
        raise ValueError(
            f'Invalid workspace name {name!r} (lowercase alphanumeric + '
            'dashes, max 49 chars).')
    state.add_workspace(name)
    return {'name': name}


def delete_workspace(name: str) -> Dict[str, Any]:
    if name == DEFAULT_WORKSPACE:
        raise ValueError('The default workspace cannot be deleted.')
    clusters = state.get_clusters(workspace=name)
    if clusters:
        raise ValueError(
            f'Workspace {name!r} still has {len(clusters)} cluster(s): '
            f'{[c["name"] for c in clusters]}. Tear them down first.')
    return {'deleted': state.delete_workspace(name)}


def validate_exists(name: str) -> str:
    if name not in state.list_workspaces():
        raise ValueError(f'Workspace {name!r} does not exist; create it '
                         'with `xsky workspaces create`.')
    return name
