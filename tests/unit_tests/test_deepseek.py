"""DeepSeek family: MLA attention (latent KV + decoupled RoPE, absorbed
decode) and DeepSeekMoE (shared + routed experts), trainer + engine
integration on the 8-device mesh."""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu import models
from skypilot_tpu.models import deepseek
from skypilot_tpu.parallel import mesh as mesh_lib

pytestmark = pytest.mark.slow  # heavy tier: jit compiles


@pytest.fixture(scope='module')
def tiny():
    return deepseek.DEEPSEEK_TINY


@pytest.fixture(scope='module')
def params(tiny):
    return deepseek.init(tiny, jax.random.PRNGKey(0))


class TestDeepSeekForward:

    def test_logits_shape_and_param_count(self, tiny, params):
        tokens = jnp.zeros((2, 16), jnp.int32)
        logits = deepseek.forward(tiny, params, tokens)
        assert logits.shape == (2, 16, tiny.vocab_size)
        assert logits.dtype == jnp.float32
        n = sum(x.size for x in jax.tree.leaves(params))
        assert n == tiny.num_params()
        assert tiny.active_params() < tiny.num_params()

    def test_moe_only_variant_param_count(self):
        c = deepseek.DEEPSEEK_TINY_MOE_ONLY
        p = deepseek.init(c, jax.random.PRNGKey(1))
        assert 'dense_layers' not in p
        assert 'wq' in p['moe_layers']          # full-rank q corner
        assert 'w_dq' not in p['moe_layers']
        n = sum(x.size for x in jax.tree.leaves(p))
        assert n == c.num_params()

    def test_causality(self, tiny, params):
        t1 = jnp.zeros((1, 8), jnp.int32)
        t2 = t1.at[0, 7].set(5)
        l1 = deepseek.forward(tiny, params, t1)
        l2 = deepseek.forward(tiny, params, t2)
        np.testing.assert_allclose(np.asarray(l1[0, :7]),
                                   np.asarray(l2[0, :7]), atol=1e-5)

    def test_rope_branch_is_live(self, tiny, params):
        """Zeroing w_kr must change logits (the decoupled-RoPE key
        branch actually participates in attention)."""
        tokens = jax.random.randint(jax.random.PRNGKey(2), (1, 8), 0,
                                    tiny.vocab_size)
        base = deepseek.forward(tiny, params, tokens)
        for group in ('dense_layers', 'moe_layers'):
            zeroed = {**params, group: {**params[group],
                                        'w_kr':
                                        params[group]['w_kr'] * 0.0}}
            out = deepseek.forward(tiny, zeroed, tokens)
            assert float(jnp.abs(out - base).max()) > 1e-4

    def test_shared_experts_are_live(self, tiny, params):
        tokens = jax.random.randint(jax.random.PRNGKey(3), (1, 8), 0,
                                    tiny.vocab_size)
        base = deepseek.forward(tiny, params, tokens)
        zeroed = {**params,
                  'moe_layers': {**params['moe_layers'],
                                 'ws_down':
                                 params['moe_layers']['ws_down'] * 0.0}}
        out = deepseek.forward(tiny, zeroed, tokens)
        assert float(jnp.abs(out - base).max()) > 1e-4

    def test_loss_decreases_under_sgd(self, tiny):
        params = deepseek.init(tiny, jax.random.PRNGKey(4))
        tokens = jax.random.randint(jax.random.PRNGKey(5), (2, 16), 0,
                                    tiny.vocab_size)
        targets = jnp.roll(tokens, -1, axis=1)
        loss0, grads = jax.value_and_grad(
            lambda p: deepseek.loss_fn(tiny, p, tokens, targets))(params)
        params2 = jax.tree.map(
            lambda p, g: (p - 0.5 * g.astype(p.dtype)), params, grads)
        loss1 = deepseek.loss_fn(tiny, params2, tokens, targets)
        assert float(loss1) < float(loss0)

    def test_registry_dispatch(self, tiny):
        assert models.module_for(tiny) is deepseek
        assert models.get_config('deepseek-v3') is deepseek.DEEPSEEK_V3
        assert models.get_config('deepseek-v2-lite') is \
            deepseek.DEEPSEEK_V2_LITE

    def test_compressed_cache_shapes(self, tiny):
        k_shape, v_shape = deepseek.kv_cache_shapes(tiny, 4, 32)
        assert k_shape == (tiny.n_layers, 4, 32, 1, tiny.kv_lora_rank)
        assert v_shape == (tiny.n_layers, 4, 32, 1,
                           tiny.qk_rope_head_dim)
        # The point of MLA: compressed row much smaller than a dense
        # KV row would be (2 sides × heads × head_dim).
        dense_row = 2 * tiny.n_heads * tiny.qk_head_dim
        mla_row = tiny.kv_lora_rank + tiny.qk_rope_head_dim
        assert mla_row < dense_row


class TestDeepSeekServing:

    @pytest.mark.parametrize('config_name',
                             ['deepseek-tiny', 'deepseek-tiny-moe-only'])
    def test_cached_decode_matches_full_forward(self, config_name):
        """Absorbed decode over the compressed cache equals the full
        expanded re-forward, greedy — for the dense+q_lora variant and
        the moe-only full-rank-q variant.

        Decode routing uses capacity == slot count (no drops); the full
        forward must route identically, pinned via a roomy
        capacity_factor as in the MoE family test."""
        from skypilot_tpu.infer import engine as engine_lib
        from skypilot_tpu.infer import orchestrator as orch_lib
        c = models.get_config(config_name)
        c = dataclasses.replace(c, capacity_factor=float(c.n_experts))
        params = deepseek.init(c, jax.random.PRNGKey(0))
        config = engine_lib.EngineConfig(
            model=c, max_slots=2, max_target_len=32,
            prefill_buckets=(16,))
        engine = engine_lib.InferenceEngine(config, params)
        # The engine allocated the compressed layout.
        state = engine.init_decode_state()
        assert state['kv_k'].shape[-1] == c.kv_lora_rank
        assert state['kv_v'].shape[-1] == c.qk_rope_head_dim

        prompt = [5, 17, 3, 99, 42]
        n_new = 6
        tokens = list(prompt)
        for _ in range(n_new):
            logits = deepseek.forward(c, params,
                                      jnp.asarray([tokens], jnp.int32))
            tokens.append(int(jnp.argmax(logits[0, -1])))
        expected = tokens[len(prompt):]

        orch = orch_lib.Orchestrator(engine)
        outputs = orch.generate([prompt], max_new_tokens=n_new)
        assert outputs[0] == expected

    def test_sharded_engine_allocates_compressed_cache(self, tiny):
        """A tensor-parallel mesh must not try to split the MLA cache's
        size-1 latent-head axis (regression: ValueError at
        init_decode_state on tensor>=2 meshes)."""
        from skypilot_tpu.infer import engine as engine_lib
        from skypilot_tpu.infer import orchestrator as orch_lib
        c = dataclasses.replace(tiny,
                                capacity_factor=float(tiny.n_experts))
        params = deepseek.init(c, jax.random.PRNGKey(0))
        mesh = mesh_lib.build_mesh(
            mesh_lib.MeshPlan(data=2, fsdp=2, tensor=2).resolve(8))
        config = engine_lib.EngineConfig(
            model=c, max_slots=4, max_target_len=32,
            prefill_buckets=(16,))
        engine = engine_lib.InferenceEngine(config, params, mesh=mesh)
        state = engine.init_decode_state()
        assert state['kv_k'].shape[-1] == c.kv_lora_rank
        orch = orch_lib.Orchestrator(engine)
        outputs = orch.generate([[5, 17, 3]], max_new_tokens=3)
        assert len(outputs[0]) == 3

    def test_int8_kv_rejected_for_compressed_cache(self, tiny, params):
        from skypilot_tpu.infer import engine as engine_lib
        config = engine_lib.EngineConfig(
            model=tiny, max_slots=2, max_target_len=32,
            prefill_buckets=(16,), kv_dtype=jnp.int8)
        with pytest.raises(NotImplementedError):
            engine_lib.InferenceEngine(config, params)


class TestDeepSeekSharded:

    def test_trainer_step_on_mesh_with_expert_axis(self, tiny):
        from skypilot_tpu.train import trainer as trainer_lib
        plan = mesh_lib.MeshPlan(data=2, fsdp=2, expert=2)
        config = trainer_lib.TrainConfig(
            model=dataclasses.replace(tiny, remat=True),
            global_batch_size=4, seq_len=32,
            optimizer='adafactor', warmup_steps=1,
            mesh_plan=plan)
        trainer = trainer_lib.Trainer(config)
        state = trainer.init_state()
        batch = trainer.synthetic_batch(0)
        state, metrics = trainer.step(state, batch)
        loss_first = float(metrics['loss'])
        # The router aux term makes single-step deltas noisy; a few
        # steps on one batch must still show clear net progress.
        for _ in range(5):
            state, metrics = trainer.step(state, batch)
        assert float(metrics['loss']) < loss_first - 0.01

    def test_pipeline_parallel_moe_only_stack(self):
        """GPipe over the uniform MoE stack (first_k_dense == 0)."""
        from skypilot_tpu.train import trainer as trainer_lib
        c = dataclasses.replace(deepseek.DEEPSEEK_TINY_MOE_ONLY,
                                remat=True)
        config = trainer_lib.TrainConfig(
            model=c, global_batch_size=4, seq_len=32,
            optimizer='adafactor', warmup_steps=1, n_microbatches=2,
            learning_rate=1e-2,
            mesh_plan=mesh_lib.MeshPlan(data=2, stage=2, expert=2))
        trainer = trainer_lib.Trainer(config)
        state = trainer.init_state()
        batch = trainer.synthetic_batch(0)
        state, metrics = trainer.step(state, batch)
        loss_first = float(metrics['loss'])
        for _ in range(5):
            state, metrics = trainer.step(state, batch)
        assert float(metrics['loss']) < loss_first - 0.01

    def test_pipeline_rejects_dense_prologue(self):
        """Rejected at trainer CONSTRUCTION, before any sharded init."""
        from skypilot_tpu import exceptions
        from skypilot_tpu.train import trainer as trainer_lib
        config = trainer_lib.TrainConfig(
            model=deepseek.DEEPSEEK_TINY,   # first_k_dense = 1
            global_batch_size=4, seq_len=32, n_microbatches=2,
            mesh_plan=mesh_lib.MeshPlan(data=2, stage=2, expert=2))
        with pytest.raises(exceptions.NotSupportedError,
                           match='first_k_dense'):
            trainer_lib.Trainer(config)

    def test_sharded_matches_single_device(self, tiny, params):
        tokens = jax.random.randint(jax.random.PRNGKey(6), (4, 16), 0,
                                    tiny.vocab_size)
        targets = jnp.roll(tokens, -1, axis=1)
        ref = deepseek.loss_fn(tiny, params, tokens, targets)
        mesh = mesh_lib.build_mesh(
            mesh_lib.MeshPlan(data=2, fsdp=2, expert=2).resolve(8))
        sharded = deepseek.loss_fn(tiny, params, tokens, targets,
                                   mesh=mesh)
        np.testing.assert_allclose(float(ref), float(sharded), rtol=2e-3)


class TestDeepSeekPagedKv:
    """The paged compressed-latent cache (shared page arenas for c_kv
    and k_rope) must be bit-identical to the dense per-slot layout."""

    def test_paged_decode_matches_dense(self, tiny):
        from skypilot_tpu.infer import engine as engine_lib
        from skypilot_tpu.infer import orchestrator as orch_lib
        c = dataclasses.replace(tiny,
                                capacity_factor=float(tiny.n_experts))
        params = deepseek.init(c, jax.random.PRNGKey(0))
        # Prompts straddle the page_size=8 boundary and generations
        # cross into later pages mid-decode.
        prompts = [[5, 17, 3, 99, 42, 6, 7],
                   [1, 2, 3, 4, 5, 6, 7, 8, 9]]
        n_new = 10

        def run(page_size):
            config = engine_lib.EngineConfig(
                model=c, max_slots=2, max_target_len=32,
                prefill_buckets=(16,), kv_page_size=page_size)
            engine = engine_lib.InferenceEngine(config, params)
            orch = orch_lib.Orchestrator(engine, decode_steps=4)
            return orch.generate(prompts, max_new_tokens=n_new), engine

        dense_out, _ = run(0)
        paged_out, engine = run(8)
        assert paged_out == dense_out
        assert all(len(o) == n_new for o in dense_out)
        state = engine.init_decode_state()
        # Paged compressed layout: [L, pages, page, 1, rank/rope].
        assert state['kv_k'].shape[2] == 8
        assert state['kv_k'].shape[-1] == c.kv_lora_rank
        assert state['kv_v'].shape[-1] == c.qk_rope_head_dim
        assert 'block_tables' in state
