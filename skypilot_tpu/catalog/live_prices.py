"""Live price refresh for the snapshot catalogs.

Twin of the reference's live fetchers: its GCP fetcher queries the Cloud
Billing SKU service (sky/catalog/data_fetchers/fetch_gcp.py:34-83) and its
Azure fetcher pages the public Retail Prices API
(sky/catalog/data_fetchers/fetch_azure.py). This repo's offline generators
embed price snapshots so everything works with zero egress; prices rot,
though, so this module patches the generated entries with *live* unit
prices whenever network (and, for GCP, credentials) are available:

  * GCP — Cloud Billing ``services/{id}/skus``, authenticated with the
    same token chain as the provisioner (`provision/gcp/rest.py`): TPU
    per-chip-hour SKUs by region. TPU slice rows are repriced as
    ``chip_price * num_chips`` via the topology database, so live prices
    stay consistent across every slice size by construction.
  * Azure — Retail Prices API (public, unauthenticated): per-VM-size
    on-demand + spot consumption rates by region.

Scope is deliberately the rows the optimizer ranks on: TPU slices (the
flagship) and Azure VM sizes. GCP GPU-VM prices are a composition of
per-core, per-GiB and per-GPU SKUs in the billing API (the reference
spends ~700 LoC decomposing them); the snapshot keeps covering those.

Failure contract mirrors `hosted.py`: any error leaves the snapshot
catalog untouched — stale prices beat a missing catalog. Never called on
the task hot path; run explicitly (``python -m
skypilot_tpu.catalog.live_prices gcp azure``) or via
``tools/build_hosted_catalog.py --live``.
"""
from __future__ import annotations

import dataclasses
import json
import os
import urllib.parse
import urllib.request
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from skypilot_tpu import exceptions
from skypilot_tpu import sky_logging
from skypilot_tpu.catalog import common
from skypilot_tpu.utils import tpu_topology

logger = sky_logging.init_logger(__name__)

# Cloud Billing TPU service ID (stable, listed at cloud.google.com/skus).
# The GCE service (6F81-5844-456A) is deliberately NOT queried: GPU-VM
# prices are a composition of per-core/per-GiB/per-GPU SKUs (see module
# docstring) and stay on the snapshot.
TPU_SERVICE_ID = 'E000-3F24-B8AA'

_BILLING_URL = ('https://cloudbilling.googleapis.com/v1/services/'
                '{service}/skus?pageSize=5000')
_AZURE_RETAIL_BASE = 'https://prices.azure.com/api/retail/prices'

# fetch_json(url, headers) -> parsed JSON body. Injectable for tests.
FetchJson = Callable[[str, Dict[str, str]], dict]


def _default_fetch(url: str, headers: Dict[str, str]) -> dict:
    req = urllib.request.Request(url, headers=headers)
    with urllib.request.urlopen(req, timeout=30) as resp:
        return json.loads(resp.read())


# --------------------------------------------------------------------------
# GCP: Cloud Billing SKU service


def _gcp_token() -> str:
    from skypilot_tpu.provision.gcp import rest as gcp_rest
    return gcp_rest.TokenProvider().token()


def iter_gcp_skus(service_id: str,
                  fetch: FetchJson,
                  token: str) -> Iterable[dict]:
    """Yield every SKU object for a billing service, following pages."""
    headers = {'Authorization': f'Bearer {token}'}
    url = _BILLING_URL.format(service=service_id)
    while True:
        page = fetch(url, headers)
        yield from page.get('skus', [])
        next_token = page.get('nextPageToken')
        if not next_token:
            return
        url = (_BILLING_URL.format(service=service_id) +
               '&pageToken=' + urllib.parse.quote(next_token))


def _sku_unit_price(sku: dict) -> Optional[float]:
    """$/usage-unit from the last (highest) tiered rate, like the ref."""
    try:
        rates = sku['pricingInfo'][0]['pricingExpression']['tieredRates']
        unit = rates[-1]['unitPrice']
        return float(unit.get('units') or 0) + unit.get('nanos', 0) * 1e-9
    except (KeyError, IndexError, TypeError, ValueError):
        return None


def gcp_tpu_chip_prices(
        skus: Iterable[dict]) -> Dict[Tuple[str, str], Dict[str, float]]:
    """(generation, region) -> {'od': $/chip-hr, 'spot': $/chip-hr}.

    TPU SKU descriptions name the generation ('Tpu-v5p ...', 'Tpu v4
    pod ...'); spot SKUs carry 'Preemptible'/'Spot' in the description or
    usageType. Commitment (1yr/3yr) SKUs are skipped — only OnDemand and
    Preemptible usage maps onto the catalog's price columns. Where one
    generation has both 'device' and 'pod' SKU variants (v5e), the pod
    rate wins: the catalog prices whole slices, and pod rates are what
    multi-host slices bill at. Unparseable SKUs are skipped — a partial
    live map is fine because apply() only patches rows it has live data
    for.
    """
    prices: Dict[Tuple[str, str], Dict[str, float]] = {}
    from_pod: Dict[Tuple[str, str, str], bool] = {}
    for sku in skus:
        category = sku.get('category', {})
        if category.get('resourceGroup') != 'TPU':
            continue
        usage = category.get('usageType', 'OnDemand')
        if usage not in ('OnDemand', 'Preemptible'):
            continue  # Commit1Yr/Commit3Yr etc.
        desc = sku.get('description', '')
        if 'Commitment' in desc:
            continue
        desc_l = desc.lower().replace(' ', '-')
        gen = None
        for name in tpu_topology.GENERATIONS:
            # 'tpu-v5e', and the SKU spellings 'tpu-v5-lite*' for v5e.
            if f'tpu-{name}' in desc_l:
                gen = name
                break
        if gen is None and 'tpu-v5-lite' in desc_l:
            gen = 'v5e'
        if gen is None:
            continue
        price = _sku_unit_price(sku)
        if price is None or price <= 0:
            continue
        spot = ('Preemptible' in desc or 'Spot' in desc
                or usage == 'Preemptible')
        kind = 'spot' if spot else 'od'
        pod = 'pod' in desc_l
        for region in sku.get('serviceRegions', []):
            slot = prices.setdefault((gen, region), {})
            key = (gen, region, kind)
            # Last-write-wins would make prices depend on API ordering;
            # instead a pod-variant rate always beats a device-variant
            # one, and ties keep the first seen.
            if kind in slot and (from_pod[key] or not pod):
                continue
            slot[kind] = price
            from_pod[key] = pod
    return prices


def apply_gcp_live(
    entries: List[common.CatalogEntry],
    chip_prices: Dict[Tuple[str, str], Dict[str, float]],
) -> Tuple[List[common.CatalogEntry], int]:
    """Reprice TPU slice rows from live per-chip prices.

    Rows without live data (unknown region/generation, GPU/CPU VMs) pass
    through unchanged. Returns (entries, patched_count).
    """
    out: List[common.CatalogEntry] = []
    patched = 0
    for entry in entries:
        if not entry.is_tpu:
            out.append(entry)
            continue
        try:
            topo = tpu_topology.parse(entry.accelerator_name)
        except (ValueError, exceptions.SkyTpuError):
            # parse raises InvalidRequestError (a SkyTpuError) for
            # unknown generations/shapes; one odd snapshot row must not
            # abort the whole refresh.
            out.append(entry)
            continue
        live = chip_prices.get((topo.generation.name, entry.region))
        if not live:
            out.append(entry)
            continue
        od = live.get('od')
        spot = live.get('spot')
        entry = dataclasses.replace(
            entry,
            price=(od * topo.num_chips if od is not None else entry.price),
            spot_price=(spot * topo.num_chips
                        if spot is not None else entry.spot_price))
        patched += 1
        out.append(entry)
    return out, patched


# --------------------------------------------------------------------------
# Azure: Retail Prices API (public)


def azure_retail_url(regions: Iterable[str]) -> str:
    """Retail Prices query scoped to the catalog's regions.

    The unrestricted 'Virtual Machines' dataset is hundreds of thousands
    of rows at ~100/page; constraining armRegionName to the handful of
    regions the catalog actually covers keeps a --live run to a few
    pages. The $filter value is URL-encoded (it contains spaces and
    quotes; urllib refuses raw spaces in a request URL).
    """
    clauses = ' or '.join(f"armRegionName eq '{r}'" for r in sorted(regions))
    filt = ("serviceName eq 'Virtual Machines' and "
            "priceType eq 'Consumption'")
    if clauses:
        filt += f' and ({clauses})'
    return _AZURE_RETAIL_BASE + '?$filter=' + urllib.parse.quote(filt)


def iter_azure_prices(fetch: FetchJson,
                      regions: Iterable[str]) -> Iterable[dict]:
    url = azure_retail_url(regions)
    while url:
        page = fetch(url, {})
        yield from page.get('Items', [])
        url = page.get('NextPageLink') or ''


def azure_vm_prices(
        items: Iterable[dict]) -> Dict[Tuple[str, str], Dict[str, float]]:
    """(armSkuName, armRegionName) -> {'od': $/hr, 'spot': $/hr}.

    Windows-licensed and low-priority rows are skipped (the catalog
    models Linux on-demand + spot, like the reference fetcher).
    """
    prices: Dict[Tuple[str, str], Dict[str, float]] = {}
    for item in items:
        sku = item.get('armSkuName') or ''
        region = item.get('armRegionName') or ''
        if not sku or not region:
            continue
        name = item.get('skuName', '') + ' ' + item.get('productName', '')
        if 'Windows' in name or 'Low Priority' in name:
            continue
        try:
            price = float(item.get('retailPrice', 0))
        except (TypeError, ValueError):
            continue
        if price <= 0:
            continue
        kind = 'spot' if 'Spot' in name else 'od'
        prices.setdefault((sku, region), {})[kind] = price
    return prices


def apply_azure_live(
    entries: List[common.CatalogEntry],
    vm_prices: Dict[Tuple[str, str], Dict[str, float]],
) -> Tuple[List[common.CatalogEntry], int]:
    out: List[common.CatalogEntry] = []
    patched = 0
    for entry in entries:
        live = vm_prices.get((entry.instance_type, entry.region))
        if not live:
            out.append(entry)
            continue
        entry = dataclasses.replace(
            entry,
            price=live.get('od', entry.price),
            spot_price=live.get('spot', entry.spot_price))
        patched += 1
        out.append(entry)
    return out, patched


# --------------------------------------------------------------------------
# Top-level refresh


def _read_catalog_csv(cloud: str) -> List[common.CatalogEntry]:
    path = common.catalog_path(cloud)
    if not os.path.exists(path):
        raise FileNotFoundError(f'no in-tree catalog for {cloud}: {path}')
    return common.read_catalog_csv(path)


def refresh(clouds: Iterable[str],
            fetch: Optional[FetchJson] = None) -> Dict[str, int]:
    """Patch each cloud's on-disk catalog with live prices.

    Best-effort per cloud: a failure (no network, no credentials, API
    change) logs and leaves that cloud's snapshot untouched. Returns
    {cloud: rows_patched} for the clouds that succeeded.
    """
    fetch = fetch or _default_fetch
    results: Dict[str, int] = {}
    for cloud in clouds:
        try:
            # Read the in-tree CSV directly — NOT load_catalog(), whose
            # hosted-download preference / lru cache could hand back a
            # stale prior build that save_catalog would then clobber the
            # fresh snapshot with.
            entries = _read_catalog_csv(cloud)
            if cloud == 'gcp':
                prices = gcp_tpu_chip_prices(
                    iter_gcp_skus(TPU_SERVICE_ID, fetch, _gcp_token()))
                entries, patched = apply_gcp_live(entries, prices)
            elif cloud == 'azure':
                regions = {e.region for e in entries}
                entries, patched = apply_azure_live(
                    entries,
                    azure_vm_prices(iter_azure_prices(fetch, regions)))
            else:
                logger.warning('live prices: no live source for %s', cloud)
                continue
            if patched:
                common.save_catalog(cloud, entries)
                common.clear_cache()
            results[cloud] = patched
            logger.info('live prices: %s — %d rows patched', cloud, patched)
        except Exception as e:  # pylint: disable=broad-except
            logger.warning('live prices: %s refresh failed (%s); '
                           'keeping snapshot', cloud, e)
    return results


def main(argv: Optional[List[str]] = None) -> int:
    import sys
    clouds = (argv if argv is not None else sys.argv[1:]) or ['gcp', 'azure']
    results = refresh(clouds)
    for cloud, patched in results.items():
        print(f'{cloud}: {patched} rows repriced')
    return 0 if results else 1


if __name__ == '__main__':
    raise SystemExit(main())
