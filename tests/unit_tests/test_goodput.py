"""Goodput attribution ledger tests: incarnation splitting, the fold's
attribution math (overlap resolution, elastic shrink windows, clock
skew between planes, missing planes degrading to `unattributed`,
restart-replay accounting), the bounded `goodput_ledger` state table,
the SQL recovery-latency aggregate, the `xsky goodput` / `xsky top` /
`/metrics` surfaces, the tier-1 fake-cloud relaunch smoke (a chaos
relaunch shows nonzero restart_replay), and the
`tools/bench_fleet.py --decompose --smoke` subprocess gate."""
import json
import os
import subprocess
import sys
import time

import pytest

from skypilot_tpu.agent import goodput
from skypilot_tpu.agent import telemetry
from skypilot_tpu.utils import chaos

REPO_ROOT = os.path.abspath(
    os.path.join(os.path.dirname(__file__), '..', '..'))

CLUSTER = 'xsky-jobs-7'
SCOPE = 'job/7'


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    monkeypatch.delenv(telemetry.ENV_DIR, raising=False)
    telemetry.reset_for_test()
    chaos.clear()
    yield
    telemetry.reset_for_test()
    chaos.clear()


@pytest.fixture
def tmp_state(monkeypatch, tmp_path):
    from skypilot_tpu import state
    monkeypatch.setenv('XSKY_STATE_DB', str(tmp_path / 'state.db'))
    state.reset_for_test()
    yield state
    state.reset_for_test()


def _feed(state, rank, start, end, started, step0=0.0, rate=1.0,
          verdict='ok', phase='step', resume=None, cluster=CLUSTER,
          dt=1.0):
    """One rank's pull history: a row every `dt` seconds with the step
    counter advancing `rate` steps/s (step_time_ema = 1/rate)."""
    t, step = float(start), float(step0)
    while t <= end + 1e-9:
        state.record_workload_telemetry(cluster, 1, [{
            'rank': rank,
            'phase': phase,
            'step': int(step) if phase == 'step' else None,
            'step_time_ema_s': 1.0 / rate if rate else None,
            'started_ts': started,
            'verdict': verdict,
            'resume_step': resume,
            'hb_ts': t,
            'last_progress_ts': t,
        }], ts=t)
        t += dt
        step += rate * dt


def _journal_at(state, ts, event_type, scope=SCOPE, latency_s=None,
                detail=None):
    """Append a journal row with a controlled timestamp (the journal
    stamps rows with time.time(): pin it for the write)."""
    real = time.time
    time.time = lambda: ts
    try:
        state.record_recovery_event(event_type, scope=scope,
                                    latency_s=latency_s, detail=detail)
    finally:
        time.time = real


def _span(state, name, start, end, cluster=CLUSTER):
    state.record_spans([{
        'trace_id': 't1', 'span_id': f's-{name}-{start}',
        'parent_span_id': None, 'name': name,
        'start_ts': start, 'end_ts': end, 'status': 'OK',
        'attrs': {'cluster': cluster},
    }])


def _assert_sums_to_wall(ledger, tol=1e-6):
    total = sum(ledger['totals'].values())
    assert abs(total - ledger['wall_s']) <= \
        max(tol, 0.02 * ledger['wall_s']), ledger['totals']
    for cat, value in ledger['totals'].items():
        assert value >= 0, (cat, value)


class TestIncarnationSplit:

    def _row(self, rank, ts, started, step=0):
        return {'rank': rank, 'ts': ts, 'started_ts': started,
                'step': step}

    def test_single_incarnation_groups_ranks(self):
        rows = [self._row(0, 10, 5.0), self._row(1, 10, 5.3),
                self._row(0, 12, 5.0)]
        incs = telemetry.split_incarnations(rows, gap_s=2.0)
        assert len(incs) == 1
        assert sorted(incs[0]['ranks']) == [0, 1]
        assert len(incs[0]['ranks'][0]) == 2

    def test_rank_reappearance_opens_new_incarnation(self):
        rows = [self._row(0, 10, 5.0), self._row(0, 20, 6.5)]
        # 1.5 s apart — under the gap — but the SAME rank cannot start
        # twice in one incarnation.
        incs = telemetry.split_incarnations(rows, gap_s=2.0)
        assert len(incs) == 2
        assert incs[0]['start_ts'] < incs[1]['start_ts']

    def test_start_gap_opens_new_incarnation(self):
        rows = [self._row(0, 10, 5.0), self._row(1, 40, 35.0)]
        incs = telemetry.split_incarnations(rows, gap_s=2.0)
        assert len(incs) == 2

    def test_rows_sorted_and_end_ts(self):
        rows = [self._row(0, 14, 5.0), self._row(0, 10, 5.0)]
        incs = telemetry.split_incarnations(rows, gap_s=2.0)
        ts = [r['ts'] for r in incs[0]['ranks'][0]]
        assert ts == sorted(ts)
        assert incs[0]['end_ts'] == 14


class TestFoldMath:

    def test_all_productive_sums_to_wall(self, tmp_state):
        for r in (0, 1):
            _feed(tmp_state, r, 10, 40, started=10.0)
        ledger = goodput.build_ledger(CLUSTER, now=40.0,
                                      window=(10.0, 40.0))
        assert ledger['full_ranks'] == 2
        assert ledger['totals']['productive'] == pytest.approx(30.0,
                                                               abs=0.1)
        _assert_sums_to_wall(ledger)
        assert ledger['goodput'] == pytest.approx(1.0, abs=0.01)

    def test_relaunch_replay_charged(self, tmp_state):
        # Incarnation 0 banks steps 0-30; the relaunch restarts from 0
        # and re-runs 0-30 before advancing: that re-run is
        # restart_replay, the part past 30 is productive.
        for r in (0, 1):
            _feed(tmp_state, r, 10, 40, started=10.0)
            _feed(tmp_state, r, 60, 100, started=60.0)
        ledger = goodput.build_ledger(CLUSTER, now=100.0,
                                      window=(10.0, 100.0))
        assert len(ledger['incarnations']) == 2
        inc1 = ledger['incarnations'][1]
        assert inc1['replayed_steps'] == 60   # 30 steps x 2 ranks
        assert ledger['totals']['restart_replay'] == pytest.approx(
            30.0, abs=1.0)
        assert ledger['totals']['productive'] == pytest.approx(
            40.0, abs=1.0)
        # The 40-60 gap has no journal/span evidence: the honesty
        # bucket, never silently productive.
        assert ledger['totals']['unattributed'] == pytest.approx(
            20.0, abs=0.5)
        _assert_sums_to_wall(ledger)

    def test_resume_step_suppresses_replay(self, tmp_state):
        # A checkpoint restore declares resume_step: steps above it are
        # NEW work even though a prior incarnation committed more.
        for r in (0, 1):
            _feed(tmp_state, r, 10, 40, started=10.0)
            _feed(tmp_state, r, 60, 100, started=60.0, step0=30,
                  resume=30)
        ledger = goodput.build_ledger(CLUSTER, now=100.0,
                                      window=(10.0, 100.0))
        assert ledger['totals']['restart_replay'] == pytest.approx(
            0.0, abs=0.5)
        assert ledger['incarnations'][1]['replayed_steps'] == 0
        _assert_sums_to_wall(ledger)

    def test_stall_inside_provision_window_is_stalled(self, tmp_state):
        # Overlap resolution: the rank's own verdict outranks a
        # control-plane span for the seconds the rank covers.
        _feed(tmp_state, 0, 20, 30, started=20.0, verdict='hung',
              rate=0)
        _span(tmp_state, 'backend.provision', 15.0, 35.0)
        ledger = goodput.build_ledger(CLUSTER, now=35.0,
                                      window=(15.0, 35.0))
        assert ledger['totals']['stalled'] == pytest.approx(10.0,
                                                            abs=0.5)
        # The uncovered edges of the provision span still score it.
        assert ledger['totals']['provision'] == pytest.approx(
            10.0, abs=0.5)
        _assert_sums_to_wall(ledger)

    def test_gap_attributed_by_span_priority(self, tmp_state):
        # No rank alive 10-30; queue-wait (10-18) outranks the
        # provision span (10-30) where both cover a second.
        _feed(tmp_state, 0, 30, 40, started=30.0)
        _span(tmp_state, 'fleet.queue_wait', 10.0, 18.0)
        _span(tmp_state, 'backend.provision', 10.0, 30.0)
        ledger = goodput.build_ledger(CLUSTER, now=40.0,
                                      window=(10.0, 40.0))
        assert ledger['totals']['queue_wait'] == pytest.approx(
            8.0, abs=0.5)
        assert ledger['totals']['provision'] == pytest.approx(
            12.0, abs=0.5)
        assert ledger['totals']['unattributed'] == pytest.approx(
            0.0, abs=0.5)
        _assert_sums_to_wall(ledger)

    def test_shrink_window_charges_missing_fraction(self, tmp_state):
        # 4-rank gang shrinks to 3 mid-run: the missing 1/4 of every
        # shrunk second is shrunk_capacity, from the journal's
        # excluded/survivors detail.
        for r in range(4):
            _feed(tmp_state, r, 10, 20, started=10.0)
        _journal_at(tmp_state, 22.0, 'job.gang_shrunk',
                    detail={'excluded': [3], 'survivors': 3})
        for r in range(3):
            _feed(tmp_state, r, 24, 44, started=24.0)
        ledger = goodput.build_ledger(CLUSTER, now=44.0,
                                      window=(10.0, 44.0))
        assert ledger['full_ranks'] == 4
        # 22->44 shrunk at 1/4 missing = 5.5 chip-weighted seconds.
        assert ledger['totals']['shrunk_capacity'] == pytest.approx(
            5.5, abs=0.6)
        _assert_sums_to_wall(ledger)

    def test_recovery_window_from_journal_latency(self, tmp_state):
        for r in (0, 1):
            _feed(tmp_state, r, 10, 40, started=10.0)
            _feed(tmp_state, r, 60, 100, started=60.0, step0=100)
        _journal_at(tmp_state, 60.0, 'job.recovered', latency_s=20.0)
        ledger = goodput.build_ledger(CLUSTER, now=100.0,
                                      window=(10.0, 100.0))
        assert ledger['totals']['recovery'] == pytest.approx(20.0,
                                                             abs=0.5)
        assert ledger['totals']['unattributed'] == pytest.approx(
            0.0, abs=0.5)
        _assert_sums_to_wall(ledger)

    def test_clock_skew_between_planes_keeps_invariants(
            self, tmp_state):
        # The workload host's clock runs 30 s ahead of the control
        # plane's span clock: attribution must stay non-negative and
        # still sum to wall (categories may blur, the total may not).
        for r in (0, 1):
            _feed(tmp_state, r, 40, 70, started=40.0)
        _span(tmp_state, 'backend.provision', 0.0, 10.0)
        _journal_at(tmp_state, 35.0, 'job.recovered', latency_s=30.0)
        ledger = goodput.build_ledger(CLUSTER, now=70.0,
                                      window=(0.0, 70.0))
        _assert_sums_to_wall(ledger)

    def test_missing_planes_degrade_to_unattributed(self, tmp_state):
        # Telemetry only — no lease, no journal, no spans: the covered
        # part scores, the rest lands in the honesty bucket.
        _feed(tmp_state, 0, 30, 40, started=30.0)
        ledger = goodput.build_ledger(CLUSTER, now=40.0,
                                      window=(10.0, 40.0))
        assert ledger['totals']['productive'] == pytest.approx(
            10.0, abs=0.5)
        assert ledger['totals']['unattributed'] == pytest.approx(
            20.0, abs=0.5)
        _assert_sums_to_wall(ledger)

    def test_no_evidence_returns_empty_ledger(self, tmp_state):
        ledger = goodput.build_ledger('xsky-jobs-99')
        assert ledger['wall_s'] == 0.0
        assert ledger['incarnations'] == []
        assert ledger['goodput'] is None

    def test_init_and_idle_phases(self, tmp_state):
        _feed(tmp_state, 0, 10, 20, started=10.0, phase='init',
              rate=0)
        _feed(tmp_state, 0, 21, 30, started=10.0, phase='idle',
              rate=0)
        ledger = goodput.build_ledger(CLUSTER, now=30.0,
                                      window=(10.0, 30.0))
        assert ledger['totals']['init_barrier'] == pytest.approx(
            10.0, abs=0.5)
        assert ledger['totals']['idle'] == pytest.approx(9.0, abs=1.0)
        _assert_sums_to_wall(ledger)

    def test_build_ledger_never_raises(self, tmp_state, monkeypatch):
        monkeypatch.setattr(tmp_state, 'get_workload_telemetry',
                            lambda **kw: 1 / 0)
        ledger = goodput.build_ledger(CLUSTER)
        assert ledger['cluster'] == CLUSTER
        assert ledger['goodput'] is None

    def test_fleet_report_never_raises(self, tmp_state, monkeypatch):
        monkeypatch.setattr(tmp_state, 'get_cluster_names',
                            lambda **kw: 1 / 0)
        report = goodput.fleet_report()
        assert report['clusters'] == []
        assert report['goodput'] is None


class TestLedgerTable:

    def _seed(self, state, now=100.0):
        for r in (0, 1):
            _feed(state, r, 10, 40, started=10.0)
            _feed(state, r, 60, 100, started=60.0)
        return goodput.record_ledger(CLUSTER, now=now)

    def test_record_and_read_round_trip(self, tmp_state):
        ledger = self._seed(tmp_state)
        assert ledger['wall_s'] > 0
        rows = tmp_state.get_goodput_ledger(cluster=CLUSTER)
        kinds = sorted((r['kind'], r['incarnation']) for r in rows)
        assert kinds == [('incarnation', 0), ('incarnation', 1),
                        ('job', None)]
        job = [r for r in rows if r['kind'] == 'job'][0]
        assert job['replayed_steps'] == 60
        assert job['seconds']['restart_replay'] == pytest.approx(
            30.0, abs=1.0)
        assert job['full_ranks'] == 2

    def test_latest_only_supersedes(self, tmp_state):
        self._seed(tmp_state, now=100.0)
        self._seed(tmp_state, now=101.0)
        rows = tmp_state.get_goodput_ledger(cluster=CLUSTER,
                                            kind='job')
        assert len(rows) == 1
        history = tmp_state.get_goodput_ledger(cluster=CLUSTER,
                                               kind='job',
                                               latest_only=False)
        assert len(history) == 2

    def test_retention_bound(self, tmp_state, monkeypatch):
        # First-batch prune (the spans/profiles rationale): even a
        # short-lived writer's very first oversized batch is bounded.
        monkeypatch.setattr(tmp_state, '_MAX_GOODPUT_LEDGER', 10)
        monkeypatch.setattr(tmp_state, '_goodput_ledger_inserts', 0)
        tmp_state.record_goodput_ledger(
            CLUSTER, 7, [{'kind': 'incarnation', 'incarnation': i,
                          'wall_s': float(i), 'seconds': {}}
                         for i in range(40)], ts=1.0)
        rows = tmp_state.get_goodput_ledger(latest_only=False,
                                            limit=1000)
        assert len(rows) == 10
        assert {r['incarnation'] for r in rows} == set(range(30, 40))

    def test_record_never_raises(self, tmp_state, monkeypatch,
                                 tmp_path):
        # The DB path's parent is a FILE, so db_utils.connect's
        # makedirs raises and every open genuinely fails (a missing
        # directory would just be created).
        blocker = tmp_path / 'blocker'
        blocker.write_text('not a directory')
        monkeypatch.setenv('XSKY_STATE_DB',
                           str(blocker / 'no' / 'such' / 'x.db'))
        tmp_state.reset_for_test()
        tmp_state.record_goodput_ledger(
            CLUSTER, 7, [{'kind': 'job', 'seconds': {}}])
        ledger = goodput.record_ledger(CLUSTER)
        assert ledger['goodput'] is None


class TestRecoveryAggregate:

    def test_counts_beyond_the_old_1000_row_limit(self, tmp_state):
        # The old Python-side sum read get_recovery_events(limit=1000)
        # and silently undercounted busier jobs; the SQL aggregate
        # must not.
        for i in range(1050):
            _journal_at(tmp_state, float(i), 'job.recovered',
                        latency_s=1.0)
        total = tmp_state.sum_recovery_latency(SCOPE)
        assert total == pytest.approx(1050.0)
        old_way = sum(e['latency_s'] or 0 for e in
                      tmp_state.get_recovery_events(scope=SCOPE,
                                                    limit=1000))
        assert old_way < total   # the bug the aggregate fixes

    def test_scope_exact_and_prefix(self, tmp_state):
        _journal_at(tmp_state, 1.0, 'job.recovered', scope='job/7',
                    latency_s=5.0)
        _journal_at(tmp_state, 2.0, 'job.recovered',
                    scope='job/7/task/1', latency_s=2.0)
        _journal_at(tmp_state, 3.0, 'job.recovered', scope='job/77',
                    latency_s=100.0)
        assert tmp_state.sum_recovery_latency('job/7') == \
            pytest.approx(7.0)

    def test_event_type_filter(self, tmp_state):
        _journal_at(tmp_state, 1.0, 'job.recovered', latency_s=5.0)
        _journal_at(tmp_state, 2.0, 'job.gang_shrunk', latency_s=3.0)
        assert tmp_state.sum_recovery_latency(
            SCOPE, event_types=('job.recovered',)) == pytest.approx(5.0)
        assert tmp_state.sum_recovery_latency(
            SCOPE, event_types=()) == 0.0

    def test_goodput_for_cluster_uses_aggregate(self, tmp_state):
        for i in range(1050):
            _journal_at(tmp_state, float(i), 'job.recovered',
                        latency_s=1.0)
        samples = {0: {'step': 10, 'step_time_ema_s': 1.0,
                       'started_ts': 0.0, 'hb_ts': 2000.0}}
        result = telemetry.goodput_for_cluster(CLUSTER, samples,
                                               now=2000.0)
        assert result['recovery_s'] == pytest.approx(1050.0)


class TestSurfaces:

    def _seed(self, state):
        for r in (0, 1):
            _feed(state, r, 10, 40, started=10.0)
            _feed(state, r, 60, 100, started=60.0)
        return goodput.record_ledger(CLUSTER, now=100.0)

    def test_cli_goodput_table_and_json(self, tmp_state):
        from click.testing import CliRunner

        from skypilot_tpu.client import cli as cli_mod
        self._seed(tmp_state)
        runner = CliRunner()
        result = runner.invoke(cli_mod.cli, ['goodput', CLUSTER])
        assert result.exit_code == 0, result.output
        assert 'WATERFALL' in result.output
        assert 'restart_replay' in result.output
        result = runner.invoke(cli_mod.cli,
                               ['goodput', CLUSTER, '--json'])
        assert result.exit_code == 0, result.output
        ledger = json.loads(result.output)
        assert ledger['totals']['restart_replay'] > 0
        assert len(ledger['incarnations']) == 2

    def test_cli_goodput_fleet_rollup(self, tmp_state):
        from click.testing import CliRunner

        from skypilot_tpu.client import cli as cli_mod
        self._seed(tmp_state)
        runner = CliRunner()
        # Not a live cluster yet: the rollup must filter it out.
        result = runner.invoke(cli_mod.cli, ['goodput', '--fleet'])
        assert result.exit_code == 0, result.output
        assert 'No persisted goodput ledgers' in result.output
        tmp_state.add_or_update_cluster(CLUSTER, None)
        result = runner.invoke(cli_mod.cli, ['goodput', '--fleet'])
        assert result.exit_code == 0, result.output
        assert CLUSTER in result.output
        assert 'restart_replay' in result.output
        result = runner.invoke(cli_mod.cli,
                               ['goodput', '--fleet', '--json'])
        report = json.loads(result.output)
        assert report['loss_by_cause']['restart_replay'] > 0

    def test_metrics_loss_counters_live_filtered(self, tmp_state):
        from skypilot_tpu.server import metrics as server_metrics
        self._seed(tmp_state)
        out = server_metrics.render()
        assert 'xsky_goodput_loss_seconds_total' not in out
        tmp_state.add_or_update_cluster(CLUSTER, None)
        out = server_metrics.render()
        assert (f'xsky_goodput_loss_seconds_total{{cluster="{CLUSTER}"'
                ',cause="restart_replay"}') in out
        # Only loss causes export — productive is the complement.
        assert 'cause="productive"' not in out

    def test_top_summary_shows_loss_decomposition(self, tmp_state):
        from click.testing import CliRunner

        from skypilot_tpu.client import cli as cli_mod
        self._seed(tmp_state)
        runner = CliRunner()
        result = runner.invoke(cli_mod.cli, ['top'])
        assert result.exit_code == 0, result.output
        assert 'loss=replay' in result.output

    def test_loss_summary_format(self):
        assert goodput.loss_summary({}) == '-'
        digest = goodput.loss_summary({
            'productive': 50.0, 'restart_replay': 30.0,
            'provision': 15.0, 'stalled': 5.0})
        assert digest == 'replay 30%/provision 15%'
        assert goodput.loss_summary(None) == '-'

    def test_goodput_report_verbs(self, tmp_state):
        from skypilot_tpu import core
        from skypilot_tpu.server import payloads
        self._seed(tmp_state)
        report = core.goodput_report(CLUSTER)
        assert report['kind'] == 'cluster'
        assert report['ledger']['totals']['restart_replay'] > 0
        fn, kwargs = payloads.resolve('goodput.report',
                                      {'cluster_name': CLUSTER})
        assert fn(**kwargs)['kind'] == 'cluster'
        fn, kwargs = payloads.resolve('goodput.report', {'fleet': True})
        assert fn(**kwargs)['kind'] == 'fleet'


class TestLedgerSmoke:
    """Tier-1 acceptance: a fake-cloud managed job whose rank is
    chaos-stalled relaunches (1 host — the head rank cannot shrink
    away) and the relaunch REBUYS the first incarnation's progress:
    `xsky goodput --json` shows nonzero restart_replay and the
    controller persisted a ledger roll-up during the run."""

    def test_chaos_relaunch_shows_restart_replay(
            self, fake_cluster_env, monkeypatch, tmp_path):
        del fake_cluster_env
        import threading

        from click.testing import CliRunner

        from skypilot_tpu import Resources, Task
        from skypilot_tpu import state as state_lib
        from skypilot_tpu.client import cli as cli_mod
        from skypilot_tpu.jobs import controller as controller_lib
        from skypilot_tpu.jobs import scheduler as jobs_scheduler
        from skypilot_tpu.jobs import state as jobs_state

        monkeypatch.setenv('XSKY_JOBS_DB',
                           str(tmp_path / 'managed_jobs.db'))
        monkeypatch.setenv('XSKY_JOBS_LOG_DIR', str(tmp_path / 'jlogs'))
        monkeypatch.setattr(controller_lib, 'POLL_INTERVAL_S', 0.2)
        monkeypatch.setenv(telemetry.ENV_INTERVAL, '0.1')
        monkeypatch.setenv(telemetry.ENV_PULL_INTERVAL, '0.15')
        monkeypatch.setenv(telemetry.ENV_PROGRESS_STALE, '0.8')
        monkeypatch.setenv(telemetry.ENV_HB_STALE, '30')
        # The controller folds + persists every 0.3 s so the run
        # leaves a roll-up behind even though it is short.
        monkeypatch.setenv(goodput.ENV_RECORD_INTERVAL, '0.3')

        # First incarnation banks 45 steps then stalls; the relaunch
        # re-runs 12 of them from 0 — all below the banked max, all
        # restart_replay — and exits 0. The banked window must outlive
        # several pull intervals: the relaunch tears the first
        # incarnation's spool down with its cluster, so a pull that
        # never landed loses the incarnation (and the replay evidence)
        # permanently — under full-suite load the old 1.5 s window
        # (30 x 0.05 s) flaked.
        marker = tmp_path / 'first-incarnation'
        script = tmp_path / 'workload.py'
        script.write_text(f'''
import os, sys, time
sys.path.insert(0, {json.dumps(REPO_ROOT)})
from skypilot_tpu.agent import telemetry
telemetry.emit(phase='init', resume_step=0)
relaunch = os.path.exists({json.dumps(str(marker))})
open({json.dumps(str(marker))}, 'w').close()
steps = 12 if relaunch else 80
for i in range(steps):
    telemetry.emit(phase='step', step=i, step_time_s=0.08)
    time.sleep(0.08)
''')
        plan_file = tmp_path / 'stall-plan.json'
        plan_file.write_text(json.dumps({'points': {
            'telemetry.stall': {'match': {'rank': 0},
                                'skip_first': 45}}}))
        monkeypatch.setenv('XSKY_CHAOS_PLAN', str(plan_file))

        task = Task('replay', run=f'{sys.executable} {script}')
        task.set_resources(Resources(accelerators='tpu-v5e-8',
                                     use_spot=True))
        job_id = jobs_state.add_job('replay',
                                    Task.chain_to_config([task]))
        jobs_state.set_status(job_id,
                              jobs_state.ManagedJobStatus.SUBMITTED)
        jobs_state.set_schedule_state(job_id,
                                      jobs_state.ScheduleState.LAUNCHING)
        jobs_state.set_controller_pid(job_id, os.getpid())
        cluster = f'xsky-jobs-{job_id}'

        def run_controller():
            try:
                controller_lib.JobsController(job_id).run()
            finally:
                jobs_scheduler.job_done(job_id)

        thread = threading.Thread(target=run_controller, daemon=True,
                                  name='xsky-goodput-smoke-controller')
        thread.start()
        thread.join(timeout=180)
        assert not thread.is_alive(), 'controller wedged'
        record = jobs_state.get_job(job_id)
        assert record['status'] == \
            jobs_state.ManagedJobStatus.SUCCEEDED, record
        assert record['recovery_count'] >= 1

        # The live fold attributes the relaunch's re-run steps.
        runner = CliRunner()
        result = runner.invoke(cli_mod.cli,
                               ['goodput', cluster, '--json'])
        assert result.exit_code == 0, result.output
        ledger = json.loads(result.output)
        assert ledger['totals']['restart_replay'] > 0, ledger
        assert len(ledger['incarnations']) >= 2, ledger
        assert sum(r['replayed_steps']
                   for r in ledger['incarnations']) > 0
        _assert_sums_to_wall(ledger)

        # The controller-side record path persisted a roll-up while
        # the job ran (the monitor loop's rate-limited fold).
        rows = state_lib.get_goodput_ledger(cluster=cluster,
                                            kind='job')
        assert rows, 'controller never persisted a ledger roll-up'


class TestBenchDecomposeGate:
    """Tier-1 gate: the chaos-storm attribution decomposition holds
    (categories sum to wall ±2%, the relaunch arm's loss is mostly
    restart_replay, the elastic arm's shifts to shrunk_capacity, fold
    overhead <2% of a controller tick)."""

    def test_bench_fleet_decompose_smoke_gate(self):
        env = dict(os.environ, JAX_PLATFORMS='cpu')
        env.pop('XSKY_API_SERVER', None)
        proc = subprocess.run(
            [sys.executable,
             os.path.join(REPO_ROOT, 'tools', 'bench_fleet.py'),
             '--decompose', '--smoke'],
            capture_output=True, text=True, timeout=400, check=False,
            env=env, cwd=REPO_ROOT)
        assert proc.returncode == 0, \
            f'decompose gate failed:\n{proc.stdout}\n{proc.stderr}'
        result = json.loads(proc.stdout.strip().splitlines()[-1])
        assert result['pass'] is True
        assert result['gates']['baseline_loss_mostly_restart_replay']
        assert result['gates']['elastic_loss_shifts_to_shrunk_capacity']
        # PR 13 checkpoint-arm gates ride the same storm: the
        # checkpointed arm must strictly beat the unchecked elastic
        # arm on goodput, shrink restart_replay strictly, restore
        # from a live tier, and cost <2% of step time on the step
        # path.
        assert result['gates']['ckpt_goodput_gt_elastic']
        assert result['gates']['ckpt_replay_share_lt_unchecked']
        assert result['gates']['ckpt_restored_from_live_tier']
        assert result['gates']['ckpt_overhead_under_2pct']
        assert result['ckpt']['sum_error'] is not None
        assert result['ckpt']['sum_error'] <= 0.02
