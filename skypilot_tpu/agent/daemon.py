"""Head-node agent daemon (twin of sky/skylet/skylet.py:17-35 + events.py).

Periodic loop on the cluster head: schedule queued jobs, enforce autostop,
touch a heartbeat. Started detached by the backend after provisioning
(twin of start_skylet_on_head_node, sky/provision/instance_setup.py:471).
"""
from __future__ import annotations

import json
import os
import sys
import time

from skypilot_tpu.agent import autostop_lib
from skypilot_tpu.agent import job_lib

EVENT_INTERVAL_S = 20


def _tick_scheduler(root: str) -> None:
    job_lib.claim_and_spawn(root)


def _tick_autostop(root: str) -> None:
    if not autostop_lib.should_autostop(root):
        return
    config = autostop_lib.get_autostop(root) or {}
    down = config.get('down', False)
    # Push model first (twin of sky/skylet/events.py:102): the agent
    # stops/terminates the cluster itself using the instance's own
    # cloud identity, so the bill stops even with no control plane
    # alive. Providers that can't be driven from on-host fall back to a
    # marker file the control plane polls during status refresh.
    from skypilot_tpu.agent import self_teardown
    done = self_teardown.attempt_self_teardown(root, down)
    if not done:
        marker = os.path.join(root, 'autostop_triggered.json')
        with open(marker, 'w', encoding='utf-8') as f:
            json.dump({'down': down, 'triggered_at': time.time()}, f)
    try:
        autostop_lib.clear_autostop(root)
    except OSError:
        pass   # teardown may have removed the whole runtime root


def _heartbeat(root: str) -> None:
    with open(os.path.join(root, 'agent_heartbeat'), 'w',
              encoding='utf-8') as f:
        f.write(str(time.time()))


def run_forever(root: str = None, interval_s: float = EVENT_INTERVAL_S,
                max_ticks: int = None) -> None:
    root = root or job_lib.cluster_root()
    os.makedirs(root, exist_ok=True)
    ticks = 0
    while True:
        for event in (_tick_scheduler, _tick_autostop, _heartbeat):
            try:
                event(root)
            except Exception as e:  # pylint: disable=broad-except
                print(f'agent event {event.__name__} failed: {e}',
                      file=sys.stderr)
        ticks += 1
        if max_ticks is not None and ticks >= max_ticks:
            return
        time.sleep(interval_s)


if __name__ == '__main__':
    run_forever()
