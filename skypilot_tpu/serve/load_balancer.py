"""Load balancer: HTTP proxy → ready replicas (twin of
sky/serve/load_balancer.py:23), stdlib-only like the API server.

Counts requests for the autoscaler (shared via a callback), retries the
next replica on connection failure.
"""
from __future__ import annotations

import threading
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, List, Optional, Tuple

from skypilot_tpu import sky_logging
from skypilot_tpu.serve import load_balancing_policies as lb_policies

logger = sky_logging.init_logger(__name__)

_HOP_HEADERS = {'connection', 'keep-alive', 'transfer-encoding',
                'upgrade', 'proxy-authenticate', 'te', 'trailers',
                'host', 'content-length'}


class SkyServeLoadBalancer:

    def __init__(self, policy: Optional[
            lb_policies.LoadBalancingPolicy] = None,
            on_request: Optional[Callable[[], None]] = None) -> None:
        self.policy = policy or lb_policies.RoundRobinPolicy()
        self.on_request = on_request or (lambda: None)
        self._server: Optional[ThreadingHTTPServer] = None

    def set_ready_replicas(self, endpoints: List[str]) -> None:
        self.policy.set_ready_replicas(endpoints)

    def _proxy(self, method: str, path: str, body: bytes,
               headers) -> Tuple[int, bytes, List[Tuple[str, str]]]:
        self.on_request()
        tried = 0
        max_tries = 3
        while tried < max_tries:
            tried += 1
            replica = self.policy.select_replica()
            if replica is None:
                return 503, b'{"error": "no ready replicas"}', []
            url = f'http://{replica}{path}'
            req = urllib.request.Request(url, data=body or None,
                                         method=method)
            for k, v in headers.items():
                if k.lower() not in _HOP_HEADERS:
                    req.add_header(k, v)
            try:
                with urllib.request.urlopen(req, timeout=120) as resp:
                    out_headers = [
                        (k, v) for k, v in resp.headers.items()
                        if k.lower() not in _HOP_HEADERS
                    ]
                    data = resp.read()
                    self.policy.request_done(replica)
                    return resp.status, data, out_headers
            except urllib.error.HTTPError as e:
                self.policy.request_done(replica)
                return e.code, e.read(), []
            except (urllib.error.URLError, OSError, TimeoutError):
                self.policy.request_done(replica)
                continue  # replica unreachable: try another
        return 502, b'{"error": "all replicas unreachable"}', []

    def make_server(self, host: str = '0.0.0.0',
                    port: int = 0) -> ThreadingHTTPServer:
        lb = self

        class _Handler(BaseHTTPRequestHandler):

            def log_message(self, *args):
                pass

            def _handle(self, method: str):
                length = int(self.headers.get('Content-Length') or 0)
                body = self.rfile.read(length) if length else b''
                status, data, out_headers = lb._proxy(
                    method, self.path, body, self.headers)
                self.send_response(status)
                for k, v in out_headers:
                    self.send_header(k, v)
                self.send_header('Content-Length', str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):  # noqa: N802
                self._handle('GET')

            def do_POST(self):  # noqa: N802
                self._handle('POST')

            def do_PUT(self):  # noqa: N802
                self._handle('PUT')

            def do_DELETE(self):  # noqa: N802
                self._handle('DELETE')

        self._server = ThreadingHTTPServer((host, port), _Handler)
        return self._server

    def run_in_thread(self, host: str = '127.0.0.1',
                      port: int = 0) -> int:
        server = self.make_server(host, port)
        thread = threading.Thread(target=server.serve_forever, daemon=True)
        thread.start()
        return server.server_address[1]

    def shutdown(self) -> None:
        if self._server is not None:
            self._server.shutdown()
