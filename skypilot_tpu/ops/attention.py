"""Attention ops: XLA reference implementation + dispatch to Pallas flash.

The XLA path is the correctness reference (and the CPU-test path); on TPU
the Pallas flash kernel (`skypilot_tpu.ops.flash_attention`) is used for
long sequences where materializing the S×S score matrix would blow HBM.

Shapes follow the framework convention: q [B, S, H, D], k/v [B, S, Hkv, D]
(Hkv <= H, grouped-query attention).
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

_FLASH_MIN_SEQ = 1024  # below this XLA's fused softmax is already fine


def _repeat_kv(k: jax.Array, num_groups: int) -> jax.Array:
    if num_groups == 1:
        return k
    b, s, h_kv, d = k.shape
    k = jnp.broadcast_to(k[:, :, :, None, :], (b, s, h_kv, num_groups, d))
    return k.reshape(b, s, h_kv * num_groups, d)


def xla_attention(q: jax.Array,
                  k: jax.Array,
                  v: jax.Array,
                  causal: bool = True,
                  segment_ids: Optional[jax.Array] = None,
                  window: Optional[int] = None,
                  logit_softcap: Optional[float] = None,
                  scale: Optional[float] = None) -> jax.Array:
    """Reference attention in pure XLA (fp32 softmax).

    window: sliding-window size W (Mistral-style) — each query attends
    to at most the W most recent positions (inclusive of itself).
    logit_softcap: Gemma-2's cap·tanh(s/cap) on the scores (before
    masking). scale: score multiplier (default head_dim**-0.5 —
    Gemma-2 uses query_pre_attn_scalar**-0.5 instead).
    """
    b, s_q, h, d = q.shape
    s_kv = k.shape[1]
    groups = h // k.shape[2]
    k = _repeat_kv(k, groups)
    v = _repeat_kv(v, groups)
    scale = d ** -0.5 if scale is None else scale
    logits = jnp.einsum('bqhd,bkhd->bhqk', q, k,
                        preferred_element_type=jnp.float32) * scale
    if logit_softcap is not None:
        logits = logit_softcap * jnp.tanh(logits / logit_softcap)
    if causal or window is not None:
        q_pos = jnp.arange(s_q)[:, None] + (s_kv - s_q)
        kv_pos = jnp.arange(s_kv)[None, :]
        mask = (q_pos >= kv_pos if causal
                else jnp.ones((s_q, s_kv), bool))
        if window is not None:
            mask &= (q_pos - kv_pos) < window
        logits = jnp.where(mask[None, None], logits, -1e30)
    if segment_ids is not None:
        seg_mask = segment_ids[:, :, None] == segment_ids[:, None, :]
        logits = jnp.where(seg_mask[:, None], logits, -1e30)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    return jnp.einsum('bhqk,bkhd->bqhd', probs.astype(v.dtype), v)


def xla_attention_with_mask(q: jax.Array, k: jax.Array, v: jax.Array,
                            mask: jax.Array,
                            logit_softcap: Optional[float] = None,
                            scale: Optional[float] = None) -> jax.Array:
    """Attention with an explicit boolean mask [B, 1|H, S_q|1, S_kv].

    Used by the decode path (KV-cache validity mask).
    """
    b, s_q, h, d = q.shape
    groups = h // k.shape[2]
    k = _repeat_kv(k, groups)
    v = _repeat_kv(v, groups)
    scale = d ** -0.5 if scale is None else scale
    logits = jnp.einsum('bqhd,bkhd->bhqk', q, k,
                        preferred_element_type=jnp.float32) * scale
    if logit_softcap is not None:
        logits = logit_softcap * jnp.tanh(logits / logit_softcap)
    logits = jnp.where(mask, logits, -1e30)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    return jnp.einsum('bhqk,bkhd->bqhd', probs.astype(v.dtype), v)


def dot_product_attention(q: jax.Array,
                          k: jax.Array,
                          v: jax.Array,
                          causal: bool = True,
                          segment_ids: Optional[jax.Array] = None,
                          implementation: str = 'auto',
                          window: Optional[int] = None,
                          logit_softcap: Optional[float] = None,
                          scale: Optional[float] = None) -> jax.Array:
    """Dispatching attention entry point used by the models.

    implementation: 'auto' | 'xla' | 'flash'; window: sliding-window
    size (both paths support it; flash also SKIPS the out-of-window
    blocks, so long-context sliding-window runs in O(S·W)).
    logit_softcap / non-default scale (Gemma-2) are supported by BOTH
    paths (the flash kernels apply the tanh cap in fwd and carry its
    (1 - tanh²) chain factor through the FA2 backward recompute).
    """
    if implementation == 'auto':
        # device_kind, not platform: TPU chips reached through a remote
        # PJRT plugin (e.g. an 'axon' tunnel) report platform != 'tpu'
        # but still run Pallas TPU kernels.
        on_tpu = any(
            d.platform == 'tpu' or
            getattr(d, 'device_kind', '').startswith('TPU')
            for d in jax.devices())
        use_flash = on_tpu and q.shape[1] >= _FLASH_MIN_SEQ and causal
        implementation = 'flash' if use_flash else 'xla'
    if implementation == 'flash':
        from skypilot_tpu.ops import flash_attention
        return flash_attention.flash_attention(
            q, k, v, causal=causal, window=window,
            segment_ids=segment_ids, logit_softcap=logit_softcap,
            scale=scale)
    return xla_attention(q, k, v, causal=causal, segment_ids=segment_ids,
                         window=window, logit_softcap=logit_softcap,
                         scale=scale)
